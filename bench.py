"""onix benchmark — judged metric: netflow events scored/sec/chip.

Measures the post-LDA suspicious-connects scoring scan (SURVEY.md §3.1
hot loop #3 — the throughput path that touches every raw event,
reference README.md:42 "filter billion of events to a few thousands")
on the available accelerator, and a Gibbs sweep rate alongside.

Baseline (BASELINE.md): the reference published NO numbers; the
operative stand-in for its 20-node CPU cluster is 20× a single-core
vectorized NumPy scorer measured on this host, which is generous to the
reference (its Scala/Spark scoring had JVM + shuffle overhead on top).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

from __future__ import annotations

import json
import time

import numpy as np


def _numpy_scoring_rate(theta, phi_wk, n_events=1 << 21, seed=1) -> float:
    """Single-core vectorized scorer — the per-node reference stand-in."""
    rng = np.random.default_rng(seed)
    d = rng.integers(0, theta.shape[0], n_events).astype(np.int32)
    w = rng.integers(0, phi_wk.shape[0], n_events).astype(np.int32)
    t0 = time.perf_counter()
    s = np.einsum("nk,nk->n", theta[d], phi_wk[w])
    dt = time.perf_counter() - t0
    assert np.isfinite(s).all()
    return n_events / dt


def main() -> None:
    import jax
    import jax.numpy as jnp

    from onix.models.scoring import top_suspicious

    n_docs, n_vocab, k = 100_000, 65_536, 20
    n_events = 1 << 24            # ~16.8M events per timed pass
    chunk = 1 << 21

    rng = np.random.default_rng(0)
    theta = rng.dirichlet(np.full(k, 0.5), size=n_docs).astype(np.float32)
    phi_wk = rng.dirichlet(np.full(k, 0.5), size=n_vocab).astype(np.float32)
    doc_ids = rng.integers(0, n_docs, n_events).astype(np.int32)
    word_ids = rng.integers(0, n_vocab, n_events).astype(np.int32)
    mask = np.ones(n_events, np.float32)

    dev = jax.devices()[0]
    theta_d = jnp.asarray(theta)
    phi_d = jnp.asarray(phi_wk)
    d_d = jnp.asarray(doc_ids)
    w_d = jnp.asarray(word_ids)
    m_d = jnp.asarray(mask)

    run = lambda: top_suspicious(theta_d, phi_d, d_d, w_d, m_d,
                                 tol=1.0, max_results=1000, chunk=chunk)
    run().scores.block_until_ready()          # compile + warm
    t0 = time.perf_counter()
    n_passes = 3
    for _ in range(n_passes):
        out = run()
    out.scores.block_until_ready()
    dt = time.perf_counter() - t0
    rate = n_passes * n_events / dt

    baseline = 20.0 * _numpy_scoring_rate(theta, phi_wk)

    print(json.dumps({
        "metric": "netflow_events_scored_per_sec_per_chip",
        "value": round(rate, 1),
        "unit": "events/s/chip",
        "vs_baseline": round(rate / baseline, 3),
        "detail": {
            "device": str(dev),
            "n_events_per_pass": n_events,
            "passes": n_passes,
            "baseline_events_per_sec_20node_numpy_proxy": round(baseline, 1),
        },
    }))


if __name__ == "__main__":
    main()
