// onix-lda-ref — C++ reference LDA engine (collapsed Gibbs + variational EM).
//
// Role (SURVEY.md §2.4.1): the correctness/performance oracle standing in for
// the reference's oni-lda-c C/MPI engine (reference README.md:84,125 — the
// binary itself is not in the mount), so the JAX/TPU engine has a faithful
// same-corpus, same-hyperparameter baseline for the judged metric
// "top-1k suspicious-connect overlap vs lda-c" (BASELINE.json `metric`).
//
// Two algorithms, matching both readings of the reference engine
// (SURVEY.md §2.1 #10: BASELINE.json says "Gibbs sampler", the Blei lda-c
// lineage is variational EM — so the oracle implements BOTH):
//
//   * collapsed Gibbs — token-sequential, exact; with n_threads > 1 it
//     becomes AD-LDA style: documents sharded across threads, each thread
//     sampling against a private copy of the word-topic counts, deltas
//     merged after every sweep. This mirrors the reference's MPI pattern
//     (docs sharded across ranks, topic-word sufficient statistics reduced
//     each iteration — SURVEY.md §2.2).
//
//   * variational EM — Blei-style per-document E-step (gamma/phi fixed
//     point with digamma), M-step re-estimating beta from sufficient
//     statistics, optional symmetric-alpha Newton update
//     (SURVEY.md §2.1 #10: "alpha Newton update").
//
// Exposed as a C ABI for ctypes (onix/oracle.py) and as a CLI writing the
// reference's file contract: final.gamma (D x K), final.beta (K x V,
// log-probs), likelihood.dat (SURVEY.md §3.1, §5.4).

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// Corpus: token-expanded view built from sparse (doc, word, count) triples.
// ---------------------------------------------------------------------------

struct Corpus {
  std::vector<int32_t> doc;   // [n_tokens]
  std::vector<int32_t> word;  // [n_tokens]
  int32_t n_docs = 0;
  int32_t n_vocab = 0;

  int64_t n_tokens() const { return static_cast<int64_t>(doc.size()); }
};

Corpus expand(const int32_t* doc_ids, const int32_t* word_ids,
              const int32_t* counts, int64_t nnz, int32_t n_docs,
              int32_t n_vocab) {
  Corpus c;
  c.n_docs = n_docs;
  c.n_vocab = n_vocab;
  int64_t total = 0;
  for (int64_t i = 0; i < nnz; ++i) total += counts[i];
  c.doc.reserve(total);
  c.word.reserve(total);
  for (int64_t i = 0; i < nnz; ++i) {
    for (int32_t r = 0; r < counts[i]; ++r) {
      c.doc.push_back(doc_ids[i]);
      c.word.push_back(word_ids[i]);
    }
  }
  return c;
}

// Sort tokens by document so each thread owns a contiguous doc range.
void sort_by_doc(Corpus& c) {
  std::vector<int64_t> idx(c.doc.size());
  for (int64_t i = 0; i < (int64_t)idx.size(); ++i) idx[i] = i;
  std::stable_sort(idx.begin(), idx.end(), [&](int64_t a, int64_t b) {
    return c.doc[a] < c.doc[b];
  });
  std::vector<int32_t> d(c.doc.size()), w(c.word.size());
  for (int64_t i = 0; i < (int64_t)idx.size(); ++i) {
    d[i] = c.doc[idx[i]];
    w[i] = c.word[idx[i]];
  }
  c.doc.swap(d);
  c.word.swap(w);
}

// Mean per-token log p(w|d) given current count-based estimates — the
// convergence series the reference prints to likelihood.dat.
double mean_loglik(const Corpus& c, const std::vector<double>& theta,
                   const std::vector<double>& phi, int K) {
  double total = 0.0;
  const int64_t n = c.n_tokens();
  for (int64_t i = 0; i < n; ++i) {
    const double* th = &theta[(int64_t)c.doc[i] * K];
    double p = 0.0;
    for (int k = 0; k < K; ++k)
      p += th[k] * phi[(int64_t)k * c.n_vocab + c.word[i]];
    total += std::log(std::max(p, 1e-300));
  }
  return n ? total / (double)n : 0.0;
}

void counts_to_estimates(const std::vector<double>& ndk,
                         const std::vector<double>& nwk, int32_t D, int32_t V,
                         int K, double alpha, double eta,
                         std::vector<double>* theta,
                         std::vector<double>* phi) {
  theta->assign((int64_t)D * K, 0.0);
  phi->assign((int64_t)K * V, 0.0);
  for (int32_t d = 0; d < D; ++d) {
    double s = 0.0;
    for (int k = 0; k < K; ++k) s += ndk[(int64_t)d * K + k];
    const double denom = s + K * alpha;
    for (int k = 0; k < K; ++k)
      (*theta)[(int64_t)d * K + k] = (ndk[(int64_t)d * K + k] + alpha) / denom;
  }
  std::vector<double> nk(K, 0.0);
  for (int32_t v = 0; v < V; ++v)
    for (int k = 0; k < K; ++k) nk[k] += nwk[(int64_t)v * K + k];
  for (int k = 0; k < K; ++k) {
    const double denom = nk[k] + V * eta;
    for (int32_t v = 0; v < V; ++v)
      (*phi)[(int64_t)k * V + v] = (nwk[(int64_t)v * K + k] + eta) / denom;
  }
}

// ---------------------------------------------------------------------------
// Collapsed Gibbs (exact when n_threads == 1; AD-LDA merge otherwise).
// ---------------------------------------------------------------------------

struct GibbsShard {
  int64_t lo = 0, hi = 0;          // token range (doc-contiguous)
  std::vector<int32_t> nwk;        // private copy of word-topic counts [V*K]
  std::vector<int32_t> nk;         // private topic totals [K]
  std::mt19937_64 rng;
};

void gibbs_run(const Corpus& c, int K, double alpha, double eta, int n_sweeps,
               int burn_in, uint64_t seed, int n_threads, float* theta_out,
               float* phi_out, double* ll_out) {
  const int32_t D = c.n_docs, V = c.n_vocab;
  const int64_t N = c.n_tokens();
  const double veta = (double)V * eta;

  std::vector<int32_t> z(N);
  std::vector<int32_t> ndk((int64_t)D * K, 0);
  std::vector<int32_t> nwk_global((int64_t)V * K, 0);
  std::vector<int32_t> nk_global(K, 0);

  std::mt19937_64 init_rng(seed);
  for (int64_t i = 0; i < N; ++i) {
    int32_t t = (int32_t)(init_rng() % (uint64_t)K);
    z[i] = t;
    ++ndk[(int64_t)c.doc[i] * K + t];
    ++nwk_global[(int64_t)c.word[i] * K + t];
    ++nk_global[t];
  }

  n_threads = std::max(1, n_threads);
  std::vector<GibbsShard> shards(n_threads);
  {
    // Doc-contiguous token split so ndk rows are thread-private.
    int64_t per = (N + n_threads - 1) / std::max(1, n_threads);
    int64_t lo = 0;
    for (int t = 0; t < n_threads; ++t) {
      int64_t hi = std::min(N, lo + per);
      // advance hi to a document boundary
      while (hi < N && hi > 0 && c.doc[hi] == c.doc[hi - 1]) ++hi;
      shards[t].lo = lo;
      shards[t].hi = hi;
      shards[t].rng.seed(seed ^ (0x9e3779b97f4a7c15ULL * (t + 1)));
      lo = hi;
    }
  }

  std::vector<double> acc_ndk((int64_t)D * K, 0.0);
  std::vector<double> acc_nwk((int64_t)V * K, 0.0);
  int n_acc = 0;

  auto sweep_shard = [&](GibbsShard& sh) {
    std::vector<double> probs(K);
    std::uniform_real_distribution<double> unif(0.0, 1.0);
    int32_t* nwk = sh.nwk.empty() ? nwk_global.data() : sh.nwk.data();
    int32_t* nk = sh.nk.empty() ? nk_global.data() : sh.nk.data();
    for (int64_t i = sh.lo; i < sh.hi; ++i) {
      const int32_t d = c.doc[i], w = c.word[i], old = z[i];
      int32_t* nd = &ndk[(int64_t)d * K];
      int32_t* nw = &nwk[(int64_t)w * K];
      --nd[old];
      --nw[old];
      --nk[old];
      double total = 0.0;
      for (int k = 0; k < K; ++k) {
        const double p = (nd[k] + alpha) * (nw[k] + eta) / (nk[k] + veta);
        total += p;
        probs[k] = total;
      }
      const double u = unif(sh.rng) * total;
      int t = 0;
      while (t < K - 1 && probs[t] < u) ++t;
      z[i] = t;
      ++nd[t];
      ++nw[t];
      ++nk[t];
    }
  };

  std::vector<double> theta, phi;
  for (int s = 0; s < n_sweeps; ++s) {
    if (n_threads == 1) {
      sweep_shard(shards[0]);
    } else {
      // AD-LDA: each thread samples against a private snapshot of the
      // word-topic counts; deltas merged after the sweep — the same
      // stale-counts compromise as the reference's per-iteration MPI
      // reduce and onix's per-sweep psum (SURVEY.md §2.2).
      for (auto& sh : shards) {
        sh.nwk = nwk_global;
        sh.nk = nk_global;
      }
      std::vector<std::thread> threads;
      for (auto& sh : shards)
        threads.emplace_back([&sweep_shard, &sh] { sweep_shard(sh); });
      for (auto& th : threads) th.join();
      // allreduce: global += sum of per-shard deltas
      std::vector<int64_t> sum_nwk((int64_t)V * K, 0);
      std::vector<int64_t> sum_nk(K, 0);
      for (auto& sh : shards) {
        for (int64_t j = 0; j < (int64_t)V * K; ++j)
          sum_nwk[j] += sh.nwk[j] - nwk_global[j];
        for (int k = 0; k < K; ++k) sum_nk[k] += sh.nk[k] - nk_global[k];
      }
      for (int64_t j = 0; j < (int64_t)V * K; ++j)
        nwk_global[j] += (int32_t)sum_nwk[j];
      for (int k = 0; k < K; ++k) nk_global[k] += (int32_t)sum_nk[k];
    }

    if (s >= burn_in) {
      for (int64_t j = 0; j < (int64_t)D * K; ++j) acc_ndk[j] += ndk[j];
      for (int64_t j = 0; j < (int64_t)V * K; ++j)
        acc_nwk[j] += nwk_global[j];
      ++n_acc;
    }
    if (ll_out) {
      std::vector<double> ndk_d(ndk.begin(), ndk.end());
      std::vector<double> nwk_d(nwk_global.begin(), nwk_global.end());
      counts_to_estimates(ndk_d, nwk_d, D, V, K, alpha, eta, &theta, &phi);
      ll_out[s] = mean_loglik(c, theta, phi, K);
    }
  }

  // Posterior-mean estimates from averaged counts (rank stability for the
  // judged top-k metric — same trick as the JAX engine).
  std::vector<double> ndk_f, nwk_f;
  if (n_acc > 0) {
    ndk_f.resize((int64_t)D * K);
    nwk_f.resize((int64_t)V * K);
    for (int64_t j = 0; j < (int64_t)D * K; ++j) ndk_f[j] = acc_ndk[j] / n_acc;
    for (int64_t j = 0; j < (int64_t)V * K; ++j) nwk_f[j] = acc_nwk[j] / n_acc;
  } else {
    ndk_f.assign(ndk.begin(), ndk.end());
    nwk_f.assign(nwk_global.begin(), nwk_global.end());
  }
  counts_to_estimates(ndk_f, nwk_f, D, V, K, alpha, eta, &theta, &phi);
  for (int64_t j = 0; j < (int64_t)D * K; ++j) theta_out[j] = (float)theta[j];
  for (int64_t j = 0; j < (int64_t)K * V; ++j) phi_out[j] = (float)phi[j];
}

// ---------------------------------------------------------------------------
// Variational EM (Blei lda-c lineage).
// ---------------------------------------------------------------------------

double digamma_(double x) {
  // Asymptotic expansion with recurrence shift (standard; accurate ~1e-12).
  double result = 0.0;
  while (x < 6.0) {
    result -= 1.0 / x;
    x += 1.0;
  }
  const double x1 = 1.0 / x, x2 = x1 * x1;
  result += std::log(x) - 0.5 * x1 -
            x2 * (1.0 / 12.0 - x2 * (1.0 / 120.0 - x2 / 252.0));
  return result;
}

struct DocView {
  int64_t lo = 0, hi = 0;  // CSR range into the sparse arrays
};

// Per-document E-step: iterate gamma/phi fixed point; accumulate
// class-word sufficient statistics. Returns the doc's likelihood bound
// contribution (up to constants independent of the variational params).
double e_step_doc(const int32_t* words, const int32_t* counts, int64_t lo,
                  int64_t hi, const std::vector<double>& log_beta, int K,
                  int32_t V, double alpha, int var_max_iter, double var_conv,
                  double* gamma_d, std::vector<double>& sstats_local) {
  const int64_t n_terms = hi - lo;
  double doc_total = 0.0;
  for (int64_t j = lo; j < hi; ++j) doc_total += counts[j];

  std::vector<double> phi((size_t)n_terms * K);
  std::vector<double> dig(K);
  for (int k = 0; k < K; ++k) {
    gamma_d[k] = alpha + doc_total / K;
    dig[k] = digamma_(gamma_d[k]);
  }

  double old_ll = 0.0;
  for (int it = 0; it < var_max_iter; ++it) {
    for (int k = 0; k < K; ++k) gamma_d[k] = alpha;
    for (int64_t j = 0; j < n_terms; ++j) {
      const int32_t w = words[lo + j];
      double maxv = -1e300;
      double* ph = &phi[(size_t)j * K];
      for (int k = 0; k < K; ++k) {
        ph[k] = dig[k] + log_beta[(int64_t)k * V + w];
        maxv = std::max(maxv, ph[k]);
      }
      double norm = 0.0;
      for (int k = 0; k < K; ++k) {
        ph[k] = std::exp(ph[k] - maxv);
        norm += ph[k];
      }
      for (int k = 0; k < K; ++k) {
        ph[k] /= norm;
        gamma_d[k] += counts[lo + j] * ph[k];
      }
    }
    for (int k = 0; k < K; ++k) dig[k] = digamma_(gamma_d[k]);
    // Convergence check on the phi-entropy-free partial bound.
    double ll = 0.0;
    for (int64_t j = 0; j < n_terms; ++j) {
      const int32_t w = words[lo + j];
      const double* ph = &phi[(size_t)j * K];
      for (int k = 0; k < K; ++k)
        if (ph[k] > 1e-12)
          ll += counts[lo + j] * ph[k] *
                (dig[k] + log_beta[(int64_t)k * V + w] - std::log(ph[k]));
    }
    if (it > 0 && std::fabs(ll - old_ll) < var_conv * std::fabs(old_ll)) {
      old_ll = ll;
      break;
    }
    old_ll = ll;
  }
  for (int64_t j = 0; j < n_terms; ++j) {
    const int32_t w = words[lo + j];
    const double* ph = &phi[(size_t)j * K];
    for (int k = 0; k < K; ++k)
      sstats_local[(int64_t)k * V + w] += counts[lo + j] * ph[k];
  }
  return old_ll;
}

void vem_run(const int32_t* doc_ids, const int32_t* word_ids,
             const int32_t* counts, int64_t nnz, int32_t D, int32_t V, int K,
             double alpha, double eta, int em_max_iter, double em_conv,
             int var_max_iter, double var_conv, uint64_t seed, int n_threads,
             float* theta_out, float* phi_out, double* ll_out) {
  // CSR doc ranges (input triples must be grouped by doc; enforce by sort).
  std::vector<int64_t> order(nnz);
  for (int64_t i = 0; i < nnz; ++i) order[i] = i;
  std::stable_sort(order.begin(), order.end(),
                   [&](int64_t a, int64_t b) { return doc_ids[a] < doc_ids[b]; });
  std::vector<int32_t> w_s(nnz), c_s(nnz), d_s(nnz);
  for (int64_t i = 0; i < nnz; ++i) {
    d_s[i] = doc_ids[order[i]];
    w_s[i] = word_ids[order[i]];
    c_s[i] = counts[order[i]];
  }
  std::vector<DocView> docs(D);
  {
    int64_t i = 0;
    for (int32_t d = 0; d < D; ++d) {
      docs[d].lo = i;
      while (i < nnz && d_s[i] == d) ++i;
      docs[d].hi = i;
    }
  }

  // Seeded init: beta from smoothed random counts (lda-c "random" init).
  std::mt19937_64 rng(seed);
  std::vector<double> log_beta((int64_t)K * V);
  {
    std::uniform_real_distribution<double> unif(0.0, 1.0);
    for (int k = 0; k < K; ++k) {
      double norm = 0.0;
      for (int32_t v = 0; v < V; ++v) {
        const double x = unif(rng) + 1.0 / V;
        log_beta[(int64_t)k * V + v] = x;
        norm += x;
      }
      for (int32_t v = 0; v < V; ++v)
        log_beta[(int64_t)k * V + v] =
            std::log(log_beta[(int64_t)k * V + v] / norm);
    }
  }

  std::vector<double> gamma((int64_t)D * K, 0.0);
  n_threads = std::max(1, n_threads);

  double old_ll = -1e300;
  for (int iter = 0; iter < em_max_iter; ++iter) {
    std::vector<std::vector<double>> sstats(
        n_threads, std::vector<double>((int64_t)K * V, 0.0));
    std::vector<double> lls(n_threads, 0.0);
    std::atomic<int32_t> next_doc{0};
    auto worker = [&](int t) {
      for (;;) {
        const int32_t d = next_doc.fetch_add(1);
        if (d >= D) break;
        lls[t] += e_step_doc(w_s.data(), c_s.data(), docs[d].lo, docs[d].hi,
                             log_beta, K, V, alpha, var_max_iter, var_conv,
                             &gamma[(int64_t)d * K], sstats[t]);
      }
    };
    std::vector<std::thread> threads;
    for (int t = 0; t < n_threads; ++t) threads.emplace_back(worker, t);
    for (auto& th : threads) th.join();

    // M-step: beta_kw ∝ sstats + eta (smoothed), reduced across threads —
    // the shape of the reference's MPI_Reduce to rank 0 (SURVEY.md §3.1).
    double ll = 0.0;
    for (int t = 0; t < n_threads; ++t) ll += lls[t];
    for (int t = 1; t < n_threads; ++t)
      for (int64_t j = 0; j < (int64_t)K * V; ++j) sstats[0][j] += sstats[t][j];
    for (int k = 0; k < K; ++k) {
      double norm = 0.0;
      for (int32_t v = 0; v < V; ++v) norm += sstats[0][(int64_t)k * V + v] + eta;
      const double log_norm = std::log(norm);
      for (int32_t v = 0; v < V; ++v)
        log_beta[(int64_t)k * V + v] =
            std::log(sstats[0][(int64_t)k * V + v] + eta) - log_norm;
    }
    if (ll_out) ll_out[iter] = ll;
    if (iter > 0 && std::fabs(ll - old_ll) < em_conv * std::fabs(old_ll)) {
      if (ll_out)
        for (int j = iter + 1; j < em_max_iter; ++j) ll_out[j] = ll;
      break;
    }
    old_ll = ll;
  }

  for (int32_t d = 0; d < D; ++d) {
    double s = 0.0;
    for (int k = 0; k < K; ++k) s += gamma[(int64_t)d * K + k];
    for (int k = 0; k < K; ++k)
      theta_out[(int64_t)d * K + k] = (float)(gamma[(int64_t)d * K + k] / s);
  }
  for (int64_t j = 0; j < (int64_t)K * V; ++j)
    phi_out[j] = (float)std::exp(log_beta[j]);
}

}  // namespace

// ---------------------------------------------------------------------------
// C ABI (ctypes surface — onix/oracle.py)
// ---------------------------------------------------------------------------

extern "C" {

int onix_lda_gibbs(const int32_t* doc_ids, const int32_t* word_ids,
                   const int32_t* counts, int64_t nnz, int32_t n_docs,
                   int32_t n_vocab, int32_t n_topics, double alpha, double eta,
                   int32_t n_sweeps, int32_t burn_in, uint64_t seed,
                   int32_t n_threads, float* theta_out, float* phi_out,
                   double* ll_out) {
  if (!doc_ids || !word_ids || !counts || !theta_out || !phi_out) return 1;
  if (n_topics < 2 || n_docs < 1 || n_vocab < 1) return 2;
  Corpus c = expand(doc_ids, word_ids, counts, nnz, n_docs, n_vocab);
  sort_by_doc(c);
  gibbs_run(c, n_topics, alpha, eta, n_sweeps, burn_in, seed, n_threads,
            theta_out, phi_out, ll_out);
  return 0;
}

int onix_lda_vem(const int32_t* doc_ids, const int32_t* word_ids,
                 const int32_t* counts, int64_t nnz, int32_t n_docs,
                 int32_t n_vocab, int32_t n_topics, double alpha, double eta,
                 int32_t em_max_iter, double em_conv, int32_t var_max_iter,
                 double var_conv, uint64_t seed, int32_t n_threads,
                 float* theta_out, float* phi_out, double* ll_out) {
  if (!doc_ids || !word_ids || !counts || !theta_out || !phi_out) return 1;
  if (n_topics < 2 || n_docs < 1 || n_vocab < 1) return 2;
  vem_run(doc_ids, word_ids, counts, nnz, n_docs, n_vocab, n_topics, alpha,
          eta, em_max_iter, em_conv, var_max_iter, var_conv, seed, n_threads,
          theta_out, phi_out, ll_out);
  return 0;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// CLI — file-contract parity with oni-lda-c (SURVEY.md §3.1, §5.4):
//   lda_ref <gibbs|vem> <K> <alpha> <eta> <iters> <seed> <corpus.ldac> <outdir>
// writes final.gamma (D x K), final.beta (K x V log-probs), likelihood.dat.
// ---------------------------------------------------------------------------

#ifndef ONIX_LDA_REF_NO_MAIN
int main(int argc, char** argv) {
  if (argc != 9 && argc != 10) {
    std::fprintf(stderr,
                 "usage: %s <gibbs|vem> <K> <alpha> <eta> <iters> <seed> "
                 "<corpus.ldac> <outdir> [n_vocab]\n",
                 argv[0]);
    return 2;
  }
  const std::string mode = argv[1];
  if (mode != "gibbs" && mode != "vem") {
    std::fprintf(stderr, "unknown mode %s (want gibbs|vem)\n", mode.c_str());
    return 1;
  }
  const int K = std::atoi(argv[2]);
  const double alpha = std::atof(argv[3]);
  const double eta = std::atof(argv[4]);
  const int iters = std::atoi(argv[5]);
  const uint64_t seed = (uint64_t)std::strtoull(argv[6], nullptr, 10);
  const std::string corpus_path = argv[7];
  const std::string outdir = argv[8];

  // Parse lda-c format: `N w:c w:c ...` per line. n_vocab may be given
  // explicitly (the true vocabulary size — matches SparseCounts.read_ldac);
  // otherwise it is inferred as max word id + 1.
  std::vector<int32_t> d, w, c;
  int32_t n_docs = 0;
  int32_t n_vocab = (argc == 10) ? std::atoi(argv[9]) : 0;
  {
    FILE* f = std::fopen(corpus_path.c_str(), "r");
    if (!f) {
      std::perror("corpus");
      return 1;
    }
    char* line = nullptr;
    size_t cap = 0;
    while (getline(&line, &cap, f) != -1) {
      char* p = line;
      long n_terms = std::strtol(p, &p, 10);
      for (long j = 0; j < n_terms; ++j) {
        long wi = std::strtol(p, &p, 10);
        if (*p == ':') ++p;
        long ci = std::strtol(p, &p, 10);
        if (wi < 0 || ci <= 0) {
          std::fprintf(stderr, "corpus line %d: bad entry %ld:%ld\n",
                       n_docs + 1, wi, ci);
          free(line);
          std::fclose(f);
          return 1;
        }
        d.push_back(n_docs);
        w.push_back((int32_t)wi);
        c.push_back((int32_t)ci);
        n_vocab = std::max(n_vocab, (int32_t)wi + 1);
      }
      ++n_docs;
    }
    free(line);
    std::fclose(f);
  }

  std::vector<float> theta((int64_t)n_docs * K), phi((int64_t)K * n_vocab);
  std::vector<double> ll(iters, 0.0);
  int rc;
  if (mode == "gibbs") {
    rc = onix_lda_gibbs(d.data(), w.data(), c.data(), (int64_t)d.size(),
                        n_docs, n_vocab, K, alpha, eta, iters, iters / 2, seed,
                        1, theta.data(), phi.data(), ll.data());
  } else {
    rc = onix_lda_vem(d.data(), w.data(), c.data(), (int64_t)d.size(), n_docs,
                      n_vocab, K, alpha, eta, iters, 1e-5, 30, 1e-6, seed, 1,
                      theta.data(), phi.data(), ll.data());
  }
  if (rc != 0) return rc;

  auto write_matrix = [&](const std::string& path, const float* m,
                          int64_t rows, int64_t cols, bool log_space) {
    FILE* f = std::fopen(path.c_str(), "w");
    if (!f) {
      std::perror(path.c_str());
      std::exit(1);
    }
    for (int64_t r = 0; r < rows; ++r) {
      for (int64_t j = 0; j < cols; ++j) {
        const double x = m[r * cols + j];
        std::fprintf(f, "%s%.10f", j ? " " : "",
                     log_space ? std::log(std::max(x, 1e-30)) : x);
      }
      std::fputc('\n', f);
    }
    std::fclose(f);
  };
  write_matrix(outdir + "/final.gamma", theta.data(), n_docs, K, false);
  write_matrix(outdir + "/final.beta", phi.data(), K, n_vocab, true);
  {
    FILE* f = std::fopen((outdir + "/likelihood.dat").c_str(), "w");
    for (int i = 0; i < iters; ++i) std::fprintf(f, "%.10f\n", ll[i]);
    std::fclose(f);
  }
  return 0;
}
#endif
