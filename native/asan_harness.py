#!/usr/bin/env python3
"""Sanitized-suite harness (SURVEY.md §5.2): drives BOTH native CLI
binaries built with -fsanitize=address,undefined through their happy
paths and their malformed-input paths. ASan/UBSan findings abort the
process with a nonzero exit and a report on stderr, so "exit code is
what the contract says and stderr carries no sanitizer report" IS the
assertion.

Run via `make -C native asan-test` (also wrapped by
tests/test_native_asan.py). Stdlib only — the harness must not depend
on the repo's Python package (it tests the binaries, not the wrappers).
"""

import pathlib
import struct
import subprocess
import sys
import tempfile

HERE = pathlib.Path(__file__).parent
LDA = HERE / "lda_ref" / "build-asan" / "lda_ref"
NFD = HERE / "nfdecode" / "build-asan" / "nfdecode"
PCD = HERE / "pcapdns" / "build-asan" / "pcapdns"
FAILED = []


def run(binary, args, expect_rc, tag, stdin_ok_empty=True):
    p = subprocess.run([str(binary), *map(str, args)], capture_output=True,
                       text=True, timeout=300)
    sanitizer = ("ERROR: AddressSanitizer" in p.stderr
                 or "runtime error:" in p.stderr
                 or "ERROR: LeakSanitizer" in p.stderr)
    ok = (p.returncode == expect_rc) and not sanitizer
    print(f"[{'ok' if ok else 'FAIL'}] {tag}: rc={p.returncode} "
          f"(want {expect_rc}){' SANITIZER REPORT' if sanitizer else ''}")
    if not ok:
        sys.stderr.write(p.stderr[-2000:] + "\n")
        FAILED.append(tag)
    return p


def v5_blob(n=7):
    """Minimal valid NetFlow v5 export packet stream."""
    out = b""
    hdr = struct.pack(">HHIIIIBBH", 5, n, 3_600_000, 1467936000, 0, 0, 0, 0, 0)
    recs = b""
    for i in range(n):
        recs += struct.pack(">IIIHHIIIIHHBBBBHHBBH",
                            (10 << 24) | i, (192 << 24) | i, 0, 0, 0,
                            5 + i, 1000 + i, 3_500_000, 3_590_000,
                            1024 + i, 443, 0, 0x18, 6, 0, 0, 0, 24, 24, 0)
    return hdr + recs


def v9_blob(pad_template=False):
    """One v9 packet: template (optionally zero-padded) + 2 records."""
    fields = [(8, 4), (12, 4), (7, 2), (11, 2), (4, 1), (6, 1),
              (2, 4), (1, 4), (22, 4), (21, 4)]
    tpl = struct.pack(">HH", 256, len(fields))
    for t, ln in fields:
        tpl += struct.pack(">HH", t, ln)
    if pad_template:
        tpl += b"\0" * 4
    tpl_set = struct.pack(">HH", 0, 4 + len(tpl)) + tpl
    rec = struct.pack(">IIHHBBIIII", 10 << 24, 192 << 24, 1024, 443, 6,
                      0x18, 5, 1000, 3_500_000, 3_590_000)
    data_set = struct.pack(">HH", 256, 4 + 2 * len(rec)) + rec + rec
    hdr = struct.pack(">HHIIII", 9, 3, 3_600_000, 1467936000, 0, 0)
    return hdr + tpl_set + data_set


def v9_options_blob(bad_scope_len=False):
    """v9 options template flowset (RFC 3954 §6.1: scope System +
    SAMPLING_INTERVAL) plus its data record; with bad_scope_len the
    scope byte length is not a multiple of the 4-byte spec size."""
    scope_len = 3 if bad_scope_len else 4
    opt = struct.pack(">HHH", 400, scope_len, 4)
    opt += struct.pack(">HH", 1, 4) + struct.pack(">HH", 34, 4)
    opt_set = struct.pack(">HH", 1, 4 + len(opt)) + opt
    opt_data = struct.pack(">HHII", 400, 12, 0, 64)
    hdr = struct.pack(">HHIIII", 9, 2, 3_600_000, 1467936000, 0, 0)
    return hdr + opt_set + opt_data


def ipfix_blob(long_varlen=False, strip_template=False):
    """One IPFIX message: template (enterprise + variable-length fields)
    + options template set + 2 data records."""
    fields = [(8, 4), (12, 4), (7, 2), (11, 2), (4, 1), (6, 1),
              (0x8000 | 55, 4), (2, 4), (1, 4), (82, 0xFFFF),
              (152, 8), (153, 8)]
    tpl = struct.pack(">HH", 310, len(fields))
    for t, ln in fields:
        tpl += struct.pack(">HH", t, ln)
        if t & 0x8000:
            tpl += struct.pack(">I", 29305)
    tpl_set = struct.pack(">HH", 2, 4 + len(tpl)) + tpl
    opt_body = struct.pack(">HHH", 320, 2, 1) + \
        struct.pack(">HH", 130, 4) + struct.pack(">HH", 41, 8)
    opt_set = struct.pack(">HH", 3, 4 + len(opt_body)) + opt_body
    name = b"eth0"
    vl = (struct.pack(">BH", 255, len(name)) + name if long_varlen
          else struct.pack(">B", len(name)) + name)
    rec = struct.pack(">IIHHBB", 10 << 24, 192 << 24, 1024, 443, 6, 0x18) \
        + struct.pack(">I", 0xDEADBEEF) + struct.pack(">II", 5, 1000) \
        + vl + struct.pack(">QQ", 1467936000000, 1467936060000)
    data_set = struct.pack(">HH", 310, 4 + 2 * len(rec)) + rec + rec
    sets = (b"" if strip_template else tpl_set + opt_set) + data_set
    hdr = struct.pack(">HHIII", 10, 16 + len(sets), 1467936000, 0, 0)
    return hdr + sets


def nfcapd_blob(compressed=False, bad_version=False, torn=False,
                v6_row=False, huge_record_size=False, compression=None,
                corrupt_payload=False):
    """Minimal nfcapd layout-v1 file: header, stat record, one type-2
    block with an extension-map record + two common records.
    `compression` ("lzo"/"lz4"/"bz2") really compresses the block via
    the fixture encoders; `corrupt_payload` then truncates the
    compressed payload mid-stream (a torn compressed block — the
    decompressors must bounds-fail, not overrun)."""
    def common(flags, sport):
        body = struct.pack("<HHHHIIBBBBHH", flags, 0, 100, 200,
                           1467979200, 1467979260, 0, 0x18, 6, 0,
                           sport, 443)
        if flags & 0x1:
            body += b"\x20\x01" + b"\x00" * 14 + b"\x20\x02" + b"\x00" * 14
        else:
            body += struct.pack("<II", 0x0A000001, 0x0A000002)
        body += struct.pack("<Q" if flags & 0x2 else "<I", 12)
        body += struct.pack("<Q" if flags & 0x4 else "<I", 3400)
        return struct.pack("<HH", 1, 4 + len(body)) + body

    ext_map = struct.pack("<HHHH", 2, 12, 0, 4) + struct.pack("<HH", 4, 0)
    recs = [ext_map, common(0, 1025), common(0x2 | 0x4, 2048)]
    if v6_row:
        recs.append(common(0x1, 53))
    if huge_record_size:
        recs.append(struct.pack("<HH", 1, 60000))   # size past block end
    payload = b"".join(recs)
    flags = 0x1 if compressed else 0
    if compression is not None:
        # Local stdlib-only encoders (the harness must not import the
        # repo's Python package): LZO as one initial literal run + EOS
        # (payload <= 238 bytes here), LZ4 as one all-literals
        # sequence, BZ2 via the stdlib module. All are valid streams
        # of their formats; the full-spec decoders are the target.
        if compression == "lzo":
            assert len(payload) <= 238, "harness lzo run limit"
            flags, payload = 0x1, (bytes([len(payload) + 17]) + payload
                                   + b"\x11\x00\x00")
        elif compression == "lz4":
            lit = len(payload)
            head = bytes([min(lit, 15) << 4])
            if lit >= 15:
                rest = lit - 15
                head += b"\xff" * (rest // 255) + bytes([rest % 255])
            flags, payload = 0x10, head + payload
        else:
            import bz2
            flags, payload = 0x8, bz2.compress(payload)
        if corrupt_payload:
            payload = payload[: len(payload) // 2]
    block = struct.pack("<IIHH", len(recs), len(payload), 2, 0) + payload
    hdr = struct.pack("<HHII", 0xA50C, 7 if bad_version else 1, flags, 1)
    hdr += b"asan".ljust(128, b"\0")
    out = hdr + struct.pack("<Q", 2) + b"\0" * 128 + block
    return out[:len(out) - 9] if torn else out


def _have_libbz2() -> bool:
    import ctypes
    for name in ("libbz2.so.1.0", "libbz2.so.1", "libbz2.so"):
        try:
            ctypes.CDLL(name)
            return True
        except OSError:
            continue
    return False


def pcapng_blob(truncate=0, bad_bom=False):
    """Minimal pcapng: SHB + IDB(ethernet) + one EPB wrapping the same
    DNS frame dns_pcap_blob emits."""
    frame = dns_pcap_blob()[40:]      # strip pcap global+record headers

    def block(btype, body):
        pad = (-len(body)) % 4
        total = 12 + len(body) + pad
        return (struct.pack("<II", btype, total) + body + b"\0" * pad
                + struct.pack("<I", total))

    bom = 0xDEADBEEF if bad_bom else 0x1A2B3C4D
    ts = 1467979200 * 1_000_000      # microsecond units (default resol)
    out = block(0x0A0D0D0A, struct.pack("<IHHq", bom, 1, 0, -1))
    out += block(1, struct.pack("<HHI", 1, 0, 0))
    out += block(6, struct.pack("<IIIII", 0, ts >> 32, ts & 0xFFFFFFFF,
                                len(frame), len(frame)) + frame)
    return out[:len(out) - truncate] if truncate else out


def dns_pcap_blob(truncate=0, ipv6=False, ext_headers=False):
    """One-response DNS pcap (Ethernet/IPv4 or /IPv6/UDP), optionally
    torn; ext_headers prepends a hop-by-hop extension header to the v6
    packet so the chain walk is exercised sanitized."""
    name = b"\x03www\x07example\x03com\x00"
    dns = struct.pack(">HHHHHH", 0x1234, 0x8180, 1, 0, 0, 0) + name + \
        struct.pack(">HH", 1, 1)
    udp = struct.pack(">HHHH", 53, 40000, 8 + len(dns), 0) + dns
    if ipv6:
        payload = udp
        nh = 17
        if ext_headers:
            payload = struct.pack(">BB", 17, 0) + b"\0" * 6 + payload
            nh = 0                       # hop-by-hop first
        ip = struct.pack(">IHBB", 6 << 28, len(payload), nh, 64)
        ip += bytes.fromhex("20010db8000000000000000000000053")
        ip += bytes.fromhex("20010db8000000000000000000000001")
        ip += payload
        etype = 0x86DD
        pkt_l3 = ip
    else:
        pkt_l3 = struct.pack(">BBHHHBBHII", 0x45, 0, 20 + len(udp), 0, 0,
                             64, 17, 0, 0xC0000235, 0x0A000001) + udp
        etype = 0x0800
    eth = b"\x02" * 6 + b"\x04" * 6 + struct.pack(">H", etype)
    pkt = eth + pkt_l3
    hdr = struct.pack("<IHHiIII", 0xA1B2C3D4, 2, 4, 0, 0, 1 << 16, 1)
    rec = struct.pack("<IIII", 1467936000, 0, len(pkt), len(pkt))
    blob = hdr + rec + pkt
    return blob[: len(blob) - truncate] if truncate else blob


def main() -> int:
    for b in (LDA, NFD, PCD):
        if not b.exists():
            print(f"missing sanitized binary {b} — run `make asan` first")
            return 2
    tmp = pathlib.Path(tempfile.mkdtemp(prefix="onix-asan-"))

    # -- pcapdns ----------------------------------------------------------
    for name, blob, rc in [
        ("dns response", dns_pcap_blob(), 0),
        ("dns response over ipv6", dns_pcap_blob(ipv6=True), 0),
        ("ipv6 + hop-by-hop extension header",
         dns_pcap_blob(ipv6=True, ext_headers=True), 0),
        ("ipv6 torn mid-extension",
         dns_pcap_blob(ipv6=True, ext_headers=True, truncate=30), 1),
        ("torn record", dns_pcap_blob(truncate=9), 1),
        ("not a pcap", b"\x00" * 48, 1),
        ("header only", dns_pcap_blob()[:24], 0),   # empty capture is fine
        ("tiny file", b"\xa1", 1),
        # pcapng container: happy, torn trailer, bad byte-order magic
        ("pcapng one response", pcapng_blob(), 0),
        ("pcapng torn block", pcapng_blob(truncate=5), 1),
        ("pcapng bad byte-order magic", pcapng_blob(bad_bom=True), 1),
    ]:
        p = tmp / "cap.pcap"
        p.write_bytes(blob)
        run(PCD, [p], rc, f"pcapdns: {name}")
    run(PCD, [], 2, "pcapdns: no args")

    # -- nfdecode ---------------------------------------------------------
    for name, blob, rc in [
        ("v5 happy path", v5_blob(), 0),
        ("v9 happy path", v9_blob(), 0),
        ("v9 padded template (RFC 3954 §5.2)", v9_blob(pad_template=True), 0),
        # contract: an empty capture is malformed (matches nfdump; a
        # zero-byte file at ingest means a broken exporter, not a quiet day)
        ("empty file", b"", 1),
        ("truncated v5", v5_blob()[:31], 1),
        ("truncated v9 set", v9_blob()[:-7], 1),
        ("garbage", b"\xff" * 97, 1),
        ("v9 oversized template count",
         struct.pack(">HHIIII", 9, 1, 0, 0, 0, 0)
         + struct.pack(">HH", 0, 12) + struct.pack(">HH", 256, 60000), 1),
        # options records are exporter state, never flow rows — a
        # stream of ONLY options sets decodes to zero flows (rc 0)
        ("v9 options template + sampling record", v9_options_blob(), 0),
        ("v9 options bad scope length", v9_options_blob(bad_scope_len=True),
         1),
        ("ipfix happy path", ipfix_blob(), 0),
        ("ipfix long varlen prefix", ipfix_blob(long_varlen=True), 0),
        ("ipfix unknown template skipped", ipfix_blob(strip_template=True), 0),
        ("ipfix truncated", ipfix_blob()[:-5], 1),
        ("mixed v5+v9+ipfix", v5_blob() + v9_blob() + ipfix_blob(), 0),
        # nfcapd container (clean-room reader): happy, v6-skip,
        # compressed gate, torn block, bad version, lying record size
        ("nfcapd v1 happy path", nfcapd_blob(), 0),
        ("nfcapd v1 with ipv6 row", nfcapd_blob(v6_row=True), 0),
        ("nfcapd lying compressed flag", nfcapd_blob(compressed=True), 1),
        ("nfcapd torn block", nfcapd_blob(torn=True), 1),
        # compressed containers: happy decode per codec, then torn
        # compressed payloads (the bounds checks ARE the product here)
        ("nfcapd lzo compressed", nfcapd_blob(compression="lzo"), 0),
        ("nfcapd lz4 compressed", nfcapd_blob(compression="lz4"), 0),
        # BZ2 is dlopen-based: without a system libbz2 the decoder's
        # documented fallback is rc 1 ("compression unavailable"), so
        # the expected rc is probed, not assumed.
        ("nfcapd bz2 compressed", nfcapd_blob(compression="bz2"),
         0 if _have_libbz2() else 1),
        ("nfcapd lzo torn payload",
         nfcapd_blob(compression="lzo", corrupt_payload=True), 1),
        ("nfcapd lz4 torn payload",
         nfcapd_blob(compression="lz4", corrupt_payload=True), 1),
        ("nfcapd bz2 torn payload",
         nfcapd_blob(compression="bz2", corrupt_payload=True), 1),
        ("nfcapd bad layout version", nfcapd_blob(bad_version=True), 1),
        ("nfcapd record size past block end",
         nfcapd_blob(huge_record_size=True), 1),
    ]:
        p = tmp / "cap.bin"
        p.write_bytes(blob)
        run(NFD, [p], rc, f"nfdecode: {name}")
    run(NFD, [tmp / "does-not-exist"], 1, "nfdecode: missing file")
    run(NFD, [], 2, "nfdecode: no args")

    # -- lda_ref ----------------------------------------------------------
    corpus = tmp / "corpus.ldac"
    import random
    rng = random.Random(7)
    lines = []
    for _ in range(40):
        n_terms = rng.randint(1, 12)
        pairs = {rng.randrange(60): rng.randint(1, 4) for _ in range(n_terms)}
        lines.append(f"{len(pairs)} " +
                     " ".join(f"{w}:{c}" for w, c in pairs.items()))
    corpus.write_text("\n".join(lines) + "\n")
    for mode in ("gibbs", "vem"):
        out = tmp / mode
        out.mkdir()
        run(LDA, [mode, 5, 0.5, 0.05, 15, 1, corpus, out, 60],
            0, f"lda_ref: {mode} happy path")
        assert (out / "final.gamma").exists()

    bad = tmp / "bad.ldac"
    bad.write_text("1 -3:2\n")
    run(LDA, ["gibbs", 5, 0.5, 0.05, 5, 1, bad, tmp], 1,
        "lda_ref: negative word id rejected")
    bad2 = tmp / "bad2.ldac"
    bad2.write_text("2 1:1\n")          # count promises 2 pairs, has 1
    run(LDA, ["gibbs", 5, 0.5, 0.05, 5, 1, bad2, tmp], 1,
        "lda_ref: short line rejected")
    run(LDA, ["nope", 5, 0.5, 0.05, 5, 1, corpus, tmp], 1,
        "lda_ref: unknown mode")
    run(LDA, [], 2, "lda_ref: no args")

    if FAILED:
        print(f"\n{len(FAILED)} sanitized checks FAILED: {FAILED}")
        return 1
    print("\nall sanitized checks passed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
