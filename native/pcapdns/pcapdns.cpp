// onix-pcapdns — minimal pcap -> DNS-reply field extractor.
//
// The reference's DNS ingest runs tshark field-extraction over pcaps
// (SURVEY.md §3.2; reference README.md:30-33 "DNS pcaps"). tshark is a
// heavyweight dependency; this native extractor emits the exact same
// tab-separated field rows tshark would with
//   -T fields -e frame.time_epoch -e frame.len -e ip.src -e ip.dst
//   -e dns.qry.name -e dns.qry.type -e dns.flags.rcode
// for the packets the pipeline consumes: UDP/IPv4 DNS *responses*
// (QR=1 — "analysis of network flows and DNS replies", README.md:25).
// The ingest path drives real tshark when installed and falls back to
// this binary, so the TSV contract is identical either way
// (onix/ingest/pcap.py).
//
// Format coverage: classic pcap (magic a1b2c3d4 / d4c3b2a1, plus the
// a1b23c4d nanosecond variant) AND pcapng (Wireshark's default save
// format: SHB/IDB/EPB/SPB blocks, both byte orders, per-interface
// linktype + if_tsresol, unknown blocks skipped whole),
// Ethernet II with optional single
// 802.1Q VLAN tag, IPv4 (any IHL, non-fragmented) and IPv6 (RFC 8200,
// chainable extension headers walked, addresses printed in RFC 5952
// canonical form), UDP src or dst port 53. Question-section names are
// plain label sequences per RFC 1035 §4.1.2 (compression pointers,
// legal but rare in questions, terminate the name defensively).
// Malformed packets are skipped, never fatal —
// a capture with junk in the middle still yields its good rows
// (tshark's behavior too).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cmath>
#include <string>
#include <vector>

namespace {

uint16_t be16(const uint8_t* p) { return (uint16_t)((p[0] << 8) | p[1]); }
uint32_t rd32(const uint8_t* p, bool swap) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  if (swap) v = __builtin_bswap32(v);
  return v;
}
uint16_t rd16(const uint8_t* p, bool swap) {
  uint16_t v;
  std::memcpy(&v, p, 2);
  if (swap) v = __builtin_bswap16(v);
  return v;
}

void ip_str(uint32_t ip, char* out) {
  std::snprintf(out, 16, "%u.%u.%u.%u", (ip >> 24) & 255, (ip >> 16) & 255,
                (ip >> 8) & 255, ip & 255);
}

// RFC 5952 canonical text form (lowercase hex, longest zero run of >=2
// groups compressed to "::", leftmost on ties) — matches what tshark
// prints for ipv6.src/dst, so the TSV contract is identical for v6
// rows. `out` must hold >= 46 bytes.
void ip6_str(const uint8_t* addr, char* out) {
  uint16_t g[8];
  for (int i = 0; i < 8; ++i)
    g[i] = (uint16_t)((addr[2 * i] << 8) | addr[2 * i + 1]);
  int best = -1, best_len = 0;
  for (int i = 0; i < 8;) {
    if (g[i] == 0) {
      int j = i;
      while (j < 8 && g[j] == 0) ++j;
      if (j - i > best_len) { best = i; best_len = j - i; }
      i = j;
    } else {
      ++i;
    }
  }
  if (best_len < 2) best = -1;   // a single zero group is not compressed
  char* p = out;
  for (int i = 0; i < 8;) {
    if (i == best) {
      *p++ = ':';
      *p++ = ':';
      i += best_len;
      continue;
    }
    if (p != out && p[-1] != ':') *p++ = ':';
    p += std::snprintf(p, 6, "%x", g[i]);
    ++i;
  }
  *p = '\0';
}

// Parse the first question name at `off`; returns false on malformed.
bool qname(const uint8_t* dns, size_t dns_len, size_t* off,
           std::string* out) {
  out->clear();
  size_t o = *off;
  while (true) {
    if (o >= dns_len) return false;
    const uint8_t len = dns[o];
    if (len == 0) { ++o; break; }
    if ((len & 0xC0) == 0xC0) {       // compression pointer: stop here
      o += 2;
      break;
    }
    if (len > 63 || o + 1 + len > dns_len) return false;
    if (!out->empty()) out->push_back('.');
    for (size_t i = 0; i < len; ++i) {
      const char c = (char)dns[o + 1 + i];
      // control chars would corrupt the TSV contract
      out->push_back((c >= 0x20 && c != 0x7f && c != '\t') ? c : '?');
    }
    o += 1 + (size_t)len;
    if (out->size() > 1024) return false;
  }
  *off = o;
  return true;
}

// Process one Ethernet frame; emit a TSV row if it is a UDP DNS
// response. Returns 1 when a row was written, 0 otherwise. Shared by
// the classic-pcap and pcapng walkers.
int process_frame(const uint8_t* pkt, size_t incl, uint32_t orig,
                  double ts, FILE* out) {
  // Ethernet II (+ optional one 802.1Q tag)
  if (incl < 14) return 0;
  size_t l2 = 12;
  uint16_t etype = be16(pkt + l2);
  l2 += 2;
  if (etype == 0x8100) {
    if (incl < l2 + 4) return 0;
    etype = be16(pkt + l2 + 2);
    l2 += 4;
  }
  const uint8_t* udp;
  char a[46], b[46];
  if (etype == 0x0800) {            // IPv4
    if (incl < l2 + 20) return 0;
    const uint8_t* ip = pkt + l2;
    if ((ip[0] >> 4) != 4) return 0;
    const size_t ihl = (size_t)(ip[0] & 0x0F) * 4;
    if (ihl < 20 || incl < l2 + ihl + 8) return 0;
    if (ip[9] != 17) return 0;      // UDP
    const uint16_t frag = be16(ip + 6);
    if (frag & 0x1FFF) return 0;    // non-first fragment
    ip_str(((uint32_t)ip[12] << 24) | (ip[13] << 16) | (ip[14] << 8) |
               ip[15], a);
    ip_str(((uint32_t)ip[16] << 24) | (ip[17] << 16) | (ip[18] << 8) |
               ip[19], b);
    udp = ip + ihl;
  } else if (etype == 0x86DD) {     // IPv6 (RFC 8200)
    if (incl < l2 + 40) return 0;
    const uint8_t* ip6 = pkt + l2;
    if ((ip6[0] >> 4) != 6) return 0;
    uint8_t nh = ip6[6];
    size_t l3 = 40;
    // Walk chainable extension headers (hop-by-hop 0, routing 43,
    // destination options 60 — all share the (next, len8) shape);
    // fragments and anything else end the walk.
    for (int hops = 0;
         hops < 4 && (nh == 0 || nh == 43 || nh == 60); ++hops) {
      if (incl < l2 + l3 + 8) { nh = 0xFF; break; }
      const uint8_t* eh = pkt + l2 + l3;
      nh = eh[0];
      l3 += ((size_t)eh[1] + 1) * 8;
    }
    if (nh != 17) return 0;         // UDP
    if (incl < l2 + l3 + 8) return 0;
    ip6_str(ip6 + 8, a);
    ip6_str(ip6 + 24, b);
    udp = ip6 + l3;
  } else {
    return 0;                       // other L3
  }
  const uint16_t sport = be16(udp);
  const uint16_t dport = be16(udp + 2);
  if (sport != 53 && dport != 53) return 0;
  const size_t udp_len = be16(udp + 4);
  if (udp_len < 8 || (size_t)(udp - pkt) + udp_len > incl) return 0;

  const uint8_t* dns = udp + 8;
  const size_t dns_len = udp_len - 8;
  if (dns_len < 12) return 0;
  const uint16_t flags = be16(dns + 2);
  if (!(flags & 0x8000)) return 0;  // responses (QR=1) only
  const uint16_t qdcount = be16(dns + 4);
  if (qdcount < 1) return 0;
  size_t qoff = 12;
  std::string name;
  if (!qname(dns, dns_len, &qoff, &name)) return 0;
  if (qoff + 4 > dns_len) return 0;
  const uint16_t qtype = be16(dns + qoff);
  const uint16_t rcode = flags & 0x000F;

  std::fprintf(out, "%.6f\t%u\t%s\t%s\t%s\t%u\t%u\n", ts, orig, a, b,
               name.c_str(), qtype, rcode);
  return 1;
}

// Classic pcap: fixed 24-byte global header + 16-byte per-record
// headers.
int64_t walk_pcap(const uint8_t* buf, int64_t len, FILE* out) {
  const uint32_t magic_raw = rd32(buf, false);
  bool swap, nanos;
  switch (magic_raw) {
    case 0xA1B2C3D4u: swap = false; nanos = false; break;
    case 0xD4C3B2A1u: swap = true;  nanos = false; break;
    case 0xA1B23C4Du: swap = false; nanos = true;  break;
    case 0x4D3CB2A1u: swap = true;  nanos = true;  break;
    default: return -1;
  }
  const uint32_t linktype = rd32(buf + 20, swap);
  if (linktype != 1) return -1;       // DLT_EN10MB only
  int64_t emitted = 0;
  size_t off = 24;
  while (off + 16 <= (size_t)len) {
    const uint32_t ts_sec = rd32(buf + off, swap);
    const uint32_t ts_frac = rd32(buf + off + 4, swap);
    const uint32_t incl = rd32(buf + off + 8, swap);
    const uint32_t orig = rd32(buf + off + 12, swap);
    off += 16;
    if (incl > 1 << 22 || off + incl > (size_t)len) return -1;  // torn file
    const double ts = (double)ts_sec +
                      (double)ts_frac / (nanos ? 1e9 : 1e6);
    emitted += process_frame(buf + off, incl, orig, ts, out);
    off += incl;
  }
  return emitted;
}

// pcapng (the format Wireshark saves by default — without this, a
// .pcapng capture on a tshark-less host had no ingest path): Section
// Header Blocks set the byte order, Interface Description Blocks carry
// per-interface linktype + timestamp resolution (option 9,
// if_tsresol), Enhanced/Simple Packet Blocks carry the frames. Unknown
// block types are skipped whole by their declared length.
struct NgIface {
  bool ethernet = false;
  double ts_div = 1e6;      // timestamp units per second (default 1e-6 s)
};

int64_t walk_pcapng(const uint8_t* buf, int64_t len, FILE* out) {
  int64_t emitted = 0;
  size_t off = 0;
  bool swap = false;
  std::vector<NgIface> ifaces;
  uint32_t snaplen_guard = 1 << 22;
  while (off + 12 <= (size_t)len) {
    const uint32_t btype = rd32(buf + off, swap);
    uint32_t blen = rd32(buf + off + 4, swap);
    if (btype == 0x0A0D0D0Au) {       // SHB: (re)establish byte order
      const uint32_t bom = rd32(buf + off + 8, false);
      if (bom == 0x1A2B3C4Du) swap = false;
      else if (bom == 0x4D3C2B1Au) swap = true;
      else return -1;
      blen = rd32(buf + off + 4, swap);
      ifaces.clear();                 // a new section, new interfaces
    }
    if (blen < 12 || (blen & 3) || off + blen > (size_t)len)
      return -1;                      // torn/corrupt block framing
    const uint8_t* body = buf + off + 8;
    const size_t body_len = blen - 12;
    if (btype == 0x00000001u) {       // IDB
      if (body_len < 8) return -1;
      NgIface nif;
      nif.ethernet = rd16(body, swap) == 1;   // LINKTYPE_ETHERNET
      // Walk options for if_tsresol (code 9, 1 byte payload).
      size_t o = 8;
      while (o + 4 <= body_len) {
        const uint16_t code = rd16(body + o, swap);
        const uint16_t olen = rd16(body + o + 2, swap);
        if (code == 0) break;
        if (o + 4 + olen > body_len) break;
        if (code == 9 && olen >= 1) {
          const uint8_t v = body[o + 4];
          // Exponents >= 64 would be UB in the shift (and absurd
          // resolutions anyway) — compute both forms in floating
          // point, where any exponent is well-defined.
          nif.ts_div = (v & 0x80) ? std::pow(2.0, (double)(v & 0x7F))
                                  : std::pow(10.0, (double)v);
        }
        o += 4 + (((size_t)olen + 3) & ~(size_t)3);
      }
      ifaces.push_back(nif);
    } else if (btype == 0x00000006u) {  // EPB
      if (body_len < 20) return -1;
      const uint32_t ifid = rd32(body, swap);
      const uint64_t ts_units = ((uint64_t)rd32(body + 4, swap) << 32)
                                | rd32(body + 8, swap);
      const uint32_t capt = rd32(body + 12, swap);
      const uint32_t orig = rd32(body + 16, swap);
      if (capt > snaplen_guard || 20 + (size_t)capt > body_len) return -1;
      if (ifid < ifaces.size() && ifaces[ifid].ethernet) {
        const double ts = (double)ts_units / ifaces[ifid].ts_div;
        emitted += process_frame(body + 20, capt, orig, ts, out);
      }
    } else if (btype == 0x00000003u) {  // SPB (no iface id: iface 0)
      if (body_len < 4) return -1;
      const uint32_t orig = rd32(body, swap);
      const size_t capt = body_len - 4 < orig ? body_len - 4 : orig;
      if (!ifaces.empty() && ifaces[0].ethernet)
        emitted += process_frame(body + 4, capt, orig, 0.0, out);
    }
    off += blen;
  }
  return off == (size_t)len ? emitted : -1;
}

}  // namespace

extern "C" int64_t pcapdns_extract(const uint8_t* buf, int64_t len,
                                   FILE* out) {
  if (len < 24) return -1;
  if (rd32(buf, false) == 0x0A0D0D0Au) return walk_pcapng(buf, len, out);
  return walk_pcap(buf, len, out);
}

#ifndef ONIX_PCAPDNS_NO_MAIN
int main(int argc, char** argv) {
  if (argc != 2) {
    std::fprintf(stderr, "usage: %s <capture.pcap>\n", argv[0]);
    return 2;
  }
  FILE* f = std::fopen(argv[1], "rb");
  if (!f) {
    std::perror(argv[1]);
    return 1;
  }
  std::fseek(f, 0, SEEK_END);
  const long sz = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  std::vector<uint8_t> buf((size_t)(sz > 0 ? sz : 0));
  if (sz > 0 && std::fread(buf.data(), 1, (size_t)sz, f) != (size_t)sz) {
    std::fclose(f);
    std::fprintf(stderr, "short read\n");
    return 1;
  }
  std::fclose(f);
  const int64_t n = pcapdns_extract(buf.data(), sz, stdout);
  if (n < 0) {
    std::fprintf(stderr, "not a pcap file (or torn/unsupported capture)\n");
    return 1;
  }
  return 0;
}
#endif
