// onix-nfdecode — C++ binary netflow decoder (≙ oni-nfdump, reference
// .gitmodules:13-15, README.md:83; SURVEY.md §2.4.2).
//
// The reference carries a patched fork of the nfdump C tool to turn binary
// netflow captures into text for the flow ingest path (SURVEY.md §3.2:
// "subprocess: oni-nfdump binary decodes nfcapd → CSV"). onix implements
// its own decoder for the OPEN protocol — Cisco NetFlow v5 export packets
// (24-byte header + N×48-byte records, big-endian) — rather than porting
// nfdump's proprietary internal nfcapd framing. A capture file here is a
// concatenation of v5 export packets as received off the wire.
//
// Exposed as a C ABI for ctypes (onix/ingest/nfdecode.py): two-pass
// (count, then fill caller-allocated SoA arrays — no ownership transfer
// across the FFI), plus a CLI that streams CSV to stdout.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <vector>

namespace {

constexpr size_t kHeaderLen = 24;
constexpr size_t kRecordLen = 48;
constexpr uint16_t kVersion = 5;
constexpr uint16_t kMaxRecordsPerPacket = 30;  // v5 spec: <= 30 flows/packet

uint16_t be16(const uint8_t* p) {
  return (uint16_t)((p[0] << 8) | p[1]);
}
uint32_t be32(const uint8_t* p) {
  return ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
         ((uint32_t)p[2] << 8) | (uint32_t)p[3];
}

struct PacketView {
  const uint8_t* records;  // first record
  uint16_t count;
  uint32_t sys_uptime_ms;
  uint32_t unix_secs;
};

// Validate + view one packet at `p`. Returns bytes consumed, 0 on error.
size_t parse_header(const uint8_t* p, size_t remaining, PacketView* out) {
  if (remaining < kHeaderLen) return 0;
  if (be16(p) != kVersion) return 0;
  const uint16_t count = be16(p + 2);
  if (count == 0 || count > kMaxRecordsPerPacket) return 0;
  const size_t need = kHeaderLen + (size_t)count * kRecordLen;
  if (remaining < need) return 0;
  out->records = p + kHeaderLen;
  out->count = count;
  out->sys_uptime_ms = be32(p + 4);
  out->unix_secs = be32(p + 8);
  return need;
}

}  // namespace

extern "C" {

// Count records in a buffer of concatenated v5 packets. Returns the
// record count, or -1 if the buffer is malformed (trailing garbage,
// bad version, truncated packet).
int64_t nf5_count(const uint8_t* buf, int64_t len) {
  if (!buf || len < 0) return -1;
  int64_t total = 0;
  size_t off = 0;
  while (off < (size_t)len) {
    PacketView pv;
    const size_t used = parse_header(buf + off, (size_t)len - off, &pv);
    if (used == 0) return -1;
    total += pv.count;
    off += used;
  }
  return total;
}

// Decode into caller-allocated arrays of length `n` (from nf5_count).
// Flow start time = unix_secs - (sys_uptime - First)/1000 (standard v5
// uptime arithmetic). Returns the number of records written, -1 on error.
int64_t nf5_decode(const uint8_t* buf, int64_t len, int64_t n,
                   uint32_t* sip, uint32_t* dip, uint16_t* sport,
                   uint16_t* dport, uint8_t* proto, uint8_t* tcp_flags,
                   uint32_t* dpkts, uint32_t* doctets, double* start_ts,
                   double* end_ts) {
  if (!buf || !sip || !dip || !sport || !dport || !proto || !tcp_flags ||
      !dpkts || !doctets || !start_ts || !end_ts)
    return -1;
  int64_t i = 0;
  size_t off = 0;
  while (off < (size_t)len) {
    PacketView pv;
    const size_t used = parse_header(buf + off, (size_t)len - off, &pv);
    if (used == 0) return -1;
    for (uint16_t r = 0; r < pv.count; ++r) {
      if (i >= n) return -1;
      const uint8_t* rec = pv.records + (size_t)r * kRecordLen;
      sip[i] = be32(rec + 0);
      dip[i] = be32(rec + 4);
      dpkts[i] = be32(rec + 16);
      doctets[i] = be32(rec + 20);
      const uint32_t first_ms = be32(rec + 24);
      const uint32_t last_ms = be32(rec + 28);
      sport[i] = be16(rec + 32);
      dport[i] = be16(rec + 34);
      tcp_flags[i] = rec[37];
      proto[i] = rec[38];
      // Router boot epoch = unix_secs - uptime/1000; flow times are
      // offsets from boot. int64 math: First may exceed uptime (clock
      // skew in the exporter) — keep the signed difference exact.
      const double boot =
          (double)pv.unix_secs - (double)pv.sys_uptime_ms / 1000.0;
      start_ts[i] = boot + (double)first_ms / 1000.0;
      end_ts[i] = boot + (double)last_ms / 1000.0;
      ++i;
    }
    off += used;
  }
  return i;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// CLI: nfdecode <capture.nf5>  — stream CSV to stdout, one row per flow,
// schema matching the ingest path's flow table (onix/ingest/nfdecode.py).
// ---------------------------------------------------------------------------

#ifndef ONIX_NFDECODE_NO_MAIN
int main(int argc, char** argv) {
  if (argc != 2) {
    std::fprintf(stderr, "usage: %s <capture.nf5>\n", argv[0]);
    return 2;
  }
  FILE* f = std::fopen(argv[1], "rb");
  if (!f) {
    std::perror(argv[1]);
    return 1;
  }
  std::fseek(f, 0, SEEK_END);
  const long sz = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  std::vector<uint8_t> buf((size_t)sz);
  if (std::fread(buf.data(), 1, (size_t)sz, f) != (size_t)sz) {
    std::fclose(f);
    std::fprintf(stderr, "short read\n");
    return 1;
  }
  std::fclose(f);

  const int64_t n = nf5_count(buf.data(), sz);
  if (n < 0) {
    std::fprintf(stderr, "malformed netflow v5 stream\n");
    return 1;
  }
  std::vector<uint32_t> sip(n), dip(n), dpkts(n), doctets(n);
  std::vector<uint16_t> sport(n), dport(n);
  std::vector<uint8_t> proto(n), flags(n);
  std::vector<double> t0(n), t1(n);
  if (nf5_decode(buf.data(), sz, n, sip.data(), dip.data(), sport.data(),
                 dport.data(), proto.data(), flags.data(), dpkts.data(),
                 doctets.data(), t0.data(), t1.data()) != n) {
    std::fprintf(stderr, "decode error\n");
    return 1;
  }
  std::printf("start_ts,end_ts,sip,dip,sport,dport,proto,tcp_flags,ipkt,ibyt\n");
  auto ip_str = [](uint32_t ip, char* out) {
    std::snprintf(out, 16, "%u.%u.%u.%u", (ip >> 24) & 255, (ip >> 16) & 255,
                  (ip >> 8) & 255, ip & 255);
  };
  char a[16], b[16];
  for (int64_t i = 0; i < n; ++i) {
    ip_str(sip[i], a);
    ip_str(dip[i], b);
    std::printf("%.3f,%.3f,%s,%s,%u,%u,%u,%u,%u,%u\n", t0[i], t1[i], a, b,
                sport[i], dport[i], proto[i], flags[i], dpkts[i], doctets[i]);
  }
  return 0;
}
#endif
