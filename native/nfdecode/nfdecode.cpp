// onix-nfdecode — C++ binary netflow decoder (≙ oni-nfdump, reference
// .gitmodules:13-15, README.md:83; SURVEY.md §2.4.2).
//
// The reference carries a patched fork of the nfdump C tool to turn binary
// netflow captures into text for the flow ingest path (SURVEY.md §3.2:
// "subprocess: oni-nfdump binary decodes nfcapd → CSV"). onix implements
// its own decoder for the OPEN protocols — Cisco NetFlow v5 export
// packets (24-byte header + N×48-byte records), template-based
// NetFlow v9 (RFC 3954: template flowsets announce record layouts, data
// flowsets carry them; options templates announce exporter-state
// records, surfaced as metadata such as the sampling interval), and
// IPFIX/v10 (RFC 7011: explicit message length, enterprise fields,
// variable-length encoding, options template sets) — rather than
// porting nfdump's proprietary internal nfcapd framing (nfcapd files
// are handled by subprocess passthrough to an installed nfdump, see
// onix/ingest/nfdecode.py). A capture file here is a concatenation of
// export packets as received off the wire; versions may be mixed.
//
// Exposed as a C ABI for ctypes (onix/ingest/nfdecode.py): two-pass
// (count, then fill caller-allocated SoA arrays — no ownership transfer
// across the FFI; v9 templates learned in pass 1 are re-learned in pass
// 2, so the passes are independent), plus a CLI that streams CSV.

#include <dlfcn.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <vector>

namespace {

constexpr size_t kHeaderLen = 24;
constexpr size_t kRecordLen = 48;
constexpr uint16_t kVersion = 5;
constexpr uint16_t kMaxRecordsPerPacket = 30;  // v5 spec: <= 30 flows/packet

uint16_t be16(const uint8_t* p) {
  return (uint16_t)((p[0] << 8) | p[1]);
}
uint32_t be32(const uint8_t* p) {
  return ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
         ((uint32_t)p[2] << 8) | (uint32_t)p[3];
}

struct PacketView {
  const uint8_t* records;  // first record
  uint16_t count;
  uint32_t sys_uptime_ms;
  uint32_t unix_secs;
};

// Validate + view one packet at `p`. Returns bytes consumed, 0 on error.
size_t parse_header(const uint8_t* p, size_t remaining, PacketView* out) {
  if (remaining < kHeaderLen) return 0;
  if (be16(p) != kVersion) return 0;
  const uint16_t count = be16(p + 2);
  if (count == 0 || count > kMaxRecordsPerPacket) return 0;
  const size_t need = kHeaderLen + (size_t)count * kRecordLen;
  if (remaining < need) return 0;
  out->records = p + kHeaderLen;
  out->count = count;
  out->sys_uptime_ms = be32(p + 4);
  out->unix_secs = be32(p + 8);
  return need;
}

// ---------------------------------------------------------------------------
// NetFlow v9 (RFC 3954)
// ---------------------------------------------------------------------------

constexpr size_t kV9HeaderLen = 20;
constexpr uint16_t kV9Version = 9;

// Field types we extract (RFC 3954 §8); everything else is skipped by
// its declared length.
enum V9Field : uint16_t {
  kInBytes = 1,
  kInPkts = 2,
  kProtocol = 4,
  kTcpFlags = 6,
  kL4SrcPort = 7,
  kIpv4Src = 8,
  kL4DstPort = 11,
  kIpv4Dst = 12,
  kLastSwitched = 21,
  kFirstSwitched = 22,
  kSamplingInterval = 34,  // options-record field: exporter sample rate
  // Sampler-table announcements (the other common way exporters state
  // their rate): v9 FLOW_SAMPLER_RANDOM_INTERVAL / IPFIX
  // samplerRandomInterval share id 50; IPFIX samplingPacketInterval is
  // 305. Fields 48 (sampler id) and 49 (sampler mode) carry no
  // interval and are deliberately not announcement triggers.
  kSamplerRandomInterval = 50,
  kSamplingPacketInterval = 305,
};

// True for any options-record field that announces a 1-in-N sampling
// interval — field 34 alone missed sampler-table exporters, which then
// silently stayed unscaled under apply_sampling (ADVICE r2).
inline bool is_sampling_announce(uint16_t type) {
  return type == kSamplingInterval || type == kSamplerRandomInterval ||
         type == kSamplingPacketInterval;
}

// Exporter metadata extracted from options records (RFC 3954 §6.1 /
// RFC 7011 §3.4.2.2). Options data carries exporter state, not flows —
// the one element the ingest path acts on is the sampling interval
// (nfdump applies it to scale counters; onix exposes it the same way).
// Sampling is tracked PER EXPORTER (same keying as the template maps:
// a v9 source id or IPFIX observation domain, tagged by format so the
// namespaces cannot collide) — exporter A announcing 1-in-64 must
// never scale exporter B's unsampled flows.
struct StreamMeta {
  uint32_t sampling_interval = 0;  // last announced by ANY exporter
  std::map<uint64_t, uint32_t> by_exporter;
  bool apply = false;              // scale counters at decode time
  bool first_wins = false;         // pre-scan mode: keep each exporter's
  //                                  FIRST announcement (the best guess
  //                                  for flows ahead of it in-stream)
  void announce(uint64_t exporter_key, uint32_t interval) {
    sampling_interval = interval;
    if (first_wins && by_exporter.count(exporter_key)) return;
    by_exporter[exporter_key] = interval;
  }
  uint32_t factor(uint64_t exporter_key) const {
    auto it = by_exporter.find(exporter_key);
    return (it != by_exporter.end() && it->second > 1) ? it->second : 1;
  }
};

constexpr uint64_t kV9ExporterTag = 0;
constexpr uint64_t kIpfixExporterTag = 1ULL << 32;

struct V9FieldSpec {
  uint16_t type;
  uint16_t len;
  uint16_t offset;  // byte offset inside one data record
};

struct V9Template {
  std::vector<V9FieldSpec> fields;
  uint16_t record_len = 0;
  // Options templates (announced via set id 1 / IPFIX set 3) describe
  // exporter-state records, not flows: their data sets update
  // StreamMeta and never reach the flow sink. Scope fields are stored
  // with type 0 (their type ids live in a separate namespace, RFC 3954
  // §6.1) so they can never alias a flow field.
  bool options = false;
};

// Key = (source_id << 16) | template_id; source ids are full 32-bit
// (RFC 3954 §5.1), so the key must be 64-bit or distinct exporters
// whose ids share low bits would collide and cross-decode.
using V9Templates = std::map<uint64_t, V9Template>;

// Read a big-endian unsigned field of 1/2/4/8 bytes (longer fields keep
// the low 64 bits, like nfdump's sampling of oversized counters).
uint64_t beN(const uint8_t* p, uint16_t len) {
  uint64_t v = 0;
  const uint16_t take = len > 8 ? 8 : len;
  p += len - take;
  for (uint16_t i = 0; i < take; ++i) v = (v << 8) | p[i];
  return v;
}

struct V9Record {
  uint32_t sip = 0, dip = 0, dpkts = 0, doctets = 0;
  uint16_t sport = 0, dport = 0;
  uint8_t proto = 0, tcp_flags = 0;
  uint32_t first_ms = 0, last_ms = 0;
  bool has_first = false, has_last = false;
  // IPv6 addresses (nfcapd container records with kFlagIpv6Addr): the
  // 128-bit values as big-endian (hi, lo) u64 halves. v4 rows leave
  // them zero with is_v6 false.
  bool is_v6 = false;
  uint64_t sip6_hi = 0, sip6_lo = 0, dip6_hi = 0, dip6_lo = 0;
};

// Sampling-scaled counters saturate at UINT32_MAX rather than wrapping
// (a 5M-packet flow at 1-in-1024 sampling overflows uint32; a pinned
// max is visibly wrong, a wrapped small number is silently wrong).
inline void scale_counters(V9Record* r, uint32_t s) {
  const uint64_t pk = (uint64_t)r->dpkts * s;
  const uint64_t by = (uint64_t)r->doctets * s;
  r->dpkts = pk > 0xFFFFFFFFULL ? 0xFFFFFFFFU : (uint32_t)pk;
  r->doctets = by > 0xFFFFFFFFULL ? 0xFFFFFFFFU : (uint32_t)by;
}

// Sink receives each decoded record; returns false to abort (capacity).
template <typename Sink>
bool parse_v9_packet(const uint8_t* p, size_t pkt_len, V9Templates* tpls,
                     StreamMeta* meta, Sink&& sink) {
  const uint32_t sys_uptime_ms = be32(p + 4);
  const uint32_t unix_secs = be32(p + 8);
  const uint32_t source_id = be32(p + 16);
  size_t off = kV9HeaderLen;
  while (off + 4 <= pkt_len) {
    const uint16_t set_id = be16(p + off);
    const uint16_t set_len = be16(p + off + 2);
    if (set_len < 4 || off + set_len > pkt_len) return false;
    const uint8_t* body = p + off + 4;
    const size_t body_len = set_len - 4;
    if (set_id == 0) {  // template flowset (options templates: set 1)
      size_t t = 0;
      while (t + 4 <= body_len) {
        const uint16_t tpl_id = be16(body + t);
        const uint16_t n_fields = be16(body + t + 2);
        // RFC 3954 §5.2 permits trailing zero padding inside a template
        // flowset; an all-zero "header" is padding, not a template.
        if (tpl_id == 0 && n_fields == 0) break;
        t += 4;
        if (tpl_id < 256 || t + (size_t)n_fields * 4 > body_len)
          return false;
        V9Template tpl;
        size_t rec_off = 0;   // size_t: field lengths are attacker data
        for (uint16_t f = 0; f < n_fields; ++f) {
          const uint16_t ftype = be16(body + t + f * 4);
          const uint16_t flen = be16(body + t + f * 4 + 2);
          // A record longer than a flowset can carry is malformed; the
          // cap also prevents offset wrap-around (out-of-bounds reads
          // in the data-record field loop).
          if (flen == 0 || rec_off + flen > 0xFFFF) return false;
          tpl.fields.push_back({ftype, flen, (uint16_t)rec_off});
          rec_off += flen;
        }
        tpl.record_len = (uint16_t)rec_off;
        (*tpls)[((uint64_t)source_id << 16) | tpl_id] = tpl;
        t += (size_t)n_fields * 4;
      }
    } else if (set_id == 1) {  // options template flowset (RFC 3954 §6.1)
      size_t t = 0;
      while (t + 6 <= body_len) {
        const uint16_t tpl_id = be16(body + t);
        const uint16_t scope_len = be16(body + t + 2);    // bytes of specs
        const uint16_t option_len = be16(body + t + 4);
        // Trailing zero padding is legal here too (§6.1 permits it the
        // same way §5.2 does for data templates).
        if (tpl_id == 0 && scope_len == 0 && option_len == 0) break;
        t += 6;
        // Scope must be non-empty (§6.1), matching the IPFIX check —
        // identical malformed exporter state fails on both formats.
        if (tpl_id < 256 || scope_len == 0 || (scope_len % 4) ||
            (option_len % 4) ||
            t + (size_t)scope_len + option_len > body_len)
          return false;
        V9Template tpl;
        tpl.options = true;
        size_t rec_off = 0;
        const size_t spec_bytes = (size_t)scope_len + option_len;
        for (size_t q = 0; q < spec_bytes; q += 4) {
          const uint16_t ftype = be16(body + t + q);
          const uint16_t flen = be16(body + t + q + 2);
          if (flen == 0 || rec_off + flen > 0xFFFF) return false;
          // Scope field types (System/Interface/...) are a separate
          // namespace — store as 0 so they never match a flow field.
          tpl.fields.push_back({q < scope_len ? (uint16_t)0 : ftype,
                                flen, (uint16_t)rec_off});
          rec_off += flen;
        }
        if (rec_off == 0) return false;
        tpl.record_len = (uint16_t)rec_off;
        (*tpls)[((uint64_t)source_id << 16) | tpl_id] = tpl;
        t += spec_bytes;
      }
    } else if (set_id >= 256) {  // data flowset
      auto it = tpls->find(((uint64_t)source_id << 16) | set_id);
      if (it != tpls->end() && it->second.options) {
        // Options data: exporter state, not flows. Extract the
        // sampling interval; nothing reaches the sink.
        const V9Template& tpl = it->second;
        const size_t n_rec = body_len / tpl.record_len;
        for (size_t r = 0; r < n_rec; ++r) {
          const uint8_t* rec = body + r * tpl.record_len;
          for (const V9FieldSpec& f : tpl.fields) {
            if (is_sampling_announce(f.type) && meta)
              meta->announce(kV9ExporterTag | source_id,
                             (uint32_t)beN(rec + f.offset, f.len));
          }
        }
      } else if (it != tpls->end() && it->second.record_len > 0) {
        const V9Template& tpl = it->second;
        const size_t n_rec = body_len / tpl.record_len;  // tail = padding
        const double boot =
            (double)unix_secs - (double)sys_uptime_ms / 1000.0;
        for (size_t r = 0; r < n_rec; ++r) {
          const uint8_t* rec = body + r * tpl.record_len;
          V9Record out;
          for (const V9FieldSpec& f : tpl.fields) {
            const uint64_t v = beN(rec + f.offset, f.len);
            switch (f.type) {
              case kIpv4Src: out.sip = (uint32_t)v; break;
              case kIpv4Dst: out.dip = (uint32_t)v; break;
              case kL4SrcPort: out.sport = (uint16_t)v; break;
              case kL4DstPort: out.dport = (uint16_t)v; break;
              case kProtocol: out.proto = (uint8_t)v; break;
              case kTcpFlags: out.tcp_flags = (uint8_t)v; break;
              case kInPkts: out.dpkts = (uint32_t)v; break;
              case kInBytes: out.doctets = (uint32_t)v; break;
              case kFirstSwitched:
                out.first_ms = (uint32_t)v;
                out.has_first = true;
                break;
              case kLastSwitched:
                out.last_ms = (uint32_t)v;
                out.has_last = true;
                break;
              default: break;  // skipped field
            }
          }
          const double t0 = out.has_first
                                ? boot + (double)out.first_ms / 1000.0
                                : (double)unix_secs;
          const double t1 = out.has_last
                                ? boot + (double)out.last_ms / 1000.0
                                : (double)unix_secs;
          if (meta && meta->apply)
            scale_counters(&out, meta->factor(kV9ExporterTag | source_id));
          if (!sink(out, t0, t1)) return false;
        }
      }
      // Unknown template: records are skipped (nfdump behavior) — the
      // exporter re-sends templates periodically.
    }
    off += set_len;
  }
  return off == pkt_len;
}

// ---------------------------------------------------------------------------
// IPFIX (RFC 7011) — NetFlow v10
// ---------------------------------------------------------------------------
//
// Same template/data-set shape as v9 with three twists the decoder must
// honor: the message header carries the total byte length (framing is
// explicit), field specifiers may set the enterprise bit (a 4-byte
// enterprise number follows; such fields are skipped by length), and a
// declared length of 0xFFFF means variable-length encoding (RFC 7011
// §7: 1 length byte, or 255 followed by 2 length bytes, per record).

constexpr size_t kIpfixHeaderLen = 16;
constexpr uint16_t kIpfixVersion = 10;
constexpr uint16_t kVarLen = 0xFFFF;

// IPFIX information elements 1..128 share NetFlow v9 field type ids
// (RFC 7011 §10.2 / IANA registry), so kInBytes..kFirstSwitched above
// apply verbatim; the absolute-timestamp IEs are IPFIX additions.
enum IpfixField : uint16_t {
  kFlowStartSeconds = 150,
  kFlowEndSeconds = 151,
  kFlowStartMilliseconds = 152,
  kFlowEndMilliseconds = 153,
};

struct IpfixFieldSpec {
  uint16_t type;
  uint16_t len;        // kVarLen = variable-length
  bool enterprise;     // enterprise-specific: skipped by length
};

struct IpfixTemplate {
  std::vector<IpfixFieldSpec> fields;
  size_t min_len = 0;  // fixed bytes + 1 per variable-length field
  bool options = false;  // set-3 template: data is exporter state
};

// Key = (observation domain id << 16) | template id (same collision
// argument as the v9 map).
using IpfixTemplates = std::map<uint64_t, IpfixTemplate>;

// Shared template-record parser for IPFIX sets 2 and 3: specifiers are
// identical (enterprise bit + optional enterprise number); options
// templates (set 3) additionally lead with a scope-field count whose
// fields get type 0 (scope ids are exporter-chosen IEs describing the
// scope, not flow values to extract — RFC 7011 §3.4.2.1).
inline bool parse_ipfix_template_body(const uint8_t* body, size_t body_len,
                                      uint32_t domain_id, bool options,
                                      IpfixTemplates* tpls) {
  const size_t head = options ? 6 : 4;
  size_t t = 0;
  while (t + head <= body_len) {
    const uint16_t tpl_id = be16(body + t);
    const uint16_t n_fields = be16(body + t + 2);
    const uint16_t n_scope = options ? be16(body + t + 4) : 0;
    if (tpl_id == 0 && n_fields == 0) break;  // trailing padding
    t += head;
    if (tpl_id < 256) return false;
    if (options && (n_scope == 0 || n_scope > n_fields))
      return false;  // §3.4.2.2: scope count is 1..field count
    IpfixTemplate tpl;
    tpl.options = options;
    for (uint16_t f = 0; f < n_fields; ++f) {
      if (t + 4 > body_len) return false;
      const uint16_t raw_type = be16(body + t);
      const uint16_t flen = be16(body + t + 2);
      t += 4;
      const bool ent = (raw_type & 0x8000) != 0;
      if (ent) {   // enterprise number follows the specifier
        if (t + 4 > body_len) return false;
        t += 4;
      }
      if (flen == kVarLen) {
        tpl.min_len += 1;  // at least the 1-byte length prefix
      } else {
        if (flen == 0 || tpl.min_len + flen > 0xFFFF) return false;
        tpl.min_len += flen;
      }
      const uint16_t ftype =
          f < n_scope ? (uint16_t)0 : (uint16_t)(raw_type & 0x7FFF);
      tpl.fields.push_back({ftype, flen, ent});
    }
    if (tpl.min_len == 0) return false;
    (*tpls)[((uint64_t)domain_id << 16) | tpl_id] = tpl;
  }
  return true;
}

template <typename Sink>
bool parse_ipfix_packet(const uint8_t* p, size_t pkt_len,
                        IpfixTemplates* tpls, StreamMeta* meta,
                        Sink&& sink) {
  const uint32_t export_secs = be32(p + 4);
  const uint32_t domain_id = be32(p + 12);
  size_t off = kIpfixHeaderLen;
  while (off + 4 <= pkt_len) {
    const uint16_t set_id = be16(p + off);
    const uint16_t set_len = be16(p + off + 2);
    if (set_len < 4 || off + set_len > pkt_len) return false;
    const uint8_t* body = p + off + 4;
    const size_t body_len = set_len - 4;
    if (set_id == 2 || set_id == 3) {  // template / options-template set
      if (!parse_ipfix_template_body(body, body_len, domain_id,
                                     set_id == 3, tpls))
        return false;
    } else if (set_id >= 256) {  // data set
      auto it = tpls->find(((uint64_t)domain_id << 16) | set_id);
      if (it != tpls->end()) {
        const IpfixTemplate& tpl = it->second;
        size_t r = 0;
        // Records run until less than one minimal record remains; the
        // tail is padding (RFC 7011 §3.3.1).
        while (body_len - r >= tpl.min_len) {
          V9Record out;
          uint64_t start_s = 0, end_s = 0, start_ms = 0, end_ms = 0;
          bool has_s0 = false, has_s1 = false, has_ms0 = false,
               has_ms1 = false;
          bool bad = false;
          for (const IpfixFieldSpec& f : tpl.fields) {
            size_t flen = f.len;
            if (f.len == kVarLen) {  // RFC 7011 §7 variable length
              if (r >= body_len) { bad = true; break; }
              flen = body[r];
              r += 1;
              if (flen == 255) {
                if (r + 2 > body_len) { bad = true; break; }
                flen = be16(body + r);
                r += 2;
              }
            }
            if (r + flen > body_len) { bad = true; break; }
            if (!f.enterprise && flen > 0) {
              const uint64_t v = beN(body + r, (uint16_t)flen);
              switch (f.type) {
                case kSamplingInterval:
                case kSamplerRandomInterval:
                case kSamplingPacketInterval:
                  if (tpl.options && meta)
                    meta->announce(kIpfixExporterTag | domain_id,
                                   (uint32_t)v);
                  break;
                case kIpv4Src: out.sip = (uint32_t)v; break;
                case kIpv4Dst: out.dip = (uint32_t)v; break;
                case kL4SrcPort: out.sport = (uint16_t)v; break;
                case kL4DstPort: out.dport = (uint16_t)v; break;
                case kProtocol: out.proto = (uint8_t)v; break;
                case kTcpFlags: out.tcp_flags = (uint8_t)v; break;
                case kInPkts: out.dpkts = (uint32_t)v; break;
                case kInBytes: out.doctets = (uint32_t)v; break;
                case kFlowStartSeconds: start_s = v; has_s0 = true; break;
                case kFlowEndSeconds: end_s = v; has_s1 = true; break;
                case kFlowStartMilliseconds:
                  start_ms = v; has_ms0 = true; break;
                case kFlowEndMilliseconds:
                  end_ms = v; has_ms1 = true; break;
                default: break;  // skipped field
              }
            }
            r += flen;
          }
          if (bad) return false;
          // Best available clock: absolute ms > absolute s > export
          // time. (Uptime-relative IEs 21/22 would need IE 160, the
          // system init time, which classic exporters rarely send —
          // export time is the honest fallback.)
          const double t0 = has_ms0 ? (double)start_ms / 1000.0
                            : has_s0 ? (double)start_s
                                     : (double)export_secs;
          const double t1 = has_ms1 ? (double)end_ms / 1000.0
                            : has_s1 ? (double)end_s
                                     : (double)export_secs;
          if (!tpl.options) {  // options data: meta only, never a flow
            if (meta && meta->apply)
              scale_counters(&out,
                             meta->factor(kIpfixExporterTag | domain_id));
            if (!sink(out, t0, t1)) return false;
          }
        }
      }
    }
    // Unknown data sets (template never seen): skipped whole.
    off += set_len;
  }
  return off == pkt_len;
}

// IPFIX framing is explicit: the message header's length field.
size_t ipfix_packet_extent(const uint8_t* p, size_t remaining) {
  if (remaining < kIpfixHeaderLen || be16(p) != kIpfixVersion) return 0;
  const uint16_t msg_len = be16(p + 2);
  if (msg_len < kIpfixHeaderLen || msg_len > remaining) return 0;
  return msg_len;
}

// v9 packets do not carry their own byte length; the header's `count`
// field is the record/template count, not bytes. Walk the flowsets to
// find the packet end. The framing is unambiguous: a flowset starts
// with id 0, 1, or >=256 (2..255 are reserved, RFC 3954 §5.2), so a
// 16-bit value of 5 or 9 at a flowset boundary can only be the next
// packet's version marker.
size_t v9_packet_extent(const uint8_t* p, size_t remaining) {
  if (remaining < kV9HeaderLen || be16(p) != kV9Version) return 0;
  size_t off = kV9HeaderLen;
  while (off + 4 <= remaining) {
    const uint16_t set_id = be16(p + off);
    if (set_id == kVersion || set_id == kV9Version ||
        set_id == kIpfixVersion)
      break;  // next packet (5/9/10 are reserved set ids, RFC 3954 §5.2)
    const uint16_t set_len = be16(p + off + 2);
    if (set_len < 4 || off + set_len > remaining) return 0;
    off += set_len;
  }
  return off;
}

}  // namespace

extern "C" {

// Count records in a buffer of concatenated v5 packets. Returns the
// record count, or -1 if the buffer is malformed (trailing garbage,
// bad version, truncated packet).
int64_t nf5_count(const uint8_t* buf, int64_t len) {
  if (!buf || len < 0) return -1;
  int64_t total = 0;
  size_t off = 0;
  while (off < (size_t)len) {
    PacketView pv;
    const size_t used = parse_header(buf + off, (size_t)len - off, &pv);
    if (used == 0) return -1;
    total += pv.count;
    off += used;
  }
  return total;
}

// Decode into caller-allocated arrays of length `n` (from nf5_count).
// Flow start time = unix_secs - (sys_uptime - First)/1000 (standard v5
// uptime arithmetic). Returns the number of records written, -1 on error.
int64_t nf5_decode(const uint8_t* buf, int64_t len, int64_t n,
                   uint32_t* sip, uint32_t* dip, uint16_t* sport,
                   uint16_t* dport, uint8_t* proto, uint8_t* tcp_flags,
                   uint32_t* dpkts, uint32_t* doctets, double* start_ts,
                   double* end_ts) {
  if (!buf || !sip || !dip || !sport || !dport || !proto || !tcp_flags ||
      !dpkts || !doctets || !start_ts || !end_ts)
    return -1;
  int64_t i = 0;
  size_t off = 0;
  while (off < (size_t)len) {
    PacketView pv;
    const size_t used = parse_header(buf + off, (size_t)len - off, &pv);
    if (used == 0) return -1;
    for (uint16_t r = 0; r < pv.count; ++r) {
      if (i >= n) return -1;
      const uint8_t* rec = pv.records + (size_t)r * kRecordLen;
      sip[i] = be32(rec + 0);
      dip[i] = be32(rec + 4);
      dpkts[i] = be32(rec + 16);
      doctets[i] = be32(rec + 20);
      const uint32_t first_ms = be32(rec + 24);
      const uint32_t last_ms = be32(rec + 28);
      sport[i] = be16(rec + 32);
      dport[i] = be16(rec + 34);
      tcp_flags[i] = rec[37];
      proto[i] = rec[38];
      // Router boot epoch = unix_secs - uptime/1000; flow times are
      // offsets from boot. int64 math: First may exceed uptime (clock
      // skew in the exporter) — keep the signed difference exact.
      const double boot =
          (double)pv.unix_secs - (double)pv.sys_uptime_ms / 1000.0;
      start_ts[i] = boot + (double)first_ms / 1000.0;
      end_ts[i] = boot + (double)last_ms / 1000.0;
      ++i;
    }
    off += used;
  }
  return i;
}

// Count records in a mixed v5/v9/IPFIX stream. Data flowsets without a
// known template are skipped (not errors) — matching nfdump; templates
// learned from earlier packets apply to later ones. Returns -1 on
// malformed framing.
int64_t nfx_count(const uint8_t* buf, int64_t len) {
  if (!buf || len < 0) return -1;
  int64_t total = 0;
  size_t off = 0;
  V9Templates tpls;
  IpfixTemplates itpls;
  auto count_sink = [&](const V9Record&, double, double) {
    ++total;
    return true;
  };
  while (off < (size_t)len) {
    const uint16_t ver = ((size_t)len - off >= 2) ? be16(buf + off) : 0;
    if (ver == kVersion) {
      PacketView pv;
      const size_t used = parse_header(buf + off, (size_t)len - off, &pv);
      if (used == 0) return -1;
      total += pv.count;
      off += used;
    } else if (ver == kV9Version) {
      const size_t used = v9_packet_extent(buf + off, (size_t)len - off);
      if (used == 0) return -1;
      if (!parse_v9_packet(buf + off, used, &tpls, nullptr, count_sink))
        return -1;
      off += used;
    } else if (ver == kIpfixVersion) {
      const size_t used = ipfix_packet_extent(buf + off, (size_t)len - off);
      if (used == 0) return -1;
      if (!parse_ipfix_packet(buf + off, used, &itpls, nullptr, count_sink))
        return -1;
      off += used;
    } else {
      return -1;
    }
  }
  return total;
}

// Metadata peek: walk a mixed v5/v9/IPFIX stream and return the
// sampling interval from the LAST options record that carried one (v9
// field / IPFIX IE 34): 0 when no options record announced a rate, -1
// on malformed framing. This is a stream-level summary; actual counter
// scaling is per exporter via nfx_decode_scaled.
// Shared walk: parse every packet, feed options records into `meta`,
// drop the flows. Used by nfx_sampling and as the sampling PRE-SCAN of
// nfx_decode_scaled. Defined outside the anonymous namespace's sinks so
// both entry points stay one-pass-each over the buffer.
static bool scan_sampling_meta(const uint8_t* buf, int64_t len,
                               StreamMeta* meta) {
  size_t off = 0;
  V9Templates tpls;
  IpfixTemplates itpls;
  auto drop_sink = [](const V9Record&, double, double) { return true; };
  while (off < (size_t)len) {
    const uint16_t ver = ((size_t)len - off >= 2) ? be16(buf + off) : 0;
    if (ver == kVersion) {
      PacketView pv;
      const size_t used = parse_header(buf + off, (size_t)len - off, &pv);
      if (used == 0) return false;
      off += used;   // v5 has no options records
    } else if (ver == kV9Version) {
      const size_t used = v9_packet_extent(buf + off, (size_t)len - off);
      if (used == 0) return false;
      if (!parse_v9_packet(buf + off, used, &tpls, meta, drop_sink))
        return false;
      off += used;
    } else if (ver == kIpfixVersion) {
      const size_t used = ipfix_packet_extent(buf + off, (size_t)len - off);
      if (used == 0) return false;
      if (!parse_ipfix_packet(buf + off, used, &itpls, meta, drop_sink))
        return false;
      off += used;
    } else {
      return false;
    }
  }
  return true;
}

int64_t nfx_sampling(const uint8_t* buf, int64_t len) {
  if (!buf || len < 0) return -1;
  StreamMeta meta;
  if (!scan_sampling_meta(buf, len, &meta)) return -1;
  return (int64_t)meta.sampling_interval;
}

// Decode a mixed v5/v9/IPFIX stream into caller-allocated arrays of
// length `n` (from nfx_count). Same output schema as nf5_decode.
// With `apply_sampling`, packet/byte counters are scaled by the
// announcing exporter's own sampling interval (per source id / domain
// id — one exporter's rate never touches another's flows; v5 has no
// options mechanism and is never scaled). Returns the number of
// records written, -1 on error.
static int64_t nfx_decode_impl(const uint8_t* buf, int64_t len, int64_t n,
                               uint32_t* sip, uint32_t* dip, uint16_t* sport,
                               uint16_t* dport, uint8_t* proto,
                               uint8_t* tcp_flags, uint32_t* dpkts,
                               uint32_t* doctets, double* start_ts,
                               double* end_ts, bool apply_sampling) {
  if (!buf || !sip || !dip || !sport || !dport || !proto || !tcp_flags ||
      !dpkts || !doctets || !start_ts || !end_ts)
    return -1;
  int64_t i = 0;
  size_t off = 0;
  V9Templates tpls;
  IpfixTemplates itpls;
  StreamMeta meta;
  meta.apply = apply_sampling;
  if (apply_sampling) {
    // Pre-scan the whole stream for sampling announcements so flows
    // decoded BEFORE an exporter's (periodically refreshed) options
    // record still scale — single-pass decoding left everything ahead
    // of a mid-file announcement at raw wire counters (ADVICE r2).
    // The pre-scan seeds each exporter's FIRST announced interval (the
    // best guess for flows ahead of it); in-stream announcements then
    // override in order, so a genuine mid-capture rate change still
    // applies from its announcement on.
    StreamMeta pre;
    pre.first_wins = true;
    if (!scan_sampling_meta(buf, len, &pre)) return -1;
    meta.by_exporter = pre.by_exporter;
    meta.sampling_interval = pre.sampling_interval;
  }
  auto write_sink = [&](const V9Record& r, double t0, double t1) {
    if (i >= n) return false;
    sip[i] = r.sip;
    dip[i] = r.dip;
    sport[i] = r.sport;
    dport[i] = r.dport;
    proto[i] = r.proto;
    tcp_flags[i] = r.tcp_flags;
    dpkts[i] = r.dpkts;
    doctets[i] = r.doctets;
    start_ts[i] = t0;
    end_ts[i] = t1;
    ++i;
    return true;
  };
  while (off < (size_t)len) {
    const uint16_t ver = ((size_t)len - off >= 2) ? be16(buf + off) : 0;
    if (ver == kVersion) {
      PacketView pv;
      const size_t used = parse_header(buf + off, (size_t)len - off, &pv);
      if (used == 0) return -1;
      const int64_t wrote = nf5_decode(buf + off, (int64_t)used, n - i,
                                       sip + i, dip + i, sport + i,
                                       dport + i, proto + i, tcp_flags + i,
                                       dpkts + i, doctets + i, start_ts + i,
                                       end_ts + i);
      if (wrote < 0) return -1;
      i += wrote;
      off += used;
    } else if (ver == kV9Version) {
      const size_t used = v9_packet_extent(buf + off, (size_t)len - off);
      if (used == 0) return -1;
      if (!parse_v9_packet(buf + off, used, &tpls, &meta, write_sink))
        return -1;
      off += used;
    } else if (ver == kIpfixVersion) {
      const size_t used = ipfix_packet_extent(buf + off, (size_t)len - off);
      if (used == 0) return -1;
      if (!parse_ipfix_packet(buf + off, used, &itpls, &meta, write_sink))
        return -1;
      off += used;
    } else {
      return -1;
    }
  }
  return i;
}

int64_t nfx_decode(const uint8_t* buf, int64_t len, int64_t n,
                   uint32_t* sip, uint32_t* dip, uint16_t* sport,
                   uint16_t* dport, uint8_t* proto, uint8_t* tcp_flags,
                   uint32_t* dpkts, uint32_t* doctets, double* start_ts,
                   double* end_ts) {
  return nfx_decode_impl(buf, len, n, sip, dip, sport, dport, proto,
                         tcp_flags, dpkts, doctets, start_ts, end_ts,
                         /*apply_sampling=*/false);
}

int64_t nfx_decode_scaled(const uint8_t* buf, int64_t len, int64_t n,
                          uint32_t* sip, uint32_t* dip, uint16_t* sport,
                          uint16_t* dport, uint8_t* proto,
                          uint8_t* tcp_flags, uint32_t* dpkts,
                          uint32_t* doctets, double* start_ts,
                          double* end_ts) {
  return nfx_decode_impl(buf, len, n, sip, dip, sport, dport, proto,
                         tcp_flags, dpkts, doctets, start_ts, end_ts,
                         /*apply_sampling=*/true);
}

}  // extern "C"

// ---------------------------------------------------------------------------
// nfcapd v1 (nfdump's on-disk container; the reference's flow landing
// format — SURVEY.md §2.1 #2, /root/reference/README.md:83). Clean-room
// reader for the layout-version-1 structure, stable across nfdump
// 1.6.x: little-endian file header (magic 0xA50C, version, flags,
// block count, 128-byte ident), a 136-byte stat record, then data
// blocks of {NumRecords, size, id, flags} headers framing typed
// records. Flow rows are CommonRecordType(1): a 28-byte fixed head
// (flags, ext-map id, msec_first/last, first/last seconds, fwd_status,
// tcp_flags, proto, tos, ports) followed by the required extensions in
// fixed order — addresses (v4 2x u32 / v6 2x 16B per flags bit 0),
// packets (u32/u64 per bit 1), bytes (u32/u64 per bit 2) — and then
// optional extensions this reader skips via the record's size field
// (so unknown extension maps can never desync framing). Extension-map
// (2), exporter (7/8) and sampler (9) records are skipped whole.
//
// Scope: little-endian layout-v1 files, uncompressed or block-
// compressed. LZO1X and LZ4 decompress through clean-room decoders
// implemented from the public formats (no third-party code or library
// needed); BZ2 loads the system libbz2 at runtime and only its absence
// falls back (-2) to an installed nfdump. A big-endian writer's file
// returns kNfcapdByteOrder.

namespace {

constexpr uint16_t kNfcapdMagic = 0xA50C;
constexpr size_t kNfcapdFileHeader = 140;  // magic..ident[128]
constexpr size_t kNfcapdStatRecord = 136;
constexpr size_t kNfcapdBlockHeader = 12;
constexpr uint32_t kNfcapdFlagLzo = 0x1;
constexpr uint32_t kNfcapdFlagBz2 = 0x8;
constexpr uint32_t kNfcapdFlagLz4 = 0x10;
constexpr uint32_t kNfcapdCompressionFlags =
    kNfcapdFlagLzo | kNfcapdFlagBz2 | kNfcapdFlagLz4;
// nfdump writes blocks from a ~1 MB buffer; decompressed payloads are
// bounded by it. 4x headroom so a future larger writer still decodes.
constexpr size_t kNfcapdBlockCap = 4u << 20;
constexpr uint16_t kCommonRecordType = 1;
constexpr uint16_t kFlagIpv6Addr = 0x1;
constexpr uint16_t kFlagPkts64 = 0x2;
constexpr uint16_t kFlagBytes64 = 0x4;

inline uint16_t le16(const uint8_t* p) { return (uint16_t)(p[0] | p[1] << 8); }
inline uint32_t le32(const uint8_t* p) {
  return (uint32_t)p[0] | (uint32_t)p[1] << 8 | (uint32_t)p[2] << 16 |
         (uint32_t)p[3] << 24;
}
inline uint64_t le64(const uint8_t* p) {
  return (uint64_t)le32(p) | ((uint64_t)le32(p + 4) << 32);
}

// --- block decompressors ---------------------------------------------------
//
// Clean-room implementations from the PUBLIC formats (LZ4 block format
// spec; LZO1X bitstream as documented in Linux Documentation/lzo.txt)
// — no third-party source consulted. Every read is bounds-checked: the
// decoders run on untrusted capture files under the ASan harness
// (native/asan_harness.py), so a torn or lying block must fail with a
// negative code, never a heap overrun.

// LZ4 block format: sequences of [token][literals+][offset u16 LE]
// [matchlen+]. High token nibble = literal count (15 → extension
// bytes), low nibble = match length - 4 (15 → extension). The final
// sequence has literals only. Returns decompressed size or -1.
int64_t lz4_block_decode(const uint8_t* src, size_t slen, uint8_t* dst,
                         size_t dcap) {
  size_t s = 0, d = 0;
  while (s < slen) {
    const uint8_t token = src[s++];
    size_t lit = token >> 4;
    if (lit == 15) {
      uint8_t b;
      do {
        if (s >= slen) return -1;
        b = src[s++];
        lit += b;
      } while (b == 255);
    }
    if (s + lit > slen || d + lit > dcap) return -1;
    std::memcpy(dst + d, src + s, lit);
    s += lit;
    d += lit;
    if (s == slen) break;  // final sequence: literals only
    if (s + 2 > slen) return -1;
    const size_t offset = le16(src + s);
    s += 2;
    if (offset == 0 || offset > d) return -1;
    size_t mlen = (token & 0xF) + 4;
    if ((token & 0xF) == 15) {
      uint8_t b;
      do {
        if (s >= slen) return -1;
        b = src[s++];
        mlen += b;
      } while (b == 255);
    }
    if (d + mlen > dcap) return -1;
    // Overlapping copy (offset < mlen) must replay bytes in order.
    for (size_t i = 0; i < mlen; ++i) dst[d + i] = dst[d + i - offset];
    d += mlen;
  }
  return (int64_t)d;
}

// LZO1X bitstream (Documentation/lzo.txt): instruction stream over a
// small state machine — `state` is how many trailing literals the
// previous instruction carried (0..4; 4 means "a long literal run just
// ran"). Returns decompressed size or -1.
int64_t lzo1x_decode(const uint8_t* src, size_t slen, uint8_t* dst,
                     size_t dcap) {
  size_t s = 0, d = 0;
  unsigned state = 0;

  auto copy_lit = [&](size_t n) -> bool {
    if (s + n > slen || d + n > dcap) return false;
    std::memcpy(dst + d, src + s, n);
    s += n;
    d += n;
    return true;
  };
  auto copy_match = [&](size_t dist, size_t n) -> bool {
    if (dist == 0 || dist > d || d + n > dcap) return false;
    for (size_t i = 0; i < n; ++i) dst[d + i] = dst[d + i - dist];
    d += n;
    return true;
  };
  // Run-length extension: L==0 → 255 per zero byte + final byte.
  auto extend = [&](size_t base) -> int64_t {
    size_t n = base;
    uint8_t b;
    do {
      if (s >= slen) return -1;
      b = src[s++];
      n += (b == 0) ? 255 : b;
      if (n > kNfcapdBlockCap) return -1;  // cap run-away lengths
    } while (b == 0);
    return (int64_t)n;
  };

  if (slen == 0) return -1;
  if (src[0] >= 18) {  // initial literal run: first byte - 17 literals
    const size_t n = (size_t)src[0] - 17;
    ++s;
    if (!copy_lit(n)) return -1;
    state = n >= 4 ? 4 : (unsigned)n;
  }
  while (s < slen) {
    const uint8_t t = src[s++];
    if (t <= 15) {
      if (state == 0) {  // long literal run
        size_t n;
        if (t == 0) {
          const int64_t e = extend(18);
          if (e < 0) return -1;
          n = (size_t)e;
        } else {
          n = (size_t)t + 3;
        }
        if (!copy_lit(n)) return -1;
        state = 4;
        continue;
      }
      // M1: 2-byte match (after 1-3 literals) or 3-byte (after a run).
      if (s >= slen) return -1;
      const uint8_t h = src[s++];
      if (state == 4) {
        if (!copy_match(((size_t)h << 2) + (t >> 2) + 2049, 3)) return -1;
      } else {
        if (!copy_match(((size_t)h << 2) + (t >> 2) + 1, 2)) return -1;
      }
      state = t & 3;
      if (!copy_lit(state)) return -1;
      continue;
    }
    size_t len, dist, trailing;
    if (t >= 64) {  // M2: distance <= 2048
      len = (t >= 128) ? 5 + ((t >> 5) & 3) : 3 + ((t >> 5) & 1);
      if (s >= slen) return -1;
      dist = ((size_t)src[s++] << 3) + ((t >> 2) & 7) + 1;
      trailing = t & 3;
    } else if (t >= 32) {  // M3: distance <= 16384
      if ((t & 31) == 0) {
        const int64_t e = extend(33);
        if (e < 0) return -1;
        len = (size_t)e;
      } else {
        len = 2 + (t & 31);
      }
      if (s + 2 > slen) return -1;
      const uint16_t S = le16(src + s);
      s += 2;
      dist = ((size_t)S >> 2) + 1;
      trailing = S & 3;
    } else {  // 16..31, M4: distance 16384..49151 (end marker included)
      if ((t & 7) == 0) {
        const int64_t e = extend(9);
        if (e < 0) return -1;
        len = (size_t)e;
      } else {
        len = 2 + (t & 7);
      }
      if (s + 2 > slen) return -1;
      const uint16_t S = le16(src + s);
      s += 2;
      dist = 16384 + (((size_t)t & 8) << 11) + ((size_t)S >> 2);
      trailing = S & 3;
      if (dist == 16384) {
        // End-of-stream marker (the canonical "11 00 00").
        return s == slen ? (int64_t)d : -1;
      }
    }
    if (!copy_match(dist, len)) return -1;
    state = (unsigned)trailing;
    if (!copy_lit(trailing)) return -1;
  }
  return -1;  // ran off the stream without an end marker
}

// BZ2 via the system runtime library, loaded lazily — headers are not
// required, only the stable BZ2_bzBuffToBuffDecompress C ABI. Absent
// lib → -2 (the caller's "use the nfdump passthrough" code).
typedef int (*bz2_decomp_fn)(char*, unsigned*, char*, unsigned, int, int);
bz2_decomp_fn load_bz2() {
  static bz2_decomp_fn fn = []() -> bz2_decomp_fn {
    void* h = dlopen("libbz2.so.1.0", RTLD_LAZY | RTLD_LOCAL);
    if (!h) h = dlopen("libbz2.so.1", RTLD_LAZY | RTLD_LOCAL);
    if (!h) h = dlopen("libbz2.so", RTLD_LAZY | RTLD_LOCAL);
    return h ? (bz2_decomp_fn)dlsym(h, "BZ2_bzBuffToBuffDecompress")
             : nullptr;
  }();
  return fn;
}

// Dispatch one compressed block payload. Returns decompressed size,
// -1 malformed, -2 decompressor unavailable.
int64_t nfcapd_decompress_block(uint32_t file_flags, const uint8_t* src,
                                size_t slen, uint8_t* dst, size_t dcap) {
  if (file_flags & kNfcapdFlagLz4) return lz4_block_decode(src, slen, dst, dcap);
  if (file_flags & kNfcapdFlagLzo) return lzo1x_decode(src, slen, dst, dcap);
  if (file_flags & kNfcapdFlagBz2) {
    bz2_decomp_fn fn = load_bz2();
    if (!fn) return -2;
    unsigned out_len = (unsigned)dcap;
    const int rc = fn((char*)dst, &out_len, (char*)src, (unsigned)slen,
                      /*small=*/0, /*verbosity=*/0);
    return rc == 0 ? (int64_t)out_len : -1;
  }
  return -2;
}

// Walk the typed records of ONE (decompressed) block payload.
// Returns 1 to continue, 0 when the sink aborted, -1 malformed.
template <typename Sink>
int nfcapd_walk_records(const uint8_t* blk, size_t blk_size,
                        uint32_t n_rec, Sink&& sink) {
  size_t r = 0;
  for (uint32_t i = 0; i < n_rec; ++i) {
    if (r + 4 > blk_size) return -1;
    const uint16_t rtype = le16(blk + r);
    const uint16_t rsize = le16(blk + r + 2);
    if (rsize < 4 || r + rsize > blk_size) return -1;
    if (rtype == kCommonRecordType) {
      if (rsize < 28) return -1;
      const uint8_t* c = blk + r;
      const uint16_t rflags = le16(c + 4);
      const uint16_t msec_first = le16(c + 8);
      const uint16_t msec_last = le16(c + 10);
      const uint32_t first = le32(c + 12);
      const uint32_t last = le32(c + 16);
      V9Record out;
      out.tcp_flags = c[21];
      out.proto = c[22];
      out.sport = le16(c + 24);
      out.dport = le16(c + 26);
      size_t d = 28;  // required extensions follow the fixed head
      if (rflags & kFlagIpv6Addr) {
        // v6 flow: two 16-byte addresses stored big-endian. Decoded
        // into (hi, lo) u64 halves; the SINK decides whether its
        // output schema can carry them (the v4-only entry points
        // filter, the v6-aware ones render strings host-side).
        if (d + 32 > rsize) return -1;
        out.is_v6 = true;
        out.sip6_hi = ((uint64_t)be32(c + d) << 32) | be32(c + d + 4);
        out.sip6_lo = ((uint64_t)be32(c + d + 8) << 32) | be32(c + d + 12);
        out.dip6_hi = ((uint64_t)be32(c + d + 16) << 32) | be32(c + d + 20);
        out.dip6_lo = ((uint64_t)be32(c + d + 24) << 32) | be32(c + d + 28);
        d += 32;
      } else {
        if (d + 8 > rsize) return -1;
        out.sip = le32(c + d);
        out.dip = le32(c + d + 4);
        d += 8;
      }
      {
        const size_t pkt_w = (rflags & kFlagPkts64) ? 8 : 4;
        const size_t byt_w = (rflags & kFlagBytes64) ? 8 : 4;
        if (d + pkt_w + byt_w > rsize) return -1;
        const uint64_t pk =
            pkt_w == 8 ? le64(c + d) : (uint64_t)le32(c + d);
        d += pkt_w;
        const uint64_t by =
            byt_w == 8 ? le64(c + d) : (uint64_t)le32(c + d);
        // Saturate at the uint32 ABI ceiling like the sampling
        // scaler: a pinned max is visibly wrong, a wrapped small
        // number silently wrong.
        out.dpkts = pk > 0xFFFFFFFFULL ? 0xFFFFFFFFU : (uint32_t)pk;
        out.doctets = by > 0xFFFFFFFFULL ? 0xFFFFFFFFU : (uint32_t)by;
        const double t0 = (double)first + msec_first / 1000.0;
        const double t1 = (double)last + msec_last / 1000.0;
        if (!sink(out, t0, t1)) return 0;
      }
    }
    // Types 2 (extension map), 7/8 (exporter), 9 (sampler), and any
    // unknown record: skipped whole by declared size.
    r += rsize;
  }
  return 1;
}

// Walk every common record; sink(rec, t0, t1) -> false aborts. Returns
// 0 on success or a negative nfcapd_* error code. Compressed files
// (LZO1X / LZ4 / BZ2 per the header flags) decompress block by block
// through the clean-room decoders above; -2 is returned only when the
// needed decompressor is genuinely unavailable (BZ2 without a system
// libbz2).
template <typename Sink>
int64_t nfcapd_walk(const uint8_t* buf, int64_t len, Sink&& sink) {
  if (!buf || len < (int64_t)(kNfcapdFileHeader + kNfcapdStatRecord))
    return -1;
  const uint16_t magic = le16(buf);
  if (magic != kNfcapdMagic)
    return be16(buf) == kNfcapdMagic ? -3 : -1;  // BE writer vs not nfcapd
  const uint16_t version = le16(buf + 2);
  if (version != 1) return -4;  // other layout (nfdump 1.7's v2): the
  //                               caller can try an installed nfdump
  const uint32_t flags = le32(buf + 4);
  const bool compressed = (flags & kNfcapdCompressionFlags) != 0;
  std::vector<uint8_t> scratch;
  if (compressed) scratch.resize(kNfcapdBlockCap);
  const uint32_t n_blocks = le32(buf + 8);
  size_t off = kNfcapdFileHeader + kNfcapdStatRecord;
  for (uint32_t b = 0; b < n_blocks; ++b) {
    if (off + kNfcapdBlockHeader > (size_t)len) return -1;
    const uint32_t n_rec = le32(buf + off);
    const uint32_t blk_size = le32(buf + off + 4);
    const uint16_t blk_id = le16(buf + off + 8);
    off += kNfcapdBlockHeader;
    if (off + blk_size > (size_t)len) return -1;
    if (blk_id != 2) {  // only DATA_BLOCK_TYPE_2 carries flow records
      off += blk_size;  // skip whole — `size` frames it either way
      continue;
    }
    const uint8_t* payload = buf + off;
    size_t payload_len = blk_size;
    if (compressed) {
      const int64_t dec = nfcapd_decompress_block(
          flags, payload, payload_len, scratch.data(), scratch.size());
      if (dec == -2) return -2;  // decompressor unavailable (no libbz2)
      // A block that fails to decompress is indistinguishable from a
      // clean-room decoder gap on an exotic real-world stream — report
      // -5 so the caller can cross-check via an installed nfdump
      // instead of declaring the capture malformed outright.
      if (dec < 0) return -5;
      payload = scratch.data();
      payload_len = (size_t)dec;
    }
    const int rc = nfcapd_walk_records(payload, payload_len, n_rec, sink);
    if (rc < 0) return -1;
    if (rc == 0) return 0;
    off += blk_size;
  }
  return off == (size_t)len ? 0 : -1;
}

}  // namespace

extern "C" {

// Count flow rows in an nfcapd v1 file. Negative codes: -1 malformed,
// -2 compression whose decompressor is unavailable (BZ2 without a
// system libbz2 — use the nfdump passthrough), -3 big-endian writer,
// -5 a compressed block failed to decode (torn file OR a decoder gap
// — the passthrough can adjudicate),
// -4 unsupported layout version (nfdump 1.7's v2 — passthrough).
int64_t nfcapd_count(const uint8_t* buf, int64_t len) {
  int64_t n = 0;
  const int64_t rc = nfcapd_walk(
      buf, len, [&](const V9Record& r, double, double) {
        if (!r.is_v6) ++n;  // v4-only output schema
        return true;
      });
  return rc < 0 ? rc : n;
}

// Count ALL flow rows (v4 + v6) — pairs with nfcapd_decode_v6.
int64_t nfcapd_count_all(const uint8_t* buf, int64_t len) {
  int64_t n = 0;
  const int64_t rc = nfcapd_walk(
      buf, len, [&](const V9Record&, double, double) {
        ++n;
        return true;
      });
  return rc < 0 ? rc : n;
}

// Raw block-decompressor entry points — exported for the test suite
// (cross-validation against the system liblz4 via ctypes) and the ASan
// harness (torn/lying compressed payloads drive the decoders directly).
int64_t onix_lz4_block_decode(const uint8_t* src, int64_t slen,
                              uint8_t* dst, int64_t dcap) {
  if (!src || !dst || slen < 0 || dcap < 0) return -1;
  return lz4_block_decode(src, (size_t)slen, dst, (size_t)dcap);
}

int64_t onix_lzo1x_decode(const uint8_t* src, int64_t slen, uint8_t* dst,
                          int64_t dcap) {
  if (!src || !dst || slen < 0 || dcap < 0) return -1;
  return lzo1x_decode(src, (size_t)slen, dst, (size_t)dcap);
}

// Decode an nfcapd v1 file into caller-allocated arrays of length `n`
// (from nfcapd_count). Same output schema as nfx_decode.
int64_t nfcapd_decode(const uint8_t* buf, int64_t len, int64_t n,
                      uint32_t* sip, uint32_t* dip, uint16_t* sport,
                      uint16_t* dport, uint8_t* proto, uint8_t* tcp_flags,
                      uint32_t* dpkts, uint32_t* doctets, double* start_ts,
                      double* end_ts) {
  if (!sip || !dip || !sport || !dport || !proto || !tcp_flags || !dpkts ||
      !doctets || !start_ts || !end_ts)
    return -1;
  int64_t i = 0;
  const int64_t rc = nfcapd_walk(
      buf, len, [&](const V9Record& r, double t0, double t1) {
        if (r.is_v6) return true;  // v4-only output schema
        if (i >= n) return false;
        sip[i] = r.sip;
        dip[i] = r.dip;
        sport[i] = r.sport;
        dport[i] = r.dport;
        proto[i] = r.proto;
        tcp_flags[i] = r.tcp_flags;
        dpkts[i] = r.dpkts;
        doctets[i] = r.doctets;
        start_ts[i] = t0;
        end_ts[i] = t1;
        ++i;
        return true;
      });
  return rc < 0 ? rc : i;
}

// v6-aware decode: every flow row (count from nfcapd_count_all). v4
// rows put the address in the *_lo halves with is_v6[i] = 0; v6 rows
// carry the 128-bit addresses as big-endian (hi, lo) u64 halves with
// is_v6[i] = 1 — the Python layer renders display strings per row
// kind (SURVEY.md §2.1 #2's decoder scope; VERDICT r03 next #8).
int64_t nfcapd_decode_v6(const uint8_t* buf, int64_t len, int64_t n,
                         uint64_t* sip_hi, uint64_t* sip_lo,
                         uint64_t* dip_hi, uint64_t* dip_lo,
                         uint8_t* is_v6, uint16_t* sport, uint16_t* dport,
                         uint8_t* proto, uint8_t* tcp_flags,
                         uint32_t* dpkts, uint32_t* doctets,
                         double* start_ts, double* end_ts) {
  if (!sip_hi || !sip_lo || !dip_hi || !dip_lo || !is_v6 || !sport ||
      !dport || !proto || !tcp_flags || !dpkts || !doctets || !start_ts ||
      !end_ts)
    return -1;
  int64_t i = 0;
  const int64_t rc = nfcapd_walk(
      buf, len, [&](const V9Record& r, double t0, double t1) {
        if (i >= n) return false;
        if (r.is_v6) {
          sip_hi[i] = r.sip6_hi;
          sip_lo[i] = r.sip6_lo;
          dip_hi[i] = r.dip6_hi;
          dip_lo[i] = r.dip6_lo;
        } else {
          sip_hi[i] = 0;
          sip_lo[i] = r.sip;
          dip_hi[i] = 0;
          dip_lo[i] = r.dip;
        }
        is_v6[i] = r.is_v6 ? 1 : 0;
        sport[i] = r.sport;
        dport[i] = r.dport;
        proto[i] = r.proto;
        tcp_flags[i] = r.tcp_flags;
        dpkts[i] = r.dpkts;
        doctets[i] = r.doctets;
        start_ts[i] = t0;
        end_ts[i] = t1;
        ++i;
        return true;
      });
  return rc < 0 ? rc : i;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// CLI: nfdecode <capture.nf5>  — stream CSV to stdout, one row per flow,
// schema matching the ingest path's flow table (onix/ingest/nfdecode.py).
// ---------------------------------------------------------------------------

#ifndef ONIX_NFDECODE_NO_MAIN
int main(int argc, char** argv) {
  if (argc != 2) {
    std::fprintf(stderr, "usage: %s <capture.nf5>\n", argv[0]);
    return 2;
  }
  FILE* f = std::fopen(argv[1], "rb");
  if (!f) {
    std::perror(argv[1]);
    return 1;
  }
  std::fseek(f, 0, SEEK_END);
  const long sz = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  std::vector<uint8_t> buf((size_t)sz);
  if (std::fread(buf.data(), 1, (size_t)sz, f) != (size_t)sz) {
    std::fclose(f);
    std::fprintf(stderr, "short read\n");
    return 1;
  }
  std::fclose(f);

  // nfcapd container files (LE magic 0xA50C) route to the container
  // reader; everything else is a wire-format packet stream.
  const bool container =
      sz >= 2 && ((buf[0] == 0x0C && buf[1] == 0xA5) ||
                  (buf[0] == 0xA5 && buf[1] == 0x0C));  // LE or BE writer
  auto count_fn = container ? nfcapd_count : nfx_count;
  auto decode_fn = container ? nfcapd_decode : nfx_decode;
  const int64_t n = count_fn(buf.data(), sz);
  if (n == -2) {
    std::fprintf(stderr, "compression unavailable (bz2 without libbz2? use nfdump)\n");
    return 1;
  }
  if (n == -3) {
    std::fprintf(stderr, "big-endian nfcapd file not supported\n");
    return 1;
  }
  if (n == -4) {
    std::fprintf(stderr, "unsupported nfcapd layout version (use nfdump)\n");
    return 1;
  }
  if (n == -5) {
    std::fprintf(stderr,
                 "compressed block failed to decode (torn file or decoder "
                 "gap — cross-check with nfdump)\n");
    return 1;
  }
  if (n < 0) {
    std::fprintf(stderr, container ? "malformed nfcapd file\n"
                                   : "malformed netflow v5/v9/ipfix stream\n");
    return 1;
  }
  // n == 0 is legal (e.g. data sets whose template was never seen):
  // size the vectors at >=1 so .data() is non-null for the FFI's
  // null-pointer guard, and print just the header.
  const size_t cap = n > 0 ? (size_t)n : 1;
  std::vector<uint32_t> sip(cap), dip(cap), dpkts(cap), doctets(cap);
  std::vector<uint16_t> sport(cap), dport(cap);
  std::vector<uint8_t> proto(cap), flags(cap);
  std::vector<double> t0(cap), t1(cap);
  if (decode_fn(buf.data(), sz, n, sip.data(), dip.data(), sport.data(),
                dport.data(), proto.data(), flags.data(), dpkts.data(),
                doctets.data(), t0.data(), t1.data()) != n) {
    std::fprintf(stderr, "decode error\n");
    return 1;
  }
  std::printf("start_ts,end_ts,sip,dip,sport,dport,proto,tcp_flags,ipkt,ibyt\n");
  auto ip_str = [](uint32_t ip, char* out) {
    std::snprintf(out, 16, "%u.%u.%u.%u", (ip >> 24) & 255, (ip >> 16) & 255,
                  (ip >> 8) & 255, ip & 255);
  };
  char a[16], b[16];
  for (int64_t i = 0; i < n; ++i) {
    ip_str(sip[i], a);
    ip_str(dip[i], b);
    std::printf("%.3f,%.3f,%s,%s,%u,%u,%u,%u,%u,%u\n", t0[i], t1[i], a, b,
                sport[i], dport[i], proto[i], flags[i], dpkts[i], doctets[i]);
  }
  return 0;
}
#endif
