"""Typed configuration for onix.

The reference shares one untyped key-value file across every layer
(`/etc/duxbay.conf`-style, sourced by Bash, parsed by Python and Scala;
see SURVEY.md §5.6 — keys like DBNAME, NODES, TOL, TOPIC_COUNT, DUPFACTOR
are structurally required by the ml_ops.sh call stack, reference
README.md:41-43). onix replaces that with schema-validated dataclasses,
YAML/JSON loading, dotted-path CLI overrides, and an archived resolved
config per run.
"""

from __future__ import annotations

import dataclasses
import json
import hashlib
import os
import pathlib
from dataclasses import dataclass, field
from typing import Any, Callable

DATATYPES = ("flow", "dns", "proxy")


def resolve_form_gate(*, gate: str, choices: tuple[str, ...],
                      explicit: str | None = None,
                      env: str | None = None,
                      env_var: str | None = None,
                      measured: Callable[[], str | None] | None = None,
                      default: str) -> str:
    """The ONE precedence chain behind every measured performance gate —
    `lda_gibbs.select_nwk_form`, `model_bank.select_bank_form`, and
    `pallas_serve.select_serve_form` each resolve through this helper so
    the three tables cannot drift in precedence order:

        env override  >  explicit form  >  measured table  >  default

    `env` is the raw override value (or `env_var` to read it here);
    empty and "auto" both mean "no override" — exporting FOO=auto resets
    an inherited override instead of crashing. Any other value outside
    `choices` raises, for env and explicit alike: a typo'd override must
    fail loudly, never silently mislabel an experiment's arms. The nwk
    gate passes no env — its engines resolve ONIX_NWK_FORM themselves,
    where an explicit test-arm pin must outrank an exported override
    (make_block_step's documented contract), and hand the result in as
    `explicit`. `measured` is the per-backend crossover-table lookup;
    None (unmeasured platform, or below the crossover) falls to
    `default` — never an unmeasured guess."""
    if env is None and env_var is not None:
        env = os.environ.get(env_var)
    for value, what in ((env, f"{gate} (env override)"),
                        (explicit, gate)):
        if value is None or value in ("", "auto"):
            continue
        if value not in choices:
            raise ValueError(
                f"{what} must be auto|{'|'.join(choices)}, got {value!r}")
        return value
    if measured is not None:
        got = measured()
        if got is not None:
            return got
    return default


#: The central registry of every `ONIX_*` environment variable the
#: linted tree (onix/, bench.py, scripts/) reads: name -> (type, doc).
#: Machine-checked by `python -m onix.analysis` (the `envs` pass): a
#: literal ONIX_* read of an undeclared name is a finding, and so is a
#: declaration nothing reads — this table can neither lag nor rot. The
#: table also renders into docs/ROBUSTNESS.md (generated section
#: `env-registry`). Leading-underscore names are internal parent/child
#: handshakes, never operator knobs. Envs are OVERRIDES for
#: experiments and drills; durable configuration belongs in the typed
#: config below.
ENV_REGISTRY: dict[str, tuple[str, str]] = {
    "ONIX_BANK_FORM": (
        "choice: auto|vmap|gather",
        "model-bank batched-scoring form override (model_bank.select_bank_form)"),
    "ONIX_BANK_SHARD": (
        "choice: auto|single|sharded",
        "model-bank mesh placement override (model_bank.select_shard_form)"),
    "ONIX_BANK_TPU": (
        "flag: 1=keep ambient backend",
        "exp_model_bank.py: opt into the real TPU instead of pinning CPU"),
    "ONIX_BENCH_COMPONENTS": (
        "csv of component names",
        "bench.py: run only these components (debugging a single arm)"),
    "ONIX_BENCH_TIMEOUT_S": (
        "float seconds",
        "bench.py child wall-clock budget before the parent kills it"),
    "ONIX_CAMPAIGN_TPU": (
        "flag: 1=keep ambient backend",
        "exp_campaign.py: opt into the real TPU instead of pinning CPU"),
    # lint: exempt[envs] -- read inside the generated notebook-cell SOURCE templates (oa/notebooks.py) and exported to kernels by oa/serve.py; no AST-visible read exists
    "ONIX_CONFIG": (
        "path",
        "notebook kernels: resolved config file the OA cells load"),
    # lint: exempt[envs] -- read inside the generated notebook-cell SOURCE templates (oa/notebooks.py); exported by oa/serve.py and the CLI
    "ONIX_DATE": (
        "string YYYY-MM-DD",
        "notebook kernels: the scored date the OA cells read"),
    "ONIX_DAILY_FORCE_COLD": (
        "flag: 1=cold every day",
        "daily supervisor drill override: ignore yesterday's model and "
        "fit every day cold (pipelines/daily.py) — daily.force_cold is "
        "the durable knob"),
    "ONIX_DAILY_TPU": (
        "flag: 1=keep ambient backend",
        "exp_daily.py: opt into the real TPU instead of pinning CPU"),
    "ONIX_DEVICE_WORDS": (
        "flag: 0=host words",
        "legacy spelling of ONIX_HOST_WORDS=1 (device_words gate)"),
    "ONIX_DP1_FAST": (
        "flag: 0=pin wrapped arm",
        "sharded engine dp=1/mp=1 shard_map-bypass fast path override"),
    "ONIX_FAULT_PLAN": (
        "plan: stage:point@N=action,...",
        "declarative chaos plan (utils/faults.py; docs/ROBUSTNESS.md)"),
    "ONIX_FLEET_TPU": (
        "flag: 1=keep ambient backend",
        "exp_fleet.py: opt into the real TPU instead of pinning CPU"),
    "ONIX_HOSTFABRIC_COORD": (
        "addr: host:port",
        "hostfabric worker: jax.distributed coordinator address (set by "
        "the local coordinator for spawned workers; real hosts export it "
        "when launching workers by hand — parallel/hostfabric.py)"),
    "ONIX_FABRIC_WORKER_PLATFORM": (
        "jax platform name (cpu, tpu)",
        "hostfabric coordinator: platform spawned fit workers run on. "
        "Default cpu (safe anywhere); tpu splits this host's chips "
        "across workers via TPU_VISIBLE_DEVICES — the coordinator must "
        "then run under JAX_PLATFORMS=cpu so it holds no chips "
        "(parallel/hostfabric.py)"),
    "ONIX_FAULT_SWEEP": (
        "int sweep number",
        "legacy one-off fit:sweep preemption hook (pre-r9 chaos drills)"),
    "ONIX_GTI_API_KEY": (
        "secret",
        "GTI reputation client credential (oa/repclients.py)"),
    "ONIX_HOST_WORDS": (
        "flag: 1=host builders",
        "force the host word-build cross-check arm (device_words gate)"),
    "ONIX_JAX_CACHE": (
        "path",
        "persistent XLA compile-cache dir (accelerators only; obs.py)"),
    "ONIX_NWK_FORM": (
        "choice: auto|scatter|matmul|pallas",
        "n_wk count-update form override (lda_gibbs.select_nwk_form)"),
    "ONIX_NWK_MATMUL": (
        "legacy flag: 1=matmul, 0=scatter",
        "pre-r8 spelling of ONIX_NWK_FORM (make_block_step only)"),
    "ONIX_PALLAS_INTERPRET": (
        "flag: 1=interpret, 0=compiled",
        "Pallas kernels: force interpret/compiled mode (pallas_gibbs)"),
    "ONIX_PREFETCH_DEPTH": (
        "int >= 1",
        "streaming ingest pipeline depth override (ColumnPrefetcher)"),
    "ONIX_PREFETCH_MODE": (
        "choice: auto|thread|process",
        "streaming ingest pipeline worker mode override"),
    "ONIX_PROBE_BUDGET_S": (
        "float seconds",
        "bench.py backend-probe total wall budget"),
    "ONIX_PROFILE_DIR": (
        "path",
        "collect a jax profiler trace into this dir (obs.maybe_trace)"),
    "ONIX_SAMPLER_FORM": (
        "choice: auto|dense|sparse",
        "Gibbs sampler-form override (lda_gibbs.select_sampler_form)"),
    "ONIX_SCREENED_SELECT": (
        "flag: 1=on, other=off",
        "bf16-screened bottom-k scan override (models/scoring.py)"),
    "ONIX_SERVE_FORM": (
        "choice: auto|xla|fused",
        "serving-scan form override (pallas_serve.select_serve_form)"),
    "ONIX_TELEMETRY": (
        "flag: 0=off",
        "kill-switch for the r18 telemetry layer (spans, flight recorder; utils/telemetry.py) — telemetry.* config is the durable knob"),
    "ONIX_TELEMETRY_DIR": (
        "path",
        "flight-recorder dump dir fallback when no telemetry.recorder_dir was applied (utils/telemetry.py)"),
    "ONIX_TX_ACCESS_TOKEN": (
        "secret",
        "ThreatExchange reputation client credential (oa/repclients.py)"),
    "_ONIX_BENCH_CHILD": (
        "internal flag",
        "bench.py parent->child marker (the child skips re-spawning)"),
    "_ONIX_BENCH_PROGRESS": (
        "internal path",
        "bench.py child progress file the watchdog parent tails"),
    "_ONIX_BENCH_T0": (
        "internal float epoch-s",
        "bench.py parent start time, for the child's deadline math"),
    "_ONIX_TELEMETRY_SNAPSHOT": (
        "internal path",
        "run_tpu_queue per-entry handshake: the child writes a counters+histograms snapshot here at exit"),
}


@dataclass
class LDAConfig:
    """Topic-model hyperparameters.

    Mirrors the knobs of the reference LDA engine (oni-lda-c settings +
    the TOPIC_COUNT central-config key): K topics, Dirichlet priors, and
    iteration counts, plus TPU-batching knobs the reference has no analog
    for (block_size controls the token-block width of the batched
    collapsed-Gibbs sweep).
    """

    n_topics: int = 20
    alpha: float = 1.2          # doc-topic Dirichlet prior (lda-c style: ~50/K)
    eta: float = 0.01           # topic-word Dirichlet prior ("beta" in lda-c)
    n_sweeps: int = 60          # Gibbs sweeps / VB epochs
    burn_in: int = 20           # sweeps before averaging posterior estimates
    block_size: int = 65536     # tokens sampled per scatter round inside a sweep
    seed: int = 0
    # Online-VB (SVI) schedule: rho_t = (tau0 + t)^(-kappa)
    svi_tau0: float = 64.0
    svi_kappa: float = 0.7
    svi_batch_size: int = 4096  # documents per SVI minibatch
    svi_local_iters: int = 30   # local E-step fixed-point iteration CAP
    # E-step convergence stop (Hoffman's onlineldavb meanchange rule):
    # iteration ends early once mean |Δgamma| over the batch drops under
    # this. Converged batches stop in a handful of iterations instead of
    # always paying the svi_local_iters cap; 0 disables (fixed count).
    svi_meanchange_tol: float = 1e-3
    # Warm/cold E-step split (r10 streaming fast path): run this many
    # fixed-trip iterations over the full padded block, then COMPACT
    # the still-unconverged docs' tokens into a pow2 bucket and run the
    # extended while_loop only there (lda_svi._run_e_step). -1 = auto:
    # OFF for the batch SVI engine (bit-preserves the r6 loop), 4 for
    # the streaming scorer whose warm-started returning docs converge
    # inside the short pass. 0 forces the legacy loop everywhere; >0
    # forces the split at that warm length. Part of the streaming
    # checkpoint fingerprint — it changes what the E-step computes.
    svi_warm_iters: int = -1
    svi_max_epochs: int = 30    # batch-mode epoch cap (streaming: n/a)
    svi_epoch_tol: float = 1e-3  # stop when relative ll gain drops below
    checkpoint_every: int = 0   # sweeps between sampler checkpoints (0=off)
    # Independent Gibbs chains, batched on device via vmap; event scores
    # average over chains. Single chains are rank-unstable (recall on the
    # same data swings with the model seed — SURVEY.md §7.3.2's
    # "rank-stability tricks"); ≥4 chains stabilize the judged top-k.
    n_chains: int = 1
    # Sharded engine only: count synchronizations per sweep. 1 = psum at
    # sweep end (the reference's MPI cadence). Each extra sync halves
    # the cross-shard count staleness (which costs singleton-heavy
    # vocabularies like DNS ~0.01-0.02 of judged overlap at dp=8) for
    # one more K x Vc collective per sweep — cheap on ICI.
    sync_splits: int = 1
    # Gibbs fit superstep: sweeps chained inside ONE jitted program per
    # dispatch (docs/PERF.md "the gibbs_fit vs sweep-microbench gap" —
    # each dispatch costs ~70 ms RTT through the device tunnel, and the
    # old loop paid it per sweep plus separate likelihood programs).
    # The burn-in accumulate fold and the boundary log-likelihood run
    # on device inside the superstep; results are bit-identical to the
    # sweep-at-a-time loop for every superstep size (tested). 0 = auto
    # (lda_gibbs.SUPERSTEP_DEFAULT = 10, the old loop's ll cadence when
    # checkpointing is off). ll_history entries land at SEGMENT ends,
    # and segments also break at checkpoint boundaries — with
    # checkpointing on, entries land every min(superstep,
    # checkpoint_every)-ish sweeps: denser than the cap, never sparser.
    # Part of the checkpoint fingerprint: resuming under a different
    # superstep is refused, not silently different.
    superstep: int = 0
    # n_wk count-update form inside the Gibbs block step: "auto" picks
    # per backend + collision density at trace time (the measured gate,
    # lda_gibbs.select_nwk_form — scatter on CPU, MXU one-hot matmul on
    # TPU at density >= 32, the Pallas fused sample+count kernel once
    # its TPU crossover lands in _NWK_PALLAS_MIN_DENSITY). Explicit
    # values pin one form; all three are bit-identical (tested), so
    # this knob is pure performance — it is NOT part of the checkpoint
    # fingerprint and may change across a resume.
    nwk_form: str = "auto"
    # Gibbs sampler form: "dense" keeps the O(K)-per-token block
    # sampler (every arm of the nwk gate); "sparse" engages the r11
    # O(K_active) arm — per-document top-A active-topic sets compacted
    # into a static pow2 block, the dense-phi remainder proposed from
    # stale F+-tree-style CDF tables rebuilt each sweep, corrected by
    # Metropolis–Hastings acceptance so the stationary distribution of
    # the blocked chain is exact (lda_gibbs.select_sampler_form /
    # make_sparse_sweep). "auto" defers to the measured per-backend
    # _SAMPLER_SPARSE_MIN_K crossover tables (empty entries keep dense,
    # so defaults are unchanged until a platform is measured);
    # ONIX_SAMPLER_FORM overrides for experiments. UNLIKE nwk_form the
    # sparse arm is a different MCMC chain (same stationary
    # distribution, different draws), so the RESOLVED form is part of
    # the checkpoint fingerprint: a resume across an arm change is
    # refused, never silently different.
    sampler_form: str = "auto"
    # Static width A of the sparse arm's per-doc active-topic block
    # (topics beyond the stale top-A stay reachable through the
    # dense-phi proposal branch; MH keeps the chain exact either way).
    # 0 = auto: the smallest pow2 >= max(8, K/16), capped at K —
    # occupancy-driven, so cost tracks topics touched as K grows.
    sparse_active: int = 0
    # Metropolis–Hastings proposals per token per sweep for the sparse
    # arm (LightLDA-style cycle length). More proposals mix faster per
    # sweep at linearly more per-token cost.
    sparse_mh: int = 2
    # Sharded-engine count-merge form (r14; ROADMAP item 5's AD-LDA
    # extension, arxiv 0909.4603). "sync" keeps the synchronous psum
    # fold: every merge window (sync group) ends in a full-barrier
    # collective whose result gates the next window's sampling — the
    # reference's MPI_Reduce+Bcast cadence. "async" is the bounded-
    # staleness exchange: each shard sweeps against a count view that
    # carries its OWN updates fresh and its peers' deltas up to
    # merge_staleness merge windows late (Streaming Gibbs Sampling for
    # LDA, arxiv 1601.01142, gives the quality argument for sweeping on
    # bounded-stale counts), so the collective at window t no longer
    # gates the sampling of window t+1..t+τ and XLA can overlap it with
    # compute instead of stalling the pipeline. All pending deltas
    # flush at every fused-superstep boundary, so superstep-boundary
    # counts (checkpoints, the boundary ll, the accumulators) are
    # EXACT global counts in both forms. τ=0 degenerates to a path
    # bit-identical to the synchronous fold (tested); τ>0 is a
    # different chain with the same stationary target, held to the
    # LL_PARITY_BAND + winner-parity contract. The RESOLVED merge form
    # joins both engines' checkpoint fingerprints: a resume across a
    # merge-form/τ change is refused, and sync contributes nothing so
    # pre-r14 checkpoints keep resuming.
    merge_form: str = "sync"
    # Merge windows a peer delta may lag in the async arm (τ). A delta
    # produced at merge window t folds in at window t+τ — never later
    # (ring FIFO, sharded_gibbs.ring_push) — or at the superstep
    # flush, whichever comes first. Ignored under merge_form="sync".
    merge_staleness: int = 1
    # Streaming local-update family: "svi" (Hoffman's uncollapsed
    # variational E-step — the default, unchanged) or "scvb0" (the
    # SCVB0 collapsed zeroth-order minibatch arm, arxiv 1305.2452 —
    # no digammas, linear-space count responsibilities) riding the
    # same superstep + union gamma store machinery. A different
    # estimator: winner-set-parity discipline, part of the streaming
    # checkpoint fingerprint.
    stream_estep: str = "svi"

    def validate(self) -> None:
        if self.n_topics < 2:
            raise ValueError(f"n_topics must be >=2, got {self.n_topics}")
        if self.alpha <= 0 or self.eta <= 0:
            raise ValueError("alpha and eta must be positive")
        if self.block_size < 1:
            raise ValueError("block_size must be >=1")
        if not (0.5 < self.svi_kappa <= 1.0):
            raise ValueError("svi_kappa must be in (0.5, 1] for convergence")
        if self.svi_max_epochs < 1:
            raise ValueError("svi_max_epochs must be >= 1")
        if self.svi_epoch_tol < 0:
            raise ValueError("svi_epoch_tol must be >= 0")
        if self.svi_meanchange_tol < 0:
            raise ValueError("svi_meanchange_tol must be >= 0")
        if self.svi_warm_iters < -1:
            raise ValueError("svi_warm_iters must be >= -1 (-1 = auto)")
        if self.checkpoint_every < 0:
            raise ValueError("checkpoint_every must be >= 0")
        if self.n_chains < 1:
            raise ValueError("n_chains must be >= 1")
        if self.sync_splits < 1:
            raise ValueError("sync_splits must be >= 1")
        if self.superstep < 0:
            raise ValueError("superstep must be >= 0 (0 = auto)")
        if self.nwk_form not in ("auto", "scatter", "matmul", "pallas"):
            raise ValueError(
                "lda.nwk_form must be auto|scatter|matmul|pallas, "
                f"got {self.nwk_form!r}")
        if self.sampler_form not in ("auto", "dense", "sparse"):
            raise ValueError(
                "lda.sampler_form must be auto|dense|sparse, "
                f"got {self.sampler_form!r}")
        if self.sparse_active < 0:
            raise ValueError("sparse_active must be >= 0 (0 = auto)")
        if self.sparse_mh < 1:
            raise ValueError("sparse_mh must be >= 1")
        if self.stream_estep not in ("svi", "scvb0"):
            raise ValueError(
                "lda.stream_estep must be svi|scvb0, "
                f"got {self.stream_estep!r}")
        if self.merge_form not in ("sync", "async"):
            raise ValueError(
                f"lda.merge_form must be sync|async, got {self.merge_form!r}")
        if self.merge_staleness < 0:
            raise ValueError("lda.merge_staleness must be >= 0")


@dataclass
class MeshConfig:
    """Device-mesh layout for multi-chip runs.

    The reference parallelizes with MPI ranks over a machinefile of NODES
    (SURVEY.md §2.3). onix uses a jax.sharding.Mesh with a data axis ("dp",
    documents/tokens sharded) and a model axis ("mp", vocabulary sharded
    when K×V outgrows one chip's HBM — SURVEY.md §5.7).
    """

    dp: int = 1                 # data-parallel axis size (documents/tokens)
    mp: int = 1                 # model-parallel axis size (vocabulary shards)
    # Multi-host runtime (SURVEY.md §2.3 — replaces mpiexec+machinefile).
    # On a TPU pod leave these empty: jax.distributed.initialize
    # auto-detects the coordinator from the TPU metadata. Off-pod (CPU
    # tests, GPU clusters) set all three; the sharded engine then calls
    # multihost_init() before building the mesh.
    coordinator: str = ""       # host:port of process 0; "" = auto/single
    num_processes: int = 0      # 0 = auto (single host unless on a pod)
    process_id: int = -1        # -1 = auto

    def validate(self) -> None:
        if self.dp < 1 or self.mp < 1:
            raise ValueError("mesh axis sizes must be >=1")
        manual = (bool(self.coordinator), self.num_processes > 0,
                  self.process_id >= 0)
        if any(manual) and not all(manual):
            raise ValueError(
                "mesh.coordinator, mesh.num_processes, and mesh.process_id "
                "must be set together for an explicit multi-host launch")

    @property
    def n_devices(self) -> int:
        return self.dp * self.mp


@dataclass
class PipelineConfig:
    """One scoring run: a day of one datatype.

    Mirrors `ml_ops.sh <YYYYMMDD> <flow|dns|proxy> [TOL] [MAXRESULTS]`
    (SURVEY.md §3.1) plus the feedback DUPFACTOR of the OA noise-filter
    loop (reference README.md:48).
    """

    datatype: str = "flow"
    date: str = "2016-07-08"
    tol: float = 1.1            # score threshold: events with score < tol survive
    max_results: int = 2000     # top-N ascending by score emitted for OA
    dupfactor: int = 1000       # analyst-labeled rows duplicated x this in corpus
    stream_max_docs: int = 0    # streaming doc-state bound (0 = unbounded):
    #                             LRU-evict idle IPs past this population
    # Streaming supersteps: chain this many minibatch updates (E-step +
    # λ-step + incremental scoring) inside ONE jitted program per
    # dispatch, winners fetched once per superstep (streaming.py
    # process_many; the SVI analog of lda.superstep). 0/1 = the
    # per-batch path. Eviction and checkpointing move to superstep
    # boundaries (the doc bound gains up to S batches of slack).
    stream_superstep: int = 0
    # Host ingest pipeline ahead of the device step: how many batches
    # the ColumnPrefetcher decodes + converts ahead (bounded, in-order
    # handoff), and where that work runs — "thread" | "process" |
    # "auto" (auto measures the first batch's conversion wall against
    # its pickle round-trip cost and picks; process sidesteps the GIL
    # the pandas/string conversion holds).
    stream_prefetch_depth: int = 2
    stream_prefetch_mode: str = "auto"
    # Cap on the streaming pad-shape lattice: once this many distinct
    # (pad_to, pad_docs) pairs have compiled, new batches re-pad into a
    # covering existing shape (or grow one ceiling shape) instead of
    # silently compiling another program (streaming.py _pick_pad).
    stream_max_shapes: int = 8
    columnar: str = "auto"      # day-read mode for `onix score`: "on" always
    #                             reads the store part-by-part into numeric
    #                             columns (the 10^8+-row path), "off" keeps
    #                             the pandas/string reference path, "auto"
    #                             switches on COLUMNAR_AUTO_MIN_ROWS

    def validate(self) -> None:
        if self.datatype not in DATATYPES:
            raise ValueError(f"datatype must be one of {DATATYPES}")
        if self.max_results < 1:
            raise ValueError("max_results must be >=1")
        if self.columnar not in ("auto", "on", "off"):
            raise ValueError("pipeline.columnar must be auto|on|off")
        if self.dupfactor < 1:
            raise ValueError("dupfactor must be >=1")
        if self.stream_max_docs < 0:
            raise ValueError("stream_max_docs must be >=0")
        if self.stream_superstep < 0:
            raise ValueError("stream_superstep must be >= 0 (0 = off)")
        if self.stream_prefetch_depth < 1:
            raise ValueError("stream_prefetch_depth must be >= 1")
        if self.stream_prefetch_mode not in ("auto", "thread", "process"):
            raise ValueError(
                "pipeline.stream_prefetch_mode must be auto|thread|process, "
                f"got {self.stream_prefetch_mode!r}")
        if self.stream_max_shapes < 1:
            raise ValueError("stream_max_shapes must be >= 1")


@dataclass
class IngestConfig:
    """Telemetry decoding options (SURVEY.md §2.1 #1-#2).

    apply_sampling scales flow packet/byte counters by the announcing
    exporter's sampling interval (NetFlow v9 / IPFIX options records:
    field 34 or the sampler-table IEs 50/305; per source/domain id,
    with a pre-scan so flows ahead of a mid-file announcement scale
    too) — nfdump-style counter scaling for sampled exporters. Off by
    default: raw wire counters are the honest record of what was
    exported."""

    apply_sampling: bool = False


@dataclass
class StoreConfig:
    """Storage substrate: partitioned Parquet in place of HDFS+Hive.

    The reference stores telemetry in Hive tables flow/dns/proxy
    partitioned by y/m/d(/h) (SURVEY.md §2.1 #3). onix keeps the same
    logical layout as Parquet datasets under `root`.
    """

    # Empty sub-dirs mean "derive from root" (<root>/<name>) at
    # validate() time, so one --set store.root=... override relocates
    # the whole store (OA output included, see OAConfig).
    root: str = "data/onix"
    feedback_dir: str = ""
    results_dir: str = ""
    checkpoint_dir: str = ""
    # Hourly sub-partitions (y=/m=/d=/h=HH) on ingest — the reference's
    # /h Hive level. Readers fold hour parts into day scans either way.
    partition_hours: bool = False


@dataclass
class ServingConfig:
    """Model-bank serving (r12, `onix/serving/`): many tenants'
    (θ, φ) tables resident on device as stacked bank arrays, scored
    through one batched program per request batch (docs/PERF.md
    "model bank"). Consumed by the `/score` endpoint on `onix serve`
    and by the load harness."""

    # Empty means "derive from store.root" (<root>/models) at
    # validate() time — where run_scoring persists fitted models
    # (save_fitted) and where the serve layer's bank loads from.
    models_dir: str = ""
    # Resident tenants per shape class (tenants bucket by pow2-padded
    # (D_pad, V_pad, K)). Banks larger than this LRU-evict at request
    # batch boundaries; winners stay identical (model_bank.py).
    bank_capacity: int = 64
    # Batched scoring form: "vmap" | "gather" | "auto" (the measured
    # per-backend crossover table model_bank._BANK_GATHER_MIN_EVENTS;
    # ONIX_BANK_FORM overrides for experiments). Bit-identical forms —
    # pure performance.
    bank_form: str = "auto"
    # Serving-scan form: "xla" keeps the three-stage XLA path (batched
    # gather/matmul scoring, feedback membership search, chunked
    # bottom-M scan); "fused" engages the r15 one-kernel Pallas serving
    # path (onix/models/pallas_serve.py — score + filter membership +
    # bottom-M in one kernel, winners flushed once per request).
    # "auto" defers to the measured per-backend crossover table
    # (pallas_serve._SERVE_FUSED_MIN_EVENTS — deliberately EMPTY for
    # every backend, tpu included, until the queued TPU_QUEUE rows
    # land, so auto resolves to xla everywhere today);
    # ONIX_SERVE_FORM overrides for experiments. Both arms are
    # bit-identical (winners, scores, tie order) — pure performance.
    serve_form: str = "auto"
    # Requests per batched dispatch at the service layer; the bank
    # further splits a batch that exceeds bank_capacity distinct
    # tenants in one shape class.
    max_batch_requests: int = 64
    # Per-(tenant, window) winner cache entries kept by the service.
    winner_cache_size: int = 4096
    # run_scoring persists the fitted (θ, φ) under models_dir as
    # <datatype>/<yyyymmdd> so `onix serve` can score against it.
    save_fitted: bool = False
    # Loader-backed models kept in the HOST registry (0 = unbounded).
    # Device residency is bank_capacity; this bounds host RAM on a
    # long-lived server walking many (datatype, day, tenant) models —
    # past it the LRU re-fetchable, non-resident host copy is dropped
    # (bank.host_evict) and reloads from models_dir on next reference.
    host_model_cache: int = 1024
    # Admission control (r16, docs/ROBUSTNESS.md "serving resilience"):
    # request batches in flight + queued at the service before new ones
    # are SHED with 503 + Retry-After (`serve.shed`). 0 disables
    # shedding (unbounded queue — the pre-r16 behavior). Shed requests
    # never touch bank residency or winner caches.
    max_queue_depth: int = 64
    # Per-request wall-clock budget in milliseconds, measured from
    # request receipt THROUGH the admission queue: a request whose
    # budget expires before scoring starts is refused 503 + Retry-After
    # (`serve.deadline_expired`) instead of burning device time on an
    # answer the client has given up on. 0 disables the deadline. Once
    # scoring starts the request runs to completion — partial winner
    # sets are never served.
    request_deadline_ms: float = 0.0
    # Degradation ladder: a "fused" (r15 Pallas) serve-form dispatch
    # that fails falls back to the bit-identical xla form, counted
    # (`serve.form_fallback`) and stamped `degraded: true` on the
    # response. Off = the failure propagates (debugging the kernel).
    degrade_form_fallback: bool = True
    # Mesh placement (r20): "single" keeps every tenant's bank on one
    # device (the pre-r20 shape); "sharded" spreads shape-class banks
    # over the visible device mesh by tenant hash — per-device waves,
    # no cross-device collective, winners bit-identical. "auto"
    # defers to the measured per-backend crossover table
    # (model_bank._BANK_SHARD_MIN_TENANTS — deliberately EMPTY until
    # the queued docs/TPU_QUEUE.json `bank_sharded_tpu` rows land, so
    # auto resolves single everywhere today); ONIX_BANK_SHARD
    # overrides for experiments.
    bank_shard: str = "auto"
    # Host-RAM tier prefetch budget (r20): tenants promoted from disk
    # into the host registry per request-batch boundary, ranked by the
    # bank's decayed Zipf demand estimate. 0 disables prefetch (misses
    # load on demand — the pre-r20 shape).
    prefetch_depth: int = 0
    # Serve replicas behind one front (r20, onix/serving/replicas.py):
    # N independent BankService replicas, tenant-hash routed, with the
    # epoch bulletin guaranteeing an out-of-band bump (feedback, daily
    # refit) reaches a tenant's serving replica before its next score.
    # 1 = a bare BankService (the pre-r20 shape).
    replicas: int = 1

    def validate(self) -> None:
        if self.bank_capacity < 1:
            raise ValueError("serving.bank_capacity must be >= 1")
        if self.host_model_cache < 0:
            raise ValueError("serving.host_model_cache must be >= 0")
        if self.max_queue_depth < 0:
            raise ValueError("serving.max_queue_depth must be >= 0 "
                             "(0 = unbounded)")
        if self.request_deadline_ms < 0:
            raise ValueError("serving.request_deadline_ms must be >= 0 "
                             "(0 = no deadline)")
        if self.bank_form not in ("auto", "vmap", "gather"):
            raise ValueError(
                "serving.bank_form must be auto|vmap|gather, "
                f"got {self.bank_form!r}")
        if self.serve_form not in ("auto", "xla", "fused"):
            raise ValueError(
                "serving.serve_form must be auto|xla|fused, "
                f"got {self.serve_form!r}")
        if self.max_batch_requests < 1:
            raise ValueError("serving.max_batch_requests must be >= 1")
        if self.winner_cache_size < 0:
            raise ValueError("serving.winner_cache_size must be >= 0")
        if self.bank_shard not in ("auto", "single", "sharded"):
            raise ValueError(
                "serving.bank_shard must be auto|single|sharded, "
                f"got {self.bank_shard!r}")
        if self.prefetch_depth < 0:
            raise ValueError("serving.prefetch_depth must be >= 0 "
                             "(0 = off)")
        if self.replicas < 1:
            raise ValueError("serving.replicas must be >= 1")


@dataclass
class FeedbackConfig:
    """The analyst feedback loop (r13, `onix/feedback/`): how captured
    verdicts turn into model behavior on two timescales — the immediate
    noise-filter rescoring (suppress/boost applied inside the scoring
    scans and the model bank) and the incremental online λ/φ update
    that rides the SVI machinery on feedback-weighted minibatches
    (PAPER.md §L5's noise filter + the Streaming-Gibbs/SCVB0 update
    family, arxiv 1601.01142 / 1305.2452)."""

    # Immediate rescoring on/off: the DEFAULT install gate — when
    # False, apply_feedback and the serve-side compile install no
    # filter unless the caller explicitly overrides (the
    # online-update-only configuration the replay harness's ≤5-batch
    # arm measures). An installed filter is always applied.
    filter_enabled: bool = True
    # Score multiplier for BOOSTED (analyst-confirmed threat) events in
    # the filtered scans: < 1 pushes a confirmed event further down the
    # ascending-suspicious order so it keeps surfacing. 1.0 disables
    # boosting while keeping suppression.
    boost_scale: float = 0.25
    # Token weight of a DISMISSED (benign) row in the online-update
    # minibatch — the streaming analog of the reference's ×DUPFACTOR
    # corpus duplication: weight-w feedback tokens update λ exactly as
    # w identical observed tokens would, raising p(word|doc) until the
    # dismissed traffic stops scoring suspicious. 0 disables the online
    # update (immediate filter only).
    dismiss_weight: float = 1000.0
    # Token weight of a CONFIRMED (threat) row in the online-update
    # minibatch. Default 0: confirmations must NOT add mass (that would
    # teach the model the attack pattern is common — the exact failure
    # load_feedback guards against); they act through the boost filter.
    confirm_weight: float = 0.0
    # SVI steps per feedback application (each step replays the
    # feedback-weighted minibatch once through svi_step).
    online_steps: int = 1
    # λ pseudo-count strength when nudging a fitted batch (θ, φ) model
    # (OnlineUpdater): λ0 = eta + prior_strength·φ, so the nudge moves
    # a posterior with this much prior mass, not a fresh model.
    prior_strength: float = 10000.0
    # θ pseudo-count strength for the nudged model's document rows:
    # new θ_d ∝ theta_strength·θ_d + (γ_d − α) after the weighted
    # E-step.
    theta_strength: float = 100.0

    def validate(self) -> None:
        if not (0.0 < self.boost_scale <= 1.0):
            raise ValueError("feedback.boost_scale must be in (0, 1]")
        if self.dismiss_weight < 0 or self.confirm_weight < 0:
            raise ValueError("feedback weights must be >= 0")
        if self.online_steps < 1:
            raise ValueError("feedback.online_steps must be >= 1")
        if self.prior_strength <= 0 or self.theta_strength <= 0:
            raise ValueError("feedback strengths must be > 0")


@dataclass
class TelemetryConfig:
    """The r18 telemetry layer (`onix/utils/telemetry.py`; operator
    page docs/OBSERVABILITY.md): request-scoped spans, log-bucketed
    latency histograms, the `/metrics` Prometheus exposition on
    `onix serve`, and the chaos flight recorder. Host-side only by
    construction — no knob here can change a device program, and
    `enabled=false` / `sample=0` is asserted winner-bit-identical with
    unchanged dispatch counts in tier-1 (tests/test_telemetry.py)."""

    # Master switch: off = no spans recorded, no flight-ring events,
    # no histogram observations, no recorder dumps. ONIX_TELEMETRY=0
    # is the env kill-switch for drills.
    enabled: bool = True
    # Trace sampling probability in [0, 1], decided once per trace id
    # (crc32 hash — deterministic, so a request's spans are all kept
    # or all dropped). 1.0 records every request; production fleets
    # drop this before they drop `enabled`.
    sample: float = 1.0
    # Flight-recorder ring capacity (recent span-close / counter-delta
    # / fault events kept for the postmortem dump).
    recorder_events: int = 1024
    # Where flight-recorder dumps land. Empty = derive
    # <store.root>/telemetry at validate() time. The recorder only
    # writes when a dir is routed (this, or ONIX_TELEMETRY_DIR for
    # processes that never applied a config) — unrouted dumps are
    # counted, never scattered into cwd.
    recorder_dir: str = ""

    def validate(self) -> None:
        if not 0.0 <= self.sample <= 1.0:
            raise ValueError("telemetry.sample must be in [0, 1], "
                             f"got {self.sample!r}")
        if self.recorder_events < 16:
            raise ValueError("telemetry.recorder_events must be >= 16")


@dataclass
class DailyConfig:
    """The r19 continuous-operation supervisor (`onix/pipelines/daily.py`;
    docs/ROBUSTNESS.md "continuous operation"): how a multi-day chain of
    campaign runs warm-starts, drift-gates, and rolls back. Production
    runs the pipeline EVERY day — these knobs govern the day-over-day
    lifecycle, not any single day's fit."""

    # Drift gate: max per-topic total-variation distance between
    # today's warm-fitted φ̂ and yesterday's φ̂ over the shared
    # vocabulary (columns renormalized over the matched rows). A warm
    # refit whose drift exceeds this is DISCARDED and the day re-fits
    # cold (counted `daily.drift_cold_refits`) — the bounded-staleness
    # quality posture of arxiv 0909.4603 applied across days: a warm
    # chain may coast on yesterday's posterior only while it provably
    # stays near it. 0 disables the gate (warm fits always accepted).
    drift_max: float = 0.5
    # Sweep budget for a warm-started fit (φ̂-as-prior z-init, the
    # Streaming Gibbs treatment of arxiv 1601.01142). 0 = auto: half
    # the cold budget, floor 2 — the chain starts near the posterior,
    # so the wall the daily loop pays is roughly halved (measured in
    # docs/DAILY_r19_cpu.json; bench `daily_loop` tracks it per run).
    warm_sweeps: int = 0
    # Burn-in for a warm-started fit. 0 = auto: 1 sweep — the warm
    # chain needs settling, not re-convergence, so posterior averaging
    # starts almost immediately.
    warm_burn_in: int = 0
    # Per-day synthetic-feed seed offset: day d draws with
    # seed + stride*(d-1). 0 = a stationary week (identical background
    # every day — the dismissal-recurrence harness arm); 1 = fresh
    # traffic daily.
    day_seed_stride: int = 1
    # Durable spelling of the ONIX_DAILY_FORCE_COLD drill: never warm-
    # start, fit every day cold (the control arm of exp_daily.py).
    force_cold: bool = False

    def validate(self) -> None:
        if not 0.0 <= self.drift_max <= 1.0:
            raise ValueError("daily.drift_max must be in [0, 1] "
                             "(per-topic total variation), "
                             f"got {self.drift_max!r}")
        if self.warm_sweeps < 0:
            raise ValueError("daily.warm_sweeps must be >= 0 (0 = auto)")
        if self.warm_burn_in < 0:
            raise ValueError("daily.warm_burn_in must be >= 0 (0 = auto)")
        if self.warm_sweeps and self.warm_burn_in >= self.warm_sweeps:
            raise ValueError("daily.warm_burn_in must be < warm_sweeps")
        if self.day_seed_stride < 0:
            raise ValueError("daily.day_seed_stride must be >= 0")


@dataclass
class OAConfig:
    """Operational Analytics (SURVEY.md §2.1 #12-#13): enrichment inputs
    and the per-date UI data directory the dashboards read."""

    # Empty means "derive from store.root" (<root>/oa) at validate()
    # time, so one --set store.root=... override relocates the whole
    # store, OA outputs included.
    data_dir: str = ""
    # Per-cell wall deadline for the in-dashboard notebook kernels; a
    # cell past it is killed (the analyst restarts the session).
    kernel_cell_timeout_s: float = 120.0
    geoip_db: str = ""          # CSV: network,country,city,latitude,longitude,isp
    reputation: str = ""        # plugin specs, comma-separated: local:<path>|noop
    top_domains: str = ""       # popular-domains list file (rank order)


@dataclass
class OnixConfig:
    lda: LDAConfig = field(default_factory=LDAConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    pipeline: PipelineConfig = field(default_factory=PipelineConfig)
    ingest: IngestConfig = field(default_factory=IngestConfig)
    store: StoreConfig = field(default_factory=StoreConfig)
    oa: OAConfig = field(default_factory=OAConfig)
    serving: ServingConfig = field(default_factory=ServingConfig)
    feedback: FeedbackConfig = field(default_factory=FeedbackConfig)
    telemetry: TelemetryConfig = field(default_factory=TelemetryConfig)
    daily: DailyConfig = field(default_factory=DailyConfig)

    def validate(self) -> "OnixConfig":
        self.lda.validate()
        self.mesh.validate()
        self.pipeline.validate()
        self.serving.validate()
        self.feedback.validate()
        self.telemetry.validate()
        self.daily.validate()
        root = pathlib.Path(self.store.root)
        for attr, sub in (("feedback_dir", "feedback"),
                          ("results_dir", "results"),
                          ("checkpoint_dir", "checkpoints")):
            if not getattr(self.store, attr):
                setattr(self.store, attr, str(root / sub))
        if not self.oa.data_dir:
            self.oa.data_dir = str(root / "oa")
        if not self.serving.models_dir:
            self.serving.models_dir = str(root / "models")
        if not self.telemetry.recorder_dir:
            self.telemetry.recorder_dir = str(root / "telemetry")
        return self

    # -- serialization ----------------------------------------------------

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)

    @property
    def config_hash(self) -> str:
        """Stable hash identifying a resolved config (run manifests, §5.5)."""
        return hashlib.sha256(self.to_json().encode()).hexdigest()[:16]

    def archive(self, path: str | pathlib.Path) -> None:
        """Write the resolved config next to the run outputs."""
        p = pathlib.Path(path)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(self.to_json())


def _coerce(value: Any, target: type) -> Any:
    """Coerce a raw (possibly string, from a CLI override) value to the
    field's declared type — `pipeline.date=20160708` must stay a string."""
    if target is str:
        return str(value)
    if isinstance(value, str):
        if target is bool:
            if value.lower() in ("true", "false"):
                return value.lower() == "true"
            raise ValueError(f"expected bool, got {value!r}")
        if target in (int, float):
            return target(value)
    if target is float and isinstance(value, int):
        return float(value)
    if not isinstance(value, target):
        raise TypeError(f"expected {target.__name__}, got {type(value).__name__}")
    return value


def _build(cls, data: dict[str, Any]):
    """Recursively build a dataclass from a dict, rejecting unknown keys."""
    import typing
    fields = {f.name: f for f in dataclasses.fields(cls)}
    unknown = set(data) - set(fields)
    if unknown:
        raise KeyError(f"unknown config keys for {cls.__name__}: {sorted(unknown)}")
    hints = typing.get_type_hints(cls)
    kwargs = {}
    for name, value in data.items():
        sub = _NESTED.get((cls, name))
        if sub is not None:
            kwargs[name] = _build(sub, value or {})
        else:
            kwargs[name] = _coerce(value, hints[name])
    return cls(**kwargs)


_NESTED = {
    (OnixConfig, "lda"): LDAConfig,
    (OnixConfig, "mesh"): MeshConfig,
    (OnixConfig, "pipeline"): PipelineConfig,
    (OnixConfig, "ingest"): IngestConfig,
    (OnixConfig, "store"): StoreConfig,
    (OnixConfig, "oa"): OAConfig,
    (OnixConfig, "serving"): ServingConfig,
    (OnixConfig, "feedback"): FeedbackConfig,
    (OnixConfig, "telemetry"): TelemetryConfig,
    (OnixConfig, "daily"): DailyConfig,
}


def from_dict(data: dict[str, Any]) -> OnixConfig:
    return _build(OnixConfig, data).validate()


def load_config(path: str | pathlib.Path | None = None,
                overrides: list[str] | None = None) -> OnixConfig:
    """Load config from a YAML/JSON file with `a.b.c=value` CLI overrides."""
    data: dict[str, Any] = {}
    if path is not None:
        text = pathlib.Path(path).read_text()
        if str(path).endswith((".yaml", ".yml")):
            import yaml
            data = yaml.safe_load(text) or {}
        else:
            data = json.loads(text)
    for ov in overrides or []:
        if "=" not in ov:
            raise ValueError(f"override must be key.path=value, got {ov!r}")
        key, _, raw = ov.partition("=")
        node = data
        parts = key.split(".")
        for part in parts[:-1]:
            nxt = node.get(part)
            if not isinstance(nxt, dict):   # missing, or a bare YAML null
                nxt = {}
                node[part] = nxt
            node = nxt
        # Raw string; _coerce converts it against the field's declared type.
        node[parts[-1]] = raw
    return from_dict(data)
