"""Shared pow2 active-set compaction helpers (r10/r11).

Two engines exploit the same structural sparsity — most of a batch's
work concentrates on a small *active* subset of a statically-padded
axis — and both need static shapes under jit:

* the r10 SVI E-step (`lda_svi._run_e_step`): unconverged docs' tokens
  are compacted to the front of the padded token axis and only the
  smallest pow2 bucket that holds them runs the extended while_loop;
* the r11 sparse Gibbs arm (`lda_gibbs` sampler_form="sparse"):
  per-document active-topic sets are compacted into a static pow2
  block (top-A stale counts per doc), so per-token work scales with
  topics *touched*, not topics allocated.

The idiom is one trick: pick a pow2 ladder of static sizes up front,
move the active entries to the front (stable, order-preserving), and
branch (lax.switch) or slice to the smallest rung that covers them.
These helpers are the single home of that trick; `lda_svi` re-exports
`pow2_ladder` as its original `_active_ladder` name and is
bit-preserved (tests/test_svi.py runs unmodified against the hoist).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def pow2_ladder(t: int, max_rungs: int = 4, floor: int = 64) -> list[int]:
    """Pow2 bucket sizes for a compacted active block, largest (the
    full pad `t`) first. Capped at `max_rungs` so a lax.switch over
    the ladder compiles a bounded number of branches per shape class;
    `floor` stops the descent where smaller buckets stop paying."""
    sizes = [t]
    while len(sizes) < max_rungs and sizes[-1] > floor and sizes[-1] % 2 == 0:
        sizes.append(sizes[-1] // 2)
    return sizes


def ladder_index(n_active: jax.Array, sizes: list[int]) -> jax.Array:
    """Index (int32) of the SMALLEST rung in `sizes` (descending, as
    produced by pow2_ladder) that still holds `n_active` entries —
    the lax.switch branch selector. sizes[0] always fits (it is the
    full pad), so the result is in [0, len(sizes))."""
    if len(sizes) <= 1:
        return jnp.int32(0)
    return sum((n_active <= jnp.int32(s)).astype(jnp.int32)
               for s in sizes[1:])


def compact_front(active: jax.Array) -> jax.Array:
    """Stable permutation moving True entries of `active` to the
    front, original order preserved on both sides — the gather
    indices of the compaction (perm[i] = source index of slot i)."""
    return jnp.argsort(~active, stable=True)


def pow2_bucket(n: int, floor: int = 8) -> int:
    """Smallest power of two >= max(n, floor) — the static width of a
    compacted active block whose realized occupancy is at most `n`."""
    n = max(int(n), int(floor), 1)
    return 1 << (n - 1).bit_length()
