"""Pallas TPU kernel: fused categorical sample + count-merge block step.

docs/PERF.md measured (twice — the r2 sampling-only ablation and the r7
fit-gap harness) that the n_wk scatter-add IS the Gibbs sweep's ceiling
on TPU: on the judged product vocabularies (V~500, block 2^17) every
row of the count table collects 128-250 colliding updates per block and
XLA serializes them. The r7 answer out-muscled the scatter with an MXU
one-hot matmul that still materializes a [B, V] one-hot in HBM. This
module is the TPU-native answer — the framework pillar named in
onix/__init__.py:5 that no code exercised until now: a Pallas kernel
that OWNS the collision-dense count update, with the same block-
parallel count-merge structure as AD-LDA (PAPERS.md, arxiv 0909.4603).

One `pallas_call` per block step, grid over tiles of the block's B
tokens. Per tile (all VMEM-resident):

  1. sampling on the VPU — the gathered n_dk[d]/n_wk[w] rows and the
     pre-generated noise come in as [tile, K] blocks, and the kernel
     runs the EXACT float ops of `lda_gibbs.make_block_step` (exclusion
     of the token's own assignment, Gumbel-argmax in log space or the
     exponential race in linear space) to draw z_new;
  2. count-merge on the MXU — the per-token delta one-hots contract
     against the tile's vocabulary one-hot ([tile, V], built and
     consumed INSIDE VMEM, never materialized to HBM) into a dense
     [V, K] per-tile partial;
  3. accumulation — the partial folds into a [V, K] int32 accumulator
     that lives in VMEM across the whole grid (constant out-block
     index map) and is flushed to HBM once, at the last tile.

There is no scatter anywhere in the n_wk update: the serialized
collision chain the r2/r7 measurements identified is gone, not merely
overpowered. The n_dk update stays an XLA scatter outside the kernel —
documents are nearly collision-free within a block (PERF.md) and the
[D, K] table is orders of magnitude too large for a dense VMEM
accumulator.

Exactness: the MXU contraction's operands are {0,1} and {-1,0,1} in
f32 and every output magnitude is bounded by the tile size (<= 1024 <<
2^24), so the per-tile partial is exact integer math; the cross-tile
accumulation is int32. Combined with noise generated OUTSIDE the
kernel from the reference's own key stream (`key, skey = split(key)`
then one draw at [B, K] — the identical sequence), the kernel is
BIT-IDENTICAL to the scatter block step: same z sequence, same counts,
same accumulators (asserted in tests/test_pallas_gibbs.py under
interpret mode at every tested shape, and in the gibbs_sweep_pallas
bench component every run).

Interpret mode: `interpret=True` (the default off-TPU) lowers the
kernel to plain XLA ops — traceable, jittable, vmappable — so tier-1
asserts bit-identity on CPU and the same code compiles through Mosaic
on a real TPU. TPU-compiled rows are queued in docs/TPU_QUEUE.json
(`pallas_tpu_tests`, `fitgap_tpu`).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# VMEM budget for the per-tile [tile, V] vocabulary one-hot (f32
# bytes). 2 MB leaves the rest of the ~16 MB/core for the [tile, K]
# sampling blocks (lane-padded to 128), the [V, K] accumulator, and
# double-buffered input tiles — the worked budget is in docs/PERF.md
# ("Pallas fused sample+count"). tile is clamped to [8, 1024]: 8 is
# the f32 sublane minimum, 1024 keeps the MXU contraction's per-output
# accumulation bound far under 2^24 (exact integers in f32).
_ONEHOT_VMEM_BYTES = 2 << 20
_TILE_MAX = 1024
_TILE_MIN = 8


def tile_for(n_rows: int) -> int:
    """Token-tile size for a count table of `n_rows` vocabulary rows."""
    t = _ONEHOT_VMEM_BYTES // (4 * max(n_rows, 1))
    t = max(_TILE_MIN, min(_TILE_MAX, t))
    return (t // _TILE_MIN) * _TILE_MIN


def _default_interpret() -> bool:
    """Interpret everywhere but a real TPU (Mosaic is TPU-only; the
    emulation is trace-time, so it jits/vmaps/shard_maps like any jnp
    code). ONIX_PALLAS_INTERPRET=0/1 pins either way for experiments.

    Keyed off the PHYSICAL device platform, not jax.default_backend():
    the verify/test idiom for driving TPU trace arms on CPU mocks
    default_backend (so the gumbel sampler and the density gate trace
    their TPU forms), and the kernel must keep emulating there — only
    hardware that can actually run Mosaic should compile it."""
    env = os.environ.get("ONIX_PALLAS_INTERPRET")
    if env in ("0", "1"):
        return env == "1"
    try:
        platform = jax.devices()[0].platform
    except Exception:                           # noqa: BLE001
        from onix.utils.obs import counters
        counters.inc("pallas.device_probe_fallback")
        platform = jax.default_backend()
    return platform != "tpu"


def _kernel(ndk_ref, nwk_ref, nk_ref, noise_ref, w_ref, z_ref, m_ref,
            z_out_ref, dwk_ref, *, tile, k_topics, n_rows, alpha, eta,
            v_eta, use_gumbel):
    i = pl.program_id(0)
    iota_k = jax.lax.broadcasted_iota(jnp.int32, (tile, k_topics), 1)
    # Equality one-hot: the padding sentinel (z == K) matches no topic
    # column and yields a zero row, exactly like jax.nn.one_hot's
    # out-of-range behavior in the reference step.
    oh_old = (z_ref[:] == iota_k).astype(jnp.int32)
    ohf = oh_old.astype(jnp.float32)
    # The same float ops, in the same order, on the same values as
    # lda_gibbs.make_block_step — bit-identity depends on it.
    ndk = ndk_ref[:].astype(jnp.float32) - ohf
    nwk = nwk_ref[:].astype(jnp.float32) - ohf
    nk = nk_ref[:].astype(jnp.float32) - ohf
    if use_gumbel:
        logp = (jnp.log(ndk + alpha)
                + jnp.log(jnp.maximum(nwk + eta, 1e-10))
                - jnp.log(nk + v_eta))
        z_new = jnp.argmax(logp + noise_ref[:], axis=-1).astype(jnp.int32)
    else:
        p = ((ndk + alpha) * jnp.maximum(nwk + eta, 1e-10)
             / (nk + v_eta))
        z_new = jnp.argmax(p / -jnp.log(noise_ref[:]),
                           axis=-1).astype(jnp.int32)
    z_new = jnp.where(m_ref[:, 0] > 0, z_new, z_ref[:, 0])
    z_out_ref[:] = z_new[:, None]
    # Count-merge: delta one-hots against the tile's vocab one-hot on
    # the MXU — [tile, V]^T @ [tile, K] -> [V, K], all in VMEM.
    delta = (z_new[:, None] == iota_k).astype(jnp.int32) - oh_old
    iota_v = jax.lax.broadcasted_iota(jnp.int32, (tile, n_rows), 1)
    oh_w = (w_ref[:] == iota_v).astype(jnp.float32)
    part = jax.lax.dot_general(oh_w, delta.astype(jnp.float32),
                               (((0,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)

    @pl.when(i == 0)
    def _():
        dwk_ref[:] = jnp.zeros_like(dwk_ref)

    dwk_ref[:] += part.astype(jnp.int32)


def sample_count_block(ndk_rows, nwk_rows, n_k, noise, w, z_old, mask, *,
                       alpha, eta, v_eta, k_topics, n_rows, use_gumbel,
                       interpret=None):
    """Fused sample + n_wk count-merge for one token block.

    Args (B = block size, K = k_topics, V = `n_rows` count-table rows —
    the LOCAL chunk width under the sharded engine's mp axis):
      ndk_rows  int32 [B, K]  gathered n_dk[d] rows (block-start counts)
      nwk_rows  int32 [B, K]  gathered n_wk[w] rows
      n_k       int32 [K]     topic totals
      noise     f32  [B, K]   jax.random.gumbel (use_gumbel=True) or
                              uniform(minval=1e-38) (race form), drawn
                              from the reference step's own skey
      w         int32 [B]     LOCAL word ids (rows of the count table)
      z_old     int32 [B]     current assignments (K = padding sentinel)
      mask      f32  [B]      1 real token, 0 padding

    Returns (z_new int32 [B], d_wk int32 [n_rows, K]) with
    d_wk == sum_t onehot(w_t) ⊗ (onehot(z_new_t) - onehot(z_old_t)) —
    the exact integer delta the scatter form produces, so the caller's
    `n_wk + d_wk` is bit-identical to `n_wk.at[w].add(delta)`.
    """
    if interpret is None:
        interpret = _default_interpret()
    b = int(w.shape[0])
    v = int(n_rows)
    if b == 0:
        # Degenerate empty block: nothing to sample, zero delta.
        return (jnp.zeros((0,), jnp.int32), jnp.zeros((v, k_topics),
                                                      jnp.int32))
    # Grid sizing: pad B up to a tile multiple. Padded rows carry
    # mask=0 and the z sentinel, so they keep their (sentinel)
    # assignment and contribute an all-zero delta — they cannot touch
    # the counts, and their z output is sliced off.
    tile = min(tile_for(v), -(-b // _TILE_MIN) * _TILE_MIN)
    bp = -(-b // tile) * tile
    pad = bp - b
    if pad:
        ndk_rows = jnp.pad(ndk_rows, ((0, pad), (0, 0)))
        nwk_rows = jnp.pad(nwk_rows, ((0, pad), (0, 0)))
        # Pad value 1.0 keeps -log(noise) finite for the race form;
        # padded rows are masked out either way.
        noise = jnp.pad(noise, ((0, pad), (0, 0)), constant_values=1.0)
        w = jnp.pad(w, (0, pad))
        z_old = jnp.pad(z_old, (0, pad), constant_values=k_topics)
        mask = jnp.pad(mask, (0, pad))
    kern = functools.partial(
        _kernel, tile=tile, k_topics=k_topics, n_rows=v,
        alpha=float(alpha), eta=float(eta), v_eta=float(v_eta),
        use_gumbel=bool(use_gumbel))
    z_new, d_wk = pl.pallas_call(
        kern,
        grid=(bp // tile,),
        in_specs=[
            pl.BlockSpec((tile, k_topics), lambda i: (i, 0)),
            pl.BlockSpec((tile, k_topics), lambda i: (i, 0)),
            pl.BlockSpec((1, k_topics), lambda i: (0, 0)),
            pl.BlockSpec((tile, k_topics), lambda i: (i, 0)),
            pl.BlockSpec((tile, 1), lambda i: (i, 0)),
            pl.BlockSpec((tile, 1), lambda i: (i, 0)),
            pl.BlockSpec((tile, 1), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((tile, 1), lambda i: (i, 0)),
            # Constant index map: the [V, K] accumulator stays resident
            # in VMEM across every grid step and flushes to HBM once.
            pl.BlockSpec((v, k_topics), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bp, 1), jnp.int32),
            jax.ShapeDtypeStruct((v, k_topics), jnp.int32),
        ],
        interpret=interpret,
    )(ndk_rows, nwk_rows, n_k[None, :], noise, w[:, None], z_old[:, None],
      mask[:, None])
    return z_new[:b, 0], d_wk
