from onix.models.lda_gibbs import GibbsLDA, GibbsState  # noqa: F401
from onix.models.lda_svi import SVILda, SVIState  # noqa: F401
from onix.models.scoring import score_events, top_suspicious  # noqa: F401
