"""Fleet-batched warm refit: every tenant's daily Gibbs refit as ONE
vmapped program per pow2 shape class (r20 tentpole; ROADMAP item 3).

The r12 bank already *serves* thousands of tenants per dispatch; this
module gives the daily loop the matching FIT path. The chains-vmap axis
of `make_sweep_kernel` (lda_gibbs.init_chains: independent lanes, one
batched program) is extended to a TENANT axis:

* **Shape classes** — tenants are grouped by pow2-padded
  (n_docs, n_vocab, n_tokens) through `compaction.pow2_bucket`, the
  model-bank padding discipline, so a thousand-tenant fleet compiles a
  handful of programs instead of a thousand. Padding rides the
  engine's existing sentinel contract: pad tokens carry mask 0 and
  z == K, whose one-hot is a zero row, so padded mass never enters a
  count table (`padding_stats` accounts the waste).

* **One fused program per class** — host-drawn per-tenant z init
  (warm: the φ̂-as-prior CDF draw of the r19 daily chain; cold:
  uniform), exact blockwise count build, the dismissal count nudge,
  S sweeps with burn-in-gated posterior accumulation, posterior
  estimates, and per-tenant boundary log-likelihoods, all inside one
  `jax.vmap` of the ONE shared sweep kernel. Tenant lanes are
  mathematically independent — a lane's results depend only on its own
  inputs and PRNG stream (`fold_in(fold_in(key(seed), uid), day)` on a
  STABLE roster uid), which is what makes per-tenant quarantine
  surgical: dropping or rolling back one tenant cannot perturb any
  other lane's bits.

* **Dismissal count nudge** — the ×DUPFACTOR corpus rebuild of the
  reference's noise-filter loop re-synthesizes and re-tokenizes the
  corpus per dismissal weight, which cannot amortize across a fleet.
  `nudge_counts` folds an analyst dismissal (doc, word, weight)
  directly into the stacked count tables before the refit sweep: one
  collapsed-Gibbs draw k̂ ~ p(k|d,w) from the current counts, then an
  int32 scatter of the weight into n_dk/n_wk/n_k — frozen pseudo-mass
  in the Streaming Gibbs style of arXiv:1601.01142 (the sweeps never
  resample it, exactly like the ×dupfactor tokens the reference never
  scores). The dismissed pair GAINS probability mass, the r13
  OnlineUpdater direction, so it leaves the suspicious bottom-k.

* **Sparse-form compatible** — the kernel keeps its sampler-form gate,
  so a large-K fleet runs the O(K_active) partially-collapsed sampler
  of arXiv:1506.03784 per lane unchanged.

The tenant axis shards over the dp mesh through
`parallel/fleet_shard.py` (lane-parallel, collective-free), and the
`pipelines/fleet.py` supervisor owns the per-tenant lifecycle (ledger
shards, drift gates, lineage, quarantine).
"""

from __future__ import annotations

import dataclasses
import hashlib

import jax
import jax.numpy as jnp
import numpy as np

from onix.config import LDAConfig
from onix.models.compaction import pow2_bucket
from onix.models.lda_gibbs import (_one_hot, log_likelihood,
                                   make_sweep_kernel)

#: pow2 floors for the three padded dims — documents and vocab rows pad
#: to at least 8 (the compaction floor), token streams to at least one
#: SIMD-friendly block.
DOC_FLOOR = 8
VOCAB_FLOOR = 8
TOKEN_FLOOR = 64

#: Token-block width cap inside a lane: classes at or below the cap run
#: one block (n_blocks == 1); bigger classes split pow2-evenly so the
#: kernel's blockwise scan bounds its [B, K] temporaries exactly like
#: the single-tenant engines.
BLOCK_CAP = 1 << 16


# ---------------------------------------------------------------------------
# Inputs: one tenant-day, host-side.
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class TenantDay:
    """One tenant's refit inputs for one day (host arrays).

    `uid` is the tenant's STABLE roster integer — the PRNG lane
    identity. It must survive quarantines and roster churn unchanged
    (never an enumeration index of today's batch), so a tenant's chain
    is reproducible regardless of which other tenants fit beside it.
    """

    name: str
    uid: int
    docs: np.ndarray                    # int32 [N] token -> doc id
    words: np.ndarray                   # int32 [N] token -> vocab id
    n_docs: int
    n_vocab: int
    init_phi: np.ndarray | None = None  # [n_vocab, K] warm prior (today's vocab)
    fb_docs: np.ndarray | None = None   # int32 [F] dismissal doc ids
    fb_words: np.ndarray | None = None  # int32 [F] dismissal word ids
    fb_weights: np.ndarray | None = None  # int32 [F] nudge weights

    @property
    def n_tokens(self) -> int:
        return int(len(self.docs))

    @property
    def n_feedback(self) -> int:
        return 0 if self.fb_docs is None else int(len(self.fb_docs))


def class_key(t: TenantDay) -> tuple[int, int, int]:
    """The tenant-day's pow2 shape class: (D_pad, V_pad, N_pad)."""
    return (pow2_bucket(t.n_docs, DOC_FLOOR),
            pow2_bucket(t.n_vocab, VOCAB_FLOOR),
            pow2_bucket(t.n_tokens, TOKEN_FLOOR))


def _block_shape(n_pad: int) -> tuple[int, int]:
    """(n_blocks, block_size) for a pow2-padded token stream."""
    b = min(n_pad, BLOCK_CAP)
    return n_pad // b, b


def _z_init(t: TenantDay, k_topics: int, rng: np.random.Generator
            ) -> np.ndarray:
    """Host-side per-tenant z draw, deterministic in the rng: warm
    lanes draw z ~ p(k|w) ∝ init_phi[w] by inverse CDF (the
    sharded_gibbs.init_state warm recipe), cold lanes draw uniform."""
    n = t.n_tokens
    if t.init_phi is None:
        return rng.integers(0, k_topics, size=n).astype(np.int32)
    prior = np.asarray(t.init_phi, np.float64)
    if prior.shape != (t.n_vocab, k_topics):
        raise ValueError(
            f"tenant {t.name}: init_phi shape {prior.shape} != "
            f"({t.n_vocab}, {k_topics}) — map the prior into TODAY's "
            "vocabulary first (campaign.map_phi_prior)")
    p = np.maximum(prior[t.words], 1e-30)
    cdf = np.cumsum(p, axis=1)
    cdf /= cdf[:, -1:]
    u = rng.random((n, 1))
    z = (cdf < u).sum(axis=1).astype(np.int32)
    return np.minimum(z, k_topics - 1)


# ---------------------------------------------------------------------------
# Stacking: tenants -> shape classes of bank-style padded arrays.
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ShapeClass:
    """One shape class's stacked, padded, device-ready arrays. The
    leading axis is the tenant lane (the vmap/sharding axis)."""

    key: tuple[int, int, int]           # (D_pad, V_pad, N_pad)
    tenants: list[TenantDay]
    docs: np.ndarray                    # int32 [T, n_blocks, B]
    words: np.ndarray                   # int32 [T, n_blocks, B]
    mask: np.ndarray                    # float32 [T, n_blocks, B]
    z0: np.ndarray                      # int32 [T, n_blocks, B]
    fb_docs: np.ndarray                 # int32 [T, F_pad]
    fb_words: np.ndarray                # int32 [T, F_pad]
    fb_weights: np.ndarray              # int32 [T, F_pad]
    keys: np.ndarray                    # uint32 [T, 2] per-lane PRNG keys

    @property
    def n_lanes(self) -> int:
        return len(self.tenants)

    @property
    def tokens_real(self) -> int:
        return sum(t.n_tokens for t in self.tenants)

    @property
    def tokens_padded(self) -> int:
        return int(self.mask.size)


def stack_tenants(tenants: list[TenantDay], *, k_topics: int, seed: int,
                  day: int) -> list[ShapeClass]:
    """Group tenant-days into pow2 shape classes and stack each class's
    arrays bank-style (classes sorted by key, lanes sorted by uid, so
    the stacking — and therefore every lane's bits — is a pure function
    of the tenant set, never of arrival order)."""
    groups: dict[tuple[int, int, int], list[TenantDay]] = {}
    for t in tenants:
        groups.setdefault(class_key(t), []).append(t)
    base = jax.random.fold_in(jax.random.PRNGKey(seed), np.uint32(day))
    out = []
    for key in sorted(groups):
        members = sorted(groups[key], key=lambda t: t.uid)
        d_pad, v_pad, n_pad = key
        n_blocks, bsz = _block_shape(n_pad)
        tn = len(members)
        docs = np.zeros((tn, n_pad), np.int32)
        words = np.zeros((tn, n_pad), np.int32)
        mask = np.zeros((tn, n_pad), np.float32)
        z0 = np.full((tn, n_pad), k_topics, np.int32)   # pad sentinel K
        f_pad = pow2_bucket(max((t.n_feedback for t in members),
                                default=0), 1) \
            if any(t.n_feedback for t in members) else 0
        fb_d = np.zeros((tn, f_pad), np.int32)
        fb_w = np.zeros((tn, f_pad), np.int32)
        fb_wt = np.zeros((tn, f_pad), np.int32)
        lane_keys = np.empty((tn, 2), np.uint32)
        for i, t in enumerate(members):
            n = t.n_tokens
            docs[i, :n] = t.docs
            words[i, :n] = t.words
            mask[i, :n] = 1.0
            rng = np.random.default_rng([abs(int(seed)), int(day),
                                         int(t.uid)])
            z0[i, :n] = _z_init(t, k_topics, rng)
            if t.n_feedback:
                f = t.n_feedback
                fb_d[i, :f] = t.fb_docs
                fb_w[i, :f] = t.fb_words
                fb_wt[i, :f] = t.fb_weights
            lane_keys[i] = np.asarray(jax.random.fold_in(
                base, np.uint32(t.uid)), np.uint32)
        shape3 = (tn, n_blocks, bsz)
        out.append(ShapeClass(
            key=key, tenants=members,
            docs=docs.reshape(shape3), words=words.reshape(shape3),
            mask=mask.reshape(shape3), z0=z0.reshape(shape3),
            fb_docs=fb_d, fb_words=fb_w, fb_weights=fb_wt,
            keys=lane_keys))
    return out


def padding_stats(classes: list[ShapeClass]) -> dict:
    """Shape-class padding waste accounting (docs/PERF.md "fleet
    refit"): how much of the stacked token/table volume is pow2
    padding rather than real tenant mass."""
    real = sum(c.tokens_real for c in classes)
    padded = sum(c.tokens_padded for c in classes)
    return {
        "n_classes": len(classes),
        "n_tenants": sum(c.n_lanes for c in classes),
        "class_shapes": {str(c.key): c.n_lanes for c in classes},
        "tokens_real": int(real),
        "tokens_padded": int(padded),
        "token_pad_waste_frac": round(1.0 - real / max(padded, 1), 4),
    }


# ---------------------------------------------------------------------------
# The dismissal count nudge (arXiv:1601.01142 streaming recipe).
# ---------------------------------------------------------------------------


def nudge_counts(n_dk, n_wk, n_k, key, fb_docs, fb_words, fb_weights, *,
                 alpha: float, eta: float):
    """Fold dismissal rows into the count tables as frozen pseudo-mass.

    Each (d, w, weight) row draws ONE hard topic
    k̂ ~ p(k|d,w) ∝ (n_dk[d]+α)(n_wk[w]+η)/(n_k+Vη) from the current
    collapsed counts, then scatter-adds its integer weight at k̂ —
    int32-exact, so a crash-replayed nudge reproduces the same tables.
    Rows with weight 0 are no-ops (the padding contract). The sweeps
    that follow never resample this mass (it is attached to no z
    token): it acts as a per-pair prior shift that RAISES
    p(word | doc) for the dismissed pair, which is the r13
    dismiss-weight direction — benign traffic must gain probability
    until it stops looking anomalous."""
    v_eta = n_wk.shape[0] * eta
    logp = (jnp.log(n_dk[fb_docs].astype(jnp.float32) + alpha)
            + jnp.log(jnp.maximum(
                n_wk[fb_words].astype(jnp.float32) + eta, 1e-10))
            - jnp.log(n_k.astype(jnp.float32) + v_eta))
    k_hat = jax.random.categorical(key, logp, axis=-1).astype(jnp.int32)
    hot = _one_hot(k_hat, n_dk.shape[1]) * fb_weights[:, None]
    return (n_dk.at[fb_docs].add(hot),
            n_wk.at[fb_words].add(hot),
            n_k + hot.sum(axis=0, dtype=jnp.int32))


def nudge_digest(t: TenantDay) -> str | None:
    """sha256[:16] identity of a tenant-day's nudge rows — joins the
    model fingerprint/meta as the `nudge` extra (the warm_init
    discipline: semantics that bypass LDAConfig still refuse a
    mismatched resume)."""
    if not t.n_feedback:
        return None
    h = hashlib.sha256()
    for a in (t.fb_docs, t.fb_words, t.fb_weights):
        arr = np.ascontiguousarray(np.asarray(a, np.int64))
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    return h.hexdigest()[:16]


# ---------------------------------------------------------------------------
# The fused per-class refit program.
# ---------------------------------------------------------------------------


def _make_refit_body(cfg: LDAConfig, *, n_docs: int, n_vocab: int,
                     nwk_form: str | None, sampler_form: str | None,
                     sparse_active: int, sampler: str | None):
    """One tenant lane's refit, sweep kernel shared with every other
    engine: count build -> nudge -> S sweeps (burn-in-gated posterior
    accumulation) -> (θ̂, φ̂, boundary lls)."""
    alpha, eta, k = cfg.alpha, cfg.eta, cfg.n_topics
    n_sweeps, burn_in = cfg.n_sweeps, cfg.burn_in
    kernel = make_sweep_kernel(alpha=alpha, eta=eta, n_vocab=n_vocab,
                               k_topics=k, nwk_form=nwk_form,
                               sampler_form=sampler_form,
                               sparse_active=sparse_active,
                               sampler=sampler)

    def count_block(carry, xs):
        n_dk, n_wk, n_k = carry
        d, w, zb = xs
        oh = _one_hot(zb, k)                    # padding (z==K) -> zero row
        return (n_dk.at[d].add(oh), n_wk.at[w].add(oh),
                n_k + oh.sum(axis=0, dtype=jnp.int32)), None

    def one_tenant(z, docs, words, mask, fb_d, fb_w, fb_wt, key):
        (n_dk, n_wk, n_k), _ = jax.lax.scan(
            count_block,
            (jnp.zeros((n_docs, k), jnp.int32),
             jnp.zeros((n_vocab, k), jnp.int32),
             jnp.zeros((k,), jnp.int32)),
            (docs, words, z))
        key, nkey = jax.random.split(key)
        n_dk, n_wk, n_k = nudge_counts(n_dk, n_wk, n_k, nkey,
                                       fb_d, fb_w, fb_wt,
                                       alpha=alpha, eta=eta)

        def estimates(ndk_f, nwk_f):
            theta = (ndk_f + alpha) / (ndk_f.sum(-1, keepdims=True)
                                       + k * alpha)
            phi = (nwk_f + eta) / (nwk_f.sum(0, keepdims=True)
                                   + n_vocab * eta)
            return theta, phi

        theta0, phi0 = estimates(n_dk.astype(jnp.float32),
                                 n_wk.astype(jnp.float32))
        ll0 = log_likelihood(theta0, phi0, docs, words, mask)

        def body(carry, i):
            z, n_dk, n_wk, n_k, key, acc_ndk, acc_nwk, n_acc = carry
            z, n_dk, n_wk, n_k, key = kernel(z, n_dk, n_wk, n_k, key,
                                             docs, words, mask)
            take = (i >= burn_in).astype(jnp.float32)
            return (z, n_dk, n_wk, n_k, key,
                    acc_ndk + take * n_dk.astype(jnp.float32),
                    acc_nwk + take * n_wk.astype(jnp.float32),
                    n_acc + take), None

        carry = (z, n_dk, n_wk, n_k, key,
                 jnp.zeros((n_docs, k), jnp.float32),
                 jnp.zeros((n_vocab, k), jnp.float32),
                 jnp.float32(0.0))
        (z, n_dk, n_wk, n_k, key, acc_ndk, acc_nwk, n_acc), _ = \
            jax.lax.scan(body, carry, jnp.arange(n_sweeps))
        use_acc = n_acc > 0
        denom = jnp.maximum(n_acc, 1.0)
        ndk_f = jnp.where(use_acc, acc_ndk / denom,
                          n_dk.astype(jnp.float32))
        nwk_f = jnp.where(use_acc, acc_nwk / denom,
                          n_wk.astype(jnp.float32))
        theta, phi = estimates(ndk_f, nwk_f)
        ll = log_likelihood(theta, phi, docs, words, mask)
        return theta, phi, ll0, ll

    return one_tenant


def make_fleet_refit(cfg: LDAConfig, *, n_docs: int, n_vocab: int,
                     nwk_form: str | None = None,
                     sampler_form: str | None = None,
                     sparse_active: int = 0,
                     sampler: str | None = None):
    """The fused per-shape-class fleet program: `one_tenant` vmapped
    over the lane axis and jitted — T tenants' warm refits in ONE
    dispatch. Returns fn(z0, docs, words, mask, fb_d, fb_w, fb_wt,
    keys) -> (theta [T,D,K], phi_wk [T,V,K], ll0 [T], ll_final [T]);
    `keys` is the uint32 [T, 2] lane-key array from stack_tenants."""
    body = _make_refit_body(cfg, n_docs=n_docs, n_vocab=n_vocab,
                            nwk_form=nwk_form, sampler_form=sampler_form,
                            sparse_active=sparse_active, sampler=sampler)

    def fleet(z0, docs, words, mask, fb_d, fb_w, fb_wt, keys):
        return jax.vmap(body)(z0, docs, words, mask, fb_d, fb_w, fb_wt,
                              keys)
    return jax.jit(fleet)


def make_tenant_refit(cfg: LDAConfig, *, n_docs: int, n_vocab: int,
                      nwk_form: str | None = None,
                      sampler_form: str | None = None,
                      sparse_active: int = 0,
                      sampler: str | None = None):
    """The SAME refit body without the tenant vmap — the sequential
    supervisor arm (one dispatch per tenant), and the per-lane parity
    reference the bench asserts against every run."""
    body = _make_refit_body(cfg, n_docs=n_docs, n_vocab=n_vocab,
                            nwk_form=nwk_form, sampler_form=sampler_form,
                            sparse_active=sparse_active, sampler=sampler)

    def one(z0, docs, words, mask, fb_d, fb_w, fb_wt, key):
        return body(z0, docs, words, mask, fb_d, fb_w, fb_wt, key)
    return jax.jit(one)


def unstack_results(sc: ShapeClass, theta, phi_wk, ll0, ll_final) -> dict:
    """Per-tenant host views of a class program's stacked outputs, pow2
    padding stripped back to each tenant's true (D, V)."""
    theta = np.asarray(theta, np.float32)
    phi_wk = np.asarray(phi_wk, np.float32)
    ll0 = np.asarray(ll0, np.float32)
    ll_final = np.asarray(ll_final, np.float32)
    out = {}
    for i, t in enumerate(sc.tenants):
        out[t.name] = {
            "theta": theta[i, :t.n_docs],
            "phi_wk": phi_wk[i, :t.n_vocab],
            "ll_initial": float(ll0[i]),
            "ll_final": float(ll_final[i]),
        }
    return out
