"""Post-LDA event scoring and suspicious-connects selection.

The reference's FlowPostLDA/DNSPostLDA/ProxyPostLDA Spark jobs broadcast
theta and phi to executors and score every raw event as
`score(event) = sum_k theta[ip,k] * phi[k,word]`, then filter `< TOL`,
sort ascending, and keep MAXRESULTS (SURVEY.md §2.1 #11, §3.1 hot loop
POST-LDA; reference README.md:42 "filter billion of events to a few
thousands"). Low probability under the topic model == suspicious.

onix renders this as a chunked `lax.scan` carrying a running bottom-M
set, so 1B events stream through a single compiled program with O(M)
memory — the throughput-critical path of the judged metric
"netflow events scored/sec/chip" (BASELINE.json).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


def score_events(theta: jax.Array, phi_wk: jax.Array,
                 doc_ids: jax.Array, word_ids: jax.Array) -> jax.Array:
    """p(word | doc) = sum_k theta[d,k] * phi_wk[w,k] — one gather-dot per
    event; K rides the VPU lanes.

    Multi-chain estimates (theta [C,D,K], phi_wk [C,V,K] from
    `LDAConfig.n_chains > 1`) combine the per-chain probabilities with a
    GEOMETRIC mean — score-averaging, not matrix-averaging, so topic
    label switching between chains cannot corrupt the estimate. Geometric
    beats arithmetic for rank stability of the suspicious tail (an event
    must be low under EVERY chain to stay in the bottom-k): measured
    top-1k ensemble-vs-ensemble overlap 0.959 vs 0.950 at C=8 on the
    100k-event flow rehearsal (docs/OVERLAP.md).
    """
    if theta.ndim == 2:
        # Upcast AFTER the gather: with bf16 tables-at-rest the gather
        # moves half the bytes and the dot still accumulates in f32
        # (free when the tables are already f32).
        return jnp.sum(theta[doc_ids].astype(jnp.float32)
                       * phi_wk[word_ids].astype(jnp.float32), axis=-1)
    p = jnp.sum(theta[:, doc_ids].astype(jnp.float32)
                * phi_wk[:, word_ids].astype(jnp.float32), axis=-1)
    return jnp.exp(jnp.log(jnp.maximum(p, 1e-38)).mean(axis=0))


class TopK(NamedTuple):
    scores: jax.Array   # float32 [M] ascending-suspicious (smallest first)
    indices: jax.Array  # int32 [M] global event index; -1 where fewer than
    #                     M events qualified (score is +inf there)


def _finalize_topk(scores: jax.Array, indices: jax.Array) -> TopK:
    order = jnp.argsort(scores)
    scores, indices = scores[order], indices[order]
    # Unfilled slots (fewer than max_results qualifying events) carry +inf
    # scores; force their indices to the -1 sentinel so a consumer can
    # never gather a real event row through a padding slot.
    indices = jnp.where(jnp.isfinite(scores), indices, -1)
    return TopK(scores=scores, indices=indices)


def _chunked_cols(arrays: tuple, n: int, chunk: int):
    """Pad arrays to a chunk multiple and reshape to [n_chunks, chunk]
    scan columns. Shapes are static under jit, so the pad amount is
    compile-time."""
    chunk = min(chunk, max(n, 1))
    pad = (-n) % chunk
    if pad:
        arrays = tuple(jnp.pad(a, (0, pad)) for a in arrays)
    n_chunks = (n + pad) // chunk
    cols = tuple(a.reshape(n_chunks, -1) for a in arrays)
    base = jnp.arange(chunk, dtype=jnp.int32)
    return cols, base, n_chunks, chunk


def _empty_topk(max_results: int) -> TopK:
    return TopK(scores=jnp.full((max_results,), jnp.inf, jnp.float32),
                indices=jnp.full((max_results,), -1, jnp.int32))


def _merge_bottom_k(best_s, best_i, s, idx, max_results: int):
    """Merge chunk scores into the running bottom-k. Ties keep the
    lower concat position, so incumbents always beat later arrivals at
    an equal score — every _scan_bottom_k entry point relies on this
    for determinism."""
    cat_s = jnp.concatenate([best_s, s])
    cat_i = jnp.concatenate([best_i, idx])
    neg, pos = jax.lax.top_k(-cat_s, max_results)
    return -neg, cat_i[pos]


def _scan_bottom_k(arrays: tuple, n: int, score_chunk, *,
                   max_results: int, chunk: int,
                   merge_buffer: int | None = None) -> TopK:
    """Shared running-bottom-k machinery: chunk the input arrays
    together, score each chunk with `score_chunk(*chunk_cols)` (which
    must already return +inf for rows it rejects), mask the tail pad by
    global index, and merge a running bottom-`max_results` through one
    `lax.scan`. Every selection entry point (bottom_k, top_suspicious,
    table_pair_bottom_k) is this scan plus a per-chunk score function —
    a fix to the selection logic lands in exactly one place.

    `merge_buffer=B` turns on the two-phase merge: count the chunk's
    candidates (scores below the running k-th best); when they fit in
    B, merge only the chunk's bottom-B instead of concatenating the
    whole chunk into top_k. Once the threshold tightens (a few chunks
    in), expected candidates per chunk fall toward k/chunks_seen, so
    the steady-state merge is O(k+B), not O(k+chunk). EXACT either way:
    count > B falls back to the full merge inside the same lax.cond —
    never a lossy cap (PERF.md lever 4)."""
    if n == 0:     # static shape: resolved at trace time, not per-call
        return _empty_topk(max_results)
    cols, base, n_chunks, chunk = _chunked_cols(arrays, n, chunk)

    def step(carry, xs):
        best_s, best_i = carry
        *cs, ci = xs
        idx = ci * chunk + base
        s = jnp.where(idx < n, score_chunk(*cs), jnp.inf)
        if merge_buffer is None or merge_buffer >= chunk:
            return _merge_bottom_k(best_s, best_i, s, idx, max_results), None

        def small_merge():
            # All candidates fit the buffer: the chunk's bottom-B is a
            # superset of them (anything outside is >= the threshold
            # and loses to an incumbent at the final top_k's tie rule).
            neg, pos = jax.lax.top_k(-s, merge_buffer)
            return _merge_bottom_k(best_s, best_i, -neg, idx[pos],
                                   max_results)

        n_cand = jnp.sum(s < best_s[-1])    # running k-th best
        return jax.lax.cond(
            n_cand <= merge_buffer, small_merge,
            lambda: _merge_bottom_k(best_s, best_i, s, idx, max_results)), \
            None

    (out_s, out_i), _ = jax.lax.scan(
        step, tuple(_empty_topk(max_results)),
        (*cols, jnp.arange(n_chunks, dtype=jnp.int32)))
    return _finalize_topk(out_s, out_i)


@functools.partial(jax.jit,
                   static_argnames=("max_results", "chunk", "merge_buffer"))
def bottom_k(
    scores: jax.Array,        # float32 [N] precomputed event scores
    *,
    tol: float,
    max_results: int,
    chunk: int = 1 << 20,
    merge_buffer: int | None = None,
) -> TopK:
    """Bottom-`max_results` among precomputed scores < tol — the selection
    half of `top_suspicious` for callers that aggregate scores before
    selecting (e.g. flow events take the min over src/dst-doc tokens)."""
    return _scan_bottom_k(
        (scores,), scores.shape[0],
        lambda sc: jnp.where(sc < tol, sc, jnp.inf),
        max_results=max_results, chunk=chunk, merge_buffer=merge_buffer)


@functools.partial(jax.jit, static_argnames=("max_results", "chunk",
                                             "merge_buffer", "table_dtype"))
def top_suspicious(
    theta: jax.Array,
    phi_wk: jax.Array,
    doc_ids: jax.Array,       # int32 [N]
    word_ids: jax.Array,      # int32 [N]
    mask: jax.Array,          # float32 [N] 0.0 for padding
    *,
    tol: float,
    max_results: int,
    chunk: int = 1 << 20,
    merge_buffer: int | None = None,
    table_dtype: str | None = None,
) -> TopK:
    """Bottom-`max_results` events by score among those with score < tol.

    N is padded internally to a chunk multiple (shapes are static under
    jit, so the pad amount is compile-time). Padding and above-threshold
    events are pushed to +inf so they never enter the result set. Single
    fused scan — no host round-trips.

    The chunk's scores are computed through an inner scan over 1/8-chunk
    slices: with top_k as the gather-dot's direct consumer XLA
    materializes both gathered [chunk, K] operands in lane-padded
    [chunk, 128] layout (~6.4x traffic); the inner scan gives the
    gather-dot a cheap [sub] consumer so it fuses, and only [chunk]
    f32 scores reach top_k (docs/PERF.md).

    A branch-and-bound variant (prune events whose score lower bound
    `θmax[d]·φ[w, argmax θ[d]]` beats the running k-th best) was built,
    proven exact, and REJECTED on measurement: the single-coordinate
    bound underestimates the score so badly that 11-61% of events stay
    candidates in every regime tried — diffuse tables, peaked tables,
    even model-generated (fitted-telemetry-like) events — so the scan
    always fell back to exhaustive scoring plus bound overhead (2.8x
    slower on chip). docs/PERF.md "round-2 selection experiments" has
    the full table; don't rebuild it without a fundamentally tighter
    bound.

    `merge_buffer` enables the exact two-phase merge (_scan_bottom_k);
    `table_dtype="bfloat16"` stores the gathered tables at half width
    (measured 1.52x on the materialization-bound r2 form — scores then
    round at bf16 precision, so keep it off where the 0.95 overlap bar
    is being judged unless the overlap study revalidates it).
    """
    if table_dtype is not None:
        theta = theta.astype(table_dtype)
        phi_wk = phi_wk.astype(table_dtype)

    def score_chunk(dc, wc, mc):
        s = _subscan_scores(theta, phi_wk, dc, wc)
        return jnp.where((mc > 0) & (s < tol), s, jnp.inf)

    return _scan_bottom_k((doc_ids, word_ids, mask), doc_ids.shape[0],
                          score_chunk, max_results=max_results, chunk=chunk,
                          merge_buffer=merge_buffer)


def _subscan_scores(theta, phi_wk, dc, wc):
    """score_events over a chunk via an inner scan of 1/8-chunk slices
    — the fusion-isolating form shared by every full-scoring chunk
    (docs/PERF.md "keep top_k away from the gather-dot")."""
    sub = max(dc.shape[0] // 8, 1)
    if dc.shape[0] % sub:
        return score_events(theta, phi_wk, dc, wc)
    ns = dc.shape[0] // sub

    def sub_step(_, xs):
        sd, sw = xs
        return None, score_events(theta, phi_wk, sd, sw)

    _, s = jax.lax.scan(sub_step, None,
                        (dc.reshape(ns, sub), wc.reshape(ns, sub)))
    return s.reshape(dc.shape[0])


_score_events_jit = jax.jit(score_events)


@jax.jit
def score_table(theta: jax.Array, phi_wk: jax.Array) -> jax.Array:
    """The full [D, V] score matrix θ·φᵀ as ONE matmul.

    Product vocabularies are small by construction (packed words, coarse
    bins — V is hundreds to a few thousand), so D×V usually fits HBM
    comfortably; a single MXU matmul replaces per-event gather-dot pairs
    and per-event scoring degrades to a flat 4-byte gather (docs/PERF.md:
    the gather runs ~250 GB/s while the gathered-operand dot wastes
    108/128 lanes). Multi-chain inputs combine with the geometric mean,
    matching score_events."""
    if theta.ndim == 2:
        return theta @ phi_wk.T
    per_chain = jnp.einsum("cdk,cvk->cdv", theta, phi_wk)
    return jnp.exp(jnp.log(jnp.maximum(per_chain, 1e-38)).mean(axis=0))


@jax.jit
def _gather_scores(table_flat: jax.Array, d: jax.Array, w: jax.Array,
                   n_vocab: int) -> jax.Array:
    # int32 flat index is safe: the table is capped at TABLE_MAX_ELEMS
    # (1<<27) elements, far under int32 range.
    return table_flat[d.astype(jnp.int32) * jnp.int32(n_vocab) + w]


# D*V budget for materializing the score table (f32 elements). 1<<27 =
# 512 MB — small next to 16 GB HBM, large enough for D=200k x V=640.
TABLE_MAX_ELEMS = 1 << 27

@functools.partial(jax.jit,
                   static_argnames=("max_results", "chunk", "merge_buffer"))
def table_pair_bottom_k(
    table_flat: jax.Array,   # float32 [D*V] from score_table().ravel()
    idx_src: jax.Array,      # int32 [N] flat index d_src*V + w per event
    idx_dst: jax.Array,      # int32 [N] flat index d_dst*V + w per event
    *,
    tol: float,
    max_results: int,
    chunk: int = 1 << 21,
    merge_buffer: int | None = None,
) -> TopK:
    """Fused flow-event scoring + selection, entirely on device: per
    event, score = min over its two tokens (src-doc and dst-doc gather
    from the θ·φᵀ table), filter < tol, keep the running bottom-k.

    Exists for the 10⁸⁺-event path: the unfused pipeline ships every
    token score to the host (hundreds of MB through the device tunnel),
    takes the pair-min there, and ships event scores back for selection.
    Here only the final [max_results] rows ever leave the device."""

    def score_chunk(si, di):
        s = jnp.minimum(table_flat[si], table_flat[di])
        return jnp.where(s < tol, s, jnp.inf)

    return _scan_bottom_k((idx_src, idx_dst), idx_src.shape[0],
                          score_chunk, max_results=max_results, chunk=chunk,
                          merge_buffer=merge_buffer)


@functools.partial(jax.jit,
                   static_argnames=("max_results", "chunk", "merge_buffer"))
def table_bottom_k(
    table_flat: jax.Array,   # float32 [D*V] from score_table().ravel()
    idx: jax.Array,          # int32 [N] flat index d*V + w per event
    *,
    tol: float,
    max_results: int,
    chunk: int = 1 << 21,
    merge_buffer: int | None = None,
) -> TopK:
    """Fused single-token scoring + selection, entirely on device: the
    dns/proxy analog of `table_pair_bottom_k` (one document — the
    client IP — per event, so score = one flat table gather). Only the
    final [max_results] rows leave the device on the 10⁸⁺-event path."""

    def score_chunk(ii):
        s = table_flat[ii]
        return jnp.where(s < tol, s, jnp.inf)

    return _scan_bottom_k((idx,), idx.shape[0], score_chunk,
                          max_results=max_results, chunk=chunk,
                          merge_buffer=merge_buffer)


# ---------------------------------------------------------------------------
# bf16-screened exact selection
#
# bf16 tables-at-rest halve the gather traffic of the selection scan (the
# measured-fastest form on chip), but raw bf16 scores round at 2^-8 and can
# flip the top-k set near the boundary — the bench's per-run identity gate
# then rejects the speed. The screened variants below keep the bf16 scan as
# a SCREEN only: they retain an oversized candidate buffer by bf16 score,
# rescore just those candidates with the f32 tables, and certify exactness
# on device from the rounding bound.
#
# Soundness argument. Inputs are f32 probabilities in [0,1] rounded once to
# bf16 (8 significand bits incl. the implicit one, unit roundoff u = 2^-8):
# each factor carries relative error <= u/(1+u) < 2^-8, a product of two
# <= (1+2^-8)^2 - 1 < 2^-6.99, and a nonnegative K-term sum accumulated in
# f32 preserves the relative bound while adding < K*2^-23 of its own. So
# for every event
#   bf16_s in [f32_s/(1+REL), f32_s*(1+REL)]   with REL = 2^-6,
# which leaves ~2x headroom over the 2^-6.99 product bound and absorbs the
# f32-accumulation term for any plausible K (equality would need
# K*2^-23 > 2^-6 - 2^-6.99, i.e. K > ~60k topics).
# Let B_max be the WORST bf16 score retained in the candidate buffer and
# s_k the k-th-best f32 score after rescoring. Any excluded event has
# bf16_s >= B_max; if B_max > s_k*(1+REL) then its f32 score is
#   f32_s >= bf16_s/(1+REL) >= B_max/(1+REL) > s_k
# — strictly worse than the k-th result, so the exclusion was safe (and
# strictness rules out boundary ties with excluded events). If the buffer
# never filled, every event passing the inflated tol screen is IN it, which
# covers every event with f32_s < tol outright. Either condition => the
# returned top-k equals the full-f32 scan's, including its
# lower-global-index tie rule (candidates are ordered by (score, index),
# which is the rule _merge_bottom_k + _finalize_topk implement). When
# neither holds the `sound` flag is False and the caller must fall back to
# the f32 path — never silently accept the screened result.
#
# Identity strength differs by variant. The table_* screened variants
# rescore by gathering the SAME f32 table the exact scan gathers — scores
# are bit-identical by construction, so sound=True certifies a
# bit-identical result. top_suspicious_screened's rescore recomputes the
# gather-dot in a separately compiled XLA program, and separately compiled
# programs can differ in the dot's last ulp (the same caveat bench.py
# records for its variant pair); sound=True there certifies the result up
# to last-ulp ties at the k-th boundary, and the bench additionally gates
# on per-run set identity before headlining it.
# ---------------------------------------------------------------------------

_SCREEN_REL = 2.0 ** -6


class ScreenedTopK(NamedTuple):
    result: TopK
    sound: jax.Array    # bool [] — True: provably identical to the f32 scan


def _screened_scan(arrays: tuple, n: int, screen_chunk, rescore, *,
                   tol: float, max_results: int, chunk: int,
                   merge_buffer: int | None,
                   buffer_mult: int) -> ScreenedTopK:
    """Screen with bf16 chunk scores into a bottom-(k*buffer_mult) buffer,
    rescore the buffer in f32, and prove exactness (see block comment).

    `screen_chunk(*cols)` returns bf16-rounded scores with mask/tol-screen
    rejects already at +inf (the screen tol must be tol*(1+2*REL) — the
    inflation keeps every f32-qualifying event eligible); `rescore(gidx)`
    returns f32 scores for global event indices, bit-matching the f32
    path's scoring of the same events."""
    if n == 0:
        return ScreenedTopK(_empty_topk(max_results), jnp.asarray(True))
    n_buffer = max_results * buffer_mult
    screen = _scan_bottom_k(arrays, n, screen_chunk,
                            max_results=n_buffer, chunk=chunk,
                            merge_buffer=merge_buffer)
    s32 = rescore(screen.indices)
    s32 = jnp.where((screen.indices >= 0) & (s32 < tol), s32, jnp.inf)
    # (score, global index) ascending == the f32 scan's deterministic
    # order: merges keep the lower concat position at equal scores and
    # the final stable argsort preserves it.
    order = jnp.lexsort((screen.indices, s32))
    s_fin = s32[order][:max_results]
    i_fin = jnp.where(jnp.isfinite(s_fin), screen.indices[order][:max_results],
                      -1)
    buffer_full = jnp.isfinite(screen.scores[-1])
    s_k = s_fin[-1]
    margin_ok = jnp.isfinite(s_k) & (
        screen.scores[-1] > s_k * (1.0 + _SCREEN_REL))
    return ScreenedTopK(TopK(s_fin, i_fin), ~buffer_full | margin_ok)


@functools.partial(jax.jit, static_argnames=("max_results", "chunk",
                                             "merge_buffer", "buffer_mult"))
def top_suspicious_screened(
    theta: jax.Array,         # float32 [D,K] (single-estimate tables only)
    phi_wk: jax.Array,        # float32 [V,K]
    doc_ids: jax.Array,       # int32 [N]
    word_ids: jax.Array,      # int32 [N]
    mask: jax.Array,          # float32 [N] 0.0 for padding
    *,
    tol: float,
    max_results: int,
    chunk: int = 1 << 20,
    merge_buffer: int | None = 128,
    buffer_mult: int = 4,
) -> ScreenedTopK:
    """`top_suspicious` at bf16-scan speed with an f32-rescored result:
    bf16 gathers drive the selection scan, the f32 tables rescore only
    the ~max_results*buffer_mult survivors. `result` is valid only when
    `sound` is True — otherwise rerun the f32 `top_suspicious` (the
    screen cannot prove it kept every true bottom-k member). sound=True
    certifies identity with the f32 scan up to last-ulp boundary ties
    (the rescore is a separately compiled dot — module block comment);
    the table_* variants below carry the strictly bit-identical claim."""
    if theta.ndim != 2:
        raise ValueError("screened selection covers single-estimate "
                         "tables; combine chains upstream")
    theta_b = theta.astype(jnp.bfloat16)
    phi_b = phi_wk.astype(jnp.bfloat16)
    tol_screen = tol * (1.0 + 2.0 * _SCREEN_REL)

    def screen_chunk(dc, wc, mc):
        s = _subscan_scores(theta_b, phi_b, dc, wc)
        return jnp.where((mc > 0) & (s < tol_screen), s, jnp.inf)

    def rescore(gidx):
        safe = jnp.maximum(gidx, 0)
        return score_events(theta, phi_wk, doc_ids[safe], word_ids[safe])

    return _screened_scan((doc_ids, word_ids, mask), doc_ids.shape[0],
                          screen_chunk, rescore, tol=tol,
                          max_results=max_results, chunk=chunk,
                          merge_buffer=merge_buffer, buffer_mult=buffer_mult)


@functools.partial(jax.jit, static_argnames=("max_results", "chunk",
                                             "merge_buffer", "buffer_mult"))
def table_bottom_k_screened(
    table_flat: jax.Array,   # float32 [D*V] from score_table().ravel()
    idx: jax.Array,          # int32 [N] flat index d*V + w per event
    table_bf16: jax.Array | None = None,   # optional precomputed bf16 copy
    *,
    tol: float,
    max_results: int,
    chunk: int = 1 << 21,
    merge_buffer: int | None = 128,
    buffer_mult: int = 4,
) -> ScreenedTopK:
    """`table_bottom_k` with a bf16 screen: the scan gathers a bf16 copy
    of the score table (half the bytes of the bandwidth-bound gather),
    f32 rescoring covers only the candidate buffer. Batch-loop callers
    should build `table_bf16 = table_flat.astype(jnp.bfloat16)` ONCE and
    pass it in — converting inside is a full extra pass over the table
    per call."""
    table_b = (table_flat.astype(jnp.bfloat16) if table_bf16 is None
               else table_bf16)
    tol_screen = tol * (1.0 + 2.0 * _SCREEN_REL)

    def screen_chunk(ii):
        s = table_b[ii].astype(jnp.float32)
        return jnp.where(s < tol_screen, s, jnp.inf)

    def rescore(gidx):
        return table_flat[idx[jnp.maximum(gidx, 0)]]

    return _screened_scan((idx,), idx.shape[0], screen_chunk, rescore,
                          tol=tol, max_results=max_results, chunk=chunk,
                          merge_buffer=merge_buffer, buffer_mult=buffer_mult)


@functools.partial(jax.jit, static_argnames=("max_results", "chunk",
                                             "merge_buffer", "buffer_mult"))
def table_pair_bottom_k_screened(
    table_flat: jax.Array,   # float32 [D*V] from score_table().ravel()
    idx_src: jax.Array,      # int32 [N]
    idx_dst: jax.Array,      # int32 [N]
    table_bf16: jax.Array | None = None,   # optional precomputed bf16 copy
    *,
    tol: float,
    max_results: int,
    chunk: int = 1 << 21,
    merge_buffer: int | None = 128,
    buffer_mult: int = 4,
) -> ScreenedTopK:
    """`table_pair_bottom_k` with a bf16 screen. min() of two
    once-rounded values stays within the same relative bound as a single
    rounded value, so the shared REL covers the pair-min too. See
    `table_bottom_k_screened` on precomputing `table_bf16`."""
    table_b = (table_flat.astype(jnp.bfloat16) if table_bf16 is None
               else table_bf16)
    tol_screen = tol * (1.0 + 2.0 * _SCREEN_REL)

    def screen_chunk(si, di):
        s = jnp.minimum(table_b[si], table_b[di]).astype(jnp.float32)
        return jnp.where(s < tol_screen, s, jnp.inf)

    def rescore(gidx):
        safe = jnp.maximum(gidx, 0)
        return jnp.minimum(table_flat[idx_src[safe]],
                           table_flat[idx_dst[safe]])

    return _screened_scan((idx_src, idx_dst), idx_src.shape[0],
                          screen_chunk, rescore, tol=tol,
                          max_results=max_results, chunk=chunk,
                          merge_buffer=merge_buffer, buffer_mult=buffer_mult)


def _screened_enabled() -> bool:
    # Platform default, env-overridable. On TPU the screened scan is
    # the measured-fastest certified form
    # (docs/BENCH_r03_builder_screened.json: 132.2M ev/s vs 118.6M
    # exact on the same run, sound + set-identical); everywhere else —
    # CPU (no gather-bandwidth win) and unmeasured accelerators (an
    # uncertifiable screen would pay BOTH scans via the fallback) — the
    # f32 scan stays the default. Any env value other than "1"
    # disables, so legacy spellings like "0"/"false"/"off" all mean
    # off; unset means the platform default.
    import os
    env = os.environ.get("ONIX_SCREENED_SELECT")
    if env is not None:
        return env == "1"
    return jax.default_backend() == "tpu"


def table_bottom_k_fast(table_flat, idx, table_bf16=None, *, tol: float,
                        max_results: int, serve_form: str = "auto") -> TopK:
    """Drop-in `table_bottom_k`: the r15 fused one-kernel arm when the
    serve gate resolves to it (pallas_serve.select_serve_form —
    `serve_form` lets config-bearing callers pass
    serving.serve_form; "auto" resolves to "xla" on every backend
    until a measured crossover lands, ONIX_SERVE_FORM overrides), else
    the bf16-screened scan when enabled (_screened_enabled: default on
    TPU, ONIX_SCREENED_SELECT overrides), falling back to the f32 scan
    whenever the device-side proof does not certify; plain f32 scan
    otherwise."""
    from onix.models import pallas_serve
    if pallas_serve.select_serve_form(serve_form,
                                      idx.shape[0]) == "fused":
        return pallas_serve.fused_table_bottom_k(
            table_flat, idx, tol=tol, max_results=max_results)
    if _screened_enabled():
        scr = table_bottom_k_screened(table_flat, idx, table_bf16,
                                      tol=tol, max_results=max_results)
        if bool(scr.sound):
            return scr.result
    return table_bottom_k(table_flat, idx, tol=tol,
                          max_results=max_results)


def table_pair_bottom_k_fast(table_flat, idx_src, idx_dst, table_bf16=None,
                             *, tol: float, max_results: int,
                             serve_form: str = "auto") -> TopK:
    """Drop-in `table_pair_bottom_k` with the same serve-gate +
    screened/fallback policy (and platform default) as
    `table_bottom_k_fast`."""
    from onix.models import pallas_serve
    if pallas_serve.select_serve_form(
            serve_form, idx_src.shape[0]) == "fused":
        return pallas_serve.fused_table_pair_bottom_k(
            table_flat, idx_src, idx_dst, tol=tol,
            max_results=max_results)
    if _screened_enabled():
        scr = table_pair_bottom_k_screened(table_flat, idx_src, idx_dst,
                                           table_bf16, tol=tol,
                                           max_results=max_results)
        if bool(scr.sound):
            return scr.result
    return table_pair_bottom_k(table_flat, idx_src, idx_dst, tol=tol,
                               max_results=max_results)


# Dedup pays once the device scan shrinks enough to cover the host-side
# np.unique sort; real telemetry is Zipf over (ip, word) pairs, so the
# unique-pair count is typically a small fraction of the event count
# (docs/PERF.md lever #1). Uniform-random data dedups to ~nothing and
# takes the direct path.
_DEDUP_THRESHOLD = 0.7


def score_all(theta, phi_wk, doc_ids, word_ids, chunk: int = 1 << 22,
              dedup: bool = True) -> np.ndarray:
    """Score every event, chunked on host to bound device memory.

    Strategy selection:
    1. D×V small (the product regime): materialize θ·φᵀ once on the MXU
       and score each event with a flat gather.
    2. Otherwise, with `dedup`, duplicate (doc, word) pairs are scored
       once on device and broadcast back through the inverse index —
       same scores bit-for-bit (scoring is a pure function of the pair).
    3. Fallback: chunked gather-dot scan.
    """
    doc_ids = np.asarray(doc_ids)
    word_ids = np.asarray(word_ids)
    n = doc_ids.shape[0]
    theta_a = np.asarray(theta)
    n_docs = int(theta_a.shape[-2])
    n_vocab = int(np.asarray(phi_wk).shape[-2])
    chains = theta_a.shape[0] if theta_a.ndim == 3 else 1
    # Table strategy gates: (a) the [C,D,V] build (plus its log/exp
    # temporaries on the chain path) must respect the memory budget;
    # (b) the D*V*4B of table traffic must amortize over the events
    # (each event replaces ~2K*8B of gathered-operand traffic, so the
    # break-even is D*V ≈ 40n; 32 keeps margin). Small batches — the
    # streaming scorer — fall through to the gather-dot/dedup paths.
    if (n and chains * n_docs * n_vocab <= TABLE_MAX_ELEMS
            and n_docs * n_vocab <= 32 * n):
        table = score_table(jnp.asarray(theta), jnp.asarray(phi_wk)).ravel()
        out = np.empty(n, np.float32)
        for lo in range(0, n, chunk):
            hi = min(lo + chunk, n)
            out[lo:hi] = np.asarray(_gather_scores(
                table, jnp.asarray(doc_ids[lo:hi]),
                jnp.asarray(word_ids[lo:hi]), n_vocab))
        return out
    if dedup and n:
        from onix.utils.arrays import unique_inverse
        key = doc_ids.astype(np.int64) * n_vocab + word_ids
        # Chunked unique-merge + searchsorted inverse — same output as
        # np.unique(return_inverse=True), ~4x faster at 10^8 keys
        # (cache-sized sorts; the cardinality is tiny vs the array).
        uniq, inv = unique_inverse(key)
        if uniq.shape[0] <= _DEDUP_THRESHOLD * n:
            pair_scores = score_all(
                theta, phi_wk, (uniq // n_vocab).astype(doc_ids.dtype),
                (uniq % n_vocab).astype(word_ids.dtype), chunk=chunk,
                dedup=False)
            return pair_scores[inv]
    out = np.empty(n, np.float32)
    for lo in range(0, n, chunk):
        hi = min(lo + chunk, n)
        out[lo:hi] = np.asarray(_score_events_jit(theta, phi_wk,
                                                  jnp.asarray(doc_ids[lo:hi]),
                                                  jnp.asarray(word_ids[lo:hi])))
    return out


def select_suspicious(scores: np.ndarray, tol: float,
                      max_results: int) -> np.ndarray:
    """Host-side suspicious selection: indices of events with score <
    tol, ascending by score, capped at max_results — the POST-LDA
    filter/sort/take contract (SURVEY.md §3.1) shared by the batch run
    and the benches."""
    cand = np.flatnonzero(scores < tol)
    if cand.size > max_results:
        part = np.argpartition(scores[cand], max_results - 1)
        cand = cand[part[:max_results]]
    return cand[np.argsort(scores[cand], kind="stable")]


def doc_rarity(theta: jax.Array, doc_weights: jax.Array) -> jax.Array:
    """Per-DOCUMENT suspiciousness: expected log corpus-popularity of
    the document's topics. Returns float32 [D], LOW = suspicious.

    Event scoring ranks words by rarity, which fades exactly when an
    attack is sustained: a campaign of hundreds of near-identical
    events accumulates word count (and, with enough mass, its own
    topic) until its events stop being individually rare — measured on
    the independent session generator, where 300-event tunnel/exfil
    campaigns score ~0 event recall while 15-event ones score 1.0
    (docs/RECALL_r05_sessions*.json). The campaign's signature is at
    the DOCUMENT level instead: its client concentrates token mass on
    a topic almost no other document uses.

        share_k = sum_d n_d * theta[d, k] / sum_d n_d   (corpus topic mass)
        score_d = sum_k theta[d, k] * log(share_k)

    A document riding globally-popular topics scores near the
    corpus-entropy baseline; a document whose mixture sits on a
    globally-rare topic scores far below it. One [D,K] contraction +
    one [D,K]@[K] matvec — MXU change, host round-trip only for the
    [D] result. Chained estimates ([C, D, K]) average the per-chain
    scores (arithmetic: log-space values, same label-switching
    robustness argument as score_events' geometric mean in p-space).
    """
    theta = jnp.asarray(theta)
    w = jnp.asarray(doc_weights, jnp.float32)

    def one(th):
        th = th.astype(jnp.float32)
        mass = w @ th                       # [K] token mass per topic
        share = mass / jnp.maximum(mass.sum(), 1e-30)
        return th @ jnp.log(jnp.maximum(share, 1e-30))

    if theta.ndim == 2:
        return one(theta)
    return jnp.mean(jax.vmap(one)(theta), axis=0)
