"""Batched collapsed-Gibbs LDA in pure JAX — the TPU replacement for
oni-lda-c (reference README.md:84, .gitmodules absent; SURVEY.md §2.1 #10).

The reference engine is a C/MPI program: documents sharded across ranks,
a sequential per-token sampler per rank, topic-word sufficient statistics
MPI-reduced each iteration. A token-sequential sampler cannot use a TPU,
so onix uses the standard SIMD compromise (SURVEY.md §7.3.1, PAPERS.md
"Sparse Partially Collapsed MCMC"): tokens are sampled in blocks of
`block_size`; within a block every token sees counts that exclude its own
assignment but are stale w.r.t. its block-mates; counts are exactly
updated between blocks via scatter-add. As block_size → 1 this is exact
collapsed Gibbs; at practical sizes the stationary distribution is close
enough that topic recovery and the top-k overlap metric survive (tested
in tests/test_gibbs.py).

Shapes: K topics, V vocabulary, D documents, N tokens.
State counts: n_dk [D,K], n_wk [V,K], n_k [K] (int32, exact — deltas are
scattered as int32, never round-tripped through float32, so counts stay
exact past 2^24 at the billion-event scale of README.md:42).
Padding tokens carry the sentinel assignment z == K: `jax.nn.one_hot`
maps out-of-range indices to all-zero rows, so padding contributes
nothing to any count without a mask multiply.
A sweep is `lax.scan` over N/block_size blocks — one fused XLA program,
no host round-trips, no Python control flow inside jit.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from onix.config import LDAConfig
from onix.corpus import Corpus


class GibbsState(NamedTuple):
    z: jax.Array          # int32 [n_blocks, B] topic per token (K = padding)
    n_dk: jax.Array       # int32 [D, K] doc-topic counts
    n_wk: jax.Array       # int32 [V, K] word-topic counts
    n_k: jax.Array        # int32 [K]    topic totals
    key: jax.Array        # PRNG key
    # Posterior-mean accumulators (populated after burn-in; improves the
    # rank stability needed for the judged top-k overlap, SURVEY.md §7.3.2).
    acc_ndk: jax.Array    # float32 [D, K]
    acc_nwk: jax.Array    # float32 [V, K]
    n_acc: jax.Array      # int32 [] number of accumulated sweeps


def _one_hot(z: jax.Array, k: int) -> jax.Array:
    """int32 one-hot; out-of-range z (the padding sentinel K) -> zero row."""
    return jax.nn.one_hot(z, k, dtype=jnp.int32)


def init_state_keyed(
    key: jax.Array,
    doc_blocks: jax.Array,
    word_blocks: jax.Array,
    mask_blocks: jax.Array,
    n_docs: int,
    n_vocab: int,
    n_topics: int,
) -> GibbsState:
    """Random topic init + exact count build, blockwise.

    Counts are scattered one token block at a time under `lax.scan`: a
    flat one-hot over the whole corpus would materialize an
    [N, K]-padded temp that OOMs HBM past ~10M tokens (hit at 40M)."""
    key, zkey = jax.random.split(key)
    shape = doc_blocks.shape
    z = jax.random.randint(zkey, shape, 0, n_topics, dtype=jnp.int32)
    z = jnp.where(mask_blocks > 0, z, n_topics)   # sentinel for padding

    def count_block(carry, xs):
        n_dk, n_wk, n_k = carry
        d, w, zb = xs
        oh = _one_hot(zb, n_topics)               # [B, K]; padding -> 0
        return (n_dk.at[d].add(oh), n_wk.at[w].add(oh),
                n_k + oh.sum(axis=0, dtype=jnp.int32)), None

    (n_dk, n_wk, n_k), _ = jax.lax.scan(
        count_block,
        (jnp.zeros((n_docs, n_topics), jnp.int32),
         jnp.zeros((n_vocab, n_topics), jnp.int32),
         jnp.zeros((n_topics,), jnp.int32)),
        (doc_blocks, word_blocks, z))
    return GibbsState(
        z=z, n_dk=n_dk, n_wk=n_wk, n_k=n_k, key=key,
        acc_ndk=jnp.zeros((n_docs, n_topics), jnp.float32),
        acc_nwk=jnp.zeros((n_vocab, n_topics), jnp.float32),
        n_acc=jnp.zeros((), jnp.int32),
    )


def init_state(
    doc_blocks: jax.Array,
    word_blocks: jax.Array,
    mask_blocks: jax.Array,
    n_docs: int,
    n_vocab: int,
    n_topics: int,
    seed: int,
) -> GibbsState:
    return init_state_keyed(jax.random.PRNGKey(seed), doc_blocks,
                            word_blocks, mask_blocks, n_docs, n_vocab,
                            n_topics)


def init_chains(
    doc_blocks: jax.Array,
    word_blocks: jax.Array,
    mask_blocks: jax.Array,
    n_docs: int,
    n_vocab: int,
    n_topics: int,
    seed: int,
    n_chains: int,
) -> GibbsState:
    """Stacked state for `n_chains` independent chains (leading chain
    axis on every array). Chains differ only in their PRNG streams; on
    TPU vmap turns the per-chain gathers/scatters into one batched
    program, so C chains cost ~one sweep of C× the tokens."""
    keys = jax.vmap(jax.random.fold_in, in_axes=(None, 0))(
        jax.random.PRNGKey(seed), jnp.arange(n_chains, dtype=jnp.uint32))
    return jax.vmap(
        lambda k: init_state_keyed(k, doc_blocks, word_blocks, mask_blocks,
                                   n_docs, n_vocab, n_topics))(keys)


# Table width up to which the n_wk delta goes through an MXU one-hot
# matmul instead of a scatter-add on TPU. Rationale: the sweep is
# scatter-bound (docs/PERF.md), and with product vocabularies (V in the
# hundreds) the n_wk scatter is COLLISION-dense — a 2^17-token block
# lands ~B/V ~ 250 colliding row-updates per word. The matmul form
# computes the same [V, K] delta as onehot(w)^T @ delta on the MXU:
# B*V*K MACs (~1.4e9 at the cap — microseconds) plus one [B, V] bf16
# one-hot materialization, with NO serialized collisions. Exact by
# construction: operands are {-1, 0, 1} (exact in bf16), accumulation
# is f32, and each output magnitude is <= B = 2^17 << 2^24. The n_dk
# scatter keeps its scatter form — documents are nearly collision-free
# within a block and D is far too large to one-hot.
_NWK_MATMUL_MAX_V = 4096
# Auto-enable also bounds the [B, V] one-hot temporary (bf16 elements):
# 2^27 = 256 MB. A block_size 2^17 sweep at V=4096 would otherwise grow
# a 1 GiB temporary (x n_chains under the vmap engine) that the scatter
# form never allocated — an OOM regression, not a speedup. Forcing
# nwk_matmul=True bypasses the bound for experiments.
_NWK_MATMUL_MAX_ELEMS = 1 << 27


def make_block_step(*, alpha: float, eta: float, n_vocab: int,
                    k_topics: int, nwk_matmul: bool | None = None):
    """The collapsed-Gibbs block sampler shared by the single-device and
    sharded engines — one definition so the documented dp=1 equivalence
    can never silently diverge.

    carry = (n_dk, n_wk, n_k, key); xs = (docs, words, mask, z_old).

    `nwk_matmul`: force the n_wk-delta form (True = one-hot matmul,
    False = scatter-add); None picks at trace time — matmul on
    accelerator backends when the n_wk table width is at most
    _NWK_MATMUL_MAX_V (ONIX_NWK_MATMUL=0/1 overrides for experiments).
    Both forms produce bit-identical int32 counts.
    """
    v_eta = n_vocab * eta
    # Sampler form is picked once at trace time; it is a platform
    # property, not runtime state, so the traced program is static.
    use_gumbel = jax.default_backend() not in ("cpu",)
    if nwk_matmul is None:
        import os
        env = os.environ.get("ONIX_NWK_MATMUL")
        if env in ("0", "1"):
            nwk_matmul = env == "1"

    def block_step(carry, xs):
        n_dk, n_wk, n_k, key = carry
        d, w, m, z_old = xs
        key, skey = jax.random.split(key)
        oh_old = _one_hot(z_old, k_topics)          # zero row for padding
        ohf = oh_old.astype(jnp.float32)
        # Counts excluding each token's own current assignment.
        ndk = n_dk[d].astype(jnp.float32) - ohf
        nwk = n_wk[w].astype(jnp.float32) - ohf
        nk = n_k.astype(jnp.float32)[None, :] - ohf
        # Categorical sampling — two statistically identical forms,
        # chosen per backend at trace time (docs/PERF.md "exponential
        # race", measured both ways on both platforms):
        #   * CPU: exponential race z = argmax p_k/e_k, e~Exp(1) — the
        #     Gumbel-argmax trick in LINEAR space at one log per
        #     element instead of four; measured 1.75x faster (the
        #     transcendentals dominate on CPU). Per-element products
        #     keep full relative precision — no cumsum, so no
        #     rare-topic rounding (why inverse-CDF was rejected: a
        #     linear f32 cumsum makes transitions to topics below
        #     ~2^-24 of the total exactly impossible).
        #   * TPU: classic log-space Gumbel-argmax — the sweep is
        #     scatter-bound there so extra transcendentals are free,
        #     and log space measured ~5% faster (37.5 vs 35.8 Mtok/s,
        #     scripts/exp_gibbs_sweep.py on v5lite).
        if use_gumbel:
            logp = (jnp.log(ndk + alpha)
                    + jnp.log(jnp.maximum(nwk + eta, 1e-10))
                    - jnp.log(nk + v_eta))
            g = jax.random.gumbel(skey, logp.shape, dtype=jnp.float32)
            z_new = jnp.argmax(logp + g, axis=-1).astype(jnp.int32)
        else:
            p = ((ndk + alpha) * jnp.maximum(nwk + eta, 1e-10)
                 / (nk + v_eta))
            u = jax.random.uniform(skey, p.shape, dtype=jnp.float32,
                                   minval=1e-38)
            z_new = jnp.argmax(p / -jnp.log(u), axis=-1).astype(jnp.int32)
        z_new = jnp.where(m > 0, z_new, z_old)      # padding keeps sentinel
        # Dense one-hot delta rows, NOT per-element scalar scatters:
        # XLA's TPU scatter vectorizes the K lane dimension of row
        # updates, so the dense [B,K] delta runs ~2x faster than the
        # "only 2 of K entries change" rank-1 formulation (measured
        # 35M vs 18M tokens/s at K=20).
        delta = _one_hot(z_new, k_topics) - oh_old  # int32-exact update
        n_dk = n_dk.at[d].add(delta)
        # n_wk shape is static under trace, so the delta form resolves
        # to ONE compiled path (module comment at _NWK_MATMUL_MAX_V).
        use_matmul = (nwk_matmul if nwk_matmul is not None
                      else (use_gumbel
                            and n_wk.shape[0] <= _NWK_MATMUL_MAX_V
                            # Exactness bound: every output of the f32
                            # accumulation is a sum of B {-1,0,1} terms,
                            # so |output| <= B must stay below 2^24 or
                            # integers stop being representable exactly.
                            # MAX_ELEMS implies it for V >= 8 only; the
                            # explicit bound covers tiny-V/huge-B days.
                            and w.shape[0] < (1 << 24)
                            and w.shape[0] * n_wk.shape[0]
                            <= _NWK_MATMUL_MAX_ELEMS))
        if nwk_matmul and w.shape[0] >= (1 << 24):
            raise ValueError(
                f"nwk_matmul=True with block size {w.shape[0]} >= 2^24: "
                "the one-hot matmul's f32 accumulation is no longer "
                "bit-exact at this block size")
        if use_matmul:
            oh_w = jax.nn.one_hot(w, n_wk.shape[0], dtype=jnp.bfloat16)
            d_wk = jax.lax.dot_general(
                oh_w, delta.astype(jnp.bfloat16),
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            n_wk = n_wk + d_wk.astype(jnp.int32)
        else:
            n_wk = n_wk.at[w].add(delta)
        n_k = n_k + delta.sum(axis=0, dtype=jnp.int32)
        return (n_dk, n_wk, n_k, key), z_new

    return block_step


def sweep(
    state: GibbsState,
    doc_blocks: jax.Array,   # int32 [n_blocks, B]
    word_blocks: jax.Array,  # int32 [n_blocks, B]
    mask_blocks: jax.Array,  # float32 [n_blocks, B]
    *,
    alpha: float,
    eta: float,
    n_vocab: int,
    accumulate: bool,
) -> GibbsState:
    """One full Gibbs sweep over all token blocks (jit-friendly)."""
    k_topics = state.n_dk.shape[1]
    block_step = make_block_step(alpha=alpha, eta=eta, n_vocab=n_vocab,
                                 k_topics=k_topics)

    (n_dk, n_wk, n_k, key), z = jax.lax.scan(
        block_step,
        (state.n_dk, state.n_wk, state.n_k, state.key),
        (doc_blocks, word_blocks, mask_blocks, state.z),
    )
    do_acc = jnp.float32(accumulate)
    return GibbsState(
        z=z, n_dk=n_dk, n_wk=n_wk, n_k=n_k, key=key,
        acc_ndk=state.acc_ndk + do_acc * n_dk.astype(jnp.float32),
        acc_nwk=state.acc_nwk + do_acc * n_wk.astype(jnp.float32),
        n_acc=state.n_acc + jnp.int32(accumulate),
    )


def posterior_estimates(
    state: GibbsState, *, alpha: float, eta: float
) -> tuple[jax.Array, jax.Array]:
    """(theta [D,K], phi_wk [V,K]) from averaged (or instantaneous) counts."""
    use_acc = state.n_acc > 0
    denom = jnp.maximum(state.n_acc.astype(jnp.float32), 1.0)
    ndk = jnp.where(use_acc, state.acc_ndk / denom, state.n_dk.astype(jnp.float32))
    nwk = jnp.where(use_acc, state.acc_nwk / denom, state.n_wk.astype(jnp.float32))
    theta = (ndk + alpha) / (ndk.sum(-1, keepdims=True) + ndk.shape[1] * alpha)
    nk = nwk.sum(axis=0, keepdims=True)
    phi_wk = (nwk + eta) / (nk + nwk.shape[0] * eta)
    return theta, phi_wk


def log_likelihood(
    theta: jax.Array, phi_wk: jax.Array,
    doc_blocks: jax.Array, word_blocks: jax.Array, mask_blocks: jax.Array,
) -> jax.Array:
    """Mean per-token log p(w|d) — the convergence series the reference
    prints to likelihood.dat (SURVEY.md §5.4). Accumulated block by
    block: gathering theta/phi rows for the whole corpus at once
    materializes an [N, K]-padded temp that OOMs HBM past ~10M tokens."""
    def block(carry, xs):
        d, w, m = xs
        p = jnp.sum(theta[d] * phi_wk[w], axis=-1)
        lp = jnp.log(jnp.maximum(p, 1e-30)) * m
        return (carry[0] + lp.sum(), carry[1] + m.sum()), None

    (total, n), _ = jax.lax.scan(
        block, (jnp.float32(0.0), jnp.float32(0.0)),
        (doc_blocks, word_blocks, mask_blocks))
    return total / jnp.maximum(n, 1.0)


class GibbsLDA:
    """Host-side driver around the functional kernel.

    Equivalent role to oni-lda-c's `lda estimate` entry point, but runs
    in-process on the accelerator instead of via ssh + mpiexec
    (SURVEY.md §3.1 hot loop #2).
    """

    def __init__(self, config: LDAConfig, n_docs: int, n_vocab: int):
        config.validate()
        self.config = config
        self.n_docs = n_docs
        self.n_vocab = n_vocab
        chains = config.n_chains
        base_sweep = functools.partial(
            sweep, alpha=config.alpha, eta=config.eta, n_vocab=n_vocab)
        base_est = functools.partial(
            posterior_estimates, alpha=config.alpha, eta=config.eta)
        if chains == 1:
            self._sweep = jax.jit(base_sweep,
                                  static_argnames=("accumulate",))
            self._estimates = jax.jit(base_est)
            self._ll = jax.jit(log_likelihood)
        else:
            # vmap over the chain axis of the state; token blocks are
            # shared (broadcast). theta/phi keep a leading chain axis —
            # scoring averages probabilities over it.
            def sweep_chains(state, d, w, m, accumulate):
                return jax.vmap(lambda s: base_sweep(
                    s, d, w, m, accumulate=accumulate))(state)

            def ll_chains(theta, phi_wk, d, w, m):
                return jax.vmap(lambda t, p: log_likelihood(
                    t, p, d, w, m))(theta, phi_wk).mean()

            self._sweep = jax.jit(sweep_chains,
                                  static_argnames=("accumulate",))
            self._estimates = jax.jit(jax.vmap(base_est))
            self._ll = jax.jit(ll_chains)

    def prepare(self, corpus: Corpus, shuffle: bool = True):
        if shuffle:
            corpus = corpus.shuffled(self.config.seed)
        block = min(self.config.block_size, max(corpus.n_tokens, 1))
        padded, mask = corpus.padded(block)
        nb = padded.n_tokens // block
        return (
            jnp.asarray(padded.doc_ids.reshape(nb, block)),
            jnp.asarray(padded.word_ids.reshape(nb, block)),
            jnp.asarray(mask.reshape(nb, block)),
        )

    def fit(self, corpus: Corpus, n_sweeps: int | None = None,
            callback=None, checkpoint_dir=None, resume: bool = True,
            fault_inject_sweep: int | None = None) -> dict:
        """Run the sweep loop; optionally checkpoint every
        `config.checkpoint_every` sweeps into `checkpoint_dir` and resume
        from the newest matching checkpoint there (SURVEY.md §5.3-5.4:
        resume-on-preemption). Resumed runs are bit-identical to
        uninterrupted ones — the sweep is a pure function of the state.

        `fault_inject_sweep` (or env ONIX_FAULT_SWEEP) simulates a
        preemption by raising SimulatedPreemption right after completing
        that sweep — the §5.3 fault-injection hook; a caller that
        retries `fit` resumes from the last checkpoint."""
        import os

        from onix import checkpoint as ckpt

        if fault_inject_sweep is None:
            env = os.environ.get("ONIX_FAULT_SWEEP")
            fault_inject_sweep = int(env) if env else None

        cfg = self.config
        n_sweeps = cfg.n_sweeps if n_sweeps is None else n_sweeps
        docs, words, mask = self.prepare(corpus)
        fp = ckpt.fingerprint(cfg, self.n_docs, self.n_vocab,
                              corpus.n_tokens)
        # Per-fingerprint subdir: checkpoints of runs with a different
        # identity can neither be adopted nor pruned by this run.
        if checkpoint_dir is not None:
            import pathlib
            checkpoint_dir = pathlib.Path(checkpoint_dir) / fp
        start = 0
        state = None
        if checkpoint_dir is not None and resume:
            saved = ckpt.load_latest(checkpoint_dir)
            if saved is not None and saved.meta.get("fingerprint") == fp:
                state = GibbsState(**{k: jnp.asarray(v)
                                      for k, v in saved.arrays.items()})
                start = saved.sweep + 1
        if state is None:
            if cfg.n_chains == 1:
                state = init_state(docs, words, mask, self.n_docs,
                                   self.n_vocab, cfg.n_topics, cfg.seed)
            else:
                state = init_chains(docs, words, mask, self.n_docs,
                                    self.n_vocab, cfg.n_topics, cfg.seed,
                                    cfg.n_chains)
        theta0, phi0 = self._estimates(state)
        ll_history = [(start - 1,
                       float(self._ll(theta0, phi0, docs, words, mask)))]
        for s in range(start, n_sweeps):
            state = self._sweep(state, docs, words, mask,
                                accumulate=s >= cfg.burn_in)
            if (checkpoint_dir is not None and cfg.checkpoint_every > 0
                    and (s + 1) % cfg.checkpoint_every == 0):
                ckpt.save(checkpoint_dir, s,
                          {k: np.asarray(v)
                           for k, v in state._asdict().items()},
                          {"fingerprint": fp, "engine": "gibbs"})
            if fault_inject_sweep is not None and s == fault_inject_sweep:
                raise ckpt.SimulatedPreemption(
                    f"fault injected after sweep {s} "
                    f"(checkpoint_dir={checkpoint_dir})")
            if callback is not None or s == n_sweeps - 1 or s % 10 == 9:
                theta, phi_wk = self._estimates(state)
                ll = float(self._ll(theta, phi_wk, docs, words, mask))
                ll_history.append((s, ll))
                if callback is not None:
                    callback(s, state, ll)
        theta, phi_wk = self._estimates(state)
        return {
            "state": state,
            # n_chains>1 stacks a leading chain axis: theta [C,D,K],
            # phi_wk [C,V,K]; scoring.score_events averages over it.
            "theta": np.asarray(theta),
            "phi_wk": np.asarray(phi_wk),   # [V,K]; phi[k,v] = phi_wk[v,k]
            "ll_history": ll_history,
        }
