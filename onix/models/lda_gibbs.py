"""Batched collapsed-Gibbs LDA in pure JAX — the TPU replacement for
oni-lda-c (reference README.md:84, .gitmodules absent; SURVEY.md §2.1 #10).

The reference engine is a C/MPI program: documents sharded across ranks,
a sequential per-token sampler per rank, topic-word sufficient statistics
MPI-reduced each iteration. A token-sequential sampler cannot use a TPU,
so onix uses the standard SIMD compromise (SURVEY.md §7.3.1, PAPERS.md
"Sparse Partially Collapsed MCMC"): tokens are sampled in blocks of
`block_size`; within a block every token sees counts that exclude its own
assignment but are stale w.r.t. its block-mates; counts are exactly
updated between blocks via scatter-add. As block_size → 1 this is exact
collapsed Gibbs; at practical sizes the stationary distribution is close
enough that topic recovery and the top-k overlap metric survive (tested
in tests/test_gibbs.py).

Shapes: K topics, V vocabulary, D documents, N tokens.
State counts: n_dk [D,K], n_wk [V,K], n_k [K] (int32, exact — deltas are
scattered as int32, never round-tripped through float32, so counts stay
exact past 2^24 at the billion-event scale of README.md:42).
Padding tokens carry the sentinel assignment z == K: `jax.nn.one_hot`
maps out-of-range indices to all-zero rows, so padding contributes
nothing to any count without a mask multiply.
A sweep is `lax.scan` over N/block_size blocks — one fused XLA program,
no host round-trips, no Python control flow inside jit.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from onix.config import LDAConfig
from onix.corpus import Corpus


class GibbsState(NamedTuple):
    z: jax.Array          # int32 [n_blocks, B] topic per token (K = padding)
    n_dk: jax.Array       # int32 [D, K] doc-topic counts
    n_wk: jax.Array       # int32 [V, K] word-topic counts
    n_k: jax.Array        # int32 [K]    topic totals
    key: jax.Array        # PRNG key
    # Posterior-mean accumulators (populated after burn-in; improves the
    # rank stability needed for the judged top-k overlap, SURVEY.md §7.3.2).
    acc_ndk: jax.Array    # float32 [D, K]
    acc_nwk: jax.Array    # float32 [V, K]
    n_acc: jax.Array      # int32 [] number of accumulated sweeps


def _one_hot(z: jax.Array, k: int) -> jax.Array:
    """int32 one-hot; out-of-range z (the padding sentinel K) -> zero row."""
    return jax.nn.one_hot(z, k, dtype=jnp.int32)


def init_state_keyed(
    key: jax.Array,
    doc_blocks: jax.Array,
    word_blocks: jax.Array,
    mask_blocks: jax.Array,
    n_docs: int,
    n_vocab: int,
    n_topics: int,
) -> GibbsState:
    """Random topic init + exact count build, blockwise.

    Counts are scattered one token block at a time under `lax.scan`: a
    flat one-hot over the whole corpus would materialize an
    [N, K]-padded temp that OOMs HBM past ~10M tokens (hit at 40M)."""
    key, zkey = jax.random.split(key)
    shape = doc_blocks.shape
    z = jax.random.randint(zkey, shape, 0, n_topics, dtype=jnp.int32)
    z = jnp.where(mask_blocks > 0, z, n_topics)   # sentinel for padding

    def count_block(carry, xs):
        n_dk, n_wk, n_k = carry
        d, w, zb = xs
        oh = _one_hot(zb, n_topics)               # [B, K]; padding -> 0
        return (n_dk.at[d].add(oh), n_wk.at[w].add(oh),
                n_k + oh.sum(axis=0, dtype=jnp.int32)), None

    (n_dk, n_wk, n_k), _ = jax.lax.scan(
        count_block,
        (jnp.zeros((n_docs, n_topics), jnp.int32),
         jnp.zeros((n_vocab, n_topics), jnp.int32),
         jnp.zeros((n_topics,), jnp.int32)),
        (doc_blocks, word_blocks, z))
    return GibbsState(
        z=z, n_dk=n_dk, n_wk=n_wk, n_k=n_k, key=key,
        acc_ndk=jnp.zeros((n_docs, n_topics), jnp.float32),
        acc_nwk=jnp.zeros((n_vocab, n_topics), jnp.float32),
        n_acc=jnp.zeros((), jnp.int32),
    )


def init_state(
    doc_blocks: jax.Array,
    word_blocks: jax.Array,
    mask_blocks: jax.Array,
    n_docs: int,
    n_vocab: int,
    n_topics: int,
    seed: int,
) -> GibbsState:
    return init_state_keyed(jax.random.PRNGKey(seed), doc_blocks,
                            word_blocks, mask_blocks, n_docs, n_vocab,
                            n_topics)


def init_chains(
    doc_blocks: jax.Array,
    word_blocks: jax.Array,
    mask_blocks: jax.Array,
    n_docs: int,
    n_vocab: int,
    n_topics: int,
    seed: int,
    n_chains: int,
) -> GibbsState:
    """Stacked state for `n_chains` independent chains (leading chain
    axis on every array). Chains differ only in their PRNG streams; on
    TPU vmap turns the per-chain gathers/scatters into one batched
    program, so C chains cost ~one sweep of C× the tokens."""
    keys = jax.vmap(jax.random.fold_in, in_axes=(None, 0))(
        jax.random.PRNGKey(seed), jnp.arange(n_chains, dtype=jnp.uint32))
    return jax.vmap(
        lambda k: init_state_keyed(k, doc_blocks, word_blocks, mask_blocks,
                                   n_docs, n_vocab, n_topics))(keys)


# Table width up to which the n_wk delta goes through an MXU one-hot
# matmul instead of a scatter-add on TPU. Rationale: the sweep is
# scatter-bound (docs/PERF.md), and with product vocabularies (V in the
# hundreds) the n_wk scatter is COLLISION-dense — a 2^17-token block
# lands ~B/V ~ 250 colliding row-updates per word. The matmul form
# computes the same [V, K] delta as onehot(w)^T @ delta on the MXU:
# B*V*K MACs (~1.4e9 at the cap — microseconds) plus one [B, V] bf16
# one-hot materialization, with NO serialized collisions. Exact by
# construction: operands are {-1, 0, 1} (exact in bf16), accumulation
# is f32, and each output magnitude is <= B = 2^17 << 2^24. The n_dk
# scatter keeps its scatter form — documents are nearly collision-free
# within a block and D is far too large to one-hot.
_NWK_MATMUL_MAX_V = 4096
# Auto-enable also bounds the [B, V] one-hot temporary (bf16 elements):
# 2^27 = 256 MB. A block_size 2^17 sweep at V=4096 would otherwise grow
# a 1 GiB temporary (x n_chains under the vmap engine) that the scatter
# form never allocated — an OOM regression, not a speedup. Forcing
# nwk_matmul=True bypasses the bound for experiments.
_NWK_MATMUL_MAX_ELEMS = 1 << 27
# Collision-density crossover per backend: the auto gate engages the
# matmul form only when the n_wk scatter is collision-DENSE, measured
# as density = block_size / V (expected colliding row-updates per vocab
# row per block), instead of the old backend-only rule ("any V <= 4096
# on an accelerator"). The decision table lives in docs/PERF.md ("the
# gibbs_fit vs sweep-microbench gap"), fed by scripts/exp_fit_gap.py
# (raw_nwk_scatter vs raw_nwk_matmul on the real corpus shape; a tiny
# CPU smoke of the same harness runs in tier-1 so it cannot rot):
#   * cpu — NO entry: the matmul form measured ~4x SLOWER than the
#     scatter at the densest judged shape (V=289, B=2^17, density ~450;
#     PERF.md r7 rows). B*V*K host MACs never beat a cache-resident
#     scatter here, so CPU stays on the scatter at every density.
#   * tpu — engage at density >= 32: the V=4096/B=2^16 microbench
#     (density 16) measured the scatter as acceptable (35-37 Mtok/s,
#     PERF.md "the exponential race"), so the crossover sits strictly
#     above it; judged product vocabularies (V~500, B=2^17, density
#     ~260) engage exactly as the old gate did. The TPU scatter-vs-
#     matmul rows of exp_fit_gap.py stay queued behind the tunnel —
#     when they land, this threshold moves to the measured crossover.
# Unmeasured accelerators (gpu) get no entry and keep the scatter —
# the same "measured platforms only" policy as scoring's bf16 gate.
_NWK_MATMUL_MIN_DENSITY = {"tpu": 32.0}
# Third arm of the n_wk gate: the Pallas fused sample+count kernel
# (onix/models/pallas_gibbs.py) — removes the scatter's collision
# serialization entirely (per-tile MXU count-merge into a VMEM-resident
# accumulator) instead of out-muscling it with the HBM one-hot matmul.
# Same "measured platforms only" policy: the table is EMPTY until the
# queued TPU rows land (docs/TPU_QUEUE.json `fitgap_tpu` measures
# scatter vs matmul vs pallas on the judged shape; the crossover
# density lands here, expected to sit at/below the matmul's 32). Until
# then the kernel is reachable via nwk_form="pallas" /
# ONIX_NWK_FORM=pallas (and runs interpret-mode bit-identity in
# tier-1), so the default path on every backend is unchanged.
_NWK_PALLAS_MIN_DENSITY: dict[str, float] = {}


def nwk_pallas_auto_reachable(backend: str) -> bool:
    """Whether the AUTO n_wk gate could resolve "pallas" on `backend` —
    the capability probe ShardedGibbsLDA uses to drop the shard_map
    static replication check (shard_map has no replication rule for
    pallas_call) exactly when the pallas arm might trace. NOT a form
    decision: the form itself still resolves through select_nwk_form's
    resolve_form_gate chain — this only answers "is the pallas row of
    that gate's table populated for this backend"."""
    # lint: exempt[gates] -- capability probe next to the table it reads; the form decision still goes through select_nwk_form's resolve_form_gate chain
    return _NWK_PALLAS_MIN_DENSITY.get(backend) is not None


def env_nwk_form() -> str | None:
    """Resolve the ONIX_NWK_FORM experiment override. "auto" (and
    empty) mean None — the same spelling LDAConfig.nwk_form accepts for
    "defer to the measured gate" — so exporting ONIX_NWK_FORM=auto
    resets an inherited override instead of crashing; anything else is
    validated by select_nwk_form at trace time. Read this ONCE per
    engine/trace decision: the sharded engine keys its shard_map
    replication-check drop off the same resolved value it samples with,
    so the two can never disagree mid-session."""
    import os
    env = os.environ.get("ONIX_NWK_FORM")
    if not env or env == "auto":
        return None
    return env


def select_nwk_form(*, backend: str, block_size: int, n_rows: int,
                    nwk_matmul: bool | None = None,
                    nwk_form: str | None = None) -> str:
    """Trace-time decision for the n_wk count-update form — the single
    gate shared by every engine (tests/test_pallas_gibbs.py exercises
    its edge cases directly).

    Priority (config.resolve_form_gate — the ONE precedence chain
    shared with `select_bank_form` and `select_serve_form`, so the
    three gate tables cannot drift): explicit `nwk_form` ("scatter" |
    "matmul" | "pallas"), then the legacy `nwk_matmul` bool, then the
    measured per-backend collision-density tables (density =
    block_size / n_rows expected colliding row-updates per count row
    per block) bounded by the exactness/memory caps. No env layer
    HERE: the engines resolve ONIX_NWK_FORM themselves (env_nwk_form),
    where an explicit test-arm pin must outrank an exported override
    (make_block_step's documented contract), and pass the result in as
    `nwk_form`. All three forms are bit-identical; this picks the
    measured-fastest one for the platform and shape.
    """
    from onix.config import resolve_form_gate
    explicit = nwk_form
    if explicit is None and nwk_matmul is not None:
        explicit = "matmul" if nwk_matmul else "scatter"

    def measured() -> str | None:
        pallas_density = _NWK_PALLAS_MIN_DENSITY.get(backend)
        if (pallas_density is not None
                and block_size >= pallas_density * n_rows
                and n_rows <= _NWK_MATMUL_MAX_V):
            return "pallas"
        min_density = _NWK_MATMUL_MIN_DENSITY.get(backend)
        if (min_density is not None
                and block_size >= min_density * n_rows
                and n_rows <= _NWK_MATMUL_MAX_V
                # Exactness bound: every output of the f32 accumulation
                # is a sum of block_size {-1,0,1} terms, so |output| <=
                # block_size must stay below 2^24 or integers stop
                # being representable exactly. MAX_ELEMS implies it for
                # V >= 8 only; the explicit bound covers tiny-V/huge-B
                # days.
                and block_size < (1 << 24)
                and block_size * n_rows <= _NWK_MATMUL_MAX_ELEMS):
            return "matmul"
        return None

    return resolve_form_gate(gate="nwk_form",
                             choices=("scatter", "matmul", "pallas"),
                             explicit=explicit, measured=measured,
                             default="scatter")


# ---------------------------------------------------------------------------
# Sampler-form gate (r11): dense O(K) block sampler vs the sparse
# O(K_active) arm.
#
# Every arm of the n_wk gate above still pays O(K) per token in three
# places — the [B,K] probability block, the K-argmax, and the one-hot
# delta — so a K=256 per-tenant model pays for every topic ALLOCATED
# even when each document touches a handful. The sparse arm (Sparse
# Partially Collapsed MCMC, arxiv 1506.03784; LightLDA-style alias/MH
# cycling) replaces all three with work that scales with topics
# TOUCHED: per-document top-A active-topic blocks (static pow2 width,
# onix/models/compaction.py), a stale F+-tree-style CDF proposal for
# the dense-phi remainder (O(log K) bisection, tables rebuilt from the
# sweep-start counts), Metropolis–Hastings acceptance against the
# FRESH blocked target so the stationary distribution is exactly the
# dense arm's blocked-chain target, and rank-1 count scatters. Same
# key-stream discipline as every other arm (the carry key splits once
# per block), so accepted states replay deterministically — but the
# DRAWS differ from the dense arm: this is a different chain with the
# same stationary distribution, tested under winner-parity +
# perplexity-band (tests/test_sparse_gibbs.py), NOT bit-identity.
#
# Crossover tables follow the measured-platforms-only policy of the
# n_wk gate: auto engages the sparse arm only where a committed
# measurement says it wins, keyed by K (the axis the win scales with).
#   * cpu — K >= 64: measured on this 2-core host
#     (docs/SPARSE_r11_cpu.json, exp_fit_gap 2e6 --k-sweep {16,64,256}):
#     sparse/dense per-token fit cost 0.87x at K=16 (A=8), 1.80x at
#     K=64 (A=8), 4.46x at K=256 (A=16, mh=2); 2.80x at K=256 on the
#     bench shape (docs/SPARSE_r11_bench_cpu.json). 64 is the LOWEST
#     MEASURED K where the sparse arm wins (the true crossover sits
#     somewhere in (16, 64), unmeasured). The crossover sits above the
#     judged K=20 pipelines — defaults there are unchanged.
#   * tpu — NO entry until the queued crossover lands
#     (docs/TPU_QUEUE.json `sparse_sampler_tpu`): the dense arm's
#     [B,K] blocks ride the VPU lanes that gathers do not, so the CPU
#     crossover must not be assumed to transfer.
_SAMPLER_SPARSE_MIN_K: dict[str, float] = {"cpu": 64.0}


def env_sampler_form() -> str | None:
    """Resolve the ONIX_SAMPLER_FORM experiment override. "auto" (and
    empty) mean None — defer to the measured gate — mirroring
    env_nwk_form. Engines read this ONCE at construction: the resolved
    form joins the checkpoint fingerprint, so the compiled sampler and
    the resume identity can never disagree."""
    import os
    env = os.environ.get("ONIX_SAMPLER_FORM")
    if not env or env == "auto":
        return None
    return env


def select_sampler_form(*, backend: str, k_topics: int,
                        sampler_form: str | None = None) -> str:
    """Trace-time decision for the sampler form ("dense" | "sparse") —
    the gate shared by GibbsLDA and ShardedGibbsLDA.

    Priority (config.resolve_form_gate — the ONE precedence chain
    shared with select_nwk_form / select_bank_form /
    select_serve_form, r17: this gate was the last hand-rolled chain):
    explicit `sampler_form`, then the measured per-backend K crossover
    (_SAMPLER_SPARSE_MIN_K; unmeasured platforms keep dense). No env
    layer HERE: the engines resolve ONIX_SAMPLER_FORM themselves
    (_resolved_sampler_form), where the dense-pin deference must sit
    BETWEEN the env and the measured table, and hand the result in as
    `sampler_form`. An explicit "sparse" is honored at ANY K — at tiny
    K the top-A block simply saturates (A == K)."""
    from onix.config import resolve_form_gate

    def measured() -> str | None:
        min_k = _SAMPLER_SPARSE_MIN_K.get(backend)
        if min_k is not None and k_topics >= min_k:
            return "sparse"
        return None

    return resolve_form_gate(gate="sampler_form",
                             choices=("dense", "sparse"),
                             explicit=sampler_form, measured=measured,
                             default="dense")


def sampler_fingerprint(form: str, sparse_active: int,
                        sparse_mh: int) -> dict:
    """Checkpoint-identity entry for the RESOLVED sampler form (shared
    by GibbsLDA and ShardedGibbsLDA fit). Dense contributes NOTHING:
    the dense chain is bit-identical to the pre-r11 code, so pre-r11
    dense checkpoints keep resuming. The sparse arm adds the form plus
    its live knobs (A and the MH cycle length change what the chain
    samples) — which is also what refuses a resume across an arm
    change in either direction."""
    if form != "sparse":
        return {}
    return {"sampler": form,
            "sparse": [int(sparse_active), int(sparse_mh)]}


def merge_fingerprint(form: str, staleness: int) -> dict:
    """Checkpoint-identity entry for the RESOLVED count-merge form
    (r14; shared by GibbsLDA and ShardedGibbsLDA fit, mirroring
    sampler_fingerprint). Sync contributes NOTHING: the synchronous
    fold is bit-identical to the pre-r14 code, so pre-r14 checkpoints
    keep resuming. The async arm adds the form plus its live staleness
    bound τ (τ>0 changes what the chain samples; τ=0 is bit-identical
    to sync but still a distinct configuration whose resume the spec
    refuses rather than silently crossing) — which is also what
    refuses a resume across a merge-form/τ change in either
    direction."""
    if form != "async":
        return {}
    return {"merge": [form, int(staleness)]}


def _resolved_sampler_form(sampler_form: str | None, *, k_topics: int,
                           pinned: bool) -> str:
    """The ONE deference chain behind every sampler-form decision —
    explicit form, then ONIX_SAMPLER_FORM, then dense when a
    dense-only knob is pinned (an n_wk form or a block-sampler draw
    form, argument or ONIX_NWK_FORM: the sparse arm has neither knob,
    so auto stealing a pinned run would silently mislabel that
    experiment), then the measured gate. Shared by resolve_sampler
    (both engines) and make_sweep_kernel (standalone callers) so a
    policy change can never make them resolve different arms for the
    same config/env."""
    form = sampler_form
    if form is None:
        form = env_sampler_form()
    if form is None and (pinned or env_nwk_form() is not None):
        form = "dense"
    return select_sampler_form(backend=jax.default_backend(),
                               k_topics=k_topics, sampler_form=form)


def resolve_sampler(config, *, k_topics: int,
                    nwk_form: str | None = None) -> tuple[str, int, dict]:
    """The ONE construction-time sampler resolution shared by GibbsLDA
    and ShardedGibbsLDA: config (explicit lda.sampler_form beats all),
    then ONIX_SAMPLER_FORM, then — only for the measured auto gate —
    deference to an explicit n_wk pin (a user who pinned
    nwk_form=matmul/pallas is running an n_wk experiment; the sparse
    arm has no n_wk form, so auto silently stealing the run would
    mislabel their measurement — auto stays dense instead; an explicit
    sampler_form/env still wins), then _SAMPLER_SPARSE_MIN_K. Returns
    (form, resolved_active, kwargs-for-make_sweep_kernel); the form
    feeds both the compiled programs and the checkpoint fingerprint,
    so keeping this in one place is what keeps the two engines from
    ever resolving different arms for the same config."""
    sform = (None if config.sampler_form == "auto"
             else config.sampler_form)
    form = _resolved_sampler_form(sform, k_topics=k_topics,
                                  pinned=nwk_form is not None)
    active = resolve_sparse_active(k_topics, config.sparse_active)
    return form, active, dict(sampler_form=form, sparse_active=active,
                              sparse_mh=config.sparse_mh)


def resolve_sparse_active(k_topics: int, sparse_active: int = 0) -> int:
    """Static width A of the per-doc active-topic block. 0 = auto: the
    smallest pow2 >= max(8, K/16), capped at K — sized to realistic
    per-doc topic occupancy so cost tracks topics touched; truncation
    below a doc's true active count costs proposal quality only (the
    dense-phi branch keeps every topic reachable and MH keeps the
    chain exact)."""
    from onix.models.compaction import pow2_bucket
    if sparse_active > 0:
        return min(int(k_topics), int(sparse_active))
    return min(int(k_topics), pow2_bucket(max(8, k_topics // 16)))


class SparseTables(NamedTuple):
    """Stale proposal tables for the sparse arm, a pure function of the
    sweep-start counts (rebuilt each sweep inside the fused superstep,
    so the sampled chain is independent of the superstep size S — the
    same S-invariance every other arm has).

    act_ids/act_cnt: per-doc top-A stale topics and their counts
    (zero-count slots carry no proposal mass). phi_cdf: row cumsum of
    the stale phi-hat (n_wk+eta)/(n_k+V*eta) — the F+-tree the dense
    branch bisects; its last column is the row total Q_w, and its f32
    interval widths are the REALIZED dense-branch proposal densities
    the acceptance ratio charges. nwk/nk are the raw stale counts for
    O(A) phi-hat evaluation over each token's active block."""

    act_ids: jax.Array   # int32  [D, A]
    act_cnt: jax.Array   # float32 [D, A] stale n_dk at act_ids
    phi_cdf: jax.Array   # float32 [V, K]
    nwk: jax.Array       # int32  [V, K] sweep-start snapshot
    nk: jax.Array        # int32  [K]


def build_sparse_tables(n_dk: jax.Array, n_wk: jax.Array, n_k: jax.Array,
                        *, eta: float, v_eta: float,
                        n_active: int) -> SparseTables:
    vals, ids = jax.lax.top_k(n_dk, n_active)
    phi = ((n_wk.astype(jnp.float32) + eta)
           / (n_k.astype(jnp.float32)[None, :] + v_eta))
    return SparseTables(act_ids=ids.astype(jnp.int32),
                        act_cnt=vals.astype(jnp.float32),
                        phi_cdf=jnp.cumsum(phi, axis=1),
                        nwk=n_wk, nk=n_k)


def cdf_lower_bound(cdf_flat: jax.Array, row: jax.Array, t: jax.Array,
                    k: int) -> jax.Array:
    """Vectorized lower_bound over rows of a flattened [*, k] CDF
    table: the count of entries cdf[row, :] < t, in [0, k] — the
    F+-tree-style bisection of the dense-phi proposal branch. log2(k)
    scalar-gather rounds per element instead of gathering the whole
    [B, K] row block (which would re-pay the O(K) the sparse arm
    exists to avoid). Matches np.searchsorted(cdf[row], t, 'left')
    exactly (tests/test_sparse_gibbs.py hypothesis property)."""
    pos = jnp.zeros(row.shape, jnp.int32)
    base = row.astype(jnp.int32) * k
    s = 1 << max(0, int(k).bit_length() - 1)   # largest pow2 <= k
    while s:
        cand = pos + s
        # Safe gather index (cand can momentarily exceed k); the move
        # condition re-checks the bound.
        val = jnp.take(cdf_flat, base + jnp.minimum(cand, k) - 1)
        pos = jnp.where((cand <= k) & (val < t), cand, pos)
        s >>= 1
    return pos


# Weight of the uniform escape branch in the sparse arm's proposal
# mixture, as a fraction of the (doc block + dense CDF) mass. It buys
# two guarantees the two main branches cannot give in f32: (i) every
# topic has NONZERO realized proposal probability even when its CDF
# interval rounds to zero width (a linear f32 cumsum makes draws of
# topics below ~2^-24 of the row total exactly impossible — the same
# failure mode the dense sampler's race replaced inverse-CDF over),
# so the chain's support is the full target support; (ii) a state
# outside both branches' realized support can still be LEFT (its
# proposal density q(z) >= u_mass/K > 0 keeps the acceptance ratio
# finite and the realized-width correction honest). 1/64 costs <2% of
# proposal draws; the MH correction absorbs the quality loss.
_SPARSE_UNIFORM_FRAC = 1.0 / 64.0


def make_sparse_block_step(*, alpha: float, eta: float, v_eta: float,
                           k_topics: int, n_mh: int,
                           tables: SparseTables):
    """The sparse-arm block step: for each token, `n_mh` independence-
    sampler MH moves whose proposal mixes (i) the doc's stale top-A
    active-topic mass — (n_dk-ish) x phi-stale over the compacted
    block, O(A) — (ii) the dense-phi remainder alpha * phi-stale drawn
    by CDF bisection, O(log K), and (iii) a thin uniform escape branch
    (_SPARSE_UNIFORM_FRAC) that keeps every topic reachable under f32;
    acceptance evaluates the FRESH blocked target at just the two
    topics involved, O(1) gathers. The acceptance ratio uses the
    REALIZED f32 proposal densities — the exact cumsum interval widths
    the inverse-CDF draws land in, not the ideal per-topic masses — so
    q() in the ratio is the distribution the sampler actually draws
    from and the corrected chain's stationary distribution matches the
    dense arm's block-stale conditional (counts exclude the token's
    own sweep-start assignment, stale w.r.t. block-mates) up to the
    uniform-draw quantization every sampler shares. Count updates are
    rank-1 scalar scatters — O(1) per token, not a [B,K] one-hot."""
    k = k_topics
    a_width = tables.act_ids.shape[1]
    cdf_flat = tables.phi_cdf.reshape(-1)
    nwk_stale = tables.nwk.reshape(-1).astype(jnp.float32)
    nk_stale = tables.nk.astype(jnp.float32)

    def block_step(carry, xs):
        n_dk, n_wk, n_k, key = carry
        d, w, m, z_old = xs
        key, skey = jax.random.split(key)   # same carry key stream as
        #                                     the dense arm
        b = d.shape[0]
        u = jax.random.uniform(skey, (n_mh, b, 3), dtype=jnp.float32,
                               minval=1e-38)
        valid = m > 0.0
        zf = jnp.where(valid, z_old, 0)     # gather-safe padding index

        # Per-token stale doc-side block: top-A ids/counts + their
        # stale phi values — the O(A) "topics touched" work. The
        # REALIZED per-slot proposal masses are the f32 cumsum interval
        # widths (exact subtractions), which is what the inverse-CDF
        # draw below actually samples; they are what q() must charge.
        a_ids = tables.act_ids[d]                       # [B, A]
        a_cnt = tables.act_cnt[d]                       # [B, A]
        phi_a = ((jnp.take(nwk_stale, w[:, None] * k + a_ids) + eta)
                 / (jnp.take(nk_stale, a_ids) + v_eta))
        s_cum = jnp.cumsum(a_cnt * phi_a, axis=1)
        s_width = jnp.diff(s_cum, axis=1,
                           prepend=jnp.zeros((b, 1), jnp.float32))
        s_mass = s_cum[:, -1]                           # [B]
        q_w = jnp.take(cdf_flat, w * k + (k - 1))       # row total
        dense_mass = alpha * q_w
        u_mass = jnp.float32(_SPARSE_UNIFORM_FRAC) * (s_mass + dense_mass)
        tot_mass = s_mass + dense_mass + u_mass

        # Fresh target (counts exclude the token's own sweep-start
        # assignment z_old — the same exclusion the dense arm applies
        # via its one-hot subtraction), evaluated at single topics.
        # Gather int32 FIRST, convert the [B]-sized result: casting the
        # live [D,K]/[V,K] here would materialize full f32 copies every
        # block, swamping the arm's O(K_active)-per-token traffic.
        ndk_flat = n_dk.reshape(-1)
        nwk_flat = n_wk.reshape(-1)

        def target(kk):
            e = (kk == zf).astype(jnp.int32)
            ndk = (jnp.take(ndk_flat, d * k + kk) - e).astype(jnp.float32)
            nwk = (jnp.take(nwk_flat, w * k + kk) - e).astype(jnp.float32)
            nk = (jnp.take(n_k, kk) - e).astype(jnp.float32)
            return ((ndk + alpha) * jnp.maximum(nwk + eta, 1e-10)
                    / (nk + v_eta))

        def proposal_weight(kk):
            """REALIZED unnormalized mixture density at kk: the f32
            interval widths the three branches actually draw — doc
            block slots matching kk (zero-count slots have exactly
            zero width), the word's CDF row interval at kk, and the
            uniform escape floor. Always >= u_mass/K > 0."""
            hit = a_ids == kk[:, None]
            doc_term = jnp.sum(jnp.where(hit, s_width, 0.0), axis=1)
            hi = jnp.take(cdf_flat, w * k + kk)
            lo = jnp.where(kk > 0,
                           jnp.take(cdf_flat, w * k
                                    + jnp.maximum(kk - 1, 0)), 0.0)
            return doc_term + alpha * (hi - lo) + u_mass / k

        def mh_step(i, carry_z):
            z_cur, t_cur, q_cur = carry_z
            u_sel, u_pos, u_acc = u[i, :, 0], u[i, :, 1], u[i, :, 2]
            # Branch pick + draw. Doc branch: inverse-CDF over the
            # [B, A] compacted block. Dense branch: bisect the word's
            # stale CDF row. Uniform branch: floor(u*K).
            t_s = u_pos * s_mass
            j = jnp.sum((s_cum < t_s[:, None]).astype(jnp.int32), axis=1)
            j = jnp.minimum(j, a_width - 1)
            k_sparse = jnp.take_along_axis(a_ids, j[:, None], axis=1)[:, 0]
            pos = cdf_lower_bound(cdf_flat, w, u_pos * q_w, k)
            k_dense = jnp.minimum(pos, k - 1)
            k_unif = jnp.minimum((u_pos * k).astype(jnp.int32), k - 1)
            t_sel = u_sel * tot_mass
            k_prop = jnp.where(t_sel < s_mass, k_sparse,
                               jnp.where(t_sel < s_mass + dense_mass,
                                         k_dense, k_unif))
            # Independence-sampler acceptance: pi(k')q(z) / pi(z)q(k').
            # target/proposal of the CURRENT state ride the loop carry
            # (counts are frozen for the token's whole MH cycle, so the
            # carried values are bit-identical to recomputation at half
            # the gather traffic of this gather-bound arm).
            t_p, q_p = target(k_prop), proposal_weight(k_prop)
            ratio = t_p * q_cur / jnp.maximum(t_cur * q_p, 1e-38)
            acc = u_acc < ratio
            return (jnp.where(acc, k_prop, z_cur),
                    jnp.where(acc, t_p, t_cur),
                    jnp.where(acc, q_p, q_cur))

        z_cur, _, _ = jax.lax.fori_loop(
            0, n_mh, mh_step, (zf, target(zf), proposal_weight(zf)))
        z_new = jnp.where(valid, z_cur, z_old)   # padding keeps sentinel

        # Rank-1 exact int32 updates; padding (index K) drops out of
        # bounds. Collisions within the block serialize inside the
        # scatter exactly as the dense delta's row updates do.
        one = jnp.ones_like(z_new)
        n_dk = (n_dk.at[d, z_new].add(one, mode="drop")
                     .at[d, z_old].add(-one, mode="drop"))
        n_wk = (n_wk.at[w, z_new].add(one, mode="drop")
                     .at[w, z_old].add(-one, mode="drop"))
        n_k = (n_k.at[z_new].add(one, mode="drop")
                   .at[z_old].add(-one, mode="drop"))
        return (n_dk, n_wk, n_k, key), z_new

    return block_step


def make_sweep_kernel(*, alpha: float, eta: float, n_vocab: int,
                      k_topics: int, nwk_form: str | None = None,
                      nwk_matmul: bool | None = None,
                      sampler_form: str | None = None,
                      sparse_active: int = 0, sparse_mh: int = 2,
                      sampler: str | None = None):
    """One FULL sweep over blocked tokens with the sampler-form gate
    applied — the shared kernel behind sweep(), the sharded engine's
    per-device sweep, and the dp=1 fast path, so the gate can never
    diverge between engines.

    Returns fn(z, n_dk, n_wk, n_k, key, docs, words, mask) ->
    (z, n_dk, n_wk, n_k, key). The sparse form rebuilds its stale
    proposal tables from the sweep-start counts on every call (table
    freshness is a per-sweep property, independent of how many sweeps
    a dispatch fuses)."""
    form = _resolved_sampler_form(
        sampler_form, k_topics=k_topics,
        pinned=(nwk_form is not None or nwk_matmul is not None
                or sampler is not None))
    if form == "dense":
        block_step = make_block_step(alpha=alpha, eta=eta,
                                     n_vocab=n_vocab, k_topics=k_topics,
                                     nwk_form=nwk_form,
                                     nwk_matmul=nwk_matmul,
                                     sampler=sampler)

        def kernel(z, n_dk, n_wk, n_k, key, docs, words, mask):
            (n_dk, n_wk, n_k, key), z = jax.lax.scan(
                block_step, (n_dk, n_wk, n_k, key),
                (docs, words, mask, z))
            return z, n_dk, n_wk, n_k, key
        return kernel

    a = resolve_sparse_active(k_topics, sparse_active)
    v_eta = n_vocab * eta

    def kernel(z, n_dk, n_wk, n_k, key, docs, words, mask):
        tables = build_sparse_tables(n_dk, n_wk, n_k, eta=eta,
                                     v_eta=v_eta, n_active=a)
        block_step = make_sparse_block_step(
            alpha=alpha, eta=eta, v_eta=v_eta, k_topics=k_topics,
            n_mh=sparse_mh, tables=tables)
        (n_dk, n_wk, n_k, key), z = jax.lax.scan(
            block_step, (n_dk, n_wk, n_k, key), (docs, words, mask, z))
        return z, n_dk, n_wk, n_k, key
    return kernel


def make_block_step(*, alpha: float, eta: float, n_vocab: int,
                    k_topics: int, nwk_matmul: bool | None = None,
                    nwk_form: str | None = None,
                    sampler: str | None = None):
    """The collapsed-Gibbs block sampler shared by the single-device and
    sharded engines — one definition so the documented dp=1 equivalence
    can never silently diverge.

    carry = (n_dk, n_wk, n_k, key); xs = (docs, words, mask, z_old).

    `nwk_form`: force the n_wk count-update form ("scatter" |
    "matmul" | "pallas"); `nwk_matmul` is the legacy bool spelling
    (True = matmul, False = scatter). None picks at trace time via
    `select_nwk_form` — the measured per-backend collision-density gate
    (ONIX_NWK_FORM / ONIX_NWK_MATMUL override for experiments). All
    forms produce bit-identical int32 counts and the same z stream.

    `sampler`: force the categorical draw form ("gumbel" | "race");
    None keeps the measured per-backend pick (gumbel on accelerators,
    race on CPU — docs/PERF.md "exponential race"). Test-only knob: it
    lets CPU tier-1 assert the TPU sampler's math bit-for-bit.
    """
    v_eta = n_vocab * eta
    # Sampler form is picked once at trace time; it is a platform
    # property, not runtime state, so the traced program is static.
    backend = jax.default_backend()
    if sampler is None:
        use_gumbel = backend not in ("cpu",)
    elif sampler in ("gumbel", "race"):
        use_gumbel = sampler == "gumbel"
    else:
        raise ValueError(f"sampler must be gumbel|race, got {sampler!r}")
    import os
    # Env overrides apply only when the caller passed NO explicit form
    # (either spelling) — an explicit nwk_matmul/nwk_form argument must
    # outrank an exported experiment override, or the test arms that
    # pin forms would silently compare a form against itself.
    if nwk_form is None and nwk_matmul is None:
        nwk_form = env_nwk_form()
        if nwk_form is None:
            env = os.environ.get("ONIX_NWK_MATMUL")
            if env in ("0", "1"):
                nwk_matmul = env == "1"

    def block_step(carry, xs):
        n_dk, n_wk, n_k, key = carry
        d, w, m, z_old = xs
        key, skey = jax.random.split(key)
        # n_wk shape is static under trace, so the form choice resolves
        # to ONE compiled path. The auto gate is the measured collision-
        # density crossover table (select_nwk_form / the module comments
        # at _NWK_MATMUL_MIN_DENSITY and _NWK_PALLAS_MIN_DENSITY).
        form = select_nwk_form(backend=backend, block_size=w.shape[0],
                               n_rows=n_wk.shape[0],
                               nwk_matmul=nwk_matmul, nwk_form=nwk_form)
        if form == "matmul" and w.shape[0] >= (1 << 24):
            raise ValueError(
                f"nwk matmul form with block size {w.shape[0]} >= 2^24: "
                "the one-hot matmul's f32 accumulation is no longer "
                "bit-exact at this block size")
        if form == "pallas":
            # Fused sample + count-merge kernel: the SAME skey feeds one
            # noise draw at the reference's [B, K] shape, so the key
            # stream is untouched; sampling and the collision-dense
            # n_wk delta run inside the kernel (pallas_gibbs module doc)
            # and the n_dk scatter stays here (collision-free).
            from onix.models import pallas_gibbs
            shape = (w.shape[0], k_topics)
            if use_gumbel:
                noise = jax.random.gumbel(skey, shape, dtype=jnp.float32)
            else:
                noise = jax.random.uniform(skey, shape, dtype=jnp.float32,
                                           minval=1e-38)
            z_new, d_wk = pallas_gibbs.sample_count_block(
                n_dk[d], n_wk[w], n_k, noise, w, z_old, m,
                alpha=alpha, eta=eta, v_eta=v_eta, k_topics=k_topics,
                n_rows=n_wk.shape[0], use_gumbel=use_gumbel)
            delta = _one_hot(z_new, k_topics) - _one_hot(z_old, k_topics)
            return (n_dk.at[d].add(delta), n_wk + d_wk,
                    n_k + delta.sum(axis=0, dtype=jnp.int32), key), z_new
        oh_old = _one_hot(z_old, k_topics)          # zero row for padding
        ohf = oh_old.astype(jnp.float32)
        # Counts excluding each token's own current assignment.
        ndk = n_dk[d].astype(jnp.float32) - ohf
        nwk = n_wk[w].astype(jnp.float32) - ohf
        nk = n_k.astype(jnp.float32)[None, :] - ohf
        # Categorical sampling — two statistically identical forms,
        # chosen per backend at trace time (docs/PERF.md "exponential
        # race", measured both ways on both platforms):
        #   * CPU: exponential race z = argmax p_k/e_k, e~Exp(1) — the
        #     Gumbel-argmax trick in LINEAR space at one log per
        #     element instead of four; measured 1.75x faster (the
        #     transcendentals dominate on CPU). Per-element products
        #     keep full relative precision — no cumsum, so no
        #     rare-topic rounding (why inverse-CDF was rejected: a
        #     linear f32 cumsum makes transitions to topics below
        #     ~2^-24 of the total exactly impossible).
        #   * TPU: classic log-space Gumbel-argmax — the sweep is
        #     scatter-bound there so extra transcendentals are free,
        #     and log space measured ~5% faster (37.5 vs 35.8 Mtok/s,
        #     scripts/exp_gibbs_sweep.py on v5lite).
        if use_gumbel:
            logp = (jnp.log(ndk + alpha)
                    + jnp.log(jnp.maximum(nwk + eta, 1e-10))
                    - jnp.log(nk + v_eta))
            g = jax.random.gumbel(skey, logp.shape, dtype=jnp.float32)
            z_new = jnp.argmax(logp + g, axis=-1).astype(jnp.int32)
        else:
            p = ((ndk + alpha) * jnp.maximum(nwk + eta, 1e-10)
                 / (nk + v_eta))
            u = jax.random.uniform(skey, p.shape, dtype=jnp.float32,
                                   minval=1e-38)
            z_new = jnp.argmax(p / -jnp.log(u), axis=-1).astype(jnp.int32)
        z_new = jnp.where(m > 0, z_new, z_old)      # padding keeps sentinel
        # Dense one-hot delta rows, NOT per-element scalar scatters:
        # XLA's TPU scatter vectorizes the K lane dimension of row
        # updates, so the dense [B,K] delta runs ~2x faster than the
        # "only 2 of K entries change" rank-1 formulation (measured
        # 35M vs 18M tokens/s at K=20).
        delta = _one_hot(z_new, k_topics) - oh_old  # int32-exact update
        n_dk = n_dk.at[d].add(delta)
        if form == "matmul":
            oh_w = jax.nn.one_hot(w, n_wk.shape[0], dtype=jnp.bfloat16)
            d_wk = jax.lax.dot_general(
                oh_w, delta.astype(jnp.bfloat16),
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            n_wk = n_wk + d_wk.astype(jnp.int32)
        else:
            n_wk = n_wk.at[w].add(delta)
        n_k = n_k + delta.sum(axis=0, dtype=jnp.int32)
        return (n_dk, n_wk, n_k, key), z_new

    return block_step


def sweep(
    state: GibbsState,
    doc_blocks: jax.Array,   # int32 [n_blocks, B]
    word_blocks: jax.Array,  # int32 [n_blocks, B]
    mask_blocks: jax.Array,  # float32 [n_blocks, B]
    *,
    alpha: float,
    eta: float,
    n_vocab: int,
    accumulate,
    nwk_form: str | None = None,
    sampler_form: str | None = None,
    sparse_active: int = 0,
    sparse_mh: int = 2,
) -> GibbsState:
    """One full Gibbs sweep over all token blocks (jit-friendly).

    `accumulate` may be a Python bool OR a traced 0-d array — the fused
    superstep derives it from the sweep counter on device. Both forms
    produce bit-identical updates: the accumulate fold is `acc + a * n`
    with a in {0.0, 1.0} and n >= 0, so a=0 adds an exact +0.0 whether
    or not XLA can constant-fold it away.

    `sampler_form`/`sparse_active`/`sparse_mh` gate the r11 sparse
    O(K_active) arm (make_sweep_kernel); None defers to the measured
    per-backend _SAMPLER_SPARSE_MIN_K gate (dense on unmeasured
    platforms and everywhere below the crossover)."""
    k_topics = state.n_dk.shape[1]
    kernel = make_sweep_kernel(alpha=alpha, eta=eta, n_vocab=n_vocab,
                               k_topics=k_topics, nwk_form=nwk_form,
                               sampler_form=sampler_form,
                               sparse_active=sparse_active,
                               sparse_mh=sparse_mh)
    z, n_dk, n_wk, n_k, key = kernel(
        state.z, state.n_dk, state.n_wk, state.n_k, state.key,
        doc_blocks, word_blocks, mask_blocks)
    do_acc = jnp.asarray(accumulate, jnp.float32)
    return GibbsState(
        z=z, n_dk=n_dk, n_wk=n_wk, n_k=n_k, key=key,
        acc_ndk=state.acc_ndk + do_acc * n_dk.astype(jnp.float32),
        acc_nwk=state.acc_nwk + do_acc * n_wk.astype(jnp.float32),
        n_acc=state.n_acc + jnp.asarray(accumulate, jnp.int32),
    )


# Auto superstep size (config.lda.superstep == 0): 10 sweeps per fused
# program reproduces the old fit loop's every-10-sweeps ll cadence
# (exactly, when checkpointing is off; checkpoint boundaries further
# split segments, making the cadence denser, never sparser) while
# amortizing the per-dispatch RTT 10x (docs/PERF.md measured ~65-70
# ms/dispatch through the device tunnel).
SUPERSTEP_DEFAULT = 10


def superstep(
    state: GibbsState,
    doc_blocks: jax.Array,
    word_blocks: jax.Array,
    mask_blocks: jax.Array,
    *,
    alpha: float,
    eta: float,
    n_vocab: int,
    burn_in: int,
    start_sweep,
    n_steps: int,
    nwk_form: str | None = None,
    sampler_form: str | None = None,
    sparse_active: int = 0,
    sparse_mh: int = 2,
) -> GibbsState:
    """Chain `n_steps` full sweeps inside ONE lax.scan — one dispatch,
    one compiled program per distinct n_steps (static), any start sweep
    (traced). The burn-in accumulate phase is folded into the scan
    carry: sweep start_sweep + i accumulates iff it is past burn_in,
    decided on device, so the posterior-mean sums never leave the chip
    between sweeps. Bit-identical to n_steps sequential sweep()
    dispatches under the same key stream (tests/test_gibbs.py) — for
    the sparse arm too: its stale proposal tables are rebuilt per
    SWEEP inside the fused program (sweep() calls make_sweep_kernel),
    so the chain is independent of the superstep size S."""
    start_sweep = jnp.asarray(start_sweep, jnp.int32)

    def one(st, i):
        return sweep(st, doc_blocks, word_blocks, mask_blocks,
                     alpha=alpha, eta=eta, n_vocab=n_vocab,
                     accumulate=start_sweep + i >= burn_in,
                     nwk_form=nwk_form, sampler_form=sampler_form,
                     sparse_active=sparse_active,
                     sparse_mh=sparse_mh), None

    state, _ = jax.lax.scan(one, state,
                            jnp.arange(n_steps, dtype=jnp.int32))
    return state


def run_fit_segments(state, start: int, segments, *, superstep_fn,
                     initial_ll_fn, checkpoint_every: int, checkpoint_dir,
                     save_fn, fault_sweep: int | None, notify):
    """Drive the fused-superstep fit loop — ONE implementation shared by
    GibbsLDA and ShardedGibbsLDA so segment/ll/checkpoint/fault
    semantics can never diverge between the engines.

    Per segment: one superstep dispatch (the first also evaluates the
    pre-sweep ll on device — no standalone warm-up dispatch), an
    ll_history entry at the boundary, then checkpoint save, fault
    raise, and callback in that order (the order the pre-superstep
    loops used). `superstep_fn(state, start_sweep, n_steps,
    with_initial_ll)` returns (state, ll) or (state, ll0, ll);
    `initial_ll_fn(state)` serves the no-segments case (resume landed
    at/after n_sweeps); `save_fn(state, sweep)` persists a checkpoint;
    `notify(sweep, state, ll)` adapts each engine's public callback
    signature. Returns (state, ll_history)."""
    from onix import checkpoint as ckpt
    from onix.utils import faults

    ll_history: list[tuple[int, float]] = []
    if not segments:
        # Nothing left to sweep: the pre-sweep ll point still belongs
        # in the history.
        ll_history.append((start - 1, float(initial_ll_fn(state))))
    for i, (seg_start, seg_len) in enumerate(segments):
        if i == 0:
            state, ll0, ll = superstep_fn(state, seg_start, seg_len, True)
            ll_history.append((seg_start - 1, float(ll0)))
        else:
            state, ll = superstep_fn(state, seg_start, seg_len, False)
        s = seg_start + seg_len - 1
        ll_history.append((s, float(ll)))
        if (checkpoint_dir is not None and checkpoint_every > 0
                and (s + 1) % checkpoint_every == 0):
            save_fn(state, s)
        if fault_sweep is not None and s == fault_sweep:
            raise ckpt.SimulatedPreemption(
                f"fault injected after sweep {s} "
                f"(checkpoint_dir={checkpoint_dir})")
        # Declarative chaos plan (ONIX_FAULT_PLAN `fit:sweep@N=...`):
        # fires at the first superstep boundary at or after sweep N —
        # the generalized form of the legacy ONIX_FAULT_SWEEP hook.
        faults.fire("fit", "sweep", index=s)
        if notify is not None:
            notify(s, state, ll_history[-1][1])
    return state, ll_history


def plan_segments(start: int, n_sweeps: int, superstep_size: int, *,
                  checkpoint_every: int = 0,
                  fault_sweep: int | None = None,
                  per_sweep: bool = False) -> list[tuple[int, int]]:
    """Split sweeps [start, n_sweeps) into fused superstep segments.

    Every segment ends exactly at a host-interaction boundary — a
    checkpoint sweep ((s+1) % checkpoint_every == 0), the fault-
    injection sweep, the final sweep — or at the superstep cap, so a
    checkpoint can never be demanded mid-superstep and every resume
    point is an exact sweep boundary. `per_sweep` collapses segments to
    length 1 (a per-sweep callback is registered). Returns a list of
    (segment_start, segment_length)."""
    cap = 1 if per_sweep else max(1, int(superstep_size))
    segs: list[tuple[int, int]] = []
    s = start
    while s < n_sweeps:
        end = min(s + cap, n_sweeps)
        if checkpoint_every and checkpoint_every > 0:
            next_ckpt = s + checkpoint_every - (s % checkpoint_every)
            end = min(end, next_ckpt)
        if fault_sweep is not None and s <= fault_sweep < end - 1:
            end = fault_sweep + 1
        segs.append((s, end - s))
        s = end
    return segs


def posterior_estimates(
    state: GibbsState, *, alpha: float, eta: float
) -> tuple[jax.Array, jax.Array]:
    """(theta [D,K], phi_wk [V,K]) from averaged (or instantaneous) counts."""
    use_acc = state.n_acc > 0
    denom = jnp.maximum(state.n_acc.astype(jnp.float32), 1.0)
    ndk = jnp.where(use_acc, state.acc_ndk / denom, state.n_dk.astype(jnp.float32))
    nwk = jnp.where(use_acc, state.acc_nwk / denom, state.n_wk.astype(jnp.float32))
    theta = (ndk + alpha) / (ndk.sum(-1, keepdims=True) + ndk.shape[1] * alpha)
    nk = nwk.sum(axis=0, keepdims=True)
    phi_wk = (nwk + eta) / (nk + nwk.shape[0] * eta)
    return theta, phi_wk


def log_likelihood(
    theta: jax.Array, phi_wk: jax.Array,
    doc_blocks: jax.Array, word_blocks: jax.Array, mask_blocks: jax.Array,
) -> jax.Array:
    """Mean per-token log p(w|d) — the convergence series the reference
    prints to likelihood.dat (SURVEY.md §5.4). Accumulated block by
    block: gathering theta/phi rows for the whole corpus at once
    materializes an [N, K]-padded temp that OOMs HBM past ~10M tokens."""
    def block(carry, xs):
        d, w, m = xs
        p = jnp.sum(theta[d] * phi_wk[w], axis=-1)
        lp = jnp.log(jnp.maximum(p, 1e-30)) * m
        return (carry[0] + lp.sum(), carry[1] + m.sum()), None

    (total, n), _ = jax.lax.scan(
        block, (jnp.float32(0.0), jnp.float32(0.0)),
        (doc_blocks, word_blocks, mask_blocks))
    return total / jnp.maximum(n, 1.0)


# Relative predictive-ll band within which the sparse arm must land on
# the dense arm — the gate-arm parity contract asserted by BOTH
# decision harnesses (bench.gibbs_sweep_sparse and exp_fit_gap
# --k-sweep), shared so the committed decision tables and the per-run
# bench assertion can never measure different contracts.
LL_PARITY_BAND = 0.05


def counts_log_likelihood(
    n_dk: jax.Array, n_wk: jax.Array, n_k: jax.Array,
    doc_blocks: jax.Array, word_blocks: jax.Array, mask_blocks: jax.Array,
    *, alpha: float, eta: float,
) -> float:
    """Mean per-token log p(w|d) straight from instantaneous raw counts
    — the smoothing formula of posterior_estimates without the
    accumulator plumbing, for harnesses that time raw sweep kernels and
    hold (n_dk, n_wk, n_k) rather than a GibbsState."""
    ndk = n_dk.astype(jnp.float32)
    nwk = n_wk.astype(jnp.float32)
    theta = (ndk + alpha) / (ndk.sum(-1, keepdims=True)
                             + ndk.shape[1] * alpha)
    phi = (nwk + eta) / (n_k.astype(jnp.float32)[None, :]
                         + nwk.shape[0] * eta)
    return float(log_likelihood(theta, phi, doc_blocks, word_blocks,
                                mask_blocks))


class GibbsLDA:
    """Host-side driver around the functional kernel.

    Equivalent role to oni-lda-c's `lda estimate` entry point, but runs
    in-process on the accelerator instead of via ssh + mpiexec
    (SURVEY.md §3.1 hot loop #2).
    """

    def __init__(self, config: LDAConfig, n_docs: int, n_vocab: int):
        config.validate()
        self.config = config
        self.n_docs = n_docs
        self.n_vocab = n_vocab
        chains = config.n_chains
        # "auto" defers to the measured per-backend gate at trace time;
        # an explicit config form pins it (select_nwk_form validates).
        form = None if config.nwk_form == "auto" else config.nwk_form
        # Sampler form resolves ONCE here (resolve_sampler: config,
        # then ONIX_SAMPLER_FORM, then nwk-pin deference, then the
        # measured gate) — the RESOLVED value feeds both the compiled
        # programs and the checkpoint fingerprint, so the two can never
        # disagree and a resume across an arm change is refused (the
        # sparse arm is a different chain, not a bit-identical form
        # like nwk).
        self.sampler_form, self.sparse_active, sampler_kw = \
            resolve_sampler(config, k_topics=config.n_topics,
                            nwk_form=form)
        base_sweep = functools.partial(
            sweep, alpha=config.alpha, eta=config.eta, n_vocab=n_vocab,
            nwk_form=form, **sampler_kw)
        base_super = functools.partial(
            superstep, alpha=config.alpha, eta=config.eta,
            n_vocab=n_vocab, burn_in=config.burn_in, nwk_form=form,
            **sampler_kw)
        base_est = functools.partial(
            posterior_estimates, alpha=config.alpha, eta=config.eta)
        # donate_argnums=(0,): the incoming GibbsState's buffers are
        # dead the moment the dispatch returns (every caller rebinds),
        # so XLA reuses them for the output counts instead of copying
        # the [D,K]+[V,K] tables every sweep — the sharded engine has
        # donated since r7 (sharded_gibbs.py); this brings the plain
        # engine level.
        if chains == 1:
            self._sweep = jax.jit(base_sweep,
                                  static_argnames=("accumulate",),
                                  donate_argnums=(0,))
            self._estimates = jax.jit(base_est)
            self._ll = jax.jit(log_likelihood)

            # The fit loop's unit of dispatch: n_steps sweeps chained in
            # one program, with the boundary log-likelihood fused in —
            # the ll gathers run on device right behind the last sweep
            # instead of costing two more dispatches (docs/PERF.md "the
            # gibbs_fit vs sweep-microbench gap", hypotheses A/D).
            # `with_initial_ll` additionally evaluates ll on the
            # INCOMING state (fit's pre-sweep ll_history point), so the
            # whole first segment — initial ll, S sweeps, boundary ll —
            # is ONE dispatch; measured worth ~14% of the CPU fit wall
            # (the standalone ll's sync + dispatch-boundary allocator
            # churn, not its compute).
            def superstep_ll(state, d, w, m, start, n_steps,
                             with_initial_ll=False):
                ll0 = None
                if with_initial_ll:
                    theta0, phi0 = base_est(state)
                    ll0 = log_likelihood(theta0, phi0, d, w, m)
                st = base_super(state, d, w, m, start_sweep=start,
                                n_steps=n_steps)
                theta, phi = base_est(st)
                ll = log_likelihood(theta, phi, d, w, m)
                return ((st, ll0, ll) if with_initial_ll else (st, ll))
        else:
            # vmap over the chain axis of the state; token blocks are
            # shared (broadcast). theta/phi keep a leading chain axis —
            # scoring averages probabilities over it.
            def sweep_chains(state, d, w, m, accumulate):
                return jax.vmap(lambda s: base_sweep(
                    s, d, w, m, accumulate=accumulate))(state)

            def ll_chains(theta, phi_wk, d, w, m):
                return jax.vmap(lambda t, p: log_likelihood(
                    t, p, d, w, m))(theta, phi_wk).mean()

            self._sweep = jax.jit(sweep_chains,
                                  static_argnames=("accumulate",),
                                  donate_argnums=(0,))
            self._estimates = jax.jit(jax.vmap(base_est))
            self._ll = jax.jit(ll_chains)

            def superstep_ll(state, d, w, m, start, n_steps,
                             with_initial_ll=False):
                ll0 = None
                if with_initial_ll:
                    theta0, phi0 = jax.vmap(base_est)(state)
                    ll0 = jax.vmap(lambda t, p: log_likelihood(
                        t, p, d, w, m))(theta0, phi0).mean()
                st = jax.vmap(lambda s: base_super(
                    s, d, w, m, start_sweep=start, n_steps=n_steps))(state)
                theta, phi = jax.vmap(base_est)(st)
                ll = jax.vmap(lambda t, p: log_likelihood(
                    t, p, d, w, m))(theta, phi).mean()
                return ((st, ll0, ll) if with_initial_ll else (st, ll))

        self._superstep = jax.jit(
            superstep_ll, static_argnames=("n_steps", "with_initial_ll"),
            donate_argnums=(0,))

    def prepare(self, corpus: Corpus, shuffle: bool = True):
        if shuffle:
            corpus = corpus.shuffled(self.config.seed)
        block = min(self.config.block_size, max(corpus.n_tokens, 1))
        padded, mask = corpus.padded(block)
        nb = padded.n_tokens // block
        return (
            jnp.asarray(padded.doc_ids.reshape(nb, block)),
            jnp.asarray(padded.word_ids.reshape(nb, block)),
            jnp.asarray(mask.reshape(nb, block)),
        )

    def fit(self, corpus: Corpus, n_sweeps: int | None = None,
            callback=None, checkpoint_dir=None, resume: bool = True,
            fault_inject_sweep: int | None = None) -> dict:
        """Run the fit loop as fused supersteps: sweeps are chained S at
        a time inside one jitted program (`superstep`), with the burn-in
        accumulate fold and the boundary log-likelihood on device — one
        dispatch and one host sync per S sweeps instead of per sweep
        (docs/PERF.md "the gibbs_fit vs sweep-microbench gap"). Segment
        boundaries land exactly on checkpoint/fault/final sweeps
        (`plan_segments`), and a per-sweep `callback` collapses segments
        to single sweeps, so host-visible behavior at every boundary is
        unchanged; the chained loop is bit-identical to sweep-at-a-time
        (tested). Like the sharded engine (since r7), the dispatch
        donates the incoming state's buffers: a `callback` that wants
        to RETAIN anything across sweeps must materialize it
        (np.asarray) inside the callback — holding the state's jax
        arrays past the next dispatch reads deleted buffers.

        Optionally checkpoint every `config.checkpoint_every` sweeps
        into `checkpoint_dir` and resume from the newest matching
        checkpoint there (SURVEY.md §5.3-5.4: resume-on-preemption).
        Resumed runs are bit-identical to uninterrupted ones — the sweep
        is a pure function of the state, and the superstep size is part
        of the checkpoint fingerprint so a resume under a different S is
        refused rather than producing a different ll cadence.

        `fault_inject_sweep` (or env ONIX_FAULT_SWEEP) simulates a
        preemption by raising SimulatedPreemption right after completing
        that sweep — the §5.3 fault-injection hook; a caller that
        retries `fit` resumes from the last checkpoint."""
        import os

        from onix import checkpoint as ckpt

        if fault_inject_sweep is None:
            env = os.environ.get("ONIX_FAULT_SWEEP")
            fault_inject_sweep = int(env) if env else None

        cfg = self.config
        n_sweeps = cfg.n_sweeps if n_sweeps is None else n_sweeps
        S = cfg.superstep or SUPERSTEP_DEFAULT
        docs, words, mask = self.prepare(corpus)
        # The RESOLVED sparse arm joins the identity (an auto gate
        # flipping arms between runs — new measured table, different
        # backend — must refuse the resume, not continue a dense chain
        # with sparse draws); dense contributes nothing, so pre-r11
        # dense checkpoints keep resuming.
        fp = ckpt.fingerprint(cfg, self.n_docs, self.n_vocab,
                              corpus.n_tokens, superstep=S,
                              extra={**sampler_fingerprint(
                                         self.sampler_form,
                                         self.sparse_active,
                                         cfg.sparse_mh),
                                     # Merge form: inert on one device
                                     # (no peers), but the identity rule
                                     # is shared with the sharded engine
                                     # — a merge-form/τ change refuses
                                     # the resume on BOTH engines.
                                     **merge_fingerprint(
                                         cfg.merge_form,
                                         cfg.merge_staleness)})
        # Per-fingerprint subdir: checkpoints of runs with a different
        # identity can neither be adopted nor pruned by this run.
        if checkpoint_dir is not None:
            import pathlib
            checkpoint_dir = pathlib.Path(checkpoint_dir) / fp
        start = 0
        state = None
        if checkpoint_dir is not None and resume:
            saved = ckpt.load_latest(checkpoint_dir)
            if saved is not None and saved.meta.get("fingerprint") == fp:
                state = GibbsState(**{k: jnp.asarray(v)
                                      for k, v in saved.arrays.items()})
                start = saved.sweep + 1
        if state is None:
            if cfg.n_chains == 1:
                state = init_state(docs, words, mask, self.n_docs,
                                   self.n_vocab, cfg.n_topics, cfg.seed)
            else:
                state = init_chains(docs, words, mask, self.n_docs,
                                    self.n_vocab, cfg.n_topics, cfg.seed,
                                    cfg.n_chains)
        segments = plan_segments(
            start, n_sweeps, S,
            checkpoint_every=(cfg.checkpoint_every
                              if checkpoint_dir is not None else 0),
            fault_sweep=fault_inject_sweep,
            per_sweep=callback is not None)
        state, ll_history = run_fit_segments(
            state, start, segments,
            superstep_fn=lambda st, s0, n, init: self._superstep(
                st, docs, words, mask, s0, n_steps=n,
                with_initial_ll=init),
            initial_ll_fn=lambda st: self._ll(*self._estimates(st),
                                              docs, words, mask),
            checkpoint_every=cfg.checkpoint_every,
            checkpoint_dir=checkpoint_dir,
            save_fn=lambda st, s: ckpt.save(
                checkpoint_dir, s,
                {k: np.asarray(v) for k, v in st._asdict().items()},
                {"fingerprint": fp, "engine": "gibbs"}),
            fault_sweep=fault_inject_sweep,
            notify=(None if callback is None
                    else lambda s, st, ll: callback(s, st, ll)))
        theta, phi_wk = self._estimates(state)
        return {
            "state": state,
            # n_chains>1 stacks a leading chain axis: theta [C,D,K],
            # phi_wk [C,V,K]; scoring.score_events averages over it.
            "theta": np.asarray(theta),
            "phi_wk": np.asarray(phi_wk),   # [V,K]; phi[k,v] = phi_wk[v,k]
            "ll_history": ll_history,
        }
