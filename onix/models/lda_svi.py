"""Online variational Bayes (SVI) LDA — the streaming engine.

Covers BASELINE.json configs[4]: "streaming online-VB LDA over
oni-ingest minibatches (incremental scoring)". The reference has no
streaming ML at all — oni-lda-c re-fits from scratch each day
(SURVEY.md §3.1); onix adds the stochastic variational inference of
Hoffman et al. (per PAPERS.md "Stochastic Collapsed Variational Bayesian
Inference for LDA"): each minibatch of ingested events performs a local
E-step on its documents and a natural-gradient step on the global
topic-word variational parameter lambda.

Everything is fixed-shape and jit-compiled: the local E-step is a
`lax.fori_loop` of dense [T,K] updates (K=20 rides the VPU), the global
update a scatter-add into lambda [V,K].
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from onix.config import LDAConfig


class SVIState(NamedTuple):
    lam: jax.Array       # float32 [V, K] topic-word variational parameter
    step: jax.Array      # int32 [] global update counter


class MiniBatch(NamedTuple):
    """A minibatch of token events, documents re-indexed densely [0, Bd).

    Both the token axis and the document axis are padded to static sizes
    so a stream of differently-shaped minibatches hits one compiled
    svi_step (no per-batch retrace). `doc_map[i]` recovers the original
    document (IP) id of local doc i (-1 for padding rows) — gamma rows
    are meaningless without it.

    `mask` carries per-row token MULTIPLICITY, not just validity: the
    deduped streaming path feeds unique (doc, word) pairs with their
    counts as weights, and every E-step/λ-step contribution multiplies
    by mask — so a weight-w row contributes exactly what w identical
    rows would (same math, a fraction of the memory passes). Plain
    callers get 1.0 per real token, 0.0 padding, as before.
    """
    doc_ids: jax.Array   # int32 [T] local-dense doc index per token
    word_ids: jax.Array  # int32 [T]
    mask: jax.Array      # float32 [T] token multiplicity; 0.0 padding
    doc_map: jax.Array   # int32 [Bd] local doc -> original doc id (-1 pad)
    n_docs: int          # Bd (padded) — static


def make_minibatch(doc_ids: np.ndarray, word_ids: np.ndarray,
                   pad_to: int | None = None,
                   pad_docs: int | None = None,
                   weights: np.ndarray | None = None) -> MiniBatch:
    """Densify document ids; pad tokens to `pad_to` and docs to
    `pad_docs`. `weights` (float32 [T]) sets per-row multiplicities for
    the deduped-pair path; default 1.0 per row."""
    uniq, local = np.unique(np.asarray(doc_ids), return_inverse=True)
    t = len(local)
    pad_to = t if pad_to is None else pad_to
    if pad_to < t:
        raise ValueError("pad_to smaller than batch")
    n_docs = pad_docs if pad_docs is not None else len(uniq)
    if n_docs < len(uniq):
        raise ValueError("pad_docs smaller than distinct docs in batch")
    rem = pad_to - t
    doc_map = np.full(n_docs, -1, np.int32)
    doc_map[: len(uniq)] = uniq
    w = (np.ones(t, np.float32) if weights is None
         else np.asarray(weights, np.float32))
    if w.shape[0] != t:
        raise ValueError("weights must match the token count")
    return MiniBatch(
        doc_ids=jnp.asarray(np.concatenate([local.astype(np.int32),
                                            np.zeros(rem, np.int32)])),
        word_ids=jnp.asarray(np.concatenate([np.asarray(word_ids, np.int32),
                                             np.zeros(rem, np.int32)])),
        mask=jnp.asarray(np.concatenate([w, np.zeros(rem, np.float32)])),
        doc_map=jnp.asarray(doc_map),
        n_docs=int(n_docs),
    )


def init_state(n_vocab: int, n_topics: int, seed: int = 0) -> SVIState:
    key = jax.random.PRNGKey(seed)
    lam = jax.random.gamma(key, 100.0, (n_vocab, n_topics)) * 0.01
    return SVIState(lam=lam.astype(jnp.float32), step=jnp.zeros((), jnp.int32))


def _e_log_dirichlet(x: jax.Array, axis: int) -> jax.Array:
    return jax.scipy.special.digamma(x) - jax.scipy.special.digamma(
        x.sum(axis=axis, keepdims=True))


def svi_step(
    state: SVIState,
    batch: MiniBatch,
    corpus_docs: jax.Array,  # D — total docs the stream represents; a
    #                          TRACED scalar so a streaming driver can
    #                          grow its running estimate without retracing
    gamma0: jax.Array | None = None,   # [Bd,K] E-step warm start
    *,
    alpha: float,
    eta: float,
    tau0: float,
    kappa: float,
    local_iters: int,
    batch_docs: int,         # static Bd for gamma shape
    meanchange_tol: float = 0.0,
) -> tuple[SVIState, jax.Array]:
    """One SVI update. Returns (new_state, gamma [Bd,K]) for scoring.

    The local E-step iterates to convergence (mean |Δgamma| under
    `meanchange_tol` — Hoffman's onlineldavb stopping rule) with
    `local_iters` as the hard cap; tol 0 keeps the fixed-count loop.
    Token weights ride `batch.mask` (MiniBatch docstring), so deduped
    (doc, word) pairs update gamma and lambda exactly as their
    multiplicity of identical tokens would. `gamma0` warm-starts the
    fixed point (a streaming driver passes each returning doc's LAST
    gamma — recurring docs then converge in a few iterations instead
    of re-walking from the prior); None keeps the cold start."""
    k = state.lam.shape[1]
    elog_beta = _e_log_dirichlet(state.lam, axis=0)      # [V,K]
    elog_beta_t = elog_beta[batch.word_ids]              # [T,K]

    def e_step(gamma):
        elog_theta = _e_log_dirichlet(gamma, axis=1)     # [Bd,K]
        logp = elog_theta[batch.doc_ids] + elog_beta_t   # [T,K]
        phi = jax.nn.softmax(logp, axis=-1) * batch.mask[:, None]
        return alpha + jnp.zeros_like(gamma).at[batch.doc_ids].add(phi)

    if gamma0 is None:
        gamma0 = jnp.full((batch_docs, k), alpha + 1.0, jnp.float32)
    if meanchange_tol > 0.0:
        def body(carry):
            gamma, _, i = carry
            g2 = e_step(gamma)
            # Per-DOCUMENT convergence, as in Hoffman's rule: iterate
            # until EVERY doc's mean |Δgamma| is under tol. A
            # batch-global mean would let a majority of converged
            # (warm-started, recurring) docs dilute away exactly the
            # still-moving first-seen docs the rarity detector needs
            # converged. Padding rows collapse to alpha after one
            # iteration and stop contributing.
            return g2, jnp.abs(g2 - gamma).mean(axis=1).max(), i + 1

        def cond(carry):
            _, delta, i = carry
            return (i < local_iters) & (delta > meanchange_tol)

        gamma, _, _ = jax.lax.while_loop(
            cond, body, (gamma0, jnp.float32(jnp.inf), jnp.int32(0)))
    else:
        gamma = jax.lax.fori_loop(0, local_iters,
                                  lambda _, g: e_step(g), gamma0)

    # Final responsibilities under converged gamma.
    elog_theta = _e_log_dirichlet(gamma, axis=1)
    phi = jax.nn.softmax(elog_theta[batch.doc_ids] + elog_beta_t, axis=-1)
    phi = phi * batch.mask[:, None]

    # Natural-gradient step on lambda, scaled to the full corpus by the
    # number of REAL documents in the batch (doc_map == -1 rows are padding).
    n_real = (batch.doc_map >= 0).sum().astype(jnp.float32)
    scale = jnp.asarray(corpus_docs, jnp.float32) / jnp.maximum(n_real, 1.0)
    lam_hat = eta + scale * jnp.zeros_like(state.lam).at[batch.word_ids].add(phi)
    rho = (tau0 + state.step.astype(jnp.float32)) ** (-kappa)
    lam = (1.0 - rho) * state.lam + rho * lam_hat
    return SVIState(lam=lam, step=state.step + 1), gamma


def phi_estimate(state: SVIState) -> jax.Array:
    """Posterior-mean topic-word distribution phi_wk [V,K]."""
    return state.lam / state.lam.sum(axis=0, keepdims=True)


class SVILda:
    """Driver for streaming fits over ingest minibatches."""

    def __init__(self, config: LDAConfig, n_vocab: int, corpus_docs: int):
        config.validate()
        self.config = config
        self.n_vocab = n_vocab
        self.corpus_docs = corpus_docs
        self._step = jax.jit(functools.partial(
            svi_step,
            alpha=config.alpha, eta=config.eta,
            tau0=config.svi_tau0, kappa=config.svi_kappa,
            local_iters=config.svi_local_iters,
            meanchange_tol=config.svi_meanchange_tol,
        ), static_argnames=("batch_docs",))

    def init(self) -> SVIState:
        return init_state(self.n_vocab, self.config.n_topics, self.config.seed)

    def update(self, state: SVIState, batch: MiniBatch,
               corpus_docs: float | None = None, gamma0=None):
        """One SVI step. `corpus_docs` overrides the construction-time D —
        streaming callers pass their running distinct-doc estimate (traced,
        so a growing value never retraces). `gamma0` warm-starts the
        E-step (svi_step docstring)."""
        d = float(self.corpus_docs if corpus_docs is None else corpus_docs)
        return self._step(state, batch, d, gamma0, batch_docs=batch.n_docs)
