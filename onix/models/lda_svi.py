"""Online variational Bayes (SVI) LDA — the streaming engine.

Covers BASELINE.json configs[4]: "streaming online-VB LDA over
oni-ingest minibatches (incremental scoring)". The reference has no
streaming ML at all — oni-lda-c re-fits from scratch each day
(SURVEY.md §3.1); onix adds the stochastic variational inference of
Hoffman et al. (per PAPERS.md "Stochastic Collapsed Variational Bayesian
Inference for LDA"): each minibatch of ingested events performs a local
E-step on its documents and a natural-gradient step on the global
topic-word variational parameter lambda.

Everything is fixed-shape and jit-compiled: the local E-step is a
`lax.fori_loop` of dense [T,K] updates (K=20 rides the VPU), the global
update a scatter-add into lambda [V,K].
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from onix.config import LDAConfig


class SVIState(NamedTuple):
    lam: jax.Array       # float32 [V, K] topic-word variational parameter
    step: jax.Array      # int32 [] global update counter


class MiniBatch(NamedTuple):
    """A minibatch of token events, documents re-indexed densely [0, Bd).

    Both the token axis and the document axis are padded to static sizes
    so a stream of differently-shaped minibatches hits one compiled
    svi_step (no per-batch retrace). `doc_map[i]` recovers the original
    document (IP) id of local doc i (-1 for padding rows) — gamma rows
    are meaningless without it.

    `mask` carries per-row token MULTIPLICITY, not just validity: the
    deduped streaming path feeds unique (doc, word) pairs with their
    counts as weights, and every E-step/λ-step contribution multiplies
    by mask — so a weight-w row contributes exactly what w identical
    rows would (same math, a fraction of the memory passes). Plain
    callers get 1.0 per real token, 0.0 padding, as before.
    """
    doc_ids: jax.Array   # int32 [T] local-dense doc index per token
    word_ids: jax.Array  # int32 [T]
    mask: jax.Array      # float32 [T] token multiplicity; 0.0 padding
    doc_map: jax.Array   # int32 [Bd] local doc -> original doc id (-1 pad)
    n_docs: int          # Bd (padded) — static


def minibatch_arrays(doc_ids: np.ndarray, word_ids: np.ndarray,
                     pad_to: int | None = None,
                     pad_docs: int | None = None,
                     weights: np.ndarray | None = None):
    """Host half of make_minibatch: densify + pad, returning plain
    NumPy arrays (doc_ids, word_ids, mask, doc_map, n_docs). The
    streaming superstep stacks S of these before ONE device transfer,
    so the per-batch jnp conversion must be separable."""
    uniq, local = np.unique(np.asarray(doc_ids), return_inverse=True)
    t = len(local)
    pad_to = t if pad_to is None else pad_to
    if pad_to < t:
        raise ValueError("pad_to smaller than batch")
    n_docs = pad_docs if pad_docs is not None else len(uniq)
    if n_docs < len(uniq):
        raise ValueError("pad_docs smaller than distinct docs in batch")
    rem = pad_to - t
    doc_map = np.full(n_docs, -1, np.int32)
    doc_map[: len(uniq)] = uniq
    w = (np.ones(t, np.float32) if weights is None
         else np.asarray(weights, np.float32))
    if w.shape[0] != t:
        raise ValueError("weights must match the token count")
    return (np.concatenate([local.astype(np.int32), np.zeros(rem, np.int32)]),
            np.concatenate([np.asarray(word_ids, np.int32),
                            np.zeros(rem, np.int32)]),
            np.concatenate([w, np.zeros(rem, np.float32)]),
            doc_map, int(n_docs))


def make_minibatch(doc_ids: np.ndarray, word_ids: np.ndarray,
                   pad_to: int | None = None,
                   pad_docs: int | None = None,
                   weights: np.ndarray | None = None) -> MiniBatch:
    """Densify document ids; pad tokens to `pad_to` and docs to
    `pad_docs`. `weights` (float32 [T]) sets per-row multiplicities for
    the deduped-pair path; default 1.0 per row."""
    d, w_ids, m, doc_map, n_docs = minibatch_arrays(
        doc_ids, word_ids, pad_to=pad_to, pad_docs=pad_docs,
        weights=weights)
    return MiniBatch(doc_ids=jnp.asarray(d), word_ids=jnp.asarray(w_ids),
                     mask=jnp.asarray(m), doc_map=jnp.asarray(doc_map),
                     n_docs=n_docs)


def init_state(n_vocab: int, n_topics: int, seed: int = 0) -> SVIState:
    key = jax.random.PRNGKey(seed)
    lam = jax.random.gamma(key, 100.0, (n_vocab, n_topics)) * 0.01
    return SVIState(lam=lam.astype(jnp.float32), step=jnp.zeros((), jnp.int32))


def _e_log_dirichlet(x: jax.Array, axis: int) -> jax.Array:
    return jax.scipy.special.digamma(x) - jax.scipy.special.digamma(
        x.sum(axis=axis, keepdims=True))


# Hoisted to onix/models/compaction.py (r11): the pow2 active-set
# compaction idiom is shared with the sparse Gibbs arm. Re-exported
# under the original name; the E-step below is bit-preserved.
from onix.models.compaction import (compact_front, ladder_index,  # noqa: E402
                                    pow2_ladder as _active_ladder)


def _run_e_step(gamma0, elog_beta_t, doc_ids, mask, *, alpha: float,
                local_iters: int, meanchange_tol: float,
                warm_iters: int, estep_form: str = "svi") -> jax.Array:
    """The local E-step over one minibatch's tokens.

    `estep_form` picks the update family (static):

    * ``"svi"`` — Hoffman's uncollapsed variational update: token
      responsibilities from exp(E[log theta] + E[log beta]) under the
      Dirichlet variational posteriors (digamma terms).
    * ``"scvb0"`` — the SCVB0 zeroth-order collapsed update
      (arxiv 1305.2452): responsibilities directly proportional to
      (N_theta[d,k] + alpha) · phi_hat[w,k] — no digammas, plain
      linear-space counts. The caller passes log(phi_hat) rows as
      `elog_beta_t` and the gamma store carries alpha + N_theta, so
      the same store/scoring machinery (theta = gamma / sum gamma)
      serves both forms.

    Three iteration regimes, chosen statically:

    * ``meanchange_tol == 0`` — the original fixed-count fori_loop.
    * ``warm_iters == 0`` — the r6 per-document while_loop: the FULL
      padded [T,K] block iterates until the slowest doc converges
      (kept bit-identical: existing streaming checkpoints and the
      batch SVI engine ride this path unchanged).
    * ``warm_iters > 0`` — the r10 warm/cold split. Warm-started
      returning docs (the stream's common case) converge within a
      short fixed-trip pass over the full block; the unconverged
      remainder is then COMPACTED — its docs' tokens gathered to the
      front and sliced into the smallest pow2 bucket that fits
      (`_active_ladder`) — and only that block runs the extended
      while_loop. Converged docs' gamma is frozen at its warm-pass
      value (each active doc keeps ALL its tokens, so its update is
      exact); the per-document Hoffman stopping rule is unchanged.
      Extended iterations therefore cost O(T_active · K), not
      O(T · K) — the r6 loop charged every token until the SLOWEST
      doc converged.
    """
    def e_step(gamma, d_ids, eb_t, m):
        if estep_form == "scvb0":
            # Collapsed zeroth-order responsibilities: gamma holds
            # alpha + N_theta (> 0 always), eb_t holds log(phi_hat)
            # rows, so softmax(log gamma + log phi_hat) is exactly the
            # normalized (N_theta + alpha) · phi_hat of SCVB0.
            elog_theta = jnp.log(gamma)                  # [Bd,K]
        else:
            elog_theta = _e_log_dirichlet(gamma, axis=1)  # [Bd,K]
        logp = elog_theta[d_ids] + eb_t                  # [T,K]
        phi = jax.nn.softmax(logp, axis=-1) * m[:, None]
        return alpha + jnp.zeros_like(gamma).at[d_ids].add(phi)

    if meanchange_tol <= 0.0:
        return jax.lax.fori_loop(
            0, local_iters,
            lambda _, g: e_step(g, doc_ids, elog_beta_t, mask), gamma0)

    if warm_iters <= 0:
        def body(carry):
            gamma, _, i = carry
            g2 = e_step(gamma, doc_ids, elog_beta_t, mask)
            # Per-DOCUMENT convergence, as in Hoffman's rule: iterate
            # until EVERY doc's mean |Δgamma| is under tol. A
            # batch-global mean would let a majority of converged
            # (warm-started, recurring) docs dilute away exactly the
            # still-moving first-seen docs the rarity detector needs
            # converged. Padding rows collapse to alpha after one
            # iteration and stop contributing.
            return g2, jnp.abs(g2 - gamma).mean(axis=1).max(), i + 1

        def cond(carry):
            _, delta, i = carry
            return (i < local_iters) & (delta > meanchange_tol)

        gamma, _, _ = jax.lax.while_loop(
            cond, body, (gamma0, jnp.float32(jnp.inf), jnp.int32(0)))
        return gamma

    t = doc_ids.shape[0]
    warm = min(int(warm_iters), int(local_iters))
    rem_iters = int(local_iters) - warm

    def warm_body(_, carry):
        g, _ = carry
        g2 = e_step(g, doc_ids, elog_beta_t, mask)
        return g2, jnp.abs(g2 - g).mean(axis=1)

    gamma, delta_d = jax.lax.fori_loop(
        0, warm, warm_body,
        (gamma0, jnp.full((gamma0.shape[0],), jnp.inf, jnp.float32)))
    if rem_iters <= 0:
        return gamma

    active_d = delta_d > meanchange_tol              # [Bd]
    act_tok = active_d[doc_ids] & (mask > 0.0)       # [T]
    n_act = act_tok.sum()
    # Stable compaction: active docs' tokens to the front, order kept.
    perm = compact_front(act_tok)
    c_doc = doc_ids[perm]
    c_eb = elog_beta_t[perm]
    c_mask = jnp.where(act_tok, mask, 0.0)[perm]

    def make_branch(size):
        d_ids = jax.lax.slice_in_dim(c_doc, 0, size)
        eb_t = jax.lax.slice_in_dim(c_eb, 0, size)
        m = jax.lax.slice_in_dim(c_mask, 0, size)

        def body(carry):
            g, _, i = carry
            g2 = e_step(g, d_ids, eb_t, m)
            # Converged docs stay frozen; active docs' updates are
            # exact (every token of an active doc sits inside the
            # compacted slice — activity is per-doc, and the slice is
            # chosen to cover n_act).
            g2 = jnp.where(active_d[:, None], g2, g)
            delta = jnp.where(active_d,
                              jnp.abs(g2 - g).mean(axis=1), 0.0).max()
            return g2, delta, i + 1

        def cond(carry):
            _, delta, i = carry
            return (i < rem_iters) & (delta > meanchange_tol)

        def branch(g):
            g2, _, _ = jax.lax.while_loop(
                cond, body,
                # n_act == 0 skips the extended phase outright (the
                # init delta fails cond on entry).
                (g, jnp.where(n_act > 0, jnp.float32(jnp.inf),
                              jnp.float32(0.0)), jnp.int32(0)))
            return g2
        return branch

    sizes = _active_ladder(t)
    # Smallest rung that still holds every active token (compaction
    # preserves order, so the first n_act compacted slots are exactly
    # the active tokens).
    idx = ladder_index(n_act, sizes)
    return jax.lax.switch(idx, [make_branch(s) for s in sizes], gamma)


def svi_step(
    state: SVIState,
    batch: MiniBatch,
    corpus_docs: jax.Array,  # D — total docs the stream represents; a
    #                          TRACED scalar so a streaming driver can
    #                          grow its running estimate without retracing
    gamma0: jax.Array | None = None,   # [Bd,K] E-step warm start
    *,
    alpha: float,
    eta: float,
    tau0: float,
    kappa: float,
    local_iters: int,
    batch_docs: int,         # static Bd for gamma shape
    meanchange_tol: float = 0.0,
    warm_iters: int = 0,
    estep_form: str = "svi",
) -> tuple[SVIState, jax.Array]:
    """One SVI update. Returns (new_state, gamma [Bd,K]) for scoring.

    `estep_form` ("svi" | "scvb0", static) picks the local-update
    family (_run_e_step docstring). The scvb0 arm is the SCVB0
    minibatch estimator of arxiv 1305.2452 riding the SAME schedule
    machinery: the lambda step below is unchanged (lambda = eta +
    N_phi, so the natural-gradient averaging IS the SCVB0 online
    average of the expected topic-word counts), with the minibatch
    scaled by documents rather than the paper's tokens — the scale
    the streaming driver already tracks. A different estimator, NOT
    bit-comparable to the svi arm; parity is winner-set discipline
    (tests/test_scvb0.py).

    The local E-step iterates to convergence (mean |Δgamma| under
    `meanchange_tol` — Hoffman's onlineldavb stopping rule) with
    `local_iters` as the hard cap; tol 0 keeps the fixed-count loop,
    and `warm_iters > 0` engages the warm/cold compacted split
    (`_run_e_step` docstring). Token weights ride `batch.mask`
    (MiniBatch docstring), so deduped (doc, word) pairs update gamma
    and lambda exactly as their multiplicity of identical tokens
    would. `gamma0` warm-starts the fixed point (a streaming driver
    passes each returning doc's LAST gamma — recurring docs then
    converge in a few iterations instead of re-walking from the
    prior); None keeps the cold start."""
    k = state.lam.shape[1]
    if estep_form == "scvb0":
        # log phi_hat rows: the collapsed arm's word term (log space so
        # the shared softmax form serves both arms).
        elog_beta = jnp.log(state.lam / state.lam.sum(axis=0,
                                                      keepdims=True))
    else:
        elog_beta = _e_log_dirichlet(state.lam, axis=0)  # [V,K]
    elog_beta_t = elog_beta[batch.word_ids]              # [T,K]

    if gamma0 is None:
        gamma0 = jnp.full((batch_docs, k), alpha + 1.0, jnp.float32)
    gamma = _run_e_step(gamma0, elog_beta_t, batch.doc_ids, batch.mask,
                        alpha=alpha, local_iters=local_iters,
                        meanchange_tol=meanchange_tol,
                        warm_iters=warm_iters, estep_form=estep_form)

    # Final responsibilities under converged gamma.
    if estep_form == "scvb0":
        elog_theta = jnp.log(gamma)
    else:
        elog_theta = _e_log_dirichlet(gamma, axis=1)
    phi = jax.nn.softmax(elog_theta[batch.doc_ids] + elog_beta_t, axis=-1)
    phi = phi * batch.mask[:, None]

    # Natural-gradient step on lambda, scaled to the full corpus by the
    # number of REAL documents in the batch (doc_map == -1 rows are padding).
    n_real = (batch.doc_map >= 0).sum().astype(jnp.float32)
    scale = jnp.asarray(corpus_docs, jnp.float32) / jnp.maximum(n_real, 1.0)
    lam_hat = eta + scale * jnp.zeros_like(state.lam).at[batch.word_ids].add(phi)
    rho = (tau0 + state.step.astype(jnp.float32)) ** (-kappa)
    lam = (1.0 - rho) * state.lam + rho * lam_hat
    return SVIState(lam=lam, step=state.step + 1), gamma


def phi_estimate(state: SVIState) -> jax.Array:
    """Posterior-mean topic-word distribution phi_wk [V,K]."""
    return state.lam / state.lam.sum(axis=0, keepdims=True)


class SuperBatch(NamedTuple):
    """S stacked minibatches sharing one static (T, Bd) shape — the
    unit the streaming superstep consumes. `doc_map` carries indices
    into the superstep's UNION gamma store (not global doc ids): the
    host maps each batch's global doc ids onto the sorted union of all
    docs the S batches touch, so warm starts chain batch-to-batch on
    device without any host round-trip. -1 marks padding doc rows."""
    doc_ids: jax.Array    # int32 [S, T] local-dense doc index per token
    word_ids: jax.Array   # int32 [S, T]
    mask: jax.Array       # float32 [S, T] token multiplicity; 0 padding
    doc_map: jax.Array    # int32 [S, Bd] local doc -> union row (-1 pad)
    n_docs: int           # Bd (padded) — static


def svi_superstep(
    state: SVIState,
    sb: SuperBatch,
    gamma_union: jax.Array,   # [U_pad, K] union warm-start/store rows;
    #                           the LAST row is a never-written dummy
    #                           that padding doc rows gather (alpha+1)
    corpus_docs: jax.Array,   # float32 [S] running-D per batch
    *,
    alpha: float,
    eta: float,
    tau0: float,
    kappa: float,
    local_iters: int,
    batch_docs: int,
    meanchange_tol: float = 0.0,
    warm_iters: int = 0,
    estep_form: str = "svi",
) -> tuple[SVIState, jax.Array, jax.Array]:
    """Chain S minibatch updates (E-step + natural-gradient λ-step +
    incremental scoring) inside ONE jitted program — the streaming
    analog of the r7 Gibbs fit supersteps. Each scan step is the exact
    `svi_step` update followed by the exact per-batch scoring math the
    per-batch path runs (theta rows from the batch's updated gamma,
    phi from the updated lambda, `score_events` over the padded token
    columns), with the union gamma store carrying warm starts across
    the S batches. Per dispatch the host fetches ONE scores block
    [S, T] plus the updated union rows — where the per-batch loop paid
    ~3 dispatch syncs per batch, the superstep pays ~1 per S batches
    (the 70 ms-RTT tunnel regime this collapses is docs/PERF.md's).

    Returns (new_state, updated gamma_union, scores [S, T])."""
    from onix.models.scoring import score_events

    k = state.lam.shape[1]
    dummy = gamma_union.shape[0] - 1

    def step(carry, xs):
        lam, stp, store = carry
        d_ids, w_ids, m, dmu, cdocs = xs
        real = dmu >= 0
        g0 = store[jnp.where(real, dmu, dummy)]
        if estep_form == "scvb0":
            elog_beta = jnp.log(lam / lam.sum(axis=0, keepdims=True))
        else:
            elog_beta = _e_log_dirichlet(lam, axis=0)
        elog_beta_t = elog_beta[w_ids]
        gamma = _run_e_step(g0, elog_beta_t, d_ids, m, alpha=alpha,
                            local_iters=local_iters,
                            meanchange_tol=meanchange_tol,
                            warm_iters=warm_iters,
                            estep_form=estep_form)
        if estep_form == "scvb0":
            elog_theta = jnp.log(gamma)
        else:
            elog_theta = _e_log_dirichlet(gamma, axis=1)
        phi = jax.nn.softmax(elog_theta[d_ids] + elog_beta_t, axis=-1)
        phi = phi * m[:, None]
        n_real = real.sum().astype(jnp.float32)
        scale = cdocs / jnp.maximum(n_real, 1.0)
        lam_hat = eta + scale * jnp.zeros_like(lam).at[w_ids].add(phi)
        rho = (tau0 + stp.astype(jnp.float32)) ** (-kappa)
        lam2 = (1.0 - rho) * lam + rho * lam_hat
        # Padding doc rows scatter nowhere: mode="drop" only drops
        # indices OUT OF BOUNDS (negative indices WRAP — -1 would
        # overwrite the dummy row), so padding maps past the store's
        # end. Real rows land so the NEXT batch's warm start sees
        # them.
        store2 = store.at[jnp.where(real, dmu, store.shape[0])].set(
            gamma, mode="drop")
        # Incremental scoring under the updated model — the same
        # theta/phi construction as the per-batch path (padding doc
        # rows at the uniform prior).
        theta = jnp.where(real[:, None],
                          gamma / gamma.sum(axis=1, keepdims=True),
                          1.0 / k)
        phi_wk = lam2 / lam2.sum(axis=0, keepdims=True)
        scores = score_events(theta, phi_wk, d_ids, w_ids)
        return (lam2, stp + 1, store2), scores

    (lam, stp, store), scores = jax.lax.scan(
        step, (state.lam, state.step, gamma_union),
        (sb.doc_ids, sb.word_ids, sb.mask, sb.doc_map, corpus_docs))
    return SVIState(lam=lam, step=stp), store, scores


class SVILda:
    """Driver for streaming fits over ingest minibatches."""

    def __init__(self, config: LDAConfig, n_vocab: int, corpus_docs: int):
        config.validate()
        self.config = config
        self.n_vocab = n_vocab
        self.corpus_docs = corpus_docs
        warm = max(config.svi_warm_iters, 0)
        # lda.stream_estep gates the local-update family: "svi" (the
        # default, unchanged) or the SCVB0 collapsed minibatch arm
        # (svi_step docstring). Static — one compiled program per form.
        estep = config.stream_estep
        self._step = jax.jit(functools.partial(
            svi_step,
            alpha=config.alpha, eta=config.eta,
            tau0=config.svi_tau0, kappa=config.svi_kappa,
            local_iters=config.svi_local_iters,
            meanchange_tol=config.svi_meanchange_tol,
            warm_iters=warm, estep_form=estep,
        ), static_argnames=("batch_docs",))
        self._superstep = jax.jit(functools.partial(
            svi_superstep,
            alpha=config.alpha, eta=config.eta,
            tau0=config.svi_tau0, kappa=config.svi_kappa,
            local_iters=config.svi_local_iters,
            meanchange_tol=config.svi_meanchange_tol,
            warm_iters=warm, estep_form=estep,
        ), static_argnames=("batch_docs",))

    def init(self) -> SVIState:
        return init_state(self.n_vocab, self.config.n_topics, self.config.seed)

    def update(self, state: SVIState, batch: MiniBatch,
               corpus_docs: float | None = None, gamma0=None):
        """One SVI step. `corpus_docs` overrides the construction-time D —
        streaming callers pass their running distinct-doc estimate (traced,
        so a growing value never retraces). `gamma0` warm-starts the
        E-step (svi_step docstring)."""
        d = float(self.corpus_docs if corpus_docs is None else corpus_docs)
        return self._step(state, batch, d, gamma0, batch_docs=batch.n_docs)

    def update_superstep(self, state: SVIState, sb: SuperBatch,
                         gamma_union, corpus_docs):
        """S chained SVI updates + incremental scoring in one dispatch
        (svi_superstep docstring). `gamma_union` is the [U_pad, K]
        union warm-start store (last row a dummy for padding docs);
        `corpus_docs` the per-batch running-D vector [S]."""
        return self._superstep(state, sb, jnp.asarray(gamma_union),
                               jnp.asarray(corpus_docs, jnp.float32),
                               batch_docs=sb.n_docs)
