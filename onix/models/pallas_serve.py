"""Pallas TPU kernel: one-kernel serving path — fused score +
filter-membership + bottom-M.

The serving hot path was three fused-but-separate XLA stages — the
batched gather/matmul scoring, the r13 feedback membership search
(measured as a 4x tax on the filtered flow path: 147M -> 37M ev/s on
CPU, docs/FEEDBACK_r13_cpu.json), and the chunked bottom-M scan — each
round-tripping the [chunk] candidate scores through HBM between
programs. This module collapses them into ONE `pallas_call` per
request, in the r8 `pallas_gibbs.py` mold (ROADMAP item 3; the
bounded-staleness literature the fit layer builds on — AD-LDA, arxiv
0909.4603; Streaming Gibbs, arxiv 1601.01142 — makes the same
argument: keep hot state resident, defer the global exchange; here the
hot state is the winner buffer and the filter tables).

One grid step per token tile. Per tile (all VMEM-resident):

  1. scoring — mode "dot": the gathered theta[d]/phi[w] rows come in as
     [tile, K] blocks (gathered OUTSIDE the kernel, like r8's count
     rows: Mosaic has no gather lowering) and the kernel takes the
     row-wise product-sum — the exact float ops of
     `scoring.score_events`, so scores are bit-identical to the XLA
     arm. Mode "min2": two pre-gathered score columns, pair-min inside
     (the `table_pair_bottom_k` / streaming flow-tail shape). Mode
     "scores": precomputed scores (the bank gather tail, plain
     bottom_k).
  2. filter membership — the r13 sorted-uint64 filter's four key
     families ride in as their packed (hi, lo) uint32 half columns,
     SENTINEL-padded pow2 (the exact `feedback/filter.py` device
     rendering), resident in VMEM across the whole grid. Membership is
     an exact BRANCHLESS search: the sorted table is swept in
     `_FILTER_SEARCH_TILE`-wide VMEM tiles and each tile answers with
     one lane-parallel compare-reduce (eq-AND-eq, reduce-or). This is
     the membership semantics of `filter._member` to the bit — the
     log2(F) gather-probe bisection itself cannot lower (Mosaic in
     this jax has NO gather rule, see the lowering-rules table), so
     the kernel trades the O(log F) serial probes for O(F/lanes)
     fully-parallel compares against tables that are typically tens of
     entries; the filter-size ladder in bench.py's `feedback_rescore`
     is the decision input for where that trade stops winning. The
     adjustment is the exact `filter.apply_filter` order: boost
     members scale by boost_scale, suppress members go to +inf, BEFORE
     the tol screen.
  3. bottom-M — the per-request winner buffer ([M] scores + [M]
     indices, lexicographically sorted ascending) lives in VMEM across
     every grid step (constant out index map) and is flushed to HBM
     ONCE per request — not once per chunk. Each tile merges by exact
     rank arithmetic: strict lexicographic (score, index) comparisons
     (global indices are unique, so the order is total and every rank
     is distinct), int32 rank sums, and a one-hot select-sum scatter —
     compare/reduce/select ops only, all with Mosaic lowerings. The
     tie rule is `_merge_bottom_k`'s by construction: at equal scores
     the lower global index wins, which is exactly what lexicographic
     rank implements, so winners, scores AND order are bit-identical
     to `_scan_bottom_k` (+inf slots get the -1 index sentinel in the
     same finalize step).

Exactness: scoring is the same f32 ops on the same values; membership
is equality against the same tables; rank sums and the scatter are
int32/select ops (no float accumulation of indices), and the score
scatter moves values by select, never arithmetic. The only float
arithmetic beyond scoring is the boost multiply — the same single f32
op `apply_filter` issues. Interpret mode (the default off-TPU, shared
`ONIX_PALLAS_INTERPRET` override) lowers to plain XLA ops, so tier-1
asserts bit-identity on CPU (tests/test_pallas_serve.py) and the same
code compiles through Mosaic on a real TPU (`tpu`-marked test; queued
rows `fused_serve_tpu` / `bench_fused_serve_tpu` in
docs/TPU_QUEUE.json).

The gate (`select_serve_form`, `serving.serve_form`, ONIX_SERVE_FORM)
resolves through `config.resolve_form_gate` next to
`model_bank.select_bank_form`; `_SERVE_FUSED_MIN_EVENTS` is
DELIBERATELY EMPTY — tpu included — until the queued crossover lands,
so `auto` resolves to "xla" on every backend today and nothing changes
behavior without a measurement. VMEM budget math is in docs/PERF.md
("fused serving kernel").
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from onix.config import resolve_form_gate
from onix.models.scoring import TopK, _empty_topk
from onix.models.pallas_gibbs import _default_interpret

# Token-tile width of the serving grid. 256 rows keeps every per-tile
# temporary comfortably inside VMEM at the budget worked in PERF.md
# (the [M, tile] cross-rank matrix is the big one) while amortizing
# the per-tile merge over enough events.
_SERVE_TILE = 256
# Filter entries compared per VMEM search tile: 2048 entries = 8 KB
# per half column, a [tile, 2048] compare temporary of 2 MB. Tables
# larger than one search tile are swept tile-by-tile (trace-time
# unrolled, branchless) — the "tiled search" arm, exercised in tier-1
# with a 4096-entry filter.
_FILTER_SEARCH_TILE = 2048
# Output rows scattered per select-sum block inside the merge, bounding
# the [block, M + tile] equality temporary.
_SCATTER_BLOCK = 256

# Measured per-backend crossover: events per request above which the
# fused one-kernel path beats the three-stage XLA path. Same
# measured-platforms-only policy as `_NWK_PALLAS_MIN_DENSITY` and
# `_BANK_GATHER_MIN_EVENTS`: DELIBERATELY EMPTY — including "tpu" —
# until the queued rows land (docs/TPU_QUEUE.json `fused_serve_tpu`,
# `bench_fused_serve_tpu`), so serve_form="auto" resolves to "xla"
# everywhere today. CPU gets no entry either way: the interpret-mode
# emulation is a correctness vehicle, never a fast path
# (docs/FUSED_r15_cpu.json records the measured emulation rate).
_SERVE_FUSED_MIN_EVENTS: dict[str, float] = {}


def select_serve_form(form: str, n_events: int,
                      backend: str | None = None) -> str:
    """Resolve the serving-scan form for one request/dispatch.

    Priority (config.resolve_form_gate — the shared chain with
    select_bank_form/select_nwk_form): ONIX_SERVE_FORM env override >
    explicit config form > the measured `_SERVE_FUSED_MIN_EVENTS`
    table for this backend > "xla". Both forms are bit-identical
    (winners, scores, tie order), so this is pure performance."""
    def measured() -> str | None:
        b = backend if backend is not None else jax.default_backend()
        min_events = _SERVE_FUSED_MIN_EVENTS.get(b)
        if min_events is not None and n_events >= min_events:
            return "fused"
        return None

    return resolve_form_gate(gate="serve_form", choices=("xla", "fused"),
                             explicit=form, env_var="ONIX_SERVE_FORM",
                             measured=measured, default="xla")


# ---------------------------------------------------------------------------
# The kernel.
# ---------------------------------------------------------------------------

# Sentinel index base for the empty winner-buffer slots: distinct
# int32 values above any real event index (per-call event counts are
# int32-indexed, far below 2^31 - M), so every (score, index) pair in
# the merge is unique and the rank arithmetic stays a permutation.
# They only ever pair with +inf, and +inf rows finalize to index -1.
def _sentinel_base(max_results: int) -> int:
    return (1 << 31) - max_results


def _lt(sa, ia, sb, ib):
    """Strict lexicographic (score, index) less-than — the total order
    `_merge_bottom_k` + `_finalize_topk` implement (ties keep the
    lower global index)."""
    return (sa < sb) | ((sa == sb) & (ia < ib))


def _member_cols(khi, klo, hi_ref, lo_ref):
    """bool [tile, 1]: (hi, lo) keys present in a sorted sentinel-
    padded (hi, lo) table ref of shape [1, F] — filter._member's
    semantics as a branchless tiled compare-reduce (module doc, item
    2). The all-sentinel (empty) table yields constant False for any
    real key."""
    f = int(hi_ref.shape[1])
    hit = jnp.zeros(khi.shape, jnp.bool_)
    for lo0 in range(0, f, _FILTER_SEARCH_TILE):
        width = min(_FILTER_SEARCH_TILE, f - lo0)
        hi_row = hi_ref[0:1, lo0:lo0 + width]
        lo_row = lo_ref[0:1, lo0:lo0 + width]
        eq = (khi == hi_row) & (klo == lo_row)      # [tile, width]
        hit = hit | jnp.any(eq, axis=1, keepdims=True)
    return hit


def _make_kernel(*, tile, n, max_results, mode, filtered, token_words,
                 use_mask, return_scores):
    """Build the fused kernel body for one static configuration. The
    ref order must match the in_specs/out_specs built in _fused_call."""

    def kernel(*refs):
        it = iter(refs)
        if mode == "dot":
            t_ref, p_ref = next(it), next(it)
        elif mode == "min2":
            sa_ref, sb_ref = next(it), next(it)
        else:                                       # "scores"
            s_ref = next(it)
        m_ref = next(it) if use_mask else None
        if filtered:
            if token_words:
                wa_ref, wb_ref = next(it), next(it)
            else:
                wl_ref = next(it)
            ph_ref, pl_ref = next(it), next(it)
            ws_hi, ws_lo = next(it), next(it)
            wb_hi, wb_lo = next(it), next(it)
            ps_hi, ps_lo = next(it), next(it)
            pb_hi, pb_lo = next(it), next(it)
            scale_ref = next(it)
        tol_ref = next(it)
        best_s_ref, best_i_ref = next(it), next(it)
        ev_ref = next(it) if return_scores else None

        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            # Empty buffer: +inf scores, distinct sentinel indices
            # (see _sentinel_base) so the merge order stays total.
            best_s_ref[:] = jnp.full((max_results, 1), jnp.inf,
                                     jnp.float32)
            best_i_ref[:] = _sentinel_base(max_results) \
                + jax.lax.broadcasted_iota(jnp.int32, (max_results, 1), 0)

        def word_adjust(s, wlo):
            """Token-level word adjustment (streaming tail order):
            HostFilter.apply_word's boost-then-suppress on one score
            column."""
            whi = jnp.zeros_like(wlo)
            boo = _member_cols(whi, wlo, wb_hi, wb_lo)
            s = jnp.where(boo, s * scale_ref[0, 0], s)
            sup = _member_cols(whi, wlo, ws_hi, ws_lo)
            return jnp.where(sup, jnp.inf, s)

        # 1. scores ------------------------------------------------------
        if mode == "dot":
            # The exact ops of scoring.score_events on the same
            # gathered rows: elementwise product, sum over K.
            s = jnp.sum(t_ref[:].astype(jnp.float32)
                        * p_ref[:].astype(jnp.float32),
                        axis=1, keepdims=True)
        elif mode == "min2":
            sa, sb = sa_ref[:], sb_ref[:]
            if filtered and token_words:
                sa = word_adjust(sa, wa_ref[:])
                sb = word_adjust(sb, wb_ref[:])
            s = jnp.minimum(sa, sb)
        else:
            s = s_ref[:]

        # 2. filter membership ------------------------------------------
        if filtered:
            if token_words:
                # Word stage already ran per token; pair stage here —
                # HostFilter.apply_pair's boost-then-suppress.
                boo = _member_cols(ph_ref[:], pl_ref[:], pb_hi, pb_lo)
                s = jnp.where(boo, s * scale_ref[0, 0], s)
                sup = _member_cols(ph_ref[:], pl_ref[:], ps_hi, ps_lo)
                s = jnp.where(sup, jnp.inf, s)
            else:
                # filter.apply_filter's exact order: ONE combined
                # boost where (word | pair members scale once), then
                # one combined suppress where.
                wlo = wl_ref[:]
                whi = jnp.zeros_like(wlo)
                boo = _member_cols(whi, wlo, wb_hi, wb_lo) \
                    | _member_cols(ph_ref[:], pl_ref[:], pb_hi, pb_lo)
                s = jnp.where(boo, s * scale_ref[0, 0], s)
                sup = _member_cols(whi, wlo, ws_hi, ws_lo) \
                    | _member_cols(ph_ref[:], pl_ref[:], ps_hi, ps_lo)
                s = jnp.where(sup, jnp.inf, s)

        if return_scores:
            # Post-filter, pre-screen: the full adjusted score stream
            # (the streaming tail's BatchResult.scores contract).
            ev_ref[:] = s

        # tol screen + tail-pad/mask rejection, the _scan_bottom_k
        # order: score_chunk's (mask & s < tol) then the global-index
        # pad mask.
        idx = i * tile + jax.lax.broadcasted_iota(jnp.int32, (tile, 1), 0)
        valid = idx < n
        if use_mask:
            valid = valid & (m_ref[:] > 0)
        s = jnp.where(valid & (s < tol_ref[0, 0]), s, jnp.inf)

        # 3. bottom-M merge by exact rank arithmetic --------------------
        bs, bi = best_s_ref[:], best_i_ref[:]           # [M, 1] sorted
        ts, ti = s, idx                                 # [tile, 1]
        # cross[k, j] = lt(buffer_k, tile_j); the order is total and
        # strict (indices unique), so lt(tile_j, buffer_k) == ~cross.
        cross = _lt(bs, bi, ts.T, ti.T)                 # [M, tile]
        lt_tt = _lt(ts, ti, ts.T, ti.T)                 # [tile, tile]
        rank_t = jnp.sum(lt_tt.astype(jnp.int32), axis=0,
                         keepdims=True).T               # [tile, 1]
        cross_i = cross.astype(jnp.int32)
        c_t = jnp.sum(cross_i, axis=0, keepdims=True).T  # [tile, 1]
        b_off = tile - jnp.sum(cross_i, axis=1, keepdims=True)  # [M, 1]
        pos_b = jax.lax.broadcasted_iota(jnp.int32, (max_results, 1), 0) \
            + b_off
        pos_t = rank_t + c_t
        pos = jnp.concatenate([pos_b, pos_t], axis=0).T  # [1, M + tile]
        s_row = jnp.concatenate([bs, ts], axis=0).T
        i_row = jnp.concatenate([bi, ti], axis=0).T
        # Select-sum scatter: positions are a permutation of
        # 0..M+tile-1, so each output row matches EXACTLY one
        # candidate; where() moves the value (never inf * 0), the sum
        # collapses the zeros.
        for m0 in range(0, max_results, _SCATTER_BLOCK):
            mb = min(_SCATTER_BLOCK, max_results - m0)
            rows = m0 + jax.lax.broadcasted_iota(jnp.int32, (mb, 1), 0)
            eq = rows == pos                            # [mb, M + tile]
            best_s_ref[m0:m0 + mb] = jnp.sum(
                jnp.where(eq, s_row, 0.0), axis=1, keepdims=True)
            best_i_ref[m0:m0 + mb] = jnp.sum(
                jnp.where(eq, i_row, 0), axis=1, keepdims=True)

    return kernel


def _col(a, dtype=None):
    a = jnp.asarray(a)
    if dtype is not None:
        a = a.astype(dtype)
    return a.reshape(-1, 1)


def _row(a):
    return jnp.asarray(a).reshape(1, -1)


@functools.partial(jax.jit, static_argnames=(
    "mode", "max_results", "token_words", "return_scores", "interpret"))
def _fused_call(ops, mask, word_keys, pair_keys, filt, tol, *, mode,
                max_results, token_words=False, return_scores=False,
                interpret=True):
    """Shared wrapper: pad the event streams to a tile multiple, build
    the spec lists to match _make_kernel's ref order, run the one
    fused program, finalize (+inf slots -> index -1, the
    _finalize_topk contract).

    ops: ("dot": (theta_rows [N,K], phi_rows [N,K])) | ("min2":
    (sa [N], sb [N])) | ("scores": (s [N],)).
    mask: f32 [N] or None. word_keys: uint32 [N] event word lo-half, or
    (wa, wb) token pair under token_words, or None when filt is None.
    pair_keys: (hi, lo) uint32 [N] or None. filt: FilterTables or None
    (the static unfiltered fast path — compiles without any membership
    search)."""
    n = int(ops[0].shape[0])
    filtered = filt is not None
    if n == 0:
        empty = _empty_topk(max_results)
        if return_scores:
            return empty, jnp.zeros((0,), jnp.float32)
        return empty
    tile = min(_SERVE_TILE, max(-(-n // 8) * 8, 8))
    bp = -(-n // tile) * tile
    pad = bp - n

    def padded(a):
        a = jnp.asarray(a)
        return jnp.pad(a, [(0, pad)] + [(0, 0)] * (a.ndim - 1)) \
            if pad else a

    inputs, specs = [], []

    def add_tiled(a, width):
        inputs.append(padded(a))
        specs.append(pl.BlockSpec((tile, width), lambda i: (i, 0)))

    def add_const(a, width):
        inputs.append(a)
        specs.append(pl.BlockSpec((1, width), lambda i: (0, 0)))

    if mode == "dot":
        k = int(ops[0].shape[1])
        add_tiled(ops[0], k)
        add_tiled(ops[1], k)
    elif mode == "min2":
        add_tiled(_col(ops[0], jnp.float32), 1)
        add_tiled(_col(ops[1], jnp.float32), 1)
    elif mode == "scores":
        add_tiled(_col(ops[0], jnp.float32), 1)
    else:
        raise ValueError(f"mode must be dot|min2|scores, got {mode!r}")
    use_mask = mask is not None
    if use_mask:
        add_tiled(_col(mask, jnp.float32), 1)
    if filtered:
        if token_words:
            add_tiled(_col(word_keys[0], jnp.uint32), 1)
            add_tiled(_col(word_keys[1], jnp.uint32), 1)
        else:
            add_tiled(_col(word_keys, jnp.uint32), 1)
        add_tiled(_col(pair_keys[0], jnp.uint32), 1)
        add_tiled(_col(pair_keys[1], jnp.uint32), 1)
        for fam in (filt.word_suppress, filt.word_boost,
                    filt.pair_suppress, filt.pair_boost):
            hi, lo = fam
            add_const(_row(hi), int(hi.shape[-1]))
            add_const(_row(lo), int(lo.shape[-1]))
        add_const(jnp.reshape(jnp.asarray(filt.boost_scale,
                                          jnp.float32), (1, 1)), 1)
    add_const(jnp.reshape(jnp.asarray(tol, jnp.float32), (1, 1)), 1)

    out_specs = [
        # Constant index maps: the winner buffer stays VMEM-resident
        # across the whole grid and flushes to HBM once per request.
        pl.BlockSpec((max_results, 1), lambda i: (0, 0)),
        pl.BlockSpec((max_results, 1), lambda i: (0, 0)),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((max_results, 1), jnp.float32),
        jax.ShapeDtypeStruct((max_results, 1), jnp.int32),
    ]
    if return_scores:
        out_specs.append(pl.BlockSpec((tile, 1), lambda i: (i, 0)))
        out_shape.append(jax.ShapeDtypeStruct((bp, 1), jnp.float32))

    kern = _make_kernel(tile=tile, n=n, max_results=max_results,
                        mode=mode, filtered=filtered,
                        token_words=token_words, use_mask=use_mask,
                        return_scores=return_scores)
    out = pl.pallas_call(kern, grid=(bp // tile,), in_specs=specs,
                         out_specs=out_specs, out_shape=out_shape,
                         interpret=interpret)(*inputs)
    best_s, best_i = out[0][:, 0], out[1][:, 0]
    topk = TopK(scores=best_s,
                indices=jnp.where(jnp.isfinite(best_s), best_i, -1))
    if return_scores:
        return topk, out[2][:n, 0]
    return topk


def _resolve_interpret(interpret):
    return _default_interpret() if interpret is None else bool(interpret)


# ---------------------------------------------------------------------------
# Entry points — one per consumer of the scan machinery.
# ---------------------------------------------------------------------------


def fused_top_suspicious(theta, phi_wk, doc_ids, word_ids, mask,
                         pair_hi=None, pair_lo=None, filt=None, *,
                         tol: float, max_results: int,
                         interpret=None) -> TopK:
    """The fused arm of `scoring.top_suspicious` /
    `rescore.top_suspicious_filtered`: theta/phi rows gather outside
    (Mosaic has no gather rule — the r8 discipline), score + filter +
    bottom-M run in one kernel. filt=None compiles the static
    unfiltered program. Single-estimate tables only (combine chains
    upstream, like the screened variants)."""
    theta = jnp.asarray(theta)
    if theta.ndim != 2:
        raise ValueError("fused serving covers single-estimate tables; "
                         "combine chains upstream")
    rows_t = theta[jnp.asarray(doc_ids)]
    rows_p = jnp.asarray(phi_wk)[jnp.asarray(word_ids)]
    return _fused_call(
        (rows_t, rows_p), mask,
        None if filt is None else jnp.asarray(word_ids),
        None if filt is None else (pair_hi, pair_lo), filt, tol,
        mode="dot", max_results=max_results,
        interpret=_resolve_interpret(interpret))


def fused_table_pair_bottom_k(table_flat, idx_src, idx_dst,
                              word_ids=None, pair_hi=None, pair_lo=None,
                              filt=None, *, tol: float, max_results: int,
                              interpret=None) -> TopK:
    """The fused arm of `table_pair_bottom_k(_filtered)` — the flow
    10^8+-event serving path: the two table gathers run outside, the
    pair-min + filter + bottom-M in one kernel."""
    table_flat = jnp.asarray(table_flat)
    sa = table_flat[jnp.asarray(idx_src)]
    sb = table_flat[jnp.asarray(idx_dst)]
    return _fused_call(
        (sa, sb), None,
        None if filt is None else jnp.asarray(word_ids),
        None if filt is None else (pair_hi, pair_lo), filt, tol,
        mode="min2", max_results=max_results,
        interpret=_resolve_interpret(interpret))


def fused_table_bottom_k(table_flat, idx, word_ids=None, pair_hi=None,
                         pair_lo=None, filt=None, *, tol: float,
                         max_results: int, interpret=None) -> TopK:
    """The fused arm of `table_bottom_k(_filtered)` (dns/proxy)."""
    table_flat = jnp.asarray(table_flat)
    return _fused_call(
        (table_flat[jnp.asarray(idx)],), None,
        None if filt is None else jnp.asarray(word_ids),
        None if filt is None else (pair_hi, pair_lo), filt, tol,
        mode="scores", max_results=max_results,
        interpret=_resolve_interpret(interpret))


def fused_bottom_k_scores(scores, word_ids=None, pair_hi=None,
                          pair_lo=None, filt=None, *, tol: float,
                          max_results: int, interpret=None) -> TopK:
    """Fused filter + bottom-M over precomputed scores — the
    `scoring.bottom_k` shape, and the tail the bank's gather form
    reuses."""
    return _fused_call(
        (jnp.asarray(scores),), None,
        None if filt is None else jnp.asarray(word_ids),
        None if filt is None else (pair_hi, pair_lo), filt, tol,
        mode="scores", max_results=max_results,
        interpret=_resolve_interpret(interpret))


def fused_stream_tail(tok_src, tok_dst, word_src=None, word_dst=None,
                      pair_hi=None, pair_lo=None, filt=None, *,
                      tol: float, max_results: int, interpret=None):
    """The streaming winner-selection tail (flow device layout): the
    host tail's exact op order — per-token word adjustment, the
    src/dst min-reduce, the pair adjustment, tol screen, bottom-M —
    in one kernel, returning (TopK, adjusted event scores). The score
    stream is the f32 twin of the host float64 tail: identical when
    boost_scale is dyadic (the 0.25 default) and no score sits inside
    the one-ulp f32(tol) gap — StreamingScorer documents the
    contract."""
    return _fused_call(
        (jnp.asarray(tok_src), jnp.asarray(tok_dst)), None,
        None if filt is None else (word_src, word_dst),
        None if filt is None else (pair_hi, pair_lo), filt, tol,
        mode="min2", max_results=max_results, token_words=True,
        return_scores=True, interpret=_resolve_interpret(interpret))


# ---------------------------------------------------------------------------
# The model bank's fused kernels (the r12 vmap/gather pair with the
# scan+filter stages replaced by the fused kernel). Request batching,
# residency, refusals, and the filter-row stacking stay in
# model_bank.py — these are drop-in replacements for
# _bank_score_vmap/_bank_score_gather, bit-identical per request.
# ---------------------------------------------------------------------------


def _bank_row_call(rows_t, rows_p, mr, dr, wr, filt_row, tol, *,
                   max_results, interpret):
    """One request row: the bank's word key is the event word id, the
    pair key the packed (doc, word) identity (model_bank.
    _row_filter_adjust's exact key construction). `filt_row` is one
    request's FilterTables slice (leaves [F]) or None."""
    wl = ph = plo = None
    if filt_row is not None:
        wl = wr.astype(jnp.uint32)
        ph, plo = dr.astype(jnp.uint32), wl
    return _fused_call((rows_t, rows_p), mr, wl,
                       None if filt_row is None else (ph, plo),
                       filt_row, tol, mode="dot",
                       max_results=max_results, interpret=interpret)


@functools.partial(jax.jit, static_argnames=("max_results", "interpret"))
def bank_score_vmap_fused(theta_bank, phi_bank, slots, doc_ids, word_ids,
                          mask, tol, filt_rows, *, max_results: int,
                          interpret=True) -> TopK:
    """Fused twin of `_bank_score_vmap`: one lane per request, the
    lane's table slice + row gathers outside, the fused kernel per
    lane. filt_rows=None is the static no-feedback fast path (no
    membership search compiles)."""
    def one(slot, dr, wr, mr, filt_row=None):
        rows_t = theta_bank[slot][dr]
        rows_p = phi_bank[slot][wr]
        return _bank_row_call(rows_t, rows_p, mr, dr, wr, filt_row,
                              tol, max_results=max_results,
                              interpret=interpret)

    if filt_rows is None:
        return jax.vmap(one)(slots, doc_ids, word_ids, mask)
    return jax.vmap(one)(slots, doc_ids, word_ids, mask, filt_rows)


@functools.partial(jax.jit, static_argnames=("max_results", "interpret"))
def bank_score_gather_fused(theta_bank, phi_bank, slots, doc_ids,
                            word_ids, mask, tol, filt_rows, *,
                            max_results: int, interpret=True) -> TopK:
    """Fused twin of `_bank_score_gather`: the tenant-composed flat
    row gathers run as ONE fused stream outside the kernel (the gather
    form's whole point), then the per-request fused kernel scores,
    filters and selects from the gathered rows."""
    b, d_pad, _ = theta_bank.shape
    v_pad = phi_bank.shape[1]
    gd = (slots[:, None] * jnp.int32(d_pad) + doc_ids).reshape(-1)
    gw = (slots[:, None] * jnp.int32(v_pad) + word_ids).reshape(-1)
    rows_t = theta_bank.reshape(b * d_pad, -1)[gd].reshape(
        (*doc_ids.shape, -1))
    rows_p = phi_bank.reshape(b * v_pad, -1)[gw].reshape(
        (*word_ids.shape, -1))

    def one(rt, rp, dr, wr, mr, filt_row=None):
        return _bank_row_call(rt, rp, mr, dr, wr, filt_row, tol,
                              max_results=max_results,
                              interpret=interpret)

    if filt_rows is None:
        return jax.vmap(one)(rows_t, rows_p, doc_ids, word_ids, mask)
    return jax.vmap(one)(rows_t, rows_p, doc_ids, word_ids, mask,
                         filt_rows)
