"""Tenant-axis sharding for the fleet refit (r20; models/fleet_gibbs).

A shape class's stacked arrays carry the tenant lane on axis 0 and the
lanes are mathematically independent, so sharding the fleet over the dp
mesh is pure data parallelism: lane t's sweeps touch only lane t's
counts, the compiled program is collective-free (the bank-shard
discipline — serving/model_bank asserts the same property for scoring),
and a dp=1 mesh degrades to a plain device_put.

The lane count pads to a multiple of the dp extent with DEAD lanes
(mask 0, z at the padding sentinel K, zero keys): a dead lane's counts
stay empty and its outputs are discarded by lane index, so padding for
the mesh can never perturb a live tenant's bits — the same contract
the pow2 token padding already carries inside each lane.

Multi-host fleets compose exactly like the r21 fit fabric: each host
runs the classes whose lanes the dp mesh places on its local devices;
there is no cross-host traffic to schedule because there are no
collectives to stall (parallel/hostfabric.py owns process lifecycle,
not this module).
"""

from __future__ import annotations

import jax
import numpy as np

from onix.parallel.mesh import DP_AXIS

#: The stacked arrays a fleet program consumes, in call order.
LANE_ARRAYS = ("z0", "docs", "words", "mask", "fb_docs", "fb_words",
               "fb_weights", "keys")


def lane_pad(n_lanes: int, n_shards: int) -> int:
    """Dead lanes needed to make the tenant axis divide the dp extent."""
    return (-int(n_lanes)) % max(int(n_shards), 1)


def pad_class_lanes(sc, *, k_topics: int, n_shards: int) -> dict:
    """The class's stacked arrays with the lane axis padded to a
    multiple of `n_shards` (host-side np views; zero-copy when no
    padding is needed)."""
    arrays = {name: getattr(sc, name) for name in LANE_ARRAYS}
    pad = lane_pad(sc.n_lanes, n_shards)
    if pad == 0:
        return arrays
    out = {}
    for name, a in arrays.items():
        dead = np.zeros((pad,) + a.shape[1:], a.dtype)
        if name == "z0":
            dead[:] = k_topics          # padding sentinel: zero one-hot row
        out[name] = np.concatenate([a, dead], axis=0)
    return out


def shard_class(sc, mesh, *, k_topics: int) -> dict:
    """Device-place one shape class's stacked arrays for the fleet
    program: lane axis padded to the mesh's dp extent and sharded over
    DP_AXIS (every other axis replicated-by-slicing, i.e. unsharded).
    With no mesh or a single-device mesh this is the identity — the
    host arrays feed jit directly."""
    if mesh is None or np.prod(list(mesh.shape.values())) <= 1:
        return {name: getattr(sc, name) for name in LANE_ARRAYS}
    dp = mesh.shape[DP_AXIS]
    arrays = pad_class_lanes(sc, k_topics=k_topics, n_shards=dp)
    out = {}
    for name, a in arrays.items():
        sharding = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(
                *([DP_AXIS] + [None] * (a.ndim - 1))))
        out[name] = jax.device_put(a, sharding)
    return out
