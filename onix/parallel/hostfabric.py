"""Multi-process fit fabric: coordinator, heartbeats, worker lifecycle.

ROADMAP item 4's missing half: `mesh.multihost_init` can join a
process-spanning mesh, but nothing drove one. This module does — N
worker PROCESSES (local subprocesses in tier-1, real hosts opt-in by
exporting ONIX_HOSTFABRIC_COORD and launching
`python -m onix.parallel.hostfabric --workdir W --host-id I` per host)
each own `local_devices` devices of one global (dp, mp=1) mesh and run
the UNCHANGED ShardedGibbsLDA superstep program over it, so the τ-ring
merge semantics (sync fold bit-identical, async τ≥1 inside the 5% ll
band) carry from virtual devices to processes with no new math.

The robustness contract (docs/ROBUSTNESS.md "multi-host fit fault
domain"):

- Each worker claims its corpus shard through the mpingest ClaimStore
  ledger and renews the claim lease from its heartbeat thread — shard
  ownership and liveness ride the SAME atomic-JSON file discipline as
  every other ledger in the repo (r9/r19).
- Workers heartbeat `hb/host-<i>.json` (atomic rename) every beat_s;
  the coordinator declares a host dead only when its lease
  (`lease_s` since the last beat) expires — a SIGKILLed worker, a
  worker that took an injected `host:death`, and a worker frozen past
  its own collective watchdog all converge to the same lease-expiry
  signal.
- On death the coordinator SIGKILLs the survivors (they are wedged in
  a collective with a dead peer anyway), quarantines the dead host's
  shard assignment with a sidecar (resilience.quarantine_file +
  ClaimStore.mark_quarantined), and either respawns the SAME topology
  — which resumes every worker from the newest sweep checkpointed
  intact by ALL hosts, bit-identical (sync) / in-band (async) to the
  fault-free run — or, only when rebalance was requested explicitly,
  re-shards the full corpus over the survivors behind a deliberate
  topology + fingerprint bump (checkpoint.claim_topology force=True).
  A topology change is NEVER resumed silently: checkpoint.
  check_topology refuses with a field diff (rc=3 from workers).
- Per-host checkpoint shards: each worker saves the LOCAL rows of the
  dp-sharded state plus the replicated tables through the ordinary
  checkpoint.save discipline into `ckpt/<fp>/host-<i>/`; resume is
  coordinator-decided (checkpoint.latest_common_sweep) so every shard
  restarts at the SAME superstep boundary.
- Collective calls get a bounded deadline + one retry before a worker
  declares a peer dead (`host.collective_deadline`, `host.peer_dead`);
  fault sites `host:death`, `host:merge`, `host:ckpt` ride
  ONIX_FAULT_PLAN pre-mutation like every prior site.

jax is imported lazily: a spawned worker must let the coordinator's
env (JAX_PLATFORMS, XLA_FLAGS device count) reach process start before
any backend is created, and `mesh.multihost_init` selects gloo CPU
collectives before initialize.
"""

from __future__ import annotations

import json
import os
import pathlib
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np

from onix.utils.obs import counters

# State fields sharded over dp on dim 0 (mp=1 fabric); everything else
# in ShardedGibbsState is replicated across the mesh.
_SHARDED_DIM0 = ("z", "n_dk", "keys", "acc_ndk")

_FATAL_RCS = {3: "topology refused", 4: "checkpoint shard load failed",
              5: "shard claim refused"}


class FabricError(RuntimeError):
    """Unrecoverable fabric failure (bad worker exit, restart budget
    exhausted, fabric timeout)."""


class HostDead(FabricError):
    """A host's heartbeat lease expired and the death policy was
    'fail' (or the fabric cannot restart, e.g. externally-launched
    workers)."""


class HostPeerDead(FabricError):
    """Raised inside a WORKER when a collective failed past its
    bounded deadline + retry — the peer is presumed dead; the
    coordinator's lease detection owns recovery."""


# ---------------------------------------------------------------------------
# Shared identity: fingerprint + topology
# ---------------------------------------------------------------------------


def fabric_fingerprint(cfg, n_hosts: int, local_devices: int,
                       n_docs: int, n_vocab: int, n_tokens: int) -> str:
    """The fabric's resume identity — mirrors ShardedGibbsLDA.fit's
    fingerprint (same config hash, mesh shape, layout, resolved
    sampler + merge forms) and adds the HOST split: per-host shards
    written by a 2×1 fabric must refuse a 1×2 fabric even though both
    are a dp=2 mesh, because the shard files hold different row
    ranges. Computed identically by coordinator and workers (both
    resolve forms through the shared lda_gibbs resolvers on the same
    backend)."""
    from onix import checkpoint as ckpt
    from onix.models import lda_gibbs

    n_data = n_hosts * local_devices
    d_local = max(1, -(-n_docs // n_data))
    s_step = cfg.superstep or lda_gibbs.SUPERSTEP_DEFAULT
    nwk_form = None if cfg.nwk_form == "auto" else cfg.nwk_form
    if nwk_form is None:
        nwk_form = lda_gibbs.env_nwk_form()
    sampler_form, sparse_active, _ = lda_gibbs.resolve_sampler(
        cfg, k_topics=cfg.n_topics, nwk_form=nwk_form)
    tau = int(cfg.merge_staleness) if cfg.merge_form == "async" else 0
    extra = {"mesh": [n_data, 1], "layout": 4,
             "hosts": [n_hosts, local_devices],
             **lda_gibbs.sampler_fingerprint(sampler_form, sparse_active,
                                             cfg.sparse_mh),
             **lda_gibbs.merge_fingerprint(cfg.merge_form, tau)}
    return ckpt.fingerprint(cfg, n_data * d_local, n_vocab, n_tokens,
                            extra=extra, superstep=s_step)


def _topology(n_hosts: int, local_devices: int, fp: str) -> dict:
    return {"n_hosts": int(n_hosts), "local_devices": int(local_devices),
            "fingerprint": fp}


# ---------------------------------------------------------------------------
# Workdir layout
# ---------------------------------------------------------------------------


def _spec_path(workdir: pathlib.Path) -> pathlib.Path:
    return workdir / "fabric.json"


def _shard_path(workdir: pathlib.Path, host_id: int) -> pathlib.Path:
    return workdir / "shards" / f"shard-host{host_id}.json"


def _hb_path(workdir: pathlib.Path, host_id: int) -> pathlib.Path:
    return workdir / "hb" / f"host-{host_id}.json"


def _result_path(workdir: pathlib.Path, host_id: int) -> pathlib.Path:
    return workdir / "result" / f"host-{host_id}.npz"


def _atomic_json(path: pathlib.Path, payload: dict) -> None:
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_name(path.name + ".tmp")
    tmp.write_text(json.dumps(payload, indent=2))
    os.replace(tmp, path)


def _load_spec(workdir: pathlib.Path) -> dict:
    return json.loads(_spec_path(workdir).read_text())


def _save_corpus(workdir: pathlib.Path, corpus) -> None:
    tmp = workdir / "corpus.npz.tmp"
    with open(tmp, "wb") as f:
        np.savez(f, doc_ids=corpus.doc_ids, word_ids=corpus.word_ids,
                 n_docs=np.int64(corpus.n_docs),
                 n_vocab=np.int64(corpus.n_vocab))
    os.replace(tmp, workdir / "corpus.npz")


def _load_corpus(workdir: pathlib.Path):
    from onix.corpus import Corpus
    with np.load(workdir / "corpus.npz") as z:
        return Corpus(doc_ids=z["doc_ids"], word_ids=z["word_ids"],
                      n_docs=int(z["n_docs"]), n_vocab=int(z["n_vocab"]))


# ---------------------------------------------------------------------------
# Heartbeats (worker side)
# ---------------------------------------------------------------------------


class HeartbeatWriter:
    """Worker-side heartbeat lease: an atomic-JSON beat every `beat_s`
    from a daemon thread, carrying the fit's progress (sweep, status)
    for the coordinator and the chaos tests. The beat thread ALSO
    renews the worker's shard-claim lease (os.utime on the ClaimStore
    claim file) so shard ownership and liveness expire together."""

    GUARDED_BY = {"sweep": "_lock", "status": "_lock",
                  "_lease_path": "_lock"}

    def __init__(self, path: pathlib.Path, host_id: int, beat_s: float):
        self.path = pathlib.Path(path)
        self.host_id = int(host_id)
        self.beat_s = float(beat_s)
        self._lock = threading.Lock()
        self.sweep = -1
        self.status = "starting"
        self._lease_path: pathlib.Path | None = None
        self._beats = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"hb-host{host_id}")

    def start(self) -> None:
        self._write()
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._write()

    def set_sweep(self, sweep: int) -> None:
        with self._lock:
            self.sweep = int(sweep)
        self._write()

    def set_status(self, status: str) -> None:
        with self._lock:
            self.status = status
        self._write()

    def attach_lease(self, claim_path: pathlib.Path) -> None:
        with self._lock:
            self._lease_path = pathlib.Path(claim_path)

    def _write(self) -> None:
        with self._lock:
            self._beats += 1
            payload = {"host": self.host_id, "pid": os.getpid(),
                       "beats": self._beats, "sweep": self.sweep,
                       "status": self.status, "ts": time.time()}
            lease = self._lease_path
        _atomic_json(self.path, payload)
        if lease is not None:
            try:
                os.utime(lease)
            except OSError:
                pass    # claim rotated (commit/quarantine) — benign

    def _run(self) -> None:
        while not self._stop.wait(self.beat_s):
            self._write()


def _read_heartbeat(path: pathlib.Path) -> dict | None:
    try:
        return json.loads(path.read_text())
    except (OSError, ValueError):
        return None


# ---------------------------------------------------------------------------
# Worker: shard extraction / restoration
# ---------------------------------------------------------------------------


def _local_block(a) -> tuple[np.ndarray, int]:
    """This process's contiguous dim-0 rows of a dp-sharded global
    array, plus the global row offset. Device order is process-major
    (make_mesh over jax.devices()), so the addressable shards form one
    contiguous row range."""
    shards = sorted(a.addressable_shards,
                    key=lambda s: s.index[0].start or 0)
    row0 = shards[0].index[0].start or 0
    return np.concatenate([np.asarray(s.data) for s in shards],
                          axis=0), int(row0)


def _put_from_local(local: np.ndarray, full_dim0: int, mesh, spec,
                    row0: int):
    """Rebuild a global dp-sharded array from this process's LOCAL
    rows (a checkpoint shard). The callback only ever materializes
    addressable blocks; a block outside [row0, row0+rows) means the
    shard was written by a different host slot — refuse."""
    import jax
    from jax.sharding import NamedSharding

    shape = (int(full_dim0),) + tuple(local.shape[1:])

    def cb(idx):
        s0 = idx[0]
        lo = 0 if s0.start is None else s0.start
        hi = shape[0] if s0.stop is None else s0.stop
        if lo < row0 or hi > row0 + local.shape[0]:
            raise RuntimeError(
                f"checkpoint shard covers rows [{row0}, "
                f"{row0 + local.shape[0]}), mesh wants [{lo}, {hi}) — "
                "shard written by a different host slot")
        return local[(slice(lo - row0, hi - row0),) + tuple(idx[1:])]

    return jax.make_array_from_callback(shape, NamedSharding(mesh, spec),
                                        cb)


def _extract_shard(state) -> tuple[dict, int]:
    """Host arrays for this worker's checkpoint shard: local rows of
    the dp-sharded fields, full copies of the replicated ones."""
    arrays, row0 = {}, 0
    for name, val in state._asdict().items():
        if name in _SHARDED_DIM0:
            arrays[name], row0 = _local_block(val)
        else:
            arrays[name] = np.asarray(val)
    return arrays, row0


def _state_from_shard(engine, saved, n_data: int):
    """Rebuild the global device state from one host's checkpoint
    shard (raises RuntimeError when the shard's rows don't cover this
    process's mesh slots)."""
    from jax.sharding import PartitionSpec as P

    from onix.parallel.sharded_gibbs import (ShardedGibbsState,
                                             put_global)
    specs = engine._specs()
    row0 = int(saved.meta["row0"])
    out = {}
    for name, spec in specs.items():
        a = saved.arrays[name]
        if name in _SHARDED_DIM0:
            out[name] = _put_from_local(a, n_data, engine.mesh, spec,
                                        row0)
        else:
            out[name] = put_global(a, engine.mesh, spec or P())
    return ShardedGibbsState(**out)


def _block_with_deadline(out, seconds: float, hb: HeartbeatWriter) -> None:
    """block_until_ready with a hard wall: a collective whose peer
    died never completes, so past the deadline the worker exits
    abruptly (rc 82) and lets the coordinator's lease detection own
    recovery — there is no safe way to unwind a wedged collective
    in-process."""
    import jax

    done = threading.Event()

    def _reap():
        if not done.wait(seconds):
            counters.inc("host.collective_deadline")
            hb.set_status("collective-deadline")
            os._exit(82)

    t = threading.Thread(target=_reap, daemon=True)
    t.start()
    try:
        jax.block_until_ready(out)
    finally:
        done.set()


# ---------------------------------------------------------------------------
# Worker main
# ---------------------------------------------------------------------------


def run_worker(workdir: str | pathlib.Path, host_id: int) -> int:
    """One fabric worker: claim shard, join the mesh, fit with
    per-segment heartbeats + guarded collectives + per-host checkpoint
    shards, write the result shard. Returns a process exit code
    (0 ok; 3 topology refused; 4 shard load failed; 5 claim refused)."""
    workdir = pathlib.Path(workdir)
    spec = _load_spec(workdir)
    hb = HeartbeatWriter(_hb_path(workdir, host_id), host_id,
                         spec["beat_s"])
    hb.start()
    try:
        return _worker_body(workdir, int(host_id), spec, hb)
    finally:
        hb.stop()


def _worker_body(workdir: pathlib.Path, host_id: int, spec: dict,
                 hb: HeartbeatWriter) -> int:
    from onix.ingest.mpingest import ClaimStore

    shard_file = _shard_path(workdir, host_id)
    store = ClaimStore(shard_file.parent, lease_seconds=spec["lease_s"])
    digest = store.try_claim(shard_file)
    if digest is None:
        hb.set_status("claim-refused")
        print(f"hostfabric host {host_id}: shard claim refused "
              f"({shard_file})", file=sys.stderr)
        return 5
    hb.attach_lease(store.dir / f"{digest}.claim")

    coord = os.environ.get("ONIX_HOSTFABRIC_COORD")
    if not coord:
        print("hostfabric worker needs ONIX_HOSTFABRIC_COORD",
              file=sys.stderr)
        return 2
    hb.set_status("init")
    from onix.parallel import mesh as mesh_mod
    mesh_mod.multihost_init(coord, spec["n_hosts"], host_id,
                            init_timeout_s=int(spec.get("init_timeout_s",
                                                        120)))

    from onix import checkpoint as ckpt
    from onix.config import LDAConfig
    cfg = LDAConfig(**spec["lda"])
    corpus = _load_corpus(workdir)
    fp = fabric_fingerprint(cfg, spec["n_hosts"], spec["local_devices"],
                            corpus.n_docs, corpus.n_vocab,
                            corpus.n_tokens)
    try:
        ckpt.check_topology(workdir / "ckpt",
                            _topology(spec["n_hosts"],
                                      spec["local_devices"], fp))
    except ckpt.TopologyMismatch as e:
        hb.set_status("topology-refused")
        print(f"hostfabric host {host_id}: {e}", file=sys.stderr)
        return 3

    from onix.parallel.mesh import make_mesh
    from onix.parallel.sharded_gibbs import ShardedGibbsLDA
    n_data = spec["n_hosts"] * spec["local_devices"]
    mesh = make_mesh(dp=n_data, mp=1)
    engine = ShardedGibbsLDA(cfg, corpus.n_vocab, mesh=mesh)
    sc = engine.prepare(corpus)
    hb.set_status("compile")
    docs, words, mask = engine.device_corpus(sc)

    shard_dir = workdir / "ckpt" / fp / f"host-{host_id}"
    resume_sweep = int(spec.get("resume_sweep", -1))
    state, start = None, 0
    if resume_sweep >= 0:
        saved = ckpt.load_at(shard_dir, resume_sweep)
        if saved is not None and saved.meta.get("fingerprint") == fp:
            try:
                state = _state_from_shard(engine, saved, n_data)
            except RuntimeError as e:
                print(f"hostfabric host {host_id}: {e}", file=sys.stderr)
                state = None
        if state is None:
            hb.set_status("shard-load-failed")
            print(f"hostfabric host {host_id}: cannot resume sweep "
                  f"{resume_sweep} from {shard_dir}", file=sys.stderr)
            return 4
        start = resume_sweep + 1
    if state is None:
        state = engine.init_state(sc)

    from onix.models.lda_gibbs import (SUPERSTEP_DEFAULT, plan_segments,
                                       run_fit_segments)
    from onix.utils import faults, telemetry
    s_step = cfg.superstep or SUPERSTEP_DEFAULT
    ckpt_every = cfg.checkpoint_every or s_step
    n_sweeps = int(spec.get("n_sweeps") or cfg.n_sweeps)
    deadline_s = float(spec.get("collective_deadline_s", 120.0))

    def save_shard(st, sweep):
        mode = faults.fire("host", "ckpt", index=sweep)
        arrays, row0 = _extract_shard(st)
        ckpt.save(shard_dir, sweep, arrays,
                  {"fingerprint": fp, "engine": "hostfabric",
                   "host": host_id, "row0": row0})
        counters.inc("host.ckpt_shards")
        if mode == "torn":
            # Render the mid-save crash: the npz renamed durable, the
            # json never written — latest_common_sweep must skip it.
            (shard_dir / f"ckpt-{sweep:06d}.json").unlink(missing_ok=True)

    def superstep(st, s0, n, with_init):
        try:
            faults.fire("host", "death", index=s0)
        except faults.InjectedFault:
            # Simulated sudden host death: no cleanup, no checkpoint —
            # the coordinator's lease detection absorbs it exactly as
            # it absorbs a real SIGKILL.
            hb.set_status("injected-death")
            os._exit(81)
        hb.set_sweep(s0)
        with telemetry.TRACER.span("host.superstep"):
            err = None
            for _ in range(2):
                try:
                    faults.fire("host", "merge", index=s0)
                    out = engine._superstep_shardmap(
                        st, docs, words, mask, s0, n_steps=n,
                        with_initial_ll=with_init)
                    _block_with_deadline(out, deadline_s, hb)
                    return out
                except RuntimeError as e:   # InjectedFault, XLA errors
                    counters.inc("host.merge_retry")
                    err = e
            counters.inc("host.peer_dead")
            hb.set_status("peer-dead")
            raise HostPeerDead(f"host {host_id}: collective failed "
                               f"twice at sweep {s0}") from err

    hb.set_status("fit")
    segments = plan_segments(start, n_sweeps, s_step,
                             checkpoint_every=ckpt_every)
    state, ll_history = run_fit_segments(
        state, start, segments,
        superstep_fn=superstep,
        initial_ll_fn=lambda st: engine._ll(st, docs, words, mask),
        checkpoint_every=ckpt_every,
        checkpoint_dir=shard_dir,
        save_fn=save_shard,
        fault_sweep=None, notify=None)

    hb.set_status("result")
    _write_result(workdir, host_id, spec, state, sc, ll_history)
    store.commit(digest)
    hb.set_status("done")
    return 0


def _write_result(workdir: pathlib.Path, host_id: int, spec: dict,
                  state, sc, ll_history) -> None:
    """Atomic per-host result shard: every host ships its local doc
    rows; host 0 additionally ships the replicated word tables, the
    doc map, and the ll series (identical on every host)."""
    res = _result_path(workdir, host_id)
    res.parent.mkdir(parents=True, exist_ok=True)
    n_dk, row0 = _local_block(state.n_dk)
    acc_ndk, _ = _local_block(state.acc_ndk)
    payload = {"n_dk": n_dk, "acc_ndk": acc_ndk,
               "row0": np.int64(row0), "n_acc": np.asarray(state.n_acc),
               "n_hosts": np.int64(spec["n_hosts"]),
               "host": np.int64(host_id),
               # This worker's host.* counter snapshot (merge retries,
               # shard saves, ...) — counters live per process, so the
               # coordinator can only surface them in the manifest if
               # the result shard carries them out.
               "host_counters": np.str_(
                   json.dumps(counters.snapshot("host.")))}
    if host_id == 0:
        payload.update(
            n_wk=np.asarray(state.n_wk),
            acc_nwk=np.asarray(state.acc_nwk),
            n_k=np.asarray(state.n_k),
            doc_map=np.asarray(sc.doc_map),
            ll_sweeps=np.asarray([s for s, _ in ll_history], np.int64),
            ll_values=np.asarray([v for _, v in ll_history], np.float64))
    tmp = res.with_name(res.name + ".tmp")
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
    os.replace(tmp, res)


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        description="onix hostfabric worker (one host of a "
                    "multi-process fit)")
    ap.add_argument("--workdir", required=True)
    ap.add_argument("--host-id", type=int, required=True)
    args = ap.parse_args(argv)
    return run_worker(args.workdir, args.host_id)


# ---------------------------------------------------------------------------
# Coordinator
# ---------------------------------------------------------------------------


class _KillWatcher(threading.Thread):
    """Chaos hook: delivers ONE real SIGKILL to a worker's process
    group the moment its heartbeat reports reaching `after_sweep` —
    i.e. mid-superstep, the hardest point to die at."""

    def __init__(self, coord: "FabricCoordinator", host: int,
                 after_sweep: int):
        super().__init__(daemon=True, name="fabric-kill-watcher")
        self.coord = coord
        self.host = host
        self.after_sweep = after_sweep
        self._halt = threading.Event()

    def halt(self) -> None:
        self._halt.set()

    def run(self) -> None:
        while not self._halt.wait(self.coord.beat_s / 4):
            beat = _read_heartbeat(_hb_path(self.coord.workdir, self.host))
            if beat is None or beat.get("sweep", -1) < self.after_sweep:
                continue
            with self.coord._lock:
                if self.coord.kill_delivered:
                    return
                self.coord.kill_delivered = True
                proc = self.coord._procs.get(self.host)
            if proc is not None and proc.poll() is None:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
                counters.inc("host.kill_delivered")
            return


class FabricCoordinator:
    """Spawns, monitors, and (on death) restarts or rebalances the
    worker fleet; assembles the final estimates from the per-host
    result shards. Lives in the CALLING process (tests, scale.py) so
    its `host.*` counters and flight-recorder dumps are visible
    there."""

    GUARDED_BY = {"kill_delivered": "_lock", "deaths": "_lock",
                  "restarts": "_lock", "_procs": "_lock"}

    def __init__(self, corpus, cfg, workdir, *, n_hosts=2,
                 local_devices=1, n_sweeps=None, on_death="restart",
                 max_restarts=2, rebalance=False, lease_s=6.0,
                 beat_s=0.5, collective_deadline_s=120.0,
                 init_timeout_s=120, timeout_s=900.0, kill_plan=None,
                 worker_env=None, spawn=True):
        if on_death not in ("restart", "rebalance", "fail"):
            raise ValueError(f"unknown on_death policy {on_death!r}")
        if n_hosts < 1:
            raise ValueError("n_hosts must be >= 1")
        self.corpus = corpus
        import dataclasses
        # The resume contract needs superstep-boundary checkpoints;
        # default the cadence to one checkpoint per superstep.
        from onix.models.lda_gibbs import SUPERSTEP_DEFAULT
        s_step = cfg.superstep or SUPERSTEP_DEFAULT
        self.cfg = (cfg if cfg.checkpoint_every
                    else dataclasses.replace(cfg, checkpoint_every=s_step))
        self.workdir = pathlib.Path(workdir)
        self.n_hosts = int(n_hosts)
        self.local_devices = int(local_devices)
        self.n_sweeps = int(n_sweeps if n_sweeps is not None
                            else self.cfg.n_sweeps)
        self.on_death = on_death
        self.max_restarts = int(max_restarts)
        self.rebalance = bool(rebalance)
        self.lease_s = float(lease_s)
        self.beat_s = float(beat_s)
        self.collective_deadline_s = float(collective_deadline_s)
        self.init_timeout_s = int(init_timeout_s)
        self.timeout_s = float(timeout_s)
        self.kill_plan = kill_plan
        self.worker_env = worker_env or {}
        self.spawn = bool(spawn)
        if not self.spawn and on_death != "fail":
            # Externally-launched workers cannot be respawned from
            # here; detection still works, recovery is the operator's.
            self.on_death = "fail"
        self._lock = threading.Lock()
        self.kill_delivered = kill_plan is None
        self.deaths: list[dict] = []
        self.restarts = 0
        self.rebalanced = False
        self._procs: dict[int, subprocess.Popen] = {}
        self._generation = 0
        self._resume_sweeps: list[int] = []

    # -- identity ---------------------------------------------------------

    def _fingerprint(self) -> str:
        return fabric_fingerprint(self.cfg, self.n_hosts,
                                  self.local_devices,
                                  self.corpus.n_docs,
                                  self.corpus.n_vocab,
                                  self.corpus.n_tokens)

    # -- lifecycle --------------------------------------------------------

    def run(self) -> dict:
        from onix import checkpoint as ckpt
        from onix.utils import telemetry

        t0 = time.monotonic()
        self.workdir.mkdir(parents=True, exist_ok=True)
        _save_corpus(self.workdir, self.corpus)
        fp = self._fingerprint()
        topo = _topology(self.n_hosts, self.local_devices, fp)
        # Raises TopologyMismatch on a changed-topology resume unless
        # the caller asked for the deliberate rebalance bump.
        stored = ckpt.claim_topology(self.workdir / "ckpt", topo,
                                     force=self.rebalance)
        if stored.get("rebalanced_from"):
            self.rebalanced = True
        gen_walls = []
        with telemetry.TRACER.span("host.fit"):
            while True:
                g0 = time.monotonic()
                fp = self._fingerprint()
                resume = ckpt.latest_common_sweep(
                    self.workdir / "ckpt" / fp, self.n_hosts)
                resume = -1 if resume is None else int(resume)
                self._resume_sweeps.append(resume)
                self._write_generation(fp, resume)
                watcher = None
                if self.spawn:
                    self._spawn_workers()
                    if not self.kill_delivered:
                        watcher = _KillWatcher(self,
                                               self.kill_plan["host"],
                                               self.kill_plan["after_sweep"])
                        watcher.start()
                try:
                    dead = self._monitor()
                finally:
                    if watcher is not None:
                        watcher.halt()
                gen_walls.append(round(time.monotonic() - g0, 3))
                if dead is None:
                    break
                self._handle_death(dead)
                with self._lock:
                    self.restarts += 1
                    n_restarts = self.restarts
                if self.on_death == "fail":
                    raise HostDead(
                        f"host {dead} heartbeat lease expired "
                        f"(generation {self._generation})")
                if n_restarts > self.max_restarts:
                    raise FabricError(
                        f"restart budget exhausted "
                        f"({self.max_restarts}) after host {dead} died")
                if self.on_death == "rebalance":
                    self.n_hosts -= 1
                    if self.n_hosts < 1:
                        raise FabricError("no surviving hosts to "
                                          "rebalance onto")
                    counters.inc("host.rebalance")
                    self.rebalanced = True
                    fp = self._fingerprint()
                    ckpt.claim_topology(
                        self.workdir / "ckpt",
                        _topology(self.n_hosts, self.local_devices, fp),
                        force=True)
                else:
                    counters.inc("host.restarts")
                self._generation += 1
            theta, phi_wk, ll_history = self._assemble()
        manifest = {
            "topology": _topology(self.n_hosts, self.local_devices,
                                  self._fingerprint()),
            "merge_form": self.cfg.merge_form,
            "merge_staleness": (self.cfg.merge_staleness
                                if self.cfg.merge_form == "async" else 0),
            "n_sweeps": self.n_sweeps,
            "generations": self._generation + 1,
            "deaths": list(self.deaths),
            "restarts": self.restarts,
            "rebalanced": self.rebalanced,
            "resume_sweeps": list(self._resume_sweeps),
            # Coordinator-side host.* counters (death detection,
            # quarantine, restarts) merged with the final generation's
            # worker-side ones (merge retries, shard saves) carried out
            # through the result shards — counters are per process.
            "counters": _merge_counters(
                counters.snapshot("host."),
                getattr(self, "_worker_counters", {})),
            "walls": {"total_s": round(time.monotonic() - t0, 3),
                      "generations_s": gen_walls},
        }
        _atomic_json(self.workdir / "manifest.json", manifest)
        return {"theta": theta, "phi_wk": phi_wk,
                "ll_history": ll_history, "manifest": manifest}

    def _write_generation(self, fp: str, resume_sweep: int) -> None:
        import dataclasses
        for i in range(self.n_hosts):
            _atomic_json(_shard_path(self.workdir, i),
                         {"host": i, "n_hosts": self.n_hosts,
                          "generation": self._generation,
                          "fingerprint": fp,
                          "rebalanced": self.rebalanced})
        if self.spawn:
            for res in self.workdir.glob("result/host-*.npz"):
                res.unlink(missing_ok=True)
        _atomic_json(_spec_path(self.workdir), {
            "n_hosts": self.n_hosts,
            "local_devices": self.local_devices,
            "lda": dataclasses.asdict(self.cfg),
            "n_sweeps": self.n_sweeps,
            "resume_sweep": resume_sweep,
            "lease_s": self.lease_s,
            "beat_s": self.beat_s,
            "collective_deadline_s": self.collective_deadline_s,
            "init_timeout_s": self.init_timeout_s,
            "generation": self._generation,
        })

    def _spawn_workers(self) -> None:
        import onix
        port = _free_port()
        root = pathlib.Path(onix.__file__).resolve().parents[1]
        (self.workdir / "log").mkdir(exist_ok=True)
        procs = {}
        worker_platform = os.environ.get("ONIX_FABRIC_WORKER_PLATFORM")
        tpu_port0 = _free_port() if worker_platform == "tpu" else 0
        for i in range(self.n_hosts):
            env = dict(os.environ)
            env.update(self.worker_env.get(i, {}))
            if worker_platform == "tpu":
                # Operator-gated TPU split: each worker owns
                # local_devices chips of THIS host via the documented
                # single-host multi-process envs. The coordinator must
                # not hold the TPU itself (run it under
                # JAX_PLATFORMS=cpu) — libtpu chips are exclusive.
                env["JAX_PLATFORMS"] = "tpu"
                env.update(_tpu_split_env(i, self.n_hosts,
                                          self.local_devices, tpu_port0))
            else:
                # Default: CPU workers with gloo collectives — safe on
                # any machine, and the tier-1 chaos surface.
                env["JAX_PLATFORMS"] = (worker_platform
                                        or env.get("JAX_PLATFORMS")
                                        or "cpu")
                env["XLA_FLAGS"] = _xla_flags_with_device_count(
                    env.get("XLA_FLAGS"), self.local_devices)
            env["ONIX_HOSTFABRIC_COORD"] = f"127.0.0.1:{port}"
            env["PYTHONPATH"] = (str(root) + os.pathsep
                                 + env.get("PYTHONPATH", ""))
            log = open(self.workdir / "log" / f"host-{i}.log", "ab")
            try:
                proc = subprocess.Popen(
                    [sys.executable, "-m", "onix.parallel.hostfabric",
                     "--workdir", str(self.workdir), "--host-id", str(i)],
                    stdout=log, stderr=subprocess.STDOUT, env=env,
                    start_new_session=True)
            finally:
                log.close()
            procs[i] = proc
        with self._lock:
            self._procs = procs

    def _monitor(self) -> int | None:
        """Poll heartbeats + worker exits until the generation either
        completes (returns None) or a host's lease expires (returns
        the dead host id). Fatal worker exit codes raise."""
        from onix import checkpoint as ckpt

        spawn_ts = time.time()
        deadline = time.monotonic() + self.timeout_s
        beats_seen: dict[int, int] = {}
        poll_s = min(self.beat_s, 0.25)
        while True:
            time.sleep(poll_s)
            if time.monotonic() > deadline:
                self._kill_all()
                raise FabricError(
                    f"fabric timed out after {self.timeout_s}s "
                    f"(generation {self._generation})")
            with self._lock:
                procs = dict(self._procs)
            if self.spawn:
                rcs = {i: p.poll() for i, p in procs.items()}
            else:
                # Externally-launched workers: a result shard present
                # is the only success signal the coordinator can see.
                rcs = {i: (0 if _result_path(self.workdir, i).exists()
                           else None) for i in range(self.n_hosts)}
            for i, rc in rcs.items():
                if rc in _FATAL_RCS:
                    self._kill_all()
                    tail = self._log_tail(i)
                    if rc == 3:
                        raise ckpt.TopologyMismatch(
                            f"worker {i} refused the topology:\n{tail}")
                    raise FabricError(f"worker {i} failed "
                                      f"({_FATAL_RCS[rc]}):\n{tail}")
            if all(rc == 0 for rc in rcs.values()):
                return None
            now = time.time()
            for i in (procs if self.spawn else range(self.n_hosts)):
                if rcs.get(i) == 0:
                    continue        # finished cleanly — never "dead"
                hb_path = _hb_path(self.workdir, i)
                try:
                    last = max(hb_path.stat().st_mtime, spawn_ts)
                except OSError:
                    last = spawn_ts
                if now - last > self.lease_s:
                    return i
                beat = _read_heartbeat(hb_path)
                if beat and beat.get("beats", 0) > beats_seen.get(i, 0):
                    beats_seen[i] = beat["beats"]
                    counters.inc("host.heartbeats")

    def _handle_death(self, dead: int) -> None:
        from onix.ingest.mpingest import ClaimStore, _digest
        from onix.utils import resilience, telemetry

        beat = _read_heartbeat(_hb_path(self.workdir, dead)) or {}
        with self._lock:
            self.deaths.append({"host": dead,
                                "generation": self._generation,
                                "last_sweep": beat.get("sweep", -1),
                                "last_status": beat.get("status")})
        counters.inc("host.death_detected")
        telemetry.RECORDER.dump(
            "host-death",
            extra={"host": dead, "generation": self._generation,
                   "last_beat": beat})
        self._kill_all()
        # Quarantine the dead incarnation's shard assignment: the
        # ledger marker pins that exact claim signature dead-lettered;
        # the sidecar + moved file keep the evidence. The NEXT
        # generation rewrites the shard file (fresh mtime → fresh
        # claimable digest), mirroring mpingest's re-delivery rule.
        shard_file = _shard_path(self.workdir, dead)
        store = ClaimStore(shard_file.parent,
                           lease_seconds=self.lease_s)
        sig = None
        try:
            digest, sig = _digest(shard_file)
            store.mark_quarantined(
                digest, {"host": dead, "reason": "heartbeat-lease-expired",
                         "generation": self._generation,
                         "path": str(shard_file)})
        except FileNotFoundError:
            digest = None
        resilience.quarantine_file(
            shard_file, self.workdir / "quarantine",
            error=f"host {dead} heartbeat lease expired mid-fit "
                  f"(last status {beat.get('status')!r}, sweep "
                  f"{beat.get('sweep', -1)})",
            attempts=self.restarts + 1,
            sig=[digest] if digest else None)
        counters.inc("host.quarantined")

    def _kill_all(self) -> None:
        if not self.spawn:
            return
        with self._lock:
            procs = dict(self._procs)
        for proc in procs.values():
            if proc.poll() is None:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
        for proc in procs.values():
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                pass

    def _log_tail(self, host: int, lines: int = 25) -> str:
        try:
            text = (self.workdir / "log" / f"host-{host}.log"
                    ).read_text(errors="replace")
        except OSError:
            return "<no log>"
        return "\n".join(text.splitlines()[-lines:])

    # -- result assembly --------------------------------------------------

    def _assemble(self):
        parts = []
        self._worker_counters: dict[str, int] = {}
        for i in range(self.n_hosts):
            with np.load(_result_path(self.workdir, i)) as z:
                parts.append({k: z[k] for k in z.files})
            raw = parts[-1].pop("host_counters", None)
            if raw is not None:
                for k, v in json.loads(str(raw)).items():
                    self._worker_counters[k] = \
                        self._worker_counters.get(k, 0) + int(v)
        for i, part in enumerate(parts):
            if int(part["n_hosts"]) != self.n_hosts:
                raise FabricError(
                    f"result shard {i} written for a "
                    f"{int(part['n_hosts'])}-host fleet, expected "
                    f"{self.n_hosts}")
        parts.sort(key=lambda p: int(p["row0"]))
        n_dk = np.concatenate([p["n_dk"] for p in parts], axis=0)
        acc_ndk = np.concatenate([p["acc_ndk"] for p in parts], axis=0)
        head = next(p for p in parts if int(p["host"]) == 0)
        theta, phi_wk = _assemble_estimates(
            self.cfg, self.corpus.n_vocab, self.corpus.n_docs,
            head["doc_map"], int(head["n_acc"]), n_dk, acc_ndk,
            head["n_wk"], head["acc_nwk"])
        ll_history = list(zip((int(s) for s in head["ll_sweeps"]),
                              (float(v) for v in head["ll_values"])))
        return theta, phi_wk, ll_history


def _assemble_estimates(cfg, n_vocab: int, n_docs: int, doc_map,
                        n_acc: int, n_dk, acc_ndk, n_wk, acc_nwk):
    """ShardedGibbsLDA.estimates' exact math over host arrays gathered
    from the result shards (the coordinator never builds a device
    state)."""
    from onix.parallel.sharded_gibbs import chunked_to_global_nwk

    use_acc = n_acc > 0
    denom = max(float(n_acc), 1.0)
    ndk_s = acc_ndk / denom if use_acc else n_dk.astype(np.float64)
    nwk_c = acc_nwk / denom if use_acc else n_wk.astype(np.float64)
    n_chains = ndk_s.shape[1]
    valid = doc_map >= 0
    thetas, phis = [], []
    for ch in range(n_chains):
        nwk = chunked_to_global_nwk(nwk_c[:, ch], n_vocab)
        ndk = np.zeros((n_docs, cfg.n_topics))
        ndk[doc_map[valid]] = ndk_s[:, ch][valid]
        thetas.append((ndk + cfg.alpha)
                      / (ndk.sum(-1, keepdims=True)
                         + cfg.n_topics * cfg.alpha))
        phis.append((nwk + cfg.eta) / (nwk.sum(0, keepdims=True)
                                       + n_vocab * cfg.eta))
    theta = np.stack(thetas).astype(np.float32)
    phi_wk = np.stack(phis).astype(np.float32)
    if n_chains == 1:
        return theta[0], phi_wk[0]
    return theta, phi_wk


def _merge_counters(coord: dict, workers: dict) -> dict:
    """Coordinator and worker processes increment DISJOINT host.*
    counters, but sum defensively in case a name ever lands on both."""
    out = dict(coord)
    for k, v in workers.items():
        out[k] = out.get(k, 0) + int(v)
    return out


def _xla_flags_with_device_count(base: str | None, n: int) -> str:
    flags = [f for f in (base or "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    flags.append(f"--xla_force_host_platform_device_count={n}")
    return " ".join(flags)


def _tpu_split_env(host_id: int, n_hosts: int, local_devices: int,
                   tpu_port0: int) -> dict[str, str]:
    """Per-worker env for the documented single-host multi-process TPU
    split: each worker sees its own `local_devices` chips and the
    runtime's own process mesh (TPU_PROCESS_ADDRESSES / PORT / task id)
    is wired alongside jax.distributed. Topology-shaped bounds vars
    (TPU_PROCESS_BOUNDS et al.) are hardware-specific; operators set
    them through `worker_env` when their slice needs them."""
    chips = range(host_id * local_devices, (host_id + 1) * local_devices)
    addresses = ",".join(f"localhost:{tpu_port0 + i}"
                         for i in range(n_hosts))
    return {
        "TPU_VISIBLE_DEVICES": ",".join(str(c) for c in chips),
        "TPU_PROCESS_ADDRESSES": addresses,
        "TPU_PROCESS_PORT": str(tpu_port0 + host_id),
        "CLOUD_TPU_TASK_ID": str(host_id),
    }


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def run_fit(corpus, cfg, workdir, **kwargs) -> dict:
    """Run one multi-host fit end-to-end; returns
    {"theta", "phi_wk", "ll_history", "manifest"} — the same estimate
    payload ShardedGibbsLDA.fit yields, assembled from the per-host
    result shards. See FabricCoordinator for the keyword surface
    (n_hosts, local_devices, on_death, rebalance, kill_plan, ...)."""
    return FabricCoordinator(corpus, cfg, workdir, **kwargs).run()


if __name__ == "__main__":
    sys.exit(main())
