"""Doc- and vocabulary-sharded collapsed Gibbs over a device mesh.

This is the TPU-native rendering of oni-lda-c's one true parallelism
(SURVEY.md §2.2): MPI ranks each own a shard of documents, run the local
sampler, and allreduce the K×V topic-word sufficient statistics every
iteration. Here:

- documents (and their tokens) are sharded over the **data axes** — a
  single-slice ``dp`` axis, or ``(dcn, dp)`` on a multislice mesh where
  the outer axis crosses slices over DCN (SURVEY.md §2.3);
- the vocabulary is optionally sharded over the ``mp`` axis (SURVEY.md
  §5.7 — the honest "tensor" axis of LDA, for K×V matrices that outgrow
  one chip's HBM): word w lives on mp shard ``w % mp`` with local row
  ``w // mp``, and each device holds only the tokens whose words fall in
  its chunk. Hashing words round-robin over chunks balances Zipf
  hotspots without a frequency-aware partitioner;
- each device sweeps its local token blocks against its local count
  replicas (stale w.r.t. other shards within a sweep — the same
  staleness the reference accepts between MPI reductions);
- at sweep end the count *deltas* are `psum`'d and folded in, replacing
  MPI_Reduce + MPI_Bcast with XLA collectives (BASELINE.json north star
  names this exact mapping): topic-word chunk deltas reduce over the
  data axes (ICI within a slice, DCN across), doc-topic deltas reduce
  over mp, and topic totals over both;
- `lda.merge_form = "async"` (r14) swaps the full-barrier fold for the
  AD-LDA-style bounded-staleness exchange (arxiv 0909.4603; quality
  argument arxiv 1601.01142): each shard's count view carries its OWN
  updates fresh while peers' psum'd deltas ride a τ-deep FIFO
  (`ring_push`) and fold in exactly `lda.merge_staleness` merge
  windows late — so the collective issued at window t no longer gates
  the sampling of windows t+1..t+τ and XLA overlaps it with compute
  instead of stalling the superstep at every barrier. All pending
  deltas flush at the fused-superstep boundary, so boundary counts
  (checkpoints, the boundary ll, the accumulators) are EXACT global
  counts in both forms, and τ=0 degenerates to a program whose count
  arithmetic is bit-identical to the synchronous fold (int32 adds are
  exact and commutative; asserted in tests/test_merge_async.py).

Equivalence: with one device this is bit-identical in distribution to
the single-device engine; tests assert count invariants and topic
recovery on a virtual 8-device CPU mesh (SURVEY.md §4.3) for dp-only,
dp×mp, and dcn×dp×mp meshes.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

try:                        # newer jax exposes shard_map at top level
    _shard_map = jax.shard_map
except AttributeError:      # older (≤0.4.37): the experimental home
    from jax.experimental.shard_map import shard_map as _shard_map

# The replication-check kwarg was renamed across jax versions
# (check_rep in the experimental shard_map, check_vma at the top level).
import inspect as _inspect

_SHARD_MAP_CHECK_KW = (
    "check_vma"
    if "check_vma" in _inspect.signature(_shard_map).parameters
    else "check_rep")

if hasattr(jax.lax, "pcast"):
    _pcast = jax.lax.pcast
else:
    # Older jax has no varying-type system: every value inside
    # shard_map is implicitly device-varying, so the cast is identity.
    def _pcast(x, axis_name, *, to="varying"):
        return x

from onix.config import LDAConfig
from onix.corpus import Corpus
from onix.models import lda_gibbs
from onix.parallel.mesh import MP_AXIS, data_axes_of, make_mesh


class ShardedCorpus(NamedTuple):
    """Host-prepared, shard-major corpus layout.

    Documents are partitioned into `n_data` balanced groups; each
    group's tokens are split over `n_mp` vocabulary chunks (bucket of
    token t = word % n_mp) and every (data, mp) bucket is padded to the
    same [n_blocks, block] shape. Word ids inside the buckets are LOCAL
    chunk rows (word // n_mp). `doc_map[p, i]` is the global doc id of
    data-shard p's local doc i (-1 padding).
    """

    doc_blocks: np.ndarray    # int32 [P, M, nb, B] local doc ids
    word_blocks: np.ndarray   # int32 [P, M, nb, B] local (chunk) word ids
    mask_blocks: np.ndarray   # float32 [P, M, nb, B]
    doc_map: np.ndarray       # int32 [P, Dl]
    n_docs_local: int         # Dl
    n_vocab: int              # global V
    n_vocab_local: int        # Vc = ceil(V / M)


def shard_corpus(corpus: Corpus, n_data: int, block_size: int,
                 seed: int = 0, n_mp: int = 1,
                 n_groups: int = 1) -> ShardedCorpus:
    """Partition documents (greedy balance) over data shards and tokens
    over vocabulary chunks; lay out every bucket in blocked form.
    `n_groups` pads the block count to a multiple so the sweep can
    synchronize counts after every group (cfg.sync_splits)."""
    n_docs = corpus.n_docs
    lengths = corpus.doc_lengths()
    # Snake round-robin over docs sorted by length (desc): near-optimal
    # load balance, fully vectorized — no per-document Python loop (the
    # partitioner must handle ~10^6 IP documents, SURVEY.md §7.3.4).
    order = np.argsort(lengths, kind="stable")[::-1]
    pos = np.arange(n_docs)
    fwd = pos % n_data
    snake = np.where((pos // n_data) % 2 == 0, fwd, n_data - 1 - fwd)
    shard_of_doc = np.empty(n_docs, np.int32)
    shard_of_doc[order] = snake.astype(np.int32)

    # Local doc numbering per shard (rank within shard, by global doc id).
    sort_idx = np.argsort(shard_of_doc, kind="stable")
    counts = np.bincount(shard_of_doc, minlength=n_data)
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    local_sorted = np.arange(n_docs) - np.repeat(starts, counts)
    local_of_doc = np.empty(n_docs, np.int32)
    local_of_doc[sort_idx] = local_sorted.astype(np.int32)
    d_local = int(counts.max()) if n_docs else 1
    doc_map = np.full((n_data, d_local), -1, np.int32)
    doc_map[shard_of_doc, local_of_doc] = np.arange(n_docs, dtype=np.int32)

    # Bucket tokens by (doc's data shard, word % n_mp); pad all buckets
    # to the max bucket token count.
    rng = np.random.default_rng(seed)
    tok_data = shard_of_doc[corpus.doc_ids]
    tok_mp = (corpus.word_ids % n_mp).astype(np.int64)
    bucket = tok_data.astype(np.int64) * n_mp + tok_mp
    bucket_counts = np.bincount(bucket, minlength=n_data * n_mp)
    max_tokens = int(bucket_counts.max()) if corpus.n_tokens else 1
    block = min(block_size, max(max_tokens, 1))
    nb = -(-max_tokens // block)
    nb = -(-nb // n_groups) * n_groups     # sync groups need equal splits
    padded_len = nb * block

    doc_blocks = np.zeros((n_data, n_mp, padded_len), np.int32)
    word_blocks = np.zeros((n_data, n_mp, padded_len), np.int32)
    mask_blocks = np.zeros((n_data, n_mp, padded_len), np.float32)
    for p in range(n_data):
        for m in range(n_mp):
            sel = bucket == p * n_mp + m
            d = local_of_doc[corpus.doc_ids[sel]]
            w = (corpus.word_ids[sel] // n_mp).astype(np.int32)
            perm = rng.permutation(d.shape[0])
            d, w = d[perm], w[perm]
            doc_blocks[p, m, : d.shape[0]] = d
            word_blocks[p, m, : d.shape[0]] = w
            mask_blocks[p, m, : d.shape[0]] = 1.0
    return ShardedCorpus(
        doc_blocks=doc_blocks.reshape(n_data, n_mp, nb, block),
        word_blocks=word_blocks.reshape(n_data, n_mp, nb, block),
        mask_blocks=mask_blocks.reshape(n_data, n_mp, nb, block),
        doc_map=doc_map,
        n_docs_local=d_local,
        n_vocab=corpus.n_vocab,
        n_vocab_local=-(-corpus.n_vocab // n_mp),
    )


def ring_push(ring, delta):
    """Bounded-staleness FIFO step for the async merge arm: returns
    (entry folding NOW, new ring). A peer delta pushed at merge window
    t is emitted at window t+τ where τ == ring.shape[0] — exactly τ
    windows late, NEVER later (the staleness bound is the ring length,
    a static property of the compiled program; the superstep flush
    folds whatever is still pending at the boundary, so a delta's
    realized lag is min(τ, windows to the boundary)). `ring is None`
    spells τ=0: the delta folds immediately, which is what makes the
    τ=0 arm's count arithmetic bit-identical to the synchronous fold.
    Pure function of arrays — unit-tested directly
    (tests/test_merge_async.py::test_ring_push_staleness_bound)."""
    if ring is None:                    # tau == 0: immediate fold
        return delta, None
    return ring[0], jnp.concatenate([ring[1:], delta[None]], axis=0)


def _ring_sum(ring):
    """Sum of a ring's pending entries (0 for the τ=0 spelling) — the
    flush term that turns a shard's stale view back into exact global
    counts: view + pending == N(0) + Σ all shards' deltas so far, at
    every merge-window boundary."""
    return 0 if ring is None else ring.sum(axis=0)


def chunked_to_global_nwk(nwk_chunks: np.ndarray, n_vocab: int) -> np.ndarray:
    """[M, Vc, K] chunked counts -> [V, K] global (w = local*M + chunk)."""
    m, vc, k = nwk_chunks.shape
    out = np.zeros((m * vc, k), nwk_chunks.dtype)
    for c in range(m):
        out[c::m] = nwk_chunks[c][: len(out[c::m])]
    return out[:n_vocab]


def put_global(a, mesh, spec) -> jax.Array:
    """Host array (identical on every process) -> device array under
    `spec` on `mesh`.

    Single-process this is a plain sharded device_put. On a process-
    spanning mesh (hostfabric) jax.device_put refuses arrays with
    non-addressable shards, so the global array is assembled from a
    callback that materializes only this process's addressable blocks —
    every process holds the same full host array (state init and corpus
    sharding are deterministic in cfg.seed), so the per-block slices
    agree across hosts by construction."""
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(jnp.asarray(a), sharding)
    host = np.asarray(a)
    return jax.make_array_from_callback(host.shape, sharding,
                                        lambda idx: host[idx])


class ShardedGibbsState(NamedTuple):
    """Device-sharded sampler state with an UNSHARDED chain axis C.

    C > 1 gives the sharded engine the same restart-ensemble estimator
    the judged overlap bar rides on the single-device engine
    (docs/OVERLAP.md): each device vmaps C independent chains over its
    local tokens, so C chains cost ~one sweep of C× the tokens and the
    per-sweep psum reduces all chains' deltas in one collective. The
    chain axis sits BEHIND the device axes so the PartitionSpecs are
    identical for every C (chains are replicated work, not sharded)."""

    z: jax.Array         # int32 [P, M, C, nb, B] (K sentinel = padding)
    n_dk: jax.Array      # int32 [P, C, Dl, K] doc-topic, data-sharded
    n_wk: jax.Array      # int32 [M, C, Vc, K] topic-word chunks, mp-sharded
    n_k: jax.Array       # int32 [C, K] replicated
    keys: jax.Array      # [P, M, C, 2] uint32 per-device/chain PRNG keys
    acc_ndk: jax.Array   # float32 [P, C, Dl, K]
    acc_nwk: jax.Array   # float32 [M, C, Vc, K]
    n_acc: jax.Array     # int32 []


def _local_sweep(z, n_dk, n_wk, n_k, key, docs, words, mask, *,
                 alpha, eta, n_vocab, k_topics, nwk_form=None,
                 sampler_form=None, sparse_active=0, sparse_mh=2):
    """The per-device sweep body — the single-device engine's sweep
    kernel, shared via lda_gibbs.make_sweep_kernel so the math (and
    the sampler-form gate) stays identical. `n_wk` may be a vocabulary
    CHUNK with local word ids; the denominator terms (n_k + V*eta)
    stay global. The n_wk count-update form (scatter | matmul |
    pallas) gates on the LOCAL chunk width — under mp sharding each
    chunk's collision density is what matters. The sparse sampler arm
    is chunk-clean too: its stale proposal tables are built from this
    device's local rows (doc-sharded n_dk, the local n_wk chunk) and
    every per-token gather is a local-row gather, so mp sharding needs
    no global rebuild."""
    kernel = lda_gibbs.make_sweep_kernel(
        alpha=alpha, eta=eta, n_vocab=n_vocab, k_topics=k_topics,
        nwk_form=nwk_form, sampler_form=sampler_form,
        sparse_active=sparse_active, sparse_mh=sparse_mh)
    return kernel(z, n_dk, n_wk, n_k, key, docs, words, mask)


class ShardedGibbsLDA:
    """Multi-chip Gibbs driver: docs on the data axes, vocabulary chunks
    on mp, psum of topic sufficient statistics.

    Covers BASELINE.json configs[3]: "1B-row synthetic netflow, 20
    topics, multi-chip doc-sharded Gibbs"; the mp axis covers the
    K×V-beyond-HBM regime of SURVEY.md §5.7, and a (dcn, dp[, mp]) mesh
    spans multiple slices (§2.3).
    """

    def __init__(self, config: LDAConfig, n_vocab: int, mesh=None):
        config.validate()
        self.config = config
        self.n_vocab = n_vocab
        self.mesh = mesh if mesh is not None else make_mesh()
        self.data_axes = data_axes_of(self.mesh)
        if not self.data_axes:
            raise ValueError(
                f"mesh axes {tuple(self.mesh.shape)} carry no data axis")
        self.n_data = int(np.prod([self.mesh.shape[a]
                                   for a in self.data_axes]))
        self.n_mp = int(self.mesh.shape.get(MP_AXIS, 1))
        k = config.n_topics
        D = self.data_axes
        M = MP_AXIS if MP_AXIS in self.mesh.shape else None
        both = D + ((M,) if M else ())

        S = max(1, int(config.sync_splits))
        burn = config.burn_in
        # "auto" defers to the measured per-backend gate at trace time
        # (lda_gibbs.select_nwk_form); explicit config forms pin it. An
        # ONIX_NWK_FORM override present at construction is captured
        # here; when it is unset (form None), BOTH the block steps and
        # the replication-check decision below re-resolve the env at
        # trace time — the same moment, so the compiled form and the
        # check can never disagree even if the env changes in between.
        nwk_form = (None if config.nwk_form == "auto" else config.nwk_form)
        if nwk_form is None:
            nwk_form = lda_gibbs.env_nwk_form()
        # Sampler form: resolved ONCE at construction via the shared
        # lda_gibbs.resolve_sampler (config, then ONIX_SAMPLER_FORM,
        # then nwk-pin deference, then the measured gate) — the
        # resolved value feeds every compiled sweep AND the checkpoint
        # fingerprint, and sharing the resolver with GibbsLDA is what
        # keeps the two engines from ever resolving different arms for
        # the same config. The sparse arm is a different chain, so a
        # resume across an arm change must be refused, not silently
        # continued.
        self.sampler_form, self.sparse_active, sampler_kw = \
            lda_gibbs.resolve_sampler(config, k_topics=k,
                                      nwk_form=nwk_form)
        # Count-merge form (r14): resolved once at construction like
        # the sampler form — the value feeds the compiled superstep AND
        # the checkpoint fingerprint (merge_fingerprint), so the
        # program and the resume identity can never disagree. τ is
        # pinned to 0 under sync so the fingerprint entry (async only)
        # is a function of what actually runs.
        self.merge_form = config.merge_form
        use_async = self.merge_form == "async"
        tau = int(config.merge_staleness) if use_async else 0
        self.merge_tau = tau
        # shard_map has no replication rule for pallas_call, so the
        # sweep-carrying shard regions must drop the static replication
        # check whenever the Pallas form CAN be traced (explicitly
        # pinned, or auto-reachable because the backend has a measured
        # pallas crossover entry). The check is a tracing-time linter,
        # not semantics — psum/out_specs behave identically without it
        # (the dp>1 pallas-vs-scatter equality tests ride this path).
        # Evaluated at TRACE time, right where make_block_step resolves
        # the same form, so the two decisions always read the same env.
        def sweep_smap_kw():
            # The async merge arm's count views are genuinely device-
            # VARYING mid-superstep (own deltas fresh, peers' stale) and
            # only the boundary flush restores replication-in-value, so
            # the static replication linter has nothing true to check —
            # drop it, exactly as the pallas arm must.
            if use_async:
                return {_SHARD_MAP_CHECK_KW: False}
            form = (nwk_form if nwk_form is not None
                    else lda_gibbs.env_nwk_form())
            maybe_pallas = (
                form == "pallas"
                or (form is None and lda_gibbs.nwk_pallas_auto_reachable(
                    jax.default_backend())))
            return {_SHARD_MAP_CHECK_KW: False} if maybe_pallas else {}

        def _group_sweep(z_g, n_dk_l, n_wk_l, n_k_l, key_c,
                         d_g, w_g, m_g):
            """ONE full sweep of this device's tokens: scan the S sync
            groups, psum-folding count deltas after each (S=1 is the
            reference's MPI cadence). Shapes are shard-LOCAL with the
            leading shard axes already dropped; z_g is the grouped
            layout [S, C, nb/S, B]. Shared by the per-sweep program and
            the fused superstep so the math can never diverge."""
            def group_step(carry, xs):
                ndk_r, nwk_r, nk_r, key_c = carry
                dg, wg, mg, zg = xs
                # Replicated bases become device-varying once each
                # device starts updating them locally — mark them
                # per group; the psum fold below restores the
                # replication the carry (and out_specs) demand.
                nwk_v = _pcast(nwk_r, D, to="varying")
                ndk_v = (_pcast(ndk_r, M, to="varying")
                         if M else ndk_r)
                nk_v = _pcast(nk_r, both, to="varying")

                def one_chain(zc, ndkc, nwkc, nkc, keyc):
                    return _local_sweep(
                        zc, ndkc, nwkc, nkc, keyc, dg, wg, mg,
                        alpha=config.alpha, eta=config.eta,
                        n_vocab=n_vocab, k_topics=k, nwk_form=nwk_form,
                        **sampler_kw)

                z_new, ndk_new, nwk_new, nk_new, key_new = \
                    jax.vmap(one_chain)(zg, ndk_v, nwk_v, nk_v, key_c)
                # The MPI_Reduce+Bcast of the reference, as psums:
                # chunk deltas over the data axes (ICI, then DCN),
                # doc-topic deltas over mp, topic totals over both.
                # All chains' deltas ride ONE collective (leading C
                # axis reduces elementwise).
                d_wk = jax.lax.psum(nwk_new - nwk_v, D)
                d_dk = (jax.lax.psum(ndk_new - ndk_v, M)
                        if M else ndk_new - ndk_v)
                d_k = jax.lax.psum(nk_new - nk_v, both)
                return (ndk_r + d_dk, nwk_r + d_wk, nk_r + d_k,
                        key_new), z_new

            (ndk_f, nwk_f, nk_f, key_f), z_out = jax.lax.scan(
                group_step, (n_dk_l, n_wk_l, n_k_l, key_c),
                (d_g, w_g, m_g, z_g))
            return z_out, ndk_f, nwk_f, nk_f, key_f

        def _zero_rings(n_dk_l, n_wk_l, n_k_l):
            """Fresh pending-delta FIFOs at superstep entry: τ slots of
            zeros per collective-reduced table — peers' first τ windows
            of deltas arrive late by construction. n_dk only rides a
            ring when mp shards exist (without mp every shard owns its
            docs' rows outright: no collective, no staleness)."""
            if tau == 0:
                return (None, None, None)
            mk = lambda a: jnp.zeros((tau,) + a.shape, a.dtype)
            return (mk(n_dk_l) if M else None, mk(n_wk_l), mk(n_k_l))

        def _group_sweep_async(z_g, n_dk_l, n_wk_l, n_k_l, key_c,
                               d_g, w_g, m_g, rings):
            """The bounded-staleness rendering of _group_sweep: the
            count carry is each shard's VIEW (own updates fresh; peer
            deltas folded from the ring exactly τ windows late), not
            the replicated fold. The psum still issues every window —
            its RESULT just stops gating the next window's sampling
            for τ>0, which is the stall the async arm removes. At τ=0
            the ring is the identity and the arithmetic
            (view + own + (psum − own) == base + psum) is bit-identical
            to _group_sweep's fold in exact int32. View + pending ==
            exact global counts at every window boundary — the
            invariant the superstep flush and the accumulator fold
            lean on."""
            def group_step(carry, xs):
                ndk_v, nwk_v, nk_v, key_c, rg = carry
                r_dk, r_wk, r_k = rg
                dg, wg, mg, zg = xs

                def one_chain(zc, ndkc, nwkc, nkc, keyc):
                    return _local_sweep(
                        zc, ndkc, nwkc, nkc, keyc, dg, wg, mg,
                        alpha=config.alpha, eta=config.eta,
                        n_vocab=n_vocab, k_topics=k, nwk_form=nwk_form,
                        **sampler_kw)

                z_new, ndk_new, nwk_new, nk_new, key_new = \
                    jax.vmap(one_chain)(zg, ndk_v, nwk_v, nk_v, key_c)
                # Peers' deltas = the collective total minus our own;
                # own deltas stay in the view immediately (the AD-LDA
                # discipline — a shard is never stale w.r.t. itself).
                own_wk = nwk_new - nwk_v
                peer_wk = jax.lax.psum(own_wk, D) - own_wk
                own_k = nk_new - nk_v
                peer_k = jax.lax.psum(own_k, both) - own_k
                fold_wk, r_wk = ring_push(r_wk, peer_wk)
                fold_k, r_k = ring_push(r_k, peer_k)
                if M:
                    own_dk = ndk_new - ndk_v
                    peer_dk = jax.lax.psum(own_dk, M) - own_dk
                    fold_dk, r_dk = ring_push(r_dk, peer_dk)
                    ndk_new = ndk_new + fold_dk
                return (ndk_new, nwk_new + fold_wk, nk_new + fold_k,
                        key_new, (r_dk, r_wk, r_k)), z_new

            (ndk_f, nwk_f, nk_f, key_f, rings_f), z_out = jax.lax.scan(
                group_step, (n_dk_l, n_wk_l, n_k_l, key_c, rings),
                (d_g, w_g, m_g, z_g))
            return z_out, ndk_f, nwk_f, nk_f, key_f, rings_f

        def _grouped(d, w, m, z):
            """Shard-local token blocks + z in sync-group layout."""
            C = z.shape[2]
            nb, B = d.shape[2], d.shape[3]
            assert nb % S == 0, (
                f"block count {nb} not divisible by "
                f"sync_splits={S}: the corpus was laid out without "
                "this engine's prepare() (shard_corpus needs "
                "n_groups=sync_splits)")
            return (d[0, 0].reshape(S, nb // S, B),
                    w[0, 0].reshape(S, nb // S, B),
                    m[0, 0].reshape(S, nb // S, B),
                    z[0, 0].reshape(C, S, nb // S, B).swapaxes(0, 1),
                    C, nb, B)

        def _chain_ll_local(ndk_f, nwk_f, nk_v, d0, w0, m0, zero):
            """Per-chain (sum log p, token sum) over this shard's tokens
            from explicit local counts — the predictive-ll math shared
            by the standalone ll program, the superstep boundary ll, and
            the dp=1 fast path (which passes plain f32 zeros)."""
            def one_chain(ndkc, nwkc, nkc):
                ndk = ndkc.astype(jnp.float32)
                theta = ((ndk + config.alpha)
                         / (ndk.sum(-1, keepdims=True)
                            + k * config.alpha))
                nwk = nwkc.astype(jnp.float32)
                phi = ((nwk + config.eta)
                       / (nkc.astype(jnp.float32)
                          + n_vocab * config.eta))

                def block(carry, xs):
                    sm, t = carry
                    db, wb, mb = xs
                    p = jnp.sum(theta[db] * phi[wb], axis=-1)
                    p = jnp.maximum(p, 1e-30)
                    return (sm + jnp.sum(mb * jnp.log(p)),
                            t + jnp.sum(mb)), None

                (sm, t), _ = jax.lax.scan(block, (zero, zero),
                                          (d0, w0, m0))
                return sm, t

            return jax.vmap(one_chain)(ndk_f, nwk_f, nk_v)

        mp_spec = (M,) if M else ()

        def sweep_fn(state: ShardedGibbsState, docs, words, mask,
                     accumulate: bool) -> ShardedGibbsState:
            def shard_fn(z, n_dk, n_wk, n_k, keys, d, w, m):
                # Leading shard axes of size (1, 1) inside shard_map;
                # the remaining leading axis is the chain axis C: the
                # SAME local token blocks, C independent sampler states,
                # batched by vmap into one program. Blocks split into S
                # sync groups (shard_corpus pads nb to a multiple): each
                # group sweeps against counts at most 1/S of a sweep
                # stale, psums its deltas, and folds them in before the
                # next group — S=1 is the reference's MPI cadence.
                d_g, w_g, m_g, z_g, C, nb, B = _grouped(d, w, m, z)
                z_out, ndk_f, nwk_f, nk_f, key_f = _group_sweep(
                    z_g, n_dk[0], n_wk[0], n_k, keys[0, 0],
                    d_g, w_g, m_g)
                z_full = z_out.swapaxes(0, 1).reshape(C, nb, B)
                return (z_full[None, None], ndk_f[None], nwk_f[None],
                        nk_f, key_f[None, None])

            z, n_dk, n_wk, n_k, keys = _shard_map(
                shard_fn, mesh=self.mesh,
                in_specs=(P(D, *mp_spec), P(D), P(*mp_spec), P(),
                          P(D, *mp_spec), P(D, *mp_spec), P(D, *mp_spec),
                          P(D, *mp_spec)),
                out_specs=(P(D, *mp_spec), P(D), P(*mp_spec), P(),
                           P(D, *mp_spec)),
                **sweep_smap_kw(),
            )(state.z, state.n_dk, state.n_wk, state.n_k, state.keys,
              docs, words, mask)
            do_acc = jnp.float32(accumulate)
            return ShardedGibbsState(
                z=z, n_dk=n_dk, n_wk=n_wk, n_k=n_k, keys=keys,
                acc_ndk=state.acc_ndk + do_acc * n_dk.astype(jnp.float32),
                acc_nwk=state.acc_nwk + do_acc * n_wk.astype(jnp.float32),
                n_acc=state.n_acc + jnp.int32(accumulate),
            )

        def superstep_fn(state: ShardedGibbsState, docs, words, mask,
                         start, n_steps: int, with_initial_ll=False):
            """`n_steps` fused sweeps + the boundary predictive ll in
            ONE program with ONE shard_map: the sweep chain runs as a
            lax.scan INSIDE the shard region, the burn-in accumulate
            fold rides the scan carry (sweep start+i accumulates iff
            past burn_in, decided on device), and the final counts feed
            the psum-reduced ll before anything returns to the host —
            one dispatch and one sync per superstep instead of per
            sweep (docs/PERF.md "the gibbs_fit vs sweep-microbench
            gap"). `with_initial_ll` also evaluates ll on the INCOMING
            counts (fit's pre-sweep history point) inside the same
            program. Bit-identical to n_steps sweep_fn dispatches."""
            def shard_fn(z, n_dk, n_wk, n_k, keys, accd, accw, nacc,
                         d, w, m, start_s):
                d_g, w_g, m_g, z_g, C, nb, B = _grouped(d, w, m, z)
                zero = _pcast(jnp.float32(0), both, to="varying")
                d0, w0, m0 = d[0, 0], w[0, 0], m[0, 0]
                if with_initial_ll:
                    nk0_v = _pcast(n_k, both, to="varying")
                    sm0, t0 = _chain_ll_local(n_dk[0], n_wk[0], nk0_v,
                                              d0, w0, m0, zero)
                    sm0 = jax.lax.psum(sm0, both)
                    t0 = jax.lax.psum(t0, both)

                def one_sweep(carry, i):
                    zg, ndk_r, nwk_r, nk_r, key_c, ad, aw, na = carry
                    zg, ndk_r, nwk_r, nk_r, key_c = _group_sweep(
                        zg, ndk_r, nwk_r, nk_r, key_c, d_g, w_g, m_g)
                    do = start_s + i >= burn
                    do_f = do.astype(jnp.float32)
                    ad = ad + do_f * ndk_r.astype(jnp.float32)
                    aw = aw + do_f * nwk_r.astype(jnp.float32)
                    na = na + do.astype(jnp.int32)
                    return (zg, ndk_r, nwk_r, nk_r, key_c,
                            ad, aw, na), None

                carry0 = (z_g, n_dk[0], n_wk[0], n_k, keys[0, 0],
                          accd[0], accw[0], nacc)
                (z_g2, ndk_f, nwk_f, nk_f, key_f, ad, aw, na), _ = \
                    jax.lax.scan(one_sweep, carry0,
                                 jnp.arange(n_steps, dtype=jnp.int32))
                nk_v = _pcast(nk_f, both, to="varying")
                sm, t = _chain_ll_local(ndk_f, nwk_f, nk_v,
                                        d0, w0, m0, zero)
                sm, t = jax.lax.psum(sm, both), jax.lax.psum(t, both)
                z_full = z_g2.swapaxes(0, 1).reshape(C, nb, B)
                outs = (z_full[None, None], ndk_f[None], nwk_f[None],
                        nk_f, key_f[None, None], ad[None], aw[None],
                        na, sm, t)
                return outs + ((sm0, t0) if with_initial_ll else ())

            out_specs = (P(D, *mp_spec), P(D), P(*mp_spec), P(),
                         P(D, *mp_spec), P(D), P(*mp_spec), P(),
                         P(), P())
            if with_initial_ll:
                out_specs = out_specs + (P(), P())
            outs = _shard_map(
                shard_fn, mesh=self.mesh,
                in_specs=(P(D, *mp_spec), P(D), P(*mp_spec), P(),
                          P(D, *mp_spec), P(D), P(*mp_spec), P(),
                          P(D, *mp_spec), P(D, *mp_spec),
                          P(D, *mp_spec), P()),
                out_specs=out_specs,
                **sweep_smap_kw(),
            )(state.z, state.n_dk, state.n_wk, state.n_k, state.keys,
              state.acc_ndk, state.acc_nwk, state.n_acc,
              docs, words, mask, jnp.asarray(start, jnp.int32))
            z, n_dk, n_wk, n_k, keys, accd, accw, nacc, sm, t = outs[:10]
            new_state = ShardedGibbsState(
                z=z, n_dk=n_dk, n_wk=n_wk, n_k=n_k, keys=keys,
                acc_ndk=accd, acc_nwk=accw, n_acc=nacc)
            # Per-chain corpus mean ll, averaged over chains (the same
            # series ll_fn exposes).
            ll = (sm / jnp.maximum(t, 1.0)).mean()
            if with_initial_ll:
                sm0, t0 = outs[10:]
                return new_state, (sm0 / jnp.maximum(t0, 1.0)).mean(), ll
            return new_state, ll

        def superstep_async_fn(state: ShardedGibbsState, docs, words,
                               mask, start, n_steps: int,
                               with_initial_ll=False):
            """The bounded-staleness superstep (merge_form="async"):
            identical host contract to superstep_fn — same inputs, same
            outputs, same out_specs — with the sweep chain riding
            _group_sweep_async's stale views and the pending-delta
            rings FLUSHED before anything returns, so the state handed
            back (and checkpointed, and ll-evaluated, and accumulated)
            is exact replicated global counts. The accumulator fold at
            each sweep boundary adds view + pending — the exact counts
            at that boundary — so posterior means are computed from the
            same count semantics as the sync arm's. τ=0 compiles a
            genuinely different program (varying carry, deferred-fold
            structure) whose results are bit-identical to superstep_fn
            (tests/test_merge_async.py); τ>0 is a different chain with
            the same stationary target, held to the ll band + winner
            parity contract."""
            def shard_fn(z, n_dk, n_wk, n_k, keys, accd, accw, nacc,
                         d, w, m, start_s):
                d_g, w_g, m_g, z_g, C, nb, B = _grouped(d, w, m, z)
                zero = jnp.float32(0)
                d0, w0, m0 = d[0, 0], w[0, 0], m[0, 0]
                if with_initial_ll:
                    # Incoming counts are exact (superstep boundaries
                    # always flush), so the pre-sweep ll needs no
                    # staleness correction.
                    sm0, t0 = _chain_ll_local(n_dk[0], n_wk[0], n_k,
                                              d0, w0, m0, zero)
                    sm0 = jax.lax.psum(sm0, both)
                    t0 = jax.lax.psum(t0, both)

                def one_sweep(carry, i):
                    (zg, ndk_r, nwk_r, nk_r, key_c, rings,
                     ad, aw, na) = carry
                    zg, ndk_r, nwk_r, nk_r, key_c, rings = \
                        _group_sweep_async(zg, ndk_r, nwk_r, nk_r,
                                           key_c, d_g, w_g, m_g, rings)
                    r_dk, r_wk, r_k = rings
                    do = start_s + i >= burn
                    do_f = do.astype(jnp.float32)
                    # Accumulate EXACT boundary counts (view + pending)
                    # so the posterior-mean estimator is arm-invariant
                    # in semantics AND replicated-in-value where the
                    # out_specs demand it (acc_ndk over mp, acc_nwk
                    # over the data axes).
                    ndk_x = ndk_r + _ring_sum(r_dk) if M else ndk_r
                    ad = ad + do_f * ndk_x.astype(jnp.float32)
                    aw = aw + do_f * ((nwk_r + _ring_sum(r_wk))
                                      .astype(jnp.float32))
                    na = na + do.astype(jnp.int32)
                    return (zg, ndk_r, nwk_r, nk_r, key_c, rings,
                            ad, aw, na), None

                carry0 = (z_g, n_dk[0], n_wk[0], n_k, keys[0, 0],
                          _zero_rings(n_dk[0], n_wk[0], n_k),
                          accd[0], accw[0], nacc)
                (z_g2, ndk_f, nwk_f, nk_f, key_f, rings_f,
                 ad, aw, na), _ = jax.lax.scan(
                    one_sweep, carry0,
                    jnp.arange(n_steps, dtype=jnp.int32))
                # The boundary FLUSH: fold every still-pending peer
                # delta, restoring exact replicated global counts —
                # what the host contract (and the ll below) reads.
                r_dk, r_wk, r_k = rings_f
                if M:
                    ndk_f = ndk_f + _ring_sum(r_dk)
                nwk_f = nwk_f + _ring_sum(r_wk)
                nk_f = nk_f + _ring_sum(r_k)
                sm, t = _chain_ll_local(ndk_f, nwk_f, nk_f,
                                        d0, w0, m0, zero)
                sm, t = jax.lax.psum(sm, both), jax.lax.psum(t, both)
                z_full = z_g2.swapaxes(0, 1).reshape(C, nb, B)
                outs = (z_full[None, None], ndk_f[None], nwk_f[None],
                        nk_f, key_f[None, None], ad[None], aw[None],
                        na, sm, t)
                return outs + ((sm0, t0) if with_initial_ll else ())

            out_specs = (P(D, *mp_spec), P(D), P(*mp_spec), P(),
                         P(D, *mp_spec), P(D), P(*mp_spec), P(),
                         P(), P())
            if with_initial_ll:
                out_specs = out_specs + (P(), P())
            outs = _shard_map(
                shard_fn, mesh=self.mesh,
                in_specs=(P(D, *mp_spec), P(D), P(*mp_spec), P(),
                          P(D, *mp_spec), P(D), P(*mp_spec), P(),
                          P(D, *mp_spec), P(D, *mp_spec),
                          P(D, *mp_spec), P()),
                out_specs=out_specs,
                **sweep_smap_kw(),
            )(state.z, state.n_dk, state.n_wk, state.n_k, state.keys,
              state.acc_ndk, state.acc_nwk, state.n_acc,
              docs, words, mask, jnp.asarray(start, jnp.int32))
            z, n_dk, n_wk, n_k, keys, accd, accw, nacc, sm, t = outs[:10]
            new_state = ShardedGibbsState(
                z=z, n_dk=n_dk, n_wk=n_wk, n_k=n_k, keys=keys,
                acc_ndk=accd, acc_nwk=accw, n_acc=nacc)
            ll = (sm / jnp.maximum(t, 1.0)).mean()
            if with_initial_ll:
                sm0, t0 = outs[10:]
                return new_state, (sm0 / jnp.maximum(t0, 1.0)).mean(), ll
            return new_state, ll

        def superstep_dp1_fn(state: ShardedGibbsState, docs, words, mask,
                             start, n_steps: int, with_initial_ll=False):
            """dp=1/mp=1 fast path: the identical superstep math with NO
            shard_map/psum wrapping — at one device every psum is an
            identity on integer deltas, so the collective wrapper buys
            nothing and costs real time (docs/PERF.md r7). Bit-identical
            to the shard_map path (asserted in
            tests/test_sharded_gibbs.py), including under
            sync_splits > 1, whose grouping is pure staleness
            bookkeeping when there is nothing to be stale against."""
            start_s = jnp.asarray(start, jnp.int32)
            d0, w0, m0 = docs[0, 0], words[0, 0], mask[0, 0]
            ll0 = None
            if with_initial_ll:
                sm0, t0 = _chain_ll_local(state.n_dk[0], state.n_wk[0],
                                          state.n_k, d0, w0, m0,
                                          jnp.float32(0))
                ll0 = (sm0 / jnp.maximum(t0, 1.0)).mean()
            sweep_kernel = lda_gibbs.make_sweep_kernel(
                alpha=config.alpha, eta=config.eta, n_vocab=n_vocab,
                k_topics=k, nwk_form=nwk_form, **sampler_kw)

            def one_sweep(carry, i):
                z, ndk, nwk, nk, keys, ad, aw, na = carry

                def one_chain(zc, ndkc, nwkc, nkc, keyc):
                    return sweep_kernel(zc, ndkc, nwkc, nkc, keyc,
                                        d0, w0, m0)

                z, ndk, nwk, nk, keys = jax.vmap(one_chain)(
                    z, ndk, nwk, nk, keys)
                do = start_s + i >= burn
                do_f = do.astype(jnp.float32)
                ad = ad + do_f * ndk.astype(jnp.float32)
                aw = aw + do_f * nwk.astype(jnp.float32)
                na = na + do.astype(jnp.int32)
                return (z, ndk, nwk, nk, keys, ad, aw, na), None

            carry0 = (state.z[0, 0], state.n_dk[0], state.n_wk[0],
                      state.n_k, state.keys[0, 0],
                      state.acc_ndk[0], state.acc_nwk[0], state.n_acc)
            (z, ndk, nwk, nk, keys, ad, aw, na), _ = jax.lax.scan(
                one_sweep, carry0, jnp.arange(n_steps, dtype=jnp.int32))
            sm, t = _chain_ll_local(ndk, nwk, nk, d0, w0, m0,
                                    jnp.float32(0))
            new_state = ShardedGibbsState(
                z=z[None, None], n_dk=ndk[None], n_wk=nwk[None], n_k=nk,
                keys=keys[None, None], acc_ndk=ad[None],
                acc_nwk=aw[None], n_acc=na)
            ll = (sm / jnp.maximum(t, 1.0)).mean()
            if with_initial_ll:
                return new_state, ll0, ll
            return new_state, ll

        def ll_fn(state: ShardedGibbsState, docs, words, mask):
            """Predictive mean log-likelihood from the CURRENT counts,
            computed where the data lives: per-shard token sums, then a
            psum — the convergence series the reference reads from
            lda-c's likelihood.dat (SURVEY.md §5.4–5.5), without
            gathering θ or the corpus to the host. The fit loop now
            evaluates ll inside the superstep program (superstep_fn);
            this standalone form serves the initial (pre-sweep) point
            and external callers."""
            def shard_fn(n_dk, n_wk, n_k, d, w, m):
                n_k_v = _pcast(n_k, both, to="varying")
                zero = _pcast(jnp.float32(0), both, to="varying")
                s, t = _chain_ll_local(n_dk[0], n_wk[0], n_k_v,
                                       d[0, 0], w[0, 0], m[0, 0], zero)
                return jax.lax.psum(s, both), jax.lax.psum(t, both)

            s, t = _shard_map(
                shard_fn, mesh=self.mesh,
                in_specs=(P(D), P(*mp_spec), P(),
                          P(D, *mp_spec), P(D, *mp_spec), P(D, *mp_spec)),
                out_specs=(P(), P()),
            )(state.n_dk, state.n_wk, state.n_k, docs, words, mask)
            # Per-chain corpus mean log-likelihood, averaged over chains
            # (matches GibbsLDA's ll_chains).
            return (s / jnp.maximum(t, 1.0)).mean()

        self._sweep = jax.jit(sweep_fn, static_argnames=("accumulate",),
                              donate_argnums=(0,))
        self._ll = jax.jit(ll_fn)
        # dp=1 fast path: engaged when the mesh has exactly one device
        # (scale.py's single-chip configuration and every CPU run of the
        # judged pipelines); ONIX_DP1_FAST=0 pins the shard_map form —
        # the cross-check arm the equality tests compare against.
        import os
        self.dp1_fast = (self.n_data == 1 and self.n_mp == 1
                         and os.environ.get("ONIX_DP1_FAST") != "0")
        # Merge-form dispatch: the dp=1/mp=1 fast path has no peers so
        # async ≡ sync there bit-for-bit (the fast path IS the τ=0
        # degenerate on one device); off the fast path the async form
        # swaps superstep_fn for the bounded-staleness program. The
        # per-sweep _sweep dispatch keeps the synchronous fold on every
        # form — it exists for the pre-r7 cross-check arms, and a merge
        # window shorter than its dispatch cannot overlap anything.
        wrapped_superstep = (superstep_async_fn if use_async
                            else superstep_fn)
        self._superstep = jax.jit(
            superstep_dp1_fn if self.dp1_fast else wrapped_superstep,
            static_argnames=("n_steps", "with_initial_ll"),
            donate_argnums=(0,))
        # The shard_map superstep stays constructible regardless, for
        # the fast-path equality tests and the pre-PR bench arm (no
        # donation: test callers reuse their input states). It carries
        # the RESOLVED merge form, so a dp=1 async model can still be
        # compared bit-for-bit against a sync model's wrapped path.
        self._superstep_shardmap = jax.jit(
            wrapped_superstep,
            static_argnames=("n_steps", "with_initial_ll"))
        self._mp_axis = M

    # -- sharding specs ----------------------------------------------------

    def _specs(self) -> dict:
        D = self.data_axes
        mp = (self._mp_axis,) if self._mp_axis else ()
        return {"z": P(D, *mp), "n_dk": P(D), "n_wk": P(*mp),
                "n_k": P(), "keys": P(D, *mp), "acc_ndk": P(D),
                "acc_nwk": P(*mp), "n_acc": None}

    # -- state construction ----------------------------------------------

    def init_state(self, sc: ShardedCorpus,
                   init_phi: np.ndarray | None = None) -> ShardedGibbsState:
        cfg = self.config
        k = cfg.n_topics
        C = cfg.n_chains
        p, m, nb, b = sc.doc_blocks.shape
        rng = np.random.default_rng(cfg.seed)
        if init_phi is None:
            # Independent initial assignments per chain (the restart
            # ensemble's whole point); padding shares the K sentinel.
            z = rng.integers(0, k, size=(p, m, C, nb, b)).astype(np.int32)
        else:
            # φ̂-as-prior warm start (Streaming Gibbs, arxiv
            # 1601.01142): draw each token's initial topic from
            # p(k|w) ∝ init_phi[w, k] — yesterday's posterior word-
            # topic distribution — instead of uniform, so the chain
            # starts near the previous day's mode and needs a fraction
            # of the cold sweep budget (daily.warm_sweeps). Host-side,
            # deterministic in cfg.seed; counts build from z below
            # exactly as in the cold path. init_phi rows are GLOBAL
            # vocab ids; the blocked layout holds local chunk ids
            # (word // n_mp for chunk word % n_mp).
            init_phi = np.asarray(init_phi, np.float64)
            if init_phi.shape[0] != sc.n_vocab:
                raise ValueError(
                    f"init_phi covers {init_phi.shape[0]} words, corpus "
                    f"has {sc.n_vocab} — map the prior into TODAY's "
                    "vocabulary first (campaign.map_phi_prior)")
            z = np.empty((p, m, C, nb * b), np.int32)
            flat_w = sc.word_blocks.reshape(p, m, -1)
            step = 1 << 18       # bound the [T, K] cdf temp, not z
            for q in range(p):
                for c in range(m):
                    w_global = flat_w[q, c].astype(np.int64) * m + c
                    w_global = np.minimum(w_global, sc.n_vocab - 1)
                    for s in range(0, w_global.shape[0], step):
                        sl = slice(s, s + step)
                        # The cdf depends only on the words — build it
                        # once per slice, draw uniforms per chain.
                        cdf = np.cumsum(init_phi[w_global[sl]], axis=1)
                        cdf /= np.maximum(cdf[:, -1:], 1e-30)
                        for ch in range(C):
                            u = rng.random(cdf.shape[0])
                            z[q, c, ch, sl] = np.minimum(
                                (cdf < u[:, None]).sum(axis=1),
                                k - 1).astype(np.int32)
            z = z.reshape(p, m, C, nb, b)
        z = np.where(sc.mask_blocks[:, :, None] > 0, z, k)
        # Exact global counts built host-side once (init only).
        n_dk = np.zeros((p, C, sc.n_docs_local, k), np.int32)
        n_wk = np.zeros((m, C, sc.n_vocab_local, k), np.int32)
        flat_z = z.reshape(p, m, C, -1)
        flat_d = sc.doc_blocks.reshape(p, m, -1)
        flat_w = sc.word_blocks.reshape(p, m, -1)
        flat_m = sc.mask_blocks.reshape(p, m, -1) > 0
        for q in range(p):
            for c in range(m):
                sel = flat_m[q, c]
                for ch in range(C):
                    np.add.at(n_dk[q, ch],
                              (flat_d[q, c][sel], flat_z[q, c, ch][sel]), 1)
                    np.add.at(n_wk[c, ch],
                              (flat_w[q, c][sel], flat_z[q, c, ch][sel]), 1)
        n_k = n_wk.sum(axis=(0, 2)).astype(np.int32)   # [C, K]
        # Independent per-device/per-chain streams: split, never adjacent
        # raw seeds (seed and seed+1 would otherwise share most streams).
        keys = jax.random.split(jax.random.PRNGKey(cfg.seed),
                                p * m * C).reshape(p, m, C, -1)

        specs = self._specs()
        arrays = {
            "z": z, "n_dk": n_dk, "n_wk": n_wk, "n_k": n_k, "keys": keys,
            "acc_ndk": np.zeros((p, C, sc.n_docs_local, k), np.float32),
            "acc_nwk": np.zeros((m, C, sc.n_vocab_local, k), np.float32),
            "n_acc": np.zeros((), np.int32),
        }
        # n_acc's None spec means "leave uncommitted" single-process; a
        # process-spanning mesh needs every jit input globally placed,
        # so it rides an explicitly replicated P() there.
        put = {name: (jnp.asarray(a)
                      if specs[name] is None and jax.process_count() == 1
                      else put_global(a, self.mesh, specs[name] or P()))
               for name, a in arrays.items()}
        return ShardedGibbsState(**put)

    def restore_state(self, arrays: dict[str, np.ndarray]) -> ShardedGibbsState:
        """Rebuild a device-sharded state from checkpointed host arrays,
        re-applying the same shardings init_state lays down."""
        specs = self._specs()
        put = {}
        for name, spec in specs.items():
            a = arrays[name]
            put[name] = (jnp.asarray(a)
                         if spec is None and jax.process_count() == 1
                         else put_global(a, self.mesh, spec or P()))
        return ShardedGibbsState(**put)

    def prepare(self, corpus: Corpus) -> ShardedCorpus:
        return shard_corpus(corpus, self.n_data, self.config.block_size,
                            self.config.seed, n_mp=self.n_mp,
                            n_groups=self.config.sync_splits)

    def device_corpus(self, sc: ShardedCorpus):
        D = self.data_axes
        mp = (self._mp_axis,) if self._mp_axis else ()
        spec = P(D, *mp)
        return (put_global(sc.doc_blocks, self.mesh, spec),
                put_global(sc.word_blocks, self.mesh, spec),
                put_global(sc.mask_blocks, self.mesh, spec))

    # -- fit --------------------------------------------------------------

    def fit(self, corpus: Corpus, n_sweeps: int | None = None,
            callback=None, checkpoint_dir=None, resume: bool = True,
            fault_inject_sweep: int | None = None,
            init_phi: np.ndarray | None = None) -> dict:
        """Sharded fit loop as fused supersteps, with optional
        checkpoint/resume — the recovery story the reference's MPI job
        lacks (SURVEY.md §5.3: "an MPI rank failure kills the LDA job");
        mandatory for preemptible TPU capacity.

        Sweeps run S at a time inside one jitted program (one shard_map,
        or the dp=1 fast path) with the burn-in accumulate fold and the
        boundary ll on device; segment boundaries land exactly on
        checkpoint/fault/final sweeps (lda_gibbs.plan_segments), so a
        checkpoint is never demanded mid-superstep and every resume
        point is an exact sweep boundary. Mesh shape AND superstep size
        are part of the checkpoint fingerprint: a state sharded dp=8
        must not resume on a dp=4 mesh, and a run fused at a different S
        is refused rather than resumed into a different ll cadence.

        `fault_inject_sweep` (or env ONIX_FAULT_SWEEP) raises
        SimulatedPreemption right after completing that sweep — the
        same §5.3 fault hook GibbsLDA has, so scale runs on the sharded
        engine can exercise their resume path too.

        `init_phi` ([n_vocab, K], today's vocab order) warm-starts the
        chain from a φ̂-as-prior z draw (init_state) — the r19 daily
        supervisor's warm refit. A warm chain is a DIFFERENT chain from
        the cold one, so the prior's content digest joins the checkpoint
        fingerprint: a cold resume can never continue a warm run or
        vice versa, and two different priors never share checkpoints."""
        import os

        from onix import checkpoint as ckpt
        from onix.models.lda_gibbs import SUPERSTEP_DEFAULT, plan_segments

        if fault_inject_sweep is None:
            env = os.environ.get("ONIX_FAULT_SWEEP")
            fault_inject_sweep = int(env) if env else None

        cfg = self.config
        n_sweeps = cfg.n_sweeps if n_sweeps is None else n_sweeps
        S_step = cfg.superstep or SUPERSTEP_DEFAULT
        sc = self.prepare(corpus)
        docs, words, mask = self.device_corpus(sc)
        # layout=4: the fused-superstep layout — the jitted carry holds
        # the accumulator state, checkpoints land only at superstep
        # boundaries, and the superstep size joins the identity
        # (checkpoint.fingerprint's superstep arg). layout=3 was the
        # chained state layout (chain axis C behind the shard axes);
        # bumping rejects earlier layouts instead of crashing on
        # restore. n_chains is part of the config hash.
        # Warm-init identity (r19): the prior changes the chain's
        # initial state, so it must join the resume identity exactly
        # like a sampler-arm change. Cold fits contribute nothing —
        # pre-r19 checkpoints keep resuming.
        warm_extra = {}
        if init_phi is not None:
            import hashlib
            a = np.asarray(init_phi, np.float32)
            hh = hashlib.sha256(repr(a.shape).encode())
            hh.update(a.tobytes())
            warm_extra["warm_init"] = hh.hexdigest()[:16]
        fp = ckpt.fingerprint(cfg,
                              sc.doc_map.shape[0] * sc.n_docs_local,
                              sc.n_vocab, corpus.n_tokens,
                              extra={"mesh": list(self.mesh.shape.values()),
                                     "layout": 4,
                                     **warm_extra,
                                     # RESOLVED sampler arm: a resume
                                     # across an arm change is refused
                                     # (GibbsLDA.fit has the same rule).
                                     **lda_gibbs.sampler_fingerprint(
                                         self.sampler_form,
                                         self.sparse_active,
                                         cfg.sparse_mh),
                                     # RESOLVED merge form (r14): τ>0
                                     # is a different chain, and even
                                     # the bit-identical τ=0 async arm
                                     # refuses a cross-form resume by
                                     # spec; sync contributes nothing
                                     # so pre-r14 checkpoints resume.
                                     **lda_gibbs.merge_fingerprint(
                                         self.merge_form,
                                         self.merge_tau)},
                              superstep=S_step)
        if checkpoint_dir is not None:
            import pathlib
            checkpoint_dir = pathlib.Path(checkpoint_dir) / fp
        start = 0
        state = None
        if checkpoint_dir is not None and resume:
            saved = ckpt.load_latest(checkpoint_dir)
            if saved is not None and saved.meta.get("fingerprint") == fp:
                state = self.restore_state(saved.arrays)
                start = saved.sweep + 1
        if state is None:
            state = self.init_state(sc, init_phi=init_phi)
        from onix.models.lda_gibbs import run_fit_segments
        segments = plan_segments(
            start, n_sweeps, S_step,
            checkpoint_every=(cfg.checkpoint_every
                              if checkpoint_dir is not None else 0),
            fault_sweep=fault_inject_sweep,
            per_sweep=callback is not None)
        state, ll_history = run_fit_segments(
            state, start, segments,
            superstep_fn=lambda st, s0, n, init: self._superstep(
                st, docs, words, mask, s0, n_steps=n,
                with_initial_ll=init),
            initial_ll_fn=lambda st: self._ll(st, docs, words, mask),
            checkpoint_every=cfg.checkpoint_every,
            checkpoint_dir=checkpoint_dir,
            save_fn=lambda st, s: ckpt.save(
                checkpoint_dir, s,
                {k: np.asarray(v) for k, v in st._asdict().items()},
                {"fingerprint": fp, "engine": "sharded_gibbs"}),
            fault_sweep=fault_inject_sweep,
            notify=(None if callback is None
                    else lambda s, st, ll: callback(s, st)))
        theta, phi_wk = self.estimates(state, sc, corpus.n_docs)
        return {"state": state, "sharded_corpus": sc,
                "theta": theta, "phi_wk": phi_wk,
                "ll_history": ll_history}

    def estimates(self, state: ShardedGibbsState, sc: ShardedCorpus,
                  n_docs: int) -> tuple[np.ndarray, np.ndarray]:
        """Gather per-shard counts back to global doc/word order.

        Matches GibbsLDA's contract: n_chains == 1 returns theta [D, K]
        and phi_wk [V, K]; n_chains > 1 stacks a leading chain axis
        (theta [C, D, K], phi_wk [C, V, K]) that scoring.score_events
        ensemble-averages over."""
        cfg = self.config
        use_acc = int(state.n_acc) > 0
        denom = max(float(state.n_acc), 1.0)
        ndk_s = (np.asarray(state.acc_ndk) / denom if use_acc
                 else np.asarray(state.n_dk, dtype=np.float64))
        nwk_c = (np.asarray(state.acc_nwk) / denom if use_acc
                 else np.asarray(state.n_wk, dtype=np.float64))
        C = ndk_s.shape[1]
        valid = sc.doc_map >= 0
        thetas, phis = [], []
        for ch in range(C):
            nwk = chunked_to_global_nwk(nwk_c[:, ch], sc.n_vocab)
            ndk = np.zeros((n_docs, cfg.n_topics))
            ndk[sc.doc_map[valid]] = ndk_s[:, ch][valid]
            thetas.append((ndk + cfg.alpha)
                          / (ndk.sum(-1, keepdims=True)
                             + cfg.n_topics * cfg.alpha))
            phis.append((nwk + cfg.eta) / (nwk.sum(0, keepdims=True)
                                           + self.n_vocab * cfg.eta))
        theta = np.stack(thetas).astype(np.float32)
        phi_wk = np.stack(phis).astype(np.float32)
        if C == 1:
            return theta[0], phi_wk[0]
        return theta, phi_wk
