"""Doc-sharded collapsed Gibbs over a device mesh.

This is the TPU-native rendering of oni-lda-c's one true parallelism
(SURVEY.md §2.2): MPI ranks each own a shard of documents, run the local
sampler, and allreduce the K×V topic-word sufficient statistics every
iteration. Here:

- documents (and their tokens) are sharded over the ``dp`` mesh axis via
  `shard_map`;
- each shard sweeps its local token blocks against a local replica of
  the topic-word counts (stale w.r.t. other shards within a sweep — the
  same staleness the reference accepts between MPI reductions);
- at sweep end the count *deltas* are `psum`'d over ICI and folded into
  the replicated matrix, replacing MPI_Reduce + MPI_Bcast with one XLA
  collective (BASELINE.json north star names this exact mapping).

Equivalence: with dp=1 this is bit-identical in distribution to the
single-device engine; tests assert count invariants and topic recovery
on a virtual 8-device CPU mesh (SURVEY.md §4.3).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from onix.config import LDAConfig
from onix.corpus import Corpus
from onix.models import lda_gibbs
from onix.parallel.mesh import DP_AXIS, make_mesh


class ShardedCorpus(NamedTuple):
    """Host-prepared, shard-major corpus layout.

    Documents are partitioned into `n_shards` balanced groups; each
    shard's tokens are padded to the same [n_blocks, block] shape and
    its documents renumbered locally. `doc_map[p, i]` is the global doc
    id of shard p's local doc i (-1 padding).
    """

    doc_blocks: np.ndarray    # int32 [P, nb, B] local doc ids
    word_blocks: np.ndarray   # int32 [P, nb, B]
    mask_blocks: np.ndarray   # float32 [P, nb, B]
    doc_map: np.ndarray       # int32 [P, Dl]
    n_docs_local: int         # Dl
    n_vocab: int


def shard_corpus(corpus: Corpus, n_shards: int, block_size: int,
                 seed: int = 0) -> ShardedCorpus:
    """Partition documents round-robin by size (greedy balance) and lay
    out each shard's tokens in blocked form."""
    n_docs = corpus.n_docs
    lengths = corpus.doc_lengths()
    # Snake round-robin over docs sorted by length (desc): near-optimal
    # load balance, fully vectorized — no per-document Python loop (the
    # partitioner must handle ~10^6 IP documents, SURVEY.md §7.3.4).
    order = np.argsort(lengths, kind="stable")[::-1]
    pos = np.arange(n_docs)
    fwd = pos % n_shards
    snake = np.where((pos // n_shards) % 2 == 0, fwd, n_shards - 1 - fwd)
    shard_of_doc = np.empty(n_docs, np.int32)
    shard_of_doc[order] = snake.astype(np.int32)

    # Local doc numbering per shard (rank within shard, by global doc id).
    sort_idx = np.argsort(shard_of_doc, kind="stable")
    counts = np.bincount(shard_of_doc, minlength=n_shards)
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    local_sorted = np.arange(n_docs) - np.repeat(starts, counts)
    local_of_doc = np.empty(n_docs, np.int32)
    local_of_doc[sort_idx] = local_sorted.astype(np.int32)
    d_local = int(counts.max()) if n_docs else 1
    doc_map = np.full((n_shards, d_local), -1, np.int32)
    doc_map[shard_of_doc, local_of_doc] = np.arange(n_docs, dtype=np.int32)

    # Per-shard token arrays, all padded to the max shard token count.
    rng = np.random.default_rng(seed)
    tok_shard = shard_of_doc[corpus.doc_ids]
    max_tokens = int(np.bincount(tok_shard, minlength=n_shards).max()) if corpus.n_tokens else 1
    block = min(block_size, max(max_tokens, 1))
    padded_len = -(-max_tokens // block) * block
    nb = padded_len // block

    doc_blocks = np.zeros((n_shards, padded_len), np.int32)
    word_blocks = np.zeros((n_shards, padded_len), np.int32)
    mask_blocks = np.zeros((n_shards, padded_len), np.float32)
    for p in range(n_shards):
        sel = tok_shard == p
        d = local_of_doc[corpus.doc_ids[sel]]
        w = corpus.word_ids[sel]
        perm = rng.permutation(d.shape[0])
        d, w = d[perm], w[perm]
        doc_blocks[p, : d.shape[0]] = d
        word_blocks[p, : d.shape[0]] = w
        mask_blocks[p, : d.shape[0]] = 1.0
    return ShardedCorpus(
        doc_blocks=doc_blocks.reshape(n_shards, nb, block),
        word_blocks=word_blocks.reshape(n_shards, nb, block),
        mask_blocks=mask_blocks.reshape(n_shards, nb, block),
        doc_map=doc_map,
        n_docs_local=d_local,
        n_vocab=corpus.n_vocab,
    )


class ShardedGibbsState(NamedTuple):
    z: jax.Array         # int32 [P, nb, B] (K sentinel = padding)
    n_dk: jax.Array      # int32 [P, Dl, K] doc-topic counts, dp-sharded
    n_wk: jax.Array      # int32 [V, K] topic-word counts, replicated
    n_k: jax.Array       # int32 [K] replicated
    keys: jax.Array      # [P, 2] uint32 per-shard PRNG keys
    acc_ndk: jax.Array   # float32 [P, Dl, K]
    acc_nwk: jax.Array   # float32 [V, K]
    n_acc: jax.Array     # int32 []


def _local_sweep(z, n_dk, n_wk, n_k, key, docs, words, mask, *,
                 alpha, eta, n_vocab, k_topics):
    """The per-shard sweep body — the single-device engine's block_step,
    shared via lda_gibbs.make_block_step so the math stays identical."""
    block_step = lda_gibbs.make_block_step(
        alpha=alpha, eta=eta, n_vocab=n_vocab, k_topics=k_topics)
    (n_dk, n_wk, n_k, key), z = jax.lax.scan(
        block_step, (n_dk, n_wk, n_k, key), (docs, words, mask, z))
    return z, n_dk, n_wk, n_k, key


class ShardedGibbsLDA:
    """Multi-chip Gibbs driver: docs on the dp axis, psum of topic stats.

    Covers BASELINE.json configs[3]: "1B-row synthetic netflow, 20
    topics, multi-chip doc-sharded Gibbs".
    """

    def __init__(self, config: LDAConfig, n_vocab: int, mesh=None):
        config.validate()
        self.config = config
        self.n_vocab = n_vocab
        self.mesh = mesh if mesh is not None else make_mesh()
        self.n_shards = self.mesh.shape[DP_AXIS]
        k = config.n_topics

        def sweep_fn(state: ShardedGibbsState, docs, words, mask,
                     accumulate: bool) -> ShardedGibbsState:
            def shard_fn(z, n_dk, n_wk, n_k, keys, d, w, m):
                # Replicated counts become device-varying once each shard
                # starts updating its local replica — mark them so.
                n_wk_v = jax.lax.pcast(n_wk, DP_AXIS, to="varying")
                n_k_v = jax.lax.pcast(n_k, DP_AXIS, to="varying")
                # Leading shard axis of size 1 inside shard_map blocks.
                z, n_dk, n_wk_new, n_k_new, key = _local_sweep(
                    z[0], n_dk[0], n_wk_v, n_k_v, keys[0], d[0], w[0], m[0],
                    alpha=config.alpha, eta=config.eta,
                    n_vocab=n_vocab, k_topics=k)
                # The MPI_Reduce+Bcast of the reference, as one psum over
                # ICI: every shard folds in everyone's deltas.
                d_wk = jax.lax.psum(n_wk_new - n_wk_v, DP_AXIS)
                d_k = jax.lax.psum(n_k_new - n_k_v, DP_AXIS)
                return (z[None], n_dk[None], n_wk + d_wk, n_k + d_k,
                        key[None])

            z, n_dk, n_wk, n_k, keys = jax.shard_map(
                shard_fn, mesh=self.mesh,
                in_specs=(P(DP_AXIS), P(DP_AXIS), P(), P(), P(DP_AXIS),
                          P(DP_AXIS), P(DP_AXIS), P(DP_AXIS)),
                out_specs=(P(DP_AXIS), P(DP_AXIS), P(), P(), P(DP_AXIS)),
            )(state.z, state.n_dk, state.n_wk, state.n_k, state.keys,
              docs, words, mask)
            do_acc = jnp.float32(accumulate)
            return ShardedGibbsState(
                z=z, n_dk=n_dk, n_wk=n_wk, n_k=n_k, keys=keys,
                acc_ndk=state.acc_ndk + do_acc * n_dk.astype(jnp.float32),
                acc_nwk=state.acc_nwk + do_acc * n_wk.astype(jnp.float32),
                n_acc=state.n_acc + jnp.int32(accumulate),
            )

        self._sweep = jax.jit(sweep_fn, static_argnames=("accumulate",),
                              donate_argnums=(0,))

    # -- state construction ----------------------------------------------

    def init_state(self, sc: ShardedCorpus) -> ShardedGibbsState:
        cfg = self.config
        k = cfg.n_topics
        p, nb, b = sc.doc_blocks.shape
        rng = np.random.default_rng(cfg.seed)
        z = rng.integers(0, k, size=(p, nb, b)).astype(np.int32)
        z = np.where(sc.mask_blocks > 0, z, k)
        # Exact global counts built host-side once (init only).
        n_dk = np.zeros((p, sc.n_docs_local, k), np.int32)
        n_wk = np.zeros((sc.n_vocab, k), np.int32)
        flat_z = z.reshape(p, -1)
        flat_d = sc.doc_blocks.reshape(p, -1)
        flat_w = sc.word_blocks.reshape(p, -1)
        flat_m = sc.mask_blocks.reshape(p, -1) > 0
        for q in range(p):
            sel = flat_m[q]
            np.add.at(n_dk[q], (flat_d[q][sel], flat_z[q][sel]), 1)
            np.add.at(n_wk, (flat_w[q][sel], flat_z[q][sel]), 1)
        n_k = n_wk.sum(axis=0).astype(np.int32)
        # Independent per-shard streams: split, never adjacent raw seeds
        # (seed and seed+1 would otherwise share p-1 of p streams).
        keys = jax.random.split(jax.random.PRNGKey(cfg.seed), p)

        shard = lambda spec: NamedSharding(self.mesh, spec)
        dev = functools.partial(jax.device_put)
        return ShardedGibbsState(
            z=dev(jnp.asarray(z), shard(P(DP_AXIS))),
            n_dk=dev(jnp.asarray(n_dk), shard(P(DP_AXIS))),
            n_wk=dev(jnp.asarray(n_wk), shard(P())),
            n_k=dev(jnp.asarray(n_k), shard(P())),
            keys=dev(jnp.asarray(keys), shard(P(DP_AXIS))),
            acc_ndk=dev(jnp.zeros((p, sc.n_docs_local, k), jnp.float32),
                        shard(P(DP_AXIS))),
            acc_nwk=dev(jnp.zeros((sc.n_vocab, k), jnp.float32), shard(P())),
            n_acc=jnp.zeros((), jnp.int32),
        )

    def restore_state(self, arrays: dict[str, np.ndarray]) -> ShardedGibbsState:
        """Rebuild a device-sharded state from checkpointed host arrays,
        re-applying the same shardings init_state lays down."""
        shard = lambda spec: NamedSharding(self.mesh, spec)
        specs = {"z": P(DP_AXIS), "n_dk": P(DP_AXIS), "n_wk": P(),
                 "n_k": P(), "keys": P(DP_AXIS), "acc_ndk": P(DP_AXIS),
                 "acc_nwk": P(), "n_acc": None}
        put = {}
        for name, spec in specs.items():
            a = jnp.asarray(arrays[name])
            put[name] = (a if spec is None
                         else jax.device_put(a, shard(spec)))
        return ShardedGibbsState(**put)

    def prepare(self, corpus: Corpus) -> ShardedCorpus:
        return shard_corpus(corpus, self.n_shards, self.config.block_size,
                            self.config.seed)

    def device_corpus(self, sc: ShardedCorpus):
        shard = NamedSharding(self.mesh, P(DP_AXIS))
        return (jax.device_put(jnp.asarray(sc.doc_blocks), shard),
                jax.device_put(jnp.asarray(sc.word_blocks), shard),
                jax.device_put(jnp.asarray(sc.mask_blocks), shard))

    # -- fit --------------------------------------------------------------

    def fit(self, corpus: Corpus, n_sweeps: int | None = None,
            callback=None, checkpoint_dir=None, resume: bool = True) -> dict:
        """Sharded sweep loop with optional checkpoint/resume — the
        recovery story the reference's MPI job lacks (SURVEY.md §5.3: "an
        MPI rank failure kills the LDA job"); mandatory for preemptible
        TPU capacity. Mesh shape is part of the checkpoint fingerprint:
        a state sharded dp=8 must not resume on a dp=4 mesh."""
        from onix import checkpoint as ckpt

        cfg = self.config
        n_sweeps = cfg.n_sweeps if n_sweeps is None else n_sweeps
        sc = self.prepare(corpus)
        docs, words, mask = self.device_corpus(sc)
        # n_chains is a GibbsLDA-only knob this sampler never reads —
        # normalize it out so toggling it cannot orphan sharded checkpoints.
        import dataclasses as _dc
        fp = ckpt.fingerprint(_dc.replace(cfg, n_chains=1),
                              sc.doc_map.shape[0] * sc.n_docs_local,
                              sc.n_vocab, corpus.n_tokens,
                              extra={"mesh": list(self.mesh.shape.values())})
        if checkpoint_dir is not None:
            import pathlib
            checkpoint_dir = pathlib.Path(checkpoint_dir) / fp
        start = 0
        state = None
        if checkpoint_dir is not None and resume:
            saved = ckpt.load_latest(checkpoint_dir)
            if saved is not None and saved.meta.get("fingerprint") == fp:
                state = self.restore_state(saved.arrays)
                start = saved.sweep + 1
        if state is None:
            state = self.init_state(sc)
        for s in range(start, n_sweeps):
            state = self._sweep(state, docs, words, mask,
                                accumulate=s >= cfg.burn_in)
            if (checkpoint_dir is not None and cfg.checkpoint_every > 0
                    and (s + 1) % cfg.checkpoint_every == 0):
                ckpt.save(checkpoint_dir, s,
                          {k: np.asarray(v)
                           for k, v in state._asdict().items()},
                          {"fingerprint": fp, "engine": "sharded_gibbs"})
            if callback is not None:
                callback(s, state)
        theta, phi_wk = self.estimates(state, sc, corpus.n_docs)
        return {"state": state, "sharded_corpus": sc,
                "theta": theta, "phi_wk": phi_wk}

    def estimates(self, state: ShardedGibbsState, sc: ShardedCorpus,
                  n_docs: int) -> tuple[np.ndarray, np.ndarray]:
        """Gather per-shard doc-topic counts back to global doc order."""
        cfg = self.config
        use_acc = int(state.n_acc) > 0
        denom = max(float(state.n_acc), 1.0)
        ndk_s = (np.asarray(state.acc_ndk) / denom if use_acc
                 else np.asarray(state.n_dk, dtype=np.float64))
        nwk = (np.asarray(state.acc_nwk) / denom if use_acc
               else np.asarray(state.n_wk, dtype=np.float64))
        ndk = np.zeros((n_docs, cfg.n_topics))
        valid = sc.doc_map >= 0
        ndk[sc.doc_map[valid]] = ndk_s[valid]
        theta = (ndk + cfg.alpha) / (ndk.sum(-1, keepdims=True)
                                     + cfg.n_topics * cfg.alpha)
        phi_wk = (nwk + cfg.eta) / (nwk.sum(0, keepdims=True)
                                    + self.n_vocab * cfg.eta)
        return theta.astype(np.float32), phi_wk.astype(np.float32)
