from onix.parallel.mesh import make_mesh, DP_AXIS, MP_AXIS  # noqa: F401
from onix.parallel.sharded_gibbs import ShardedGibbsLDA  # noqa: F401
