"""Device-mesh construction and sharding helpers.

The reference's distributed backend is MPI + ssh + a shared filesystem
(SURVEY.md §2.3): ranks are launched by `mpiexec -machinefile NODES`,
collectives are MPI_Reduce/MPI_Bcast of dense K×V float matrices. The
TPU-native equivalent is a `jax.sharding.Mesh` over the pod slice with
XLA collectives over ICI — `psum` replaces MPI_Reduce+Bcast, and there
is no launcher because the TPU multi-host runtime (jax.distributed)
owns process placement.

Axes:
- ``dp`` — data parallel: documents/tokens sharded (the reference's only
  model-math parallelism, SURVEY.md §2.2).
- ``mp`` — model parallel: vocabulary sharded, for K×V matrices that
  outgrow one chip's HBM (SURVEY.md §5.7 — the honest "tensor" axis of
  LDA).
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

DP_AXIS = "dp"
MP_AXIS = "mp"
DCN_AXIS = "dcn"        # across-slice axis (data-center network)
# Data-parallel collective axes for a multislice mesh: psum over both
# rides ICI within a slice and DCN across slices; XLA decomposes the
# collective hierarchically.
DATA_AXES = (DCN_AXIS, DP_AXIS)


def make_mesh(dp: int | None = None, mp: int = 1,
              devices: list | None = None) -> Mesh:
    """Build a (dp, mp) mesh from available devices.

    With `dp=None`, all remaining devices go to the data axis. On a real
    slice the device order from `jax.devices()` follows the ICI torus, so
    neighboring dp shards are ICI neighbors and the per-sweep psum of
    topic sufficient statistics rides ICI (BASELINE.json north star:
    "topic-sufficient-statistics allreduced over ICI").
    """
    devs = devices if devices is not None else jax.devices()
    n = len(devs)
    if dp is None:
        if n % mp:
            raise ValueError(f"{n} devices not divisible by mp={mp}")
        dp = n // mp
    need = dp * mp
    if need > n:
        raise ValueError(f"mesh {dp}x{mp} needs {need} devices, have {n}")
    grid = np.asarray(devs[:need]).reshape(dp, mp)
    return Mesh(grid, (DP_AXIS, MP_AXIS))


def make_multislice_mesh(dcn: int, dp: int | None = None, mp: int = 1,
                         devices: list | None = None) -> Mesh:
    """(dcn, dp, mp) mesh spanning `dcn` slices.

    The reference's 20-node MPI job treats all ranks as one flat ring;
    on multislice TPU the topology is two-tier — ICI within a slice, DCN
    between slices (SURVEY.md §2.3) — so the slice axis is explicit and
    OUTERMOST: psum over (dcn, dp) lets XLA reduce within each slice
    over ICI first and exchange only the reduced K×V stats over DCN.

    On real multislice hardware, pass `devices` grouped slice-major
    (jax.devices() already is); for CPU/fake-device tests any ordering
    works and the axis is purely logical.
    """
    devs = devices if devices is not None else jax.devices()
    n = len(devs)
    if n % dcn:
        raise ValueError(f"{n} devices not divisible by dcn={dcn}")
    per_slice = n // dcn
    if dp is None:
        if per_slice % mp:
            raise ValueError(
                f"{per_slice} devices/slice not divisible by mp={mp}")
        dp = per_slice // mp
    need = dcn * dp * mp
    if need > n:
        raise ValueError(f"mesh {dcn}x{dp}x{mp} needs {need} devices, "
                         f"have {n}")
    grid = np.asarray(devs[:need]).reshape(dcn, dp, mp)
    return Mesh(grid, (DCN_AXIS, DP_AXIS, MP_AXIS))


def data_axes_of(mesh: Mesh) -> tuple[str, ...]:
    """The data-parallel axis names present in `mesh` (dcn first)."""
    return tuple(a for a in DATA_AXES if a in mesh.shape)


def enable_cpu_collectives() -> bool:
    """Route cross-process CPU collectives over gloo. Returns True when
    the gloo implementation was selected.

    jax 0.4.x's CPU backend refuses multi-process computations outright
    ("Multiprocess computations aren't implemented on the CPU backend")
    unless `jax_cpu_collectives_implementation` is set BEFORE the CPU
    client is created — env vars alone don't reach the flag in time, so
    every process of a CPU fabric (hostfabric workers, the 2-process
    suite) must call this before its first jax computation. Gated on
    JAX_PLATFORMS naming cpu: a TPU pod's collectives ride ICI/DCN and
    must not be redirected. Older jaxlibs without gloo degrade to False
    (the caller's distributed init then fails loudly, never silently
    single-process)."""
    import os
    if "cpu" not in os.environ.get("JAX_PLATFORMS", ""):
        return False
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):
        return False
    return True


def _distributed_is_initialized() -> bool:
    """Backend-safe "is jax.distributed up" probe. jax >= 0.5 exposes
    `jax.distributed.is_initialized`; 0.4.x (this container's 0.4.37)
    does not, so fall back to the distributed global state's client —
    the same object is_initialized reads — which exists on every 0.4.x
    and never instantiates the XLA backend."""
    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        return bool(probe())
    from jax._src import distributed as _dist
    return getattr(_dist.global_state, "client", None) is not None


def multihost_init(coordinator: str | None = None,
                   num_processes: int | None = None,
                   process_id: int | None = None,
                   init_timeout_s: int | None = None) -> bool:
    """Initialize the multi-host runtime. Returns True when this process
    is part of a multi-process job after the call.

    Replaces the reference's ssh + machinefile launch (SURVEY.md §3.1):
    on a TPU pod each host calls this once with no arguments —
    `jax.distributed.initialize` auto-detects the coordinator from the
    pod metadata — and the runtime wires up DCN/ICI; there is no
    external launcher to maintain. Off-pod (CPU soak tests, the
    2-process suite in tests/test_multihost.py) pass all three
    arguments explicitly, exactly as `mesh.coordinator/num_processes/
    process_id` feed them from the config.

    Failure RAISES: a pod job continuing single-process after a botched
    init would silently train on 1/N of the data (the round-2
    `except: pass` bug, VERDICT weak #7). The only swallowed case is
    the explicit single-process one: no arguments given and no
    multi-host environment detected, where running solo is the
    requested behavior.
    """
    # Probe via distributed.is_initialized, NOT process_count():
    # process_count() instantiates the XLA backend, after which
    # jax.distributed.initialize refuses to run at all.
    if _distributed_is_initialized():
        return jax.process_count() > 1
    explicit = coordinator is not None
    if explicit:
        # Explicit init is how CPU fabrics launch (hostfabric workers,
        # the 2-process suite) — those need gloo collectives selected
        # before the backend exists; on TPU the gate inside is a no-op.
        enable_cpu_collectives()
        kw = ({"initialization_timeout": init_timeout_s}
              if init_timeout_s else {})
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=num_processes,
                                   process_id=process_id, **kw)
        return jax.process_count() > 1
    # Auto mode: only a real multi-host environment should initialize.
    # jax.distributed.initialize() raises on single-host CPU/GPU dev
    # boxes (no cluster-detection env) — treat exactly that as "running
    # solo was requested", WARN so a botched cluster launch is visible
    # in every rank's log (a silent solo rank trains on 1/N of the
    # data), and re-raise anything else.
    try:
        jax.distributed.initialize()
    except (RuntimeError, ValueError) as e:
        msg = str(e).lower()
        # "must be called before any JAX calls": the backend is already
        # up in this process. In a genuinely solo session (the sharded
        # engine invoked mid-process, tests) that is a benign no-op —
        # but if the environment says this process is one rank of a
        # multi-process job, running solo would silently train on 1/N
        # of the data (the round-2 bug), so it must still RAISE.
        solo_shaped = ("detect" in msg or "coordinator_address" in msg
                       or "single-process" in msg or "called before" in msg)
        if solo_shaped and not _cluster_env_says_multiprocess():
            import sys
            print("multihost_init: no multi-host environment detected; "
                  f"running single-process ({e})", file=sys.stderr)
            return False
        raise
    return jax.process_count() > 1


def _cluster_env_says_multiprocess() -> bool:
    """True when launcher env vars claim >1 processes — the guard that
    keeps auto-mode's solo fallback from swallowing a real pod/cluster
    rank's init failure."""
    import os
    for var in ("JAX_NUM_PROCESSES", "SLURM_NTASKS",
                "OMPI_COMM_WORLD_SIZE", "PMI_SIZE"):
        try:
            if int(os.environ.get(var, "1")) > 1:
                return True
        except ValueError:
            pass
    return False
