"""Device-mesh construction and sharding helpers.

The reference's distributed backend is MPI + ssh + a shared filesystem
(SURVEY.md §2.3): ranks are launched by `mpiexec -machinefile NODES`,
collectives are MPI_Reduce/MPI_Bcast of dense K×V float matrices. The
TPU-native equivalent is a `jax.sharding.Mesh` over the pod slice with
XLA collectives over ICI — `psum` replaces MPI_Reduce+Bcast, and there
is no launcher because the TPU multi-host runtime (jax.distributed)
owns process placement.

Axes:
- ``dp`` — data parallel: documents/tokens sharded (the reference's only
  model-math parallelism, SURVEY.md §2.2).
- ``mp`` — model parallel: vocabulary sharded, for K×V matrices that
  outgrow one chip's HBM (SURVEY.md §5.7 — the honest "tensor" axis of
  LDA).
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

DP_AXIS = "dp"
MP_AXIS = "mp"


def make_mesh(dp: int | None = None, mp: int = 1,
              devices: list | None = None) -> Mesh:
    """Build a (dp, mp) mesh from available devices.

    With `dp=None`, all remaining devices go to the data axis. On a real
    slice the device order from `jax.devices()` follows the ICI torus, so
    neighboring dp shards are ICI neighbors and the per-sweep psum of
    topic sufficient statistics rides ICI (BASELINE.json north star:
    "topic-sufficient-statistics allreduced over ICI").
    """
    devs = devices if devices is not None else jax.devices()
    n = len(devs)
    if dp is None:
        if n % mp:
            raise ValueError(f"{n} devices not divisible by mp={mp}")
        dp = n // mp
    need = dp * mp
    if need > n:
        raise ValueError(f"mesh {dp}x{mp} needs {need} devices, have {n}")
    grid = np.asarray(devs[:need]).reshape(dp, mp)
    return Mesh(grid, (DP_AXIS, MP_AXIS))


def multihost_init() -> None:
    """Initialize the multi-host runtime (no-op on a single host).

    Replaces the reference's ssh + machinefile launch (SURVEY.md §3.1):
    on a TPU pod each host calls this once and the runtime wires up
    DCN/ICI; there is no external launcher to maintain.
    """
    if jax.process_count() > 1:
        return  # already initialized by the launcher
    try:
        jax.distributed.initialize()
    except Exception:
        # Single-process (CPU tests, one-chip dev): nothing to do.
        pass
