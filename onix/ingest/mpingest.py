"""Multi-process / multi-host parallel ingest.

The reference's "Parallel Ingest Framework" fans work out from a master
collector to worker processes across nodes over Kafka partitions
(reference README.md:35-38; SURVEY.md §3.2). onix keeps that fan-out
shape with the shared filesystem as the coordination plane instead of a
broker: any number of worker PROCESSES — on one machine or many hosts
mounting the same landing directory — consume the same directory of
capture files with no master and no broker.

Coordination protocol (all steps NFS-safe — no flock):

  claim   a worker reserves a file by creating
          `.onix_claims/<digest>.claim` with O_EXCL (atomic create;
          exactly one creator wins). <digest> hashes the file's resolved
          path + size + mtime, so a file that later grows or is
          re-delivered gets a fresh identity and is re-ingested —
          identical semantics to the single-process watcher's ledger.
  commit  after the rows are durably in the store, the claim is renamed
          to `<digest>.done` (atomic rename). A crash before commit
          leaves a claim but no done marker.
  lease   a claim older than `lease_seconds` with no done marker is
          presumed dead. Takeover: rename it to a unique tombstone —
          rename is atomic, so exactly one contender wins — then claim
          fresh. At-least-once delivery, like Kafka offset redelivery.

Poison handling mirrors the single-process watcher's: failed attempts
are counted in `<digest>.attempts` (written only by the claim holder —
single-writer, so no flock needed), with backoff gates between retries
and salvage-mode decode on the final attempt; an exhausted file moves
to `quarantine/` with a sidecar and leaves a `<digest>.quarantined`
marker no worker will ever re-claim. Because the digest hashes
path+size+mtime, changed/re-delivered content gets a fresh identity —
and a fresh retry budget — automatically.

Part-file writes are safe under this concurrency because Store.append
allocates part numbers with an atomic hard-link (see onix/store.py).
"""

from __future__ import annotations

import hashlib
import json
import logging
import multiprocessing
import os
import pathlib
import socket
import time

from onix.config import OnixConfig
from onix.ingest.run import DEFAULT_PATTERNS, ingest_file
from onix.store import Store
from onix.utils.obs import counters
from onix.utils.resilience import (RetryPolicy, format_exception,
                                   quarantine_file)

log = logging.getLogger("onix.ingest.mp")

CLAIMS_DIR = ".onix_claims"
QUARANTINE_DIR = "quarantine"


def _digest(path: pathlib.Path) -> tuple[str, dict]:
    st = path.stat()
    ident = f"{path.resolve()}|{st.st_size}|{st.st_mtime}"
    return hashlib.sha1(ident.encode()).hexdigest()[:24], {
        "path": str(path.resolve()),
        "size": st.st_size,
        "mtime": st.st_mtime,
    }


class ClaimStore:
    """The on-disk claim/done protocol for one landing directory."""

    def __init__(self, landing: pathlib.Path, lease_seconds: float = 300.0):
        self.dir = landing / CLAIMS_DIR
        self.dir.mkdir(exist_ok=True)
        self.lease_seconds = lease_seconds

    def try_claim(self, path: pathlib.Path) -> str | None:
        """Atomically claim `path`; returns the digest on success, None
        if done, quarantined, backing off after a failed attempt,
        claimed by a live worker, or lost a race."""
        digest, meta = _digest(path)
        if (self.dir / f"{digest}.done").exists():
            return None
        if (self.dir / f"{digest}.quarantined").exists():
            return None
        if time.time() < self._not_before(digest):
            return None             # retry backoff window
        claim = self.dir / f"{digest}.claim"
        try:
            st = claim.stat()
        except FileNotFoundError:
            st = None
        if st is not None:
            if time.time() - st.st_mtime < self.lease_seconds:
                return None     # live claim — someone else is on it
            # Stale claim: exactly one contender wins this rename.
            tomb = self.dir / f"{digest}.stale-{os.getpid()}-{time.time_ns()}"
            try:
                os.rename(claim, tomb)
            except FileNotFoundError:
                return None     # another contender took it over first
        meta.update(pid=os.getpid(), host=socket.gethostname(),
                    claimed_at=time.time())
        try:
            with open(claim, "x") as f:     # O_EXCL: atomic create
                json.dump(meta, f)
        except FileExistsError:
            return None
        # Close the claim/commit TOCTOU window (the r18 drain flake —
        # one file ingested twice): the done check above can predate
        # another worker's commit, whose claim→done rename FREES the
        # claim path right before our O_EXCL create wins it. Re-check
        # now that we hold the claim: commit/quarantine only ever
        # create their markers BEFORE the claim path frees, so a
        # marker present here proves an earlier attempt finished —
        # drop ours instead of double-ingesting.
        if (self.dir / f"{digest}.done").exists() \
                or (self.dir / f"{digest}.quarantined").exists():
            self.release(digest)
            return None
        return digest

    def commit(self, digest: str) -> None:
        """Durably mark done (atomic rename of the claim); clears any
        attempts marker — a fail-then-succeed file must not leave a
        stale backoff gate behind (Ledger.commit does the same)."""
        os.rename(self.dir / f"{digest}.claim", self.dir / f"{digest}.done")
        self._attempts_path(digest).unlink(missing_ok=True)

    def release(self, digest: str) -> None:
        """Drop a claim after a failed ingest so any worker may retry."""
        (self.dir / f"{digest}.claim").unlink(missing_ok=True)

    # -- retry budget / dead-letter (single-writer: only the claim
    # holder touches a digest's attempts file, so no flock needed) ------

    def _attempts_path(self, digest: str) -> pathlib.Path:
        return self.dir / f"{digest}.attempts"

    def attempts_of(self, digest: str) -> int:
        try:
            return int(json.loads(
                self._attempts_path(digest).read_text())["n"])
        except (OSError, ValueError, KeyError):
            return 0

    def _not_before(self, digest: str) -> float:
        try:
            return float(json.loads(
                self._attempts_path(digest).read_text())["not_before"])
        except (OSError, ValueError, KeyError):
            return 0.0

    def record_failure(self, digest: str, path: pathlib.Path,
                       backoff_s: float) -> int:
        """Durably count one failed attempt and set the backoff gate;
        returns the attempt count."""
        n = self.attempts_of(digest) + 1
        tmp = self._attempts_path(digest).with_suffix(".attempts.tmp")
        tmp.write_text(json.dumps(
            {"n": n, "not_before": time.time() + backoff_s,
             "path": str(pathlib.Path(path).resolve()),
             "pid": os.getpid(), "host": socket.gethostname()}))
        os.replace(tmp, self._attempts_path(digest))
        return n

    def mark_quarantined(self, digest: str, meta: dict) -> None:
        """Durable never-re-claim marker; clears the claim + attempts."""
        marker = self.dir / f"{digest}.quarantined"
        tmp = marker.with_suffix(".quarantined.tmp")
        tmp.write_text(json.dumps(meta))
        os.replace(tmp, marker)
        self.release(digest)
        self._attempts_path(digest).unlink(missing_ok=True)

    def prune_missing(self) -> int:
        """Drop done/attempts markers whose file no longer exists —
        the multi-process rendering of the ledger compaction (markers
        for rotated-away captures otherwise accumulate forever).
        Quarantined markers are KEPT: they pin that exact signature
        dead-lettered across restarts (a re-delivered copy has a fresh
        mtime and therefore a fresh digest + budget, by design)."""
        gone = 0
        for marker in (*self.dir.glob("*.done"),
                       *self.dir.glob("*.attempts")):
            try:
                path = json.loads(marker.read_text()).get("path")
            except (OSError, ValueError):
                continue
            if path and not pathlib.Path(path).exists():
                marker.unlink(missing_ok=True)
                gone += 1
        return gone

    def done_count(self) -> int:
        return sum(1 for _ in self.dir.glob("*.done"))


def worker_loop(cfg: OnixConfig, datatype: str,
                landing: str | pathlib.Path, *,
                patterns: tuple[str, ...] = DEFAULT_PATTERNS,
                poll_interval: float = 0.5,
                max_seconds: float | None = None,
                lease_seconds: float = 300.0,
                settle_seconds: float = 2.0,
                idle_exit: bool = False,
                retry: RetryPolicy | None = None) -> dict:
    """One worker process: claim→ingest→commit until stopped.

    With `idle_exit`, returns after a poll that found nothing claimable
    (batch drain mode); otherwise polls until `max_seconds`.

    A file is only claimable once its mtime is at least `settle_seconds`
    old — the multi-host rendering of the watcher's two-poll stability
    gate. Claiming a still-growing capture would ingest its truncated
    head, commit it done under the truncated signature, and then ingest
    the finished file again under a fresh digest: head rows duplicated.

    Failures follow the shared retry policy: bounded attempts counted
    durably in the claims dir (any worker may perform any attempt),
    salvage-mode decode on the last one, then quarantine with sidecar."""
    landing = pathlib.Path(landing)
    claims = ClaimStore(landing, lease_seconds=lease_seconds)
    store = Store(cfg.store.root)
    retry = retry or RetryPolicy()
    stats = {"files": 0, "rows": 0, "errors": 0, "retries": 0,
             "quarantined": 0, "salvaged": 0}
    t0 = time.monotonic()
    polls = 0
    while True:
        dispatched = 0
        candidates: list[pathlib.Path] = []
        for pat in patterns:
            candidates.extend(landing.glob(pat))
        for path in sorted(candidates):
            try:
                if time.time() - path.stat().st_mtime < settle_seconds:
                    continue    # possibly still being written
                digest = claims.try_claim(path)
            except OSError:
                continue    # vanished between glob and stat
            if digest is None:
                continue
            attempt = claims.attempts_of(digest) + 1
            salvage: dict = {}
            try:
                counts = ingest_file(store, datatype, path,
                                     apply_sampling=cfg.ingest.apply_sampling,
                                     by_hour=cfg.store.partition_hours,
                                     strict=retry.strict_for_attempt(attempt),
                                     salvage=salvage)
                claims.commit(digest)
                stats["files"] += 1
                stats["rows"] += sum(counts.values())
                if salvage:
                    stats["salvaged"] += 1
                    log.warning("mp salvage-ingested %s: %s", path, salvage)
                dispatched += 1
            except Exception as e:
                stats["errors"] += 1
                attempts = claims.record_failure(
                    digest, path, retry.backoff(attempt))
                if retry.exhausted(attempts):
                    try:
                        _, meta = _digest(path)
                        sig = [meta["size"], meta["mtime"]]
                    except OSError:     # vanished mid-failure
                        meta, sig = {"path": str(path)}, None
                    claims.mark_quarantined(digest, dict(
                        meta, error=repr(e), attempts=attempts))
                    sidecar = quarantine_file(
                        path, landing / QUARANTINE_DIR, error=repr(e),
                        attempts=attempts, traceback=format_exception(e),
                        sig=sig)
                    stats["quarantined"] += 1
                    log.error("mp quarantined %s after %d attempts (%r) — "
                              "sidecar %s", path, attempts, e, sidecar)
                else:
                    log.exception("mp ingest failed for %s (attempt %d/%d, "
                                  "released)", path, attempts,
                                  retry.max_attempts)
                    claims.release(digest)
                    stats["retries"] += 1
                    counters.inc("ingest.retries")
        polls += 1
        if polls % 50 == 0:
            claims.prune_missing()
        if idle_exit and dispatched == 0:
            return stats
        if max_seconds is not None and time.monotonic() - t0 > max_seconds:
            return stats
        time.sleep(poll_interval)


def _worker_entry(cfg_dict: dict, datatype: str, landing: str,
                  kwargs: dict, stats_path: str) -> None:
    from onix.config import from_dict
    stats = worker_loop(from_dict(cfg_dict), datatype, landing, **kwargs)
    # Durable stats handoff: tmp + rename, so the parent reads either a
    # complete report or nothing (the claims-dir discipline). A queue
    # would be simpler but its feeder thread races process exit — the
    # parent's bounded q.get() can miss stats that ARE in flight, which
    # made the drain tests weather-dependent (the r18 flake).
    tmp = pathlib.Path(f"{stats_path}.tmp")
    tmp.write_text(json.dumps(stats))
    os.replace(tmp, stats_path)


def run_workers(cfg: OnixConfig, datatype: str,
                landing: str | pathlib.Path, n_procs: int = 4, *,
                patterns: tuple[str, ...] = DEFAULT_PATTERNS,
                poll_interval: float = 0.2,
                max_seconds: float | None = None,
                lease_seconds: float = 300.0,
                settle_seconds: float = 2.0,
                idle_exit: bool = True) -> dict:
    """Fan ingest out over `n_procs` OS processes (the single-host
    rendering of the reference's multi-node worker fleet — on a shared
    filesystem the same invocation on N hosts cooperates identically).

    Returns the merged stats dict. Each worker writes its stats to a
    per-worker file (tmp + atomic rename) as its LAST act before exit,
    and the parent joins every process before reading them — a
    deterministic handoff with no sleep-bounded queue drain (the old
    mp.Queue path raced the feeder thread against process exit and made
    the drain tests weather-dependent). A worker that dies without
    reporting (OOM kill, native crash) leaves no stats file, is counted
    under `dead_workers` and as an error — the parent never hangs
    waiting for a corpse's stats; its claimed file is released to other
    workers by the lease takeover."""
    import tempfile

    ctx = multiprocessing.get_context("spawn")   # fork is unsafe under JAX
    kwargs = dict(patterns=patterns, poll_interval=poll_interval,
                  max_seconds=max_seconds, lease_seconds=lease_seconds,
                  settle_seconds=settle_seconds, idle_exit=idle_exit)
    merged = {"files": 0, "rows": 0, "errors": 0, "retries": 0,
              "quarantined": 0, "salvaged": 0, "workers": n_procs,
              "dead_workers": 0}
    with tempfile.TemporaryDirectory(prefix="onix-mpingest-") as td:
        stats_paths = [pathlib.Path(td) / f"worker-{i}.json"
                       for i in range(n_procs)]
        procs = [ctx.Process(target=_worker_entry,
                             args=(cfg.to_dict(), datatype, str(landing),
                                   kwargs, str(sp)))
                 for sp in stats_paths]
        for p in procs:
            p.start()
        for p in procs:
            p.join()
        reported = 0
        for sp in stats_paths:
            try:
                st = json.loads(sp.read_text())
            except (OSError, ValueError):
                continue        # died before its atomic stats rename
            for k in ("files", "rows", "errors", "retries", "quarantined",
                      "salvaged"):
                merged[k] += st.get(k, 0)
            reported += 1
    dead = n_procs - reported
    if dead:
        log.error("%d ingest worker(s) died without reporting", dead)
        merged["dead_workers"] = dead
        merged["errors"] += dead
    return merged
