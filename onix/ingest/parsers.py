"""Text decoders for the dns and proxy ingest paths.

The reference's DNS path runs tshark field-extraction over pcaps and the
proxy path parses Bluecoat access logs (SURVEY.md §3.2 "DNS variant:
tshark field-extraction over pcap; proxy variant: log parsing" — the
`bluecoat.py` Spark-Streaming parser of SURVEY.md §2.1 #1). onix ingests
the equivalent text forms directly: tshark's tab-separated field output
for DNS (pcap decoding itself is out of scope — tshark is the reference's
decoder too), and Bluecoat W3C-style access log lines for proxy.
"""

from __future__ import annotations

import pathlib
import shlex

import numpy as np
import pandas as pd

# tshark -T fields -e frame.time_epoch -e frame.len -e ip.src -e ip.dst
#   -e dns.qry.name -e dns.qry.type -e dns.flags.rcode
TSHARK_FIELDS = ["frame_time_epoch", "frame_len", "ip_src", "ip_dst",
                 "dns_qry_name", "dns_qry_type", "dns_qry_rcode"]


def _count_salvaged(path, n_bad: int, n_good: int,
                    salvage: dict | None) -> None:
    """Record a text decoder's skipped-line tally (obs counters + the
    caller's per-file salvage dict). A file with bad lines and ZERO
    good ones is not salvage material — callers raise before this."""
    from onix.utils.obs import counters

    if n_bad == 0:
        return
    counters.inc("salvage.skipped_lines", n_bad)
    counters.inc("salvage.files")
    if salvage is not None:
        salvage["skipped_lines"] = salvage.get("skipped_lines", 0) + n_bad
        salvage["salvaged_records"] = (salvage.get("salvaged_records", 0)
                                       + n_good)


def parse_tshark_dns(path: str | pathlib.Path, strict: bool = True,
                     salvage: dict | None = None) -> pd.DataFrame:
    """Parse tshark TSV field output into the dns table schema.

    `strict=False` (the retry policy's final attempt) skips malformed
    lines — wrong field count, non-numeric epoch/length — with a
    per-file salvage count instead of rejecting the whole file. A file
    whose every line is malformed still raises (quarantine material,
    not an empty success)."""
    rows = []
    n_bad = 0
    had_lines = False
    # errors="replace" ONLY in salvage mode: strict mode must hard-error
    # on undecodable bytes (retry -> salvage), never commit mojibake as
    # a first-attempt success.
    text = pathlib.Path(path).read_text(
        errors="replace" if not strict else "strict")
    for line_no, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        had_lines = True
        parts = line.split("\t")
        if len(parts) != len(TSHARK_FIELDS):
            if not strict:
                n_bad += 1
                continue
            raise ValueError(
                f"{path}:{line_no}: expected {len(TSHARK_FIELDS)} "
                f"tab-separated fields, got {len(parts)}")
        rows.append(parts)
    if not strict and rows:
        # Numeric sanity per row: a bit-flipped epoch/frame_len must
        # drop its row, not poison the whole frame's conversion.
        keep = []
        for r in rows:
            try:
                float(r[0]), int(r[1])
                keep.append(r)
            except ValueError:
                n_bad += 1
        rows = keep
    if had_lines and not rows:
        raise ValueError(f"{path}: no parseable tshark TSV lines")
    _count_salvaged(path, n_bad, len(rows), salvage)
    if not rows:
        return pd.DataFrame(columns=["frame_time", "frame_len", "ip_dst",
                                     "dns_qry_name", "dns_qry_type",
                                     "dns_qry_rcode"])
    raw = pd.DataFrame(rows, columns=TSHARK_FIELDS)
    epoch = pd.to_numeric(raw["frame_time_epoch"])
    return pd.DataFrame({
        "frame_time": pd.to_datetime(epoch, unit="s")
                        .dt.strftime("%Y-%m-%d %H:%M:%S"),
        "frame_len": pd.to_numeric(raw["frame_len"]).astype(np.int32),
        "ip_dst": raw["ip_dst"],
        "dns_qry_name": raw["dns_qry_name"],
        "dns_qry_type": pd.to_numeric(raw["dns_qry_type"],
                                      errors="coerce").fillna(0).astype(np.int32),
        "dns_qry_rcode": pd.to_numeric(raw["dns_qry_rcode"],
                                       errors="coerce").fillna(0).astype(np.int32),
    })


# Bluecoat main-format field order (the subset the proxy pipeline needs;
# quoted fields are shlex-split). [R-med on the exact upstream order —
# the contract is the emitted schema, shared with synth_proxy_day.]
BLUECOAT_FIELDS = ["date", "time", "time_taken", "clientip", "respcode",
                   "action", "reqmethod", "urischeme", "host", "uriport",
                   "uripath", "uriquery", "username", "authgroup",
                   "resconttype", "useragent", "referer", "scbytes",
                   "csbytes"]


def parse_bluecoat(path: str | pathlib.Path, strict: bool = True,
                   salvage: dict | None = None) -> pd.DataFrame:
    """Parse Bluecoat-style access log lines into the proxy table schema.

    `strict=False` skips malformed lines (unbalanced quotes, wrong field
    count, non-numeric respcode/byte counters) with a per-file salvage
    count; a file with lines but NO parseable ones still raises."""
    rows = []
    n_bad = 0
    had_lines = False
    text = pathlib.Path(path).read_text(
        errors="replace" if not strict else "strict")
    for line_no, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        had_lines = True
        try:
            parts = shlex.split(line)
        except ValueError as e:     # unbalanced quote in a field
            if not strict:
                n_bad += 1
                continue
            raise ValueError(f"{path}:{line_no}: unparseable log line "
                             f"({e})") from e
        if len(parts) != len(BLUECOAT_FIELDS):
            if not strict:
                n_bad += 1
                continue
            raise ValueError(
                f"{path}:{line_no}: expected {len(BLUECOAT_FIELDS)} fields, "
                f"got {len(parts)}")
        rows.append(parts)
    if not strict and rows:
        keep = []
        for r in rows:
            try:
                int(r[BLUECOAT_FIELDS.index("respcode")])
                int(r[BLUECOAT_FIELDS.index("csbytes")])
                int(r[BLUECOAT_FIELDS.index("scbytes")])
                keep.append(r)
            except ValueError:
                n_bad += 1
        rows = keep
    if had_lines and not rows:
        raise ValueError(f"{path}: no parseable bluecoat log lines")
    _count_salvaged(path, n_bad, len(rows), salvage)
    cols = ["p_date", "p_time", "clientip", "host", "reqmethod", "useragent",
            "resconttype", "respcode", "uripath", "csbytes", "scbytes"]
    if not rows:
        return pd.DataFrame(columns=cols)
    raw = pd.DataFrame(rows, columns=BLUECOAT_FIELDS)
    return pd.DataFrame({
        "p_date": raw["date"],
        "p_time": raw["time"],
        "clientip": raw["clientip"],
        "host": raw["host"],
        "reqmethod": raw["reqmethod"],
        "useragent": raw["useragent"],
        "resconttype": raw["resconttype"],
        "respcode": pd.to_numeric(raw["respcode"]).astype(np.int32),
        "uripath": raw["uripath"] + np.where(raw["uriquery"].ne("-"),
                                             "?" + raw["uriquery"], ""),
        "csbytes": pd.to_numeric(raw["csbytes"]).astype(np.int64),
        "scbytes": pd.to_numeric(raw["scbytes"]).astype(np.int64),
    })


def format_bluecoat(table: pd.DataFrame) -> str:
    """Inverse of parse_bluecoat for synthetic captures/round-trip tests.

    Double quotes inside a user-agent are degraded to single quotes —
    a '"' inside the quoted field would make the emitted line
    unparseable (the same normalization proxy appliances apply)."""
    lines = []
    for _, r in table.iterrows():
        uripath, _, uriquery = str(r["uripath"]).partition("?")
        ua = str(r["useragent"]).replace('"', "'")
        lines.append(" ".join([
            str(r["p_date"]), str(r["p_time"]), "120", str(r["clientip"]),
            str(r["respcode"]), "TCP_HIT", str(r["reqmethod"]), "http",
            str(r["host"]), "80", uripath or "/", uriquery or "-", "-", "-",
            str(r["resconttype"]), f'"{ua}"', "-",
            str(r["scbytes"]), str(r["csbytes"]),
        ]))
    return "\n".join(lines) + "\n"
