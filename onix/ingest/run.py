"""Ingest execution: decode raw telemetry files into store partitions.

The collector→worker→Hive-load path of the reference (SURVEY.md §3.2)
rendered as: decode (C++ nfdecode subprocess-free via ctypes, tshark TSV,
Bluecoat log) → partition rows by day → write Parquet parts. Each input
file becomes its own part file (numbered by an atomic per-partition
counter), so parallel workers never collide — the reference got the same
property from HDFS staging files.
"""

from __future__ import annotations

import dataclasses
import pathlib

import pandas as pd

from onix.config import OnixConfig
from onix.store import Store

#: Landing-dir globs both ingest modes watch (single-process watcher and
#: the multi-process claim fleet — ONE definition so the modes can never
#: drift apart). `nfcapd.2*` matches nfdump's rotated
#: `nfcapd.YYYYMMDDhhmm` names but NOT the live in-progress
#: `nfcapd.current*` file, whose truncated head must never be ingested.
DEFAULT_PATTERNS = ("*.nf5", "*.tsv", "*.log", "*.csv", "*.pcap",
                    "*.pcapng", "*.cap", "nfcapd.2*")


def decode(datatype: str, path: str | pathlib.Path,
           apply_sampling: bool = False, strict: bool = True,
           salvage: dict | None = None) -> pd.DataFrame:
    """Decode one raw file. `strict=False` is SALVAGE mode — the retry
    policy's final attempt: malformed records/blocks/lines are skipped
    and counted (`salvage` dict + obs counters) instead of rejecting
    the whole file; a file with nothing decodable still raises.

    Chaos hook: an `ingest:decode` rule in the active fault plan fires
    here, before any bytes are read — the injected error is
    indistinguishable from a poison file to the retry machinery."""
    from onix.utils import faults

    faults.fire("ingest", "decode")
    if datatype == "flow":
        from onix.ingest.nfdecode import decode_file
        return decode_file(path, apply_sampling=apply_sampling,
                           strict=strict, salvage=salvage)
    if datatype == "dns":
        # .pcap goes through tshark-or-native extraction (SURVEY.md
        # §3.2 DNS variant); anything else is pre-extracted tshark TSV.
        if str(path).endswith((".pcap", ".pcapng", ".cap")):
            from onix.ingest.pcap import parse_dns_pcap
            return parse_dns_pcap(path, strict=strict, salvage=salvage)
        from onix.ingest.parsers import parse_tshark_dns
        return parse_tshark_dns(path, strict=strict, salvage=salvage)
    if datatype == "proxy":
        from onix.ingest.parsers import parse_bluecoat
        return parse_bluecoat(path, strict=strict, salvage=salvage)
    raise ValueError(f"unknown datatype {datatype!r}")


@dataclasses.dataclass(frozen=True)
class DecodeItem:
    """Picklable decode work unit: calling it decodes one raw file
    (same contract as `decode`). A module-level dataclass — not a
    closure — so the streaming prefetch pipeline can ship it to a
    process-pool worker and run the whole file decode off the
    consumer (streaming.ColumnPrefetcher; thread pools accept it
    identically)."""

    datatype: str
    path: str
    apply_sampling: bool = False

    def __call__(self) -> pd.DataFrame:
        return decode(self.datatype, self.path,
                      apply_sampling=self.apply_sampling)


def _day_of(datatype: str, table: pd.DataFrame) -> pd.Series:
    if datatype == "flow":
        return table["treceived"].str.slice(0, 10)
    if datatype == "dns":
        return table["frame_time"].str.slice(0, 10)
    return table["p_date"].astype(str)


def _hour_of(datatype: str, table: pd.DataFrame) -> pd.Series:
    """Integer hour-of-day per row — the `h=` partition key. Same
    robust parsing as store.hour_of (format="mixed" handles unpadded
    hours like a bluecoat '9:15:00'); a fragile two-digit regex would
    file such rows into the wrong hour silently."""
    if datatype == "flow":
        col = table["treceived"]
    elif datatype == "dns":
        col = table["frame_time"]
    else:
        col = table["p_time"].astype(str)
    return pd.to_datetime(col, format="mixed").dt.hour


def ingest_file(store: Store, datatype: str,
                path: str | pathlib.Path,
                apply_sampling: bool = False,
                by_hour: bool = False, strict: bool = True,
                salvage: dict | None = None) -> dict[str, int]:
    """Decode one raw file and append its rows to the day partitions it
    spans (Store.append allocates part numbers atomically, so parallel
    worker threads AND processes never collide). With `by_hour`
    (store.partition_hours), rows land in y=/m=/d=/h= sub-partitions —
    the reference's hourly Hive level (SURVEY.md §2.1 #3) — which every
    day-scoped reader folds in transparently. `strict=False` decodes in
    salvage mode (skip-and-count — the retry policy's final attempt).
    Returns {date: n_rows}."""
    table = decode(datatype, path, apply_sampling=apply_sampling,
                   strict=strict, salvage=salvage)
    out: dict[str, int] = {}
    if not len(table):
        return out
    for date, day_rows in table.groupby(_day_of(datatype, table)):
        if by_hour:
            for hour, hr_rows in day_rows.groupby(
                    _hour_of(datatype, day_rows)):
                store.append(datatype, str(date),
                             hr_rows.reset_index(drop=True), hour=int(hour))
        else:
            store.append(datatype, str(date),
                         day_rows.reset_index(drop=True))
        out[str(date)] = len(day_rows)
    return out


def run_ingest(cfg: OnixConfig, datatype: str, paths: list[str]) -> int:
    store = Store(cfg.store.root)
    total = 0
    for p in paths:
        counts = ingest_file(store, datatype, p,
                             apply_sampling=cfg.ingest.apply_sampling,
                             by_hour=cfg.store.partition_hours)
        for date, n in sorted(counts.items()):
            print(f"{p}: {n} rows -> {datatype} {date}")
            total += n
    print(f"ingested {total} rows from {len(paths)} file(s)")
    return 0
