"""Directory watcher + worker pool — the ingest collector.

The reference's master collector watches landing directories and fans
work out to workers over Kafka (SURVEY.md §3.2). onix keeps the shape —
a polling watcher feeding a bounded worker pool — in one process with a
durable ledger of processed files, so restart gives at-least-once
redelivery (the property Kafka offsets gave the reference) without a
broker dependency. Files are claimed atomically from the ledger
(single-writer discipline, SURVEY.md §5.2).

Delivery semantics: the ledger records a file only AFTER its rows are in
the store, so a crash mid-ingest re-ingests the file on restart
(at-least-once — duplicate part files are possible after a crash, never
silent loss). A file must show the same size+mtime on two consecutive
polls before it is claimed, so half-written or still-growing captures
are left alone until the producer finishes them.
"""

from __future__ import annotations

import concurrent.futures
import json
import logging
import pathlib
import threading
import time

from onix.config import OnixConfig
from onix.ingest.run import DEFAULT_PATTERNS, ingest_file
from onix.store import Store

log = logging.getLogger("onix.ingest")


class Ledger:
    """Durable record of files already ingested (name+size+mtime keyed),
    guarded by a lock for worker threads.

    `claim` only reserves a file in memory (so two workers never race on
    it); `commit` persists it as done once ingest succeeds. A crash
    between the two leaves no durable record — the file is retried on
    restart."""

    def __init__(self, path: str | pathlib.Path):
        self.path = pathlib.Path(path)
        self._lock = threading.Lock()
        self._done: dict[str, list] = {}
        self._inflight: dict[str, list] = {}    # key -> sig AT CLAIM TIME
        if self.path.exists():
            self._done = json.loads(self.path.read_text())

    @staticmethod
    def _key(p: pathlib.Path) -> tuple[str, list]:
        st = p.stat()
        return str(p.resolve()), [st.st_size, st.st_mtime]

    def claim(self, p: pathlib.Path) -> bool:
        """Reserve a file for this process; False if done or in flight."""
        key, sig = self._key(p)
        with self._lock:
            if self._done.get(key) == sig or key in self._inflight:
                return False
            self._inflight[key] = sig
            return True

    def commit(self, p: pathlib.Path) -> None:
        """Durably record a successfully ingested file — under the
        signature captured at claim time, NOT the file's current one:
        rows appended while ingest was reading must leave the file
        looking changed, so the next poll re-offers it."""
        key = str(p.resolve())
        with self._lock:
            sig = self._inflight.pop(key, None)
            if sig is not None:
                self._done[key] = sig
                self._flush()

    def release(self, p: pathlib.Path) -> None:
        """Un-claim after a failed ingest so the next poll retries it."""
        key = str(p.resolve())
        with self._lock:
            self._inflight.pop(key, None)
            self._done.pop(key, None)
            self._flush()

    def _flush(self) -> None:
        tmp = self.path.with_suffix(".tmp")
        tmp.write_text(json.dumps(self._done))
        tmp.replace(self.path)


class IngestWatcher:
    """Poll a landing directory; ingest new files via a worker pool."""

    def __init__(self, cfg: OnixConfig, datatype: str,
                 landing_dir: str | pathlib.Path,
                 n_workers: int = 2, poll_interval: float = 0.5,
                 patterns: tuple[str, ...] = DEFAULT_PATTERNS,
                 require_stable: bool = True):
        self.cfg = cfg
        self.datatype = datatype
        self.landing = pathlib.Path(landing_dir)
        self.store = Store(cfg.store.root)
        self.poll_interval = poll_interval
        self.patterns = patterns
        self.require_stable = require_stable
        self.ledger = Ledger(self.landing / ".onix_ingest_ledger.json")
        self._last_sig: dict[str, list] = {}    # quiescence tracking
        self._pool = concurrent.futures.ThreadPoolExecutor(n_workers)
        self._stop = threading.Event()
        self._stats_lock = threading.Lock()
        self.stats: dict[str, int] = {"files": 0, "rows": 0, "errors": 0}

    def _candidates(self) -> list[pathlib.Path]:
        out: list[pathlib.Path] = []
        for pat in self.patterns:
            out.extend(self.landing.glob(pat))
        return sorted(out)

    def _stable(self, path: pathlib.Path) -> bool:
        """True once size+mtime are unchanged since the previous poll —
        a still-growing capture would otherwise be ingested twice (once
        truncated, once whole), duplicating its head rows."""
        key, sig = Ledger._key(path)
        prev = self._last_sig.get(key)
        self._last_sig[key] = sig
        return prev == sig

    def _work(self, path: pathlib.Path) -> None:
        try:
            counts = ingest_file(self.store, self.datatype, path,
                                 apply_sampling=self.cfg.ingest.apply_sampling,
                                 by_hour=self.cfg.store.partition_hours)
            self.ledger.commit(path)
            with self._stats_lock:
                self.stats["files"] += 1
                self.stats["rows"] += sum(counts.values())
        except Exception:
            log.exception("ingest failed for %s (will retry next poll)",
                          path)
            self.ledger.release(path)
            with self._stats_lock:
                self.stats["errors"] += 1

    def poll_once(self) -> int:
        """One poll cycle; returns the number of files dispatched."""
        dispatched = 0
        futures = []
        for path in self._candidates():
            try:
                if self.require_stable and not self._stable(path):
                    continue
                claimed = self.ledger.claim(path)
            except OSError:
                continue    # vanished/rotated between glob and stat
            if claimed:
                futures.append(self._pool.submit(self._work, path))
                dispatched += 1
        concurrent.futures.wait(futures)
        return dispatched

    def run(self, max_seconds: float | None = None) -> None:
        t0 = time.time()
        while not self._stop.is_set():
            self.poll_once()
            if max_seconds is not None and time.time() - t0 > max_seconds:
                break
            self._stop.wait(self.poll_interval)
        self._pool.shutdown(wait=True)

    def stop(self) -> None:
        self._stop.set()
