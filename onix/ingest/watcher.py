"""Directory watcher + worker pool — the ingest collector.

The reference's master collector watches landing directories and fans
work out to workers over Kafka (SURVEY.md §3.2). onix keeps the shape —
a polling watcher feeding a bounded worker pool — in one process with a
durable ledger of processed files, so restart gives at-least-once
redelivery (the property Kafka offsets gave the reference) without a
broker dependency. Files are claimed atomically from the ledger
(single-writer discipline, SURVEY.md §5.2).

Delivery semantics: the ledger records a file only AFTER its rows are in
the store, so a crash mid-ingest re-ingests the file on restart
(at-least-once — duplicate part files are possible after a crash, never
silent loss). A file must show the same size+mtime on two consecutive
polls before it is claimed, so half-written or still-growing captures
are left alone until the producer finishes them.

Poison handling (the resilience layer): a file that fails ingest is
retried at most `RetryPolicy.max_attempts` times — attempt counts
persisted in the ledger, exponential backoff between attempts, and the
FINAL attempt decoded in salvage mode (skip malformed records/blocks,
count them) so a mostly-good capture still lands. A file that exhausts
its budget moves to the `quarantine/` dead-letter directory with a JSON
sidecar (error, attempts, traceback, claim-time signature) and is
durably marked so that signature is never re-claimed — the pre-r8
watcher retried a poison file on every poll forever. (A RE-DELIVERED
copy has a fresh mtime and deliberately gets a fresh bounded budget —
the rule that lets an operator fix a capture and drop it back in.)
Every retry/quarantine/salvage event flows through `obs.counters` and
the watcher's `stats`.
"""

from __future__ import annotations

import concurrent.futures
import json
import logging
import pathlib
import threading
import time

from onix.config import OnixConfig
from onix.ingest.run import DEFAULT_PATTERNS, ingest_file
from onix.store import Store
from onix.utils.obs import counters
from onix.utils.resilience import (RetryPolicy, format_exception,
                                   quarantine_file)

log = logging.getLogger("onix.ingest")

QUARANTINE_DIR = "quarantine"


class Ledger:
    """Durable record of files already ingested (name+size+mtime keyed),
    plus per-file failure ATTEMPTS and the QUARANTINED dead-letter set,
    guarded by a lock for worker threads.

    `claim` only reserves a file in memory (so two workers never race on
    it); `commit` persists it as done once ingest succeeds. A crash
    between the two leaves no durable record — the file is retried on
    restart. Failure attempts persist across restarts too, so a watcher
    that crashes mid-retry-budget never resets a poison file's count.

    On-disk layout v2: {"done": {...}, "attempts": {key: [n, sig]},
    "quarantined": {key: sig}}. The v1 flat {key: sig} layout loads as
    all-done (upgraded on first flush)."""

    #: Lock discipline, machine-checked by the `locks` analysis pass:
    #: claim/commit/release/quarantine race across worker threads.
    GUARDED_BY = {"_done": "_lock", "_attempts": "_lock",
                  "_quarantined": "_lock", "_inflight": "_lock"}

    def __init__(self, path: str | pathlib.Path):
        self.path = pathlib.Path(path)
        self._lock = threading.Lock()
        self._done: dict[str, list] = {}
        self._attempts: dict[str, list] = {}    # key -> [n, sig]
        self._quarantined: dict[str, list] = {}
        self._inflight: dict[str, list] = {}    # key -> sig AT CLAIM TIME
        if self.path.exists():
            raw = json.loads(self.path.read_text())
            if "done" in raw and isinstance(raw.get("done"), dict):
                self._done = raw["done"]
                self._attempts = raw.get("attempts", {})
                self._quarantined = raw.get("quarantined", {})
            else:                   # v1 flat layout
                self._done = raw

    @staticmethod
    def _key(p: pathlib.Path) -> tuple[str, list]:
        st = p.stat()
        return str(p.resolve()), [st.st_size, st.st_mtime]

    def claim(self, p: pathlib.Path) -> bool:
        """Reserve a file for this process; False if done, quarantined
        (same signature — changed content gets a fresh chance), or in
        flight."""
        key, sig = self._key(p)
        with self._lock:
            if (self._done.get(key) == sig or key in self._inflight
                    or self._quarantined.get(key) == sig):
                return False
            self._inflight[key] = sig
            return True

    def commit(self, p: pathlib.Path) -> None:
        """Durably record a successfully ingested file — under the
        signature captured at claim time, NOT the file's current one:
        rows appended while ingest was reading must leave the file
        looking changed, so the next poll re-offers it."""
        key = str(p.resolve())
        with self._lock:
            sig = self._inflight.pop(key, None)
            if sig is not None:
                self._done[key] = sig
                self._attempts.pop(key, None)
                self._flush()

    def release(self, p: pathlib.Path) -> None:
        """Un-claim after a failed ingest so the next poll retries it.
        Only the in-flight claim is dropped: the durable `done` record
        of an EARLIER successful ingest of this path (the file has
        since changed) must survive a failed re-ingest."""
        key = str(p.resolve())
        with self._lock:
            self._inflight.pop(key, None)

    def attempts_of(self, p: pathlib.Path) -> int:
        """Persisted failure count for the file's CURRENT signature (a
        changed file restarts its budget)."""
        try:
            key, sig = self._key(p)
        except OSError:
            return 0
        with self._lock:
            n, rec_sig = self._attempts.get(key, (0, None))
            return int(n) if rec_sig == sig else 0

    def record_failure(self, p: pathlib.Path) -> tuple[int, list | None]:
        """Durably count one failed ingest attempt, keyed under the
        claim-time signature (a changed file restarts at 1). Returns
        (attempts so far, sig). The in-flight claim is left in place —
        the caller decides between release() and quarantine()."""
        key = str(p.resolve())
        with self._lock:
            sig = self._inflight.get(key)
            if sig is None:
                try:
                    _, sig = self._key(p)
                except OSError:
                    sig = None
            prev_n, prev_sig = self._attempts.get(key, (0, None))
            n = int(prev_n) + 1 if prev_sig == sig else 1
            self._attempts[key] = [n, sig]
            self._flush()
            return n, sig

    def quarantine(self, p: pathlib.Path, sig: list | None) -> None:
        """Durably mark a poison file so it is never re-claimed (under
        this signature); clears its claim and attempt record."""
        key = str(p.resolve())
        with self._lock:
            self._inflight.pop(key, None)
            self._attempts.pop(key, None)
            self._quarantined[key] = sig
            self._flush()

    def prune_missing(self) -> int:
        """Drop `done`/`attempts` entries whose file no longer exists on
        disk — a long-lived watcher over a rotating landing directory
        must not grow its ledger unboundedly. Quarantined entries are
        KEPT: their file was deliberately moved away (or the move
        failed), and the record is what keeps that exact signature
        dead-lettered across restarts."""
        with self._lock:
            gone = [k for k in (*self._done, *self._attempts)
                    if not pathlib.Path(k).exists()]
            for k in gone:
                self._done.pop(k, None)
                self._attempts.pop(k, None)
            if gone:
                self._flush()
            return len(gone)

    def _flush(self) -> None:
        tmp = self.path.with_suffix(".tmp")
        tmp.write_text(json.dumps({"done": self._done,
                                   "attempts": self._attempts,
                                   "quarantined": self._quarantined}))
        tmp.replace(self.path)


class IngestWatcher:
    """Poll a landing directory; ingest new files via a worker pool."""

    #: Lock discipline, machine-checked by the `locks` analysis pass:
    #: the worker pool's threads all tally into stats.
    GUARDED_BY = {"stats": "_stats_lock"}

    def __init__(self, cfg: OnixConfig, datatype: str,
                 landing_dir: str | pathlib.Path,
                 n_workers: int = 2, poll_interval: float = 0.5,
                 patterns: tuple[str, ...] = DEFAULT_PATTERNS,
                 require_stable: bool = True,
                 retry: RetryPolicy | None = None,
                 prune_every: int = 50):
        self.cfg = cfg
        self.datatype = datatype
        self.landing = pathlib.Path(landing_dir)
        self.store = Store(cfg.store.root)
        self.poll_interval = poll_interval
        self.patterns = patterns
        self.require_stable = require_stable
        self.retry = retry or RetryPolicy()
        self.quarantine_dir = self.landing / QUARANTINE_DIR
        self.ledger = Ledger(self.landing / ".onix_ingest_ledger.json")
        self._last_sig: dict[str, list] = {}    # quiescence tracking
        self._not_before: dict[str, float] = {}  # retry backoff gates
        self._prune_every = max(int(prune_every), 1)
        self._polls = 0
        self._pool = concurrent.futures.ThreadPoolExecutor(n_workers)
        self._stop = threading.Event()
        # GUARDED_BY is declared on the class (the `locks` pass reads
        # it there); the pool's worker threads all tally into stats.
        self._stats_lock = threading.Lock()
        self.stats: dict[str, int] = {"files": 0, "rows": 0, "errors": 0,
                                      "retries": 0, "quarantined": 0,
                                      "salvaged": 0}

    def _candidates(self) -> list[pathlib.Path]:
        out: list[pathlib.Path] = []
        for pat in self.patterns:
            out.extend(self.landing.glob(pat))
        return sorted(out)

    def _stable(self, path: pathlib.Path) -> bool:
        """True once size+mtime are unchanged since the previous poll —
        a still-growing capture would otherwise be ingested twice (once
        truncated, once whole), duplicating its head rows."""
        key, sig = Ledger._key(path)
        prev = self._last_sig.get(key)
        self._last_sig[key] = sig
        return prev == sig

    def _work(self, path: pathlib.Path) -> None:
        # Attempt number = persisted failures + this try; the LAST
        # budgeted attempt runs the decoder in salvage mode so a
        # mostly-good capture lands before the file is given up on.
        attempt = self.ledger.attempts_of(path) + 1
        strict = self.retry.strict_for_attempt(attempt)
        salvage: dict = {}
        try:
            counts = ingest_file(self.store, self.datatype, path,
                                 apply_sampling=self.cfg.ingest.apply_sampling,
                                 by_hour=self.cfg.store.partition_hours,
                                 strict=strict, salvage=salvage)
            self.ledger.commit(path)
            with self._stats_lock:
                self.stats["files"] += 1
                self.stats["rows"] += sum(counts.values())
                if salvage:
                    self.stats["salvaged"] += 1
            if salvage:
                log.warning("salvage-ingested %s: %s", path, salvage)
        except Exception as e:
            attempts, sig = self.ledger.record_failure(path)
            with self._stats_lock:
                self.stats["errors"] += 1
            if self.retry.exhausted(attempts):
                # Dead-letter: durable never-re-claim mark FIRST (the
                # mark survives even if the move below half-fails),
                # then the move + sidecar. An unwritable quarantine dir
                # (read-only mount, disk full) must not un-count the
                # quarantine or crash the worker — the ledger mark
                # already guarantees the file is never re-claimed.
                self.ledger.quarantine(path, sig)
                try:
                    sidecar = quarantine_file(
                        path, self.quarantine_dir, error=repr(e),
                        attempts=attempts, traceback=format_exception(e),
                        sig=sig)
                except OSError as move_err:
                    sidecar = None
                    counters.inc("ingest.quarantine_move_failed")
                    log.error("could not move %s to %s (%r) — ledger "
                              "mark still blocks re-claim", path,
                              self.quarantine_dir, move_err)
                with self._stats_lock:
                    self.stats["quarantined"] += 1
                log.error("quarantined %s after %d attempts (%r) — "
                          "sidecar %s", path, attempts, e, sidecar)
            else:
                self.ledger.release(path)
                delay = self.retry.backoff(attempts)
                self._not_before[str(path.resolve())] = time.time() + delay
                counters.inc("ingest.retries")
                with self._stats_lock:
                    self.stats["retries"] += 1
                log.exception(
                    "ingest failed for %s (attempt %d/%d, retry in %.1fs)",
                    path, attempts, self.retry.max_attempts, delay)

    def poll_once(self) -> int:
        """One poll cycle; returns the number of files dispatched."""
        dispatched = 0
        futures = []
        now = time.time()
        candidates = self._candidates()
        for path in candidates:
            try:
                key = str(path.resolve())
                if now < self._not_before.get(key, 0.0):
                    continue        # backing off after a failed attempt
                if self.require_stable and not self._stable(path):
                    continue
                claimed = self.ledger.claim(path)
            except OSError:
                continue    # vanished/rotated between glob and stat
            if claimed:
                self._not_before.pop(key, None)
                futures.append(self._pool.submit(self._work, path))
                dispatched += 1
        done, _ = concurrent.futures.wait(futures)
        for fut in done:
            # _work handles ingest errors itself; anything escaping it
            # (ledger flush on a full disk, a bug) must be LOUD — an
            # unread future is the one swallow the AST lint can't see.
            exc = fut.exception()
            if exc is not None:
                counters.inc("ingest.worker_crashes")
                log.error("ingest worker crashed: %r", exc)
        self._polls += 1
        if self._polls % self._prune_every == 0:
            # Bounded memory for long-lived watchers: ledger entries and
            # quiescence signatures of files that left the disk.
            self.ledger.prune_missing()
            live = {str(p.resolve()) for p in candidates}
            for k in [k for k in self._last_sig if k not in live]:
                del self._last_sig[k]
            for k in [k for k in self._not_before if k not in live]:
                del self._not_before[k]
        return dispatched

    def pending_retries(self) -> int:
        """Files still present in the landing dir whose retry budget is
        not yet resolved (backing off toward another attempt). Drain
        mode keeps polling while this is non-zero, so a single drain
        run carries every failure to its salvage-or-quarantine verdict
        instead of abandoning it mid-budget."""
        n = 0
        for key in list(self._not_before):
            if pathlib.Path(key).exists():
                n += 1
            else:
                self._not_before.pop(key, None)
        return n

    def run(self, max_seconds: float | None = None) -> None:
        t0 = time.time()
        while not self._stop.is_set():
            self.poll_once()
            if max_seconds is not None and time.time() - t0 > max_seconds:
                break
            self._stop.wait(self.poll_interval)
        self._pool.shutdown(wait=True)

    def stop(self) -> None:
        self._stop.set()
