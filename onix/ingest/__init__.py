"""Parallel ingest: decoders + watcher/worker loading into the store.

The TPU-era rendering of oni-ingest (reference README.md:35-38,79;
SURVEY.md §2.1 #1, §3.2) without the Kafka/Hadoop footprint: a polling
directory watcher fans decoded files out to a worker pool that writes
partitioned Parquet (onix.store) — same collector→worker→store shape,
one process.
"""
