"""DNS pcap ingest: tshark when installed, the native extractor always.

The reference ingests DNS *pcaps* via tshark field-extraction
(SURVEY.md §3.2; reference README.md:30-33). onix accepts a `.pcap`
directly: `extract_dns_tsv` drives real tshark as a subprocess when it
exists on PATH (same field list the reference used), otherwise the
native `onix-pcapdns` binary — both emit identical TSV, parsed by the
one `parse_tshark_dns` contract. `write_dns_pcap` synthesizes captures
for round-trip tests (the environment ships no pcap fixtures).
"""

from __future__ import annotations

import pathlib
import shutil
import struct
import subprocess

import numpy as np
import pandas as pd

_NATIVE_DIR = pathlib.Path(__file__).parent.parent.parent / "native" / "pcapdns"
_BIN_PATH = _NATIVE_DIR / "build" / "pcapdns"

TSHARK_ARGS = [
    "-T", "fields", "-e", "frame.time_epoch", "-e", "frame.len",
    "-e", "ip.src", "-e", "ip.dst", "-e", "dns.qry.name",
    "-e", "dns.qry.type", "-e", "dns.flags.rcode",
    "-Y", "dns.flags.response == 1 && ip && udp",
]


class PcapUnavailable(RuntimeError):
    pass


def _build_native() -> None:
    src = _NATIVE_DIR / "pcapdns.cpp"
    if (_BIN_PATH.exists()
            and _BIN_PATH.stat().st_mtime >= src.stat().st_mtime):
        return
    try:
        subprocess.run(["make", "-C", str(_NATIVE_DIR)], check=True,
                       capture_output=True)
    except (OSError, subprocess.CalledProcessError) as e:
        raise PcapUnavailable(f"cannot build onix-pcapdns: {e}") from e


def extract_dns_tsv(pcap_path: str | pathlib.Path) -> str:
    """pcap -> tshark-format TSV rows (DNS responses only)."""
    pcap_path = str(pcap_path)
    tshark = shutil.which("tshark")
    if tshark:
        p = subprocess.run([tshark, "-r", pcap_path, *TSHARK_ARGS],
                           capture_output=True, text=True, timeout=600)
        if p.returncode == 0:
            return p.stdout
        # fall through: a tshark that cannot read the file gets the
        # native decoder's (stricter) error instead
    _build_native()
    p = subprocess.run([str(_BIN_PATH), pcap_path], capture_output=True,
                       text=True, timeout=600)
    if p.returncode != 0:
        raise ValueError(f"{pcap_path}: {p.stderr.strip() or 'decode failed'}")
    return p.stdout


def parse_dns_pcap(pcap_path: str | pathlib.Path) -> pd.DataFrame:
    """pcap -> the dns table schema (via the shared TSV contract)."""
    import tempfile

    from onix.ingest.parsers import parse_tshark_dns

    tsv = extract_dns_tsv(pcap_path)
    with tempfile.NamedTemporaryFile("w", suffix=".tsv", delete=False) as f:
        f.write(tsv)
        tmp = f.name
    try:
        return parse_tshark_dns(tmp)
    finally:
        pathlib.Path(tmp).unlink(missing_ok=True)


# -- synthesized captures for round-trip tests ------------------------------


def _dns_response(qname: str, qtype: int, rcode: int) -> bytes:
    flags = 0x8000 | (rcode & 0xF)           # QR=1
    hdr = struct.pack(">HHHHHH", 0x1234, flags, 1, 0, 0, 0)
    q = b""
    for label in qname.strip(".").split("."):
        enc = label.encode()
        q += bytes([len(enc)]) + enc
    q += b"\x00" + struct.pack(">HH", qtype, 1)
    return hdr + q


def _ip_u32(s: str) -> int:
    a, b, c, d = (int(x) for x in s.split("."))
    return (a << 24) | (b << 16) | (c << 8) | d


def write_dns_pcap(table: pd.DataFrame, nanos: bool = False) -> bytes:
    """Encode dns rows (ip_src?, ip_dst, dns_qry_name, dns_qry_type,
    dns_qry_rcode, frame_time or epoch) as an Ethernet/IPv4/UDP pcap of
    DNS responses. frame_len in the OUTPUT equals the synthesized
    packet's length (self-consistent round trip)."""
    magic = 0xA1B23C4D if nanos else 0xA1B2C3D4
    out = bytearray(struct.pack("<IHHiIII", magic, 2, 4, 0, 0, 1 << 16, 1))
    if "frame_time_epoch" in table:
        epochs = table["frame_time_epoch"].to_numpy(np.float64)
    else:
        epochs = (pd.to_datetime(table["frame_time"]).astype(np.int64)
                  / 1e9).to_numpy()
    srcs = (table["ip_src"] if "ip_src" in table
            else pd.Series(["192.0.2.53"] * len(table)))
    for i in range(len(table)):
        dns = _dns_response(str(table["dns_qry_name"].iloc[i]),
                            int(table["dns_qry_type"].iloc[i]),
                            int(table["dns_qry_rcode"].iloc[i]))
        udp = struct.pack(">HHHH", 53, 33333, 8 + len(dns), 0) + dns
        total = 20 + len(udp)
        ip = struct.pack(">BBHHHBBHII", 0x45, 0, total, 0, 0, 64, 17, 0,
                         _ip_u32(str(srcs.iloc[i])),
                         _ip_u32(str(table["ip_dst"].iloc[i])))
        eth = b"\x02" * 6 + b"\x04" * 6 + struct.pack(">H", 0x0800)
        pkt = eth + ip + udp
        sec = int(epochs[i])
        frac = epochs[i] - sec
        out += struct.pack("<IIII", sec,
                           int(frac * (1e9 if nanos else 1e6)),
                           len(pkt), len(pkt))
        out += pkt
    return bytes(out)
