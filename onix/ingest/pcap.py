"""DNS pcap ingest: tshark when installed, the native extractor always.

The reference ingests DNS *pcaps* via tshark field-extraction
(SURVEY.md §3.2; reference README.md:30-33). onix accepts a `.pcap`
directly: `extract_dns_tsv` drives real tshark as a subprocess when it
exists on PATH (same field list the reference used), otherwise the
native `onix-pcapdns` binary — both emit identical TSV, parsed by the
one `parse_tshark_dns` contract. `write_dns_pcap` synthesizes captures
for round-trip tests (the environment ships no pcap fixtures).
"""

from __future__ import annotations

import pathlib
import shutil
import struct
import subprocess

import numpy as np
import pandas as pd

_NATIVE_DIR = pathlib.Path(__file__).parent.parent.parent / "native" / "pcapdns"
_BIN_PATH = _NATIVE_DIR / "build" / "pcapdns"

# tshark extracts v4 and v6 addresses through separate fields; the v6
# columns are merged back into the 7-column TSV contract the native
# extractor emits (RFC 5952 canonical text on both paths).
# `ip.proto != 41` drops IPv4-tunneled IPv6 (6in4/6to4/ISATAP): for
# those frames tshark populates BOTH address pairs (outer v4 + inner
# v6) while the native extractor skips them (outer proto 41, not UDP)
# — excluding them keeps the two branches' output identical for the
# same capture (ADVICE r2). `!ip` keeps native v6: for a plain IPv6
# frame the ip layer is absent, so the clause passes.
TSHARK_ARGS = [
    "-T", "fields", "-e", "frame.time_epoch", "-e", "frame.len",
    "-e", "ip.src", "-e", "ipv6.src", "-e", "ip.dst", "-e", "ipv6.dst",
    "-e", "dns.qry.name", "-e", "dns.qry.type", "-e", "dns.flags.rcode",
    "-Y", ("dns.flags.response == 1 && (ip || ipv6) && udp"
           " && (!ip || ip.proto != 41)"),
]


def _merge_tshark_v6(tsv: str) -> str:
    """Collapse the (ip.src, ipv6.src) and (ip.dst, ipv6.dst) column
    pairs into single src/dst columns. Exactly one of each pair is
    non-empty per row: the display filter requires ip or ipv6 and
    excludes proto-41 tunnels, the only frames that populate both. The
    ipv6 side still wins on a both-populated row (innermost layer —
    defense against filter drift)."""
    out = []
    for line in tsv.splitlines():
        if not line.strip():
            continue
        f = line.split("\t")
        if len(f) != 9:      # unexpected shape: let the parser complain
            out.append(line)
            continue
        out.append("\t".join([f[0], f[1], f[3] or f[2], f[5] or f[4],
                              f[6], f[7], f[8]]))
    return "\n".join(out) + ("\n" if out else "")


class PcapUnavailable(RuntimeError):
    pass


def _build_native() -> None:
    src = _NATIVE_DIR / "pcapdns.cpp"
    if (_BIN_PATH.exists()
            and _BIN_PATH.stat().st_mtime >= src.stat().st_mtime):
        return
    try:
        subprocess.run(["make", "-C", str(_NATIVE_DIR)], check=True,
                       capture_output=True)
    except (OSError, subprocess.CalledProcessError) as e:
        raise PcapUnavailable(f"cannot build onix-pcapdns: {e}") from e


def extract_dns_tsv(pcap_path: str | pathlib.Path) -> str:
    """pcap -> tshark-format TSV rows (DNS responses only)."""
    pcap_path = str(pcap_path)
    tshark = shutil.which("tshark")
    if tshark:
        p = subprocess.run([tshark, "-r", pcap_path, *TSHARK_ARGS],
                           capture_output=True, text=True, timeout=600)
        if p.returncode == 0:
            return _merge_tshark_v6(p.stdout)
        # fall through: a tshark that cannot read the file gets the
        # native decoder's (stricter) error instead
    _build_native()
    p = subprocess.run([str(_BIN_PATH), pcap_path], capture_output=True,
                       text=True, timeout=600)
    if p.returncode != 0:
        raise ValueError(f"{pcap_path}: {p.stderr.strip() or 'decode failed'}")
    return p.stdout


def _salvage_capture_bytes(data: bytes) -> tuple[bytes, int]:
    """Best-effort clean of a corrupt capture: (cleaned bytes, skipped
    block/record count). pcapng blocks carry explicit framed lengths
    (type, total_length, trailing total_length) — blocks whose framing
    lies are dropped and the walk resynchronizes at the reported
    boundary; classic pcap records are truncated at the first
    implausible header (incl_len past the snap ceiling). The cleaned
    bytes go back through the normal extractor."""
    import struct as _s

    if len(data) >= 4 and data[:4] == b"\x0a\x0d\x0d\x0a":     # pcapng

        def consistent(at: int) -> int:
            """Block length at `at` if its framing is self-consistent
            (sane length + the trailing total_length echo), else 0."""
            if at + 12 > len(data):
                return 0
            blen = _s.unpack_from("<I", data, at + 4)[0]
            if blen < 12 or blen % 4 or at + blen > len(data):
                return 0
            return blen if _s.unpack_from(
                "<I", data, at + blen - 4)[0] == blen else 0

        out = bytearray()
        skipped = 0
        off = 0
        while off + 12 <= len(data):
            blen = consistent(off)
            if blen:
                out += data[off:off + blen]
                off += blen
                continue
            # Corrupt framing: drop this block and RESYNC at the next
            # self-consistent block header (blocks are 4-aligned and
            # carry their length twice, so a scan re-anchors reliably).
            skipped += 1
            p = off + 4
            while p + 12 <= len(data) and not consistent(p):
                p += 4
            if p + 12 > len(data):
                break
            off = p
        return bytes(out), skipped
    if len(data) >= 24 and data[:4] in (b"\xd4\xc3\xb2\xa1",
                                        b"\x4d\x3c\xb2\xa1"):  # LE pcap
        out = bytearray(data[:24])
        skipped = 0
        off = 24
        while off + 16 <= len(data):
            incl = _s.unpack_from("<I", data, off + 8)[0]
            if incl > (1 << 20) or off + 16 + incl > len(data):
                skipped += 1
                break               # implausible record: truncate here
            out += data[off:off + 16 + incl]
            off += 16 + incl
        return bytes(out), skipped
    return data, 0


def parse_dns_pcap(pcap_path: str | pathlib.Path, strict: bool = True,
                   salvage: dict | None = None) -> pd.DataFrame:
    """pcap -> the dns table schema (via the shared TSV contract).

    `strict=False` (the retry policy's final attempt) salvages a
    corrupt capture: undecodable pcapng blocks / truncated pcap records
    are dropped (counted) and the surviving frames go through the
    normal extractor; malformed TSV rows are then line-skipped too. A
    capture yielding NOTHING still raises — quarantine material."""
    import tempfile

    from onix.ingest.parsers import parse_tshark_dns

    try:
        tsv = extract_dns_tsv(pcap_path)
    except ValueError:
        if strict:
            raise
        from onix.utils.obs import counters

        data = pathlib.Path(pcap_path).read_bytes()
        cleaned, skipped = _salvage_capture_bytes(data)
        if not skipped and cleaned == data:
            raise               # nothing to clean: not salvage material
        with tempfile.NamedTemporaryFile(
                suffix=pathlib.Path(pcap_path).suffix,
                delete=False) as f:
            f.write(cleaned)
            tmp_cap = f.name
        try:
            tsv = extract_dns_tsv(tmp_cap)
        finally:
            pathlib.Path(tmp_cap).unlink(missing_ok=True)
        if not tsv.strip():
            raise ValueError(f"{pcap_path}: nothing salvageable "
                             f"({skipped} corrupt blocks dropped)")
        counters.inc("salvage.pcap_skipped_blocks", skipped)
        counters.inc("salvage.files")
        if salvage is not None:
            salvage["skipped_blocks"] = (salvage.get("skipped_blocks", 0)
                                         + skipped)
    with tempfile.NamedTemporaryFile("w", suffix=".tsv", delete=False) as f:
        f.write(tsv)
        tmp = f.name
    try:
        return parse_tshark_dns(tmp, strict=strict, salvage=salvage)
    finally:
        pathlib.Path(tmp).unlink(missing_ok=True)


# -- synthesized captures for round-trip tests ------------------------------


def _dns_response(qname: str, qtype: int, rcode: int) -> bytes:
    flags = 0x8000 | (rcode & 0xF)           # QR=1
    hdr = struct.pack(">HHHHHH", 0x1234, flags, 1, 0, 0, 0)
    q = b""
    for label in qname.strip(".").split("."):
        enc = label.encode()
        q += bytes([len(enc)]) + enc
    q += b"\x00" + struct.pack(">HH", qtype, 1)
    return hdr + q


def _ip_u32(s: str) -> int:
    a, b, c, d = (int(x) for x in s.split("."))
    return (a << 24) | (b << 16) | (c << 8) | d


def write_dns_pcap(table: pd.DataFrame, nanos: bool = False) -> bytes:
    """Encode dns rows (ip_src?, ip_dst, dns_qry_name, dns_qry_type,
    dns_qry_rcode, frame_time or epoch) as an Ethernet/IP/UDP pcap of
    DNS responses; rows whose addresses contain ':' become IPv6
    packets. frame_len in the OUTPUT equals the synthesized packet's
    length (self-consistent round trip)."""
    import socket
    magic = 0xA1B23C4D if nanos else 0xA1B2C3D4
    out = bytearray(struct.pack("<IHHiIII", magic, 2, 4, 0, 0, 1 << 16, 1))
    if "frame_time_epoch" in table:
        epochs = table["frame_time_epoch"].to_numpy(np.float64)
    else:
        epochs = (pd.to_datetime(table["frame_time"]).astype(np.int64)
                  / 1e9).to_numpy()
    srcs = (table["ip_src"] if "ip_src" in table
            else table["ip_dst"].map(       # default server follows the
                lambda d: "2001:db8::53"    # row's address family
                if ":" in str(d) else "192.0.2.53"))
    for i in range(len(table)):
        dns = _dns_response(str(table["dns_qry_name"].iloc[i]),
                            int(table["dns_qry_type"].iloc[i]),
                            int(table["dns_qry_rcode"].iloc[i]))
        udp = struct.pack(">HHHH", 53, 33333, 8 + len(dns), 0) + dns
        src_s = str(srcs.iloc[i])
        dst_s = str(table["ip_dst"].iloc[i])
        if (":" in src_s) != (":" in dst_s):
            raise ValueError(
                f"row {i}: mixed address families ({src_s!r}, {dst_s!r}) "
                "cannot share one packet")
        if ":" in src_s:
            ip = struct.pack(">IHBB", 6 << 28, len(udp), 17, 64)
            ip += socket.inet_pton(socket.AF_INET6, src_s)
            ip += socket.inet_pton(socket.AF_INET6, dst_s)
            etype = 0x86DD
        else:
            total = 20 + len(udp)
            ip = struct.pack(">BBHHHBBHII", 0x45, 0, total, 0, 0, 64, 17, 0,
                             _ip_u32(src_s), _ip_u32(dst_s))
            etype = 0x0800
        eth = b"\x02" * 6 + b"\x04" * 6 + struct.pack(">H", etype)
        pkt = eth + ip + udp
        sec = int(epochs[i])
        frac = epochs[i] - sec
        out += struct.pack("<IIII", sec,
                           int(frac * (1e9 if nanos else 1e6)),
                           len(pkt), len(pkt))
        out += pkt
    return bytes(out)


def write_dns_pcapng(table: pd.DataFrame, *, tsresol: int | None = None,
                     extra_blocks: bool = True) -> bytes:
    """Encode dns rows as a pcapng capture (SHB + IDB + one Enhanced
    Packet Block per row) — Wireshark's default save format, which the
    native extractor must ingest without tshark. `tsresol` sets the
    IDB if_tsresol option (power-of-10 exponent; None = the 10^-6
    default); `extra_blocks` interleaves an unknown block type and a
    Name Resolution Block the reader must skip whole."""
    # Reuse the classic writer for the per-row Ethernet frames.
    pcap = write_dns_pcap(table)
    frames = []
    off = 24
    data = memoryview(pcap)
    while off + 16 <= len(pcap):
        ts_sec, ts_usec, incl, orig = struct.unpack_from("<IIII", pcap, off)
        off += 16
        frames.append((ts_sec + ts_usec / 1e6, orig,
                       bytes(data[off:off + incl])))
        off += incl

    def block(btype: int, body: bytes) -> bytes:
        pad = (-len(body)) % 4
        total = 12 + len(body) + pad
        return (struct.pack("<II", btype, total) + body + b"\0" * pad
                + struct.pack("<I", total))

    shb = block(0x0A0D0D0A,
                struct.pack("<IHHq", 0x1A2B3C4D, 1, 0, -1))
    idb_body = struct.pack("<HHI", 1, 0, 0)          # ethernet, snaplen 0
    if tsresol is not None:
        idb_body += struct.pack("<HHB3x", 9, 1, tsresol)   # if_tsresol
        idb_body += struct.pack("<HH", 0, 0)               # opt_endofopt
    out = bytearray(shb + block(0x00000001, idb_body))
    div = 10 ** (tsresol if tsresol is not None else 6)
    if extra_blocks:
        out += block(0x0BADBEEF, b"\x55" * 10)       # unknown: skip whole
    for i, (ts, orig, frame) in enumerate(frames):
        units = int(round(ts * div))
        out += block(0x00000006, struct.pack(
            "<IIIII", 0, units >> 32, units & 0xFFFFFFFF,
            len(frame), orig) + frame)
        if extra_blocks and i == 0:
            out += block(0x00000004, b"\x00" * 8)    # NRB: skip whole
    return bytes(out)
