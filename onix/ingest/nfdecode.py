"""ctypes bridge to the C++ netflow decoder + v5/v9/IPFIX writers.

The decoder (native/nfdecode) stands in for the reference's patched
nfdump fork (SURVEY.md §2.1 #2): binary NetFlow v5/v9/IPFIX capture →
flow table. The writers generate spec-conformant packet streams for
round-trip tests and synthetic captures (SURVEY.md §4.1 "C++ decoder
round-trip on synthesized nfcapd records").

nfcapd files (nfdump's on-disk container — the reference's flow landing
format, /root/reference/README.md:83) decode NATIVELY for layout-v1
files, uncompressed or block-compressed: the clean-room reader in
native/nfdecode decodes LZO1X and LZ4 blocks itself and BZ2 via the
system libbz2. Subprocess passthrough to an installed `nfdump` binary
(the DNS path's tshark pattern) remains only for layout v2+, BZ2
without a system libbz2, and compressed blocks the native decoders
reject (torn file or decoder gap — nfdump adjudicates). `write_nfcapd`
emits the same structure (with optional real block compression) so CI
decodes pinned committed fixtures without the tool.
"""

from __future__ import annotations

import ctypes
import pathlib
import struct
import subprocess

import numpy as np
import pandas as pd

_NATIVE_DIR = pathlib.Path(__file__).resolve().parent.parent.parent / \
    "native" / "nfdecode"
_LIB_PATH = _NATIVE_DIR / "build" / "libonix_nfdecode.so"
_BIN_PATH = _NATIVE_DIR / "build" / "nfdecode"

_lib = None

PROTO_NAMES = {1: "ICMP", 6: "TCP", 17: "UDP", 47: "GRE", 50: "ESP"}


class DecoderUnavailable(RuntimeError):
    pass


def _stale() -> bool:
    if not _LIB_PATH.exists() or not _BIN_PATH.exists():
        return True
    built = min(_LIB_PATH.stat().st_mtime, _BIN_PATH.stat().st_mtime)
    return any(built < (_NATIVE_DIR / f).stat().st_mtime
               for f in ("nfdecode.cpp", "Makefile"))


def load_library() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    if _stale():
        try:
            subprocess.run(["make", "-C", str(_NATIVE_DIR)], check=True,
                           capture_output=True, text=True)
        except (OSError, subprocess.CalledProcessError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            raise DecoderUnavailable(f"cannot build nfdecode: {detail}") from e
    lib = ctypes.CDLL(str(_LIB_PATH))
    u8 = ctypes.POINTER(ctypes.c_uint8)
    u16 = ctypes.POINTER(ctypes.c_uint16)
    u32 = ctypes.POINTER(ctypes.c_uint32)
    f64 = ctypes.POINTER(ctypes.c_double)
    lib.nf5_count.restype = ctypes.c_int64
    lib.nf5_count.argtypes = [u8, ctypes.c_int64]
    lib.nf5_decode.restype = ctypes.c_int64
    lib.nf5_decode.argtypes = [u8, ctypes.c_int64, ctypes.c_int64,
                               u32, u32, u16, u16, u8, u8, u32, u32, f64, f64]
    # Unified mixed v5/v9 entry points (template-based v9, RFC 3954).
    lib.nfx_count.restype = ctypes.c_int64
    lib.nfx_count.argtypes = [u8, ctypes.c_int64]
    lib.nfx_decode.restype = ctypes.c_int64
    lib.nfx_decode.argtypes = [u8, ctypes.c_int64, ctypes.c_int64,
                               u32, u32, u16, u16, u8, u8, u32, u32, f64, f64]
    lib.nfx_sampling.restype = ctypes.c_int64
    lib.nfx_sampling.argtypes = [u8, ctypes.c_int64]
    lib.nfx_decode_scaled.restype = ctypes.c_int64
    lib.nfx_decode_scaled.argtypes = list(lib.nfx_decode.argtypes)
    # nfcapd v1 container (clean-room reader; uncompressed or
    # block-compressed files).
    lib.nfcapd_count.restype = ctypes.c_int64
    lib.nfcapd_count.argtypes = [u8, ctypes.c_int64]
    lib.nfcapd_decode.restype = ctypes.c_int64
    lib.nfcapd_decode.argtypes = list(lib.nfx_decode.argtypes)
    u64 = ctypes.POINTER(ctypes.c_uint64)
    lib.nfcapd_count_all.restype = ctypes.c_int64
    lib.nfcapd_count_all.argtypes = [u8, ctypes.c_int64]
    lib.nfcapd_decode_v6.restype = ctypes.c_int64
    lib.nfcapd_decode_v6.argtypes = [
        u8, ctypes.c_int64, ctypes.c_int64,
        u64, u64, u64, u64, u8, u16, u16, u8, u8, u32, u32, f64, f64]
    # Raw block decompressors (tests cross-validate the clean-room LZ4
    # against the system liblz4; ASan drives torn/lying payloads).
    for fn in (lib.onix_lz4_block_decode, lib.onix_lzo1x_decode):
        fn.restype = ctypes.c_int64
        fn.argtypes = [u8, ctypes.c_int64, u8, ctypes.c_int64]
    _lib = lib
    return lib


def sampling_interval(data: bytes) -> int:
    """Exporter sampling interval from the stream's options records
    (NetFlow v9 field / IPFIX IE 34, the sampler-table IEs 50
    samplerRandomInterval / 305 samplingPacketInterval; carried in
    options data sets — RFC 3954 §6.1 / RFC 7011 §3.4.2.2). Returns 0
    when no options record announced one (v5 has no options mechanism).
    Last value in stream order wins, matching how exporters refresh
    exporter state."""
    lib = load_library()
    buf = np.frombuffer(data, np.uint8)
    bp = buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    s = lib.nfx_sampling(bp, len(data))
    if s < 0:
        raise ValueError("malformed netflow v5/v9/ipfix stream")
    return int(s)


def ip_to_str(ips: np.ndarray) -> np.ndarray:
    """uint32 host-order IPs -> dotted-quad strings, vectorized."""
    ips = np.asarray(ips, np.uint32)
    return np.char.add(
        np.char.add(
            np.char.add((ips >> 24).astype(str), "."),
            np.char.add(((ips >> 16) & 255).astype(str), ".")),
        np.char.add(((ips >> 8) & 255).astype(str),
                    np.char.add(".", (ips & 255).astype(str))))


def str_to_ip(strs) -> np.ndarray:
    parts = np.array([s.split(".") for s in strs], np.uint32)
    return (parts[:, 0] << 24) | (parts[:, 1] << 16) | (parts[:, 2] << 8) | parts[:, 3]


def decode_bytes(data: bytes, apply_sampling: bool = False,
                 strict: bool = True,
                 salvage: dict | None = None) -> pd.DataFrame:
    """Decode a (possibly mixed) v5/v9/IPFIX packet stream into the
    ingest flow table.

    With `strict=False` (the retry policy's final attempt), a malformed
    stream is SALVAGED instead of rejected: the longest decodable
    packet-aligned prefix lands as rows, the corrupt tail is skipped
    and counted (`salvage` dict + obs counters) — see
    `_salvage_wire_stream`. A stream with nothing decodable still
    raises, so a pure-garbage file quarantines rather than committing
    as an empty success.

    With `apply_sampling`, packet/byte counters are scaled by the
    ANNOUNCING exporter's sampling interval (options records, field 34
    or the sampler-table IEs 50/305; per v9 source id / IPFIX domain
    id, so one exporter's rate never inflates another's flows) — the
    equivalent of running the reference's nfdump fork with counter
    scaling on a sampled exporter. The decoder PRE-SCANS the stream for
    announcements, so flows ahead of a mid-capture (periodic-refresh)
    options record scale by the exporter's first announced rate rather
    than staying raw. Off by default: raw wire counters are the honest
    record of what was exported."""
    lib = load_library()
    buf = np.frombuffer(data, np.uint8)
    bp = buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    n = lib.nfx_count(bp, len(data))
    if n < 0:
        if not strict:
            return _salvage_wire_stream(data, apply_sampling, salvage)
        raise ValueError("malformed netflow v5/v9 stream")
    arrays = _flow_arrays(n)
    decode = lib.nfx_decode_scaled if apply_sampling else lib.nfx_decode
    wrote = _call_decode(decode, bp, len(data), n, arrays)
    if wrote != n:
        raise ValueError(f"decode error: wrote {wrote} of {n}")
    return _arrays_to_table(arrays, n)


def _wire_packet_cuts(data: bytes) -> list[int]:
    """Best-effort packet boundary offsets [0, end_of_pkt_1, ...] for a
    mixed v5/v9/IPFIX stream, walked from the headers alone: v5 length
    is computed from its record count, IPFIX carries an explicit length,
    and v9 is walked flowset-by-flowset (set ids 2..255 are reserved on
    the wire, so a u16 of 5/9/10 where a set id should be IS the next
    packet header). The walk stops at the first frame that no longer
    parses — everything before it is a candidate salvage prefix."""
    cuts = [0]
    off = 0
    n = len(data)
    while off + 4 <= n:
        ver = int.from_bytes(data[off:off + 2], "big")
        if ver == 5:
            cnt = int.from_bytes(data[off + 2:off + 4], "big")
            if not 0 < cnt <= 3000:
                break
            end = off + 24 + 48 * cnt
        elif ver == 10:
            ln = int.from_bytes(data[off + 2:off + 4], "big")
            if ln < 16:
                break
            end = off + ln
        elif ver == 9:
            p = off + 20
            if p > n:
                break
            while p + 4 <= n:
                sid = int.from_bytes(data[p:p + 2], "big")
                if sid in (5, 9, 10):
                    break           # next packet header
                flen = int.from_bytes(data[p + 2:p + 4], "big")
                if flen < 4 or p + flen > n:
                    p = -1          # malformed flowset framing
                    break
                p += flen
            if p < 0:
                break
            end = p
        else:
            break
        if end > n:
            break
        off = end
        cuts.append(off)
    return cuts


def _salvage_wire_stream(data: bytes, apply_sampling: bool,
                         salvage: dict | None) -> pd.DataFrame:
    """Salvage-mode decode of a malformed wire stream: bisect the
    longest packet-aligned prefix the native decoder accepts (prefix
    validity is monotone — packets are independently framed), decode
    it, and count the skipped tail. Raises the original malformed error
    when NOTHING decodes — an all-garbage file must quarantine, never
    commit as an empty success."""
    from onix.utils.obs import counters

    lib = load_library()
    buf = np.frombuffer(data, np.uint8)
    bp = buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    cuts = _wire_packet_cuts(data)
    lo, hi = 0, len(cuts) - 1       # cuts[lo] always decodable (empty)
    while lo < hi:
        mid = (lo + hi + 1) // 2
        if lib.nfx_count(bp, cuts[mid]) >= 0:
            lo = mid
        else:
            hi = mid - 1
    good = cuts[lo]
    n_rows = lib.nfx_count(bp, good) if good else 0
    if good == 0 or n_rows <= 0:
        raise ValueError("malformed netflow v5/v9 stream "
                         "(nothing salvageable)")
    skipped = len(data) - good
    counters.inc("salvage.wire_skipped_bytes", skipped)
    counters.inc("salvage.files")
    if salvage is not None:
        salvage["skipped_bytes"] = salvage.get("skipped_bytes", 0) + skipped
        salvage["salvaged_records"] = (salvage.get("salvaged_records", 0)
                                       + int(n_rows))
    return decode_bytes(data[:good], apply_sampling=apply_sampling)


def _flow_arrays(n: int) -> dict[str, np.ndarray]:
    return {
        "sip": np.empty(n, np.uint32), "dip": np.empty(n, np.uint32),
        "sport": np.empty(n, np.uint16), "dport": np.empty(n, np.uint16),
        "proto": np.empty(n, np.uint8), "tcp_flags": np.empty(n, np.uint8),
        "ipkt": np.empty(n, np.uint32), "ibyt": np.empty(n, np.uint32),
        "start_ts": np.empty(n, np.float64), "end_ts": np.empty(n, np.float64),
    }


def _call_decode(fn, bp, n_bytes: int, n: int,
                 arrays: dict[str, np.ndarray]) -> int:
    """Invoke one of the native decode entry points (they all share the
    10-output-pointer ABI) over the _flow_arrays columns — ONE copy of
    the pointer-order contract for every decode path."""
    def p(name, ct):
        return arrays[name].ctypes.data_as(ctypes.POINTER(ct))

    return fn(
        bp, n_bytes, n,
        p("sip", ctypes.c_uint32), p("dip", ctypes.c_uint32),
        p("sport", ctypes.c_uint16), p("dport", ctypes.c_uint16),
        p("proto", ctypes.c_uint8), p("tcp_flags", ctypes.c_uint8),
        p("ipkt", ctypes.c_uint32), p("ibyt", ctypes.c_uint32),
        p("start_ts", ctypes.c_double), p("end_ts", ctypes.c_double))


def _arrays_to_table(arrays: dict[str, np.ndarray], n: int,
                     ips_rendered: bool = False) -> pd.DataFrame:
    """Decoded column arrays -> the ingest flow table schema (shared by
    the wire-format and nfcapd-container decode paths). With
    `ips_rendered`, sip/dip are already display strings (the container
    path's mixed v4/v6 rendering)."""
    ts = pd.to_datetime(arrays["start_ts"], unit="s")
    return pd.DataFrame({
        "treceived": ts.strftime("%Y-%m-%d %H:%M:%S"),
        "sip": arrays["sip"] if ips_rendered else ip_to_str(arrays["sip"]),
        "dip": arrays["dip"] if ips_rendered else ip_to_str(arrays["dip"]),
        "sport": arrays["sport"].astype(np.int32),
        "dport": arrays["dport"].astype(np.int32),
        "proto": np.array([PROTO_NAMES.get(x, str(x))
                           for x in arrays["proto"]], dtype=object),
        "ipkt": arrays["ipkt"].astype(np.int64),
        "ibyt": arrays["ibyt"].astype(np.int64),
        "opkt": np.zeros(n, np.int64),    # v5 is unidirectional
        "obyt": np.zeros(n, np.int64),
        "tcp_flags": arrays["tcp_flags"].astype(np.int32),
    })


#: nfcapd file magic (uint16 0xA50C) in both byte orders — a BE-host
#: file must route to the container reader so the byte-order diagnostic
#: fires instead of a misleading "malformed wire stream".
_NFCAPD_MAGICS = (b"\x0c\xa5", b"\xa5\x0c")


def is_nfcapd(data: bytes) -> bool:
    return data[:2] in _NFCAPD_MAGICS


def decode_nfcapd(path: str | pathlib.Path, strict: bool = True,
                  salvage: dict | None = None) -> pd.DataFrame:
    """Decode an nfcapd file natively for layout-v1 files — uncompressed
    OR block-compressed (the clean-room reader in native/nfdecode
    decodes LZO1X and LZ4 blocks itself and BZ2 via the system libbz2;
    the reference's landing format, routinely compressed in the wild,
    no longer requires an external binary — VERDICT r2 next #7 and r03
    missing #1). Subprocess passthrough to an installed `nfdump` covers
    only what's genuinely left: BZ2 without a system libbz2 and other
    layout versions (nfdump 1.7's v2) — those stay the format owner's
    concern. Raises DecoderUnavailable when a file needs the absent
    tool.

    With `strict=False`, a malformed file (truncated mid-block,
    bit-flipped payload, lying block size) is salvaged block by block:
    the container's explicit block framing lets each data block decode
    independently, so intact blocks land as rows and corrupt ones are
    skipped and counted (`_salvage_nfcapd`).

    Counters come back exactly as stored: nfdump applies any sampling
    scaling when it captures/stores, so there is nothing left to scale
    here (the wire-format paths' apply_sampling has no container
    equivalent)."""
    data = pathlib.Path(path).read_bytes()
    lib = load_library()
    buf = np.frombuffer(data, np.uint8)
    bp = buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    n = lib.nfcapd_count_all(bp, len(data))
    if n == -1 or n == -5:
        # Malformed framing / a compressed block the native decoders
        # reject — both salvageable per block in non-strict mode.
        if not strict:
            return _salvage_nfcapd(data, path, salvage)
        if n == -1:
            raise ValueError(f"malformed nfcapd file: {path}")
    if n == -3:
        raise ValueError(
            f"{path}: nfcapd file written by a big-endian host is not "
            "supported (nfcapd is host-byte-order on disk)")
    # -2 decompressor unavailable (BZ2 w/o libbz2) / -4 other layout
    # version / -5 compressed block the native decoders reject (torn
    # file or decoder gap): all adjudicated by the format owner's tool.
    if n < 0:
        return _decode_nfcapd_nfdump(path)
    try:
        return _nfcapd_arrays_decode(data, lib, bp, int(n))
    except ValueError:
        if not strict:
            return _salvage_nfcapd(data, path, salvage)
        raise


def _nfcapd_arrays_decode(data: bytes, lib, bp, n: int) -> pd.DataFrame:
    arrays = {
        "sip_hi": np.empty(n, np.uint64), "sip_lo": np.empty(n, np.uint64),
        "dip_hi": np.empty(n, np.uint64), "dip_lo": np.empty(n, np.uint64),
        "is_v6": np.empty(n, np.uint8),
        "sport": np.empty(n, np.uint16), "dport": np.empty(n, np.uint16),
        "proto": np.empty(n, np.uint8), "tcp_flags": np.empty(n, np.uint8),
        "ipkt": np.empty(n, np.uint32), "ibyt": np.empty(n, np.uint32),
        "start_ts": np.empty(n, np.float64), "end_ts": np.empty(n, np.float64),
    }

    def p(name, ct):
        return arrays[name].ctypes.data_as(ctypes.POINTER(ct))

    wrote = lib.nfcapd_decode_v6(
        bp, len(data), n,
        p("sip_hi", ctypes.c_uint64), p("sip_lo", ctypes.c_uint64),
        p("dip_hi", ctypes.c_uint64), p("dip_lo", ctypes.c_uint64),
        p("is_v6", ctypes.c_uint8),
        p("sport", ctypes.c_uint16), p("dport", ctypes.c_uint16),
        p("proto", ctypes.c_uint8), p("tcp_flags", ctypes.c_uint8),
        p("ipkt", ctypes.c_uint32), p("ibyt", ctypes.c_uint32),
        p("start_ts", ctypes.c_double), p("end_ts", ctypes.c_double))
    if wrote != n:
        raise ValueError(f"nfcapd decode error: wrote {wrote} of {n}")
    v6 = arrays["is_v6"] != 0
    arrays["sip"] = _mixed_ip_strings(arrays["sip_hi"], arrays["sip_lo"], v6)
    arrays["dip"] = _mixed_ip_strings(arrays["dip_hi"], arrays["dip_lo"], v6)
    return _arrays_to_table(arrays, n, ips_rendered=True)


def _mixed_ip_strings(hi: np.ndarray, lo: np.ndarray,
                      v6: np.ndarray) -> np.ndarray:
    """(hi, lo) u64 halves + v6 mask -> display strings: dotted-quad
    for v4 rows, RFC 5952 compressed form for v6 (rendered per UNIQUE
    128-bit value — v6 rows are typically few)."""
    import ipaddress

    out = np.empty(len(lo), object)
    out[~v6] = ip_to_str(lo[~v6].astype(np.uint32)).astype(object)
    if v6.any():
        pairs = np.stack([hi[v6], lo[v6]], axis=1)
        uniq, inv = np.unique(pairs, axis=0, return_inverse=True)
        strs = np.array(
            [ipaddress.IPv6Address((int(h) << 64) | int(l)).compressed
             for h, l in uniq.tolist()], dtype=object)
        out[v6] = strs[inv]
    return out


#: nfcapd layout-v1 geometry shared by the reader and the salvager:
#: file header (12-byte fixed part + 128-byte ident), stat record, and
#: the per-block header (<IIHH: NumRecords, size, id, pad).
_NFCAPD_HEADER_LEN = 12 + 128
_NFCAPD_STAT_LEN = 136
_NFCAPD_BLOCK_HDR_LEN = 12


def _salvage_nfcapd(data: bytes, path, salvage: dict | None) -> pd.DataFrame:
    """Block-granular salvage of a malformed nfcapd v1 file. The
    container frames every block with an explicit size and blocks are
    self-contained (no cross-block template state, unlike v9), so each
    block is re-wrapped as its own single-block file and decoded
    independently: intact blocks land as rows, a truncated tail or a
    bit-flipped/lying block is skipped and counted. Raises when nothing
    decodes — an all-garbage file must quarantine, not commit empty."""
    from onix.utils.obs import counters

    lib = load_library()
    body_off = _NFCAPD_HEADER_LEN + _NFCAPD_STAT_LEN
    if len(data) < body_off or not is_nfcapd(data[:2]):
        raise ValueError(f"malformed nfcapd file: {path} "
                         "(header too short to salvage)")
    head, stat = data[:_NFCAPD_HEADER_LEN], data[body_off - _NFCAPD_STAT_LEN:
                                                 body_off]
    one_block_head = head[:8] + (1).to_bytes(4, "little") + head[12:]
    tables: list[pd.DataFrame] = []
    skipped = 0
    off = body_off
    while off + _NFCAPD_BLOCK_HDR_LEN <= len(data):
        size = int.from_bytes(data[off + 4:off + 8], "little")
        end = off + _NFCAPD_BLOCK_HDR_LEN + size
        if end > len(data):
            skipped += 1            # truncated tail block
            break
        blob = one_block_head + stat + data[off:end]
        buf = np.frombuffer(blob, np.uint8)
        bp = buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
        n = lib.nfcapd_count_all(bp, len(blob))
        if n < 0:
            skipped += 1            # bit-flipped / lying block
        else:
            try:
                tables.append(_nfcapd_arrays_decode(blob, lib, bp, int(n)))
            except ValueError:
                skipped += 1
        off = end
    total = sum(len(t) for t in tables)
    if total == 0:
        raise ValueError(f"malformed nfcapd file: {path} "
                         "(nothing salvageable)")
    counters.inc("salvage.nfcapd_skipped_blocks", skipped)
    counters.inc("salvage.files")
    if salvage is not None:
        salvage["skipped_blocks"] = (salvage.get("skipped_blocks", 0)
                                     + skipped)
        salvage["salvaged_records"] = (salvage.get("salvaged_records", 0)
                                       + total)
    return pd.concat(tables, ignore_index=True)


def _decode_nfcapd_nfdump(path: str | pathlib.Path) -> pd.DataFrame:
    """Compressed-nfcapd passthrough via an installed `nfdump` binary —
    same pattern as the DNS path's tshark passthrough. Raises
    DecoderUnavailable when nfdump is not installed."""
    try:
        # -N: plain numbers — without it nfdump scales big counters to
        # '1.2 M', which would crash the int() parse below.
        proc = subprocess.run(
            ["nfdump", "-r", str(path), "-q", "-N", "-o",
             "fmt:%ts,%te,%sa,%da,%sp,%dp,%pr,%flg,%ipkt,%ibyt"],
            check=True, capture_output=True, text=True, timeout=600)
    except FileNotFoundError as e:
        raise DecoderUnavailable(
            "this nfcapd file needs the nfdump tool installed — it is "
            "layout v2+, BZ2-compressed without a system libbz2, or "
            "carries a compressed block the native decoders reject "
            "(torn file or decoder gap). onix reads layout-v1 — "
            "uncompressed, LZO, LZ4, and (with libbz2) BZ2 — natively; "
            "COMPRESSED files beyond that need the format owner's "
            "tool") from e
    except subprocess.CalledProcessError as e:
        raise ValueError(f"nfdump failed on {path}: {e.stderr}") from e
    rows = [ln.split(",") for ln in proc.stdout.splitlines()
            if ln.strip() and ln.count(",") == 9]
    if not rows:
        return pd.DataFrame(columns=["treceived", "sip", "dip", "sport",
                                     "dport", "proto", "ipkt", "ibyt",
                                     "opkt", "obyt", "tcp_flags"])
    cols = list(zip(*rows))
    n = len(rows)
    flags = np.zeros(n, np.int32)   # nfdump prints symbolic flags; unused

    def port(x):
        # ICMP flows print 'type.code' (e.g. '8.0') in the port column.
        return int(float(x))

    return pd.DataFrame({
        "treceived": [t.strip().split(".")[0] for t in cols[0]],
        "sip": [s.strip() for s in cols[2]],
        "dip": [s.strip() for s in cols[3]],
        "sport": np.array([port(x) for x in cols[4]], np.int32),
        "dport": np.array([port(x) for x in cols[5]], np.int32),
        "proto": np.array([s.strip().upper() for s in cols[6]],
                          dtype=object),
        "ipkt": np.array([int(x) for x in cols[8]], np.int64),
        "ibyt": np.array([int(x) for x in cols[9]], np.int64),
        "opkt": np.zeros(n, np.int64),
        "obyt": np.zeros(n, np.int64),
        "tcp_flags": flags,
    })


def decode_file(path: str | pathlib.Path,
                apply_sampling: bool = False, strict: bool = True,
                salvage: dict | None = None) -> pd.DataFrame:
    data = pathlib.Path(path).read_bytes()
    if is_nfcapd(data):
        # Container files carry counters as nfdump stored them (any
        # sampling scaling already applied at capture) — apply_sampling
        # is a wire-format concern and has no effect here.
        return decode_nfcapd(path, strict=strict, salvage=salvage)
    return decode_bytes(data, apply_sampling=apply_sampling,
                        strict=strict, salvage=salvage)


# -- v5 packet writer (synthetic captures + round-trip tests) --------------


def write_v5(table: pd.DataFrame, *, sys_uptime_ms: int = 3_600_000,
             records_per_packet: int = 30) -> bytes:
    """Encode a flow table (uint32 sip/dip or dotted strings, numeric
    ports/proto/counters, float start_ts/end_ts epoch seconds) as a
    NetFlow v5 packet stream."""
    n = len(table)
    sip, dip, proto, flags = _numeric_cols(table)
    sport = table["sport"].to_numpy(np.int64)
    dport = table["dport"].to_numpy(np.int64)
    ipkt = table["ipkt"].to_numpy(np.int64)
    ibyt = table["ibyt"].to_numpy(np.int64)
    start = table["start_ts"].to_numpy(np.float64)
    end = table["end_ts"].to_numpy(np.float64)

    out = bytearray()
    seq = 0
    for lo in range(0, n, records_per_packet):
        hi = min(lo + records_per_packet, n)
        cnt = hi - lo
        # Router "boot" chosen per packet so flow offsets fit in uint32 ms:
        # unix_secs = first flow start; First/Last are offsets from boot.
        unix_secs = int(start[lo])
        boot = unix_secs - sys_uptime_ms / 1000.0
        out += struct.pack(">HHIIIIBBH", 5, cnt, sys_uptime_ms, unix_secs,
                           0, seq, 0, 0, 0)
        for i in range(lo, hi):
            first_ms = max(0, int(round((start[i] - boot) * 1000)))
            last_ms = max(first_ms, int(round((end[i] - boot) * 1000)))
            out += struct.pack(
                ">IIIHHIIIIHHBBBBHHBBH",
                int(sip[i]), int(dip[i]), 0, 0, 0,
                int(ipkt[i]) & 0xFFFFFFFF, int(ibyt[i]) & 0xFFFFFFFF,
                first_ms & 0xFFFFFFFF, last_ms & 0xFFFFFFFF,
                int(sport[i]) & 0xFFFF, int(dport[i]) & 0xFFFF,
                0, int(flags[i]) & 0xFF, int(proto[i]) & 0xFF, 0,
                0, 0, 0, 0, 0)
        seq += cnt
    return bytes(out)


# -- v9 packet writer (RFC 3954; round-trip tests + synthetic captures) ----

# (field_type, length) for the template the writer emits. Interleaved
# with a 2-byte padding field (type 210) so the decoder's skip-by-length
# path is exercised by every round-trip test.
_V9_FIELDS = [(8, 4), (12, 4), (7, 2), (11, 2), (4, 1), (6, 1),
              (210, 2), (2, 4), (1, 4), (22, 4), (21, 4)]
_V9_TEMPLATE_ID = 300


def _numeric_cols(table: pd.DataFrame):
    n = len(table)
    sip = table["sip"].to_numpy()
    if sip.dtype.kind in ("U", "O", "S"):
        sip = str_to_ip(table["sip"].astype(str))
        dip = str_to_ip(table["dip"].astype(str))
    else:
        sip = sip.astype(np.uint32)
        dip = table["dip"].to_numpy(np.uint32)
    proto = table["proto"].to_numpy()
    if proto.dtype.kind in ("U", "O", "S"):
        rev = {v: k for k, v in PROTO_NAMES.items()}
        proto = np.array([rev.get(str(x).upper(), 6) for x in proto], np.int64)
    flags = (table["tcp_flags"].to_numpy(np.int64)
             if "tcp_flags" in table else np.zeros(n, np.int64))
    return sip, dip, proto, flags


# -- IPFIX writer (RFC 7011; round-trip tests + synthetic captures) --------

# Template the IPFIX writer emits. Alongside the classic fields it
# plants the two RFC 7011 features absent from v9, so every round-trip
# test exercises the decoder's handling of them:
#   * an enterprise-specific field (bit 15 set + 4-byte enterprise
#     number) that the decoder must skip by length, and
#   * a variable-length field (declared length 0xFFFF; per-record 1- or
#     3-byte length prefix).
_IPFIX_TEMPLATE_ID = 310
_IPFIX_OPTIONS_TEMPLATE_ID = 320
_IPFIX_ENTERPRISE_NUM = 29305
_IPFIX_FIELDS = [(8, 4), (12, 4), (7, 2), (11, 2), (4, 1), (6, 1),
                 (0x8000 | 55, 4),     # enterprise field: skipped
                 (2, 4), (1, 4),
                 (82, 0xFFFF),         # interfaceName: variable-length
                 (152, 8), (153, 8)]   # flowStart/EndMilliseconds


def write_ipfix(table: pd.DataFrame, *, records_per_packet: int = 20,
                domain_id: int = 0, template_every_packet: bool = False,
                varlen_long_form: bool = False,
                with_options_set: bool = True,
                sampling_interval: int | None = None,
                sampling_field: int = 34) -> bytes:
    """Encode a flow table as an IPFIX (NetFlow v10) message stream.
    Same input schema as write_v5/write_v9.

    varlen_long_form encodes the variable-length field with the 3-byte
    (255 + uint16) prefix; with_options_set emits an options template
    set (id 3) plus its data set — exporter state the decoder must
    parse for metadata (sampling interval when `sampling_interval` is
    given) without ever emitting it as flow rows."""
    n = len(table)
    # The sampling announcement rides in the options set — asking for
    # one without the other would silently produce an unsampled stream.
    with_options_set = with_options_set or sampling_interval is not None
    sip, dip, proto, flags = _numeric_cols(table)
    sport = table["sport"].to_numpy(np.int64)
    dport = table["dport"].to_numpy(np.int64)
    ipkt = table["ipkt"].to_numpy(np.int64)
    ibyt = table["ibyt"].to_numpy(np.int64)
    start = table["start_ts"].to_numpy(np.float64)
    end = table["end_ts"].to_numpy(np.float64)

    tpl_body = struct.pack(">HH", _IPFIX_TEMPLATE_ID, len(_IPFIX_FIELDS))
    for ftype, flen in _IPFIX_FIELDS:
        tpl_body += struct.pack(">HH", ftype, flen)
        if ftype & 0x8000:
            tpl_body += struct.pack(">I", _IPFIX_ENTERPRISE_NUM)
    tpl_set = struct.pack(">HH", 2, 4 + len(tpl_body)) + tpl_body

    # Options template (scope: exporting process) and a matching data
    # set — exporter state, never flow rows. With `sampling_interval`
    # the record also carries IE 34, which the decoder surfaces via
    # nfx_sampling.
    n_opt_fields = 3 if sampling_interval is not None else 2
    opt_body = struct.pack(">HHH", _IPFIX_OPTIONS_TEMPLATE_ID,
                           n_opt_fields, 1)
    opt_body += struct.pack(">HH", 130, 4)   # scope: exporterIPv4Address
    opt_body += struct.pack(">HH", 41, 8)    # exportedMessageTotalCount
    rec_len = 12
    if sampling_interval is not None:
        # IE 34 by default; tests also exercise the sampler-table IEs
        # (50 samplerRandomInterval / 305 samplingPacketInterval).
        opt_body += struct.pack(">HH", sampling_field, 4)
        rec_len += 4
    opt_set = struct.pack(">HH", 3, 4 + len(opt_body)) + opt_body
    opt_data = struct.pack(">HH", _IPFIX_OPTIONS_TEMPLATE_ID, 4 + rec_len)
    opt_data += struct.pack(">IQ", 0x7F000001, 0)
    if sampling_interval is not None:
        opt_data += struct.pack(">I", sampling_interval)

    out = bytearray()
    seq = 0
    first_packet = True
    for lo in range(0, max(n, 1), records_per_packet):
        hi = min(lo + records_per_packet, n)
        cnt = hi - lo
        if cnt == 0 and not first_packet:
            break
        export_secs = int(start[lo]) if n else 0
        recs = bytearray()
        for i in range(lo, hi):
            name = b"eth0"
            recs += struct.pack(">IIHHBB", int(sip[i]), int(dip[i]),
                                int(sport[i]) & 0xFFFF,
                                int(dport[i]) & 0xFFFF,
                                int(proto[i]) & 0xFF, int(flags[i]) & 0xFF)
            recs += struct.pack(">I", 0xDEADBEEF)   # enterprise field
            recs += struct.pack(">II", int(ipkt[i]) & 0xFFFFFFFF,
                                int(ibyt[i]) & 0xFFFFFFFF)
            if varlen_long_form:                    # RFC 7011 §7 fig. S
                recs += struct.pack(">BH", 255, len(name)) + name
            else:
                recs += struct.pack(">B", len(name)) + name
            recs += struct.pack(">QQ", int(round(start[i] * 1000)),
                                int(round(end[i] * 1000)))
        pad = (-len(recs)) % 4
        recs += b"\0" * pad
        data_set = (struct.pack(">HH", _IPFIX_TEMPLATE_ID, 4 + len(recs))
                    + recs) if cnt else b""
        sets = b""
        if first_packet or template_every_packet:
            sets += tpl_set
            if with_options_set:
                sets += opt_set + opt_data
        sets += data_set
        msg_len = 16 + len(sets)
        out += struct.pack(">HHIII", 10, msg_len, export_secs, seq,
                           domain_id)
        out += sets
        seq += cnt
        first_packet = False
        if n == 0:
            break
    return bytes(out)


_V9_OPTIONS_TEMPLATE_ID = 400


def write_v9(table: pd.DataFrame, *, sys_uptime_ms: int = 3_600_000,
             records_per_packet: int = 20, source_id: int = 0,
             template_every_packet: bool = False,
             pad_template_flowset: bool = False,
             sampling_interval: int | None = None,
             sampling_field: int = 34) -> bytes:
    """Encode a flow table as a NetFlow v9 packet stream: a template
    flowset in the first packet (or every packet), then data flowsets.
    Same input schema as write_v5.

    pad_template_flowset appends RFC 3954 §5.2 zero padding after the
    template — real exporters do this; the decoder must treat it as
    padding, not as a malformed template header.

    sampling_interval additionally emits an options template flowset
    (RFC 3954 §6.1: scope + option field specs) plus an options data
    record carrying SAMPLING_INTERVAL (field 34) — exporter state that
    must surface through nfx_sampling, never as a flow row."""
    n = len(table)
    sip, dip, proto, flags = _numeric_cols(table)
    sport = table["sport"].to_numpy(np.int64)
    dport = table["dport"].to_numpy(np.int64)
    ipkt = table["ipkt"].to_numpy(np.int64)
    ibyt = table["ibyt"].to_numpy(np.int64)
    start = table["start_ts"].to_numpy(np.float64)
    end = table["end_ts"].to_numpy(np.float64)

    tpl_body = struct.pack(">HH", _V9_TEMPLATE_ID, len(_V9_FIELDS))
    for ftype, flen in _V9_FIELDS:
        tpl_body += struct.pack(">HH", ftype, flen)
    if pad_template_flowset:
        tpl_body += b"\0" * 4
    tpl_set = struct.pack(">HH", 0, 4 + len(tpl_body)) + tpl_body

    opt_sets = b""
    n_opt_items = 0
    if sampling_interval is not None:
        # Options template: scope System (4 bytes) + SAMPLING_INTERVAL
        # (34, 4 bytes); then one options data record.
        opt_body = struct.pack(">HHH", _V9_OPTIONS_TEMPLATE_ID, 4, 4)
        opt_body += struct.pack(">HH", 1, 4)    # scope spec: System
        opt_body += struct.pack(">HH", sampling_field, 4)   # option spec
        opt_sets = struct.pack(">HH", 1, 4 + len(opt_body)) + opt_body
        opt_sets += struct.pack(">HHII", _V9_OPTIONS_TEMPLATE_ID, 4 + 8,
                                source_id, sampling_interval)
        n_opt_items = 2   # header count: 1 options template + 1 record

    out = bytearray()
    seq = 0
    first_packet = True
    for lo in range(0, max(n, 1), records_per_packet):
        hi = min(lo + records_per_packet, n)
        cnt = hi - lo
        if cnt == 0 and not first_packet:
            break
        unix_secs = int(start[lo]) if n else 0
        boot = unix_secs - sys_uptime_ms / 1000.0
        recs = bytearray()
        for i in range(lo, hi):
            first_ms = max(0, int(round((start[i] - boot) * 1000)))
            last_ms = max(first_ms, int(round((end[i] - boot) * 1000)))
            recs += struct.pack(
                ">IIHHBBHIIII",
                int(sip[i]), int(dip[i]),
                int(sport[i]) & 0xFFFF, int(dport[i]) & 0xFFFF,
                int(proto[i]) & 0xFF, int(flags[i]) & 0xFF,
                0,                                  # padding field 210
                int(ipkt[i]) & 0xFFFFFFFF, int(ibyt[i]) & 0xFFFFFFFF,
                first_ms & 0xFFFFFFFF, last_ms & 0xFFFFFFFF)
        pad = (-len(recs)) % 4
        recs += b"\0" * pad
        data_set = (struct.pack(">HH", _V9_TEMPLATE_ID, 4 + len(recs))
                    + recs) if cnt else b""
        sets = b""
        n_items = cnt
        if first_packet or template_every_packet:
            sets += tpl_set + opt_sets
            n_items += 1 + n_opt_items
        sets += data_set
        out += struct.pack(">HHIIII", 9, n_items, sys_uptime_ms, unix_secs,
                           seq, source_id)
        out += sets
        seq += 1
        first_packet = False
        if n == 0:
            break
    return bytes(out)


# -- nfcapd v1 writer (fixtures + round-trip tests) ------------------------
#
# Emits the same on-disk structure the clean-room reader parses
# (native/nfdecode: file header 0xA50C/v1, stat record, type-2 data
# blocks of type-1 common records with the required extensions in
# order). The writer exists so CI can commit and decode a pinned binary
# fixture (tests/fixtures/) without an nfdump install; it deliberately
# exercises the layout's degrees of freedom — 32/64-bit counter flags,
# optional-extension tails, extension-map/exporter records to skip,
# IPv6 rows the flow schema drops.


def _lz4_block_compress(payload: bytes) -> bytes:
    """LZ4 block encoding for fixtures: the system liblz4 when loadable
    (real streams, matches included — the committed fixture uses this),
    else a single all-literals sequence (always valid per the block
    format: token literal nibble + extension bytes, no match after the
    final literals)."""
    try:
        lib = ctypes.CDLL("liblz4.so.1")
        lib.LZ4_compress_default.restype = ctypes.c_int
        lib.LZ4_compressBound.restype = ctypes.c_int
        bound = lib.LZ4_compressBound(len(payload))
        out = ctypes.create_string_buffer(bound)
        n = lib.LZ4_compress_default(payload, out, len(payload), bound)
        if n > 0:
            return out.raw[:n]
    except OSError:
        pass
    lit = len(payload)
    tok = min(lit, 15)
    head = bytes([tok << 4])
    if tok == 15:
        rest = lit - 15
        while rest >= 255:
            head += b"\xff"
            rest -= 255
        head += bytes([rest])
    return head + payload


def _lzo1x_compress(payload: bytes) -> bytes:
    """Greedy LZO1X encoder for fixtures — clean-room, emitting the
    well-specified subset: an initial/long literal run, M3 matches
    (3..33 bytes, distance <= 16384, found via a 3-byte hash table over
    prior output), and the M4 end-of-stream marker. The format requires
    a match between consecutive literal runs, so a payload with no
    3-byte repeats beyond the first 238 bytes is unencodable here —
    nfcapd block payloads (struct-packed records) always repeat.
    Decoded by the full-spec clean-room decoder in native/nfdecode."""
    n = len(payload)
    out = bytearray()
    pos = 0
    table: dict[bytes, int] = {}

    def find_match(p: int):
        """Next position >= p with a 3+ byte match within 16384 back."""
        while p + 3 <= n:
            key = payload[p:p + 3]
            prev = table.get(key)
            table[key] = p
            if prev is not None and p - prev <= 16384:
                length = 3
                while (length < 33 and p + length < n
                       and payload[prev + length] == payload[p + length]):
                    length += 1
                return p, prev, length
            p += 1
        return None

    def emit_literals(lo: int, hi: int, first: bool) -> None:
        run = hi - lo
        if run == 0:
            return          # back-to-back matches: no literals needed
        if first and run <= 238:
            out.append(run + 17)
        elif run <= 3:
            # Runs of 1-3 between matches ride the PREVIOUS match's
            # trailing-literal state — callers arrange that; a leading
            # short run has nowhere to go in this subset.
            raise ValueError("lzo subset: short literal run needs a "
                             "preceding match")
        elif run <= 18:
            out.append(run - 3)
        else:
            out.append(0)
            rest = run - 18
            while rest > 255:
                out.append(0)
                rest -= 255
            out.append(rest)
        out.extend(payload[lo:hi])

    def ride_previous_match(lo: int, hi: int) -> None:
        # The last three emitted bytes are always the previous M3
        # triple; its S & 3 bits carry 1-3 trailing literals.
        S = (out[-2] | (out[-1] << 8)) | (hi - lo)
        out[-2], out[-1] = S & 0xFF, S >> 8
        out.extend(payload[lo:hi])

    first = True
    while pos < n:
        m = find_match(pos)
        if m is None:
            if 1 <= n - pos <= 3 and not first:
                ride_previous_match(pos, n)     # short tail after a match
            else:
                emit_literals(pos, n, first)
            pos = n
            break
        at, prev, length = m
        lit_run = at - pos
        if 1 <= lit_run <= 3 and not first:
            ride_previous_match(pos, at)
        else:
            emit_literals(pos, at, first)
        first = False
        dist = at - prev
        out.append(32 | (length - 2))           # M3, lengths 3..33
        S = (dist - 1) << 2                     # trailing literals = 0
        out.extend((S & 0xFF, S >> 8))
        pos = at + length
    out.extend((0x11, 0x00, 0x00))              # M4 EOS (distance 16384)
    return bytes(out)


_NFCAPD_COMPRESSORS = {
    "lzo": (0x1, _lzo1x_compress),
    "bz2": (0x8, lambda p: __import__("bz2").compress(p)),
    "lz4": (0x10, _lz4_block_compress),
}


def write_nfcapd(table: pd.DataFrame, *, ident: str = "onix-fixture",
                 records_per_block: int = 100, with_extras: bool = True,
                 n_v6_rows: int = 0, compressed_flag: bool = False,
                 compression: str = "none") -> bytes:
    """Encode a flow table as an nfcapd layout-v1 file. Same input
    schema as write_v5. `n_v6_rows` appends IPv6 flow records (skipped
    by the v4 flow schema); `compression` in {"none","lzo","lz4","bz2"}
    block-compresses every data block like nfdump's -z/-y/-j;
    `compressed_flag` sets the LZO bit WITHOUT compressing — a lying
    header the reader must reject as malformed."""
    n = len(table)
    sip, dip, proto, flags = _numeric_cols(table)
    sport = table["sport"].to_numpy(np.int64)
    dport = table["dport"].to_numpy(np.int64)
    ipkt = table["ipkt"].to_numpy(np.int64)
    ibyt = table["ibyt"].to_numpy(np.int64)
    start = table["start_ts"].to_numpy(np.float64)
    end = table["end_ts"].to_numpy(np.float64)

    def common_record(i: int) -> bytes:
        rflags = 0
        if ipkt[i] > 0xFFFFFFFF:
            rflags |= 0x2                       # FLAG_PKG_64
        if ibyt[i] > 0xFFFFFFFF:
            rflags |= 0x4                       # FLAG_BYTES_64
        first, msec_first = int(start[i]), int(round((start[i] % 1) * 1000))
        last, msec_last = int(end[i]), int(round((end[i] % 1) * 1000))
        body = struct.pack("<HHHHIIBBBBHH", rflags, 0, msec_first % 1000,
                           msec_last % 1000, first, last, 0,
                           int(flags[i]) & 0xFF, int(proto[i]) & 0xFF, 0,
                           int(sport[i]) & 0xFFFF, int(dport[i]) & 0xFFFF)
        body += struct.pack("<II", int(sip[i]), int(dip[i]))
        body += struct.pack("<Q" if rflags & 0x2 else "<I", int(ipkt[i]))
        body += struct.pack("<Q" if rflags & 0x4 else "<I", int(ibyt[i]))
        if with_extras:
            # An optional extension tail (e.g. EX_IO_SNMP_2 in/out
            # interfaces) the reader must skip via the size field.
            body += struct.pack("<HH", 7, 11)
        return struct.pack("<HH", 1, 4 + len(body)) + body

    def v6_record() -> bytes:
        body = struct.pack("<HHHHIIBBBBHH", 0x1, 0, 0, 0, int(start[0]) if n
                           else 0, int(end[0]) if n else 0, 0, 0, 17, 0,
                           53, 53)
        body += b"\x20\x01\x0d\xb8" + b"\x00" * 12      # src 2001:db8::
        body += b"\x20\x01\x0d\xb8" + b"\x00" * 11 + b"\x01"
        body += struct.pack("<II", 3, 300)              # pkts, bytes
        return struct.pack("<HH", 1, 4 + len(body)) + body

    # Extension-map + exporter records the reader must skip whole.
    ext_map = struct.pack("<HHHH", 2, 12, 0, 4) + struct.pack("<HH", 4, 0)
    exporter = struct.pack("<HH", 7, 12) + b"\x00" * 8

    records: list[bytes] = [ext_map, exporter]
    records += [common_record(i) for i in range(n)]
    records += [v6_record() for _ in range(n_v6_rows)]

    if compression != "none" and compression not in _NFCAPD_COMPRESSORS:
        raise ValueError(f"unknown nfcapd compression {compression!r}")
    compress = (None if compression == "none"
                else _NFCAPD_COMPRESSORS[compression][1])
    blocks = b""
    n_blocks = 0
    for lo in range(0, max(len(records), 1), records_per_block):
        chunk = records[lo:lo + records_per_block]
        if not chunk:
            break
        payload = b"".join(chunk)
        if compress is not None:
            payload = compress(payload)
        blocks += struct.pack("<IIHH", len(chunk), len(payload), 2, 0)
        blocks += payload
        n_blocks += 1

    flags_word = (0x1 if compressed_flag else
                  0 if compression == "none"
                  else _NFCAPD_COMPRESSORS[compression][0])
    header = struct.pack("<HHII", 0xA50C, 1, flags_word, n_blocks)
    header += ident.encode()[:127].ljust(128, b"\0")
    stat = struct.pack("<Q", n) + b"\0" * 128            # numflows + rest
    return header + stat + blocks
