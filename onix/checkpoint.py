"""Sampler-state checkpointing for resume-on-preemption.

The reference's only "checkpointing" is its file-based stage contract —
lda-c writes model snapshots every N EM iterations and any stage can be
re-run by hand (SURVEY.md §5.4) — and an MPI rank failure kills the whole
LDA job with no resume (§5.3). onix checkpoints the full sampler state
(topic counts, token assignments, PRNG key, accumulators, sweep number)
every K sweeps, so a preempted TPU run resumes bit-identically: the
sweep kernel is a deterministic function of the saved state, which makes
resume-equals-uninterrupted a testable property, not a hope
(tests/test_checkpoint.py).

Format: one .npz of arrays + one .json of metadata per checkpoint,
written atomically (tmp + rename) with bounded retention. Orbax would
add async multi-host IO; for the K×V + N-token state sizes here, a
synchronous npz keeps the dependency surface flat while preserving the
same resume contract.

Integrity (the resilience layer): `save` stamps the sha256 of the npz
bytes into the meta json (`npz_sha256`, format bump `ckpt_format: 2`);
`load_latest` re-hashes the file and REFUSES a mismatching checkpoint —
a bit-flipped or short-written npz falls back to the previous
checkpoint instead of resuming from silently corrupt state (counted
under `ckpt.digest_mismatch`). Pre-digest checkpoints (no `npz_sha256`
key) keep loading: their torn-file semantics — json renamed only after
the npz is durable — already guard the failure mode they were written
under.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import pathlib

import numpy as np


class SimulatedPreemption(RuntimeError):
    """Raised by the fault-injection hook (SURVEY.md §5.3) to simulate a
    TPU preemption between sweeps; callers retry fit() to exercise the
    checkpoint-resume path."""


@dataclasses.dataclass
class Checkpoint:
    arrays: dict[str, np.ndarray]
    meta: dict

    @property
    def sweep(self) -> int:
        return int(self.meta["sweep"])


def _paths(ckpt_dir: pathlib.Path, sweep: int) -> tuple[pathlib.Path, pathlib.Path]:
    stem = f"ckpt-{sweep:06d}"
    return ckpt_dir / f"{stem}.npz", ckpt_dir / f"{stem}.json"


def save(ckpt_dir: str | pathlib.Path, sweep: int,
         arrays: dict[str, np.ndarray], meta: dict, keep: int = 2) -> None:
    """Atomically persist one checkpoint; prune to the newest `keep`.

    The .json is written (renamed into place) only after the .npz is
    durable, so a crash mid-save can never leave a checkpoint that
    `load_latest` would trust. The json carries the npz's sha256, which
    load_latest verifies — a checkpoint that rotted on disk after a
    clean save is refused, not resumed from.

    Chaos hook: a `ckpt:save=torn` rule in the active fault plan makes
    this save stop after the npz rename (the mid-crash torn state),
    exactly once."""
    from onix.utils import faults

    ckpt_dir = pathlib.Path(ckpt_dir)
    ckpt_dir.mkdir(parents=True, exist_ok=True)
    npz_path, json_path = _paths(ckpt_dir, sweep)

    tmp = npz_path.with_suffix(".npz.tmp")
    with open(tmp, "wb") as f:
        np.savez(f, **{k: np.asarray(v) for k, v in arrays.items()})
    h = hashlib.sha256()
    with open(tmp, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 22), b""):
            h.update(chunk)
    meta = dict(meta, sweep=int(sweep), npz_sha256=h.hexdigest(),
                ckpt_format=2)
    tmp.replace(npz_path)
    if faults.fire("ckpt", "save") == "torn":
        return      # simulated crash between the npz and json renames
    tmp_j = json_path.with_suffix(".json.tmp")
    tmp_j.write_text(json.dumps(meta, indent=2))
    tmp_j.replace(json_path)

    done = sorted(ckpt_dir.glob("ckpt-*.json"))
    for old in done[:-keep] if keep > 0 else []:
        old.with_suffix(".npz").unlink(missing_ok=True)
        old.unlink(missing_ok=True)


def load_latest(ckpt_dir: str | pathlib.Path) -> Checkpoint | None:
    """Newest complete AND intact checkpoint, or None. Incomplete pairs
    (crash between npz and json rename), unreadable npzs, and digest
    mismatches (bit rot, short write) all fall back to the next-older
    checkpoint — never a resume from corrupt state."""
    import logging

    from onix.utils.obs import counters

    ckpt_dir = pathlib.Path(ckpt_dir)
    if not ckpt_dir.exists():
        return None
    for json_path in sorted(ckpt_dir.glob("ckpt-*.json"), reverse=True):
        npz_path = json_path.with_suffix(".npz")
        if not npz_path.exists():
            continue
        try:
            meta = json.loads(json_path.read_text())
            want = meta.get("npz_sha256")
            if want is not None:
                # Chunked hash: a multi-GB sampler state must not be
                # double-buffered just to verify it.
                h = hashlib.sha256()
                with open(npz_path, "rb") as f:
                    for chunk in iter(lambda: f.read(1 << 22), b""):
                        h.update(chunk)
                if h.hexdigest() != want:
                    counters.inc("ckpt.digest_mismatch")
                    from onix.utils import telemetry
                    telemetry.RECORDER.dump(
                        "ckpt-digest-mismatch",
                        extra={"path": str(npz_path)})
                    logging.getLogger("onix.checkpoint").warning(
                        "checkpoint %s fails its sha256 digest — skipping "
                        "to the previous checkpoint", npz_path)
                    continue
            with np.load(npz_path) as z:
                arrays = {k: z[k] for k in z.files}
        except (json.JSONDecodeError, OSError, ValueError):
            continue        # torn file: fall back to an older checkpoint
        return Checkpoint(arrays=arrays, meta=meta)
    return None


# ---------------------------------------------------------------------------
# Multi-host shard layout (r21 hostfabric, onix/parallel/hostfabric.py).
#
# A multi-host fit checkpoints per HOST: ckpt_root/topology.json pins
# the (n_hosts, local_devices, fingerprint) shape of the run, and each
# worker writes ordinary `save()` checkpoints of its LOCAL state rows
# into ckpt_root/<fingerprint>/host-<i>/. The topology file lives
# OUTSIDE the fingerprint subdir on purpose: a topology change must be
# refused LOUDLY with a field-by-field diff, not silently miss the
# fingerprint-keyed directory and cold-start. Resume picks the newest
# sweep that is intact on EVERY host (a host that crashed mid-save has
# a newer shard the others lack — that sweep never resumes). The
# pre-r21 single-process layout (ckpt_dir/<fp>/ckpt-*.npz, no host-*
# subdirs, no topology.json) is untouched by all of this.
# ---------------------------------------------------------------------------

TOPOLOGY_FILE = "topology.json"


class TopologyMismatch(RuntimeError):
    """A sharded-fit resume was attempted under a different topology
    (host count, per-host device count, or fit fingerprint) than the
    one that wrote the checkpoints. Refused loudly — resuming per-host
    shards under a different shard assignment would silently corrupt
    counts. The explicit rebalance path (`--rebalance`) re-writes the
    topology deliberately via `claim_topology(..., force=True)`."""


def check_topology(ckpt_root: str | pathlib.Path, topo: dict) -> dict | None:
    """Compare `topo` against ckpt_root/topology.json. Returns the
    stored topology on match (None when no topology is claimed yet);
    raises TopologyMismatch with a per-field diff otherwise."""
    path = pathlib.Path(ckpt_root) / TOPOLOGY_FILE
    if not path.exists():
        return None
    stored = json.loads(path.read_text())
    diffs = [f"{k}: checkpoint has {stored.get(k)!r}, run wants {topo[k]!r}"
             for k in sorted(topo) if stored.get(k) != topo[k]]
    if diffs:
        raise TopologyMismatch(
            "refusing resume under a changed topology ("
            + "; ".join(diffs)
            + ") — restart with the original topology, or re-shard "
            "deliberately with --rebalance")
    return stored


def claim_topology(ckpt_root: str | pathlib.Path, topo: dict,
                   force: bool = False) -> dict:
    """Claim `topo` for ckpt_root: first claim writes topology.json
    atomically; a matching re-claim is a no-op; a mismatched re-claim
    raises TopologyMismatch unless `force` (the rebalance path), which
    re-writes the file stamping the displaced topology as
    `rebalanced_from` so the shard history stays auditable."""
    root = pathlib.Path(ckpt_root)
    root.mkdir(parents=True, exist_ok=True)
    path = root / TOPOLOGY_FILE
    try:
        stored = check_topology(root, topo)
    except TopologyMismatch:
        if not force:
            raise
        old = json.loads(path.read_text())
        old.pop("rebalanced_from", None)
        topo = dict(topo, rebalanced_from=old)
        stored = None
    if stored is not None:
        return stored
    tmp = path.with_suffix(".json.tmp")
    tmp.write_text(json.dumps(topo, indent=2))
    tmp.replace(path)
    return topo


def intact_sweeps(ckpt_dir: str | pathlib.Path) -> list[int]:
    """Sweeps in `ckpt_dir` with BOTH files of the pair present, sorted.
    (Presence only — the digest is verified at load time by load_at.)"""
    d = pathlib.Path(ckpt_dir)
    if not d.exists():
        return []
    return sorted(int(p.stem.split("-")[1]) for p in d.glob("ckpt-*.json")
                  if p.with_suffix(".npz").exists())


def latest_common_sweep(fp_dir: str | pathlib.Path,
                        n_hosts: int) -> int | None:
    """Newest sweep checkpointed intact by EVERY host-<i> dir under the
    fingerprint dir, or None when no sweep is common to all hosts."""
    common: set[int] | None = None
    for i in range(n_hosts):
        sweeps = set(intact_sweeps(pathlib.Path(fp_dir) / f"host-{i}"))
        common = sweeps if common is None else common & sweeps
        if not common:
            return None
    return max(common) if common else None


def load_at(ckpt_dir: str | pathlib.Path, sweep: int) -> Checkpoint | None:
    """Load exactly `sweep` from `ckpt_dir`, digest-verified; None when
    the pair is missing, torn, or fails its sha256. Unlike load_latest
    there is no fallback to an older sweep — multi-host resume must put
    every shard at the SAME sweep, so the coordinator picks the sweep
    (latest_common_sweep) and each worker either loads it or refuses."""
    from onix.utils.obs import counters

    npz_path, json_path = _paths(pathlib.Path(ckpt_dir), sweep)
    if not (npz_path.exists() and json_path.exists()):
        return None
    try:
        meta = json.loads(json_path.read_text())
        want = meta.get("npz_sha256")
        if want is not None:
            h = hashlib.sha256()
            with open(npz_path, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 22), b""):
                    h.update(chunk)
            if h.hexdigest() != want:
                counters.inc("ckpt.digest_mismatch")
                from onix.utils import telemetry
                telemetry.RECORDER.dump("ckpt-digest-mismatch",
                                        extra={"path": str(npz_path)})
                return None
        with np.load(npz_path) as z:
            arrays = {k: z[k] for k in z.files}
    except (json.JSONDecodeError, OSError, ValueError):
        return None
    return Checkpoint(arrays=arrays, meta=meta)


# ---------------------------------------------------------------------------
# Fitted-model persistence (r12 model bank, onix/serving/).
#
# A checkpoint is resumable sampler STATE; a model is the finished
# (θ, φ) PRODUCT a serving bank loads. Same file discipline as
# checkpoints — one npz + one json meta, atomic rename, sha256 stamped
# and verified — but keyed by a tenant NAME (slash-separated, e.g.
# "flow/20160708" from store.model_name) instead of a sweep number.
# `load_models` is the bank-aware bulk path: it returns HOST arrays
# for many tenants in one call so the bank can stack them and ship ONE
# device_put per table family (model_bank._ensure_resident), not B
# round-trips.
# ---------------------------------------------------------------------------


class ModelIntegrityError(RuntimeError):
    """A stored model's npz fails its sha256 digest — refuse to serve
    from it (counted under `ckpt.model_digest_mismatch`; the serving
    layer surfaces the refusal, docs/ROBUSTNESS.md)."""


def model_path(models_dir: str | pathlib.Path, name: str) -> pathlib.Path:
    """<models_dir>/<name>.npz, with the path-traversal guard the name
    (client-supplied through /score) requires."""
    root = pathlib.Path(models_dir).resolve()
    target = (root / f"{name}.npz").resolve()
    if root != target and root not in target.parents:
        raise ValueError(f"model name escapes the models dir: {name!r}")
    return target


def model_content_digest(theta, phi_wk) -> str:
    """Deterministic identity of a model's TABLES: sha256 over the raw
    array bytes + shapes. This — not `npz_sha256` — is what model
    LINEAGE chains on (`parent_digest`): npz bytes embed zip member
    timestamps, so two byte-identical fits saved at different times
    hash differently at the file level, while a crash-replayed daily
    supervisor re-saving the same fit must provably produce the same
    lineage (docs/ROBUSTNESS.md "continuous operation")."""
    h = hashlib.sha256()
    for a in (np.asarray(theta, np.float32), np.asarray(phi_wk, np.float32)):
        h.update(repr((a.shape, str(a.dtype))).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def save_model(models_dir: str | pathlib.Path, name: str,
               theta, arrays_phi_wk, meta: dict | None = None,
               epoch: int = 0, parent_epoch: int | None = None,
               parent_digest: str | None = None,
               extra_arrays: dict | None = None) -> pathlib.Path:
    """Atomically persist one tenant's fitted tables (npz + sha256'd
    json meta, the checkpoint discipline).

    `epoch` is the MODEL EPOCH (meta key `model_epoch`): 0 for a fresh
    fit, bumped by every online feedback update
    (feedback.online.OnlineUpdater.nudge_and_save) and by every daily
    refit (pipelines/daily.py). The serving bank keys its winner cache
    on it, so a consumer that re-banks the file can never serve winners
    computed under an older epoch.

    `parent_epoch`/`parent_digest` are the MODEL LINEAGE (r19): the
    epoch and `content_sha256` of the model this fit warm-started
    from, stamped so a day-N+1 model provably descends from day-N's —
    None (fresh/cold chain start) omits the keys. `extra_arrays` ride
    the npz next to theta/phi_wk (e.g. the daily supervisor's
    vocab word-key table, which maps φ̂ rows across days); loaders
    that only read theta/phi_wk are unaffected."""
    npz_path = model_path(models_dir, name)
    npz_path.parent.mkdir(parents=True, exist_ok=True)
    theta = np.asarray(theta, np.float32)
    phi_wk = np.asarray(arrays_phi_wk, np.float32)
    tmp = npz_path.with_suffix(".npz.tmp")
    with open(tmp, "wb") as f:
        np.savez(f, theta=theta, phi_wk=phi_wk,
                 **{k: np.asarray(v) for k, v in (extra_arrays or {}).items()})
    h = hashlib.sha256()
    with open(tmp, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 22), b""):
            h.update(chunk)
    lineage = {}
    if parent_epoch is not None:
        lineage["parent_epoch"] = int(parent_epoch)
    if parent_digest is not None:
        lineage["parent_digest"] = str(parent_digest)
    meta = dict(meta or {}, name=name,
                n_docs=int(theta.shape[-2]), n_vocab=int(phi_wk.shape[-2]),
                n_topics=int(theta.shape[-1]),
                model_epoch=int(epoch),
                content_sha256=model_content_digest(theta, phi_wk),
                **lineage,
                npz_sha256=h.hexdigest(), model_format=1)
    # Stage BOTH tmp files before either final rename, so the
    # npz/json-mismatch window on a re-save is just the two adjacent
    # replaces (a crash between them leaves a digest mismatch, which
    # load_model refuses — fail-closed, repaired by re-saving).
    tmp_j = npz_path.with_suffix(".json.tmp")
    tmp_j.write_text(json.dumps(meta, indent=2))
    tmp.replace(npz_path)
    tmp_j.replace(npz_path.with_suffix(".json"))
    return npz_path


def load_model(models_dir: str | pathlib.Path, name: str) -> Checkpoint | None:
    """One tenant's model as a Checkpoint (arrays: theta, phi_wk), or
    None when absent. Digest mismatches REFUSE (ModelIntegrityError) —
    a serving bank must never score against silently-rotted tables."""
    from onix.utils.obs import counters

    npz_path = model_path(models_dir, name)
    json_path = npz_path.with_suffix(".json")
    if not (npz_path.exists() and json_path.exists()):
        return None
    # Two reads on mismatch: a concurrent re-save replaces npz then
    # json (save_model), so a first read can catch new-npz/old-json;
    # the re-read sees the settled pair. A PERSISTENT mismatch (crash
    # mid-save, bit rot) still refuses.
    for attempt in range(2):
        meta = json.loads(json_path.read_text())
        want = meta.get("npz_sha256")
        if want is None:
            break
        h = hashlib.sha256()
        with open(npz_path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 22), b""):
                h.update(chunk)
        if h.hexdigest() == want:
            break
        if attempt:
            counters.inc("ckpt.model_digest_mismatch")
            # r18 flight recorder: a rot refusal on a serving model is
            # exactly the event an operator wants the runup to.
            from onix.utils import telemetry
            telemetry.RECORDER.dump("model-digest-mismatch",
                                    extra={"model": name})
            raise ModelIntegrityError(
                f"model {name!r} fails its sha256 digest — refusing to "
                "serve from it")
    with np.load(npz_path) as z:
        arrays = {k: z[k] for k in z.files}
    return Checkpoint(arrays=arrays, meta=meta)


def model_meta_epoch(models_dir: str | pathlib.Path,
                     name: str) -> int | None:
    """The persisted `model_epoch` of a stored model, or None when no
    model (complete meta) exists — WITHOUT hashing the npz. Writers
    re-saving a tenant (a re-fit, an online nudge) read this to bump
    past it: the serving winner cache keys on the epoch, so a re-save
    that kept the old epoch could serve winners computed under the
    previous tables forever."""
    json_path = model_path(models_dir, name).with_suffix(".json")
    if not json_path.exists():
        return None
    try:
        return int(json.loads(json_path.read_text()).get("model_epoch", 0))
    except (json.JSONDecodeError, OSError, ValueError):
        return None


def load_models(models_dir: str | pathlib.Path,
                names: list[str]) -> dict[str, Checkpoint]:
    """Bulk host-side load of many tenants' models (missing names are
    simply absent from the result; integrity failures still raise).
    The caller stacks these and ships one device_put per table family
    — the whole point of loading in bulk."""
    out = {}
    for name in names:
        m = load_model(models_dir, name)
        if m is not None:
            out[name] = m
    return out


def list_models(models_dir: str | pathlib.Path) -> list[str]:
    """Tenant names with a complete (npz + json) model under
    models_dir, sorted — what /bank/stats and the CLI enumerate."""
    root = pathlib.Path(models_dir)
    if not root.exists():
        return []
    out = []
    for p in root.rglob("*.npz"):
        if p.with_suffix(".json").exists():
            out.append(str(p.relative_to(root))[:-len(".npz")])
    return sorted(out)


# The LDAConfig fields that actually change what a Gibbs sweep computes.
# Deliberately NOT the whole config: raising n_sweeps to extend a run, or
# tweaking checkpoint_every / svi_* knobs the sampler never reads, must
# not discard resumable progress.
_SAMPLING_FIELDS = ("n_topics", "alpha", "eta", "burn_in", "block_size",
                    "seed", "n_chains", "sync_splits")

#: The fingerprint CONTRACT, machine-checked by `python -m
#: onix.analysis` (the `fingerprints` pass): every LDAConfig field the
#: engine modules read must appear here (value = where it joins a
#: checkpoint fingerprint) or in FINGERPRINT_EXEMPT (value = why it is
#: safe outside one). A new semantics-changing knob that reaches an
#: engine without joining either table is a lint finding — the next
#: `merge_staleness`-class knob cannot ship without resume refusal
#: (the r11/r14 contract; resume-refusal behavior itself is covered by
#: tests/test_sparse_gibbs.py, test_merge_async.py, test_scvb0.py).
FINGERPRINT_FIELDS: dict[str, str] = {
    "n_topics": "_SAMPLING_FIELDS (every fingerprint)",
    "alpha": "_SAMPLING_FIELDS (every fingerprint)",
    "eta": "_SAMPLING_FIELDS (every fingerprint)",
    "burn_in": "_SAMPLING_FIELDS (every fingerprint)",
    "block_size": "_SAMPLING_FIELDS (every fingerprint)",
    "seed": "_SAMPLING_FIELDS (every fingerprint)",
    "n_chains": "_SAMPLING_FIELDS (every fingerprint)",
    "sync_splits": "_SAMPLING_FIELDS (every fingerprint)",
    "superstep": "fingerprint(superstep=...) — the RESOLVED fused size",
    "sampler_form": "lda_gibbs.sampler_fingerprint (sparse arm only)",
    "sparse_active": "lda_gibbs.sampler_fingerprint (sparse arm only)",
    "sparse_mh": "lda_gibbs.sampler_fingerprint (sparse arm only)",
    "merge_form": "lda_gibbs.merge_fingerprint (async arm only)",
    "merge_staleness": "lda_gibbs.merge_fingerprint (async arm only)",
    "svi_tau0": "streaming _fingerprint svi list (layout 5)",
    "svi_kappa": "streaming _fingerprint svi list (layout 5)",
    "svi_local_iters": "streaming _fingerprint svi list (layout 5)",
    "svi_meanchange_tol": "streaming _fingerprint svi list (layout 5)",
    "svi_warm_iters": "streaming _fingerprint svi list (EFFECTIVE value)",
    "stream_estep": "streaming _fingerprint svi list (layout 5)",
}

#: Fields engines may read WITHOUT fingerprinting, each with the reason
#: it cannot silently change a resumed chain. Reviewed additions only.
FINGERPRINT_EXEMPT: dict[str, str] = {
    "n_sweeps": "run EXTENT, not chain semantics — extending a "
                "preempted run is the whole point of resume",
    "checkpoint_every": "save cadence: segments also break here, but "
                        "ll entries land denser-never-sparser and the "
                        "async τ>0 segmentation-dependence is the "
                        "documented in-band contract (ROBUSTNESS.md)",
    "nwk_form": "all three count-update forms are bit-identical "
                "(tested) — pure performance, documented as NOT part "
                "of the fingerprint in config.py",
    "svi_batch_size": "batch SVI minibatch slicing; the batch engine "
                      "has no checkpoint/resume path and the streaming "
                      "scorer's minibatches are the file feed",
    "svi_max_epochs": "batch SVI epoch cap — run extent, like n_sweeps",
    "svi_epoch_tol": "batch SVI early-stop — run extent, like n_sweeps",
}


def fingerprint(config, n_docs: int, n_vocab: int, n_tokens: int,
                extra: dict | None = None,
                superstep: int | None = None) -> str:
    """Identity of a resumable run: sampling-relevant hyperparams +
    corpus shape. A checkpoint from a different config/corpus must never
    be resumed into — shape-compatible mismatches (same D,V, different
    seed) are caught here; checkpoints live in a per-fingerprint subdir
    so runs with different identities never interfere.

    `superstep` is the RESOLVED fused-superstep size of the writing
    engine (not the raw config field, whose 0 means "auto"): the fused
    carry holds accumulator state and checkpoints land only at superstep
    boundaries, so resuming a run under a different S is refused here
    rather than producing a subtly different ll cadence/artifact. The
    parameter joining the payload is itself a layout bump — every
    pre-superstep checkpoint is refused, never misread."""
    full = dataclasses.asdict(config)
    payload = {
        "lda": {k: full[k] for k in _SAMPLING_FIELDS},
        "n_docs": int(n_docs), "n_vocab": int(n_vocab),
        "n_tokens": int(n_tokens),
        **(extra or {}),
    }
    if superstep is not None:
        payload["superstep"] = int(superstep)
    import hashlib
    return hashlib.sha256(
        json.dumps(payload, sort_keys=True).encode()).hexdigest()[:16]
