"""Partitioned telemetry store — the Hive/HDFS substrate equivalent.

The reference keeps telemetry in Hive tables `flow`/`dns`/`proxy`
partitioned by y/m/d(/h) on HDFS (SURVEY.md §2.1 #3, L3; reference
README.md:37 "Load data in Hadoop"). onix keeps the same logical layout
as a local (or network-mounted) Parquet dataset:

    <root>/<datatype>/y=YYYY/m=MM/d=DD[/h=HH]/part-NNNNN.parquet

The hourly level (the reference's `/h` — SURVEY.md §2.1 #3) is
optional per write: day-level parts and hour sub-partitions coexist,
and every day-scoped reader sees both. Hour partitions are what
streaming-by-hour ingest appends to and what `read_hour` slices
without touching the rest of the day.

Stage boundaries remain files (SURVEY.md §1 "Interfaces between layers
are files, not RPCs") so every stage stays independently re-runnable.
"""

from __future__ import annotations

import dataclasses
import os
import pathlib
import re
import uuid

import numpy as np
import pandas as pd

DATE_RE = re.compile(r"^(\d{4})-?(\d{2})-?(\d{2})$")


def parse_date(date: str) -> tuple[str, str, str]:
    """'2016-07-08' or '20160708' -> ('2016', '07', '08')."""
    m = DATE_RE.match(date)
    if not m:
        raise ValueError(f"bad date {date!r}; want YYYY-MM-DD or YYYYMMDD")
    return m.group(1), m.group(2), m.group(3)


@dataclasses.dataclass
class Store:
    root: str | pathlib.Path

    def partition_dir(self, datatype: str, date: str,
                      hour: int | None = None) -> pathlib.Path:
        y, mo, d = parse_date(date)
        pdir = (pathlib.Path(self.root) / datatype
                / f"y={y}" / f"m={mo}" / f"d={d}")
        if hour is not None:
            if not 0 <= int(hour) <= 23:
                raise ValueError(f"bad hour {hour!r}")
            pdir = pdir / f"h={int(hour):02d}"
        return pdir

    @staticmethod
    def day_part_files(pdir: pathlib.Path) -> list[pathlib.Path]:
        """All part files under a DAY dir: day-level parts first, then
        hour sub-partitions in hour order — the one enumeration every
        day-scoped reader shares."""
        return (sorted(pdir.glob("part-*.parquet"))
                + sorted(pdir.glob("h=*/part-*.parquet")))

    def write(self, datatype: str, date: str, table: pd.DataFrame,
              part: int = 0, hour: int | None = None) -> pathlib.Path:
        """Write one partition file (append-style via distinct part numbers)."""
        pdir = self.partition_dir(datatype, date, hour)
        pdir.mkdir(parents=True, exist_ok=True)
        path = pdir / f"part-{part:05d}.parquet"
        table.to_parquet(path, index=False)
        return path

    def append(self, datatype: str, date: str,
               table: pd.DataFrame,
               hour: int | None = None) -> pathlib.Path:
        """Append rows as the next free part file, safely across
        processes AND hosts sharing the store.

        The parquet is written to a unique temp name, then `os.link`ed
        into the next free `part-NNNNN` slot — link fails atomically
        (EEXIST) if another writer took the slot first (works on POSIX
        local filesystems and NFSv3+, unlike flock), in which case the
        next slot is tried. The visible part file is therefore always a
        complete parquet."""
        pdir = self.partition_dir(datatype, date, hour)
        pdir.mkdir(parents=True, exist_ok=True)
        tmp = pdir / f".tmp-{os.getpid()}-{uuid.uuid4().hex[:8]}.parquet"
        table.to_parquet(tmp, index=False)
        try:
            while True:
                # Numeric max, not lexicographic sort: at >=100001 parts
                # the 6-digit names sort before 5-digit ones and a
                # lexicographic last() would retry a taken slot forever.
                part = 1 + max(
                    (int(p.stem.split("-")[1])
                     for p in pdir.glob("part-*.parquet")), default=-1)
                path = pdir / f"part-{part:05d}.parquet"
                try:
                    os.link(tmp, path)
                    return path
                except FileExistsError:
                    continue    # lost the slot race; try the next number
        finally:
            tmp.unlink(missing_ok=True)

    def read(self, datatype: str, date: str) -> pd.DataFrame:
        """Read a full day partition — day-level parts AND hour
        sub-partitions, concatenated in enumeration order."""
        pdir = self.partition_dir(datatype, date)
        parts = self.day_part_files(pdir)
        if not parts:
            raise FileNotFoundError(
                f"no data for {datatype} {date} under {pdir}")
        return pd.concat([pd.read_parquet(p) for p in parts],
                         ignore_index=True)

    def read_hour(self, datatype: str, date: str, hour: int) -> pd.DataFrame:
        """Read ONE hour sub-partition."""
        pdir = self.partition_dir(datatype, date, hour)
        parts = sorted(pdir.glob("part-*.parquet"))
        if not parts:
            raise FileNotFoundError(
                f"no data for {datatype} {date} h={hour:02d} under {pdir}")
        return pd.concat([pd.read_parquet(p) for p in parts],
                         ignore_index=True)

    def hours(self, datatype: str, date: str) -> list[int]:
        """Hour sub-partitions present for a day, ascending."""
        pdir = self.partition_dir(datatype, date)
        return sorted(int(h.name[2:]) for h in pdir.glob("h=*")
                      if any(h.glob("part-*.parquet")))

    def dates(self, datatype: str) -> list[str]:
        """All dates with data for a datatype, ascending."""
        base = pathlib.Path(self.root) / datatype
        out = []
        for ddir in base.glob("y=*/m=*/d=*"):
            if self.day_part_files(ddir):
                y = ddir.parent.parent.name[2:]
                mo = ddir.parent.name[2:]
                d = ddir.name[2:]
                out.append(f"{y}-{mo}-{d}")
        return sorted(out)

    def has(self, datatype: str, date: str) -> bool:
        try:
            return bool(self.day_part_files(self.partition_dir(datatype,
                                                               date)))
        except ValueError:
            return False


def results_path(results_dir: str | pathlib.Path, datatype: str,
                 date: str) -> pathlib.Path:
    """Per-day scored-results CSV for OA — the L4→L5 contract
    (SURVEY.md §1: 'a scored-results CSV per day per datatype')."""
    y, mo, d = parse_date(date)
    return (pathlib.Path(results_dir) / f"{y}{mo}{d}"
            / f"{datatype}_results.csv")


def model_name(datatype: str, date: str, tenant: str | None = None) -> str:
    """Canonical bank key for a fitted model: the per-datatype ×
    per-day (× per-tenant) identity the serving layer addresses models
    by — `flow/20160708` or `flow/20160708/acme`. Used as the path stem
    under serving.models_dir (checkpoint.model_path) and as the tenant
    id in /score requests."""
    y, mo, d = parse_date(date)
    base = f"{datatype}/{y}{mo}{d}"
    return f"{base}/{tenant}" if tenant else base


def feedback_path(feedback_dir: str | pathlib.Path, datatype: str,
                  date: str) -> pathlib.Path:
    """Analyst feedback CSV the next ML run consumes (the L5→L4 noise
    filter loop, reference README.md:48)."""
    y, mo, d = parse_date(date)
    return (pathlib.Path(feedback_dir) / f"{datatype}_scores_{y}{mo}{d}.csv")


def hour_of(ts: pd.Series) -> np.ndarray:
    """Hour-of-day [0,24) as float (hour + minute fraction) from a
    timestamp-like column (string or datetime)."""
    dt = pd.to_datetime(ts, format="mixed")
    return (dt.dt.hour + dt.dt.minute / 60.0).to_numpy(np.float32)
