"""Pre-LDA corpus build: (ip, word) pairs → integer corpus + feedback loop.

The reference's FlowPreLDA/DNSPreLDA/ProxyPreLDA Spark jobs group words
per document (IP), assign integer word ids, write the lda-c corpus file,
and apply analyst feedback by duplicating labeled events ×DUPFACTOR —
the model-biasing "noise filter" loop (SURVEY.md §2.1 #8, reference
README.md:48). onix keeps the token-expanded view on device arrays
instead of a corpus file (onix.corpus), and the feedback contract is a
CSV of (ip, word) rows the analyst marked benign.
"""

from __future__ import annotations

import dataclasses
import pathlib

import numpy as np
import pandas as pd

from onix.corpus import Corpus
from onix.utils.arrays import unique_inverse
from onix.pipelines.words import WordTable


# Chunked unique-merge lives in onix.utils.arrays (shared with the
# scoring dedup path); keep the historical private alias for callers.
_unique_inverse = unique_inverse


def _sorted_table_lookup(keys: np.ndarray, values: np.ndarray,
                         ids: np.ndarray | None = None,
                         fill: int = -1) -> tuple[np.ndarray, np.ndarray]:
    """One searchsorted pass into an ascending key table. Returns
    (result, hit_mask): hits map to `ids[pos]` (or the table position
    when `ids` is None); misses map to `fill`. The single lookup idiom
    shared by the string path and the packed 10⁹-event streaming path —
    an edge-handling fix lands in exactly one place."""
    if len(keys) == 0:
        miss = np.zeros(len(values), bool)
        return np.full(len(values), fill, np.int32), miss
    pos = np.minimum(np.searchsorted(keys, values), len(keys) - 1)
    ok = keys[pos] == values
    out = ids[pos] if ids is not None else pos.astype(np.int32)
    return np.where(ok, out, np.int32(fill)), ok


def _lookup_sorted(keys: np.ndarray, values: np.ndarray, strict: bool,
                   what: str) -> np.ndarray:
    """Vectorized sorted-array lookup; unknown values -> -1 (strict=False)."""
    out, ok = _sorted_table_lookup(keys, values)
    if strict and not ok.all():
        missing = np.unique(np.asarray(values)[~ok])[:5]
        raise KeyError(f"unknown {what} (first 5): {missing.tolist()}")
    return out.astype(np.int32, copy=False)


@dataclasses.dataclass
class Vocabulary:
    """Deterministic word-string ↔ integer-id mapping (sorted unique)."""

    words: np.ndarray              # object [V], sorted

    @staticmethod
    def fit(*word_arrays: np.ndarray) -> "Vocabulary":
        return Vocabulary(np.unique(np.concatenate(word_arrays)))

    @property
    def size(self) -> int:
        return int(self.words.shape[0])

    def ids(self, words: np.ndarray, strict: bool = True) -> np.ndarray:
        """Map word strings to ids; unknown words -> -1 (strict=False)."""
        return _lookup_sorted(self.words, words, strict, "words")

    def save(self, path: str | pathlib.Path) -> None:
        pathlib.Path(path).write_text("\n".join(self.words) + "\n")

    @staticmethod
    def load(path: str | pathlib.Path) -> "Vocabulary":
        return Vocabulary(np.array(
            pathlib.Path(path).read_text().splitlines(), dtype=object))


@dataclasses.dataclass
class CorpusBundle:
    """A built corpus plus everything needed to attribute scores back to
    source events and to reproduce the build."""

    corpus: Corpus                 # includes feedback-duplicated tokens
    vocab: Vocabulary
    doc_keys: np.ndarray           # object [D] doc id -> IP string
    token_event: np.ndarray        # int64 [n_real_tokens] token -> event row
    n_real_tokens: int             # tokens from real events (before feedback)
    # Integer-keyed lookup tables, populated by the packed fast path:
    # ascending packed word keys / uint32 IPs with their vocab/doc ids.
    # They let the streaming scale path map a raw 10⁸-token chunk into
    # the TRAINED id spaces with one searchsorted against a tiny table —
    # no per-chunk unique sort, no string rendering (docs/PERF.md).
    word_key_sorted: np.ndarray | None = None   # int64 [V] ascending
    word_key_ids: np.ndarray | None = None      # int32 [V] -> vocab id
    doc_u32_sorted: np.ndarray | None = None    # uint32 [D] ascending
    doc_u32_ids: np.ndarray | None = None       # int32 [D] -> doc id

    def doc_index(self, ips: np.ndarray, strict: bool = True) -> np.ndarray:
        """Map IP strings to doc ids; unknown IPs -> -1 (strict=False)."""
        return _lookup_sorted(self.doc_keys, ips, strict, "IPs")

    def word_ids_packed(self, word_key: np.ndarray,
                        fill: int = -1) -> np.ndarray:
        """Map packed int64 word keys to trained vocab ids; unseen ->
        `fill`. O(n log V) against the [V]-sized table — built for
        full-chunk mapping on the 10⁹-event streaming path."""
        assert self.word_key_sorted is not None, "bundle lacks packed keys"
        return _sorted_table_lookup(self.word_key_sorted, word_key,
                                    self.word_key_ids, fill)[0]

    def doc_ids_u32(self, ip_u32: np.ndarray, fill: int = -1) -> np.ndarray:
        """Map uint32 IPs to trained doc ids; unseen -> `fill`."""
        assert self.doc_u32_sorted is not None, "bundle lacks u32 docs"
        return _sorted_table_lookup(self.doc_u32_sorted, ip_u32,
                                    self.doc_u32_ids, fill)[0]


def build_corpus(words: WordTable,
                 feedback: pd.DataFrame | None = None,
                 dupfactor: int = 1000) -> CorpusBundle:
    """Assemble the integer corpus; append feedback tokens ×dupfactor.

    Feedback rows are (ip, word) pairs the analyst labeled NOT suspicious
    (oa label == 3 in the reference's severity scheme [R-med]); massively
    duplicating them raises p(word|ip) so similar events stop surfacing —
    exactly the reference's DUPFACTOR mechanism (SURVEY.md §2.1 #8).
    Feedback referencing unseen ips/words is ignored (stale feedback from
    an earlier vocabulary must not poison today's run).
    """
    # Integer fast path — this runs once per token and is on the
    # billion-event path: unique/inverse over packed int64 word keys and
    # uint32 IPs, then render display strings for the UNIQUE entries only
    # (V and D are small) and remap ids to string-sorted order so the
    # result is bit-identical to the original string-keyed build.
    if words.word_key is not None:
        ukeys, winv = _unique_inverse(words.word_key)
        strings = words.render_keys(ukeys)
        worder = np.argsort(strings)
        wrank = np.empty(len(worder), np.int64)
        wrank[worder] = np.arange(len(worder))
        vocab = Vocabulary(strings[worder])
        word_ids = wrank[winv].astype(np.int32)
    else:
        vocab = Vocabulary.fit(words.word)
        word_ids = vocab.ids(words.word)

    if words.ip_u32 is not None or words.ip_u64 is not None:
        from onix.pipelines.words import ip_keys_to_strings, u32_to_ips
        if words.ip_u32 is not None:
            udocs, dinv = _unique_inverse(words.ip_u32)
            dstrings = u32_to_ips(udocs)
        else:
            # uint64 keys: canonical-v4 values plus IP_TAG'd dictionary
            # entries (IPv6 / non-canonical strings) — same unique-then-
            # render recipe, same string-sorted final ids.
            udocs, dinv = _unique_inverse(words.ip_u64)
            dstrings = ip_keys_to_strings(udocs, words.ip_table)
        dorder = np.argsort(dstrings)
        drank = np.empty(len(dorder), np.int64)
        drank[dorder] = np.arange(len(dorder))
        doc_keys = dstrings[dorder]
        doc_ids = drank[dinv].astype(np.int32)
    else:
        doc_keys = np.unique(words.ip)
        doc_ids = _lookup_sorted(doc_keys, words.ip, True, "IPs")

    fb_docs = np.empty(0, np.int32)
    fb_words = np.empty(0, np.int32)
    if feedback is not None and len(feedback):
        did = _lookup_sorted(doc_keys, feedback["ip"].astype(str).to_numpy(),
                             False, "IPs")
        wid = vocab.ids(feedback["word"].astype(str).to_numpy(), strict=False)
        keep = (did >= 0) & (wid >= 0)
        if keep.any():
            fb_docs = np.repeat(did[keep], dupfactor)
            fb_words = np.repeat(wid[keep], dupfactor)

    # No feedback: reuse the arrays — np.concatenate with an empty tail
    # still copies ~GBs at 10^8 tokens.
    corpus = Corpus(
        doc_ids=(np.concatenate([doc_ids, fb_docs]) if len(fb_docs)
                 else doc_ids),
        word_ids=(np.concatenate([word_ids, fb_words]) if len(fb_words)
                  else word_ids),
        n_docs=len(doc_keys),
        n_vocab=vocab.size,
    )
    return CorpusBundle(
        corpus=corpus,
        vocab=vocab,
        doc_keys=doc_keys,
        token_event=words.event_idx.astype(np.int64),
        n_real_tokens=words.n_rows,
        # ukeys/udocs come out of _unique_inverse ascending, so they are
        # the searchsorted tables; wrank/drank carry the final ids.
        word_key_sorted=(ukeys if words.word_key is not None else None),
        word_key_ids=(wrank.astype(np.int32)
                      if words.word_key is not None else None),
        doc_u32_sorted=(udocs if words.ip_u32 is not None else None),
        doc_u32_ids=(drank.astype(np.int32)
                     if words.ip_u32 is not None else None),
    )


def _flow_pair_layout(bundle: CorpusBundle, n_events: int) -> bool:
    """True when tokens are [src-doc | dst-doc] for the same events in
    order — the layout flow_words emits."""
    te = bundle.token_event
    return (te.shape[0] == 2 * n_events
            and np.array_equal(te[:n_events], np.arange(n_events))
            and np.array_equal(te[n_events:], te[:n_events]))


def _single_token_layout(bundle: CorpusBundle, n_events: int) -> bool:
    """True when token i IS event i — the dns/proxy layout (one client-IP
    document per event)."""
    te = bundle.token_event
    return (te.shape[0] == n_events
            and np.array_equal(te, np.arange(n_events)))


def select_suspicious_events(bundle: CorpusBundle, theta, phi_wk,
                             n_events: int, *, tol: float,
                             max_results: int,
                             serve_form: str = "auto"):
    """Score every event and select the bottom-`max_results` under
    `tol`, returning a scoring.TopK of EVENT indices.

    Strategy: when the θ·φᵀ table fits the device budget and the corpus
    has the flow [src|dst] token layout, the whole score→pair-min→
    select pipeline runs fused on device and only the winners transfer
    (scoring.table_pair_bottom_k). Otherwise fall back to token scoring
    + host pair-min + device selection. `serve_form` routes the table
    paths through the r15 serve gate (serving.serve_form for
    config-bearing callers; "auto"/ONIX_SERVE_FORM otherwise)."""
    import jax.numpy as jnp

    from onix.models import scoring

    theta_a = np.asarray(theta)
    n_vocab = int(np.asarray(phi_wk).shape[-2])
    n_docs = int(theta_a.shape[-2])
    chains = theta_a.shape[0] if theta_a.ndim == 3 else 1
    corpus = bundle.corpus
    n_real = bundle.n_real_tokens
    table_fits = chains * n_docs * n_vocab <= scoring.TABLE_MAX_ELEMS
    single = _single_token_layout(bundle, n_events)
    if table_fits and (single or _flow_pair_layout(bundle, n_events)):
        table = scoring.score_table(jnp.asarray(theta),
                                    jnp.asarray(phi_wk)).ravel()
        d = corpus.doc_ids[:n_real]
        w = corpus.word_ids[:n_real]
        idx = d.astype(np.int64) * n_vocab + w
        if single:
            return scoring.table_bottom_k_fast(
                table, jnp.asarray(idx.astype(np.int32)),
                tol=tol, max_results=max_results, serve_form=serve_form)
        return scoring.table_pair_bottom_k_fast(
            table, jnp.asarray(idx[:n_events].astype(np.int32)),
            jnp.asarray(idx[n_events:].astype(np.int32)),
            tol=tol, max_results=max_results, serve_form=serve_form)
    tok = scoring.score_all(theta, phi_wk, corpus.doc_ids[:n_real],
                            corpus.word_ids[:n_real])
    ev = event_scores(bundle, tok, n_events).astype(np.float32)
    return scoring.bottom_k(jnp.asarray(ev), tol=tol,
                            max_results=max_results)


def event_scores(bundle: CorpusBundle, token_scores: np.ndarray,
                 n_events: int) -> np.ndarray:
    """Per-event score = min over the event's tokens (most suspicious
    direction wins — flow events carry a src-doc and a dst-doc token).

    `token_scores` covers the REAL tokens only (feedback duplicates are
    training-only and never scored)."""
    if token_scores.shape[0] != bundle.n_real_tokens:
        raise ValueError("token_scores must cover exactly the real tokens")
    te = bundle.token_event
    # Flow layout fast path: the reduction is a single elementwise min —
    # np.minimum.at's unbuffered scatter is ~100x slower and dominates
    # at 10^8+ events. The O(n) layout check is cheap by comparison.
    if _flow_pair_layout(bundle, n_events):
        return np.minimum(token_scores[:n_events],
                          token_scores[n_events:]).astype(np.float64)
    out = np.full(n_events, np.inf, np.float64)
    np.minimum.at(out, te, token_scores)
    return out


def doc_rarity_scores(bundle: CorpusBundle, theta,
                      weights: np.ndarray | None = None):
    """Full per-document topic-rarity vector (scoring.doc_rarity), with
    evidence-free documents (feedback-only or padding rows) masked to
    +inf. Returns (scores [D], weights [D]); pass `weights` when the
    caller already holds the per-doc token counts so the O(n_tokens)
    bincount runs once per scoring run."""
    import jax.numpy as jnp

    from onix.models import scoring

    corpus = bundle.corpus
    if weights is None:
        weights = np.bincount(corpus.doc_ids[:bundle.n_real_tokens],
                              minlength=corpus.n_docs)
    weights = np.asarray(weights, np.float32)
    scores = np.asarray(scoring.doc_rarity(jnp.asarray(theta), weights))
    return np.where(weights > 0, scores, np.inf), weights


def select_suspicious_docs(bundle: CorpusBundle, theta,
                           max_results: int = 100,
                           weights: np.ndarray | None = None):
    """Rank DOCUMENTS (clients/IPs) by topic rarity — the campaign
    detector that complements per-event word rarity (scoring.doc_rarity
    has the full rationale). Returns (doc_index ascending-suspicious,
    scores) as numpy arrays, at most `max_results` rows."""
    scores, _w = doc_rarity_scores(bundle, theta, weights)
    order = np.argsort(scores, kind="stable")[:max_results]
    order = order[np.isfinite(scores[order])]
    return order, scores[order]
