"""Continuous-operation supervisor: the crash-anywhere multi-day loop
(r19; ROADMAP item 4, docs/ROBUSTNESS.md "continuous operation").

The r14 campaign orchestrator executes exactly ONE day's
ingest→fit→score→OA; production runs the pipeline EVERY day. This
supervisor drives `run_campaign` over N simulated days and owns the
lifecycle pieces a single day never needed:

* **Durable day ledger** — one atomic JSON per day (`DayLedger`, the r9
  checkpoint discipline: tmp + rename, sha256-stamped, schema-versioned,
  torn/rotted entries REFUSED on load) recording per-day per-datatype
  stage outcomes, winners, refit form, drift, and model lineage. A
  `kill -9` at ANY point — mid-prepare, mid-fit-superstep, mid-score,
  mid-ledger-write — resumes to artifacts identical to the
  uninterrupted run: completed days are skipped by their ledger entry,
  the interrupted day re-executes deterministically with its fits
  resuming through the r14 per-datatype checkpoint dirs (extended here
  across the day boundary), and a torn ledger entry is refused and the
  day re-run rather than trusted.

* **Model lineage** — each day's accepted fit persists through
  `checkpoint.save_model` with `parent_epoch`/`parent_digest` pointing
  at the previous ok day's model (content digests, not npz-file hashes,
  so a crash-replayed save provably reproduces the same chain). The
  stable `<datatype>/current` tenant re-saves every day with its epoch
  bumped past whatever is on disk — the r13 bank/winner-cache
  invalidation contract fires across days exactly as it does within
  one: a live server re-banking the file can never serve a mixed
  answer.

* **Warm-vs-cold refit, drift-gated** — each day's fit warm-starts
  from yesterday's persisted φ̂ (φ̂-as-prior z-init in the Streaming
  Gibbs style of arxiv 1601.01142, mapped across day vocabularies by
  packed word key) under a reduced sweep budget; the drift monitor
  (campaign.phi_topic_drift — per-topic total variation day-over-day,
  surfaced in OA output, the ledger, and the `daily.drift` histogram
  `/metrics` renders) falls back to a cold fit past `daily.drift_max`,
  the bounded-staleness quality posture of arxiv 0909.4603 applied
  across days.

* **Poison-day rollback** — a day whose fit diverges (non-finite or
  collapsing ll, NaN tables) or whose prepare stage fails past its
  bounded retry is marked `failed` in the ledger, its partial
  artifacts move to `<root>/quarantine/` with a JSON sidecar (the r9
  dead-letter discipline), and the NEXT day warm-starts from the last
  `ok` day's model — the chain degrades, never corrupts.

Fault sites (docs/ROBUSTNESS.md site table): `daily:day` (day entry,
one bounded retry), `daily:refit` (the warm/cold decision inside
run_campaign's fit stage, one bounded retry), `daily:ledger` (ledger
write entry; `raise` absorbed by one bounded retry, `torn` renders the
crash-between-write-and-rename state which the read-back verify
repairs). All three fire PRE-MUTATION, so the bounded retry replays a
deterministic computation.

Word-binning edges are fitted on the first executed day and persisted
(`<root>/edges/<datatype>.json`), then reused all week, so word
identities — and therefore φ̂ rows, feedback pairs, and the analyst's
dismissals — stay comparable across days.

Drivers: `python -m onix.pipelines.daily` (the chaos tests' subprocess
entry), scripts/exp_daily.py (the acceptance experiment), and the
bench `daily_loop` component.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import pathlib
import shutil
import time

import numpy as np

from onix import checkpoint
from onix.config import DATATYPES, DailyConfig
from onix.models.lda_gibbs import LL_PARITY_BAND
from onix.pipelines.campaign import run_campaign
from onix.utils import faults, telemetry
from onix.utils.obs import counters

log = logging.getLogger("onix.daily")

#: Supervisor manifest schema.
DAILY_SCHEMA = 1

#: Day-ledger entry schema. Bumping refuses (re-runs) old entries
#: instead of misreading them — the checkpoint `ckpt_format` rule.
LEDGER_FORMAT = 1

_RECORD_KEYS = ("ledger_format", "day", "body", "timing")


def _canonical(doc) -> bytes:
    return json.dumps(doc, sort_keys=True,
                      separators=(",", ":")).encode()


class DayLedger:
    """Durable JSON-per-day ledger under one directory.

    Write discipline (the r9 checkpoint rules, applied to the day
    chain): the record is staged to a `.tmp` and atomically renamed
    into place; a sha256 over the canonical record body is stamped
    inside, so `read` refuses torn files (crash mid-write), truncated
    renames, and bit rot alike — a refused entry means the day simply
    re-executes, which is safe because every day is deterministic in
    its inputs and its fits resume from their own checkpoints.

    `daily:ledger` is the fault site: fired at write entry
    (pre-mutation). `raise` is absorbed by one bounded retry; `torn`
    makes the write stop after staging the tmp (the crash-between-
    write-and-rename state), which the read-back verification below
    detects and repairs — and which a REAL crash at the same point
    leaves for the next run's resume scan to refuse."""

    def __init__(self, ledger_dir: str | pathlib.Path):
        self.dir = pathlib.Path(ledger_dir)
        self.dir.mkdir(parents=True, exist_ok=True)

    def path(self, day: int) -> pathlib.Path:
        return self.dir / f"day-{day:03d}.json"

    @staticmethod
    def _stamp(record: dict) -> dict:
        body = {k: record[k] for k in _RECORD_KEYS}
        return dict(body, sha256=hashlib.sha256(
            _canonical(body)).hexdigest())

    def write(self, day: int, body: dict, timing: dict) -> pathlib.Path:
        for attempt in (0, 1):
            try:
                action = faults.fire("daily", "ledger")
                break
            except faults.InjectedFault:
                counters.inc("daily.ledger_retry")
                if attempt:
                    raise
        record = self._stamp({"ledger_format": LEDGER_FORMAT,
                              "day": int(day), "body": body,
                              "timing": timing})
        path = self.path(day)
        tmp = path.with_suffix(".json.tmp")
        tmp.write_text(json.dumps(record, indent=2) + "\n")
        if action == "torn":
            counters.inc("daily.ledger_torn")
        else:
            tmp.replace(path)
        # Read-back verification: the entry a restart would trust must
        # exist NOW, or this process would hand the next day a chain
        # state the disk does not back. Repairs the torn render above
        # (one-shot, so the repair lands) and catches fs lies.
        if self.read(day) is None:
            counters.inc("daily.ledger_repair")
            tmp.write_text(json.dumps(record, indent=2) + "\n")
            tmp.replace(path)
            if self.read(day) is None:
                raise RuntimeError(
                    f"day ledger entry {path} unreadable after repair")
        return path

    def read(self, day: int) -> dict | None:
        """The verified record for `day`, or None (absent, torn,
        truncated, rotted, wrong format — all counted, all safe: the
        supervisor re-executes the day)."""
        path = self.path(day)
        if not path.exists():
            return None
        try:
            record = json.loads(path.read_text())
        except (OSError, ValueError):
            counters.inc("daily.ledger_refused")
            log.warning("day ledger %s is unparseable — refusing it; "
                        "the day will re-execute", path)
            return None
        if (record.get("ledger_format") != LEDGER_FORMAT
                or record.get("day") != day
                or any(k not in record for k in _RECORD_KEYS)):
            counters.inc("daily.ledger_refused")
            log.warning("day ledger %s has the wrong format/day — "
                        "refusing it", path)
            return None
        want = record.get("sha256")
        got = hashlib.sha256(_canonical(
            {k: record[k] for k in _RECORD_KEYS})).hexdigest()
        if want != got:
            counters.inc("daily.ledger_refused")
            log.warning("day ledger %s fails its sha256 — refusing it "
                        "(torn or rotted); the day will re-execute", path)
            return None
        return record


# ---------------------------------------------------------------------------
# Fitted-edges persistence: day 1 fits the word binning, every later
# day applies it, and a restart reloads it — cross-day word identity is
# a DURABLE property, not an accident of process lifetime.
# ---------------------------------------------------------------------------


def _encode_edges(edges: dict) -> dict:
    out = {}
    for name, v in edges.items():
        if isinstance(v, np.ndarray):
            out[name] = {"__nd__": v.tolist(), "dtype": str(v.dtype)}
        else:
            out[name] = v
    return out


def _decode_edges(doc: dict) -> dict:
    out = {}
    for name, v in doc.items():
        if isinstance(v, dict) and "__nd__" in v:
            out[name] = np.asarray(v["__nd__"], dtype=v["dtype"])
        else:
            out[name] = v
    return out


def _edges_path(root: pathlib.Path, datatype: str) -> pathlib.Path:
    return root / "edges" / f"{datatype}.json"


def _save_edges(root: pathlib.Path, datatype: str, edges: dict) -> None:
    path = _edges_path(root, datatype)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_suffix(".json.tmp")
    tmp.write_text(json.dumps(_encode_edges(edges)) + "\n")
    tmp.replace(path)


def _load_edges(root: pathlib.Path, datatypes) -> dict:
    out = {}
    for dt in datatypes:
        path = _edges_path(root, dt)
        if not path.exists():
            continue
        try:
            out[dt] = _decode_edges(json.loads(path.read_text()))
        except (OSError, ValueError):
            counters.inc("daily.edges_refused")
            log.warning("fitted edges %s unreadable — refitting fresh "
                        "edges this run", path)
    return out


# ---------------------------------------------------------------------------
# The supervisor.
# ---------------------------------------------------------------------------


def _day_dir(root: pathlib.Path, day: int) -> pathlib.Path:
    return root / "days" / f"day-{day:03d}"


def _quarantine_day(root: pathlib.Path, day: int, error: str) -> None:
    """Dead-letter a poison day (the r9 quarantine discipline): its
    partial artifacts (fit checkpoints, anything staged under the day
    dir) MOVE to `<root>/quarantine/day-NNN` with a JSON sidecar, so
    the failed state is preserved for the operator but can never be
    resumed from."""
    qdir = root / "quarantine"
    qdir.mkdir(parents=True, exist_ok=True)
    day_dir = _day_dir(root, day)
    target = qdir / f"day-{day:03d}"
    if day_dir.exists():
        if target.exists():
            shutil.rmtree(target)   # a re-poisoned retry of the same day
        shutil.move(str(day_dir), str(target))
    sidecar = qdir / f"day-{day:03d}.quarantine.json"
    sidecar.write_text(json.dumps({
        "day": int(day), "error": error,
        "quarantined": str(target) if target.exists() else None,
        "quarantined_at": round(time.time(), 3)}, indent=2) + "\n")
    counters.inc("daily.quarantined_days")
    log.error("day %d poisoned (%s) — artifacts quarantined under %s",
              day, error, qdir)


def _poison_check(manifest: dict, model_sink: dict, datatypes) -> str | None:
    """The divergence screen a day's fit must pass before its model may
    father day N+1: finite ll that did not COLLAPSE over the fit
    (final >= initial − LL_PARITY_BAND·|initial| — a Gibbs chain's
    predictive ll improves; a poisoned prior or corrupt feed drives it
    down), and finite tables."""
    for dt in datatypes:
        d = manifest["per_datatype"][dt]
        if not np.isfinite(d["ll_final"]):
            return f"ll band violation: {dt} final ll {d['ll_final']}"
        ll0 = d.get("ll_initial")
        if ll0 is not None and np.isfinite(ll0) \
                and d["ll_final"] < ll0 - LL_PARITY_BAND * abs(ll0):
            return (f"ll band violation: {dt} ll collapsed "
                    f"{ll0} -> {d['ll_final']}")
        sink = model_sink.get(dt)
        if sink is None:
            return f"no fitted model captured for {dt}"
        for k in ("theta", "phi_wk"):
            if not np.isfinite(sink[k]).all():
                return f"NaN counts in {dt} {k}"
    return None


def _persisted_meta(models_dir, name: str) -> dict | None:
    json_path = checkpoint.model_path(models_dir, name).with_suffix(".json")
    try:
        return json.loads(json_path.read_text())
    except (OSError, ValueError):
        return None


def run_daily(n_days: int, root: str | pathlib.Path, *,
              n_events: int = 4000, datatypes=("flow",),
              n_hosts: int | None = None, n_anomalies: int = 0,
              plants: dict | None = None, n_sweeps: int = 8,
              n_topics: int = 20, max_results: int = 500, seed: int = 0,
              generator: str = "mixture", merge_form: str = "sync",
              merge_staleness: int = 1, dp: int = 1, fit_hosts: int = 1,
              overlap: bool = True,
              feedback: dict | None = None, dupfactor: int = 1000,
              daily: DailyConfig | None = None,
              collect_winner_pairs: bool = False,
              out_path: str | pathlib.Path | None = None) -> dict:
    """Drive `run_campaign` over `n_days` simulated days under `root`.

    Day d draws its feed with seed `seed + daily.day_seed_stride*(d-1)`
    and `plants.get(d, n_anomalies)` planted anomalies (`plants` keys
    are 1-based day numbers). `feedback` maps a day number to a
    DataFrame of (ip, word) dismissal rows that apply from that day ON
    (accumulated — the analyst's verdicts persist). The supervisor is
    RESUMABLE: rerunning the same call against the same `root` skips
    every day with a verified ledger entry and re-executes the rest,
    which is the crash-recovery path (kill -9 anywhere, restart,
    converge to the uninterrupted run's artifacts).

    Returns the supervisor manifest (also written to `out_path`)."""
    daily = daily if daily is not None else DailyConfig()
    daily.validate()
    datatypes = tuple(datatypes)
    unknown = set(datatypes) - set(DATATYPES)
    if unknown:
        raise ValueError(f"unknown datatypes {sorted(unknown)}")
    plants = {int(k): int(v) for k, v in (plants or {}).items()}
    feedback = {int(k): v for k, v in (feedback or {}).items()}
    root = pathlib.Path(root)
    root.mkdir(parents=True, exist_ok=True)
    ledger = DayLedger(root / "ledger")
    models_dir = root / "models"
    force_cold = daily.force_cold \
        or os.environ.get("ONIX_DAILY_FORCE_COLD") == "1"
    if fit_hosts > 1 and not force_cold:
        # The multi-host fabric is cold-fit only (run_campaign refuses
        # warm_start); a multi-host chain must opt out of the warm
        # carry explicitly rather than die on day 2.
        raise ValueError("fit_hosts > 1 needs force_cold: the fit "
                         "fabric has no warm-start surface (pass "
                         "--force-cold / DailyConfig(force_cold=True))")
    edges = _load_edges(root, datatypes)

    def feedback_upto(day: int):
        frames = [df for d, df in sorted(feedback.items(), key=lambda kv:
                  kv[0]) if d <= day and df is not None and len(df)]
        if not frames:
            return None
        import pandas as pd
        return pd.concat(frames, ignore_index=True)

    def load_warm(prev_ok: dict | None):
        """Yesterday's persisted φ̂ + word keys per datatype, from the
        last ok day's ARCHIVE models — integrity-checked by load_model
        (a rotted parent refuses, and the day falls back to cold)."""
        if prev_ok is None or force_cold:
            return None
        warm = {}
        for dt, info in prev_ok.items():
            try:
                m = checkpoint.load_model(models_dir, info["name"])
            except checkpoint.ModelIntegrityError:
                counters.inc("daily.warm_parent_refused")
                continue
            if m is None or "word_key" not in m.arrays:
                counters.inc("daily.warm_unmappable")
                continue
            warm[dt] = {"phi": m.arrays["phi_wk"],
                        "word_key": m.arrays["word_key"]}
        return warm or None

    prev_ok: dict | None = None
    ok_count = 0
    day_records: list[dict] = []
    executed_wall_s = 0.0
    t_run = time.perf_counter()

    for day in range(1, int(n_days) + 1):
        record = ledger.read(day)
        if record is not None:
            body = record["body"]
            # Refuse a mixed-parameter splice: a verified entry written
            # by a DIFFERENT invocation (other seed/datatypes/plants
            # against the same root) must not be silently adopted into
            # this chain — the refuse-don't-trust posture the torn
            # entries already get, applied to operator error.
            exp_seed = seed + daily.day_seed_stride * (day - 1)
            if (body.get("seed") != exp_seed
                    or body.get("datatypes") != list(datatypes)
                    or (body.get("status") == "ok"
                        and body.get("planted")
                        != plants.get(day, n_anomalies))):
                raise ValueError(
                    f"day {day} ledger entry under {root} was produced "
                    "by a different invocation (seed/datatypes/plants "
                    "mismatch) — refusing to splice chains; use a "
                    "fresh root or rerun with the original parameters")
            counters.inc("daily.resumed_days")
            if body.get("status") == "ok":
                ok_count += 1
                prev_ok = {dt: dict(info)
                           for dt, info in body["model"].items()}
            # Same record shape as a freshly-executed day (the ledger
            # holds the walls): manifest consumers must not care
            # whether a day was resumed.
            day_records.append(dict(body, timing=record["timing"],
                                    resumed=True))
            continue

        # ---- execute the day (daily:day — one bounded retry) ----------
        for attempt in (0, 1):
            try:
                faults.fire("daily", "day")
                break
            except faults.InjectedFault:
                counters.inc("daily.day_retry")
                if attempt:
                    raise
        day_seed = seed + daily.day_seed_stride * (day - 1)
        t_day = time.perf_counter()
        warm = load_warm(prev_ok)
        model_sink: dict = {}
        edges_sink: dict = {}
        manifest = err = None
        with telemetry.TRACER.trace(f"daily-{seed}-{day:03d}"), \
                telemetry.TRACER.span("daily.day", day=day):
            try:
                manifest = run_campaign(
                    n_events, datatypes=datatypes, n_hosts=n_hosts,
                    n_anomalies=plants.get(day, n_anomalies),
                    n_sweeps=n_sweeps, n_topics=n_topics,
                    max_results=max_results, seed=day_seed,
                    overlap=overlap, merge_form=merge_form,
                    merge_staleness=merge_staleness, dp=dp,
                    fit_hosts=fit_hosts, generator=generator,
                    resume_dir=_day_dir(root, day),
                    feedback=feedback_upto(day), dupfactor=dupfactor,
                    edges=edges or None, edges_sink=edges_sink,
                    warm_start=warm, warm_sweeps=daily.warm_sweeps,
                    warm_burn_in=daily.warm_burn_in,
                    drift_max=daily.drift_max, model_sink=model_sink,
                    collect_winner_pairs=collect_winner_pairs)
                err = _poison_check(manifest, model_sink, datatypes)
            except Exception as e:      # the poison day: recover, don't
                counters.inc("daily.day_failed_exception")  # kill the chain
                log.exception("day %d failed", day)
                err = repr(e)

        if err is not None:
            # ---- poison-day rollback ---------------------------------
            counters.inc("daily.failed_days")
            _quarantine_day(root, day, err)
            body = {"day": day, "status": "failed", "seed": day_seed,
                    "datatypes": list(datatypes), "error": err}
            timing = {"wall_s": round(time.perf_counter() - t_day, 3)}
            ledger.write(day, body, timing)
            executed_wall_s += time.perf_counter() - t_day
            day_records.append(dict(body, timing=timing))
            continue        # day N+1 warm-starts from the last OK day

        # ---- accept the day: edges, models + lineage, ledger ---------
        for dt, fitted in edges_sink.items():
            if dt not in edges:
                _save_edges(root, dt, fitted)
                edges[dt] = fitted
        epoch = ok_count + 1
        model_body: dict = {}
        for dt in datatypes:
            sink = model_sink[dt]
            content = checkpoint.model_content_digest(sink["theta"],
                                                      sink["phi_wk"])
            parent = (prev_ok or {}).get(dt)
            extra = ({"word_key": sink["word_key"]}
                     if sink.get("word_key") is not None else None)
            per = manifest["per_datatype"][dt]
            meta = {"day": day, "refit_form": per["refit_form"],
                    "drift": per["drift"]}
            name = f"{dt}/day-{day:03d}"
            checkpoint.save_model(
                models_dir, name, sink["theta"], sink["phi_wk"],
                meta=meta, epoch=epoch,
                parent_epoch=(parent or {}).get("epoch"),
                parent_digest=(parent or {}).get("content_sha256"),
                extra_arrays=extra)
            # The stable serving tenant: SAME tables, epoch bumped past
            # whatever is persisted — except a crash-replayed save of
            # identical content, which keeps its epoch (idempotent). A
            # day OLDER than the persisted current's day never writes
            # it: re-executing a ledger-refused day 3 while day 4's
            # model is current must not roll the serving surface back
            # to yesterday's tables. The current tenant's epoch is
            # therefore history-dependent by design (it moves with
            # every content change, including replays) and lives in
            # the on-disk meta, NOT in the ledger identity body.
            cur_name = f"{dt}/current"
            persisted = _persisted_meta(models_dir, cur_name)
            cur_day = int(persisted.get("day", -1)) if persisted else -1
            if cur_day <= day:
                cur_epoch = epoch
                if persisted is not None \
                        and int(persisted.get("model_epoch", 0)) \
                        >= cur_epoch \
                        and persisted.get("content_sha256") != content:
                    cur_epoch = int(persisted["model_epoch"]) + 1
                checkpoint.save_model(
                    models_dir, cur_name, sink["theta"], sink["phi_wk"],
                    meta=meta, epoch=cur_epoch,
                    parent_epoch=(parent or {}).get("epoch"),
                    parent_digest=(parent or {}).get("content_sha256"),
                    extra_arrays=extra)
            else:
                counters.inc("daily.current_not_rolled_back")
            model_body[dt] = {
                "name": name, "epoch": epoch,
                "content_sha256": content,
                "parent_epoch": (parent or {}).get("epoch"),
                "parent_digest": (parent or {}).get("content_sha256"),
            }
        body = {
            "day": day, "status": "ok", "seed": day_seed,
            "datatypes": list(datatypes),
            "planted": plants.get(day, n_anomalies),
            "stages": {dt: {st: "ok" for st in
                            ("prepare", "fit", "score", "oa")}
                       for dt in datatypes},
            "refit": {dt: {"form": manifest["per_datatype"][dt]
                           ["refit_form"],
                           "drift": manifest["per_datatype"][dt]["drift"],
                           "warm_sweeps": manifest["per_datatype"][dt]
                           ["warm_sweeps"]}
                      for dt in datatypes},
            "winners": {dt: {
                "indices": manifest["per_datatype"][dt]["winner_indices"],
                "scores": manifest["per_datatype"][dt]["winner_scores"],
                "planted_in_bottom_k": manifest["per_datatype"][dt]
                ["planted_in_bottom_k"],
                **({"winner_pairs": manifest["per_datatype"][dt]
                    ["winner_pairs"]} if collect_winner_pairs else {}),
            } for dt in datatypes},
            "model": model_body,
        }
        timing = {
            "wall_s": round(time.perf_counter() - t_day, 3),
            "stage_walls_s": manifest["orchestration"]
            ["per_datatype_stage_walls_s"],
            "fit_preemptions": manifest["aggregate"]["fit_preemptions"],
        }
        ledger.write(day, body, timing)
        ok_count += 1
        prev_ok = {dt: dict(info) for dt, info in model_body.items()}
        executed_wall_s += time.perf_counter() - t_day
        day_records.append(dict(body, timing=timing))

    snap = counters.snapshot
    out = {
        "daily_schema": DAILY_SCHEMA,
        "supervisor": {
            "n_days": int(n_days), "datatypes": list(datatypes),
            "n_events": int(n_events), "n_sweeps": n_sweeps,
            "n_topics": n_topics, "max_results": max_results,
            "seed": seed, "generator": generator,
            "merge_form": merge_form,
            "merge_staleness": (int(merge_staleness)
                                if merge_form == "async" else 0),
            "dp": dp, "fit_hosts": fit_hosts,
            "plants": {str(k): v for k, v in sorted(plants.items())},
            "base_anomalies": n_anomalies,
            "daily": dataclasses.asdict(daily),
            "force_cold": bool(force_cold),
            "feedback_days": sorted(feedback),
            "root": str(root),
        },
        "days": day_records,
        "aggregate": {
            "ok_days": ok_count,
            "failed_days": sum(1 for r in day_records
                               if r.get("status") == "failed"),
            "resumed_days": sum(1 for r in day_records
                                if r.get("resumed")),
            "warm_fit_days": sum(
                1 for r in day_records if r.get("status") == "ok"
                and all(v["form"] == "warm" for v in r["refit"].values())),
            "executed_wall_s": round(executed_wall_s, 3),
            "wall_s": round(time.perf_counter() - t_run, 3),
        },
        "resilience": {**snap("daily"), **snap("campaign"),
                       **snap("faults"), **snap("ckpt")},
        "telemetry": telemetry.snapshot(),
    }
    if out_path is not None:
        out_path = pathlib.Path(out_path)
        out_path.parent.mkdir(parents=True, exist_ok=True)
        out_path.write_text(json.dumps(out, indent=2) + "\n")
    return out


def lineage_of(manifest: dict, datatype: str) -> list[dict]:
    """The datatype's model chain from a supervisor manifest: one row
    per ok day — (day, epoch, content digest, parent linkage) — the
    thing the chaos acceptance compares bit-for-bit across runs."""
    out = []
    for rec in manifest["days"]:
        if rec.get("status") != "ok":
            continue
        info = rec["model"][datatype]
        out.append({"day": rec["day"], "epoch": info["epoch"],
                    "content_sha256": info["content_sha256"],
                    "parent_epoch": info["parent_epoch"],
                    "parent_digest": info["parent_digest"]})
    return out


def _parse_plants(spec: str) -> dict:
    """`1:30,7:30` -> {1: 30, 7: 30}."""
    out = {}
    for part in filter(None, (p.strip() for p in spec.split(","))):
        day, _, n = part.partition(":")
        out[int(day)] = int(n)
    return out


def main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="continuous-operation supervisor: N simulated days "
                    "of ingest→fit→score→OA with a durable day ledger")
    ap.add_argument("--days", type=int, default=7)
    ap.add_argument("--root", required=True,
                    help="state root (ledger, models, day dirs)")
    ap.add_argument("--events", type=int, default=4000)
    ap.add_argument("--datatypes", default="flow",
                    help="csv subset of flow,dns,proxy")
    ap.add_argument("--hosts", type=int, default=None)
    ap.add_argument("--anomalies", type=int, default=0,
                    help="baseline planted anomalies per day")
    ap.add_argument("--plants", default="",
                    help="day:n_anomalies overrides, e.g. 1:30,7:30")
    ap.add_argument("--sweeps", type=int, default=8)
    ap.add_argument("--topics", type=int, default=20)
    ap.add_argument("--max-results", type=int, default=500)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--merge-form", default="sync")
    ap.add_argument("--merge-staleness", type=int, default=1,
                    help="merge windows a peer delta may lag in the "
                         "async arm (0 = the bit-identity arm)")
    ap.add_argument("--fit-hosts", type=int, default=1,
                    help="fit worker processes in the r21 multi-host "
                         "fabric (cold-fit only: requires --force-cold)")
    ap.add_argument("--generator", default="mixture")
    ap.add_argument("--drift-max", type=float, default=None)
    ap.add_argument("--warm-sweeps", type=int, default=None)
    ap.add_argument("--day-seed-stride", type=int, default=None)
    ap.add_argument("--force-cold", action="store_true")
    ap.add_argument("--fault-plan", default=None,
                    help="install a chaos plan (utils/faults.py grammar)")
    ap.add_argument("--out", default=None,
                    help="write the supervisor manifest here")
    args = ap.parse_args(argv)

    if args.fault_plan:
        faults.install_plan(args.fault_plan)
    dcfg = DailyConfig()
    if args.drift_max is not None:
        dcfg.drift_max = args.drift_max
    if args.warm_sweeps is not None:
        dcfg.warm_sweeps = args.warm_sweeps
    if args.day_seed_stride is not None:
        dcfg.day_seed_stride = args.day_seed_stride
    if args.force_cold:
        dcfg.force_cold = True
    manifest = run_daily(
        args.days, args.root, n_events=args.events,
        datatypes=tuple(d.strip() for d in args.datatypes.split(",")
                        if d.strip()),
        n_hosts=args.hosts, n_anomalies=args.anomalies,
        plants=_parse_plants(args.plants), n_sweeps=args.sweeps,
        n_topics=args.topics, max_results=args.max_results,
        seed=args.seed, generator=args.generator,
        merge_form=args.merge_form,
        merge_staleness=args.merge_staleness, dp=args.dp,
        fit_hosts=args.fit_hosts, daily=dcfg,
        out_path=args.out)
    agg = manifest["aggregate"]
    print(json.dumps({"ok_days": agg["ok_days"],
                      "failed_days": agg["failed_days"],
                      "resumed_days": agg["resumed_days"],
                      "warm_fit_days": agg["warm_fit_days"],
                      "wall_s": agg["wall_s"]}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
