"""Overlapped multi-datatype campaign orchestrator (r14; ROADMAP item 5).

The scale runner (scale.py) executes ONE datatype end-to-end and the
three judged pipelines ran strictly sequentially: flow's host
synthesize/word-build/corpus-build finished before flow's device fit
started, and dns's host work waited for flow's fit to drain — on the
measured host-bound pattern (docs/PERF.md r10: ~0.5 s/batch of host
decode/convert on the cores XLA also uses) that serializes host work
against device compute instead of overlapping it. This orchestrator
composes the pieces ROADMAP item 5 names — the sharded Gibbs engine
(sync or r14 async bounded-staleness merge), device scoring, and the
r9 resilience layer — into one campaign over flow+dns+proxy where one
datatype's host PREPARE stage (synthesize → word build → corpus build)
runs on a worker thread while another datatype's FIT occupies the
device, behind a bounded in-order queue (the depth-k prefetcher's
backpressure discipline, streaming.py ColumnPrefetcher).

Accounting is overlap-exact (utils/obs.OccupancyClock): per-stage,
per-datatype busy seconds; `prepare_wait` counts CONSUMER-BLOCKED
seconds only (the orchestration-level barrier stall — what the
overlapped arm exists to shrink); `overlap_s` counts genuinely
concurrent stage seconds; and the driver thread's stage-sum identity
(Σ busy + Σ blocked + idle == span) is asserted every run.

Fault semantics (docs/ROBUSTNESS.md "campaign fault plan"): the
engine-level sites stay live — `fit:sweep` preemptions land on
superstep boundaries, which are exactly the async arm's merge-flush
boundaries, and `ckpt:save=torn` exercises the digest fallback — and
the campaign adds `campaign:prepare` (a poisoned input batch, absorbed
by one bounded retry like the watcher's poison path). A preempted fit
retries through its per-datatype checkpoint dir, so a fault-riddled
campaign resumes to artifacts identical to the fault-free run in the
exact (sync / async τ=0) arm, and to in-band artifacts in the async
τ>0 arm (a mid-superstep preemption re-segments the merge windows —
the chain is segmentation-dependent for τ>0 by construction).

Every stage is the production code path: the *_words_from_arrays
builders, build_corpus, ShardedGibbsLDA, select_suspicious_events.
Nothing here is a special-cased benchmark kernel.
"""

from __future__ import annotations

import dataclasses
import json
import pathlib
import queue
import tempfile
import threading
import time

import numpy as np

from onix.config import DATATYPES, LDAConfig
from onix.pipelines.corpus_build import build_corpus, select_suspicious_events
from onix.pipelines.scale import _default_anomalies, _words_from_cols
from onix.pipelines.synth import SYNTH_ARRAYS
from onix.utils import faults, telemetry
from onix.utils.obs import OccupancyClock, counters

#: Campaign manifest schema — stamped so downstream evidence JSONs are
#: self-describing (the r3-era SCALE_1B artifacts carried no topology).
CAMPAIGN_SCHEMA = 1

#: Bounded retries for a preempted fit: every retry resumes from the
#: per-datatype checkpoint dir (or replays deterministically without
#: one), and fault-plan rules are one-shot, so this bound only guards
#: against a plan that preempts more often than it can make progress.
_MAX_FIT_ATTEMPTS = 8


class _Prepared:
    """One datatype's host-side inputs, ready for the device stages."""

    def __init__(self, datatype: str, cols: dict, bundle, planted: set,
                 words=None):
        self.datatype = datatype
        self.cols = cols
        self.bundle = bundle
        self.planted = planted
        self.words = words


def _prepare(datatype: str, n_events: int, n_hosts: int, n_anomalies: int,
             seed: int, gen_arrays, feedback=None, dupfactor: int = 1000,
             edges: dict | None = None) -> _Prepared:
    """The host PREPARE stage: synthesize → word build → corpus build.
    `campaign:prepare` is the fault site (a poisoned input batch); one
    bounded retry absorbs a raise — the same recover-don't-crash rule
    as the watcher's poison path — because the synthesizer is
    deterministic in seed, so the retry reproduces the same batch.

    `edges` applies a previously FITTED binning (the r19 daily chain
    reuses day 1's edges all week so word identities stay comparable
    across days); None fits fresh quantile edges from this feed.
    `feedback` rows ((ip, word) dismissals) duplicate ×dupfactor into
    the corpus — the reference's DUPFACTOR noise-filter loop, which is
    what makes a mid-week dismissal stay suppressed through the NEXT
    day's refit (the model itself learns the traffic is common)."""
    for attempt in (0, 1):
        try:
            faults.fire("campaign", "prepare")
            break
        except faults.InjectedFault:
            counters.inc("campaign.prepare_retry")
            if attempt:
                raise
    cols = gen_arrays[datatype](n_events, n_hosts=n_hosts,
                                n_anomalies=n_anomalies, seed=seed)
    wt = _words_from_cols(datatype, cols, edges=edges)
    bundle = build_corpus(wt, feedback, dupfactor)
    planted = set(cols["anomaly_idx"].tolist())
    return _Prepared(datatype, cols, bundle, planted, words=wt)


def _winner_pairs(prep: _Prepared, winner_idx: np.ndarray, n_events: int,
                  limit: int = 16) -> list[dict]:
    """The top winners' (ip, word) string pairs — the handle an analyst
    verdict needs (a dismissal is exactly such a pair, fed back through
    build_corpus ×dupfactor). Flow events carry two pairs (src-doc and
    dst-doc); dns/proxy one. Bounded at `limit` winners and gated by
    collect_winner_pairs — the string render is per-unique-then-
    broadcast but still O(rows)."""
    wt = prep.words
    if wt is None or len(winner_idx) == 0:
        return []
    from onix.pipelines.corpus_build import (_flow_pair_layout,
                                             _single_token_layout)
    bundle = prep.bundle
    ips, words = wt.ip, wt.word
    flow_pair = _flow_pair_layout(bundle, n_events)
    single = _single_token_layout(bundle, n_events)
    out = []
    for e in winner_idx[:limit].tolist():
        if flow_pair:
            rows = (e, n_events + e)
        elif single:
            rows = (e,)
        else:
            rows = tuple(np.nonzero(bundle.token_event == e)[0].tolist())
        out.append({"event": int(e),
                    "pairs": [[str(ips[r]), str(words[r])] for r in rows]})
    return out


# ---------------------------------------------------------------------------
# Day-over-day model carry (r19, pipelines/daily.py): mapping yesterday's
# φ̂ into today's vocabulary and measuring how far the warm chain drifted.
# Both key arrays are the PACKED int64 word keys aligned to vocab ids
# (vocab_word_keys), so rows match by word IDENTITY, not by id order.
# ---------------------------------------------------------------------------


def vocab_word_keys(bundle) -> np.ndarray | None:
    """Packed int64 word key per vocab id ([V], today's id order), or
    None when the bundle was built from the string path (no packed
    keys — the warm carry then falls back to a cold fit, counted)."""
    if bundle.word_key_sorted is None:
        return None
    keys = np.empty(len(bundle.word_key_sorted), np.int64)
    keys[bundle.word_key_ids] = bundle.word_key_sorted
    return keys


def _prev_rows_of(key_new: np.ndarray, key_prev: np.ndarray
                  ) -> tuple[np.ndarray, np.ndarray]:
    """(prev_row, hit) per today-key: the previous model's row index
    holding the same packed word key — ONE searchsorted pass through
    the shared `_sorted_table_lookup` idiom (corpus_build), so the
    edge handling lives in exactly one place."""
    from onix.pipelines.corpus_build import _sorted_table_lookup
    order = np.argsort(key_prev, kind="stable")
    return _sorted_table_lookup(key_prev[order], key_new,
                                ids=order.astype(np.int32))


def map_phi_prior(key_today: np.ndarray, phi_prev: np.ndarray,
                  key_prev: np.ndarray) -> tuple[np.ndarray, float]:
    """Yesterday's φ̂ re-indexed into TODAY's vocabulary: row w gets the
    prior topic distribution of the same packed word key, words unseen
    yesterday get a flat row (uniform p(k|w) once normalized — the
    φ̂-as-prior z-init only reads rows as unnormalized topic weights).
    Returns (prior [V_today, K] float32, matched row fraction)."""
    rows, hit = _prev_rows_of(key_today, key_prev)
    k = int(phi_prev.shape[-1])
    out = np.ones((len(key_today), k), np.float32)
    if hit.any():
        out[hit] = np.asarray(phi_prev, np.float32)[rows[hit]]
    return out, float(hit.mean()) if len(hit) else 0.0


def phi_topic_drift(phi_new: np.ndarray, key_new: np.ndarray,
                    phi_prev: np.ndarray, key_prev: np.ndarray,
                    exclude_keys: np.ndarray | None = None) -> float | None:
    """Per-topic φ divergence day-over-day — the drift monitor's
    number: over the SHARED vocabulary (matched packed keys), each
    topic's column is renormalized and compared by total-variation
    distance; the max over topics is returned (in [0, 1]). None when
    fewer than 2 words are shared (nothing comparable). Surfaced in
    the campaign manifest's per-datatype OA block, the day ledger, and
    the `daily.drift` histogram `/metrics` renders.

    `exclude_keys` drops those words from the comparison: the fit
    stage passes the day's FEEDBACK words, because an analyst's
    ×dupfactor dismissal deliberately moves p(word|·) by orders of
    magnitude — a KNOWN intervention, not the organic drift the gate
    exists to trip on (without this, every dismissal day would force a
    spurious cold refit)."""
    rows, hit = _prev_rows_of(key_new, key_prev)
    if exclude_keys is not None and len(exclude_keys):
        hit = hit & ~np.isin(key_new, exclude_keys)
    if hit.sum() < 2:
        return None
    a = np.asarray(phi_new, np.float64)[hit]
    b = np.asarray(phi_prev, np.float64)[rows[hit]]
    a = a / np.maximum(a.sum(axis=0, keepdims=True), 1e-30)
    b = b / np.maximum(b.sum(axis=0, keepdims=True), 1e-30)
    tv = 0.5 * np.abs(a - b).sum(axis=0)
    return float(tv.max())


def run_campaign(n_events: int, datatypes=DATATYPES, n_hosts: int | None = None,
                 n_anomalies: int | None = None, n_sweeps: int = 8,
                 n_topics: int = 20, max_results: int = 500, seed: int = 0,
                 n_chains: int = 1, overlap: bool = True,
                 overlap_depth: int = 1, merge_form: str = "sync",
                 merge_staleness: int = 1, dp: int = 0,
                 fit_hosts: int = 1, rebalance: bool = False,
                 generator: str = "mixture",
                 resume_dir: str | pathlib.Path | None = None,
                 out_path: str | pathlib.Path | None = None,
                 feedback=None, dupfactor: int = 1000,
                 edges: dict | None = None, edges_sink: dict | None = None,
                 warm_start: dict | None = None, warm_sweeps: int = 0,
                 warm_burn_in: int = 0, drift_max: float = 0.0,
                 model_sink: dict | None = None,
                 collect_winner_pairs: bool = False) -> dict:
    """One orchestrated ingest→fit→score→OA campaign over `datatypes`.

    `overlap=True` pipelines datatype d+1's host prepare against
    datatype d's device fit/score (bounded at `overlap_depth` prepared
    datatypes in flight); `overlap=False` is the sequential control —
    the SAME stages on the driver thread, so the two arms' artifacts
    are identical (deterministic in seed) and the accounting delta is
    pure orchestration. `merge_form`/`merge_staleness` select the
    sharded engine's count-merge arm (LDAConfig r14 gate). `dp=0`
    shards the fit over every visible device.

    The r19 daily-supervisor hooks (pipelines/daily.py drives these;
    every one defaults off and single-day callers are unchanged):

    * `feedback`/`dupfactor` — analyst dismissal rows for the corpus
      build (the reference's ×DUPFACTOR noise-filter loop);
    * `edges`/`edges_sink` — per-datatype fitted word-binning reuse
      across days (in) and capture (out: edges_sink[dt] = the fitted
      dict), so a multi-day chain's word identities stay comparable;
    * `warm_start` — per-datatype {"phi": φ̂ [V_prev, K], "word_key":
      int64 [V_prev]} from yesterday's persisted model: the fit
      warm-starts from a φ̂-as-prior z draw under a reduced
      `warm_sweeps`/`warm_burn_in` budget (0 = auto: half the cold
      sweeps / 1), then the DRIFT MONITOR compares the warm fit's φ̂
      to the prior per topic (phi_topic_drift); past `drift_max` (> 0
      enables the gate) the warm fit is discarded and the datatype
      re-fits cold, counted `daily.drift_cold_refits`. The decision is
      the `daily:refit` fault site (pre-mutation, one bounded retry);
    * `model_sink` — model_sink[dt] = {"theta", "phi_wk", "word_key"}
      host arrays of the accepted fit (requires n_chains == 1 — the
      persisted-model contract is single-estimate);
    * `collect_winner_pairs` — per_dt gains the top winners' (ip,
      word) string pairs, the handle an analyst dismissal needs.
    """
    import jax

    from onix.parallel.mesh import make_mesh
    from onix.parallel.sharded_gibbs import ShardedGibbsLDA

    if (model_sink is not None or warm_start) and n_chains != 1:
        raise ValueError(
            "the daily model carry (warm_start/model_sink) is "
            "single-estimate by contract: combine chains upstream "
            "(the model-bank rule) or fit with n_chains=1")
    if fit_hosts > 1 and warm_start:
        # The fabric workers have no init_phi surface (a warm prior
        # would have to be sharded per host and fingerprinted); refuse
        # loudly instead of silently fitting cold.
        raise ValueError(
            "the multi-host fit fabric (fit_hosts > 1) is cold-fit "
            "only: drop warm_start or fit with fit_hosts=1")

    if generator == "sessions":
        from onix.pipelines.synth2 import SYNTH2_ARRAYS as gen_arrays
    elif generator == "mixture":
        gen_arrays = SYNTH_ARRAYS
    else:
        raise ValueError(f"unknown generator {generator!r}; "
                         "expected 'mixture' or 'sessions'")
    datatypes = tuple(datatypes)
    unknown = set(datatypes) - set(DATATYPES)
    if unknown:
        raise ValueError(f"unknown datatypes {sorted(unknown)}")
    if n_hosts is None:
        n_hosts = max(120, min(200_000, n_events // 500))
    if n_anomalies is None:
        n_anomalies = _default_anomalies(n_events)

    n_dev = len(jax.devices()) if dp <= 0 else dp
    mesh = make_mesh(dp=n_dev, mp=1, devices=jax.devices()[:n_dev])
    from onix.models.lda_gibbs import SUPERSTEP_DEFAULT
    cfg = LDAConfig(n_topics=n_topics, n_sweeps=n_sweeps,
                    burn_in=max(1, n_sweeps // 2),
                    block_size=1 << 17, seed=seed, n_chains=n_chains,
                    merge_form=merge_form, merge_staleness=merge_staleness,
                    # Superstep-cadence checkpoints whenever a resume
                    # dir exists: preemptions land on superstep (==
                    # merge-flush) boundaries and resume from the last
                    # completed one instead of repaying the fit. Capped
                    # at half the sweep budget so harness-scale runs
                    # (sweeps < SUPERSTEP_DEFAULT) still checkpoint —
                    # a cadence past n_sweeps would never save and a
                    # preempted tiny fit would replay from scratch.
                    checkpoint_every=(min(SUPERSTEP_DEFAULT,
                                          max(1, n_sweeps // 2))
                                      if resume_dir is not None else 0))

    clock = OccupancyClock()
    per_dt: dict[str, dict] = {}
    dp1_fast = None
    fit_preemptions = 0

    def seed_of(i: int) -> int:
        # Distinct per-datatype streams; deterministic across arms.
        return seed + 7919 * i

    def trace_of(i: int, dt: str) -> str:
        # Per-item trace id (r18): the prepare worker and the driver
        # open the SAME id for one datatype's stages, so its span tree
        # (campaign.prepare on the worker thread, fit/score/oa on the
        # driver) reads as one trace. Deterministic in (seed, dt) —
        # identical across the sequential/overlapped arms.
        return f"campaign-{seed_of(i)}-{dt}"

    # -- the prepare pipeline (worker thread, bounded in-order queue) --
    handoff: queue.Queue = queue.Queue(maxsize=max(1, overlap_depth))

    def prepare_of(i: int, dt: str) -> _Prepared:
        return _prepare(dt, n_events, n_hosts, n_anomalies, seed_of(i),
                        gen_arrays, feedback=feedback, dupfactor=dupfactor,
                        edges=(edges or {}).get(dt))

    def producer():
        for i, dt in enumerate(datatypes):
            try:
                # The span FEEDS the clock (clock=/clock_name= enters
                # clock.busy unconditionally) — occupancy accounting is
                # identical with telemetry off.
                with telemetry.TRACER.trace(trace_of(i, dt)), \
                        telemetry.TRACER.span(
                            "campaign.prepare", clock=clock,
                            clock_name=f"{dt}.prepare", datatype=dt):
                    item = prepare_of(i, dt)
            except BaseException as e:          # noqa: BLE001 — relayed
                counters.inc("campaign.prepare_failed")
                handoff.put((dt, e))            # relayed to the driver,
                return                          # which raises it in-order
            handoff.put((dt, item))

    worker = None
    if overlap:
        worker = threading.Thread(target=producer, name="campaign-prepare",
                                  daemon=True)
        worker.start()

    def next_prepared(i: int, dt: str) -> _Prepared:
        if not overlap:
            with telemetry.TRACER.trace(trace_of(i, dt)), \
                    telemetry.TRACER.span(
                        "campaign.prepare", clock=clock,
                        clock_name=f"{dt}.prepare", datatype=dt):
                return prepare_of(i, dt)
        with clock.blocked("prepare_wait"):
            got_dt, item = handoff.get()
        assert got_dt == dt, f"prepare handoff out of order: {got_dt}!={dt}"
        if isinstance(item, BaseException):
            raise item
        return item

    def fit_with_resume(model, corpus, ckpt_dir, init_phi=None):
        """One fit through the bounded preemption-retry drill: resume
        from the last superstep-boundary checkpoint (or replay
        deterministically without one) instead of dying like the
        reference's MPI job."""
        nonlocal fit_preemptions
        from onix.checkpoint import SimulatedPreemption
        attempts = 0
        while True:
            try:
                return model.fit(corpus, checkpoint_dir=ckpt_dir,
                                 init_phi=init_phi)
            except SimulatedPreemption:
                counters.inc("campaign.fit_preempted")
                fit_preemptions += 1
                attempts += 1
                if attempts >= _MAX_FIT_ATTEMPTS:
                    raise

    t_loop = time.perf_counter()
    events_total = 0
    for i, dt in enumerate(datatypes):
        prep = next_prepared(i, dt)
        if edges_sink is not None and prep.words is not None:
            edges_sink[dt] = prep.words.edges
        corpus = prep.bundle.corpus
        key_today = vocab_word_keys(prep.bundle)
        warm = (warm_start or {}).get(dt)
        init_phi = matched_frac = None
        if warm is not None:
            if key_today is None or warm.get("word_key") is None:
                # String-path bundle or a pre-r19 model without its
                # word-key table: nothing to map the prior through.
                counters.inc("daily.warm_unmappable")
            else:
                init_phi, matched_frac = map_phi_prior(
                    key_today, warm["phi"], warm["word_key"])
        refit_form, drift = "cold", None
        ws_eff = None
        ckpt_dir = (pathlib.Path(resume_dir) / dt / "fit_ckpt"
                    if resume_dir is not None else None)
        with telemetry.TRACER.trace(trace_of(i, dt)), \
                telemetry.TRACER.span("campaign.fit", clock=clock,
                                      clock_name=f"{dt}.fit", datatype=dt):
            if init_phi is not None:
                # The r19 refit decision: warm fit under the reduced
                # budget, drift check against yesterday's φ̂, cold
                # fallback past the gate. `daily:refit` fires at the
                # decision's entry — BEFORE any fit state mutates — so
                # a raise is absorbed by one bounded retry (the
                # decision is deterministic in its inputs).
                with telemetry.TRACER.span("daily.refit", datatype=dt):
                    for attempt in (0, 1):
                        try:
                            faults.fire("daily", "refit")
                            break
                        except faults.InjectedFault:
                            counters.inc("daily.refit_retry")
                            if attempt:
                                raise
                    ws_eff = warm_sweeps or max(2, n_sweeps // 2)
                    wb_eff = min(warm_burn_in or 1, ws_eff - 1)
                    wcfg = dataclasses.replace(
                        cfg, n_sweeps=ws_eff, burn_in=wb_eff,
                        checkpoint_every=(min(SUPERSTEP_DEFAULT,
                                              max(1, ws_eff // 2))
                                          if resume_dir is not None else 0))
                    model = ShardedGibbsLDA(wcfg, corpus.n_vocab, mesh=mesh)
                    fit = fit_with_resume(model, corpus, ckpt_dir,
                                          init_phi=init_phi)
                    counters.inc("daily.warm_fits")
                    fb_keys = None
                    if feedback is not None and len(feedback):
                        wid = prep.bundle.vocab.ids(
                            feedback["word"].astype(str).to_numpy(),
                            strict=False)
                        wid = np.unique(wid[wid >= 0])
                        fb_keys = key_today[wid] if len(wid) else None
                    drift = phi_topic_drift(
                        np.asarray(fit["phi_wk"]), key_today,
                        warm["phi"], warm["word_key"],
                        exclude_keys=fb_keys)
                    if drift is not None:
                        telemetry.histograms.observe("daily.drift", drift)
                    if (drift is not None and drift_max > 0
                            and drift > drift_max):
                        # The warm chain drifted past the bounded-
                        # staleness band (arxiv 0909.4603's posture
                        # across days): discard it, re-fit cold.
                        counters.inc("daily.drift_cold_refits")
                        refit_form = "cold_drift"
                        model = ShardedGibbsLDA(cfg, corpus.n_vocab,
                                                mesh=mesh)
                        fit = fit_with_resume(model, corpus, ckpt_dir)
                    else:
                        refit_form = "warm"
            else:
                if warm_start is not None:
                    counters.inc("daily.cold_fits")
                if fit_hosts > 1:
                    # r21 multi-host fabric: this datatype's fit runs
                    # in fit_hosts worker processes; the fabric dir
                    # rides resume_dir so a killed run resumes at the
                    # last common superstep-boundary shard.
                    from onix.parallel import hostfabric
                    fabric_dir = (pathlib.Path(resume_dir) / dt
                                  / "fit_fabric"
                                  if resume_dir is not None
                                  else tempfile.mkdtemp(
                                      prefix=f"onix-fabric-{dt}-"))
                    model = None
                    fit = hostfabric.run_fit(
                        corpus, cfg, fabric_dir, n_hosts=fit_hosts,
                        on_death=("rebalance" if rebalance
                                  else "restart"),
                        rebalance=rebalance)
                else:
                    model = ShardedGibbsLDA(cfg, corpus.n_vocab,
                                            mesh=mesh)
                    fit = fit_with_resume(model, corpus, ckpt_dir)
        dp1_fast = bool(getattr(model, "dp1_fast", False))
        theta, phi_wk = fit["theta"], fit["phi_wk"]
        if model_sink is not None:
            model_sink[dt] = {
                "theta": np.asarray(theta, np.float32),
                "phi_wk": np.asarray(phi_wk, np.float32),
                "word_key": key_today,
            }
        with telemetry.TRACER.trace(trace_of(i, dt)), \
                telemetry.TRACER.span("campaign.score", clock=clock,
                                      clock_name=f"{dt}.score",
                                      datatype=dt):
            top = select_suspicious_events(prep.bundle, theta, phi_wk,
                                           n_events, tol=1.0,
                                           max_results=max_results)
            idx = np.asarray(top.indices)
            scores = np.asarray(top.scores)
        with telemetry.TRACER.trace(trace_of(i, dt)), \
                telemetry.TRACER.span("campaign.oa", clock=clock,
                                      clock_name=f"{dt}.oa", datatype=dt):
            keep = idx >= 0
            hits = len(prep.planted & set(idx[keep].tolist()))
            finite = scores[np.isfinite(scores)]
            per_dt[dt] = {
                "n_events": n_events,
                "n_docs": int(corpus.n_docs),
                "n_vocab": int(corpus.n_vocab),
                "n_tokens": int(corpus.n_tokens),
                "planted_anomalies": len(prep.planted),
                "planted_in_bottom_k": hits,
                "selected_score_range": (
                    [float(finite.min()), float(finite.max())]
                    if len(finite) else None),
                "ll_initial": round(float(fit["ll_history"][0][1]), 6),
                "ll_final": round(float(fit["ll_history"][-1][1]), 6),
                "winner_indices": idx[keep].tolist(),
                "winner_scores": [float(s) for s in scores[keep]],
                # r19 continuous-operation surfacing: which refit arm
                # produced this day's model and how far it drifted from
                # yesterday's φ̂ — the OA-visible face of the drift
                # monitor (ledger + /metrics carry the same numbers).
                "refit_form": refit_form,
                "drift": (round(drift, 6) if drift is not None else None),
                "warm_sweeps": ws_eff,
                "warm_matched_vocab_frac": (
                    round(matched_frac, 4) if matched_frac is not None
                    else None),
            }
            if collect_winner_pairs:
                per_dt[dt]["winner_pairs"] = _winner_pairs(
                    prep, idx[keep], n_events)
        events_total += n_events
    driver_span = time.perf_counter() - t_loop
    if worker is not None:
        worker.join(timeout=60)

    # -- overlap-exact accounting + the stage-sum identity ---------------
    occ = clock.snapshot()
    per_stage = {dt: {st: occ["busy_s"].get(f"{dt}.{st}", 0.0)
                      for st in ("prepare", "fit", "score", "oa")}
                 for dt in datatypes}
    prepare_total = sum(w["prepare"] for w in per_stage.values())
    blocked_total = sum(occ["blocked_s"].values())
    # Driver-thread stages: everything except the worker's prepares.
    driver_stages = [f"{dt}.{st}" for dt in datatypes
                     for st in (("fit", "score", "oa") if overlap else
                                ("prepare", "fit", "score", "oa"))]
    ok, idle = clock.check_stage_sum(driver_stages, span_s=driver_span,
                                     tol_s=0.25 + 0.02 * driver_span)
    assert ok, (
        f"stage-sum identity violated: driver stages + blocked exceed the "
        f"driver span by {-idle:.3f}s (accounting must never exceed wall)")
    # Barrier stall: seconds the device-feeding thread sat waiting for
    # stage inputs. Sequential arm: every prepare second is on the
    # critical path; overlapped arm: only the consumer-blocked residue.
    stall_s = blocked_total if overlap else prepare_total

    manifest = {
        "campaign_schema": CAMPAIGN_SCHEMA,
        "orchestration": {
            "datatypes": list(datatypes),
            "overlap": bool(overlap),
            "overlap_depth": int(overlap_depth) if overlap else 0,
            "merge_form": merge_form,
            "merge_staleness": (int(merge_staleness)
                                if merge_form == "async" else 0),
            "lda_superstep": cfg.superstep or SUPERSTEP_DEFAULT,
            "dp1_fast_path": dp1_fast,
            "mesh": dict(mesh.shape),
            "fit_hosts": fit_hosts,
            "n_sweeps": n_sweeps, "n_topics": n_topics,
            "n_chains": n_chains, "seed": seed,
            "generator": generator,
            "per_datatype_stage_walls_s": {
                dt: {st: round(v, 3) for st, v in walls.items()}
                for dt, walls in per_stage.items()},
        },
        "per_datatype": per_dt,
        "aggregate": {
            "events_total": events_total,
            "wall_seconds": round(driver_span, 3),
            "events_per_second": round(events_total
                                       / max(driver_span, 1e-9), 1),
            "barrier_stall_s": round(stall_s, 3),
            "prepare_busy_s": round(prepare_total, 3),
            "driver_idle_s": round(max(idle, 0.0), 3),
            "stage_sum_identity_ok": True,
            "fit_preemptions": fit_preemptions,
        },
        "occupancy": occ,
        # r18: the live-telemetry view of the same run — per-stage span
        # histograms (quantiles, not just sums) and recorder tallies.
        "telemetry": telemetry.snapshot(),
    }
    resil = {**counters.snapshot("ingest"), **counters.snapshot("salvage"),
             **counters.snapshot("faults"), **counters.snapshot("ckpt"),
             **counters.snapshot("campaign"), **counters.snapshot("daily")}
    if resil:
        manifest["resilience"] = resil
    if out_path is not None:
        out_path = pathlib.Path(out_path)
        out_path.parent.mkdir(parents=True, exist_ok=True)
        out_path.write_text(json.dumps(manifest, indent=2) + "\n")
    return manifest


def winners_identical(a: dict, b: dict) -> bool:
    """Exact per-datatype winner-set/score identity between two
    campaign manifests — the cross-arm parity check bench and the
    chaos smoke assert (deterministic stages ⇒ identical artifacts)."""
    if set(a["per_datatype"]) != set(b["per_datatype"]):
        return False
    for dt, pa in a["per_datatype"].items():
        pb = b["per_datatype"][dt]
        if (pa["winner_indices"] != pb["winner_indices"]
                or pa["winner_scores"] != pb["winner_scores"]):
            return False
    return True
