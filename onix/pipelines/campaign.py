"""Overlapped multi-datatype campaign orchestrator (r14; ROADMAP item 5).

The scale runner (scale.py) executes ONE datatype end-to-end and the
three judged pipelines ran strictly sequentially: flow's host
synthesize/word-build/corpus-build finished before flow's device fit
started, and dns's host work waited for flow's fit to drain — on the
measured host-bound pattern (docs/PERF.md r10: ~0.5 s/batch of host
decode/convert on the cores XLA also uses) that serializes host work
against device compute instead of overlapping it. This orchestrator
composes the pieces ROADMAP item 5 names — the sharded Gibbs engine
(sync or r14 async bounded-staleness merge), device scoring, and the
r9 resilience layer — into one campaign over flow+dns+proxy where one
datatype's host PREPARE stage (synthesize → word build → corpus build)
runs on a worker thread while another datatype's FIT occupies the
device, behind a bounded in-order queue (the depth-k prefetcher's
backpressure discipline, streaming.py ColumnPrefetcher).

Accounting is overlap-exact (utils/obs.OccupancyClock): per-stage,
per-datatype busy seconds; `prepare_wait` counts CONSUMER-BLOCKED
seconds only (the orchestration-level barrier stall — what the
overlapped arm exists to shrink); `overlap_s` counts genuinely
concurrent stage seconds; and the driver thread's stage-sum identity
(Σ busy + Σ blocked + idle == span) is asserted every run.

Fault semantics (docs/ROBUSTNESS.md "campaign fault plan"): the
engine-level sites stay live — `fit:sweep` preemptions land on
superstep boundaries, which are exactly the async arm's merge-flush
boundaries, and `ckpt:save=torn` exercises the digest fallback — and
the campaign adds `campaign:prepare` (a poisoned input batch, absorbed
by one bounded retry like the watcher's poison path). A preempted fit
retries through its per-datatype checkpoint dir, so a fault-riddled
campaign resumes to artifacts identical to the fault-free run in the
exact (sync / async τ=0) arm, and to in-band artifacts in the async
τ>0 arm (a mid-superstep preemption re-segments the merge windows —
the chain is segmentation-dependent for τ>0 by construction).

Every stage is the production code path: the *_words_from_arrays
builders, build_corpus, ShardedGibbsLDA, select_suspicious_events.
Nothing here is a special-cased benchmark kernel.
"""

from __future__ import annotations

import json
import pathlib
import queue
import threading
import time

import numpy as np

from onix.config import DATATYPES, LDAConfig
from onix.pipelines.corpus_build import build_corpus, select_suspicious_events
from onix.pipelines.scale import _default_anomalies, _words_from_cols
from onix.pipelines.synth import SYNTH_ARRAYS
from onix.utils import faults, telemetry
from onix.utils.obs import OccupancyClock, counters

#: Campaign manifest schema — stamped so downstream evidence JSONs are
#: self-describing (the r3-era SCALE_1B artifacts carried no topology).
CAMPAIGN_SCHEMA = 1

#: Bounded retries for a preempted fit: every retry resumes from the
#: per-datatype checkpoint dir (or replays deterministically without
#: one), and fault-plan rules are one-shot, so this bound only guards
#: against a plan that preempts more often than it can make progress.
_MAX_FIT_ATTEMPTS = 8


class _Prepared:
    """One datatype's host-side inputs, ready for the device stages."""

    def __init__(self, datatype: str, cols: dict, bundle, planted: set):
        self.datatype = datatype
        self.cols = cols
        self.bundle = bundle
        self.planted = planted


def _prepare(datatype: str, n_events: int, n_hosts: int, n_anomalies: int,
             seed: int, gen_arrays) -> _Prepared:
    """The host PREPARE stage: synthesize → word build → corpus build.
    `campaign:prepare` is the fault site (a poisoned input batch); one
    bounded retry absorbs a raise — the same recover-don't-crash rule
    as the watcher's poison path — because the synthesizer is
    deterministic in seed, so the retry reproduces the same batch."""
    for attempt in (0, 1):
        try:
            faults.fire("campaign", "prepare")
            break
        except faults.InjectedFault:
            counters.inc("campaign.prepare_retry")
            if attempt:
                raise
    cols = gen_arrays[datatype](n_events, n_hosts=n_hosts,
                                n_anomalies=n_anomalies, seed=seed)
    wt = _words_from_cols(datatype, cols)
    bundle = build_corpus(wt)
    planted = set(cols["anomaly_idx"].tolist())
    return _Prepared(datatype, cols, bundle, planted)


def run_campaign(n_events: int, datatypes=DATATYPES, n_hosts: int | None = None,
                 n_anomalies: int | None = None, n_sweeps: int = 8,
                 n_topics: int = 20, max_results: int = 500, seed: int = 0,
                 n_chains: int = 1, overlap: bool = True,
                 overlap_depth: int = 1, merge_form: str = "sync",
                 merge_staleness: int = 1, dp: int = 0,
                 generator: str = "mixture",
                 resume_dir: str | pathlib.Path | None = None,
                 out_path: str | pathlib.Path | None = None) -> dict:
    """One orchestrated ingest→fit→score→OA campaign over `datatypes`.

    `overlap=True` pipelines datatype d+1's host prepare against
    datatype d's device fit/score (bounded at `overlap_depth` prepared
    datatypes in flight); `overlap=False` is the sequential control —
    the SAME stages on the driver thread, so the two arms' artifacts
    are identical (deterministic in seed) and the accounting delta is
    pure orchestration. `merge_form`/`merge_staleness` select the
    sharded engine's count-merge arm (LDAConfig r14 gate). `dp=0`
    shards the fit over every visible device."""
    import jax

    from onix.parallel.mesh import make_mesh
    from onix.parallel.sharded_gibbs import ShardedGibbsLDA

    if generator == "sessions":
        from onix.pipelines.synth2 import SYNTH2_ARRAYS as gen_arrays
    elif generator == "mixture":
        gen_arrays = SYNTH_ARRAYS
    else:
        raise ValueError(f"unknown generator {generator!r}; "
                         "expected 'mixture' or 'sessions'")
    datatypes = tuple(datatypes)
    unknown = set(datatypes) - set(DATATYPES)
    if unknown:
        raise ValueError(f"unknown datatypes {sorted(unknown)}")
    if n_hosts is None:
        n_hosts = max(120, min(200_000, n_events // 500))
    if n_anomalies is None:
        n_anomalies = _default_anomalies(n_events)

    n_dev = len(jax.devices()) if dp <= 0 else dp
    mesh = make_mesh(dp=n_dev, mp=1, devices=jax.devices()[:n_dev])
    from onix.models.lda_gibbs import SUPERSTEP_DEFAULT
    cfg = LDAConfig(n_topics=n_topics, n_sweeps=n_sweeps,
                    burn_in=max(1, n_sweeps // 2),
                    block_size=1 << 17, seed=seed, n_chains=n_chains,
                    merge_form=merge_form, merge_staleness=merge_staleness,
                    # Superstep-cadence checkpoints whenever a resume
                    # dir exists: preemptions land on superstep (==
                    # merge-flush) boundaries and resume from the last
                    # completed one instead of repaying the fit. Capped
                    # at half the sweep budget so harness-scale runs
                    # (sweeps < SUPERSTEP_DEFAULT) still checkpoint —
                    # a cadence past n_sweeps would never save and a
                    # preempted tiny fit would replay from scratch.
                    checkpoint_every=(min(SUPERSTEP_DEFAULT,
                                          max(1, n_sweeps // 2))
                                      if resume_dir is not None else 0))

    clock = OccupancyClock()
    per_dt: dict[str, dict] = {}
    dp1_fast = None
    fit_preemptions = 0

    def seed_of(i: int) -> int:
        # Distinct per-datatype streams; deterministic across arms.
        return seed + 7919 * i

    def trace_of(i: int, dt: str) -> str:
        # Per-item trace id (r18): the prepare worker and the driver
        # open the SAME id for one datatype's stages, so its span tree
        # (campaign.prepare on the worker thread, fit/score/oa on the
        # driver) reads as one trace. Deterministic in (seed, dt) —
        # identical across the sequential/overlapped arms.
        return f"campaign-{seed_of(i)}-{dt}"

    # -- the prepare pipeline (worker thread, bounded in-order queue) --
    handoff: queue.Queue = queue.Queue(maxsize=max(1, overlap_depth))

    def producer():
        for i, dt in enumerate(datatypes):
            try:
                # The span FEEDS the clock (clock=/clock_name= enters
                # clock.busy unconditionally) — occupancy accounting is
                # identical with telemetry off.
                with telemetry.TRACER.trace(trace_of(i, dt)), \
                        telemetry.TRACER.span(
                            "campaign.prepare", clock=clock,
                            clock_name=f"{dt}.prepare", datatype=dt):
                    item = _prepare(dt, n_events, n_hosts, n_anomalies,
                                    seed_of(i), gen_arrays)
            except BaseException as e:          # noqa: BLE001 — relayed
                counters.inc("campaign.prepare_failed")
                handoff.put((dt, e))            # relayed to the driver,
                return                          # which raises it in-order
            handoff.put((dt, item))

    worker = None
    if overlap:
        worker = threading.Thread(target=producer, name="campaign-prepare",
                                  daemon=True)
        worker.start()

    def next_prepared(i: int, dt: str) -> _Prepared:
        if not overlap:
            with telemetry.TRACER.trace(trace_of(i, dt)), \
                    telemetry.TRACER.span(
                        "campaign.prepare", clock=clock,
                        clock_name=f"{dt}.prepare", datatype=dt):
                return _prepare(dt, n_events, n_hosts, n_anomalies,
                                seed_of(i), gen_arrays)
        with clock.blocked("prepare_wait"):
            got_dt, item = handoff.get()
        assert got_dt == dt, f"prepare handoff out of order: {got_dt}!={dt}"
        if isinstance(item, BaseException):
            raise item
        return item

    t_loop = time.perf_counter()
    events_total = 0
    for i, dt in enumerate(datatypes):
        prep = next_prepared(i, dt)
        corpus = prep.bundle.corpus
        model = ShardedGibbsLDA(cfg, corpus.n_vocab, mesh=mesh)
        dp1_fast = bool(getattr(model, "dp1_fast", False))
        ckpt_dir = (pathlib.Path(resume_dir) / dt / "fit_ckpt"
                    if resume_dir is not None else None)
        with telemetry.TRACER.trace(trace_of(i, dt)), \
                telemetry.TRACER.span("campaign.fit", clock=clock,
                                      clock_name=f"{dt}.fit", datatype=dt):
            from onix.checkpoint import SimulatedPreemption
            attempts = 0
            while True:
                try:
                    fit = model.fit(corpus, checkpoint_dir=ckpt_dir)
                    break
                except SimulatedPreemption:
                    # The drill: resume from the last superstep-boundary
                    # checkpoint (or replay deterministically without
                    # one) instead of dying like the reference's MPI job.
                    counters.inc("campaign.fit_preempted")
                    fit_preemptions += 1
                    attempts += 1
                    if attempts >= _MAX_FIT_ATTEMPTS:
                        raise
        theta, phi_wk = fit["theta"], fit["phi_wk"]
        with telemetry.TRACER.trace(trace_of(i, dt)), \
                telemetry.TRACER.span("campaign.score", clock=clock,
                                      clock_name=f"{dt}.score",
                                      datatype=dt):
            top = select_suspicious_events(prep.bundle, theta, phi_wk,
                                           n_events, tol=1.0,
                                           max_results=max_results)
            idx = np.asarray(top.indices)
            scores = np.asarray(top.scores)
        with telemetry.TRACER.trace(trace_of(i, dt)), \
                telemetry.TRACER.span("campaign.oa", clock=clock,
                                      clock_name=f"{dt}.oa", datatype=dt):
            keep = idx >= 0
            hits = len(prep.planted & set(idx[keep].tolist()))
            finite = scores[np.isfinite(scores)]
            per_dt[dt] = {
                "n_events": n_events,
                "n_docs": int(corpus.n_docs),
                "n_vocab": int(corpus.n_vocab),
                "n_tokens": int(corpus.n_tokens),
                "planted_anomalies": len(prep.planted),
                "planted_in_bottom_k": hits,
                "selected_score_range": (
                    [float(finite.min()), float(finite.max())]
                    if len(finite) else None),
                "ll_final": round(float(fit["ll_history"][-1][1]), 6),
                "winner_indices": idx[keep].tolist(),
                "winner_scores": [float(s) for s in scores[keep]],
            }
        events_total += n_events
    driver_span = time.perf_counter() - t_loop
    if worker is not None:
        worker.join(timeout=60)

    # -- overlap-exact accounting + the stage-sum identity ---------------
    occ = clock.snapshot()
    per_stage = {dt: {st: occ["busy_s"].get(f"{dt}.{st}", 0.0)
                      for st in ("prepare", "fit", "score", "oa")}
                 for dt in datatypes}
    prepare_total = sum(w["prepare"] for w in per_stage.values())
    blocked_total = sum(occ["blocked_s"].values())
    # Driver-thread stages: everything except the worker's prepares.
    driver_stages = [f"{dt}.{st}" for dt in datatypes
                     for st in (("fit", "score", "oa") if overlap else
                                ("prepare", "fit", "score", "oa"))]
    ok, idle = clock.check_stage_sum(driver_stages, span_s=driver_span,
                                     tol_s=0.25 + 0.02 * driver_span)
    assert ok, (
        f"stage-sum identity violated: driver stages + blocked exceed the "
        f"driver span by {-idle:.3f}s (accounting must never exceed wall)")
    # Barrier stall: seconds the device-feeding thread sat waiting for
    # stage inputs. Sequential arm: every prepare second is on the
    # critical path; overlapped arm: only the consumer-blocked residue.
    stall_s = blocked_total if overlap else prepare_total

    manifest = {
        "campaign_schema": CAMPAIGN_SCHEMA,
        "orchestration": {
            "datatypes": list(datatypes),
            "overlap": bool(overlap),
            "overlap_depth": int(overlap_depth) if overlap else 0,
            "merge_form": merge_form,
            "merge_staleness": (int(merge_staleness)
                                if merge_form == "async" else 0),
            "lda_superstep": cfg.superstep or SUPERSTEP_DEFAULT,
            "dp1_fast_path": dp1_fast,
            "mesh": dict(mesh.shape),
            "n_sweeps": n_sweeps, "n_topics": n_topics,
            "n_chains": n_chains, "seed": seed,
            "generator": generator,
            "per_datatype_stage_walls_s": {
                dt: {st: round(v, 3) for st, v in walls.items()}
                for dt, walls in per_stage.items()},
        },
        "per_datatype": per_dt,
        "aggregate": {
            "events_total": events_total,
            "wall_seconds": round(driver_span, 3),
            "events_per_second": round(events_total
                                       / max(driver_span, 1e-9), 1),
            "barrier_stall_s": round(stall_s, 3),
            "prepare_busy_s": round(prepare_total, 3),
            "driver_idle_s": round(max(idle, 0.0), 3),
            "stage_sum_identity_ok": True,
            "fit_preemptions": fit_preemptions,
        },
        "occupancy": occ,
        # r18: the live-telemetry view of the same run — per-stage span
        # histograms (quantiles, not just sums) and recorder tallies.
        "telemetry": telemetry.snapshot(),
    }
    resil = {**counters.snapshot("ingest"), **counters.snapshot("salvage"),
             **counters.snapshot("faults"), **counters.snapshot("ckpt"),
             **counters.snapshot("campaign")}
    if resil:
        manifest["resilience"] = resil
    if out_path is not None:
        out_path = pathlib.Path(out_path)
        out_path.parent.mkdir(parents=True, exist_ok=True)
        out_path.write_text(json.dumps(manifest, indent=2) + "\n")
    return manifest


def winners_identical(a: dict, b: dict) -> bool:
    """Exact per-datatype winner-set/score identity between two
    campaign manifests — the cross-arm parity check bench and the
    chaos smoke assert (deterministic stages ⇒ identical artifacts)."""
    if set(a["per_datatype"]) != set(b["per_datatype"]):
        return False
    for dt, pa in a["per_datatype"].items():
        pb = b["per_datatype"][dt]
        if (pa["winner_indices"] != pb["winner_indices"]
                or pa["winner_scores"] != pb["winner_scores"]):
            return False
    return True
