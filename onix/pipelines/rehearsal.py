"""Judged-metric rehearsal: top-1k suspicious-connect overlap vs oracle.

BASELINE.json's fidelity metric is "top-1k suspicious-connect overlap vs
lda-c >= 0.95". The reference binary is absent from the mount, so the
C++ `onix-lda-ref` engine stands in for lda-c (SURVEY.md §2.4 #1). This
module runs the full pairing on a realistic role-structured flow day and
records every number that contextualizes the bar:

  * jax_vs_oracle      — the judged number: JAX multi-chain Gibbs
                         (geometric score-average over chains) vs an
                         oracle restart-ensemble.
  * oracle_vs_oracle   — the achievable ceiling: two disjoint oracle
                         ensembles against each other. Run-to-run
                         posterior noise bounds ANY engine's agreement.
  * single_run_floor   — one oracle run vs another: what the metric
                         looks like without ensemble averaging (the
                         round-1 design measured ~0.85 here).
  * gibbs_vs_vem       — the inter-algorithm gap SURVEY.md §7.3.2 asks
                         to quantify (lda-c lineage is VEM; BASELINE
                         calls it a Gibbs sampler — the truth is the
                         band between them).

Method notes in docs/OVERLAP.md. Reproduce with:
    python -m onix.pipelines.rehearsal --events 100000 --out <path>
"""

from __future__ import annotations

import json
import pathlib
import time

import numpy as np

JUDGED_K = 1000
JUDGED_BAR = 0.95


def run_rehearsal(n_events: int = 100_000, n_sweeps: int = 300,
                  n_chains: int = 8, n_oracle_runs: int = 8,
                  n_topics: int = 20, alpha: float = 0.5, eta: float = 0.05,
                  seed: int = 5, datatype: str = "flow",
                  generator: str = "mixture",
                  bf16_arm: bool = False, engine: str = "gibbs",
                  engine_mesh: tuple[int, int] | None = None,
                  sync_splits: int = 1,
                  out_path=None) -> dict:
    """engine="sharded" runs the SAME judged pairing with the multi-chip
    ShardedGibbsLDA (chained restart ensemble vmapped per device over
    the ambient mesh) instead of the single-device GibbsLDA — closing
    VERDICT r03 weak #5: the 0.95 bar and the multi-chip engine must be
    satisfiable by ONE engine, not one each."""
    from onix import oracle
    from onix.config import LDAConfig
    from onix.models.lda_gibbs import GibbsLDA
    from onix.models.scoring import score_all
    from onix.pipelines.corpus_build import build_corpus
    from onix.pipelines.synth import SYNTH
    from onix.pipelines.words import WORD_FNS

    if generator not in ("mixture", "sessions"):
        raise ValueError(f"unknown generator {generator!r}; "
                         "expected 'mixture' or 'sessions'")
    if generator == "sessions":
        # The independent witness: session/state-machine telemetry the
        # model family did NOT generate (synth2.py; VERDICT r04 next
        # #4). The overlap pairing itself is engine-vs-oracle on the
        # SAME corpus, so the bar is meaningful on any data — running
        # it here shows the agreement doesn't depend on
        # mixture-generated input.
        from onix.pipelines.scale import _words_from_cols
        from onix.pipelines.synth2 import SYNTH2_ARRAYS
        cols = SYNTH2_ARRAYS[datatype](
            n_events, n_hosts=max(120, n_events // 250),
            n_anomalies=max(30, n_events // 650), seed=seed)
        n_day = len(cols["hour"])
        planted = cols["anomaly_idx"]
        bundle = build_corpus(_words_from_cols(datatype, cols))
        del cols
    else:
        day, planted = SYNTH[datatype](
            n_events=n_events, n_hosts=max(120, n_events // 250),
            n_anomalies=max(30, n_events // 650), seed=seed)
        n_day = len(day)
        bundle = build_corpus(WORD_FNS[datatype](day))
    corpus = bundle.corpus
    sc = corpus.to_doc_word_counts()

    walls = {}
    t = time.monotonic()
    ora_a = oracle.gibbs_ensemble_scores(
        sc, corpus.doc_ids, corpus.word_ids, n_topics=n_topics, alpha=alpha,
        eta=eta, n_sweeps=n_sweeps, n_runs=n_oracle_runs, seed=100)
    ora_b = oracle.gibbs_ensemble_scores(
        sc, corpus.doc_ids, corpus.word_ids, n_topics=n_topics, alpha=alpha,
        eta=eta, n_sweeps=n_sweeps, n_runs=n_oracle_runs, seed=500)
    walls["oracle_ensembles"] = round(time.monotonic() - t, 1)

    t = time.monotonic()
    g1 = oracle.gibbs(sc, n_topics=n_topics, alpha=alpha, eta=eta,
                      n_sweeps=n_sweeps, burn_in=n_sweeps // 2, seed=31)
    g2 = oracle.gibbs(sc, n_topics=n_topics, alpha=alpha, eta=eta,
                      n_sweeps=n_sweeps, burn_in=n_sweeps // 2, seed=32)
    s1 = oracle.score_events_np(g1["theta"], g1["phi"],
                                corpus.doc_ids, corpus.word_ids)
    s2 = oracle.score_events_np(g2["theta"], g2["phi"],
                                corpus.doc_ids, corpus.word_ids)
    vem = oracle.vem(sc, n_topics=n_topics, alpha=alpha, eta=eta,
                     em_max_iter=80, seed=31)
    sv = oracle.score_events_np(vem["theta"], vem["phi"],
                                corpus.doc_ids, corpus.word_ids)
    walls["oracle_singles_and_vem"] = round(time.monotonic() - t, 1)

    t = time.monotonic()
    cfg = LDAConfig(n_topics=n_topics, alpha=alpha, eta=eta,
                    n_sweeps=n_sweeps, burn_in=n_sweeps // 2,
                    block_size=8192, seed=0, n_chains=n_chains,
                    sync_splits=sync_splits)
    if engine == "sharded":
        from onix.parallel.mesh import make_mesh
        from onix.parallel.sharded_gibbs import ShardedGibbsLDA
        mesh = (make_mesh(dp=engine_mesh[0], mp=engine_mesh[1])
                if engine_mesh else None)
        fit = ShardedGibbsLDA(cfg, corpus.n_vocab, mesh=mesh).fit(corpus)
    else:
        fit = GibbsLDA(cfg, corpus.n_docs, corpus.n_vocab).fit(corpus)
    jx = np.asarray(score_all(fit["theta"], fit["phi_wk"],
                              corpus.doc_ids, corpus.word_ids))
    walls["jax_fit_and_score"] = round(time.monotonic() - t, 1)
    jx16 = None
    if bf16_arm:
        # The bf16 arm: identical fit, tables rounded to bfloat16 at
        # rest — exactly what `top_suspicious(..., table_dtype=
        # "bfloat16")` does on TPU (gather bf16, upcast, f32 dot).
        # Scoring it against the SAME oracle answers whether the 1.27x
        # bench lever meets the judged fidelity bar (docs/PERF.md
        # round-3 selection measurements #3). Opt-in: it costs a full
        # extra score_all pass, and its wall is recorded apart so
        # jax_fit_and_score stays comparable across rounds.
        import jax.numpy as jnp
        t = time.monotonic()
        rb = lambda a: np.asarray(jnp.asarray(a).astype(jnp.bfloat16)
                                  .astype(jnp.float32))
        jx16 = np.asarray(score_all(rb(fit["theta"]), rb(fit["phi_wk"]),
                                    corpus.doc_ids, corpus.word_ids))
        walls["bf16_score"] = round(time.monotonic() - t, 1)

    k = JUDGED_K
    # Detection sanity alongside fidelity: fraction of planted exfil
    # events each engine surfaces in its bottom-k (event score = min
    # over the event's tokens, via the layout-checked shared helper).
    from onix.pipelines.corpus_build import event_scores
    n = n_day
    hits = {}
    for name, sc_tok in (("jax", jx), ("oracle", ora_a)):
        ev = event_scores(bundle, np.asarray(sc_tok), n)
        bottom = set(np.argsort(ev)[:k].tolist())
        hits[name] = round(
            len(bottom & set(planted.tolist())) / len(planted), 4)
    result = {
        "metric": f"top-{k} suspicious-connect overlap vs oracle",
        "bar": JUDGED_BAR,
        "jax_vs_oracle": round(oracle.topk_overlap(jx, ora_a, k), 4),
        "jax_vs_oracle_b": round(oracle.topk_overlap(jx, ora_b, k), 4),
        "oracle_vs_oracle": round(oracle.topk_overlap(ora_a, ora_b, k), 4),
        "single_run_floor": round(oracle.topk_overlap(s1, s2, k), 4),
        "gibbs_vs_vem": round(oracle.topk_overlap(s1, sv, k), 4),
        "jax_vs_vem": round(oracle.topk_overlap(jx, sv, k), 4),
        "overlap_at_k": {
            str(kk): round(oracle.topk_overlap(jx, ora_a, kk), 4)
            for kk in (100, 500, 1000, 2000)},
        "planted_hit_at_k": hits,
        "config": {
            "datatype": datatype, "engine": engine,
            "generator": generator,
            "engine_mesh": list(engine_mesh) if engine_mesh else None,
            "n_events": n_events, "n_docs": int(corpus.n_docs),
            "n_vocab": int(corpus.n_vocab),
            "n_tokens": int(corpus.n_tokens), "n_topics": n_topics,
            "alpha": alpha, "eta": eta, "n_sweeps": n_sweeps,
            "n_chains": n_chains, "n_oracle_runs": n_oracle_runs,
            "sync_splits": sync_splits,
            "seed": seed},
        "walls_seconds": walls,
    }
    if jx16 is not None:
        result["jax_bf16_vs_oracle"] = round(
            oracle.topk_overlap(jx16, ora_a, k), 4)
        result["bf16_vs_f32"] = round(oracle.topk_overlap(jx16, jx, k), 4)
    result["passes_bar"] = bool(result["jax_vs_oracle"] >= JUDGED_BAR)
    if out_path is not None:
        out_path = pathlib.Path(out_path)
        out_path.parent.mkdir(parents=True, exist_ok=True)
        out_path.write_text(json.dumps(result, indent=2) + "\n")
    return result


def summarize_cells(cells: dict) -> dict:
    """Per-datatype min-over-seeds summary of rehearsal cells keyed
    "<datatype>/seed<N>". The r03–r05 study drivers and the artifact
    merge tool that consumed this were consolidated in r14 (their
    recipes live in the committed docs/OVERLAP_r0*.json artifacts;
    single cells re-run via `scripts/exp_campaign.py --rehearsal-cell`
    — docs/PERF.md "overlap study drivers, consolidated"); this stays
    the ONE judged-bar aggregation for any future study."""
    per_dt = {}
    for dt in sorted({k.split("/")[0] for k in cells}):
        mine = [c for k, c in cells.items() if k.startswith(dt + "/")]
        vals = [c["jax_vs_oracle"] for c in mine]
        per_dt[dt] = {
            "jax_vs_oracle_by_seed": vals,
            "min_over_seeds": min(vals),
            "oracle_ceiling_by_seed": [c["oracle_vs_oracle"] for c in mine],
            "n_chains": sorted({c["config"]["n_chains"] for c in mine}),
            "n_oracle_runs": sorted({c["config"]["n_oracle_runs"]
                                     for c in mine}),
            "passes_bar_min": min(vals) >= JUDGED_BAR,
        }
    return per_dt


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description="judged overlap rehearsal")
    ap.add_argument("--events", type=int, default=100_000)
    ap.add_argument("--sweeps", type=int, default=300)
    ap.add_argument("--chains", type=int, default=8)
    ap.add_argument("--oracle-runs", type=int, default=8)
    ap.add_argument("--datatype", choices=("flow", "dns", "proxy"),
                    default="flow")
    ap.add_argument("--seed", type=int, default=5)
    ap.add_argument("--generator", choices=("mixture", "sessions"),
                    default="mixture",
                    help="telemetry source: role-mixture synth or the "
                         "independent session/state-machine generator")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    r = run_rehearsal(n_events=args.events, n_sweeps=args.sweeps,
                      n_chains=args.chains, n_oracle_runs=args.oracle_runs,
                      datatype=args.datatype, seed=args.seed,
                      generator=args.generator,
                      out_path=args.out)
    print(json.dumps(r, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
