"""Columnar day reading for the scoring CLI — the 10⁸⁺-row path.

`run_scoring` historically read a stored day as ONE pandas frame and
built words through the per-row string functions: correct, but a
billion-row day neither fits in memory as objects nor survives per-row
Python (reference contract README.md:42 "filter billion of events to a
few thousands"). This module reads the day's parquet parts one at a
time, converts each to the numeric/dictionary-encoded columns the
`*_words_from_arrays` fast paths consume (words.py — bit-exact vs the
string paths), and merges the per-part dictionaries, so `onix score`
rides the same zero-per-row machinery the scale artifacts prove.

Per-part memory is one part's frame; the merged output holds only
numeric arrays (~tens of bytes/event) plus the tiny unique-string
tables.
"""

from __future__ import annotations

import re

import numpy as np
import pandas as pd

from onix.pipelines.words import IP_TAG, _factorize
from onix.store import Store, hour_of

_IPV4_RE = re.compile(r"^\d{1,3}\.\d{1,3}\.\d{1,3}\.\d{1,3}$")


# Doc-key encoding: canonical v4 keys are the u32 address value; keys
# with words.IP_TAG set index the day's sorted dictionary of other
# strings (IPv6, non-canonical v4). A pure-v4 day downcasts to uint32
# and takes the fast path everywhere.


def _canonical_v4_mask(uniq: np.ndarray):
    """(mask of canonical dotted-quad v4 strings, their u32 values)."""
    from onix.ingest.nfdecode import str_to_ip

    shaped = np.array([bool(_IPV4_RE.match(s)) for s in uniq])
    vals = np.zeros(len(uniq), np.uint32)
    if shaped.any():
        v4 = str_to_ip(uniq[shaped])
        canon = np.array(
            [f"{v >> 24}.{(v >> 16) & 255}.{(v >> 8) & 255}.{v & 255}"
             for v in v4.tolist()], dtype=object)
        ok = canon == uniq[shaped]
        shaped[np.flatnonzero(shaped)[~ok]] = False
        vals[shaped] = v4[ok]
    return shaped, vals[shaped]


def _ip_keys(series: list[pd.Series]) -> tuple[list[np.ndarray], np.ndarray]:
    """IP columns -> per-column uint64 doc keys + the shared dictionary
    table, via the joint unique set (rows >> uniques, so per-string
    work is O(distinct IPs)). Doc identity is the raw STRING — exactly
    the pandas path's semantics — so canonical v4 maps to its u32 value
    and everything else (IPv6, non-canonical v4) gets a tagged index
    into one per-day sorted dictionary SHARED by all columns (the same
    address in sip and dip must be one document)."""
    arrs = [s.astype(str).to_numpy() for s in series]
    if sum(len(a) for a in arrs) == 0:
        return [np.zeros(0, np.uint64) for _ in arrs], np.empty(0, object)
    joint = np.concatenate([np.asarray(a, object) for a in arrs])
    # Hash-factorize then sort the (tiny) unique table: identical
    # (sorted uniq, inverse) output to np.unique(return_inverse=True),
    # but the per-row pass is a hash probe instead of an object-compare
    # sort — measured 1.9 s -> ~0.2 s on a 500k-row flow batch, the
    # single largest host cost of the frame conversion.
    codes, uniq_f = _factorize(joint)
    order = np.argsort(uniq_f)
    uniq = uniq_f[order]
    rank = np.empty(len(order), np.int64)
    rank[order] = np.arange(len(order))
    inv = rank[codes]
    is_v4, v4_vals = _canonical_v4_mask(uniq)
    keys = np.zeros(len(uniq), np.uint64)
    keys[is_v4] = v4_vals.astype(np.uint64)
    table = uniq[~is_v4]                      # already sorted (np.unique)
    keys[~is_v4] = IP_TAG | np.arange(len(table), dtype=np.uint64)
    flat = keys[inv]
    out, lo = [], 0
    for a in arrs:
        out.append(flat[lo:lo + len(a)])
        lo += len(a)
    return out, table


def _ip_cols(series: list[pd.Series], names: list[str]) -> dict:
    """IP columns -> frame-cols entries: pure-v4 parts downcast to the
    uint32 fast path under `<name>_u32`; a part with any IPv6 or
    non-canonical string ships uint64 keys under `<name>_u64` plus the
    shared `ip_table` dictionary."""
    keys, table = _ip_keys(series)
    if len(table) == 0:
        return {f"{n}_u32": k.astype(np.uint32)
                for n, k in zip(names, keys)}
    out = {f"{n}_u64": k for n, k in zip(names, keys)}
    out["ip_table"] = table
    return out


def flow_frame_cols(df: pd.DataFrame) -> dict:
    """One part's frame -> flow_words_from_arrays kwargs (same recipe
    the words equivalence tests pin against the string path)."""
    proto_codes, protos = _factorize(
        df["proto"].astype(str).str.upper().to_numpy())
    return {
        **_ip_cols([df["sip"], df["dip"]], ["sip", "dip"]),
        "sport": df["sport"].to_numpy(np.int32),
        "dport": df["dport"].to_numpy(np.int32),
        "proto_id": proto_codes,
        "hour": hour_of(df["treceived"]),
        "ibyt": df["ibyt"].to_numpy(np.int64),
        "ipkt": df["ipkt"].to_numpy(np.int64),
        "proto_classes": protos,
    }


def dns_frame_cols(df: pd.DataFrame) -> dict:
    codes, uniq = _factorize(df["dns_qry_name"].astype(str).to_numpy())
    return {
        **_ip_cols([df["ip_dst"]], ["client"]),
        "qname_codes": codes,
        "qnames": uniq,
        "qtype": df["dns_qry_type"].to_numpy(np.int64),
        "rcode": df["dns_qry_rcode"].to_numpy(np.int64),
        "frame_len": df["frame_len"].to_numpy(np.float64),
        "hour": hour_of(df["frame_time"]),
    }


def proxy_frame_cols(df: pd.DataFrame) -> dict:
    uri_codes, uris = _factorize(df["uripath"].astype(str).to_numpy())
    host_codes, hosts = _factorize(df["host"].astype(str).to_numpy())
    ua_codes, agents = _factorize(df["useragent"].astype(str).to_numpy())
    return {
        **_ip_cols([df["clientip"]], ["client"]),
        "uri_codes": uri_codes, "uris": uris,
        "host_codes": host_codes, "hosts": hosts,
        "ua_codes": ua_codes, "agents": agents,
        "respcode": df["respcode"].to_numpy(np.int64),
        "hour": hour_of(df["p_date"].astype(str) + " "
                        + df["p_time"].astype(str)),
    }


FRAME_COLS = {"flow": flow_frame_cols, "dns": dns_frame_cols,
              "proxy": proxy_frame_cols}

# (dictionary-code column, unique-table column) pairs per datatype —
# what merge_cols must re-key across parts.
_DICT_PAIRS = {
    "flow": (("proto_id", "proto_classes"),),
    "dns": (("qname_codes", "qnames"),),
    "proxy": (("uri_codes", "uris"), ("host_codes", "hosts"),
              ("ua_codes", "agents")),
}


_IP_COL_NAMES = {"flow": ("sip", "dip"), "dns": ("client",),
                 "proxy": ("client",)}


def _merge_ip_keys(datatype: str, parts: list[dict]) -> dict:
    """Unify the per-part IP key spaces: if ANY part carries a
    dictionary (`ip_table`), upcast every part to u64 keys and re-index
    tagged entries against the merged sorted table."""
    names = _IP_COL_NAMES[datatype]
    if not any("ip_table" in p for p in parts):
        return {}
    merged = np.unique(np.concatenate(
        [p.get("ip_table", np.empty(0, object)) for p in parts]))
    out: dict = {"ip_table": merged}
    for n in names:
        pieces = []
        for p in parts:
            if f"{n}_u32" in p:
                pieces.append(p[f"{n}_u32"].astype(np.uint64))
                continue
            k = p[f"{n}_u64"]
            tagged = (k & IP_TAG) != 0
            k = k.copy()
            idx = (k[tagged] & ~IP_TAG).astype(np.int64)
            k[tagged] = IP_TAG | np.searchsorted(
                merged, p["ip_table"][idx]).astype(np.uint64)
            pieces.append(k)
        out[f"{n}_u64"] = np.concatenate(pieces)
    return out


def merge_cols(datatype: str, parts: list[dict]) -> dict:
    """Concatenate per-part column dicts; dictionary codes are re-keyed
    into one merged unique table per string column (sorted-unique merge
    + searchsorted remap — O(total uniques log uniques), tiny)."""
    if len(parts) == 1:
        return parts[0]
    ip_merged = _merge_ip_keys(datatype, parts)
    dict_pairs = _DICT_PAIRS[datatype]
    uniq_cols = {u for _, u in dict_pairs}
    out: dict = dict(ip_merged)
    for code_col, uniq_col in dict_pairs:
        merged = np.unique(np.concatenate([p[uniq_col] for p in parts]))
        remapped = []
        for p in parts:
            remap = np.searchsorted(merged, p[uniq_col])
            remapped.append(remap[p[code_col]])
        out[code_col] = np.concatenate(remapped)
        out[uniq_col] = merged
    # Per-part IP columns already unified above when any part carried a
    # dictionary; their per-part names must not re-concatenate.
    ip_handled = ({f"{n}_u32" for n in _IP_COL_NAMES[datatype]}
                  | {f"{n}_u64" for n in _IP_COL_NAMES[datatype]}
                  | {"ip_table"} if ip_merged else set())
    for key in parts[0]:
        if key in out or key in uniq_cols or key in ip_handled:
            continue
        out[key] = np.concatenate([p[key] for p in parts])
    return out


def read_day_cols(store: Store, datatype: str, date: str) -> dict:
    """Read a stored day part by part into merged columnar form."""
    pdir = store.partition_dir(datatype, date)
    part_files = Store.day_part_files(pdir)
    if not part_files:
        raise FileNotFoundError(
            f"no data for {datatype} {date} under {pdir}")
    to_cols = FRAME_COLS[datatype]
    parts = [to_cols(pd.read_parquet(p)) for p in part_files]
    return merge_cols(datatype, parts)


def words_from_cols(datatype: str, cols: dict, edges: dict | None = None):
    """Dispatch merged columns into the *_words_from_arrays fast path."""
    from onix.pipelines.words import (dns_words_from_arrays,
                                      flow_words_from_arrays,
                                      proxy_words_from_arrays)

    c = {k: v for k, v in cols.items() if k != "proto_classes"}
    if datatype == "flow":
        return flow_words_from_arrays(
            **c, proto_classes=list(cols["proto_classes"]), edges=edges)
    if datatype == "dns":
        return dns_words_from_arrays(**c, edges=edges)
    if datatype == "proxy":
        return proxy_words_from_arrays(**c, edges=edges)
    raise ValueError(f"unknown datatype {datatype!r}")


# Frames below this many rows stay on the pandas/string path ("auto"):
# the columnar win is memory/scan-speed at scale, and the string path
# is the reference implementation the bit-exactness tests pin.
COLUMNAR_AUTO_MIN_ROWS = 2_000_000


def rows_at(store: Store, datatype: str, date: str,
            indices: np.ndarray) -> pd.DataFrame:
    """The selected raw rows by global day index, caller order
    preserved — re-read part by part so only the few-thousand winners
    ever materialize as pandas objects (the columnar path never holds
    the day as a frame)."""
    import pyarrow.parquet as pq

    idx = np.asarray(indices, np.int64)
    order = np.argsort(idx, kind="stable")
    wanted = idx[order]
    pdir = store.partition_dir(datatype, date)
    chunks = []
    offset = 0
    # Same enumeration as Store.read/read_day_cols — the row-index
    # contract (winners re-read by index) depends on matching order.
    for p in Store.day_part_files(pdir):
        n = pq.ParquetFile(p).metadata.num_rows
        lo = np.searchsorted(wanted, offset)
        hi = np.searchsorted(wanted, offset + n)
        if hi > lo:
            df = pd.read_parquet(p)
            chunks.append(df.iloc[wanted[lo:hi] - offset])
        offset += n
    if wanted.size and wanted[-1] >= offset:
        raise IndexError(f"row index {wanted[-1]} beyond day size {offset}")
    if not chunks:
        # Zero winners: an EMPTY frame with the day's full raw-column
        # schema (parquet metadata only), matching table.iloc[[]].
        import pyarrow.parquet as pq

        first = Store.day_part_files(pdir)[0]
        return (pq.ParquetFile(first).schema_arrow.empty_table()
                .to_pandas())
    allf = pd.concat(chunks)
    inv = np.empty(len(idx), np.int64)
    inv[order] = np.arange(len(idx))
    return allf.iloc[inv].reset_index(drop=True)


def day_row_count(store: Store, datatype: str, date: str) -> int:
    """Row count from parquet footers only — no data pages read."""
    import pyarrow.parquet as pq

    pdir = store.partition_dir(datatype, date)
    return sum(pq.ParquetFile(p).metadata.num_rows
               for p in Store.day_part_files(pdir))
