"""Columnar day reading for the scoring CLI — the 10⁸⁺-row path.

`run_scoring` historically read a stored day as ONE pandas frame and
built words through the per-row string functions: correct, but a
billion-row day neither fits in memory as objects nor survives per-row
Python (reference contract README.md:42 "filter billion of events to a
few thousands"). This module reads the day's parquet parts one at a
time, converts each to the numeric/dictionary-encoded columns the
`*_words_from_arrays` fast paths consume (words.py — bit-exact vs the
string paths), and merges the per-part dictionaries, so `onix score`
rides the same zero-per-row machinery the scale artifacts prove.

Per-part memory is one part's frame; the merged output holds only
numeric arrays (~tens of bytes/event) plus the tiny unique-string
tables.
"""

from __future__ import annotations

import re

import numpy as np
import pandas as pd

from onix.pipelines.words import _factorize
from onix.store import Store, hour_of

_IPV4_RE = re.compile(r"^\d{1,3}\.\d{1,3}\.\d{1,3}\.\d{1,3}$")


def _ips_u32(values: pd.Series, col: str) -> np.ndarray:
    """IP column -> uint32, via the unique table (rows >> uniques, so
    the per-string work is O(distinct IPs)). The u32 mapping must be
    INJECTIVE on the day's strings for doc-identity parity with the
    string path, so only canonical dotted-quad IPv4 is accepted — an
    IPv6 or non-canonical string raises with guidance instead of
    silently merging documents."""
    from onix.ingest.nfdecode import str_to_ip

    codes, uniq = _factorize(values.astype(str).to_numpy())
    if uniq.size == 0:
        # A zero-row part (empty day slice) has nothing to map; without
        # this guard str_to_ip's vectorized split raises a bare
        # IndexError instead of returning the empty mapping.
        return np.zeros(0, np.uint32)
    bad = [s for s in uniq if not _IPV4_RE.match(s)]
    if not bad:
        u32 = str_to_ip(uniq)
        canon = [f"{v >> 24}.{(v >> 16) & 255}.{(v >> 8) & 255}.{v & 255}"
                 for v in u32.tolist()]
        bad = [s for s, c in zip(uniq, canon) if s != c]
    if bad:
        raise ValueError(
            f"column {col!r} holds non-IPv4/non-canonical addresses "
            f"(e.g. {bad[0]!r}); the columnar day reader needs a "
            "canonical uint32 IP mapping — run with "
            "pipeline.columnar=off for this day")
    return u32[codes]


def flow_frame_cols(df: pd.DataFrame) -> dict:
    """One part's frame -> flow_words_from_arrays kwargs (same recipe
    the words equivalence tests pin against the string path)."""
    proto_codes, protos = _factorize(
        df["proto"].astype(str).str.upper().to_numpy())
    return {
        "sip_u32": _ips_u32(df["sip"], "sip"),
        "dip_u32": _ips_u32(df["dip"], "dip"),
        "sport": df["sport"].to_numpy(np.int32),
        "dport": df["dport"].to_numpy(np.int32),
        "proto_id": proto_codes,
        "hour": hour_of(df["treceived"]),
        "ibyt": df["ibyt"].to_numpy(np.int64),
        "ipkt": df["ipkt"].to_numpy(np.int64),
        "proto_classes": protos,
    }


def dns_frame_cols(df: pd.DataFrame) -> dict:
    codes, uniq = _factorize(df["dns_qry_name"].astype(str).to_numpy())
    return {
        "client_u32": _ips_u32(df["ip_dst"], "ip_dst"),
        "qname_codes": codes,
        "qnames": uniq,
        "qtype": df["dns_qry_type"].to_numpy(np.int64),
        "rcode": df["dns_qry_rcode"].to_numpy(np.int64),
        "frame_len": df["frame_len"].to_numpy(np.float64),
        "hour": hour_of(df["frame_time"]),
    }


def proxy_frame_cols(df: pd.DataFrame) -> dict:
    uri_codes, uris = _factorize(df["uripath"].astype(str).to_numpy())
    host_codes, hosts = _factorize(df["host"].astype(str).to_numpy())
    ua_codes, agents = _factorize(df["useragent"].astype(str).to_numpy())
    return {
        "client_u32": _ips_u32(df["clientip"], "clientip"),
        "uri_codes": uri_codes, "uris": uris,
        "host_codes": host_codes, "hosts": hosts,
        "ua_codes": ua_codes, "agents": agents,
        "respcode": df["respcode"].to_numpy(np.int64),
        "hour": hour_of(df["p_date"].astype(str) + " "
                        + df["p_time"].astype(str)),
    }


FRAME_COLS = {"flow": flow_frame_cols, "dns": dns_frame_cols,
              "proxy": proxy_frame_cols}

# (dictionary-code column, unique-table column) pairs per datatype —
# what merge_cols must re-key across parts.
_DICT_PAIRS = {
    "flow": (("proto_id", "proto_classes"),),
    "dns": (("qname_codes", "qnames"),),
    "proxy": (("uri_codes", "uris"), ("host_codes", "hosts"),
              ("ua_codes", "agents")),
}


def merge_cols(datatype: str, parts: list[dict]) -> dict:
    """Concatenate per-part column dicts; dictionary codes are re-keyed
    into one merged unique table per string column (sorted-unique merge
    + searchsorted remap — O(total uniques log uniques), tiny)."""
    if len(parts) == 1:
        return parts[0]
    dict_pairs = _DICT_PAIRS[datatype]
    uniq_cols = {u for _, u in dict_pairs}
    out: dict = {}
    for code_col, uniq_col in dict_pairs:
        merged = np.unique(np.concatenate([p[uniq_col] for p in parts]))
        remapped = []
        for p in parts:
            remap = np.searchsorted(merged, p[uniq_col])
            remapped.append(remap[p[code_col]])
        out[code_col] = np.concatenate(remapped)
        out[uniq_col] = merged
    for key in parts[0]:
        if key in out or key in uniq_cols:
            continue
        out[key] = np.concatenate([p[key] for p in parts])
    return out


def read_day_cols(store: Store, datatype: str, date: str) -> dict:
    """Read a stored day part by part into merged columnar form."""
    pdir = store.partition_dir(datatype, date)
    part_files = sorted(pdir.glob("part-*.parquet"))
    if not part_files:
        raise FileNotFoundError(
            f"no data for {datatype} {date} under {pdir}")
    to_cols = FRAME_COLS[datatype]
    parts = [to_cols(pd.read_parquet(p)) for p in part_files]
    return merge_cols(datatype, parts)


def words_from_cols(datatype: str, cols: dict, edges: dict | None = None):
    """Dispatch merged columns into the *_words_from_arrays fast path."""
    from onix.pipelines.words import (dns_words_from_arrays,
                                      flow_words_from_arrays,
                                      proxy_words_from_arrays)

    c = {k: v for k, v in cols.items() if k != "proto_classes"}
    if datatype == "flow":
        return flow_words_from_arrays(
            **c, proto_classes=list(cols["proto_classes"]), edges=edges)
    if datatype == "dns":
        return dns_words_from_arrays(**c, edges=edges)
    if datatype == "proxy":
        return proxy_words_from_arrays(**c, edges=edges)
    raise ValueError(f"unknown datatype {datatype!r}")


# Frames below this many rows stay on the pandas/string path ("auto"):
# the columnar win is memory/scan-speed at scale, and the string path
# is the reference implementation the bit-exactness tests pin.
COLUMNAR_AUTO_MIN_ROWS = 2_000_000


def rows_at(store: Store, datatype: str, date: str,
            indices: np.ndarray) -> pd.DataFrame:
    """The selected raw rows by global day index, caller order
    preserved — re-read part by part so only the few-thousand winners
    ever materialize as pandas objects (the columnar path never holds
    the day as a frame)."""
    import pyarrow.parquet as pq

    idx = np.asarray(indices, np.int64)
    order = np.argsort(idx, kind="stable")
    wanted = idx[order]
    pdir = store.partition_dir(datatype, date)
    chunks = []
    offset = 0
    for p in sorted(pdir.glob("part-*.parquet")):
        n = pq.ParquetFile(p).metadata.num_rows
        lo = np.searchsorted(wanted, offset)
        hi = np.searchsorted(wanted, offset + n)
        if hi > lo:
            df = pd.read_parquet(p)
            chunks.append(df.iloc[wanted[lo:hi] - offset])
        offset += n
    if wanted.size and wanted[-1] >= offset:
        raise IndexError(f"row index {wanted[-1]} beyond day size {offset}")
    if not chunks:
        # Zero winners: an EMPTY frame with the day's full raw-column
        # schema (parquet metadata only), matching table.iloc[[]].
        import pyarrow.parquet as pq

        first = sorted(pdir.glob("part-*.parquet"))[0]
        return (pq.ParquetFile(first).schema_arrow.empty_table()
                .to_pandas())
    allf = pd.concat(chunks)
    inv = np.empty(len(idx), np.int64)
    inv[order] = np.arange(len(idx))
    return allf.iloc[inv].reset_index(drop=True)


def day_row_count(store: Store, datatype: str, date: str) -> int:
    """Row count from parquet footers only — no data pages read."""
    import pyarrow.parquet as pq

    pdir = store.partition_dir(datatype, date)
    return sum(pq.ParquetFile(p).metadata.num_rows
               for p in sorted(pdir.glob("part-*.parquet")))
