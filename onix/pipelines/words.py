"""Word creation — telemetry events → (document, word) pairs.

The TPU-era rendering of the reference's Scala word-creation jobs
(SURVEY.md §2.1 #5–#7: FlowWordCreation / DNSWordCreation /
ProxyWordCreation). One document per IP address; every event becomes one
word per associated IP. The exact feature recipes below are
reconstructions [R-high at the feature level, R-med at the exact
encoding] — the mount carries no oni-ml code (SURVEY.md §0), so the
load-bearing property is the reconstructed CONTRACT: low-probability
(word | IP) events under the topic model are surfaced as suspicious.

Words are PACKED INTEGERS, not strings: every word is a tuple of small
integer fields (bins, class ids), packed into one int64 with vectorized
shifts. Display strings are rendered lazily and only for the UNIQUE
vocabulary entries (V is small), never per event row — per-row Python
string formatting was the 10⁹-row bottleneck of the first design. The
rendered strings keep the original `a_b_c` format, so vocab dumps and
the analyst-feedback CSV contract are unchanged.

All transforms are vectorized over pandas/NumPy columns; the fitted
quantile edges are returned as explicit metadata so (a) a later
scoring-only run can re-apply identical binning and (b) the run manifest
can archive them (SURVEY.md §5.5).
"""

from __future__ import annotations

import dataclasses
import re

import numpy as np
import pandas as pd

from onix.store import hour_of
from onix.utils.features import (tail_quantile_edges,
                                 digitize, entropy_array, qname_features,
                                 quantile_edges)

# Coarse on purpose: words must repeat for topic structure to exist. A
# 10-bin grid on a day of O(10^4) events makes nearly every word a
# singleton and the model learns nothing (tested in test_pipeline_e2e).
N_BINS_DEFAULT = 5
_IP_RE = re.compile(r"^\d{1,3}\.\d{1,3}\.\d{1,3}\.\d{1,3}$")

# Reserved categorical codes.
_PROTO_UNK = 255          # proto not in the fitted table (apply mode)
_PCLASS_HH = 65536        # ephemeral<->ephemeral marker ("HH")
_UA_RARE = 1023           # user-agent outside the fitted common set


@dataclasses.dataclass(frozen=True)
class WordSpec:
    """Bit layout of a packed word key, LSB-first: (field, bits)."""

    datatype: str
    fields: tuple[tuple[str, int], ...]

    def pack(self, cols: dict[str, np.ndarray]) -> np.ndarray:
        out = np.zeros(len(next(iter(cols.values()))), np.int64)
        shift = 0
        for name, bits in self.fields:
            v = np.asarray(cols[name], np.int64) & ((1 << bits) - 1)
            out |= v << shift
            shift += bits
        assert shift < 63, "word key overflows int64"
        return out

    def shifts(self) -> dict[str, int]:
        """Field name -> LSB shift, derived from the layout — the one
        source of truth pack/unpack and the device packers share."""
        out = {}
        at = 0
        for name, bits in self.fields:
            out[name] = at
            at += bits
        return out

    def unpack(self, keys: np.ndarray) -> dict[str, np.ndarray]:
        keys = np.asarray(keys, np.int64)
        out = {}
        shift = 0
        for name, bits in self.fields:
            out[name] = (keys >> shift) & ((1 << bits) - 1)
            shift += bits
        return out


FLOW_SPEC = WordSpec("flow", (("pbin", 6), ("bbin", 6), ("hbin", 6),
                              ("pclass", 17), ("proto", 8)))
DNS_SPEC = WordSpec("dns", (("tld", 1), ("rcode", 8), ("qtype", 16),
                            ("nlabels", 3), ("ebin", 6), ("slbin", 6),
                            ("hbin", 6), ("flbin", 6)))
PROXY_SPEC = WordSpec("proxy", (("hbin", 6), ("uebin", 6), ("ulbin", 6),
                                ("hostip", 1), ("ua", 10), ("cclass", 4)))


def render_words(spec: WordSpec, keys: np.ndarray, edges: dict) -> np.ndarray:
    """Display strings for (typically unique) packed keys — identical
    format to the original per-row f-strings."""
    f = spec.unpack(keys)
    if spec.datatype == "flow":
        protos = list(edges.get("proto_classes", ()))
        pr = [protos[p] if p < len(protos) else "UNK" for p in f["proto"]]
        pc = ["HH" if c == _PCLASS_HH else str(c) for c in f["pclass"]]
        it = zip(pr, pc, f["hbin"], f["bbin"], f["pbin"])
        return np.array([f"{a}_{b}_{c}_{d}_{e}" for a, b, c, d, e in it],
                        dtype=object)
    if spec.datatype == "dns":
        it = zip(f["flbin"], f["hbin"], f["slbin"], f["ebin"], f["nlabels"],
                 f["qtype"], f["rcode"], f["tld"])
        return np.array(
            [f"{fl}_{h}_{sl}_{e}_{nl}_{qt}_{rc}_{tv}"
             for fl, h, sl, e, nl, qt, rc, tv in it], dtype=object)
    if spec.datatype == "proxy":
        ua = ["R" if u == _UA_RARE else f"C{u}" for u in f["ua"]]
        it = zip(f["cclass"], ua, f["hostip"], f["ulbin"], f["uebin"],
                 f["hbin"])
        return np.array([f"{cc}_{u}_{hi}_{ul}_{ue}_{h}"
                         for cc, u, hi, ul, ue, h in it], dtype=object)
    raise ValueError(f"unknown datatype {spec.datatype!r}")


def u32_to_ips(vals: np.ndarray) -> np.ndarray:
    """uint32 -> dotted-quad object strings (display path; call on
    uniques). Delegates to the decoder module's vectorized converter."""
    from onix.ingest.nfdecode import ip_to_str
    return ip_to_str(vals).astype(object)


# High bit of a uint64 doc key marks a dictionary entry (IPv6 or any
# non-canonical-v4 string; low bits index the day's sorted `ip_table`);
# untagged keys are canonical-v4 u32 values. Doc identity is the raw
# STRING either way — exactly the pandas path's semantics.
IP_TAG = np.uint64(1) << np.uint64(63)


def ip_keys_to_strings(keys: np.ndarray, ip_table: np.ndarray) -> np.ndarray:
    """uint64 doc keys -> IP strings (v4 rendered, tagged from table)."""
    out = np.empty(len(keys), object)
    tagged = (keys & IP_TAG) != 0
    out[~tagged] = u32_to_ips(keys[~tagged].astype(np.uint32))
    if tagged.any():
        out[tagged] = ip_table[(keys[tagged] & ~IP_TAG).astype(np.int64)]
    return out


class WordTable:
    """(document, word) rows with provenance back to source events.

    Canonical storage is integer: `word_key` (packed int64 per the
    table's `spec`) and, when the producer had numeric IPs, `ip_u32`
    (pure-v4 days) or `ip_u64` + `ip_table` (days with IPv6 or
    non-canonical addresses — see IP_TAG). `word` / `ip` are
    lazily-rendered string views (rendered per UNIQUE value then
    broadcast — never per-row Python formatting), kept for display,
    vocab dumps, and the feedback CSV contract.

    `event_idx[i]` is the source row of pair i — flow events contribute
    two rows (src-IP doc and dst-IP doc), dns/proxy one. `edges` holds
    the fitted binning metadata needed to reproduce the words.
    """

    def __init__(self, *, event_idx: np.ndarray, edges: dict,
                 spec: WordSpec | None = None,
                 word_key: np.ndarray | None = None,
                 word: np.ndarray | None = None,
                 ip: np.ndarray | None = None,
                 ip_u32: np.ndarray | None = None,
                 ip_u64: np.ndarray | None = None,
                 ip_table: np.ndarray | None = None):
        if ip is None and ip_u32 is None and ip_u64 is None:
            raise ValueError("need ip strings, ip_u32, or ip_u64")
        if ip_u64 is not None and ip_table is None:
            raise ValueError("ip_u64 needs the ip_table dictionary")
        if word is None and word_key is None:
            raise ValueError("need word strings or (word_key, spec)")
        if word is None and spec is None:
            raise ValueError("word_key needs a spec to render strings")
        self.event_idx = event_idx
        self.edges = edges
        self.spec = spec
        self.word_key = word_key
        self.ip_u32 = ip_u32
        self.ip_u64 = ip_u64
        self.ip_table = ip_table
        self._ip = ip
        self._word = word

    @property
    def n_rows(self) -> int:
        arr = self.word_key if self.word_key is not None else self._word
        return int(arr.shape[0])

    @property
    def ip(self) -> np.ndarray:
        if self._ip is None:
            if self.ip_u32 is not None:
                uniq, inv = np.unique(self.ip_u32, return_inverse=True)
                self._ip = u32_to_ips(uniq)[inv]
            else:
                uniq, inv = np.unique(self.ip_u64, return_inverse=True)
                self._ip = ip_keys_to_strings(uniq, self.ip_table)[inv]
        return self._ip

    @property
    def word(self) -> np.ndarray:
        if self._word is None:
            uniq, inv = np.unique(self.word_key, return_inverse=True)
            self._word = render_words(self.spec, uniq, self.edges)[inv]
        return self._word

    def render_keys(self, keys: np.ndarray) -> np.ndarray:
        return render_words(self.spec, keys, self.edges)


def _bins(values: np.ndarray, name: str, n_bins: int, edges: dict,
          tail: bool = False) -> np.ndarray:
    """Quantile-bin `values`, fitting edges if absent (fit vs apply
    mode). tail=True adds 99/99.9th-percentile cut points so
    out-of-support magnitudes isolate into rare-by-construction words
    instead of saturating the top equal-mass bin — applied to every
    magnitude-like feature (sizes, lengths, entropies), never to
    cyclic ones (hour). See features.tail_quantile_edges."""
    if name not in edges:
        edges[name] = (tail_quantile_edges(values, n_bins) if tail
                       else quantile_edges(values, n_bins))
    return digitize(values, edges[name])


def _factorize(values: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(codes, uniques) for a string column — the unique-then-broadcast
    pivot every string feature goes through: per-row Python over 10⁸
    rows was the DNS/proxy bottleneck; per-UNIQUE work is O(distinct
    names), thousands not hundreds of millions."""
    codes, uniques = pd.factorize(np.asarray(values, dtype=object))
    return codes.astype(np.int64), np.asarray(uniques, dtype=object)


def proto_remap_codes(fitted_table, caller_names, unk_code: int) -> np.ndarray:
    """Caller proto-id order -> fitted-table codes; names absent from
    the fitted table (apply mode with new protocols) get `unk_code`,
    never a silent wrong class. ONE implementation shared by the host
    builder and both device paths (trained-vocab compact tables and the
    streaming hash tables) — the cross-check parity tests rely on these
    never diverging."""
    table = np.asarray(fitted_table, dtype=object)
    names = np.asarray(caller_names, dtype=object)
    pos = np.searchsorted(table, names)
    pos_c = np.clip(pos, 0, max(len(table) - 1, 0))
    return np.where(len(table) and table[pos_c] == names,
                    pos_c, unk_code).astype(np.int64)


def _categorical(values: np.ndarray, name: str, edges: dict,
                 unk_code: int) -> np.ndarray:
    """Map strings to ids via a fitted sorted table; unseen -> unk_code."""
    if name not in edges:
        edges[name] = sorted(np.unique(values).tolist())
    table = np.asarray(edges[name], dtype=object)
    idx = np.searchsorted(table, values)
    idx = np.clip(idx, 0, max(len(table) - 1, 0))
    ok = table[idx] == values if len(table) else np.zeros(len(values), bool)
    return np.where(ok, idx, unk_code).astype(np.int64)


# ---------------------------------------------------------------------------
# flow (SURVEY.md §2.1 #5: "protocol + src/dst port class + quantile-binned
# bytes, packets, and time-of-day; one document per IP address")
# ---------------------------------------------------------------------------


def _port_class_codes(sport: np.ndarray, dport: np.ndarray) -> np.ndarray:
    """Collapse the port pair to the service port that identifies the
    conversation: the privileged (<=1024) side when exactly one side is
    privileged, the smaller port when both are, and the high-high marker
    when neither is (ephemeral↔ephemeral — the interesting class)."""
    sport = np.asarray(sport, np.int64)
    dport = np.asarray(dport, np.int64)
    both_low = (sport <= 1024) & (dport <= 1024)
    s_low = (sport <= 1024) & (dport > 1024)
    d_low = (dport <= 1024) & (sport > 1024)
    out = np.full(sport.shape, _PCLASS_HH, np.int64)
    np.copyto(out, np.minimum(sport, dport), where=both_low)
    np.copyto(out, sport, where=s_low)
    np.copyto(out, dport, where=d_low)
    return out


def flow_words_from_arrays(
        *, sport: np.ndarray, dport: np.ndarray, proto_id: np.ndarray,
        hour: np.ndarray, ibyt: np.ndarray, ipkt: np.ndarray,
        proto_classes: list[str],
        sip_u32: np.ndarray | None = None,
        dip_u32: np.ndarray | None = None,
        sip_u64: np.ndarray | None = None,
        dip_u64: np.ndarray | None = None,
        ip_table: np.ndarray | None = None,
        n_bins: int = N_BINS_DEFAULT, edges: dict | None = None) -> WordTable:
    """Numeric fast path: flow words straight from columnar arrays —
    zero per-row Python, the 10⁹-row ingest contract (BASELINE.json
    configs[3]). `proto_id` indexes `proto_classes` (uppercase names).
    IPs come as uint32 (pure-v4 days) or uint64 keys + `ip_table`
    (days with IPv6/non-canonical addresses, IP_TAG encoding)."""
    edges = dict(edges) if edges else {}
    edges.setdefault("proto_classes", sorted(proto_classes))
    # proto_id refers to caller order; remap to the sorted fitted table
    # (same contract as the string path's _categorical).
    remap = proto_remap_codes(edges["proto_classes"], proto_classes,
                              _PROTO_UNK)
    u64 = sip_u64 is not None
    if u64 == (sip_u32 is not None):
        raise ValueError("need exactly one of sip_u32/dip_u32 or "
                         "sip_u64/dip_u64(+ip_table)")
    n = (sip_u64 if u64 else sip_u32).shape[0]
    hbin = _bins(np.asarray(hour, np.float64), "hour", n_bins, edges)
    bbin = _bins(np.log1p(np.asarray(ibyt, np.float64)), "log_ibyt",
                 n_bins, edges, tail=True)
    pbin = _bins(np.log1p(np.asarray(ipkt, np.float64)), "log_ipkt",
                 n_bins, edges, tail=True)
    key = FLOW_SPEC.pack({
        "proto": remap[np.asarray(proto_id, np.int64)],
        "pclass": _port_class_codes(sport, dport),
        "hbin": hbin, "bbin": bbin, "pbin": pbin,
    })
    ip_kw = (dict(ip_u64=np.concatenate([np.asarray(sip_u64, np.uint64),
                                         np.asarray(dip_u64, np.uint64)]),
                  ip_table=ip_table) if u64 else
             dict(ip_u32=np.concatenate([np.asarray(sip_u32, np.uint32),
                                         np.asarray(dip_u32, np.uint32)])))
    return WordTable(
        word_key=np.concatenate([key, key]),
        event_idx=np.concatenate([np.arange(n), np.arange(n)]).astype(np.int64),
        edges=edges, spec=FLOW_SPEC, **ip_kw,
    )


def flow_words(table: pd.DataFrame, n_bins: int = N_BINS_DEFAULT,
               edges: dict | None = None) -> WordTable:
    """word = proto_portclass_hourbin_bytebin_pktbin; docs = {sip, dip}."""
    edges = dict(edges) if edges else {}
    n = len(table)
    hour = hour_of(table["treceived"])
    hbin = _bins(hour, "hour", n_bins, edges)
    bbin = _bins(np.log1p(table["ibyt"].to_numpy(np.float64)),
                 "log_ibyt", n_bins, edges, tail=True)
    pbin = _bins(np.log1p(table["ipkt"].to_numpy(np.float64)),
                 "log_ipkt", n_bins, edges, tail=True)
    pclass = _port_class_codes(table["sport"].to_numpy(),
                               table["dport"].to_numpy())
    proto = table["proto"].astype(str).str.upper().to_numpy()
    proto_id = _categorical(proto, "proto_classes", edges, _PROTO_UNK)
    key = FLOW_SPEC.pack({"proto": proto_id, "pclass": pclass,
                          "hbin": hbin, "bbin": bbin, "pbin": pbin})
    sip = table["sip"].astype(str).to_numpy()
    dip = table["dip"].astype(str).to_numpy()
    return WordTable(
        ip=np.concatenate([sip, dip]),
        word_key=np.concatenate([key, key]),
        event_idx=np.concatenate([np.arange(n), np.arange(n)]).astype(np.int64),
        edges=edges, spec=FLOW_SPEC,
    )


# ---------------------------------------------------------------------------
# dns (SURVEY.md §2.1 #6: "subdomain length/entropy, #dots, TLD validity,
# query type, rcode, frame length/time bins; document per client IP")
# ---------------------------------------------------------------------------


def _dns_pack(*, qname_codes: np.ndarray, qf: dict, hour: np.ndarray,
              frame_len: np.ndarray, qtype: np.ndarray, rcode: np.ndarray,
              n_bins: int, edges: dict) -> np.ndarray:
    """Shared DNS packing: per-UNIQUE qname features (`qf`, from
    qname_features) broadcast through `qname_codes`, bins fitted on the
    broadcast (row-weighted) values so fit-mode edges match the per-row
    implementation exactly."""
    hbin = _bins(np.asarray(hour, np.float64), "hour", n_bins, edges)
    flbin = _bins(np.asarray(frame_len, np.float64), "frame_len",
                  n_bins, edges, tail=True)
    slbin = _bins(qf["sub_len"][qname_codes], "sub_len", n_bins, edges,
                  tail=True)
    ebin = _bins(qf["sub_entropy"][qname_codes].astype(np.float64),
                 "sub_entropy", n_bins, edges, tail=True)
    return DNS_SPEC.pack({
        "flbin": flbin, "hbin": hbin, "slbin": slbin, "ebin": ebin,
        "nlabels": qf["n_labels"][qname_codes],
        "qtype": np.asarray(qtype, np.int64),
        "rcode": np.asarray(rcode, np.int64),
        "tld": qf["tld_ok"][qname_codes],
    })


def dns_words(table: pd.DataFrame, n_bins: int = N_BINS_DEFAULT,
              edges: dict | None = None) -> WordTable:
    edges = dict(edges) if edges else {}
    n = len(table)
    codes, uniq = _factorize(table["dns_qry_name"].astype(str).to_numpy())
    key = _dns_pack(
        qname_codes=codes, qf=qname_features(uniq),
        hour=hour_of(table["frame_time"]),
        frame_len=table["frame_len"].to_numpy(np.float64),
        qtype=table["dns_qry_type"].to_numpy(np.int64),
        rcode=table["dns_qry_rcode"].to_numpy(np.int64),
        n_bins=n_bins, edges=edges)
    return WordTable(
        ip=table["ip_dst"].astype(str).to_numpy(),   # reply → client IP
        word_key=key,
        event_idx=np.arange(n, dtype=np.int64),
        edges=edges, spec=DNS_SPEC,
    )



def _client_ip_kw(client_u32, client_u64, ip_table) -> dict:
    """One-client-column twin of the flow builders' ip_kw selection."""
    if (client_u64 is not None) == (client_u32 is not None):
        raise ValueError("need exactly one of client_u32 or "
                         "client_u64(+ip_table)")
    if client_u64 is not None:
        return dict(ip_u64=np.asarray(client_u64, np.uint64),
                    ip_table=ip_table)
    return dict(ip_u32=np.asarray(client_u32, np.uint32))

def dns_words_from_arrays(
        *, qname_codes: np.ndarray,
        qnames: np.ndarray, qtype: np.ndarray, rcode: np.ndarray,
        frame_len: np.ndarray, hour: np.ndarray,
        client_u32: np.ndarray | None = None,
        client_u64: np.ndarray | None = None,
        ip_table: np.ndarray | None = None,
        n_bins: int = N_BINS_DEFAULT, edges: dict | None = None) -> WordTable:
    """Numeric fast path: DNS words from dictionary-encoded columns —
    `qnames` is the UNIQUE name table, `qname_codes` the per-row index
    into it. String work (subdomain split, entropy) runs once per unique
    name; everything per-row is NumPy. The 10⁸-row contract for
    BASELINE.json configs[1] (VERDICT r2 next #3)."""
    edges = dict(edges) if edges else {}
    key = _dns_pack(
        qname_codes=np.asarray(qname_codes, np.int64),
        qf=qname_features(qnames),
        hour=hour, frame_len=frame_len, qtype=qtype, rcode=rcode,
        n_bins=n_bins, edges=edges)
    n = key.shape[0]
    return WordTable(
        word_key=key,
        event_idx=np.arange(n, dtype=np.int64),
        edges=edges, spec=DNS_SPEC,
        **_client_ip_kw(client_u32, client_u64, ip_table),
    )


# ---------------------------------------------------------------------------
# proxy (SURVEY.md §2.1 #7: "domain, URI length/entropy bins, user-agent
# class, response code, time bin; document per client IP")
# ---------------------------------------------------------------------------


def _ua_codes_uniq(agents_uniq: np.ndarray, row_counts: np.ndarray,
                   n_rows: int, edges: dict,
                   min_frac: float = 0.01) -> np.ndarray:
    """Per-UNIQUE user-agent class ids (broadcast through factorize
    codes): common agents keep their identity (index into the fitted
    common table), rare ones collapse to _UA_RARE (rarity is the
    signal). Commonness is judged on ROW counts (`row_counts[i]` = rows
    carrying agents_uniq[i]), so the fit matches the original per-row
    implementation. The common set is fitted metadata so apply-mode
    runs reproduce the classes."""
    if "ua_common" not in edges:
        keep = agents_uniq[row_counts >= max(2, int(min_frac * n_rows))]
        edges["ua_common"] = sorted(map(str, keep.tolist()))[:_UA_RARE]
    return _categorical(np.asarray(agents_uniq, dtype=object),
                        "ua_common", edges, _UA_RARE)


def _proxy_pack(*, uri_codes: np.ndarray, uris: np.ndarray,
                host_codes: np.ndarray, hosts: np.ndarray,
                ua_codes: np.ndarray, agents: np.ndarray,
                respcode: np.ndarray, hour: np.ndarray,
                n_bins: int, edges: dict) -> np.ndarray:
    """Shared proxy packing over dictionary-encoded string columns.

    The reference's proxy word recipe is "domain, URI length/entropy
    bins, user-agent class, response code, time bin" (SURVEY.md §2.1 #7)
    — deliberately few components so words repeat per client. All string
    work runs once per unique URI/host/agent and broadcasts."""
    uri_codes = np.asarray(uri_codes, np.int64)
    host_codes = np.asarray(host_codes, np.int64)
    ua_codes = np.asarray(ua_codes, np.int64)
    n = uri_codes.shape[0]
    hbin = _bins(np.asarray(hour, np.float64), "hour", n_bins, edges)
    uri_len_u = np.fromiter((len(str(u)) for u in uris), np.float64,
                            len(uris))
    ulbin = _bins(uri_len_u[uri_codes], "uri_len", n_bins, edges,
                  tail=True)
    uebin = _bins(entropy_array(uris)[uri_codes].astype(np.float64),
                  "uri_entropy", n_bins, edges, tail=True)
    host_ip_u = np.fromiter(
        (int(bool(_IP_RE.match(str(h)))) for h in hosts), np.int64,
        len(hosts))
    ua_id_u = _ua_codes_uniq(
        agents, np.bincount(ua_codes, minlength=len(agents)), n, edges)
    return PROXY_SPEC.pack({
        "cclass": np.asarray(respcode, np.int64) // 100,
        "ua": ua_id_u[ua_codes],
        "hostip": host_ip_u[host_codes],
        "ulbin": ulbin, "uebin": uebin, "hbin": hbin,
    })


def proxy_words(table: pd.DataFrame, n_bins: int = N_BINS_DEFAULT,
                edges: dict | None = None) -> WordTable:
    edges = dict(edges) if edges else {}
    n = len(table)
    uri_codes, uris = _factorize(table["uripath"].astype(str).to_numpy())
    host_codes, hosts = _factorize(table["host"].astype(str).to_numpy())
    ua_codes, agents = _factorize(table["useragent"].astype(str).to_numpy())
    key = _proxy_pack(
        uri_codes=uri_codes, uris=uris, host_codes=host_codes, hosts=hosts,
        ua_codes=ua_codes, agents=agents,
        respcode=table["respcode"].to_numpy(np.int64),
        hour=hour_of(table["p_date"].astype(str) + " "
                     + table["p_time"].astype(str)),
        n_bins=n_bins, edges=edges)
    return WordTable(
        ip=table["clientip"].astype(str).to_numpy(),
        word_key=key,
        event_idx=np.arange(n, dtype=np.int64),
        edges=edges, spec=PROXY_SPEC,
    )


def proxy_words_from_arrays(
        *, uri_codes: np.ndarray, uris: np.ndarray,
        host_codes: np.ndarray, hosts: np.ndarray, ua_codes: np.ndarray,
        agents: np.ndarray, respcode: np.ndarray, hour: np.ndarray,
        client_u32: np.ndarray | None = None,
        client_u64: np.ndarray | None = None,
        ip_table: np.ndarray | None = None,
        n_bins: int = N_BINS_DEFAULT, edges: dict | None = None) -> WordTable:
    """Numeric fast path: proxy words from dictionary-encoded columns —
    `uris`/`hosts`/`agents` are UNIQUE string tables, `*_codes` the
    per-row indices. The 10⁸-row contract for BASELINE.json configs[2]
    (VERDICT r2 next #3)."""
    edges = dict(edges) if edges else {}
    key = _proxy_pack(
        uri_codes=uri_codes, uris=uris, host_codes=host_codes, hosts=hosts,
        ua_codes=ua_codes, agents=agents, respcode=respcode, hour=hour,
        n_bins=n_bins, edges=edges)
    n = key.shape[0]
    return WordTable(
        word_key=key,
        event_idx=np.arange(n, dtype=np.int64),
        edges=edges, spec=PROXY_SPEC,
        **_client_ip_kw(client_u32, client_u64, ip_table),
    )


WORD_FNS = {"flow": flow_words, "dns": dns_words, "proxy": proxy_words}
