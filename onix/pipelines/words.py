"""Word creation — telemetry events → (document, word) pairs.

The TPU-era rendering of the reference's Scala word-creation jobs
(SURVEY.md §2.1 #5–#7: FlowWordCreation / DNSWordCreation /
ProxyWordCreation). One document per IP address; every event becomes one
word per associated IP. The exact feature recipes below are
reconstructions [R-high at the feature level, R-med at the exact
encoding] — the mount carries no oni-ml code (SURVEY.md §0), so the
load-bearing property is the reconstructed CONTRACT: low-probability
(word | IP) events under the topic model are surfaced as suspicious.

All transforms are vectorized over pandas/NumPy columns; the fitted
quantile edges are returned as explicit metadata so (a) a later
scoring-only run can re-apply identical binning and (b) the run manifest
can archive them (SURVEY.md §5.5).
"""

from __future__ import annotations

import dataclasses
import re

import numpy as np
import pandas as pd

from onix.store import hour_of
from onix.utils.features import (digitize, entropy_array, quantile_edges,
                                 subdomain_split)

# Coarse on purpose: words must repeat for topic structure to exist. A
# 10-bin grid on a day of O(10^4) events makes nearly every word a
# singleton and the model learns nothing (tested in test_pipeline_e2e).
N_BINS_DEFAULT = 5
_IP_RE = re.compile(r"^\d{1,3}\.\d{1,3}\.\d{1,3}\.\d{1,3}$")


@dataclasses.dataclass
class WordTable:
    """(document, word) rows with provenance back to source events.

    `event_idx[i]` is the source row of pair i — flow events contribute
    two rows (src-IP doc and dst-IP doc), dns/proxy one. `edges` holds
    the fitted binning metadata needed to reproduce the words.
    """

    ip: np.ndarray          # object [n_rows] document key (IP string)
    word: np.ndarray        # object [n_rows] word string
    event_idx: np.ndarray   # int64 [n_rows] source event row
    edges: dict

    @property
    def n_rows(self) -> int:
        return int(self.ip.shape[0])


def _bins(values: np.ndarray, name: str, n_bins: int, edges: dict) -> np.ndarray:
    """Quantile-bin `values`, fitting edges if absent (fit vs apply mode)."""
    if name not in edges:
        edges[name] = quantile_edges(values, n_bins)
    return digitize(values, edges[name])


# ---------------------------------------------------------------------------
# flow (SURVEY.md §2.1 #5: "protocol + src/dst port class + quantile-binned
# bytes, packets, and time-of-day; one document per IP address")
# ---------------------------------------------------------------------------


def _port_class(sport: np.ndarray, dport: np.ndarray) -> np.ndarray:
    """Collapse the port pair to the service port that identifies the
    conversation: the privileged (<=1024) side when exactly one side is
    privileged, the smaller port when both are, and a single high-high
    marker when neither is (ephemeral↔ephemeral — the interesting class)."""
    sport = np.asarray(sport, np.int64)
    dport = np.asarray(dport, np.int64)
    both_low = (sport <= 1024) & (dport <= 1024)
    s_low = (sport <= 1024) & (dport > 1024)
    d_low = (dport <= 1024) & (sport > 1024)
    out = np.full(sport.shape, "HH", dtype=object)       # high-high
    out[both_low] = np.minimum(sport, dport)[both_low].astype(str)
    out[s_low] = sport[s_low].astype(str)
    out[d_low] = dport[d_low].astype(str)
    return out


def flow_words(table: pd.DataFrame, n_bins: int = N_BINS_DEFAULT,
               edges: dict | None = None) -> WordTable:
    """word = proto_portclass_hourbin_bytebin_pktbin; docs = {sip, dip}."""
    edges = dict(edges) if edges else {}
    n = len(table)
    hour = hour_of(table["treceived"])
    hbin = _bins(hour, "hour", n_bins, edges)
    bbin = _bins(np.log1p(table["ibyt"].to_numpy(np.float64)),
                 "log_ibyt", n_bins, edges)
    pbin = _bins(np.log1p(table["ipkt"].to_numpy(np.float64)),
                 "log_ipkt", n_bins, edges)
    pclass = _port_class(table["sport"].to_numpy(), table["dport"].to_numpy())
    proto = table["proto"].astype(str).str.upper().to_numpy()
    word = np.array([f"{pr}_{pc}_{h}_{b}_{p}" for pr, pc, h, b, p
                     in zip(proto, pclass, hbin, bbin, pbin)], dtype=object)
    sip = table["sip"].astype(str).to_numpy()
    dip = table["dip"].astype(str).to_numpy()
    return WordTable(
        ip=np.concatenate([sip, dip]),
        word=np.concatenate([word, word]),
        event_idx=np.concatenate([np.arange(n), np.arange(n)]).astype(np.int64),
        edges=edges,
    )


# ---------------------------------------------------------------------------
# dns (SURVEY.md §2.1 #6: "subdomain length/entropy, #dots, TLD validity,
# query type, rcode, frame length/time bins; document per client IP")
# ---------------------------------------------------------------------------


def dns_words(table: pd.DataFrame, n_bins: int = N_BINS_DEFAULT,
              edges: dict | None = None) -> WordTable:
    edges = dict(edges) if edges else {}
    n = len(table)
    hour = hour_of(table["frame_time"])
    hbin = _bins(hour, "hour", n_bins, edges)
    flbin = _bins(table["frame_len"].to_numpy(np.float64),
                  "frame_len", n_bins, edges)

    qnames = table["dns_qry_name"].astype(str).to_numpy()
    splits = [subdomain_split(q) for q in qnames]
    sub_len = np.array([len(s[0]) for s in splits], np.float64)
    n_labels = np.array([min(s[2], 6) for s in splits], np.int64)
    tld_ok = np.array([int(s[3]) for s in splits], np.int64)
    sub_entropy = entropy_array([s[0] for s in splits])

    slbin = _bins(sub_len, "sub_len", n_bins, edges)
    ebin = _bins(sub_entropy, "sub_entropy", n_bins, edges)
    qtype = table["dns_qry_type"].to_numpy(np.int64)
    rcode = table["dns_qry_rcode"].to_numpy(np.int64)

    word = np.array(
        [f"{fl}_{h}_{sl}_{e}_{nl}_{qt}_{rc}_{tv}" for
         fl, h, sl, e, nl, qt, rc, tv in
         zip(flbin, hbin, slbin, ebin, n_labels, qtype, rcode, tld_ok)],
        dtype=object)
    return WordTable(
        ip=table["ip_dst"].astype(str).to_numpy(),   # reply → client IP
        word=word,
        event_idx=np.arange(n, dtype=np.int64),
        edges=edges,
    )


# ---------------------------------------------------------------------------
# proxy (SURVEY.md §2.1 #7: "domain, URI length/entropy bins, user-agent
# class, response code, time bin; document per client IP")
# ---------------------------------------------------------------------------


def _ua_classes(agents: np.ndarray, edges: dict,
                min_frac: float = 0.01) -> np.ndarray:
    """User-agent class: common agents keep their identity, rare ones
    collapse to 'RARE' (rarity is the signal). The common set is fitted
    metadata so apply-mode runs reproduce the classes."""
    if "ua_common" not in edges:
        vals, counts = np.unique(agents, return_counts=True)
        keep = vals[counts >= max(2, int(min_frac * agents.size))]
        edges["ua_common"] = sorted(keep.tolist())
    common = set(edges["ua_common"])
    return np.array([a if a in common else "RARE" for a in agents],
                    dtype=object)


def proxy_words(table: pd.DataFrame, n_bins: int = N_BINS_DEFAULT,
                edges: dict | None = None) -> WordTable:
    edges = dict(edges) if edges else {}
    n = len(table)
    hour = hour_of(table["p_date"].astype(str) + " " + table["p_time"].astype(str))
    hbin = _bins(hour, "hour", n_bins, edges)

    # The reference's proxy word recipe is "domain, URI length/entropy
    # bins, user-agent class, response code, time bin" (SURVEY.md §2.1 #7)
    # — deliberately few components so words repeat per client.
    uri = table["uripath"].astype(str).to_numpy()
    ulbin = _bins(np.array([len(u) for u in uri], np.float64),
                  "uri_len", n_bins, edges)
    uebin = _bins(entropy_array(uri), "uri_entropy", n_bins, edges)

    host = table["host"].astype(str).to_numpy()
    host_is_ip = np.array([int(bool(_IP_RE.match(h))) for h in host], np.int64)
    ua = _ua_classes(table["useragent"].astype(str).to_numpy(), edges)
    # Compact UA class id for the word string (single O(n) map pass).
    ua_code = {a: f"C{i}" for i, a in enumerate(edges["ua_common"])}
    ua_id = np.array([ua_code.get(a, "R") for a in ua], dtype=object)
    code_class = (table["respcode"].to_numpy(np.int64) // 100)

    word = np.array(
        [f"{cc}_{u}_{hi}_{ul}_{ue}_{h}" for cc, u, hi, ul, ue, h in
         zip(code_class, ua_id, host_is_ip, ulbin, uebin, hbin)],
        dtype=object)
    return WordTable(
        ip=table["clientip"].astype(str).to_numpy(),
        word=word,
        event_idx=np.arange(n, dtype=np.int64),
        edges=edges,
    )


WORD_FNS = {"flow": flow_words, "dns": dns_words, "proxy": proxy_words}
