"""Fleet-batched warm refit supervisor: N tenants' daily refits as one
vmapped Gibbs program per shape class (r20 tentpole; ROADMAP item 3).

The r19 supervisor (pipelines/daily.py) drives ONE model chain per
datatype-day; production ONI is per-tenant models, and the r12 bank
already *serves* thousands of tenants per dispatch. This supervisor
closes the loop on the FIT side: every tenant's warm refit for day d
runs through `models/fleet_gibbs` — tenants stacked into pow2 shape
classes (`compaction.pow2_bucket`, the model-bank padding discipline),
ONE fused vmapped program per (shape class, sweep budget), sharded
over the dp mesh through `parallel/fleet_shard` — so the fleet's fit
wall scales with the number of shape classes and the device's batch
throughput, not with the tenant count.

Per-tenant lifecycle state scales with it (every mechanism is the r19
discipline, sharded by tenant):

* **Ledger shards** — one `daily.DayLedger` per tenant under
  `<root>/ledger/<tenant>/` (sha256-stamped JSON-per-day, torn/rotted
  entries refused and the tenant-day re-executed). Resume skips only
  the (tenant, day) cells with verified entries; the rest re-execute
  deterministically.

* **Lineage shards** — each tenant's accepted day persists through
  `checkpoint.save_model` under `models/<tenant>/day-NNN` plus the
  stable `<tenant>/current` serving name, with parent_epoch /
  parent_digest chaining that TENANT's last ok day (content digests,
  so a crash-replayed save provably reproduces the same chain).

* **Drift gates** — per-tenant: each warm lane's fitted φ̂ is compared
  against its own prior (campaign.phi_topic_drift, nudged words
  excluded); lanes past `drift_max` re-fit COLD in a second stacked
  pass, never one-by-one.

* **Poison quarantine** — per-tenant: a tenant whose prepare fails,
  whose fit diverges (non-finite or collapsing ll, NaN tables), or
  whose accept exhausts its bounded retry is quarantined ALONE — a
  failed ledger entry, a sidecar under `<root>/quarantine/<tenant>/`,
  no model persisted — and warm-starts tomorrow from its last ok
  model. Tenant lanes are mathematically independent under the vmap
  (a lane's bits depend only on its own inputs and PRNG stream), so
  one tenant's bad day cannot perturb any other tenant's tables by
  even a bit — the property tests/test_fleet.py asserts literally.

* **Dismissal count nudge** — analyst dismissals fold into the stacked
  count tables as frozen pseudo-mass (`fleet_gibbs.nudge_counts`, the
  arXiv:1601.01142 streaming recipe) BEFORE the refit sweeps, replacing
  the ×DUPFACTOR corpus rebuild: the corpus is built once per
  tenant-day with no duplicated tokens, and the nudge's identity
  (sha256 of the dismissal rows) rides the model meta.

Fault sites (docs/ROBUSTNESS.md site table): `fleet:refit` fires once
per executed day at fleet-refit entry, PRE-MUTATION (before any model
save or ledger write), one bounded retry — the refit is deterministic
in its inputs, so the retry reproduces identical per-tenant lineage
digests (the chaos drill). `fleet:tenant` fires at each tenant's
accept entry, one bounded retry; exhaustion quarantines THAT tenant
alone.

Epoch propagation: accepted tenants publish to a serving
`ModelBank` (serving.model_bank.publish_refit) with their lineage
epoch, so a live bank invalidates exactly the refitted tenants'
cached winners and no others.

Drivers: `python -m onix.pipelines.fleet` (the chaos tests' subprocess
entry), scripts/exp_fleet.py (the acceptance experiment), and the
bench `daily_fleet` component.
"""

from __future__ import annotations

import json
import pathlib
import time

import numpy as np

from onix import checkpoint
from onix.config import DATATYPES, DailyConfig, LDAConfig
from onix.models import fleet_gibbs
from onix.models.lda_gibbs import LL_PARITY_BAND
from onix.pipelines.campaign import (_prepare, _winner_pairs,
                                     map_phi_prior, phi_topic_drift,
                                     vocab_word_keys)
from onix.pipelines.corpus_build import select_suspicious_events
from onix.pipelines.daily import DayLedger, _load_edges, _save_edges
from onix.utils import faults, telemetry
from onix.utils.obs import counters

#: Fleet supervisor manifest schema.
FLEET_SCHEMA = 1


class PoisonedFeed(RuntimeError):
    """A tenant-day's feed declared poisoned upstream — the chaos
    stand-in for a corrupt per-tenant ingest batch (the statistical
    screen in `_tenant_poison_check` guards the organic case)."""


def tenant_name(uid: int) -> str:
    return f"t{uid:04d}"


def _tenant_seed(seed: int, uid: int) -> int:
    # The campaign's per-item stream stride: distinct per-tenant feeds,
    # deterministic across arms and runs.
    return seed + 7919 * uid


def _nudge_rows(bundle, rows, dupfactor: int):
    """Map accumulated (ip, word) dismissal strings into TODAY's id
    spaces as nudge arrays: unique mapped pairs, weight `dupfactor`
    each — the exact mass the ×DUPFACTOR rebuild would have appended
    as tokens, delivered as a count nudge instead. Unmapped rows drop
    (the build_corpus stale-feedback rule)."""
    if not rows:
        return None, None, None
    ips = np.asarray([r[0] for r in rows], dtype=object)
    words = np.asarray([r[1] for r in rows], dtype=object)
    did = bundle.doc_index(ips, strict=False)
    wid = bundle.vocab.ids(words, strict=False)
    keep = (did >= 0) & (wid >= 0)
    if not keep.any():
        return None, None, None
    pairs = np.unique(np.stack([did[keep], wid[keep]], axis=1), axis=0)
    return (pairs[:, 0].astype(np.int32), pairs[:, 1].astype(np.int32),
            np.full(len(pairs), int(dupfactor), np.int32))


def _quarantine_tenant(root: pathlib.Path, tenant: str, day: int,
                       error: str) -> None:
    """Dead-letter ONE tenant's day (the r9 quarantine discipline,
    sharded): a JSON sidecar under `<root>/quarantine/<tenant>/`
    preserves the failure for the operator; no model persists, so the
    tenant's chain warm-starts tomorrow from its last ok day."""
    qdir = root / "quarantine" / tenant
    qdir.mkdir(parents=True, exist_ok=True)
    sidecar = qdir / f"day-{day:03d}.quarantine.json"
    sidecar.write_text(json.dumps({
        "tenant": tenant, "day": int(day), "error": error,
        "quarantined_at": round(time.time(), 3)}, indent=2) + "\n")
    counters.inc("fleet.quarantined_tenant_days")


def _tenant_poison_check(res: dict) -> str | None:
    """The per-tenant divergence screen (daily._poison_check, one
    lane): finite ll that did not collapse past the parity band, and
    finite tables."""
    ll, ll0 = res["ll_final"], res["ll_initial"]
    if not np.isfinite(ll):
        return f"ll band violation: final ll {ll}"
    if np.isfinite(ll0) and ll < ll0 - LL_PARITY_BAND * abs(ll0):
        return f"ll band violation: ll collapsed {ll0} -> {ll}"
    for k in ("theta", "phi_wk"):
        if not np.isfinite(res[k]).all():
            return f"NaN counts in {k}"
    return None


def _persisted_meta(models_dir, name: str) -> dict | None:
    json_path = checkpoint.model_path(models_dir, name).with_suffix(".json")
    try:
        return json.loads(json_path.read_text())
    except (OSError, ValueError):
        return None


def _refit_classes(classes, cfg: LDAConfig, programs: dict, *,
                   batched: bool, mesh=None) -> dict:
    """Run every shape class under ONE sweep budget and return the
    merged per-tenant results. `batched=True` is the fleet arm (one
    vmapped dispatch per class); `batched=False` is the sequential-
    supervisor arm — the SAME per-lane program dispatched once per
    tenant, which is the O(N) wall this module exists to remove and
    the bit-identity reference the bench asserts against."""
    from onix.parallel import fleet_shard

    k = cfg.n_topics
    results: dict[str, dict] = {}
    for sc in classes:
        d_pad, v_pad, _ = sc.key
        pkey = ("fleet" if batched else "seq", d_pad, v_pad,
                cfg.n_sweeps, cfg.burn_in)
        if pkey not in programs:
            make = (fleet_gibbs.make_fleet_refit if batched
                    else fleet_gibbs.make_tenant_refit)
            programs[pkey] = make(cfg, n_docs=d_pad, n_vocab=v_pad)
        program = programs[pkey]
        if batched:
            a = fleet_shard.shard_class(sc, mesh, k_topics=k)
            theta, phi, ll0, ll = program(
                a["z0"], a["docs"], a["words"], a["mask"], a["fb_docs"],
                a["fb_words"], a["fb_weights"], a["keys"])
            results.update(fleet_gibbs.unstack_results(sc, theta, phi,
                                                       ll0, ll))
        else:
            for i, t in enumerate(sc.tenants):
                theta, phi, ll0, ll = program(
                    sc.z0[i], sc.docs[i], sc.words[i], sc.mask[i],
                    sc.fb_docs[i], sc.fb_words[i], sc.fb_weights[i],
                    sc.keys[i])
                results[t.name] = {
                    "theta": np.asarray(theta, np.float32)[:t.n_docs],
                    "phi_wk": np.asarray(phi, np.float32)[:t.n_vocab],
                    "ll_initial": float(np.asarray(ll0)),
                    "ll_final": float(np.asarray(ll)),
                }
    return results


def run_fleet(n_days: int, n_tenants: int, root: str | pathlib.Path, *,
              n_events: int = 600, datatype: str = "flow",
              n_hosts: int | None = None, n_anomalies: int = 0,
              plants: dict | None = None, n_sweeps: int = 8,
              n_topics: int = 20, max_results: int = 100, seed: int = 0,
              generator: str = "mixture", dp: int = 1,
              feedback: dict | None = None, dupfactor: int = 1000,
              daily: DailyConfig | None = None, batched: bool = True,
              poison_feed=None, bank=None,
              collect_winner_pairs: bool = False,
              out_path: str | pathlib.Path | None = None) -> dict:
    """Drive `n_tenants` per-tenant model chains over `n_days` days.

    Tenant uid u (roster name `t{u:04d}`) draws day d's feed with seed
    `_tenant_seed(seed, u) + daily.day_seed_stride*(d-1)` and
    `plants.get(d, n_anomalies)` planted anomalies. `feedback` maps a
    day number to {tenant: [(ip, word), ...]} dismissal rows that
    apply from that day ON (accumulated per tenant); they reach the
    fit as the count nudge, weight `dupfactor`. `poison_feed` is a set
    of (tenant, day) pairs whose feed is declared poisoned (the chaos
    hook). `batched=False` runs the sequential-supervisor arm: same
    per-lane programs, one dispatch per tenant — bit-identical
    artifacts, O(N) fit wall. `bank` (a serving ModelBank) receives
    every accepted model with its lineage epoch.

    Resumable per (tenant, day): rerunning against the same root skips
    every cell with a verified ledger-shard entry. Returns the fleet
    manifest (also written to `out_path`)."""
    import jax

    from onix.parallel.mesh import make_mesh

    daily = daily if daily is not None else DailyConfig()
    daily.validate()
    if datatype not in DATATYPES:
        raise ValueError(f"unknown datatype {datatype!r}")
    plants = {int(k): int(v) for k, v in (plants or {}).items()}
    feedback = {int(k): dict(v) for k, v in (feedback or {}).items()}
    poison_feed = {(str(t), int(d)) for t, d in (poison_feed or ())}
    root = pathlib.Path(root)
    root.mkdir(parents=True, exist_ok=True)
    models_dir = root / "models"
    names = [tenant_name(u) for u in range(int(n_tenants))]
    ledgers = {t: DayLedger(root / "ledger" / t) for t in names}
    mesh = (make_mesh(dp=dp, mp=1, devices=jax.devices()[:dp])
            if dp > 1 else None)
    force_cold = daily.force_cold
    cfg = LDAConfig(n_topics=n_topics, n_sweeps=n_sweeps,
                    burn_in=max(1, n_sweeps // 2), seed=seed)
    ws_eff = daily.warm_sweeps or max(2, n_sweeps // 2)
    wb_eff = min(daily.warm_burn_in or 1, ws_eff - 1)
    wcfg = LDAConfig(n_topics=n_topics, n_sweeps=ws_eff,
                     burn_in=wb_eff, seed=seed)
    if generator == "sessions":
        from onix.pipelines.synth2 import SYNTH2_ARRAYS as gen_arrays
    else:
        from onix.pipelines.synth import SYNTH_ARRAYS as gen_arrays
    if n_hosts is None:
        n_hosts = max(120, min(200_000, n_events // 500))

    def feedback_upto(day: int, tenant: str) -> list:
        rows = []
        for d in sorted(feedback):
            if d <= day:
                rows.extend(feedback[d].get(tenant, ()))
        return rows

    # Per-tenant chain state, reconstructed from resumed ledger entries
    # as the day loop encounters them.
    prev_ok: dict[str, dict | None] = {t: None for t in names}
    ok_count: dict[str, int] = {t: 0 for t in names}
    edges = _load_edges(root, names)
    programs: dict = {}
    day_records: list[dict] = []
    fit_wall_s = 0.0
    padding: dict | None = None
    t_run = time.perf_counter()

    for day in range(1, int(n_days) + 1):
        tenant_bodies: dict[str, dict] = {}
        resumed: set[str] = set()
        for t in names:
            record = ledgers[t].read(day)
            if record is None:
                continue
            body = record["body"]
            exp_seed = (_tenant_seed(seed, names.index(t))
                        + daily.day_seed_stride * (day - 1))
            if (body.get("seed") != exp_seed
                    or body.get("datatype") != datatype):
                raise ValueError(
                    f"tenant {t} day {day} ledger entry under {root} "
                    "was produced by a different invocation — refusing "
                    "to splice chains (fresh root, or rerun with the "
                    "original parameters)")
            counters.inc("fleet.resumed_tenant_days")
            if body.get("status") == "ok":
                ok_count[t] += 1
                prev_ok[t] = dict(body["model"])
            tenant_bodies[t] = dict(body, timing=record["timing"],
                                    resumed=True)
            resumed.add(t)

        todo = [t for t in names if t not in resumed]
        if not todo:
            day_records.append({"day": day, "executed": 0,
                                "tenants": tenant_bodies})
            continue

        t_day = time.perf_counter()
        with telemetry.TRACER.trace(f"fleet-{seed}-{day:03d}"), \
                telemetry.TRACER.span("fleet.day", day=day,
                                      tenants=len(todo)):
            # ---- per-tenant PREPARE (host): synthesize -> corpus ----
            preps: dict[str, dict] = {}
            failed: dict[str, str] = {}
            for t in todo:
                uid = names.index(t)
                day_seed = (_tenant_seed(seed, uid)
                            + daily.day_seed_stride * (day - 1))
                try:
                    if (t, day) in poison_feed:
                        counters.inc("fleet.poisoned_feeds")
                        raise PoisonedFeed(
                            f"feed for {t} day {day} declared poisoned")
                    prep = _prepare(datatype, n_events, n_hosts,
                                    plants.get(day, n_anomalies),
                                    day_seed, gen_arrays,
                                    edges=edges.get(t))
                except Exception as e:   # poison tenant, not the fleet
                    counters.inc("fleet.tenant_prepare_failed")
                    failed[t] = repr(e)
                    continue
                if t not in edges and prep.words is not None:
                    _save_edges(root, t, prep.words.edges)
                    edges[t] = prep.words.edges
                bundle = prep.bundle
                key_today = vocab_word_keys(bundle)
                fb_d, fb_w, fb_wt = _nudge_rows(
                    bundle, feedback_upto(day, t), dupfactor)
                if fb_d is not None:
                    counters.inc("fleet.nudged_tenant_days")
                init_phi = warm = None
                if not force_cold and prev_ok[t] is not None:
                    try:
                        m = checkpoint.load_model(models_dir,
                                                  prev_ok[t]["name"])
                    except checkpoint.ModelIntegrityError:
                        counters.inc("fleet.warm_parent_refused")
                        m = None
                    if m is None or "word_key" not in m.arrays \
                            or key_today is None:
                        counters.inc("fleet.warm_unmappable")
                    else:
                        warm = {"phi": m.arrays["phi_wk"],
                                "word_key": m.arrays["word_key"]}
                        init_phi, _ = map_phi_prior(
                            key_today, warm["phi"], warm["word_key"])
                td = fleet_gibbs.TenantDay(
                    name=t, uid=uid,
                    docs=bundle.corpus.doc_ids,
                    words=bundle.corpus.word_ids,
                    n_docs=bundle.corpus.n_docs,
                    n_vocab=bundle.corpus.n_vocab,
                    init_phi=init_phi, fb_docs=fb_d, fb_words=fb_w,
                    fb_weights=fb_wt)
                preps[t] = {"prep": prep, "bundle": bundle,
                            "key_today": key_today, "warm": warm,
                            "tenant_day": td, "seed": day_seed,
                            "fb_words": fb_w}

            # ---- the fleet refit (fleet:refit — pre-mutation, one
            # bounded retry; deterministic, so a retried day reproduces
            # identical lineage digests) -----------------------------
            t_fit = time.perf_counter()
            results: dict[str, dict] = {}
            form: dict[str, str] = {}
            drift: dict[str, float | None] = {}
            if preps:
                with telemetry.TRACER.span("fleet.refit",
                                           tenants=len(preps)):
                    for attempt in (0, 1):
                        try:
                            faults.fire("fleet", "refit")
                            break
                        except faults.InjectedFault:
                            counters.inc("fleet.refit_retry")
                            if attempt:
                                raise
                    warm_tds = [p["tenant_day"] for p in preps.values()
                                if p["tenant_day"].init_phi is not None]
                    cold_tds = [p["tenant_day"] for p in preps.values()
                                if p["tenant_day"].init_phi is None]
                    if warm_tds:
                        counters.inc("fleet.warm_tenant_days",
                                     len(warm_tds))
                        classes = fleet_gibbs.stack_tenants(
                            warm_tds, k_topics=n_topics, seed=seed,
                            day=day)
                        if padding is None:
                            padding = fleet_gibbs.padding_stats(classes)
                        results.update(_refit_classes(
                            classes, wcfg, programs, batched=batched,
                            mesh=mesh))
                        form.update({t.name: "warm" for t in warm_tds})
                    if cold_tds:
                        counters.inc("fleet.cold_tenant_days",
                                     len(cold_tds))
                        classes = fleet_gibbs.stack_tenants(
                            cold_tds, k_topics=n_topics, seed=seed,
                            day=day)
                        if padding is None:
                            padding = fleet_gibbs.padding_stats(classes)
                        results.update(_refit_classes(
                            classes, cfg, programs, batched=batched,
                            mesh=mesh))
                        form.update({t.name: "cold" for t in cold_tds})

                    # Per-tenant drift gates: warm lanes past the band
                    # re-fit COLD in one second stacked pass.
                    drifted = []
                    for t in list(results):
                        if form[t] != "warm":
                            drift[t] = None
                            continue
                        p = preps[t]
                        fb_keys = None
                        if p["fb_words"] is not None \
                                and p["key_today"] is not None:
                            fb_keys = p["key_today"][np.unique(
                                p["fb_words"])]
                        d = phi_topic_drift(
                            results[t]["phi_wk"], p["key_today"],
                            p["warm"]["phi"], p["warm"]["word_key"],
                            exclude_keys=fb_keys)
                        drift[t] = d
                        if d is not None:
                            telemetry.histograms.observe("fleet.drift",
                                                         d)
                        if d is not None and daily.drift_max > 0 \
                                and d > daily.drift_max:
                            drifted.append(t)
                    if drifted:
                        counters.inc("fleet.drift_cold_refits",
                                     len(drifted))
                        cold2 = []
                        for t in drifted:
                            td = preps[t]["tenant_day"]
                            cold2.append(fleet_gibbs.TenantDay(
                                name=td.name, uid=td.uid, docs=td.docs,
                                words=td.words, n_docs=td.n_docs,
                                n_vocab=td.n_vocab, init_phi=None,
                                fb_docs=td.fb_docs,
                                fb_words=td.fb_words,
                                fb_weights=td.fb_weights))
                        classes = fleet_gibbs.stack_tenants(
                            cold2, k_topics=n_topics, seed=seed,
                            day=day)
                        results.update(_refit_classes(
                            classes, cfg, programs, batched=batched,
                            mesh=mesh))
                        form.update({t: "cold_drift" for t in drifted})
            fit_wall_day = time.perf_counter() - t_fit
            fit_wall_s += fit_wall_day

            # ---- per-tenant accept: screen, score, persist ----------
            for t in todo:
                uid = names.index(t)
                day_seed = (_tenant_seed(seed, uid)
                            + daily.day_seed_stride * (day - 1))
                err = failed.get(t)
                if err is None:
                    err = _tenant_poison_check(results[t])
                winners = None
                if err is None:
                    try:
                        # fleet:tenant — accept entry, pre-mutation for
                        # THIS tenant; exhaustion quarantines it alone.
                        for attempt in (0, 1):
                            try:
                                faults.fire("fleet", "tenant")
                                break
                            except faults.InjectedFault:
                                counters.inc("fleet.tenant_retry")
                                if attempt:
                                    raise
                        p = preps[t]
                        res = results[t]
                        top = select_suspicious_events(
                            p["bundle"], res["theta"], res["phi_wk"],
                            n_events, tol=1.0, max_results=max_results)
                        idx = np.asarray(top.indices)
                        scores = np.asarray(top.scores)
                        keep = idx >= 0
                        winners = {
                            "indices": idx[keep].tolist(),
                            "scores": [float(s) for s in scores[keep]],
                            "planted_in_bottom_k": len(
                                p["prep"].planted
                                & set(idx[keep].tolist())),
                        }
                        if collect_winner_pairs:
                            winners["winner_pairs"] = _winner_pairs(
                                p["prep"], idx[keep], n_events)
                    except Exception as e:
                        counters.inc("fleet.tenant_accept_failed")
                        err = repr(e)

                if err is not None:
                    counters.inc("fleet.failed_tenant_days")
                    _quarantine_tenant(root, t, day, err)
                    body = {"tenant": t, "day": day, "status": "failed",
                            "seed": day_seed, "datatype": datatype,
                            "error": err}
                    timing = {"wall_s": round(fit_wall_day, 3)}
                    ledgers[t].write(day, body, timing)
                    tenant_bodies[t] = dict(body, timing=timing)
                    continue

                p, res = preps[t], results[t]
                td = p["tenant_day"]
                content = checkpoint.model_content_digest(
                    res["theta"], res["phi_wk"])
                parent = prev_ok[t]
                epoch = ok_count[t] + 1
                extra = ({"word_key": p["key_today"]}
                         if p["key_today"] is not None else None)
                meta = {"day": day, "tenant": t, "refit_form": form[t],
                        "drift": drift.get(t),
                        "nudge": fleet_gibbs.nudge_digest(td)}
                name = f"{t}/day-{day:03d}"
                checkpoint.save_model(
                    models_dir, name, res["theta"], res["phi_wk"],
                    meta=meta, epoch=epoch,
                    parent_epoch=(parent or {}).get("epoch"),
                    parent_digest=(parent or {}).get("content_sha256"),
                    extra_arrays=extra)
                # The stable serving name: the daily.py current-tenant
                # rules (never roll back to an older day; epoch moves
                # past a persisted stamp on content change).
                cur_name = f"{t}/current"
                persisted = _persisted_meta(models_dir, cur_name)
                cur_day = (int(persisted.get("day", -1))
                           if persisted else -1)
                if cur_day <= day:
                    cur_epoch = epoch
                    if persisted is not None \
                            and int(persisted.get("model_epoch", 0)) \
                            >= cur_epoch \
                            and persisted.get("content_sha256") \
                            != content:
                        cur_epoch = int(persisted["model_epoch"]) + 1
                    checkpoint.save_model(
                        models_dir, cur_name, res["theta"],
                        res["phi_wk"], meta=meta, epoch=cur_epoch,
                        parent_epoch=(parent or {}).get("epoch"),
                        parent_digest=(parent or {})
                        .get("content_sha256"),
                        extra_arrays=extra)
                model_body = {
                    "name": name, "epoch": epoch,
                    "content_sha256": content,
                    "parent_epoch": (parent or {}).get("epoch"),
                    "parent_digest": (parent or {}).get("content_sha256"),
                }
                body = {
                    "tenant": t, "day": day, "status": "ok",
                    "seed": day_seed, "datatype": datatype,
                    "planted": plants.get(day, n_anomalies),
                    "refit": {"form": form[t], "drift": drift.get(t)},
                    "winners": winners,
                    "nudge": meta["nudge"],
                    "model": model_body,
                }
                timing = {"wall_s": round(fit_wall_day, 3)}
                ledgers[t].write(day, body, timing)
                ok_count[t] += 1
                prev_ok[t] = dict(model_body)
                tenant_bodies[t] = dict(body, timing=timing)
                if bank is not None:
                    from onix.serving.model_bank import publish_refit
                    publish_refit(bank, t, res["theta"], res["phi_wk"],
                                  epoch=epoch)

        day_records.append({
            "day": day, "executed": len(todo),
            "fit_wall_s": round(fit_wall_day, 3),
            "day_wall_s": round(time.perf_counter() - t_day, 3),
            "tenants": tenant_bodies,
        })

    snap = counters.snapshot
    out = {
        "fleet_schema": FLEET_SCHEMA,
        "supervisor": {
            "n_days": int(n_days), "n_tenants": int(n_tenants),
            "datatype": datatype, "n_events": int(n_events),
            "n_sweeps": n_sweeps, "n_topics": n_topics,
            "max_results": max_results, "seed": seed,
            "generator": generator, "dp": int(dp),
            "batched": bool(batched),
            "plants": {str(k): v for k, v in sorted(plants.items())},
            "base_anomalies": n_anomalies,
            "warm_sweeps": ws_eff, "warm_burn_in": wb_eff,
            "drift_max": daily.drift_max,
            "force_cold": bool(force_cold),
            "feedback_days": sorted(feedback),
            "poison_feed": sorted([t, d] for t, d in poison_feed),
            "root": str(root),
        },
        "days": day_records,
        "padding": padding,
        "aggregate": {
            "ok_tenant_days": sum(
                1 for r in day_records for b in r["tenants"].values()
                if b.get("status") == "ok"),
            "failed_tenant_days": sum(
                1 for r in day_records for b in r["tenants"].values()
                if b.get("status") == "failed"),
            "resumed_tenant_days": sum(
                1 for r in day_records for b in r["tenants"].values()
                if b.get("resumed")),
            "fit_wall_s": round(fit_wall_s, 3),
            "wall_s": round(time.perf_counter() - t_run, 3),
        },
        "resilience": {**snap("fleet"), **snap("campaign"),
                       **snap("faults"), **snap("ckpt"),
                       **snap("daily")},
        "telemetry": telemetry.snapshot(),
    }
    if out_path is not None:
        out_path = pathlib.Path(out_path)
        out_path.parent.mkdir(parents=True, exist_ok=True)
        out_path.write_text(json.dumps(out, indent=2) + "\n")
    return out


def tenant_lineage(manifest: dict, tenant: str) -> list[dict]:
    """One tenant's model chain from a fleet manifest: (day, epoch,
    content digest, parent linkage) per ok day — what the chaos drill
    compares bit-for-bit across runs."""
    out = []
    for rec in manifest["days"]:
        body = rec["tenants"].get(tenant)
        if body is None or body.get("status") != "ok":
            continue
        info = body["model"]
        out.append({"day": body["day"], "epoch": info["epoch"],
                    "content_sha256": info["content_sha256"],
                    "parent_epoch": info["parent_epoch"],
                    "parent_digest": info["parent_digest"]})
    return out


def main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="fleet-batched warm refit: N tenants' daily model "
                    "chains through one vmapped Gibbs program per "
                    "shape class")
    ap.add_argument("--days", type=int, default=7)
    ap.add_argument("--tenants", type=int, default=8)
    ap.add_argument("--root", required=True)
    ap.add_argument("--events", type=int, default=600)
    ap.add_argument("--datatype", default="flow")
    ap.add_argument("--anomalies", type=int, default=0)
    ap.add_argument("--sweeps", type=int, default=8)
    ap.add_argument("--topics", type=int, default=20)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--sequential", action="store_true",
                    help="the sequential-supervisor arm (one dispatch "
                         "per tenant; bit-identical artifacts)")
    ap.add_argument("--force-cold", action="store_true")
    ap.add_argument("--fault-plan", default=None,
                    help="install a chaos plan (utils/faults.py grammar)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    if args.fault_plan:
        faults.install_plan(args.fault_plan)
    dcfg = DailyConfig()
    if args.force_cold:
        dcfg.force_cold = True
    manifest = run_fleet(
        args.days, args.tenants, args.root, n_events=args.events,
        datatype=args.datatype, n_anomalies=args.anomalies,
        n_sweeps=args.sweeps, n_topics=args.topics, seed=args.seed,
        dp=args.dp, daily=dcfg, batched=not args.sequential,
        out_path=args.out)
    agg = manifest["aggregate"]
    print(json.dumps({"ok_tenant_days": agg["ok_tenant_days"],
                      "failed_tenant_days": agg["failed_tenant_days"],
                      "resumed_tenant_days": agg["resumed_tenant_days"],
                      "fit_wall_s": agg["fit_wall_s"],
                      "wall_s": agg["wall_s"]}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
