"""Synthetic telemetry generators with planted anomalies.

The reference validated end-to-end behavior with a canned demo day
(2016-07-08, reference README.md:50-58) — the Docker demo effectively IS
its integration fixture (SURVEY.md §4). The mount carries no data, so
onix generates its own demo days.

Background traffic is ROLE-STRUCTURED: each host draws a mixture over a
small set of behavior profiles (web browsing, DNS-heavy, backup, mail,
…) and its events are emitted from that mixture — the same latent
structure real enterprise traffic has and exactly what a topic model can
learn per-IP. Anomalies are off-profile events (exfil-shaped flows,
DGA/tunnel DNS, beaconing proxy hits) whose row indices are returned for
assertion — the "filter billion of events to a few thousands" contract
(reference README.md:42).
"""

from __future__ import annotations

import numpy as np
import pandas as pd

DEMO_DATE = "2016-07-08"


def _ips(n_hosts: int, prefix: str = "10.0") -> np.ndarray:
    return np.array([f"{prefix}.{i // 256}.{i % 256}" for i in range(n_hosts)])


def _host_mixture(rng: np.random.Generator, n_hosts: int,
                  n_profiles: int) -> np.ndarray:
    """Sparse per-host profile mixture (each host has 1-2 dominant roles)."""
    return rng.dirichlet(np.full(n_profiles, 0.3), size=n_hosts)


def _times(date: str, hours: np.ndarray) -> list[str]:
    hh = hours.astype(int)
    mm = ((hours - hh) * 60).astype(int)
    return [f"{date} {h:02d}:{m:02d}:00" for h, m in zip(hh, mm)]


def _shuffle(table: pd.DataFrame, n_bg: int, n_events: int,
             rng: np.random.Generator) -> tuple[pd.DataFrame, np.ndarray]:
    """Shuffle rows; return (table, new indices of the planted anomalies)."""
    perm = rng.permutation(n_events)
    table = table.iloc[perm].reset_index(drop=True)
    inv = np.empty(n_events, np.int64)
    inv[perm] = np.arange(n_events)
    return table, np.sort(inv[np.arange(n_bg, n_events)])


# ---------------------------------------------------------------------------
# flow
# ---------------------------------------------------------------------------

# (dport, proto, peak_hour, hour_sd, log_pkt_mu, log_byte_per_pkt_mu)
_FLOW_PROFILES = [
    (443, "TCP", 14.0, 2.5, 3.0, 6.2),    # web browsing
    (80, "TCP", 11.0, 3.0, 2.5, 6.0),     # legacy web
    (53, "UDP", 13.0, 5.0, 0.7, 4.2),     # dns chatter
    (22, "TCP", 10.0, 4.0, 4.0, 5.5),     # ssh/dev
    (445, "TCP", 2.0, 1.5, 6.0, 7.0),     # nightly backup/smb
    (25, "TCP", 9.0, 3.0, 3.5, 6.5),      # mail
]


def synth_flow_day(n_events: int = 20000, n_hosts: int = 120,
                   n_anomalies: int = 30, date: str = DEMO_DATE,
                   seed: int = 0) -> tuple[pd.DataFrame, np.ndarray]:
    """One day of netflow records (nfdump-style columns, SURVEY.md §2.1 #2).

    Returns (table, anomaly_row_indices)."""
    rng = np.random.default_rng(seed)
    hosts = _ips(n_hosts)
    n_prof = len(_FLOW_PROFILES)
    mix = _host_mixture(rng, n_hosts, n_prof)
    # Each profile talks to its own small server pool (per-role peers).
    servers = {p: np.array([f"192.168.{p}.{i + 1}" for i in range(4)])
               for p in range(n_prof)}

    n_bg = n_events - n_anomalies
    h_idx = rng.integers(0, n_hosts, n_bg)
    # Vectorized profile draw per event from the host's mixture.
    u = rng.random(n_bg)
    prof = (mix[h_idx].cumsum(axis=1) < u[:, None]).sum(axis=1)
    prof = np.clip(prof, 0, n_prof - 1)

    cfg = np.array(_FLOW_PROFILES, dtype=object)
    dport = np.array([cfg[p][0] for p in prof], np.int64)
    proto = np.array([cfg[p][1] for p in prof], dtype=object)
    hour = np.clip(rng.normal([cfg[p][2] for p in prof],
                              [cfg[p][3] for p in prof]), 0, 23.99)
    ipkt = np.exp(rng.normal([cfg[p][4] for p in prof], 0.6)).astype(np.int64) + 1
    bpp = np.exp(rng.normal([cfg[p][5] for p in prof], 0.3)).astype(np.int64) + 40
    ibyt = ipkt * bpp
    sip = hosts[h_idx]
    dip = np.array([servers[p][i % 4] for p, i in
                    zip(prof, rng.integers(0, 4, n_bg))])
    sport = rng.integers(1025, 65535, n_bg)

    # Anomalies: exfil-shaped — ephemeral↔ephemeral ports (the off-profile
    # signature: background traffic always has a service port) to rare
    # external peers. Each anomaly is its OWN campaign: sizes drawn
    # log-uniform across the whole background range and hours uniform, so
    # the plant spreads over the hour/packet/byte bin grid — tiny beacons
    # through bulk exfil at all times of day — and no signature word
    # accumulates count. (A homogeneous plant collapses into one word
    # whose count reaches the vocabulary median and stops being rare —
    # word rarity IS the detection signal.)
    a_sip = hosts[rng.integers(0, n_hosts, n_anomalies)]
    # External peers from the RFC 5737 documentation nets — proper
    # address space for synthetic data, and the builtin GeoIPDB places
    # them at demo coordinates so the dashboard's geo view lights up
    # with exactly the suspicious endpoints.
    a_net = rng.integers(0, 3, n_anomalies)
    a_dip = np.array([f"{('192.0.2', '198.51.100', '203.0.113')[n]}"
                      f".{rng.integers(1, 255)}"
                      for n in a_net])
    a_dport = rng.integers(31337, 65535, n_anomalies)
    a_sport = rng.integers(1025, 65535, n_anomalies)
    a_proto = np.where(rng.random(n_anomalies) < 0.25,
                       "UDP", "TCP").astype(object)
    a_hour = rng.uniform(0, 24, n_anomalies) % 23.99
    a_ipkt = np.exp(rng.uniform(0.3, 8.5, n_anomalies)).astype(np.int64) + 1
    a_bpp = np.exp(rng.uniform(3.7, 7.2, n_anomalies)) + 40
    a_ibyt = a_ipkt * a_bpp.astype(np.int64)

    def col(bg, an):
        return np.concatenate([bg, an])

    table = pd.DataFrame({
        "treceived": _times(date, col(hour, a_hour)),
        "sip": col(sip, a_sip),
        "dip": col(dip, a_dip),
        "sport": col(sport, a_sport).astype(np.int32),
        "dport": col(dport, a_dport).astype(np.int32),
        "proto": col(proto, a_proto),
        "ipkt": col(ipkt, a_ipkt),
        "ibyt": col(ibyt, a_ibyt),
        "opkt": (col(ipkt, a_ipkt) * 0.8).astype(np.int64),
        "obyt": (col(ibyt, a_ibyt) * 0.3).astype(np.int64),
    })
    return _shuffle(table, n_bg, n_events, rng)


FLOW_PROTO_CLASSES = ["ICMP", "TCP", "UDP"]    # id table for numeric path


def synth_flow_day_arrays(n_events: int, n_hosts: int = 100_000,
                          n_anomalies: int | None = None, seed: int = 0,
                          chunk: int = 10_000_000) -> dict:
    """Columnar NUMERIC flow day for the 10⁸–10⁹-row configs
    (BASELINE.json configs[3]): same role-mixture background and
    exfil-shaped anomalies as `synth_flow_day`, but zero Python-object
    columns — uint32 IPs, small-int ports/protocols, float hours —
    generated in chunks so peak memory stays bounded.

    Returns a dict of arrays (sip_u32, dip_u32, sport, dport, proto_id,
    hour, ipkt, ibyt, anomaly_idx, proto_classes). Rows are NOT shuffled
    (background first, anomalies last — `anomaly_idx` says where); the
    Gibbs engine shuffles tokens itself and the corpus build is
    order-insensitive.
    """
    if n_anomalies is None:
        n_anomalies = max(30, n_events // 10_000)
    # A tail chunk smaller than the anomaly floor must not make the
    # background count negative.
    n_anomalies = min(n_anomalies, n_events)
    rng = np.random.default_rng(seed)
    n_prof = len(_FLOW_PROFILES)
    mix_cum = _host_mixture(rng, n_hosts, n_prof).cumsum(axis=1)
    mix_cum = mix_cum.astype(np.float32)

    proto_of = np.array([FLOW_PROTO_CLASSES.index(p[1])
                         for p in _FLOW_PROFILES], np.int8)
    dport_of = np.array([p[0] for p in _FLOW_PROFILES], np.int32)
    peak_of = np.array([p[2] for p in _FLOW_PROFILES], np.float32)
    hsd_of = np.array([p[3] for p in _FLOW_PROFILES], np.float32)
    lpkt_of = np.array([p[4] for p in _FLOW_PROFILES], np.float32)
    lbpp_of = np.array([p[5] for p in _FLOW_PROFILES], np.float32)
    # 10.x.y.z host space; 192.168.p.i per-profile server pools.
    host_base = np.uint32(10 << 24)
    srv_base = np.uint32((192 << 24) | (168 << 16))

    n_bg = n_events - n_anomalies
    out = {
        "sip_u32": np.empty(n_events, np.uint32),
        "dip_u32": np.empty(n_events, np.uint32),
        "sport": np.empty(n_events, np.int32),
        "dport": np.empty(n_events, np.int32),
        "proto_id": np.empty(n_events, np.int8),
        "hour": np.empty(n_events, np.float32),
        "ipkt": np.empty(n_events, np.int64),
        "ibyt": np.empty(n_events, np.int64),
    }
    for lo in range(0, n_bg, chunk):
        hi = min(lo + chunk, n_bg)
        m = hi - lo
        h_idx = rng.integers(0, n_hosts, m)
        u = rng.random(m, np.float32)
        prof = (mix_cum[h_idx] < u[:, None]).sum(axis=1)
        prof = np.clip(prof, 0, n_prof - 1)
        out["sip_u32"][lo:hi] = host_base + h_idx.astype(np.uint32)
        out["dip_u32"][lo:hi] = (srv_base
                                 + (prof.astype(np.uint32) << 8)
                                 + rng.integers(1, 5, m).astype(np.uint32))
        out["sport"][lo:hi] = rng.integers(1025, 65535, m)
        out["dport"][lo:hi] = dport_of[prof]
        out["proto_id"][lo:hi] = proto_of[prof]
        out["hour"][lo:hi] = np.clip(
            rng.normal(peak_of[prof], hsd_of[prof]), 0, 23.99)
        ipkt = np.exp(rng.normal(lpkt_of[prof], 0.6)).astype(np.int64) + 1
        bpp = np.exp(rng.normal(lbpp_of[prof], 0.3)).astype(np.int64) + 40
        out["ipkt"][lo:hi] = ipkt
        out["ibyt"][lo:hi] = ipkt * bpp

    # Anomalies: exfil-shaped, each its own campaign spread across the
    # bin grid — same recipe (and same rationale) as synth_flow_day.
    a = slice(n_bg, n_events)
    out["sip_u32"][a] = host_base + rng.integers(
        0, n_hosts, n_anomalies).astype(np.uint32)
    out["dip_u32"][a] = ((np.uint32(203 << 24))
                         + (rng.integers(0, 16, n_anomalies) << 8).astype(np.uint32)
                         + rng.integers(1, 255, n_anomalies).astype(np.uint32))
    out["sport"][a] = rng.integers(1025, 65535, n_anomalies)
    out["dport"][a] = rng.integers(31337, 65535, n_anomalies)
    out["proto_id"][a] = np.where(rng.random(n_anomalies) < 0.25,
                                  FLOW_PROTO_CLASSES.index("UDP"),
                                  FLOW_PROTO_CLASSES.index("TCP")).astype(np.int8)
    out["hour"][a] = rng.uniform(0, 24, n_anomalies) % 23.99
    a_ipkt = np.exp(rng.uniform(0.3, 8.5, n_anomalies)).astype(np.int64) + 1
    a_bpp = np.exp(rng.uniform(3.7, 7.2, n_anomalies)) + 40
    out["ipkt"][a] = a_ipkt
    out["ibyt"][a] = a_ipkt * a_bpp.astype(np.int64)
    out["anomaly_idx"] = np.arange(n_bg, n_events, dtype=np.int64)
    out["proto_classes"] = list(FLOW_PROTO_CLASSES)
    return out


# ---------------------------------------------------------------------------
# dns
# ---------------------------------------------------------------------------

# (domain pool, subdomain pool, qtype dist, peak_hour, hour_sd)
_DNS_PROFILES = [
    (["google.com", "gstatic.com", "youtube.com"],
     ["www", "", "apis"], [1, 28], 13.0, 3.0),
    (["github.com", "npmjs.org", "pypi.org"],
     ["api", "registry", ""], [1, 28], 11.0, 2.5),
    (["office365.com", "windowsupdate.com", "live.com"],
     ["outlook", "login", "update"], [1], 10.0, 3.5),
    (["netflix.com", "nflxvideo.net", "akamai.net"],
     ["www", "cdn", "media"], [1, 28], 20.0, 2.5),
    (["facebook.com", "fbcdn.net", "instagram.com"],
     ["www", "static", "edge"], [1], 15.0, 4.0),
]


def synth_dns_day(n_events: int = 20000, n_hosts: int = 120,
                  n_anomalies: int = 30, date: str = DEMO_DATE,
                  seed: int = 0) -> tuple[pd.DataFrame, np.ndarray]:
    """One day of DNS replies (tshark-style columns, SURVEY.md §2.1 #6).

    Anomalies: DGA/tunnel-shaped — long high-entropy subdomains, TXT
    queries, off-hours, NXDOMAIN mixes."""
    rng = np.random.default_rng(seed)
    hosts = _ips(n_hosts)
    n_prof = len(_DNS_PROFILES)
    mix = _host_mixture(rng, n_hosts, n_prof)

    n_bg = n_events - n_anomalies
    h_idx = rng.integers(0, n_hosts, n_bg)
    u = rng.random(n_bg)
    prof = np.clip((mix[h_idx].cumsum(axis=1) < u[:, None]).sum(axis=1),
                   0, n_prof - 1)

    qname, qtype, hour = [], [], []
    for p in prof:
        doms, subs, qts, mu, sd = _DNS_PROFILES[p]
        sub = subs[rng.integers(0, len(subs))]
        dom = doms[rng.integers(0, len(doms))]
        qname.append(f"{sub}.{dom}" if sub else dom)
        qtype.append(qts[rng.integers(0, len(qts))])
        hour.append(np.clip(rng.normal(mu, sd), 0, 23.99))
    qname = np.array(qname, dtype=object)
    qtype = np.array(qtype, np.int32)
    hour = np.array(hour)
    rcode = np.zeros(n_bg, np.int32)
    frame_len = (80 + 1.2 * np.char.str_len(qname.astype(str))
                 + rng.integers(0, 12, n_bg)).astype(np.int32)

    a_qname = _dga_names(rng, n_anomalies)
    a_hour = rng.uniform(0, 6, n_anomalies)
    a_qtype = rng.choice([16, 10, 255], n_anomalies).astype(np.int32)  # TXT/NULL/ANY
    a_rcode = rng.choice([0, 3], n_anomalies).astype(np.int32)
    a_frame_len = (120 + 4 * np.char.str_len(a_qname.astype(str))).astype(np.int32)

    def col(bg, an):
        return np.concatenate([bg, an])

    table = pd.DataFrame({
        "frame_time": _times(date, col(hour, a_hour)),
        "frame_len": col(frame_len, a_frame_len),
        "ip_dst": col(hosts[h_idx], hosts[rng.integers(0, n_hosts, n_anomalies)]),
        "dns_qry_name": col(qname, a_qname),
        "dns_qry_type": col(qtype, a_qtype),
        "dns_qry_rcode": col(rcode, a_rcode),
    })
    return _shuffle(table, n_bg, n_events, rng)


def _dga_names(rng: np.random.Generator, n: int) -> np.ndarray:
    """DGA/tunnel-shaped names: long high-entropy random labels under
    junk TLDs — each one its own campaign (heterogeneous in word space,
    same rationale as the flow anomaly recipe)."""
    alphabet = np.array(list("abcdefghijklmnopqrstuvwxyz0123456789"))
    tlds = np.array(["biz", "info", "notld", "xy"], dtype=object)
    lens = rng.integers(18, 40, n)
    return np.array(
        ["".join(rng.choice(alphabet, m)) + "." + tlds[rng.integers(0, 4)]
         for m in lens], dtype=object)


def synth_dns_day_arrays(n_events: int, n_hosts: int = 100_000,
                         n_anomalies: int | None = None, seed: int = 0,
                         chunk: int = 10_000_000) -> dict:
    """Columnar DNS day for the 10⁸-row configs[1] path: same
    role-mixture background and DGA-shaped anomalies as `synth_dns_day`
    but DICTIONARY-ENCODED — `qnames` is the unique name table (profile
    pool + one DGA name per anomaly, tiny vs rows), `qname_codes` the
    per-row index, everything else numeric. Rows are background-first,
    anomalies last (`anomaly_idx` says where), matching
    synth_flow_day_arrays' contract."""
    if n_anomalies is None:
        n_anomalies = max(30, n_events // 10_000)
    n_anomalies = min(n_anomalies, n_events)
    rng = np.random.default_rng(seed)
    n_prof = len(_DNS_PROFILES)
    mix_cum = _host_mixture(rng, n_hosts, n_prof).cumsum(axis=1).astype(np.float32)

    # Flattened unique background name table: per profile, subs x doms.
    names: list[str] = []
    prof_name_lo = np.zeros(n_prof + 1, np.int64)
    prof_qts: list[np.ndarray] = []
    for p, (doms, subs, qts, _mu, _sd) in enumerate(_DNS_PROFILES):
        for s in subs:
            for d in doms:
                names.append(f"{s}.{d}" if s else d)
        prof_name_lo[p + 1] = len(names)
        prof_qts.append(np.asarray(qts, np.int64))
    peak_of = np.array([p[3] for p in _DNS_PROFILES], np.float32)
    hsd_of = np.array([p[4] for p in _DNS_PROFILES], np.float32)
    n_names_of = np.diff(prof_name_lo)
    # Per-profile qtype pools ragged -> rectangular for vectorized draw.
    qt_w = max(len(q) for q in prof_qts)
    qt_table = np.stack([np.pad(q, (0, qt_w - len(q)), mode="edge")
                         for q in prof_qts])
    qt_n = np.array([len(q) for q in prof_qts], np.int64)

    host_base = np.uint32(10 << 24)
    n_bg = n_events - n_anomalies
    out = {
        "client_u32": np.empty(n_events, np.uint32),
        "qname_codes": np.empty(n_events, np.int64),
        "qtype": np.empty(n_events, np.int32),
        "rcode": np.empty(n_events, np.int32),
        "frame_len": np.empty(n_events, np.int32),
        "hour": np.empty(n_events, np.float32),
    }
    uniq_len = np.fromiter((len(s) for s in names), np.int64, len(names))
    for lo in range(0, n_bg, chunk):
        hi = min(lo + chunk, n_bg)
        m = hi - lo
        h_idx = rng.integers(0, n_hosts, m)
        u = rng.random(m, np.float32)
        prof = np.clip((mix_cum[h_idx] < u[:, None]).sum(axis=1),
                       0, n_prof - 1)
        codes = prof_name_lo[prof] + rng.integers(0, n_names_of[prof])
        out["client_u32"][lo:hi] = host_base + h_idx.astype(np.uint32)
        out["qname_codes"][lo:hi] = codes
        out["qtype"][lo:hi] = qt_table[prof, rng.integers(0, qt_n[prof])]
        out["rcode"][lo:hi] = 0
        out["frame_len"][lo:hi] = (80 + 1.2 * uniq_len[codes]
                                   + rng.integers(0, 12, m)).astype(np.int32)
        out["hour"][lo:hi] = np.clip(
            rng.normal(peak_of[prof], hsd_of[prof]), 0, 23.99)

    a = slice(n_bg, n_events)
    a_names = _dga_names(rng, n_anomalies)
    a_len = np.fromiter((len(s) for s in a_names), np.int64, n_anomalies)
    out["client_u32"][a] = host_base + rng.integers(
        0, n_hosts, n_anomalies).astype(np.uint32)
    out["qname_codes"][a] = len(names) + np.arange(n_anomalies)
    out["qtype"][a] = rng.choice([16, 10, 255], n_anomalies)  # TXT/NULL/ANY
    out["rcode"][a] = rng.choice([0, 3], n_anomalies)
    out["frame_len"][a] = (120 + 4 * a_len).astype(np.int32)
    out["hour"][a] = rng.uniform(0, 6, n_anomalies)
    out["qnames"] = np.concatenate(
        [np.asarray(names, dtype=object), a_names])
    out["anomaly_idx"] = np.arange(n_bg, n_events, dtype=np.int64)
    return out


# ---------------------------------------------------------------------------
# proxy
# ---------------------------------------------------------------------------

# (site pool, path pool, content type, method dist, peak_hour)
_PROXY_PROFILES = [
    (["www.google.com", "www.bing.com"],
     ["/search?q=news", "/search?q=weather", "/"], "text/html", 13.0),
    (["cdn.jsdelivr.net", "static.cloudflare.com"],
     ["/js/app.min.js", "/css/site.css", "/fonts/r.woff2"],
     "application/javascript", 12.0),
    (["update.microsoft.com", "dl.delivery.mp.microsoft.com"],
     ["/update/v11/cab", "/filestream/x"],
     "application/octet-stream", 4.0),
    (["www.youtube.com", "i.ytimg.com"],
     ["/watch?v=abc123", "/vi/xyz/hq.jpg"], "video/mp4", 19.0),
    (["mail.office365.com", "outlook.office.com"],
     ["/owa/", "/api/v2/messages"], "application/json", 10.0),
]

_AGENTS = np.array([
    "Mozilla/5.0 (Windows NT 10.0; Win64; x64)",
    "Mozilla/5.0 (Macintosh; Intel Mac OS X 10_15)",
    "Mozilla/5.0 (X11; Linux x86_64)"])


_JUNK_ALPHA = np.array(list("abcdefghijklmnopqrstuvwxyz0123456789%2F"))


def _proxy_campaigns(rng: np.random.Generator, n_anomalies: int):
    """Shared anomaly-campaign recipe for BOTH proxy generators (row
    and columnar): the campaign count scales with the anomaly count
    (one per ~8 anomalies, min 5) and each campaign draws its own
    URI-length range and hour window. A fixed handful of campaigns
    collapses 10³ anomalies onto ~tens of word keys whose counts let
    the sampler give the attack its own topic — the events then stop
    being low-probability (measured at 10⁸ rows: 396/1000 recovered
    with 5 fixed campaigns vs 840+/1000 heterogeneous)."""
    n_camps = max(5, n_anomalies // 8)
    camp = rng.integers(0, n_camps, n_anomalies)
    camp_lo = rng.integers(25, 260, n_camps)
    camp_hi = camp_lo + rng.integers(10, 140, n_camps)
    camp_hour = rng.uniform(0, 22.4, n_camps).astype(np.float32)
    return camp, camp_lo, camp_hi, camp_hour


def _junk_uris(rng: np.random.Generator, camp: np.ndarray,
               camp_lo: np.ndarray, camp_hi: np.ndarray) -> np.ndarray:
    return np.array(
        ["/" + "".join(rng.choice(_JUNK_ALPHA,
                                  rng.integers(camp_lo[c], camp_hi[c])))
         for c in camp], dtype=object)


def synth_proxy_day(n_events: int = 20000, n_hosts: int = 120,
                    n_anomalies: int = 30, date: str = DEMO_DATE,
                    seed: int = 0) -> tuple[pd.DataFrame, np.ndarray]:
    """One day of proxy logs (Bluecoat-style columns, SURVEY.md §2.1 #1).

    Anomalies: beaconing to raw-IP hosts, long high-entropy URIs, rare
    agents, octet-stream POSTs at night."""
    rng = np.random.default_rng(seed)
    hosts = _ips(n_hosts)
    n_prof = len(_PROXY_PROFILES)
    mix = _host_mixture(rng, n_hosts, n_prof)

    n_bg = n_events - n_anomalies
    h_idx = rng.integers(0, n_hosts, n_bg)
    u = rng.random(n_bg)
    prof = np.clip((mix[h_idx].cumsum(axis=1) < u[:, None]).sum(axis=1),
                   0, n_prof - 1)

    site, path, ctype, hour = [], [], [], []
    for p in prof:
        sites, paths, ct, mu = _PROXY_PROFILES[p]
        site.append(sites[rng.integers(0, len(sites))])
        path.append(paths[rng.integers(0, len(paths))])
        ctype.append(ct)
        hour.append(np.clip(rng.normal(mu, 2.5), 0, 23.99))
    site = np.array(site, dtype=object)
    path = np.array(path, dtype=object)
    ctype = np.array(ctype, dtype=object)
    hour = np.array(hour)
    method = rng.choice(np.array(["GET", "POST"]), n_bg, p=[.92, .08])
    respcode = rng.choice([200, 304, 404], n_bg, p=[.85, .1, .05])
    agent = _AGENTS[rng.integers(0, len(_AGENTS), n_bg)]
    csbytes = np.exp(rng.normal(6, 1, n_bg)).astype(np.int64)

    # Anomalies come from distinct small "campaigns" (different tools,
    # URI styles, hours) so they are heterogeneous in word space — a
    # single repeated signature would form its own topic and stop being
    # rare to the model (the same reason the reference needs DUPFACTOR
    # to deliberately un-rare analyst-cleared patterns). ONE recipe
    # shared with synth_proxy_day_arrays so the fidelity studies and
    # the 10⁸-row scale runs plant the same anomaly distribution.
    camp, camp_lo, camp_hi, camp_hour = _proxy_campaigns(rng, n_anomalies)
    a_paths = _junk_uris(rng, camp, camp_lo, camp_hi)
    a_sites = np.array([f"198.51.{rng.integers(0, 100)}.{rng.integers(1, 255)}"
                        for _ in range(n_anomalies)], dtype=object)
    a_hour = np.clip(camp_hour[camp] + rng.uniform(0, 1.5, n_anomalies),
                     0, 23.99)
    a_agents = np.array([f"tool{c}/{rng.integers(1, 9)}.{rng.integers(0, 9)}"
                         for c in camp], dtype=object)
    a_cs = np.exp(rng.normal(10, 1, n_anomalies)).astype(np.int64)

    def col(bg, an):
        return np.concatenate([bg, an])

    hours_all = col(hour, a_hour)
    table = pd.DataFrame({
        "p_date": np.full(n_events, date),
        "p_time": [t.split(" ")[1] for t in _times(date, hours_all)],
        "clientip": col(hosts[h_idx], hosts[rng.integers(0, n_hosts, n_anomalies)]),
        "host": col(site, a_sites),
        "reqmethod": col(method, np.full(n_anomalies, "POST", dtype=object)),
        "useragent": col(agent, a_agents),
        "resconttype": col(ctype, np.full(n_anomalies,
                                          "application/octet-stream",
                                          dtype=object)),
        "respcode": col(respcode, rng.choice([200, 503], n_anomalies)).astype(np.int32),
        "uripath": col(path, a_paths),
        "csbytes": col(csbytes, a_cs),
        "scbytes": np.exp(rng.normal(7, 1, n_events)).astype(np.int64),
    })
    return _shuffle(table, n_bg, n_events, rng)


def synth_proxy_day_arrays(n_events: int, n_hosts: int = 100_000,
                           n_anomalies: int | None = None, seed: int = 0,
                           chunk: int = 10_000_000) -> dict:
    """Columnar proxy day for the 10⁸-row configs[2] path:
    dictionary-encoded `uris`/`hosts`/`agents` unique tables with
    per-row codes, background-first/anomalies-last like the flow and
    DNS array generators."""
    if n_anomalies is None:
        n_anomalies = max(30, n_events // 10_000)
    n_anomalies = min(n_anomalies, n_events)
    rng = np.random.default_rng(seed)
    n_prof = len(_PROXY_PROFILES)
    mix_cum = _host_mixture(rng, n_hosts, n_prof).cumsum(axis=1).astype(np.float32)

    uris: list[str] = []
    hosts: list[str] = []
    uri_lo = np.zeros(n_prof + 1, np.int64)
    host_lo = np.zeros(n_prof + 1, np.int64)
    for p, (sites, paths, _ct, _mu) in enumerate(_PROXY_PROFILES):
        uris.extend(paths)
        hosts.extend(sites)
        uri_lo[p + 1] = len(uris)
        host_lo[p + 1] = len(hosts)
    peak_of = np.array([p[3] for p in _PROXY_PROFILES], np.float32)
    n_uris_of = np.diff(uri_lo)
    n_hosts_of = np.diff(host_lo)

    host_base = np.uint32(10 << 24)
    n_bg = n_events - n_anomalies
    out = {
        "client_u32": np.empty(n_events, np.uint32),
        "uri_codes": np.empty(n_events, np.int64),
        "host_codes": np.empty(n_events, np.int64),
        "ua_codes": np.empty(n_events, np.int64),
        "respcode": np.empty(n_events, np.int32),
        "hour": np.empty(n_events, np.float32),
    }
    for lo in range(0, n_bg, chunk):
        hi = min(lo + chunk, n_bg)
        m = hi - lo
        h_idx = rng.integers(0, n_hosts, m)
        u = rng.random(m, np.float32)
        prof = np.clip((mix_cum[h_idx] < u[:, None]).sum(axis=1),
                       0, n_prof - 1)
        out["client_u32"][lo:hi] = host_base + h_idx.astype(np.uint32)
        out["uri_codes"][lo:hi] = uri_lo[prof] + rng.integers(0, n_uris_of[prof])
        out["host_codes"][lo:hi] = host_lo[prof] + rng.integers(0, n_hosts_of[prof])
        out["ua_codes"][lo:hi] = rng.integers(0, len(_AGENTS), m)
        out["respcode"][lo:hi] = rng.choice(
            np.array([200, 304, 404], np.int32), m, p=[.85, .1, .05])
        out["hour"][lo:hi] = np.clip(rng.normal(peak_of[prof], 2.5), 0, 23.99)

    # Anomaly campaigns: beaconing to raw-IP hosts with junk URIs and
    # rare per-campaign agents — the _proxy_campaigns recipe shared
    # with synth_proxy_day (heterogeneity rationale in its docstring).
    camp, camp_lo, camp_hi, camp_hour = _proxy_campaigns(rng, n_anomalies)
    a_uris = _junk_uris(rng, camp, camp_lo, camp_hi)
    a_hosts = np.array(
        [f"198.51.{rng.integers(0, 100)}.{rng.integers(1, 255)}"
         for _ in range(n_anomalies)], dtype=object)
    a_agents_u, a_ua_codes = np.unique(np.array(
        [f"tool{c}/{rng.integers(1, 9)}.{rng.integers(0, 9)}"
         for c in camp], dtype=object), return_inverse=True)

    a = slice(n_bg, n_events)
    out["client_u32"][a] = host_base + rng.integers(
        0, n_hosts, n_anomalies).astype(np.uint32)
    out["uri_codes"][a] = len(uris) + np.arange(n_anomalies)
    out["host_codes"][a] = len(hosts) + np.arange(n_anomalies)
    out["ua_codes"][a] = len(_AGENTS) + a_ua_codes
    out["respcode"][a] = rng.choice(np.array([200, 503], np.int32),
                                    n_anomalies)
    out["hour"][a] = np.clip(camp_hour[camp]
                             + rng.uniform(0, 1.5, n_anomalies), 0, 23.99)
    out["uris"] = np.concatenate([np.asarray(uris, dtype=object), a_uris])
    out["hosts"] = np.concatenate([np.asarray(hosts, dtype=object), a_hosts])
    out["agents"] = np.concatenate(
        [np.asarray(list(_AGENTS), dtype=object), a_agents_u])
    out["anomaly_idx"] = np.arange(n_bg, n_events, dtype=np.int64)
    return out


SYNTH = {"flow": synth_flow_day, "dns": synth_dns_day, "proxy": synth_proxy_day}
SYNTH_ARRAYS = {"flow": synth_flow_day_arrays, "dns": synth_dns_day_arrays,
                "proxy": synth_proxy_day_arrays}
