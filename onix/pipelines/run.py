"""The scoring run: one day of one datatype, end to end.

The `ml_ops.sh <date> <type> [TOL] [MAXRESULTS]` equivalent
(SURVEY.md §3.1): read the day's partition from the store, create words,
build the corpus (applying analyst feedback ×DUPFACTOR), fit the LDA
engine (batched collapsed Gibbs or streaming SVI), score every raw
event, and emit the per-day results CSV for OA plus a run manifest
(config hash, seed, convergence series — SURVEY.md §5.5).
"""

from __future__ import annotations

import json
import pathlib
import time

import numpy as np
import pandas as pd

from onix.config import OnixConfig
from onix.models.scoring import score_all, select_suspicious
from onix.pipelines.corpus_build import CorpusBundle, build_corpus, event_scores
from onix.pipelines.words import WORD_FNS
from onix.store import Store, feedback_path, results_path
from onix.utils.obs import Meter, RunLog, maybe_trace, trace_scope


BENIGN_LABEL = 3   # the reference's severity scale: 1/2 = threat, 3 = benign


def load_feedback(cfg: OnixConfig, datatype: str, date: str) -> pd.DataFrame | None:
    """Most recent feedback CSV at or before `date` (the reference consumes
    the analyst labels on the NEXT ML run — SURVEY.md §3.3).

    Only rows the analyst marked BENIGN bias the model — duplicating a
    confirmed-threat row would teach the model to stop surfacing the
    attack pattern."""
    fdir = pathlib.Path(cfg.store.feedback_dir)
    if not fdir.exists():
        return None
    candidates = sorted(fdir.glob(f"{datatype}_scores_*.csv"))
    cutoff = feedback_path(fdir, datatype, date).name
    eligible = [p for p in candidates if p.name <= cutoff]
    if not eligible:
        return None
    fb = pd.read_csv(eligible[-1], dtype=str)
    if "label" in fb.columns:
        fb = fb[pd.to_numeric(fb["label"], errors="coerce") == BENIGN_LABEL]
    return fb


def fit_engine(cfg: OnixConfig, bundle: CorpusBundle, engine: str) -> dict:
    """Fit theta/phi_wk with the requested engine on the bundle's corpus."""
    if engine not in ("gibbs", "sharded") and cfg.lda.n_chains > 1:
        raise ValueError(
            f"lda.n_chains={cfg.lda.n_chains} is only implemented for the "
            f"'gibbs' and 'sharded' engines; the {engine!r} engine would "
            "silently run one chain")
    corpus = bundle.corpus
    # Resume-on-preemption (SURVEY.md §5.3-5.4): per-(datatype, date)
    # checkpoint dir, active when the config asks for it.
    ck_dir = None
    if cfg.lda.checkpoint_every > 0:
        ck_dir = (pathlib.Path(cfg.store.checkpoint_dir)
                  / cfg.pipeline.datatype / cfg.pipeline.date.replace("-", ""))
    if engine == "gibbs":
        from onix.models.lda_gibbs import GibbsLDA
        model = GibbsLDA(cfg.lda, corpus.n_docs, corpus.n_vocab)
        fit = model.fit(corpus, checkpoint_dir=ck_dir)
        return {"theta": fit["theta"], "phi_wk": fit["phi_wk"],
                "ll_history": fit["ll_history"]}
    if engine == "sharded":
        from onix.parallel.mesh import make_mesh, multihost_init
        from onix.parallel.sharded_gibbs import ShardedGibbsLDA
        # Multi-host first (SURVEY.md §2.3): on a pod every host runs
        # this same CLI and the runtime wires them into one job; the
        # mesh below then spans the GLOBAL device set. Explicit
        # coordinator config (CPU/GPU clusters) feeds straight through.
        multihost_init(
            coordinator=cfg.mesh.coordinator or None,
            num_processes=cfg.mesh.num_processes or None,
            process_id=(cfg.mesh.process_id
                        if cfg.mesh.process_id >= 0 else None))
        mesh = make_mesh(dp=cfg.mesh.dp, mp=cfg.mesh.mp)
        model = ShardedGibbsLDA(cfg.lda, corpus.n_vocab, mesh=mesh)
        fit = model.fit(corpus, checkpoint_dir=ck_dir)
        return {"theta": np.asarray(fit["theta"]),
                "phi_wk": np.asarray(fit["phi_wk"]),
                "ll_history": fit.get("ll_history", [])}
    if engine == "svi":
        from onix.models.lda_svi import SVILda, make_minibatch, phi_estimate
        model = SVILda(cfg.lda, corpus.n_vocab, corpus.n_docs)
        state = model.init()
        rng = np.random.default_rng(cfg.lda.seed)
        # DOCUMENT minibatches (svi_batch_size is documents per batch —
        # the config contract): group tokens by doc, batch whole docs.
        order = np.argsort(corpus.doc_ids, kind="stable")
        d_sorted = corpus.doc_ids[order]
        w_sorted = corpus.word_ids[order]
        bounds = np.searchsorted(d_sorted, np.arange(corpus.n_docs + 1))
        bs_docs = min(cfg.lda.svi_batch_size, corpus.n_docs)
        doc_perm = rng.permutation(corpus.n_docs)
        doc_batches = [doc_perm[i:i + bs_docs]
                       for i in range(0, corpus.n_docs, bs_docs)]
        tok_sel = [np.concatenate([np.arange(bounds[d], bounds[d + 1])
                                   for d in b]) for b in doc_batches]
        # One static token shape across batches -> one compiled svi_step.
        pad_to = max(int(s.size) for s in tok_sel)
        gamma_by_doc = np.full((corpus.n_docs, cfg.lda.n_topics),
                               cfg.lda.alpha, np.float32)
        # Epochs run until the predictive mean log-likelihood stops
        # improving (relative gain < svi_epoch_tol), capped at
        # svi_max_epochs — the convergence criterion lda-c applies to its
        # EM loop (SURVEY.md §2.1 #10 "iterate to convergence"), which
        # the first design replaced with a magic sweep-count fraction.
        ll_history: list[tuple[int, float]] = []
        prev_ll = -np.inf
        # SVI is stochastic: an epoch can regress the full-corpus ll.
        # Keep the best-ll parameters so a regressed final epoch is
        # never what gets returned.
        best = None
        for epoch in range(cfg.lda.svi_max_epochs):
            for sel in tok_sel:
                if sel.size == 0:
                    continue
                batch = make_minibatch(d_sorted[sel], w_sorted[sel],
                                       pad_to=pad_to, pad_docs=bs_docs)
                state, gamma = model.update(state, batch)
                gm = np.asarray(gamma)
                dm = np.asarray(batch.doc_map)
                real = dm >= 0
                gamma_by_doc[dm[real]] = gm[real]
            theta = gamma_by_doc / gamma_by_doc.sum(1, keepdims=True)
            phi_wk = np.asarray(phi_estimate(state))
            tok_p = score_all(theta, phi_wk, corpus.doc_ids, corpus.word_ids)
            ll = float(np.log(np.maximum(tok_p, 1e-30)).mean())
            ll_history.append((epoch, ll))
            if best is None or ll > best[0]:
                best = (ll, theta, phi_wk)
            if ll - prev_ll < cfg.lda.svi_epoch_tol * abs(prev_ll):
                break
            prev_ll = ll
        _, theta, phi_wk = best
        return {"theta": theta, "phi_wk": phi_wk,
                "ll_history": ll_history}
    raise ValueError(f"unknown engine {engine!r}")


def run_scoring(cfg: OnixConfig, engine: str = "gibbs",
                table: pd.DataFrame | None = None) -> int:
    """Execute one scoring run; returns a process exit code.

    `table` lets tests/embedding callers inject the day's events directly;
    otherwise the store partition for (datatype, date) is read.
    """
    t0 = time.time()
    datatype = cfg.pipeline.datatype
    date = cfg.pipeline.date
    store = Store(cfg.store.root)

    out_csv = results_path(cfg.store.results_dir, datatype, date)
    log = RunLog(out_csv.with_suffix(".runlog.jsonl"))
    log.emit("run_start", datatype=datatype, date=date, engine=engine,
             config_hash=cfg.config_hash)

    with log.stage("read"):
        cols = None
        if table is None:
            # Columnar day read (the 10^8+-row path, columnar.py): the
            # day never materializes as one pandas frame — numeric
            # columns + tiny unique-string tables per part, merged.
            from onix.pipelines import columnar
            mode = cfg.pipeline.columnar
            if mode == "on" or (mode == "auto"
                                and columnar.day_row_count(
                                    store, datatype, date)
                                >= columnar.COLUMNAR_AUTO_MIN_ROWS):
                try:
                    cols = columnar.read_day_cols(store, datatype, date)
                    n_events = len(cols["hour"])
                except ValueError as e:
                    # A malformed/unconvertible column (IPv6 days ride
                    # the tagged-u64 dictionary since r04 and no longer
                    # land here). auto falls back to the reference
                    # path (and says so); an explicit "on" propagates.
                    if mode == "on":
                        raise
                    log.emit("columnar_fallback", reason=str(e)[:200])
            if cols is None and table is None:
                table = store.read(datatype, date)
        if table is not None:
            n_events = len(table)
        log.emit("read_mode", columnar=cols is not None)

    with log.stage("word_creation", n_events=n_events):
        # Same words either way: the *_from_arrays paths are bit-exact
        # vs the string paths (tests/test_words.py equivalence suite).
        if cols is not None:
            from onix.pipelines.columnar import words_from_cols
            words = words_from_cols(datatype, cols)
        else:
            words = WORD_FNS[datatype](table)
    with log.stage("corpus_build"):
        feedback = load_feedback(cfg, datatype, date)
        bundle = build_corpus(words, feedback, cfg.pipeline.dupfactor)

    with maybe_trace(), log.stage(
            "lda_fit", n_tokens=int(bundle.corpus.n_tokens)), \
            trace_scope(f"onix.fit.{engine}"):
        fit = fit_engine(cfg, bundle, engine)
    for s, ll in fit["ll_history"]:
        log.emit("likelihood", sweep=int(s), ll=float(ll))

    # Serving handoff (r12 model bank): persist the fitted tables under
    # serving.models_dir keyed store.model_name(datatype, date), so
    # `onix serve`'s /score endpoint can bank this day's model
    # alongside every other tenant's (digest-stamped npz,
    # checkpoint.save_model).
    model_saved = None
    if cfg.serving.save_fitted:
        from onix.checkpoint import model_meta_epoch, save_model
        from onix.store import model_name
        name = model_name(datatype, date)
        # A RE-fit bumps past the stored epoch (which an online nudge
        # may have raised): the serving winner cache keys on it, and a
        # re-save that reset the epoch to 0 would let a bank that
        # reloads this file keep serving pre-refit cached winners.
        prev = model_meta_epoch(cfg.serving.models_dir, name)
        model_saved = str(save_model(
            cfg.serving.models_dir, name,
            fit["theta"], fit["phi_wk"],
            meta={"engine": engine, "config_hash": cfg.config_hash},
            epoch=0 if prev is None else prev + 1))
        log.emit("model_saved", path=model_saved)

    # Score REAL tokens only (feedback duplicates are training-only).
    meter = Meter()
    with log.stage("scoring"), trace_scope("onix.score"):
        tok_scores = score_all(
            fit["theta"], fit["phi_wk"],
            bundle.corpus.doc_ids[:bundle.n_real_tokens],
            bundle.corpus.word_ids[:bundle.n_real_tokens])
        ev_scores = event_scores(bundle, tok_scores, n_events)

        # Filter < TOL, ascending, top MAXRESULTS (SURVEY.md §3.1
        # POST-LDA). Event scores are already host-side here, so select
        # with argpartition: the fused device scan (scoring.bottom_k /
        # top_suspicious — the 1B-event benchmark path) pays a ~25s
        # cold compile through the device tunnel for zero benefit when
        # the array is already on the host.
        top = select_suspicious(ev_scores, cfg.pipeline.tol,
                                cfg.pipeline.max_results)
        meter.add(n_events)
    # Snapshot now: the judged events/sec must not absorb the result-
    # frame assembly and CSV write below.
    scoring_seconds = meter.seconds
    events_per_sec = meter.items / scoring_seconds if scoring_seconds else 0.0

    if table is not None:
        results = table.iloc[top].copy().reset_index(drop=True)
    else:
        # Columnar read: fetch just the winners' raw rows from the
        # store parts (caller order = `top` order).
        from onix.pipelines.columnar import rows_at
        results = rows_at(store, datatype, date, top)
    results.insert(0, "score", ev_scores[top])
    results.insert(1, "event_idx", top)
    # Word/doc provenance: attribute each selected event to the token that
    # ACHIEVED its min score (for flow that may be the dst-IP doc — the
    # analyst must label the endpoint that actually drove the detection,
    # or the feedback loop can never suppress it).
    achieving = np.flatnonzero(
        tok_scores <= ev_scores[bundle.token_event])
    min_tok = np.full(n_events, -1, np.int64)
    # Reversed fancy assignment: last write wins, so each event keeps its
    # FIRST achieving token.
    min_tok[bundle.token_event[achieving][::-1]] = achieving[::-1]
    results.insert(2, "ip", bundle.doc_keys[
        bundle.corpus.doc_ids[min_tok[top]]])
    results.insert(3, "word", bundle.vocab.words[
        bundle.corpus.word_ids[min_tok[top]]])

    out_csv.parent.mkdir(parents=True, exist_ok=True)
    results.to_csv(out_csv, index=False)

    # Campaign complement (round 5): per-event word rarity fades on
    # sustained homogeneous campaigns (the repeated word stops being
    # rare once its count grows); DOCUMENT topic rarity is the signal
    # that survives (scoring.doc_rarity). Top clients ship beside the
    # event results for the OA layer.
    from onix.pipelines.corpus_build import select_suspicious_docs
    tok_counts = np.bincount(
        bundle.corpus.doc_ids[:bundle.n_real_tokens],
        minlength=bundle.corpus.n_docs)
    doc_idx, doc_scores = select_suspicious_docs(
        bundle, fit["theta"], max_results=100, weights=tok_counts)
    clients = pd.DataFrame({
        "rank": np.arange(1, len(doc_idx) + 1),
        "client": bundle.doc_keys[doc_idx],
        "topic_rarity": doc_scores,
        "n_tokens": tok_counts[doc_idx],
    })
    clients_csv = out_csv.with_name(out_csv.stem + "_clients.csv")
    clients.to_csv(clients_csv, index=False)

    # Run manifest (SURVEY.md §5.5: config hash, data partition, seed;
    # §5.1: the judged events-scored/sec is a first-class number).
    from onix.models.lda_gibbs import SUPERSTEP_DEFAULT
    manifest = {
        "datatype": datatype, "date": date, "engine": engine,
        "config_hash": cfg.config_hash,
        "seed": cfg.lda.seed,
        # Fit-loop structure (r7): Gibbs engines chain sweeps S at a
        # time in one fused program; ll_history entries land at those
        # superstep boundaries (plus the pre-sweep point). SVI ignores
        # it.
        "lda_superstep": (cfg.lda.superstep or SUPERSTEP_DEFAULT
                          if engine in ("gibbs", "sharded") else None),
        "n_events": int(n_events),
        "n_docs": int(bundle.corpus.n_docs),
        "n_vocab": int(bundle.corpus.n_vocab),
        "n_tokens": int(bundle.corpus.n_tokens),
        "n_feedback_tokens": int(bundle.corpus.n_tokens - bundle.n_real_tokens),
        "n_results": int(len(results)),
        "n_client_results": int(len(clients)),
        "wall_seconds": round(time.time() - t0, 3),
        "scoring_seconds": round(scoring_seconds, 4),
        "events_per_sec": round(events_per_sec, 1),
        "ll_history": fit["ll_history"],
        "bin_edges": {k: (v if isinstance(v, list) else np.asarray(v).tolist())
                      for k, v in words.edges.items()},
    }
    if model_saved is not None:
        manifest["model_saved"] = model_saved
    # Resilience events tallied during this run (salvage skips, injected
    # faults, checkpoint digest mismatches) — absent on a clean run.
    from onix.utils.obs import counters as _counters
    resil = {**_counters.snapshot("salvage"), **_counters.snapshot("faults"),
             **_counters.snapshot("ckpt")}
    if resil:
        manifest["resilience"] = resil
    out_csv.with_suffix(".manifest.json").write_text(
        json.dumps(manifest, indent=2))
    cfg.archive(out_csv.with_suffix(".config.json"))
    log.emit("run_end", n_results=int(len(results)),
             wall_s=manifest["wall_seconds"],
             events_per_sec=manifest["events_per_sec"])
    return 0
