"""On-device word creation + id mapping — the DEFAULT hot path.

The 1B-event artifact's dominant pipeline stage was host-side word
creation + trained-id mapping (`stream_words_map`, 48% of the round-3
pipeline wall) — and this host exposes ONE CPU core, so the numpy path
cannot be parallelized sideways. The TPU-first answer is to move the
transform onto the chip: raw numeric telemetry columns stream to the
device (~25 B/event) and ONE fused program does binning → word packing
→ vocab/doc lookup → θ·φᵀ gather → pair-min → running bottom-k, so only
the winners ever come back. This renders SURVEY.md §2.1 #5's word
creation (reference FlowWordCreation, a Spark executor map) as device
compute on the VPU instead of a host preprocessing stage.

As of round 6 this path is the DEFAULT for all three datatypes in both
the scale runner's streaming stage and the SVI streaming scorer
(`ONIX_HOST_WORDS=1` — or the legacy `ONIX_DEVICE_WORDS=0` — pins the
host reference builders, kept as the cross-check arm the parity tests
compare winners against). Two supporting pieces live here too:

* **Double-buffered chunk staging** (`stage_*_cols` / STAGE_FNS):
  `jax.device_put` returns with the H2D copy in flight, so the scale
  runner stages chunk i+1's columns while chunk i's fused scan occupies
  the compute units — transfer overlaps compute instead of serializing
  with it.
* **Hashed-vocabulary streaming buckets** (`*_stream_buckets`): the SVI
  stream has no trained vocabulary, so the fused program ends in
  splitmix64 bucketing (32-bit-limb arithmetic, bit-identical to the
  host hash) instead of a vocab lookup.

Why a compact key: the host path packs words into 43-bit int64 keys
(words.FLOW_SPEC). JAX runs x64-disabled, so the device path re-encodes
the TRAINED vocabulary once on the host into an equivalent <=31-bit
int32 key (pclass 17 | proto 3 | hbin 3 | bbin 3 | pbin 3) and the
device packs events the same way — the event→vocab-id mapping is
identical; only the key representation differs.

Fidelity: binning compares f32 values against f32-cast edges while the
host compares f64; a value within half an f32 ulp of a quantile edge
can land one bin over (expected ~1e-7/event; tests assert agreement on
synthetic days). The stream scorer's contract is the suspicious tail,
not bit-stable word strings, and the planted-detection metric is
unaffected.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from onix.models import scoring
from onix.pipelines.words import (FLOW_SPEC, _PCLASS_HH, _PROTO_UNK,
                                  N_BINS_DEFAULT)

def host_words_forced() -> bool:
    """True when the env pins the HOST word builders. Device-resident
    word creation is the default hot path in the scale and streaming
    pipelines; `ONIX_HOST_WORDS=1` (or the legacy spelling
    `ONIX_DEVICE_WORDS=0`) selects the host reference implementation —
    kept as the cross-check arm the device-vs-host parity tests and
    artifacts compare against."""
    import os

    return (os.environ.get("ONIX_HOST_WORDS") == "1"
            or os.environ.get("ONIX_DEVICE_WORDS") == "0")


# Compact-key layout (int32), LSB-first: pbin | bbin | hbin | proto |
# pclass. Shifts must match between build() (host) and _pack() (device).
_BIN_BITS = 3
_PROTO_BITS = 3
_PROTO_SHIFT = 3 * _BIN_BITS
_PCLASS_SHIFT = _PROTO_SHIFT + _PROTO_BITS
_COMPACT_UNK = (1 << _PROTO_BITS) - 1     # _PROTO_UNK re-encoded


class FlowDeviceTables(NamedTuple):
    """Trained lookup state, re-encoded for on-device mapping.

    A NamedTuple so the whole bundle is a pytree — it rides into the
    jitted scan as one argument and stays device-resident across
    chunks.
    """

    word_key_c: jax.Array     # int32 [V] compact keys, ascending
    word_ids: jax.Array       # int32 [V] compact key -> trained vocab id
    doc_u32: jax.Array        # uint32 [D] trained doc IPs, ascending
    doc_ids: jax.Array        # int32 [D]
    hour_edges: jax.Array     # f32 [n_bins-1]
    byt_edges: jax.Array      # f32 [n_bins-1] (log1p space)
    pkt_edges: jax.Array      # f32 [n_bins-1]
    proto_remap: jax.Array    # int32 [n_proto_classes] caller id -> compact


def build_flow_tables(bundle, edges: dict,
                      proto_classes: list[str]) -> FlowDeviceTables:
    """Re-encode the trained bundle once per run (host side, O(V+D)).

    `edges` are the FITTED bin edges/proto table archived by the
    training corpus build; `proto_classes` is the caller's proto id
    order for the streamed columns (synth/ingest contract)."""
    fields = FLOW_SPEC.unpack(np.asarray(bundle.word_key_sorted))
    for name in ("pbin", "bbin", "hbin"):
        if fields[name].max(initial=0) >= (1 << _BIN_BITS):
            raise ValueError(
                "n_bins too large for the compact key; raise _BIN_BITS")
    table = np.asarray(edges["proto_classes"], dtype=object)
    if len(table) >= _COMPACT_UNK:
        raise ValueError("too many protocol classes for the compact key")
    proto = np.where(fields["proto"] == _PROTO_UNK, _COMPACT_UNK,
                     np.minimum(fields["proto"], _COMPACT_UNK))
    key_c = (fields["pclass"] << _PCLASS_SHIFT
             | proto << _PROTO_SHIFT
             | fields["hbin"] << (2 * _BIN_BITS)
             | fields["bbin"] << _BIN_BITS
             | fields["pbin"]).astype(np.int64)
    assert key_c.max(initial=0) < 2 ** 31, "compact key overflows int32"
    order = np.argsort(key_c, kind="stable")
    # Caller proto id -> compact code (the shared remap rule: absent
    # from the fitted table -> UNK).
    from onix.pipelines.words import proto_remap_codes
    remap = proto_remap_codes(table, proto_classes,
                              _COMPACT_UNK).astype(np.int32)
    return FlowDeviceTables(
        word_key_c=jnp.asarray(key_c[order].astype(np.int32)),
        word_ids=jnp.asarray(
            np.asarray(bundle.word_key_ids)[order].astype(np.int32)),
        doc_u32=jnp.asarray(np.asarray(bundle.doc_u32_sorted)),
        doc_ids=jnp.asarray(np.asarray(bundle.doc_u32_ids).astype(np.int32)),
        hour_edges=_edges1d(edges, "hour"),
        byt_edges=_edges1d(edges, "log_ibyt"),
        pkt_edges=_edges1d(edges, "log_ipkt"),
        proto_remap=jnp.asarray(remap),
    )


def _edges1d(edges: dict, name: str) -> "jnp.ndarray":
    """Fitted edge array as f32 [n_edges] for device searchsorted.

    Sized from the FITTED edges, not N_BINS_DEFAULT: magnitude features
    carry two extra tail-resolution cut points (words._bins tail=True),
    so edge counts differ per feature and the old fixed reshape(nb)
    crashed the flow path / silently disabled the dns path."""
    e = np.asarray(edges[name], np.float32).ravel()
    if e.size and np.any(np.diff(e) < 0):
        raise ValueError(f"fitted edges for {name!r} are not sorted")
    return jnp.asarray(e)


def _lookup_sorted(table: jax.Array, ids: jax.Array, keys: jax.Array,
                   fill: int) -> jax.Array:
    """ids[searchsorted(table, keys)] where the hit is exact, else fill
    — the device rendering of CorpusBundle's sorted-table lookups."""
    pos = jnp.searchsorted(table, keys)
    pos_c = jnp.clip(pos, 0, table.shape[0] - 1)
    hit = table[pos_c] == keys
    return jnp.where(hit, ids[pos_c], jnp.int32(fill))


def _flow_flat_idx(t: FlowDeviceTables, v_x: int, unseen_w: int,
                   unseen_d: int, sip, dip, sport, dport, proto, hour,
                   byt, pkt):
    """Per-chunk device transform: raw columns -> (idx_src, idx_dst)
    flat score-table indices. Mirrors flow_words_from_arrays +
    word_ids_packed/doc_ids_u32 field for field."""
    sport = sport.astype(jnp.int32)
    dport = dport.astype(jnp.int32)
    s_low = sport <= 1024
    d_low = dport <= 1024
    pclass = jnp.where(
        s_low & d_low, jnp.minimum(sport, dport),
        jnp.where(s_low, sport,
                  jnp.where(d_low, dport, jnp.int32(_PCLASS_HH))))
    hbin = jnp.searchsorted(t.hour_edges, hour, side="right")
    bbin = jnp.searchsorted(t.byt_edges, jnp.log1p(byt), side="right")
    pbin = jnp.searchsorted(t.pkt_edges, jnp.log1p(pkt), side="right")
    key = (pclass << _PCLASS_SHIFT
           | t.proto_remap[proto.astype(jnp.int32)] << _PROTO_SHIFT
           | hbin.astype(jnp.int32) << (2 * _BIN_BITS)
           | bbin.astype(jnp.int32) << _BIN_BITS
           | pbin.astype(jnp.int32))
    wid = _lookup_sorted(t.word_key_c, t.word_ids, key, unseen_w)
    did_s = _lookup_sorted(t.doc_u32, t.doc_ids, sip, unseen_d)
    did_d = _lookup_sorted(t.doc_u32, t.doc_ids, dip, unseen_d)
    return did_s * jnp.int32(v_x) + wid, did_d * jnp.int32(v_x) + wid


@functools.partial(jax.jit, static_argnames=("v_x", "unseen_w", "unseen_d",
                                             "tol", "max_results", "chunk"))
def _flow_stream_scan(tables: FlowDeviceTables, table_flat: jax.Array,
                      sip, dip, sport, dport, proto, hour, byt, pkt, *,
                      v_x: int, unseen_w: int, unseen_d: int, tol: float,
                      max_results: int, chunk: int) -> scoring.TopK:
    def score_chunk(s_ip, d_ip, s_p, d_p, pr, hr, by, pk):
        idx_s, idx_d = _flow_flat_idx(tables, v_x, unseen_w, unseen_d,
                                      s_ip, d_ip, s_p, d_p, pr, hr, by, pk)
        s = jnp.minimum(table_flat[idx_s], table_flat[idx_d])
        return jnp.where(s < tol, s, jnp.inf)

    return scoring._scan_bottom_k(
        (sip, dip, sport, dport, proto, hour, byt, pkt), sip.shape[0],
        score_chunk, max_results=max_results, chunk=chunk,
        merge_buffer=128)


# ---------------------------------------------------------------------------
# DNS / proxy device paths.
#
# Same design as flow with one extra split: the string-derived features
# (subdomain entropy, URI length, user-agent class, ...) are computed
# per UNIQUE value on the host — thousands of strings, microseconds —
# and packed into per-unique PARTIAL compact keys; the device gathers
# the partials through the dictionary codes and packs in the per-event
# numeric fields. Compact layouts (LSB-first):
#   dns:   flbin 3 | hbin 3 | ebin 3 | slbin 3 | nlabels 3 | qtype 8 |
#          rcode 4 | tld 1                                   (28 bits)
#   proxy: cclass 3 | hbin 3 | uebin 3 | ulbin 3 | hostip 1 | ua 7
#                                                            (20 bits)
# build_*_tables validates that the TRAINED vocab fits these ranges
# (qtype < 256, rcode < 16, <126 common user agents, ...) and raises
# otherwise — the caller then stays on the host path. Streamed events
# outside the ranges get key -1 (matches no table entry), landing on
# the UNSEEN word row exactly as the host lookup would.
# ---------------------------------------------------------------------------

_DNS_HBIN_SHIFT = 3
_DNS_EBIN_SHIFT = 6
_DNS_SLBIN_SHIFT = 9
_DNS_NLABELS_SHIFT = 12
_DNS_QTYPE_SHIFT = 15
_DNS_RCODE_SHIFT = 23
_DNS_TLD_SHIFT = 27
_PROXY_HBIN_SHIFT = 3
_PROXY_UEBIN_SHIFT = 6
_PROXY_ULBIN_SHIFT = 9
_PROXY_HOSTIP_SHIFT = 12
_PROXY_UA_SHIFT = 13
_PROXY_UA_RARE_C = 126     # words._UA_RARE (1023) re-encoded to 7 bits


class DnsDeviceTables(NamedTuple):
    word_key_c: jax.Array     # int32 [V] compact keys, ascending
    word_ids: jax.Array       # int32 [V]
    doc_u32: jax.Array        # uint32 [D] trained client IPs, ascending
    doc_ids: jax.Array        # int32 [D]
    hour_edges: jax.Array     # f32 [n_bins-1]
    flen_edges: jax.Array     # f32 [n_bins-1]


def build_dns_tables(bundle, edges: dict) -> DnsDeviceTables:
    from onix.pipelines.words import DNS_SPEC

    fields = DNS_SPEC.unpack(np.asarray(bundle.word_key_sorted))
    if fields["qtype"].max(initial=0) >= 256:
        raise ValueError("trained qtype exceeds the compact key range")
    if fields["rcode"].max(initial=0) >= 16:
        raise ValueError("trained rcode exceeds the compact key range")
    for name in ("flbin", "hbin", "ebin", "slbin", "nlabels"):
        if fields[name].max(initial=0) >= 8:
            raise ValueError(f"trained {name} exceeds the compact key range")
    key_c = (fields["flbin"]
             | fields["hbin"] << _DNS_HBIN_SHIFT
             | fields["ebin"] << _DNS_EBIN_SHIFT
             | fields["slbin"] << _DNS_SLBIN_SHIFT
             | fields["nlabels"] << _DNS_NLABELS_SHIFT
             | fields["qtype"] << _DNS_QTYPE_SHIFT
             | fields["rcode"] << _DNS_RCODE_SHIFT
             | fields["tld"] << _DNS_TLD_SHIFT).astype(np.int64)
    order = np.argsort(key_c, kind="stable")
    return DnsDeviceTables(
        word_key_c=jnp.asarray(key_c[order].astype(np.int32)),
        word_ids=jnp.asarray(
            np.asarray(bundle.word_key_ids)[order].astype(np.int32)),
        doc_u32=jnp.asarray(np.asarray(bundle.doc_u32_sorted)),
        doc_ids=jnp.asarray(np.asarray(bundle.doc_u32_ids).astype(np.int32)),
        hour_edges=_edges1d(edges, "hour"),
        flen_edges=_edges1d(edges, "frame_len"),
    )


def _pad_pow2(a: np.ndarray) -> np.ndarray:
    """Pad a per-unique table to the next power of two so the jitted
    per-chunk scan sees a handful of distinct shapes, not one per
    chunk's unique count (each distinct shape is a recompile)."""
    n = max(1, int(a.shape[0]))
    size = 1 << (n - 1).bit_length()
    return np.pad(a, (0, size - a.shape[0]))


def _dns_unique_bins(qnames: np.ndarray, edges: dict) -> dict:
    """Per-UNIQUE qname word fields under the fitted edges — the ONE
    string-feature pipeline shared by the trained-vocab compact
    partials and the streaming full-spec partials (a drifted copy
    would silently break host/device word identity)."""
    from onix.utils.features import digitize, qname_features

    qf = qname_features(qnames)
    return {
        "slbin": digitize(qf["sub_len"], edges["sub_len"]).astype(np.int64),
        "ebin": digitize(qf["sub_entropy"].astype(np.float64),
                         edges["sub_entropy"]).astype(np.int64),
        "nlabels": qf["n_labels"],
        "tld": qf["tld_ok"],
    }


def dns_partial_keys(qnames: np.ndarray, edges: dict) -> np.ndarray:
    """Per-UNIQUE compact partials (ebin|slbin|nlabels|tld at their
    shifts) from the fitted edges — host side, O(uniques)."""
    b = _dns_unique_bins(qnames, edges)
    return (b["ebin"] << _DNS_EBIN_SHIFT
            | b["slbin"] << _DNS_SLBIN_SHIFT
            | b["nlabels"] << _DNS_NLABELS_SHIFT
            | b["tld"] << _DNS_TLD_SHIFT).astype(np.int32)


@functools.partial(jax.jit, static_argnames=("v_x", "unseen_w", "unseen_d",
                                             "tol", "max_results", "chunk"))
def _dns_stream_scan(tables: DnsDeviceTables, table_flat: jax.Array,
                     partial_u: jax.Array, client, codes, qtype, rcode,
                     flen, hour, *, v_x: int, unseen_w: int, unseen_d: int,
                     tol: float, max_results: int,
                     chunk: int) -> scoring.TopK:
    def score_chunk(cl, co, qt, rc, fl, hr):
        flbin = jnp.searchsorted(tables.flen_edges, fl, side="right")
        hbin = jnp.searchsorted(tables.hour_edges, hr, side="right")
        key = (partial_u[co]
               | flbin.astype(jnp.int32)
               | hbin.astype(jnp.int32) << _DNS_HBIN_SHIFT
               | qt << _DNS_QTYPE_SHIFT
               | rc << _DNS_RCODE_SHIFT)
        valid = ((qt >= 0) & (qt < 256) & (rc >= 0) & (rc < 16))
        key = jnp.where(valid, key, jnp.int32(-1))
        wid = _lookup_sorted(tables.word_key_c, tables.word_ids, key,
                             unseen_w)
        did = _lookup_sorted(tables.doc_u32, tables.doc_ids, cl, unseen_d)
        s = table_flat[did * jnp.int32(v_x) + wid]
        return jnp.where(s < tol, s, jnp.inf)

    return scoring._scan_bottom_k(
        (client, codes, qtype, rcode, flen, hour), client.shape[0],
        score_chunk, max_results=max_results, chunk=chunk,
        merge_buffer=128)


# ---------------------------------------------------------------------------
# Chunk staging (double-buffered ingestion).
#
# `jax.device_put` returns immediately with the H2D copy in flight, so a
# scale runner can stage chunk i+1's columns WHILE chunk i's fused scan
# occupies the compute units — the transfer overlaps compute instead of
# serializing with it (scale.py's double-buffered stream loop). Each
# stage_* helper does the per-chunk HOST work too (dtype casts; for
# dns/proxy the per-UNIQUE string partials), so once a staged dict
# exists the stream_bottom_k call is pure device dispatch. Staged dicts
# are marked with "_staged" and pass through the stream_bottom_k entry
# points untouched; raw numpy column dicts still work (staged on the
# spot) so existing callers and tests see one API.
# ---------------------------------------------------------------------------


def _put(a) -> jax.Array:
    return jax.device_put(a)


def stage_flow_cols(cols: dict) -> dict:
    """Cast + async-transfer one flow chunk's raw columns (~25 B/event)."""
    return {
        "_staged": True,
        "sip_u32": _put(np.asarray(cols["sip_u32"], np.uint32)),
        "dip_u32": _put(np.asarray(cols["dip_u32"], np.uint32)),
        "sport": _put(np.asarray(cols["sport"], np.int32)),
        "dport": _put(np.asarray(cols["dport"], np.int32)),
        "proto_id": _put(np.asarray(cols["proto_id"], np.int32)),
        "hour": _put(np.asarray(cols["hour"], np.float32)),
        "ibyt": _put(np.asarray(cols["ibyt"], np.float32)),
        "ipkt": _put(np.asarray(cols["ipkt"], np.float32)),
        "proto_classes": list(cols["proto_classes"]),
    }


def stage_dns_cols(cols: dict, edges: dict) -> dict:
    """Host string features per UNIQUE qname, then async-transfer."""
    return {
        "_staged": True,
        "partial_u": _put(_pad_pow2(dns_partial_keys(cols["qnames"],
                                                     edges))),
        "client_u32": _put(np.asarray(cols["client_u32"], np.uint32)),
        "qname_codes": _put(np.asarray(cols["qname_codes"], np.int32)),
        "qtype": _put(np.asarray(cols["qtype"], np.int32)),
        "rcode": _put(np.asarray(cols["rcode"], np.int32)),
        "frame_len": _put(np.asarray(cols["frame_len"], np.float32)),
        "hour": _put(np.asarray(cols["hour"], np.float32)),
    }


def stage_proxy_cols(cols: dict, edges: dict) -> dict:
    """Host string features per UNIQUE uri/host/agent, then transfer."""
    uri_p, host_p, ua_p = proxy_partial_keys(
        cols["uris"], cols["hosts"], cols["agents"], edges)
    return {
        "_staged": True,
        "uri_p": _put(_pad_pow2(uri_p)),
        "host_p": _put(_pad_pow2(host_p)),
        "ua_p": _put(_pad_pow2(ua_p)),
        "client_u32": _put(np.asarray(cols["client_u32"], np.uint32)),
        "uri_codes": _put(np.asarray(cols["uri_codes"], np.int32)),
        "host_codes": _put(np.asarray(cols["host_codes"], np.int32)),
        "ua_codes": _put(np.asarray(cols["ua_codes"], np.int32)),
        "respcode": _put(np.asarray(cols["respcode"], np.int32)),
        "hour": _put(np.asarray(cols["hour"], np.float32)),
    }


STAGE_FNS = {"flow": lambda cols, edges: stage_flow_cols(cols),
             "dns": stage_dns_cols,
             "proxy": stage_proxy_cols}


def dns_stream_bottom_k(tables: DnsDeviceTables, table_flat: jax.Array,
                        cols: dict, edges: dict, *, v_x: int, unseen_w: int,
                        unseen_d: int, tol: float, max_results: int,
                        chunk: int = 1 << 21) -> scoring.TopK:
    """Fused words→map→score→select for one streamed DNS chunk: string
    features run per unique name on the host, everything per-event on
    the device. `cols` may be raw numpy columns or a stage_dns_cols
    dict (double-buffered callers stage the next chunk early)."""
    if not cols.get("_staged"):
        cols = stage_dns_cols(cols, edges)
    return _dns_stream_scan(
        tables, table_flat, cols["partial_u"], cols["client_u32"],
        cols["qname_codes"], cols["qtype"], cols["rcode"],
        cols["frame_len"], cols["hour"],
        v_x=v_x, unseen_w=unseen_w, unseen_d=unseen_d, tol=tol,
        max_results=max_results, chunk=chunk)


class ProxyDeviceTables(NamedTuple):
    word_key_c: jax.Array     # int32 [V] compact keys, ascending
    word_ids: jax.Array       # int32 [V]
    doc_u32: jax.Array        # uint32 [D]
    doc_ids: jax.Array        # int32 [D]
    hour_edges: jax.Array     # f32 [n_bins-1]


def build_proxy_tables(bundle, edges: dict) -> ProxyDeviceTables:
    from onix.pipelines.words import _UA_RARE, PROXY_SPEC

    fields = PROXY_SPEC.unpack(np.asarray(bundle.word_key_sorted))
    if len(edges.get("ua_common", ())) >= _PROXY_UA_RARE_C:
        raise ValueError("too many common user agents for the compact key")
    ua = fields["ua"]
    bad_ua = (ua >= len(edges.get("ua_common", ()))) & (ua != _UA_RARE)
    if bad_ua.any():
        raise ValueError("trained ua code outside the fitted common table")
    ua_c = np.where(ua == _UA_RARE, _PROXY_UA_RARE_C, ua)
    if fields["cclass"].max(initial=0) >= 8:
        raise ValueError("trained cclass exceeds the compact key range")
    for name in ("hbin", "uebin", "ulbin"):
        if fields[name].max(initial=0) >= 8:
            raise ValueError(f"trained {name} exceeds the compact key range")
    key_c = (fields["cclass"]
             | fields["hbin"] << _PROXY_HBIN_SHIFT
             | fields["uebin"] << _PROXY_UEBIN_SHIFT
             | fields["ulbin"] << _PROXY_ULBIN_SHIFT
             | fields["hostip"] << _PROXY_HOSTIP_SHIFT
             | ua_c << _PROXY_UA_SHIFT).astype(np.int64)
    order = np.argsort(key_c, kind="stable")
    return ProxyDeviceTables(
        word_key_c=jnp.asarray(key_c[order].astype(np.int32)),
        word_ids=jnp.asarray(
            np.asarray(bundle.word_key_ids)[order].astype(np.int32)),
        doc_u32=jnp.asarray(np.asarray(bundle.doc_u32_sorted)),
        doc_ids=jnp.asarray(np.asarray(bundle.doc_u32_ids).astype(np.int32)),
        hour_edges=_edges1d(edges, "hour"),
    )


def proxy_partial_keys(uris: np.ndarray, hosts: np.ndarray,
                       agents: np.ndarray, edges: dict) -> tuple:
    """Per-UNIQUE compact partials for the three dictionary columns —
    host side, O(uniques). Returns (uri_p, host_p, ua_p) int32."""
    from onix.pipelines.words import _IP_RE, _UA_RARE, _categorical
    from onix.utils.features import digitize, entropy_array

    uri_len = np.fromiter((len(str(u)) for u in uris), np.float64,
                          len(uris))
    ulbin = digitize(uri_len, edges["uri_len"]).astype(np.int64)
    uebin = digitize(entropy_array(uris).astype(np.float64),
                     edges["uri_entropy"]).astype(np.int64)
    uri_p = (uebin << _PROXY_UEBIN_SHIFT
             | ulbin << _PROXY_ULBIN_SHIFT).astype(np.int32)
    host_p = (np.fromiter((int(bool(_IP_RE.match(str(h)))) for h in hosts),
                          np.int64, len(hosts))
              << _PROXY_HOSTIP_SHIFT).astype(np.int32)
    ua = _categorical(np.asarray(agents, dtype=object), "ua_common", edges,
                      _UA_RARE)
    ua_c = np.where(ua == _UA_RARE, _PROXY_UA_RARE_C, ua)
    return uri_p, host_p, (ua_c << _PROXY_UA_SHIFT).astype(np.int32)


@functools.partial(jax.jit, static_argnames=("v_x", "unseen_w", "unseen_d",
                                             "tol", "max_results", "chunk"))
def _proxy_stream_scan(tables: ProxyDeviceTables, table_flat: jax.Array,
                       uri_p: jax.Array, host_p: jax.Array, ua_p: jax.Array,
                       client, uri_c, host_c, ua_c, respcode, hour, *,
                       v_x: int, unseen_w: int, unseen_d: int, tol: float,
                       max_results: int, chunk: int) -> scoring.TopK:
    def score_chunk(cl, uc, hc, ac, rc, hr):
        hbin = jnp.searchsorted(tables.hour_edges, hr, side="right")
        cclass = rc // 100
        key = (uri_p[uc] | host_p[hc] | ua_p[ac]
               | cclass
               | hbin.astype(jnp.int32) << _PROXY_HBIN_SHIFT)
        valid = (rc >= 0) & (cclass < 8)
        key = jnp.where(valid, key, jnp.int32(-1))
        wid = _lookup_sorted(tables.word_key_c, tables.word_ids, key,
                             unseen_w)
        did = _lookup_sorted(tables.doc_u32, tables.doc_ids, cl, unseen_d)
        s = table_flat[did * jnp.int32(v_x) + wid]
        return jnp.where(s < tol, s, jnp.inf)

    return scoring._scan_bottom_k(
        (client, uri_c, host_c, ua_c, respcode, hour), client.shape[0],
        score_chunk, max_results=max_results, chunk=chunk,
        merge_buffer=128)


def proxy_stream_bottom_k(tables: ProxyDeviceTables, table_flat: jax.Array,
                          cols: dict, edges: dict, *, v_x: int,
                          unseen_w: int, unseen_d: int, tol: float,
                          max_results: int,
                          chunk: int = 1 << 21) -> scoring.TopK:
    """Fused words→map→score→select for one streamed proxy chunk.
    `cols` may be raw numpy columns or a stage_proxy_cols dict."""
    if not cols.get("_staged"):
        cols = stage_proxy_cols(cols, edges)
    return _proxy_stream_scan(
        tables, table_flat, cols["uri_p"], cols["host_p"], cols["ua_p"],
        cols["client_u32"], cols["uri_codes"], cols["host_codes"],
        cols["ua_codes"], cols["respcode"], cols["hour"],
        v_x=v_x, unseen_w=unseen_w, unseen_d=unseen_d, tol=tol,
        max_results=max_results, chunk=chunk)


# ---------------------------------------------------------------------------
# Hashed-vocabulary streaming path (onix/pipelines/streaming.py).
#
# The SVI stream has no trained vocabulary to look keys up in — words
# hash into a fixed bucket space (streaming.py `_bucket_of_keys`:
# splitmix64 over the packed int64 `word_key`, mod n_buckets). The
# device rendering below computes the SAME buckets on-chip: binning →
# full-spec int64 key packing (as two uint32 limbs — x64 stays
# disabled) → splitmix64 in 32-bit limb arithmetic → low-bits mod for
# power-of-two bucket counts. Bucket identity is therefore preserved
# EXACTLY against the host path given identical bin indices; the one
# divergence source is the f32-vs-f64 bin-edge comparison documented in
# the module docstring (~1e-7/event). Per-UNIQUE string features
# (dns/proxy) stay host-side, pre-packed into int64 partial keys whose
# uint32 halves the device gathers through the dictionary codes.
# ---------------------------------------------------------------------------

_SM64_C1 = 0x9E3779B97F4A7C15
_SM64_C2 = 0xBF58476D1CE4E5B9
_SM64_C3 = 0x94D049BB133111EB


def _u32(x: int) -> "jnp.ndarray":
    return jnp.uint32(x & 0xFFFFFFFF)


def _shr64(hi, lo, s: int):
    """(hi, lo) >> s for static 0 < s < 32."""
    return hi >> s, (lo >> s) | (hi << (32 - s))


def _mul64(ah, al, b: int):
    """Low 64 bits of (ah, al) * constant b, in uint32 limbs (16-bit
    partial products for the 32x32→64 low half; upper cross terms wrap
    into hi, exactly like uint64 multiplication)."""
    bh, bl = _u32(b >> 32), _u32(b)
    a0 = al & _u32(0xFFFF)
    a1 = al >> 16
    b0 = bl & _u32(0xFFFF)
    b1 = bl >> 16
    p00 = a0 * b0
    p01 = a0 * b1
    p10 = a1 * b0
    mid = (p01 & _u32(0xFFFF)) + (p10 & _u32(0xFFFF)) + (p00 >> 16)
    lo = (p00 & _u32(0xFFFF)) | ((mid & _u32(0xFFFF)) << 16)
    hi = (a1 * b1 + (p01 >> 16) + (p10 >> 16) + (mid >> 16)
          + al * bh + ah * bl)
    return hi, lo


def _splitmix64_bucket(hi, lo, salt: int, n_buckets: int):
    """splitmix64(key ^ salt) % n_buckets on (hi, lo) uint32 limbs —
    bit-identical to streaming._bucket_of_keys for power-of-two
    n_buckets (the mod is the low bits of the finalized value)."""
    hi = hi ^ _u32(salt >> 32)
    lo = lo ^ _u32(salt)
    lo2 = lo + _u32(_SM64_C1)
    hi = hi + _u32(_SM64_C1 >> 32) + (lo2 < lo).astype(jnp.uint32)
    lo = lo2
    sh, sl = _shr64(hi, lo, 30)
    hi, lo = hi ^ sh, lo ^ sl
    hi, lo = _mul64(hi, lo, _SM64_C2)
    sh, sl = _shr64(hi, lo, 27)
    hi, lo = hi ^ sh, lo ^ sl
    hi, lo = _mul64(hi, lo, _SM64_C3)
    sh, sl = _shr64(hi, lo, 31)
    hi, lo = hi ^ sh, lo ^ sl
    return (lo & _u32(n_buckets - 1)).astype(jnp.int32)


def _pack64(spec, vals: dict):
    """Device twin of WordSpec.pack: field values → packed int64 key as
    (hi, lo) uint32 limbs. Shifts/masks are Python ints (static), so
    each field contributes one or two OR terms — no 64-bit ops."""
    hi = lo = None
    shift = 0
    for name, bits in spec.fields:
        v = vals[name].astype(jnp.uint32) & _u32((1 << bits) - 1)
        parts_lo = []
        parts_hi = []
        if shift < 32:
            parts_lo.append(v << shift if shift else v)
            if shift + bits > 32:
                parts_hi.append(v >> (32 - shift))
        else:
            parts_hi.append(v << (shift - 32) if shift > 32 else v)
        for p in parts_lo:
            lo = p if lo is None else lo | p
        for p in parts_hi:
            hi = p if hi is None else hi | p
        shift += bits
    zero = jnp.zeros_like(lo if lo is not None else hi)
    return (zero if hi is None else hi), (zero if lo is None else lo)


def _partial_halves(partial: np.ndarray):
    """Host int64 partial keys → (hi, lo) uint32 arrays, pow2-padded."""
    p = _pad_pow2(np.asarray(partial, np.int64))
    return (p >> 32).astype(np.uint32), (p & 0xFFFFFFFF).astype(np.uint32)


class FlowStreamTables(NamedTuple):
    hour_edges: jax.Array     # f32 — frozen fitted edges (f32 caveat)
    byt_edges: jax.Array
    pkt_edges: jax.Array
    proto_remap: jax.Array    # int32 [n_caller_protos] -> fitted id / UNK


def build_flow_stream_tables(edges: dict,
                             proto_classes: list[str]) -> FlowStreamTables:
    """Frozen-edge tables for the hashed streaming path. The proto
    remap keys on the CALLER's per-batch proto order (same rule as
    flow_words_from_arrays: absent from the fitted table -> UNK), so it
    is rebuilt per batch — O(#protos), trivially cheap."""
    from onix.pipelines.words import _PROTO_UNK, proto_remap_codes

    remap = proto_remap_codes(edges["proto_classes"], proto_classes,
                              _PROTO_UNK).astype(np.int32)
    return FlowStreamTables(
        hour_edges=_edges1d(edges, "hour"),
        byt_edges=_edges1d(edges, "log_ibyt"),
        pkt_edges=_edges1d(edges, "log_ipkt"),
        proto_remap=jnp.asarray(remap))


@functools.partial(jax.jit, static_argnames=("salt", "n_buckets"))
def flow_stream_buckets(t: FlowStreamTables, sport, dport, proto, hour,
                        byt, pkt, *, salt: int,
                        n_buckets: int) -> jax.Array:
    """Per-event word bucket ids [n] for one flow minibatch — binning,
    FLOW_SPEC packing, and splitmix64 bucketing in one program. Both
    tokens of a flow event (src-doc, dst-doc) carry the same word, so
    one bucket per event covers the [src|dst] token layout."""
    sport = sport.astype(jnp.int32)
    dport = dport.astype(jnp.int32)
    s_low = sport <= 1024
    d_low = dport <= 1024
    pclass = jnp.where(
        s_low & d_low, jnp.minimum(sport, dport),
        jnp.where(s_low, sport,
                  jnp.where(d_low, dport, jnp.int32(_PCLASS_HH))))
    hi, lo = _pack64(FLOW_SPEC, {
        "pbin": jnp.searchsorted(t.pkt_edges, jnp.log1p(pkt),
                                 side="right").astype(jnp.uint32),
        "bbin": jnp.searchsorted(t.byt_edges, jnp.log1p(byt),
                                 side="right").astype(jnp.uint32),
        "hbin": jnp.searchsorted(t.hour_edges, hour,
                                 side="right").astype(jnp.uint32),
        "pclass": pclass.astype(jnp.uint32),
        "proto": t.proto_remap[proto.astype(jnp.int32)].astype(jnp.uint32),
    })
    return _splitmix64_bucket(hi, lo, salt, n_buckets)


class DnsStreamTables(NamedTuple):
    hour_edges: jax.Array
    flen_edges: jax.Array
    partial_hi: jax.Array     # uint32 [U] per-unique-qname key partials
    partial_lo: jax.Array


def build_dns_stream_tables(edges: dict, qnames: np.ndarray) -> DnsStreamTables:
    """Frozen edges + per-UNIQUE qname partial keys (tld, nlabels,
    ebin, slbin at their DNS_SPEC shifts) — host string work is
    O(uniques), as in the trained-vocab dns path."""
    from onix.pipelines.words import DNS_SPEC

    b = _dns_unique_bins(qnames, edges)
    sh = DNS_SPEC.shifts()
    bits = dict(DNS_SPEC.fields)
    partial = np.zeros(len(qnames), np.int64)
    for name in ("tld", "nlabels", "ebin", "slbin"):
        # Same bit masking as WordSpec.pack, same shifts by definition.
        partial |= (b[name] & ((1 << bits[name]) - 1)) << sh[name]
    hi, lo = _partial_halves(partial)
    return DnsStreamTables(
        hour_edges=_edges1d(edges, "hour"),
        flen_edges=_edges1d(edges, "frame_len"),
        partial_hi=jnp.asarray(hi), partial_lo=jnp.asarray(lo))


@functools.partial(jax.jit, static_argnames=("salt", "n_buckets"))
def dns_stream_buckets(t: DnsStreamTables, codes, qtype, rcode, flen,
                       hour, *, salt: int, n_buckets: int) -> jax.Array:
    from onix.pipelines.words import DNS_SPEC

    hi, lo = _pack64(DNS_SPEC, {
        "tld": jnp.zeros_like(codes).astype(jnp.uint32),
        "rcode": rcode.astype(jnp.uint32),
        "qtype": qtype.astype(jnp.uint32),
        "nlabels": jnp.zeros_like(codes).astype(jnp.uint32),
        "ebin": jnp.zeros_like(codes).astype(jnp.uint32),
        "slbin": jnp.zeros_like(codes).astype(jnp.uint32),
        "hbin": jnp.searchsorted(t.hour_edges, hour,
                                 side="right").astype(jnp.uint32),
        "flbin": jnp.searchsorted(t.flen_edges, flen,
                                  side="right").astype(jnp.uint32),
    })
    c = codes.astype(jnp.int32)
    hi = hi | t.partial_hi[c]
    lo = lo | t.partial_lo[c]
    return _splitmix64_bucket(hi, lo, salt, n_buckets)


class ProxyStreamTables(NamedTuple):
    hour_edges: jax.Array
    uri_hi: jax.Array         # uint32 [Uu] per-unique-URI partials
    uri_lo: jax.Array
    host_hi: jax.Array        # uint32 [Uh]
    host_lo: jax.Array
    ua_hi: jax.Array          # uint32 [Ua]
    ua_lo: jax.Array


def build_proxy_stream_tables(edges: dict, uris: np.ndarray,
                              hosts: np.ndarray,
                              agents: np.ndarray) -> ProxyStreamTables:
    from onix.pipelines.words import (_IP_RE, _UA_RARE, _categorical,
                                      PROXY_SPEC)
    from onix.utils.features import digitize, entropy_array

    shift = PROXY_SPEC.shifts()
    uri_len = np.fromiter((len(str(u)) for u in uris), np.float64,
                          len(uris))
    ulbin = digitize(uri_len, edges["uri_len"]).astype(np.int64)
    uebin = digitize(entropy_array(uris).astype(np.float64),
                     edges["uri_entropy"]).astype(np.int64)
    uri_p = ((uebin & 63) << shift["uebin"]
             | (ulbin & 63) << shift["ulbin"])
    host_p = (np.fromiter((int(bool(_IP_RE.match(str(h)))) for h in hosts),
                          np.int64, len(hosts)) << shift["hostip"])
    ua = _categorical(np.asarray(agents, dtype=object), "ua_common", edges,
                      _UA_RARE)
    ua_p = (ua & 1023) << shift["ua"]
    uh, ul = _partial_halves(uri_p)
    hh, hl = _partial_halves(host_p)
    ah, al = _partial_halves(ua_p)
    return ProxyStreamTables(
        hour_edges=_edges1d(edges, "hour"),
        uri_hi=jnp.asarray(uh), uri_lo=jnp.asarray(ul),
        host_hi=jnp.asarray(hh), host_lo=jnp.asarray(hl),
        ua_hi=jnp.asarray(ah), ua_lo=jnp.asarray(al))


@functools.partial(jax.jit, static_argnames=("salt", "n_buckets"))
def proxy_stream_buckets(t: ProxyStreamTables, uri_c, host_c, ua_c,
                         respcode, hour, *, salt: int,
                         n_buckets: int) -> jax.Array:
    from onix.pipelines.words import PROXY_SPEC

    rc = respcode.astype(jnp.int32)
    hi, lo = _pack64(PROXY_SPEC, {
        "hbin": jnp.searchsorted(t.hour_edges, hour,
                                 side="right").astype(jnp.uint32),
        "uebin": jnp.zeros_like(rc).astype(jnp.uint32),
        "ulbin": jnp.zeros_like(rc).astype(jnp.uint32),
        "hostip": jnp.zeros_like(rc).astype(jnp.uint32),
        "ua": jnp.zeros_like(rc).astype(jnp.uint32),
        "cclass": (rc // 100).astype(jnp.uint32),
    })
    u = uri_c.astype(jnp.int32)
    h = host_c.astype(jnp.int32)
    a = ua_c.astype(jnp.int32)
    hi = hi | t.uri_hi[u] | t.host_hi[h] | t.ua_hi[a]
    lo = lo | t.uri_lo[u] | t.host_lo[h] | t.ua_lo[a]
    return _splitmix64_bucket(hi, lo, salt, n_buckets)


def flow_stream_bottom_k(
    tables: FlowDeviceTables,
    table_flat: jax.Array,     # f32 [D_x * V_x] extended score table
    cols: dict,                # numpy columns (synth/ingest schema)
    *,
    v_x: int,
    unseen_w: int,
    unseen_d: int,
    tol: float,
    max_results: int,
    chunk: int = 1 << 21,
) -> scoring.TopK:
    """Fused words→map→score→select for one streamed flow chunk,
    entirely on device: eight raw columns go up, `max_results` winners
    come back. Selection runs through the shared exact scan
    (scoring._scan_bottom_k), so tie rules, padding semantics, and the
    two-phase merge match every other selection entry point. `cols`
    may be raw numpy columns or a stage_flow_cols dict."""
    if not cols.get("_staged"):
        cols = stage_flow_cols(cols)
    return _flow_stream_scan(
        tables, table_flat,
        cols["sip_u32"], cols["dip_u32"], cols["sport"], cols["dport"],
        cols["proto_id"], cols["hour"], cols["ibyt"], cols["ipkt"],
        v_x=v_x, unseen_w=unseen_w, unseen_d=unseen_d, tol=tol,
        max_results=max_results, chunk=chunk)
