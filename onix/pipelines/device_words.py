"""On-device word creation + id mapping for the streaming flow path.

The 1B-event artifact's dominant pipeline stage is host-side word
creation + trained-id mapping (`stream_words_map`, 48% of the round-3
pipeline wall) — and this host exposes ONE CPU core, so the numpy path
cannot be parallelized sideways. The TPU-first answer is to move the
transform onto the chip: raw numeric telemetry columns stream to the
device (~25 B/event) and ONE fused program does binning → word packing
→ vocab/doc lookup → θ·φᵀ gather → pair-min → running bottom-k, so only
the winners ever come back. This renders SURVEY.md §2.1 #5's word
creation (reference FlowWordCreation, a Spark executor map) as device
compute on the VPU instead of a host preprocessing stage.

Why a compact key: the host path packs words into 43-bit int64 keys
(words.FLOW_SPEC). JAX runs x64-disabled, so the device path re-encodes
the TRAINED vocabulary once on the host into an equivalent <=31-bit
int32 key (pclass 17 | proto 3 | hbin 3 | bbin 3 | pbin 3) and the
device packs events the same way — the event→vocab-id mapping is
identical; only the key representation differs.

Fidelity: binning compares f32 values against f32-cast edges while the
host compares f64; a value within half an f32 ulp of a quantile edge
can land one bin over (expected ~1e-7/event; tests assert agreement on
synthetic days). The stream scorer's contract is the suspicious tail,
not bit-stable word strings, and the planted-detection metric is
unaffected.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from onix.models import scoring
from onix.pipelines.words import (FLOW_SPEC, _PCLASS_HH, _PROTO_UNK,
                                  N_BINS_DEFAULT)

# Compact-key layout (int32), LSB-first: pbin | bbin | hbin | proto |
# pclass. Shifts must match between build() (host) and _pack() (device).
_BIN_BITS = 3
_PROTO_BITS = 3
_PROTO_SHIFT = 3 * _BIN_BITS
_PCLASS_SHIFT = _PROTO_SHIFT + _PROTO_BITS
_COMPACT_UNK = (1 << _PROTO_BITS) - 1     # _PROTO_UNK re-encoded


class FlowDeviceTables(NamedTuple):
    """Trained lookup state, re-encoded for on-device mapping.

    A NamedTuple so the whole bundle is a pytree — it rides into the
    jitted scan as one argument and stays device-resident across
    chunks.
    """

    word_key_c: jax.Array     # int32 [V] compact keys, ascending
    word_ids: jax.Array       # int32 [V] compact key -> trained vocab id
    doc_u32: jax.Array        # uint32 [D] trained doc IPs, ascending
    doc_ids: jax.Array        # int32 [D]
    hour_edges: jax.Array     # f32 [n_bins-1]
    byt_edges: jax.Array      # f32 [n_bins-1] (log1p space)
    pkt_edges: jax.Array      # f32 [n_bins-1]
    proto_remap: jax.Array    # int32 [n_proto_classes] caller id -> compact


def build_flow_tables(bundle, edges: dict,
                      proto_classes: list[str]) -> FlowDeviceTables:
    """Re-encode the trained bundle once per run (host side, O(V+D)).

    `edges` are the FITTED bin edges/proto table archived by the
    training corpus build; `proto_classes` is the caller's proto id
    order for the streamed columns (synth/ingest contract)."""
    fields = FLOW_SPEC.unpack(np.asarray(bundle.word_key_sorted))
    for name in ("pbin", "bbin", "hbin"):
        if fields[name].max(initial=0) >= (1 << _BIN_BITS):
            raise ValueError(
                "n_bins too large for the compact key; raise _BIN_BITS")
    table = np.asarray(edges["proto_classes"], dtype=object)
    if len(table) >= _COMPACT_UNK:
        raise ValueError("too many protocol classes for the compact key")
    proto = np.where(fields["proto"] == _PROTO_UNK, _COMPACT_UNK,
                     np.minimum(fields["proto"], _COMPACT_UNK))
    key_c = (fields["pclass"] << _PCLASS_SHIFT
             | proto << _PROTO_SHIFT
             | fields["hbin"] << (2 * _BIN_BITS)
             | fields["bbin"] << _BIN_BITS
             | fields["pbin"]).astype(np.int64)
    assert key_c.max(initial=0) < 2 ** 31, "compact key overflows int32"
    order = np.argsort(key_c, kind="stable")
    # Caller proto id -> compact code (same remap rule as
    # flow_words_from_arrays: absent from the fitted table -> UNK).
    names = np.asarray(proto_classes, dtype=object)
    pos = np.searchsorted(table, names)
    pos_c = np.clip(pos, 0, max(len(table) - 1, 0))
    remap = np.where(len(table) and table[pos_c] == names,
                     pos_c, _COMPACT_UNK).astype(np.int32)
    nb = N_BINS_DEFAULT - 1
    return FlowDeviceTables(
        word_key_c=jnp.asarray(key_c[order].astype(np.int32)),
        word_ids=jnp.asarray(
            np.asarray(bundle.word_key_ids)[order].astype(np.int32)),
        doc_u32=jnp.asarray(np.asarray(bundle.doc_u32_sorted)),
        doc_ids=jnp.asarray(np.asarray(bundle.doc_u32_ids).astype(np.int32)),
        hour_edges=jnp.asarray(
            np.asarray(edges["hour"], np.float32).reshape(nb)),
        byt_edges=jnp.asarray(
            np.asarray(edges["log_ibyt"], np.float32).reshape(nb)),
        pkt_edges=jnp.asarray(
            np.asarray(edges["log_ipkt"], np.float32).reshape(nb)),
        proto_remap=jnp.asarray(remap),
    )


def _lookup_sorted(table: jax.Array, ids: jax.Array, keys: jax.Array,
                   fill: int) -> jax.Array:
    """ids[searchsorted(table, keys)] where the hit is exact, else fill
    — the device rendering of CorpusBundle's sorted-table lookups."""
    pos = jnp.searchsorted(table, keys)
    pos_c = jnp.clip(pos, 0, table.shape[0] - 1)
    hit = table[pos_c] == keys
    return jnp.where(hit, ids[pos_c], jnp.int32(fill))


def _flow_flat_idx(t: FlowDeviceTables, v_x: int, unseen_w: int,
                   unseen_d: int, sip, dip, sport, dport, proto, hour,
                   byt, pkt):
    """Per-chunk device transform: raw columns -> (idx_src, idx_dst)
    flat score-table indices. Mirrors flow_words_from_arrays +
    word_ids_packed/doc_ids_u32 field for field."""
    sport = sport.astype(jnp.int32)
    dport = dport.astype(jnp.int32)
    s_low = sport <= 1024
    d_low = dport <= 1024
    pclass = jnp.where(
        s_low & d_low, jnp.minimum(sport, dport),
        jnp.where(s_low, sport,
                  jnp.where(d_low, dport, jnp.int32(_PCLASS_HH))))
    hbin = jnp.searchsorted(t.hour_edges, hour, side="right")
    bbin = jnp.searchsorted(t.byt_edges, jnp.log1p(byt), side="right")
    pbin = jnp.searchsorted(t.pkt_edges, jnp.log1p(pkt), side="right")
    key = (pclass << _PCLASS_SHIFT
           | t.proto_remap[proto.astype(jnp.int32)] << _PROTO_SHIFT
           | hbin.astype(jnp.int32) << (2 * _BIN_BITS)
           | bbin.astype(jnp.int32) << _BIN_BITS
           | pbin.astype(jnp.int32))
    wid = _lookup_sorted(t.word_key_c, t.word_ids, key, unseen_w)
    did_s = _lookup_sorted(t.doc_u32, t.doc_ids, sip, unseen_d)
    did_d = _lookup_sorted(t.doc_u32, t.doc_ids, dip, unseen_d)
    return did_s * jnp.int32(v_x) + wid, did_d * jnp.int32(v_x) + wid


@functools.partial(jax.jit, static_argnames=("v_x", "unseen_w", "unseen_d",
                                             "tol", "max_results", "chunk"))
def _flow_stream_scan(tables: FlowDeviceTables, table_flat: jax.Array,
                      sip, dip, sport, dport, proto, hour, byt, pkt, *,
                      v_x: int, unseen_w: int, unseen_d: int, tol: float,
                      max_results: int, chunk: int) -> scoring.TopK:
    def score_chunk(s_ip, d_ip, s_p, d_p, pr, hr, by, pk):
        idx_s, idx_d = _flow_flat_idx(tables, v_x, unseen_w, unseen_d,
                                      s_ip, d_ip, s_p, d_p, pr, hr, by, pk)
        s = jnp.minimum(table_flat[idx_s], table_flat[idx_d])
        return jnp.where(s < tol, s, jnp.inf)

    return scoring._scan_bottom_k(
        (sip, dip, sport, dport, proto, hour, byt, pkt), sip.shape[0],
        score_chunk, max_results=max_results, chunk=chunk,
        merge_buffer=128)


def flow_stream_bottom_k(
    tables: FlowDeviceTables,
    table_flat: jax.Array,     # f32 [D_x * V_x] extended score table
    cols: dict,                # numpy columns (synth/ingest schema)
    *,
    v_x: int,
    unseen_w: int,
    unseen_d: int,
    tol: float,
    max_results: int,
    chunk: int = 1 << 21,
) -> scoring.TopK:
    """Fused words→map→score→select for one streamed flow chunk,
    entirely on device: eight raw columns go up, `max_results` winners
    come back. Selection runs through the shared exact scan
    (scoring._scan_bottom_k), so tie rules, padding semantics, and the
    two-phase merge match every other selection entry point."""
    return _flow_stream_scan(
        tables, table_flat,
        jnp.asarray(cols["sip_u32"]),
        jnp.asarray(cols["dip_u32"]),
        jnp.asarray(np.asarray(cols["sport"], np.int32)),
        jnp.asarray(np.asarray(cols["dport"], np.int32)),
        jnp.asarray(np.asarray(cols["proto_id"], np.int32)),
        jnp.asarray(np.asarray(cols["hour"], np.float32)),
        jnp.asarray(np.asarray(cols["ibyt"], np.float32)),
        jnp.asarray(np.asarray(cols["ipkt"], np.float32)),
        v_x=v_x, unseen_w=unseen_w, unseen_d=unseen_d, tol=tol,
        max_results=max_results, chunk=chunk)
