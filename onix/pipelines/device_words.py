"""On-device word creation + id mapping for the streaming flow path.

The 1B-event artifact's dominant pipeline stage is host-side word
creation + trained-id mapping (`stream_words_map`, 48% of the round-3
pipeline wall) — and this host exposes ONE CPU core, so the numpy path
cannot be parallelized sideways. The TPU-first answer is to move the
transform onto the chip: raw numeric telemetry columns stream to the
device (~25 B/event) and ONE fused program does binning → word packing
→ vocab/doc lookup → θ·φᵀ gather → pair-min → running bottom-k, so only
the winners ever come back. This renders SURVEY.md §2.1 #5's word
creation (reference FlowWordCreation, a Spark executor map) as device
compute on the VPU instead of a host preprocessing stage.

Why a compact key: the host path packs words into 43-bit int64 keys
(words.FLOW_SPEC). JAX runs x64-disabled, so the device path re-encodes
the TRAINED vocabulary once on the host into an equivalent <=31-bit
int32 key (pclass 17 | proto 3 | hbin 3 | bbin 3 | pbin 3) and the
device packs events the same way — the event→vocab-id mapping is
identical; only the key representation differs.

Fidelity: binning compares f32 values against f32-cast edges while the
host compares f64; a value within half an f32 ulp of a quantile edge
can land one bin over (expected ~1e-7/event; tests assert agreement on
synthetic days). The stream scorer's contract is the suspicious tail,
not bit-stable word strings, and the planted-detection metric is
unaffected.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from onix.models import scoring
from onix.pipelines.words import (FLOW_SPEC, _PCLASS_HH, _PROTO_UNK,
                                  N_BINS_DEFAULT)

# Compact-key layout (int32), LSB-first: pbin | bbin | hbin | proto |
# pclass. Shifts must match between build() (host) and _pack() (device).
_BIN_BITS = 3
_PROTO_BITS = 3
_PROTO_SHIFT = 3 * _BIN_BITS
_PCLASS_SHIFT = _PROTO_SHIFT + _PROTO_BITS
_COMPACT_UNK = (1 << _PROTO_BITS) - 1     # _PROTO_UNK re-encoded


class FlowDeviceTables(NamedTuple):
    """Trained lookup state, re-encoded for on-device mapping.

    A NamedTuple so the whole bundle is a pytree — it rides into the
    jitted scan as one argument and stays device-resident across
    chunks.
    """

    word_key_c: jax.Array     # int32 [V] compact keys, ascending
    word_ids: jax.Array       # int32 [V] compact key -> trained vocab id
    doc_u32: jax.Array        # uint32 [D] trained doc IPs, ascending
    doc_ids: jax.Array        # int32 [D]
    hour_edges: jax.Array     # f32 [n_bins-1]
    byt_edges: jax.Array      # f32 [n_bins-1] (log1p space)
    pkt_edges: jax.Array      # f32 [n_bins-1]
    proto_remap: jax.Array    # int32 [n_proto_classes] caller id -> compact


def build_flow_tables(bundle, edges: dict,
                      proto_classes: list[str]) -> FlowDeviceTables:
    """Re-encode the trained bundle once per run (host side, O(V+D)).

    `edges` are the FITTED bin edges/proto table archived by the
    training corpus build; `proto_classes` is the caller's proto id
    order for the streamed columns (synth/ingest contract)."""
    fields = FLOW_SPEC.unpack(np.asarray(bundle.word_key_sorted))
    for name in ("pbin", "bbin", "hbin"):
        if fields[name].max(initial=0) >= (1 << _BIN_BITS):
            raise ValueError(
                "n_bins too large for the compact key; raise _BIN_BITS")
    table = np.asarray(edges["proto_classes"], dtype=object)
    if len(table) >= _COMPACT_UNK:
        raise ValueError("too many protocol classes for the compact key")
    proto = np.where(fields["proto"] == _PROTO_UNK, _COMPACT_UNK,
                     np.minimum(fields["proto"], _COMPACT_UNK))
    key_c = (fields["pclass"] << _PCLASS_SHIFT
             | proto << _PROTO_SHIFT
             | fields["hbin"] << (2 * _BIN_BITS)
             | fields["bbin"] << _BIN_BITS
             | fields["pbin"]).astype(np.int64)
    assert key_c.max(initial=0) < 2 ** 31, "compact key overflows int32"
    order = np.argsort(key_c, kind="stable")
    # Caller proto id -> compact code (same remap rule as
    # flow_words_from_arrays: absent from the fitted table -> UNK).
    names = np.asarray(proto_classes, dtype=object)
    pos = np.searchsorted(table, names)
    pos_c = np.clip(pos, 0, max(len(table) - 1, 0))
    remap = np.where(len(table) and table[pos_c] == names,
                     pos_c, _COMPACT_UNK).astype(np.int32)
    return FlowDeviceTables(
        word_key_c=jnp.asarray(key_c[order].astype(np.int32)),
        word_ids=jnp.asarray(
            np.asarray(bundle.word_key_ids)[order].astype(np.int32)),
        doc_u32=jnp.asarray(np.asarray(bundle.doc_u32_sorted)),
        doc_ids=jnp.asarray(np.asarray(bundle.doc_u32_ids).astype(np.int32)),
        hour_edges=_edges1d(edges, "hour"),
        byt_edges=_edges1d(edges, "log_ibyt"),
        pkt_edges=_edges1d(edges, "log_ipkt"),
        proto_remap=jnp.asarray(remap),
    )


def _edges1d(edges: dict, name: str) -> "jnp.ndarray":
    """Fitted edge array as f32 [n_edges] for device searchsorted.

    Sized from the FITTED edges, not N_BINS_DEFAULT: magnitude features
    carry two extra tail-resolution cut points (words._bins tail=True),
    so edge counts differ per feature and the old fixed reshape(nb)
    crashed the flow path / silently disabled the dns path."""
    e = np.asarray(edges[name], np.float32).ravel()
    if e.size and np.any(np.diff(e) < 0):
        raise ValueError(f"fitted edges for {name!r} are not sorted")
    return jnp.asarray(e)


def _lookup_sorted(table: jax.Array, ids: jax.Array, keys: jax.Array,
                   fill: int) -> jax.Array:
    """ids[searchsorted(table, keys)] where the hit is exact, else fill
    — the device rendering of CorpusBundle's sorted-table lookups."""
    pos = jnp.searchsorted(table, keys)
    pos_c = jnp.clip(pos, 0, table.shape[0] - 1)
    hit = table[pos_c] == keys
    return jnp.where(hit, ids[pos_c], jnp.int32(fill))


def _flow_flat_idx(t: FlowDeviceTables, v_x: int, unseen_w: int,
                   unseen_d: int, sip, dip, sport, dport, proto, hour,
                   byt, pkt):
    """Per-chunk device transform: raw columns -> (idx_src, idx_dst)
    flat score-table indices. Mirrors flow_words_from_arrays +
    word_ids_packed/doc_ids_u32 field for field."""
    sport = sport.astype(jnp.int32)
    dport = dport.astype(jnp.int32)
    s_low = sport <= 1024
    d_low = dport <= 1024
    pclass = jnp.where(
        s_low & d_low, jnp.minimum(sport, dport),
        jnp.where(s_low, sport,
                  jnp.where(d_low, dport, jnp.int32(_PCLASS_HH))))
    hbin = jnp.searchsorted(t.hour_edges, hour, side="right")
    bbin = jnp.searchsorted(t.byt_edges, jnp.log1p(byt), side="right")
    pbin = jnp.searchsorted(t.pkt_edges, jnp.log1p(pkt), side="right")
    key = (pclass << _PCLASS_SHIFT
           | t.proto_remap[proto.astype(jnp.int32)] << _PROTO_SHIFT
           | hbin.astype(jnp.int32) << (2 * _BIN_BITS)
           | bbin.astype(jnp.int32) << _BIN_BITS
           | pbin.astype(jnp.int32))
    wid = _lookup_sorted(t.word_key_c, t.word_ids, key, unseen_w)
    did_s = _lookup_sorted(t.doc_u32, t.doc_ids, sip, unseen_d)
    did_d = _lookup_sorted(t.doc_u32, t.doc_ids, dip, unseen_d)
    return did_s * jnp.int32(v_x) + wid, did_d * jnp.int32(v_x) + wid


@functools.partial(jax.jit, static_argnames=("v_x", "unseen_w", "unseen_d",
                                             "tol", "max_results", "chunk"))
def _flow_stream_scan(tables: FlowDeviceTables, table_flat: jax.Array,
                      sip, dip, sport, dport, proto, hour, byt, pkt, *,
                      v_x: int, unseen_w: int, unseen_d: int, tol: float,
                      max_results: int, chunk: int) -> scoring.TopK:
    def score_chunk(s_ip, d_ip, s_p, d_p, pr, hr, by, pk):
        idx_s, idx_d = _flow_flat_idx(tables, v_x, unseen_w, unseen_d,
                                      s_ip, d_ip, s_p, d_p, pr, hr, by, pk)
        s = jnp.minimum(table_flat[idx_s], table_flat[idx_d])
        return jnp.where(s < tol, s, jnp.inf)

    return scoring._scan_bottom_k(
        (sip, dip, sport, dport, proto, hour, byt, pkt), sip.shape[0],
        score_chunk, max_results=max_results, chunk=chunk,
        merge_buffer=128)


# ---------------------------------------------------------------------------
# DNS / proxy device paths.
#
# Same design as flow with one extra split: the string-derived features
# (subdomain entropy, URI length, user-agent class, ...) are computed
# per UNIQUE value on the host — thousands of strings, microseconds —
# and packed into per-unique PARTIAL compact keys; the device gathers
# the partials through the dictionary codes and packs in the per-event
# numeric fields. Compact layouts (LSB-first):
#   dns:   flbin 3 | hbin 3 | ebin 3 | slbin 3 | nlabels 3 | qtype 8 |
#          rcode 4 | tld 1                                   (28 bits)
#   proxy: cclass 3 | hbin 3 | uebin 3 | ulbin 3 | hostip 1 | ua 7
#                                                            (20 bits)
# build_*_tables validates that the TRAINED vocab fits these ranges
# (qtype < 256, rcode < 16, <126 common user agents, ...) and raises
# otherwise — the caller then stays on the host path. Streamed events
# outside the ranges get key -1 (matches no table entry), landing on
# the UNSEEN word row exactly as the host lookup would.
# ---------------------------------------------------------------------------

_DNS_HBIN_SHIFT = 3
_DNS_EBIN_SHIFT = 6
_DNS_SLBIN_SHIFT = 9
_DNS_NLABELS_SHIFT = 12
_DNS_QTYPE_SHIFT = 15
_DNS_RCODE_SHIFT = 23
_DNS_TLD_SHIFT = 27
_PROXY_HBIN_SHIFT = 3
_PROXY_UEBIN_SHIFT = 6
_PROXY_ULBIN_SHIFT = 9
_PROXY_HOSTIP_SHIFT = 12
_PROXY_UA_SHIFT = 13
_PROXY_UA_RARE_C = 126     # words._UA_RARE (1023) re-encoded to 7 bits


class DnsDeviceTables(NamedTuple):
    word_key_c: jax.Array     # int32 [V] compact keys, ascending
    word_ids: jax.Array       # int32 [V]
    doc_u32: jax.Array        # uint32 [D] trained client IPs, ascending
    doc_ids: jax.Array        # int32 [D]
    hour_edges: jax.Array     # f32 [n_bins-1]
    flen_edges: jax.Array     # f32 [n_bins-1]


def build_dns_tables(bundle, edges: dict) -> DnsDeviceTables:
    from onix.pipelines.words import DNS_SPEC

    fields = DNS_SPEC.unpack(np.asarray(bundle.word_key_sorted))
    if fields["qtype"].max(initial=0) >= 256:
        raise ValueError("trained qtype exceeds the compact key range")
    if fields["rcode"].max(initial=0) >= 16:
        raise ValueError("trained rcode exceeds the compact key range")
    for name in ("flbin", "hbin", "ebin", "slbin", "nlabels"):
        if fields[name].max(initial=0) >= 8:
            raise ValueError(f"trained {name} exceeds the compact key range")
    key_c = (fields["flbin"]
             | fields["hbin"] << _DNS_HBIN_SHIFT
             | fields["ebin"] << _DNS_EBIN_SHIFT
             | fields["slbin"] << _DNS_SLBIN_SHIFT
             | fields["nlabels"] << _DNS_NLABELS_SHIFT
             | fields["qtype"] << _DNS_QTYPE_SHIFT
             | fields["rcode"] << _DNS_RCODE_SHIFT
             | fields["tld"] << _DNS_TLD_SHIFT).astype(np.int64)
    order = np.argsort(key_c, kind="stable")
    return DnsDeviceTables(
        word_key_c=jnp.asarray(key_c[order].astype(np.int32)),
        word_ids=jnp.asarray(
            np.asarray(bundle.word_key_ids)[order].astype(np.int32)),
        doc_u32=jnp.asarray(np.asarray(bundle.doc_u32_sorted)),
        doc_ids=jnp.asarray(np.asarray(bundle.doc_u32_ids).astype(np.int32)),
        hour_edges=_edges1d(edges, "hour"),
        flen_edges=_edges1d(edges, "frame_len"),
    )


def _pad_pow2(a: np.ndarray) -> np.ndarray:
    """Pad a per-unique table to the next power of two so the jitted
    per-chunk scan sees a handful of distinct shapes, not one per
    chunk's unique count (each distinct shape is a recompile)."""
    n = max(1, int(a.shape[0]))
    size = 1 << (n - 1).bit_length()
    return np.pad(a, (0, size - a.shape[0]))


def dns_partial_keys(qnames: np.ndarray, edges: dict) -> np.ndarray:
    """Per-UNIQUE compact partials (ebin|slbin|nlabels|tld at their
    shifts) from the fitted edges — host side, O(uniques)."""
    from onix.utils.features import digitize, qname_features

    qf = qname_features(qnames)
    slbin = digitize(qf["sub_len"], edges["sub_len"]).astype(np.int64)
    ebin = digitize(qf["sub_entropy"].astype(np.float64),
                    edges["sub_entropy"]).astype(np.int64)
    return (ebin << _DNS_EBIN_SHIFT
            | slbin << _DNS_SLBIN_SHIFT
            | qf["n_labels"] << _DNS_NLABELS_SHIFT
            | qf["tld_ok"] << _DNS_TLD_SHIFT).astype(np.int32)


@functools.partial(jax.jit, static_argnames=("v_x", "unseen_w", "unseen_d",
                                             "tol", "max_results", "chunk"))
def _dns_stream_scan(tables: DnsDeviceTables, table_flat: jax.Array,
                     partial_u: jax.Array, client, codes, qtype, rcode,
                     flen, hour, *, v_x: int, unseen_w: int, unseen_d: int,
                     tol: float, max_results: int,
                     chunk: int) -> scoring.TopK:
    def score_chunk(cl, co, qt, rc, fl, hr):
        flbin = jnp.searchsorted(tables.flen_edges, fl, side="right")
        hbin = jnp.searchsorted(tables.hour_edges, hr, side="right")
        key = (partial_u[co]
               | flbin.astype(jnp.int32)
               | hbin.astype(jnp.int32) << _DNS_HBIN_SHIFT
               | qt << _DNS_QTYPE_SHIFT
               | rc << _DNS_RCODE_SHIFT)
        valid = ((qt >= 0) & (qt < 256) & (rc >= 0) & (rc < 16))
        key = jnp.where(valid, key, jnp.int32(-1))
        wid = _lookup_sorted(tables.word_key_c, tables.word_ids, key,
                             unseen_w)
        did = _lookup_sorted(tables.doc_u32, tables.doc_ids, cl, unseen_d)
        s = table_flat[did * jnp.int32(v_x) + wid]
        return jnp.where(s < tol, s, jnp.inf)

    return scoring._scan_bottom_k(
        (client, codes, qtype, rcode, flen, hour), client.shape[0],
        score_chunk, max_results=max_results, chunk=chunk,
        merge_buffer=128)


def dns_stream_bottom_k(tables: DnsDeviceTables, table_flat: jax.Array,
                        cols: dict, edges: dict, *, v_x: int, unseen_w: int,
                        unseen_d: int, tol: float, max_results: int,
                        chunk: int = 1 << 21) -> scoring.TopK:
    """Fused words→map→score→select for one streamed DNS chunk: string
    features run per unique name on the host, everything per-event on
    the device."""
    partial_u = jnp.asarray(_pad_pow2(dns_partial_keys(cols["qnames"], edges)))
    return _dns_stream_scan(
        tables, table_flat, partial_u,
        jnp.asarray(cols["client_u32"]),
        jnp.asarray(np.asarray(cols["qname_codes"], np.int32)),
        jnp.asarray(np.asarray(cols["qtype"], np.int32)),
        jnp.asarray(np.asarray(cols["rcode"], np.int32)),
        jnp.asarray(np.asarray(cols["frame_len"], np.float32)),
        jnp.asarray(np.asarray(cols["hour"], np.float32)),
        v_x=v_x, unseen_w=unseen_w, unseen_d=unseen_d, tol=tol,
        max_results=max_results, chunk=chunk)


class ProxyDeviceTables(NamedTuple):
    word_key_c: jax.Array     # int32 [V] compact keys, ascending
    word_ids: jax.Array       # int32 [V]
    doc_u32: jax.Array        # uint32 [D]
    doc_ids: jax.Array        # int32 [D]
    hour_edges: jax.Array     # f32 [n_bins-1]


def build_proxy_tables(bundle, edges: dict) -> ProxyDeviceTables:
    from onix.pipelines.words import _UA_RARE, PROXY_SPEC

    fields = PROXY_SPEC.unpack(np.asarray(bundle.word_key_sorted))
    if len(edges.get("ua_common", ())) >= _PROXY_UA_RARE_C:
        raise ValueError("too many common user agents for the compact key")
    ua = fields["ua"]
    bad_ua = (ua >= len(edges.get("ua_common", ()))) & (ua != _UA_RARE)
    if bad_ua.any():
        raise ValueError("trained ua code outside the fitted common table")
    ua_c = np.where(ua == _UA_RARE, _PROXY_UA_RARE_C, ua)
    if fields["cclass"].max(initial=0) >= 8:
        raise ValueError("trained cclass exceeds the compact key range")
    for name in ("hbin", "uebin", "ulbin"):
        if fields[name].max(initial=0) >= 8:
            raise ValueError(f"trained {name} exceeds the compact key range")
    key_c = (fields["cclass"]
             | fields["hbin"] << _PROXY_HBIN_SHIFT
             | fields["uebin"] << _PROXY_UEBIN_SHIFT
             | fields["ulbin"] << _PROXY_ULBIN_SHIFT
             | fields["hostip"] << _PROXY_HOSTIP_SHIFT
             | ua_c << _PROXY_UA_SHIFT).astype(np.int64)
    order = np.argsort(key_c, kind="stable")
    return ProxyDeviceTables(
        word_key_c=jnp.asarray(key_c[order].astype(np.int32)),
        word_ids=jnp.asarray(
            np.asarray(bundle.word_key_ids)[order].astype(np.int32)),
        doc_u32=jnp.asarray(np.asarray(bundle.doc_u32_sorted)),
        doc_ids=jnp.asarray(np.asarray(bundle.doc_u32_ids).astype(np.int32)),
        hour_edges=_edges1d(edges, "hour"),
    )


def proxy_partial_keys(uris: np.ndarray, hosts: np.ndarray,
                       agents: np.ndarray, edges: dict) -> tuple:
    """Per-UNIQUE compact partials for the three dictionary columns —
    host side, O(uniques). Returns (uri_p, host_p, ua_p) int32."""
    from onix.pipelines.words import _IP_RE, _UA_RARE, _categorical
    from onix.utils.features import digitize, entropy_array

    uri_len = np.fromiter((len(str(u)) for u in uris), np.float64,
                          len(uris))
    ulbin = digitize(uri_len, edges["uri_len"]).astype(np.int64)
    uebin = digitize(entropy_array(uris).astype(np.float64),
                     edges["uri_entropy"]).astype(np.int64)
    uri_p = (uebin << _PROXY_UEBIN_SHIFT
             | ulbin << _PROXY_ULBIN_SHIFT).astype(np.int32)
    host_p = (np.fromiter((int(bool(_IP_RE.match(str(h)))) for h in hosts),
                          np.int64, len(hosts))
              << _PROXY_HOSTIP_SHIFT).astype(np.int32)
    ua = _categorical(np.asarray(agents, dtype=object), "ua_common", edges,
                      _UA_RARE)
    ua_c = np.where(ua == _UA_RARE, _PROXY_UA_RARE_C, ua)
    return uri_p, host_p, (ua_c << _PROXY_UA_SHIFT).astype(np.int32)


@functools.partial(jax.jit, static_argnames=("v_x", "unseen_w", "unseen_d",
                                             "tol", "max_results", "chunk"))
def _proxy_stream_scan(tables: ProxyDeviceTables, table_flat: jax.Array,
                       uri_p: jax.Array, host_p: jax.Array, ua_p: jax.Array,
                       client, uri_c, host_c, ua_c, respcode, hour, *,
                       v_x: int, unseen_w: int, unseen_d: int, tol: float,
                       max_results: int, chunk: int) -> scoring.TopK:
    def score_chunk(cl, uc, hc, ac, rc, hr):
        hbin = jnp.searchsorted(tables.hour_edges, hr, side="right")
        cclass = rc // 100
        key = (uri_p[uc] | host_p[hc] | ua_p[ac]
               | cclass
               | hbin.astype(jnp.int32) << _PROXY_HBIN_SHIFT)
        valid = (rc >= 0) & (cclass < 8)
        key = jnp.where(valid, key, jnp.int32(-1))
        wid = _lookup_sorted(tables.word_key_c, tables.word_ids, key,
                             unseen_w)
        did = _lookup_sorted(tables.doc_u32, tables.doc_ids, cl, unseen_d)
        s = table_flat[did * jnp.int32(v_x) + wid]
        return jnp.where(s < tol, s, jnp.inf)

    return scoring._scan_bottom_k(
        (client, uri_c, host_c, ua_c, respcode, hour), client.shape[0],
        score_chunk, max_results=max_results, chunk=chunk,
        merge_buffer=128)


def proxy_stream_bottom_k(tables: ProxyDeviceTables, table_flat: jax.Array,
                          cols: dict, edges: dict, *, v_x: int,
                          unseen_w: int, unseen_d: int, tol: float,
                          max_results: int,
                          chunk: int = 1 << 21) -> scoring.TopK:
    """Fused words→map→score→select for one streamed proxy chunk."""
    uri_p, host_p, ua_p = proxy_partial_keys(
        cols["uris"], cols["hosts"], cols["agents"], edges)
    return _proxy_stream_scan(
        tables, table_flat, jnp.asarray(_pad_pow2(uri_p)),
        jnp.asarray(_pad_pow2(host_p)), jnp.asarray(_pad_pow2(ua_p)),
        jnp.asarray(cols["client_u32"]),
        jnp.asarray(np.asarray(cols["uri_codes"], np.int32)),
        jnp.asarray(np.asarray(cols["host_codes"], np.int32)),
        jnp.asarray(np.asarray(cols["ua_codes"], np.int32)),
        jnp.asarray(np.asarray(cols["respcode"], np.int32)),
        jnp.asarray(np.asarray(cols["hour"], np.float32)),
        v_x=v_x, unseen_w=unseen_w, unseen_d=unseen_d, tol=tol,
        max_results=max_results, chunk=chunk)


def flow_stream_bottom_k(
    tables: FlowDeviceTables,
    table_flat: jax.Array,     # f32 [D_x * V_x] extended score table
    cols: dict,                # numpy columns (synth/ingest schema)
    *,
    v_x: int,
    unseen_w: int,
    unseen_d: int,
    tol: float,
    max_results: int,
    chunk: int = 1 << 21,
) -> scoring.TopK:
    """Fused words→map→score→select for one streamed flow chunk,
    entirely on device: eight raw columns go up, `max_results` winners
    come back. Selection runs through the shared exact scan
    (scoring._scan_bottom_k), so tie rules, padding semantics, and the
    two-phase merge match every other selection entry point."""
    return _flow_stream_scan(
        tables, table_flat,
        jnp.asarray(cols["sip_u32"]),
        jnp.asarray(cols["dip_u32"]),
        jnp.asarray(np.asarray(cols["sport"], np.int32)),
        jnp.asarray(np.asarray(cols["dport"], np.int32)),
        jnp.asarray(np.asarray(cols["proto_id"], np.int32)),
        jnp.asarray(np.asarray(cols["hour"], np.float32)),
        jnp.asarray(np.asarray(cols["ibyt"], np.float32)),
        jnp.asarray(np.asarray(cols["ipkt"], np.float32)),
        v_x=v_x, unseen_w=unseen_w, unseen_d=unseen_d, tol=tol,
        max_results=max_results, chunk=chunk)
