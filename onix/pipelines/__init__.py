"""Per-datatype scoring pipelines: word creation → corpus → LDA → results.

The TPU-era rendering of oni-ml's Spark jobs (SURVEY.md §2.1 #4–#8, #11).
"""
